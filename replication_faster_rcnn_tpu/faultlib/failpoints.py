"""Deterministic, seeded failpoint registry.

Every fault-tolerance path in this repo (retry-then-substitute loading,
scheduled-save containment, verified-restore walk-back, per-flush error
relay, load shedding) exists because some real failure motivates it —
but until now each was exercised only by hand-written test plumbing
(monkeypatched ``save``, datasets whose ``__getitem__`` raises, files
garbled with ``write_bytes``). Failpoints make those faults first-class:
NAMED injection sites in production code, off by default, armed by a
seeded schedule, so the same chaos run is reproducible bit-for-bit.

Design constraints, in order:

* **Zero overhead off.** ``fire(site)`` is a module-global boolean test
  on the disarmed path — no registry lookup, no lock, no allocation.
  Production code can consult a site unconditionally.
* **Determinism independent of thread interleaving.** A naive per-site
  ``random.Random`` stream would make the k-th *draw* depend on which
  thread got the lock first — fine — but any shared stream across sites
  would not be. Here the decision for the k-th hit of a site is a PURE
  function of ``(rule.seed, site, kind, k)`` via SHA-256: the per-site
  hit counter is the only mutable state (one locked increment), so two
  runs with the same seed inject the exact same fault at the exact same
  per-site hit index no matter how threads interleave across sites.
* **Faults ride existing containment.** ``ioerror`` raises a real
  ``OSError`` subclass from inside the site, so the retry/substitute/
  containment code that handles a real disk or decode failure handles
  the injected one identically. Data faults (``torn_write``,
  ``crc_corrupt``, ``nan``, ``drop``) are returned to the call site,
  which applies them where only it can (the saved file, the batch).

Sites (see the README failpoint table):
  loader.fetch         data/loader.py::fetch_sample, per sample access
  checkpoint.write     train/trainer.py sync + async save bodies
  checkpoint.manifest  train/fault.py::write_manifest
  prefetch.stage       data/prefetch_device.py producer, per staged chunk
  batcher.flush        serving/batcher.py::MicroBatcher._flush
  collective.init      parallel/mesh.py::initialize_distributed
  http.handler         serving/server.py POST handler
  heartbeat.beat       parallel/elastic.py::ElasticAgent.beat, per lease
                       renewal; a ``drop`` whose ``arg`` equals this
                       rank's index kills the rank (seeded rank loss —
                       ``prob=1.0, after=k, max_fires=1`` lands it on
                       exactly the k-th beat)
  router.dispatch      serving/fleet/router.py::FleetRouter, per replica
                       attempt; ``drop`` kills the selected replica
                       mid-request (the router's kill hook takes it out
                       of the fleet, then the attempt fails with a
                       dropped connection — failover/hedging must absorb
                       it), ``ioerror``/``delay`` fault just the attempt
  router.probe         serving/fleet/registry.py per /healthz probe;
                       ``ioerror`` fails the probe (lease keeps aging),
                       ``delay`` stalls it
  rollout.swap         serving/rollout/controller.py, fired before each
                       per-replica swap RPC; ``drop``/``ioerror`` abort
                       the wave mid-swap — the controller must roll the
                       drained replica back to the prior version
  rollout.promote      serving/rollout/controller.py, fired at the
                       windowed promote decision; ``drop``/``ioerror``
                       force the rollback path instead of promotion

Kinds:
  ioerror      raise ChaosError (an OSError) at the site
  torn_write   caller truncates the target file(s) after ``arg`` bytes
  crc_corrupt  caller flips one byte per target file (same length)
  nan          caller poisons the sample/batch images with NaN
  delay        sleep ``arg`` milliseconds at the site
  drop         caller discards the unit of work (request/connection)

Activation: ``configure("site:kind:prob:seed[:arg[:max_fires[:after]]],...")``
or a JSON schedule file (``configure("/path/sched.json")`` — a list of
rule objects, or ``{"rules": [...]}``). ``--chaos-spec`` on the CLI and
``debug.chaos_spec`` in the config route here.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from replication_faster_rcnn_tpu.telemetry import tracecontext

__all__ = [
    "SITES",
    "KINDS",
    "ChaosError",
    "Fault",
    "Rule",
    "apply_file_fault",
    "armed",
    "configure",
    "disarm",
    "event_log",
    "fire",
    "parse_spec",
    "poison_batch",
    "set_sink",
]

SITES = (
    "loader.fetch",
    "checkpoint.write",
    "checkpoint.manifest",
    "prefetch.stage",
    "batcher.flush",
    "collective.init",
    "http.handler",
    "heartbeat.beat",
    "router.dispatch",
    "router.probe",
    "rollout.swap",
    "rollout.promote",
)

KINDS = ("ioerror", "torn_write", "crc_corrupt", "nan", "delay", "drop")


class ChaosError(OSError):
    """Injected I/O failure (failpoint kind ``ioerror``).

    An ``OSError`` so every containment path written for real disk /
    network trouble (retry, substitute, contain-and-continue) treats the
    injection exactly like the fault it stands in for.
    """


@dataclasses.dataclass(frozen=True)
class Rule:
    """One activation: inject ``kind`` at ``site`` with probability
    ``prob`` per hit, decided by ``seed``. ``arg`` parameterizes the
    kind (delay ms, torn-write byte offset); ``max_fires`` caps total
    injections (0 = unlimited); hits before ``after`` never fire — so
    ``prob=1.0, after=k, max_fires=1`` means "exactly the k-th hit",
    the deterministic scheduling idiom the chaos suites lean on."""

    site: str
    kind: str
    prob: float
    seed: int
    arg: float = 0.0
    max_fires: int = 0
    after: int = 0

    def __post_init__(self):
        if self.site not in SITES:
            raise ValueError(
                f"unknown failpoint site {self.site!r} (sites: {', '.join(SITES)})"
            )
        if self.kind not in KINDS:
            raise ValueError(
                f"unknown fault kind {self.kind!r} (kinds: {', '.join(KINDS)})"
            )
        if not 0.0 <= self.prob <= 1.0:
            raise ValueError(f"prob must be in [0, 1], got {self.prob}")
        if self.max_fires < 0:
            raise ValueError(f"max_fires must be >= 0, got {self.max_fires}")
        if self.after < 0:
            raise ValueError(f"after must be >= 0, got {self.after}")


@dataclasses.dataclass(frozen=True)
class Fault:
    """An injected fault: which site fired, what kind, at which per-site
    hit index (``seq``), with the rule's parameter."""

    site: str
    kind: str
    seq: int
    arg: float


def _decision(rule: Rule, n: int) -> float:
    """Uniform in [0, 1) for the n-th hit — a pure function of the rule
    and the hit index, so thread interleaving cannot change it."""
    h = hashlib.sha256(
        f"{rule.seed}:{rule.site}:{rule.kind}:{n}".encode()
    ).digest()
    return int.from_bytes(h[:8], "big") / 2.0**64


class Registry:
    """Rules grouped by site + per-site hit counters + the event log.

    All mutable state (counters, fire tallies, events) lives behind one
    lock; the injection decision itself needs none of it beyond the hit
    index, which is why determinism survives threading.
    """

    def __init__(self, rules: Sequence[Rule]) -> None:
        self._rules: Dict[str, List[Rule]] = {}
        for r in rules:
            self._rules.setdefault(r.site, []).append(r)
        self._lock = threading.Lock()
        self._hits: Dict[str, int] = {s: 0 for s in self._rules}
        self._fired: Dict[tuple, int] = {}
        self._events: List[Dict[str, Any]] = []

    def consult(self, site: str) -> Optional[Fault]:
        rules = self._rules.get(site)
        if not rules:
            return None
        with self._lock:
            n = self._hits[site]
            self._hits[site] = n + 1
            for i, rule in enumerate(rules):
                if n < rule.after:
                    continue
                if rule.max_fires and self._fired.get((site, i), 0) >= rule.max_fires:
                    continue
                if _decision(rule, n) < rule.prob:
                    self._fired[(site, i)] = self._fired.get((site, i), 0) + 1
                    self._events.append(
                        {"site": site, "seq": n, "kind": rule.kind, "arg": rule.arg}
                    )
                    return Fault(site, rule.kind, n, rule.arg)
        return None

    def events(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [dict(e) for e in self._events]

    def hits(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._hits)


# Module state: `_armed` is the disarmed-path fast check (a plain bool
# read — benign race by design: arming happens before the workload under
# test starts). The registry/sink swap under `_state_lock`.
_state_lock = threading.Lock()
_armed = False
_registry: Optional[Registry] = None
_sink: Optional[Callable[[Dict[str, Any]], None]] = None


def parse_spec(spec: str) -> List[Rule]:
    """Rules from a ``site:kind:prob:seed[:arg[:max_fires[:after]]],...``
    string or a JSON schedule file (a path ending ``.json`` or prefixed
    ``@``)."""
    spec = spec.strip()
    if not spec:
        return []
    if spec.startswith("@") or spec.endswith(".json"):
        return load_schedule(spec.lstrip("@"))
    rules = []
    for part in spec.split(","):
        fields = part.strip().split(":")
        if len(fields) < 4 or len(fields) > 7:
            raise ValueError(
                f"bad failpoint spec {part!r}: want "
                "site:kind:prob:seed[:arg[:max_fires[:after]]]"
            )
        site, kind, prob, seed = fields[:4]
        arg = float(fields[4]) if len(fields) > 4 else 0.0
        max_fires = int(fields[5]) if len(fields) > 5 else 0
        after = int(fields[6]) if len(fields) > 6 else 0
        rules.append(
            Rule(site, kind, float(prob), int(seed), arg=arg,
                 max_fires=max_fires, after=after)
        )
    return rules


def load_schedule(path: str) -> List[Rule]:
    """Rules from a JSON schedule: ``[{"site": ..., "kind": ...,
    "prob": ..., "seed": ..., "arg": ..., "max_fires": ...}, ...]`` or
    the same list under a ``"rules"`` key."""
    with open(path) as f:
        data = json.load(f)
    if isinstance(data, dict):
        data = data.get("rules", [])
    rules = []
    for i, d in enumerate(data):
        try:
            rules.append(
                Rule(
                    site=d["site"],
                    kind=d["kind"],
                    prob=float(d["prob"]),
                    seed=int(d["seed"]),
                    arg=float(d.get("arg", 0.0)),
                    max_fires=int(d.get("max_fires", 0)),
                    after=int(d.get("after", 0)),
                )
            )
        except (KeyError, TypeError, ValueError) as e:
            raise ValueError(f"bad schedule entry {i} in {path}: {e}") from e
    return rules


def configure(
    spec: Any = "", sink: Optional[Callable[[Dict[str, Any]], None]] = None
) -> List[Rule]:
    """Arm the registry from a spec string / schedule path / Rule list.
    An empty spec disarms. Returns the parsed rules."""
    global _armed, _registry, _sink
    if isinstance(spec, str):
        rules = parse_spec(spec)
    else:
        rules = [r if isinstance(r, Rule) else Rule(**r) for r in spec]
    with _state_lock:
        if not rules:
            _armed = False
            _registry = None
            _sink = None
            return []
        _registry = Registry(rules)
        if sink is not None:
            _sink = sink
        _armed = True
    return rules


def disarm() -> None:
    """Disarm and drop the registry + sink (test/teardown hook)."""
    configure("")


def armed() -> bool:
    return _armed


def set_sink(fn: Optional[Callable[[Dict[str, Any]], None]]) -> None:
    """Per-injection observer: called with the event dict (site, seq,
    kind, arg + call-site context) for every injected fault. The trainer
    wires this to its watchdog incident log so a chaos run's post-mortem
    shows exactly which faults landed."""
    global _sink
    with _state_lock:
        _sink = fn


def event_log() -> List[Dict[str, Any]]:
    """Injected events so far, in registry order (the determinism tests
    compare these across two runs of the same schedule)."""
    reg = _registry
    return reg.events() if reg is not None else []


def site_hits() -> Dict[str, int]:
    """Per-site consult counts (armed sites only)."""
    reg = _registry
    return reg.hits() if reg is not None else {}


def fire(site: str, **ctx: Any) -> Optional[Fault]:
    """Consult a failpoint. Disarmed: a single boolean test, returns
    None. Armed: decide deterministically for this site hit; ``ioerror``
    raises :class:`ChaosError` and ``delay`` sleeps here (fully applied),
    every injected kind is returned so call sites can apply the data
    faults they own (``nan``/``torn_write``/``crc_corrupt``/``drop``) —
    a site simply ignores kinds it has no behavior for."""
    if not _armed:
        return None
    reg = _registry
    if reg is None:  # pragma: no cover - disarm raced a fire
        return None
    fault = reg.consult(site)
    if fault is None:
        return None
    sink = _sink
    if sink is not None:
        try:
            event = {
                "site": fault.site,
                "seq": fault.seq,
                "kind": fault.kind,
                "arg": fault.arg,
                **ctx,
            }
            # a request-scoped fault carries its trace id, so the
            # chaos_injected incident joins the merged request timeline
            trace = tracecontext.current_trace()
            if trace is not None:
                event.setdefault("trace_id", trace.trace_id)
            sink(event)
        except Exception:  # noqa: BLE001 - observer must not alter the fault
            pass
    if fault.kind == "delay":
        time.sleep(fault.arg / 1000.0)
    elif fault.kind == "ioerror":
        raise ChaosError(
            f"injected IOError at failpoint {site!r} (hit {fault.seq})"
        )
    return fault


# ------------------------------------------------------- fault appliers
#
# Call-site helpers for the data faults fire() returns. Kept here so
# every site applies "torn write" / "CRC corrupt" / "NaN batch" the same
# way and the chaos tests pin one behavior.


def _target_files(path: str) -> List[str]:
    if os.path.isfile(path):
        return [path]
    out = []
    for root, _, names in os.walk(path):
        out.extend(os.path.join(root, n) for n in names)
    return sorted(out)


def apply_file_fault(fault: Fault, path: str) -> List[str]:
    """Apply ``torn_write`` (truncate after ``arg`` bytes) or
    ``crc_corrupt`` (flip one mid-file byte, length preserved) to a file
    or to every file under a directory. Returns the files touched."""
    touched = []
    for f in _target_files(path):
        size = os.path.getsize(f)
        if fault.kind == "torn_write":
            keep = min(int(fault.arg), size)
            with open(f, "r+b") as fh:
                fh.truncate(keep)
            touched.append(f)
        elif fault.kind == "crc_corrupt":
            if size == 0:
                continue
            pos = size // 2
            with open(f, "r+b") as fh:
                fh.seek(pos)
                b = fh.read(1)
                fh.seek(pos)
                fh.write(bytes([b[0] ^ 0xFF]))
            touched.append(f)
    return touched


def poison_batch(batch: Dict[str, Any]) -> Dict[str, Any]:
    """The ``nan`` fault: a copy of a sample/batch dict whose float
    ``image`` is all-NaN (the exact poison the guarded-update tests
    inject by hand) — non-float images pass through untouched."""
    out = dict(batch)
    img = out.get("image")
    if img is None:
        return out
    img = np.array(img, copy=True)
    if img.dtype.kind == "f":
        img.fill(np.nan)
        out["image"] = img
    return out


def find_step_dir(
    workdir: str, step: int, exclude: Sequence[str] = ()
) -> Optional[str]:
    """The orbax step directory for ``step`` under ``workdir`` (the dir
    whose digit content equals the step number), for file-fault targets."""
    want = str(int(step))
    try:
        names = os.listdir(workdir)
    except OSError:
        return None
    for name in sorted(names):
        full = os.path.join(workdir, name)
        if not os.path.isdir(full) or name in exclude:
            continue
        digits = "".join(c for c in name if c.isdigit())
        if digits and str(int(digits)) == want:
            return full
    return None
