"""The jitted train step — the whole reference training iteration
(`train.py:59-127`) as ONE XLA program.

Where the reference crosses the device boundary four times per step (host
anchor generation `nets/rpn.py:127`, per-image NMS loop `nets/rpn.py:131-136`,
host numpy RPN targets `train.py:71-79`, roi.cpu() head targets
`train.py:91-104`), here the entire pipeline — trunk -> RPN -> proposals ->
both target creators -> head -> 4 losses -> grad -> update — is traced once
and compiled. Sharding the batch over the mesh's data axis turns the loss's
global reductions and the gradient sums into XLA allreduces automatically.

Loss structure (reference `train.py:81-123`): rpn_reg (smooth-L1 on anchor
positives), rpn_cls (binary CE, ignore -1), head_reg (smooth-L1 on sampled
positives, class-specific deltas via `train.py:112-117` gather semantics),
head_cls (21-way CE, ignore -1); total is their weighted sum (reference:
unweighted, `train.py:123`).
"""

from __future__ import annotations

import math
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import optax
from flax import struct

from replication_faster_rcnn_tpu.config import FasterRCNNConfig
from replication_faster_rcnn_tpu.models.faster_rcnn import FasterRCNN
from replication_faster_rcnn_tpu.models.head import select_class_deltas
from replication_faster_rcnn_tpu.targets import (
    batched_anchor_targets,
    batched_proposal_targets,
)
from replication_faster_rcnn_tpu.train import fault, losses

Array = jnp.ndarray


class TrainState(struct.PyTreeNode):
    """Carried training state (params + BN stats + optimizer + step + rng)."""

    step: Array
    params: Any
    batch_stats: Any
    opt_state: Any
    rng: Array


def create_train_state(
    config: FasterRCNNConfig, rng: Array, tx: optax.GradientTransformation
) -> Tuple[FasterRCNN, TrainState]:
    model = FasterRCNN(config)
    h, w = config.data.image_size
    init_rng, state_rng = jax.random.split(rng)
    variables = model.init(
        {"params": init_rng}, jnp.zeros((1, h, w, 3), jnp.float32), train=False
    )
    params = variables["params"]
    batch_stats = variables.get("batch_stats", {})
    return model, TrainState(
        step=jnp.zeros((), jnp.int32),
        params=params,
        batch_stats=batch_stats,
        opt_state=tx.init(params),
        rng=state_rng,
    )


def compute_losses(
    model: FasterRCNN,
    config: FasterRCNNConfig,
    params: Any,
    batch_stats: Any,
    batch: Dict[str, Array],
    rng: Array,
    train: bool = True,
    axis_name: str = None,
    positions: Array = None,
    features_wall: bool = False,
    targets_only: bool = False,
    train_resolution=None,
) -> Tuple[Array, Tuple[Dict[str, Array], Any]]:
    """Forward + 4 losses. Returns (total, (metrics, new_batch_stats)).

    ``axis_name``/``positions`` support the explicit shard_map backend
    (`parallel/spmd.py`): loss normalizers psum over the axis, per-image
    sampling keys fold in the global batch position so the objective and
    randomness match the jit auto-partitioned path exactly.

    ``features_wall`` stops gradients at the trunk/neck features, so a
    grad of this loss excludes the whole trunk backward. Diagnostics
    only (`benchmarks/grad_breakdown.py` uses the full-vs-walled time
    difference to attribute backward cost on hardware, since the
    tunnel-side ``jax.profiler`` is a wedge risk — verify SKILL.md);
    never set in training.

    ``targets_only`` returns right after the second-stage target
    creators with a scalar probe consuming their outputs (empty
    metrics) — the bench's `targets_ms` stage prefix, kept inside this
    function so the timed prefix can't drift from the real step.
    Diagnostics only.

    ``train_resolution`` (STATIC ``(h, w)`` or None) is one multi-scale
    training bucket (data.train_resolutions): the batch arrives at the
    base canvas shape and is resampled to the bucket's shape on device
    (`ops/image.py::resize_batch_with_boxes`, boxes tracked) right after
    the jitter resample — so each bucket is its own compiled program,
    exactly like a serving bucket. None (the default) leaves the program
    byte-identical to the pre-bucket trace.
    """
    images = batch["image"]
    if "jitter" in batch:
        # device-side scale-jitter resample (data.augment_scale_device):
        # the host shipped raw images + integer jitter geometry; the
        # boxes in this batch are already transformed host-side
        from replication_faster_rcnn_tpu.ops.image import batched_scale_jitter

        images = batched_scale_jitter(images, batch["jitter"])
    gt_boxes = batch["boxes"]
    gt_labels = batch["labels"]
    gt_mask = batch["mask"]
    if "aug" in batch:
        # FULLY on-device augmentation (data.augment_device): the host
        # shipped raw samples + int32 (idx, epoch) rows; flip, translate
        # and scale-jitter decisions are splitmix draws of
        # (seed, epoch, idx) computed here, identical on every shard and
        # every resume with zero communication. Runs at the base canvas,
        # ahead of the bucket resample below.
        from replication_faster_rcnn_tpu.ops.image import augment_batch

        images, gt_boxes, gt_labels, gt_mask = augment_batch(
            images,
            gt_boxes,
            gt_labels,
            gt_mask,
            batch["aug"],
            seed=config.train.seed,
            hflip=config.data.augment_hflip,
            scale_range=config.data.augment_scale,
            translate=config.data.augment_translate,
        )
    if train_resolution is not None:
        # multi-scale bucket resample (static shape, per-bucket program)
        from replication_faster_rcnn_tpu.ops.image import (
            resize_batch_with_boxes,
        )

        images, gt_boxes = resize_batch_with_boxes(
            images, gt_boxes, train_resolution
        )
    img_h, img_w = float(images.shape[1]), float(images.shape[2])
    variables = {"params": params, "batch_stats": batch_stats}
    sigma = config.train.smooth_l1_sigma
    if positions is None:
        positions = jnp.arange(images.shape[0], dtype=jnp.int32)

    rng_at, rng_pt, rng_do = jax.random.split(rng, 3)
    if axis_name is not None:
        # decorrelate dropout across shards (rng is replicated; without this
        # every shard would draw the same mask). Sampling rngs stay
        # shard-invariant — their per-image keys fold in global positions.
        rng_do = jax.random.fold_in(rng_do, jax.lax.axis_index(axis_name))

    # trunk + RPN (train mode: BN batch stats update)
    feat, mut = model.apply(
        variables, images, train, method="extract_features", mutable=["batch_stats"]
    )
    if features_wall:
        feat = jax.tree_util.tree_map(jax.lax.stop_gradient, feat)
    logits, deltas, anchors = model.apply(variables, feat, method="rpn_forward")

    # first-stage targets, on device
    reg_t, lab_t = batched_anchor_targets(
        rng_at, gt_boxes, gt_mask, anchors, config.rpn_targets, positions
    )
    rpn_reg_loss = losses.loc_loss(deltas, reg_t, lab_t, sigma, axis_name)
    rpn_cls_loss = losses.ignore_cross_entropy(logits, lab_t, axis_name)

    # proposals (stop-grad, reference detach semantics) + second-stage targets
    rois, roi_valid = model.apply(
        variables, logits, deltas, anchors, img_h, img_w, train, method="propose"
    )
    sample_rois, reg_t2, lab_t2 = batched_proposal_targets(
        rng_pt, rois, roi_valid, gt_boxes, gt_labels, gt_mask, config.roi_targets,
        positions,
        strategy=config.train.sampling_strategy,
    )
    if targets_only:
        probe = (
            reg_t.sum() + lab_t.sum() + sample_rois.sum()
            + reg_t2.sum() + lab_t2.sum()
        ).astype(jnp.float32)
        return probe, ({}, mut.get("batch_stats", {}))

    # head on the sampled rois (BN in the tail also updates; the VGG16
    # tail's dropout draws from the 'dropout' rng in train mode)
    (cls_out, reg_out), mut2 = model.apply(
        # norm="group" models carry no batch_stats collection — flax then
        # omits the key from the mutated-state dict
        {"params": params, "batch_stats": mut.get("batch_stats", {})},
        feat,
        sample_rois,
        img_h,
        img_w,
        train,
        method="head_forward",
        mutable=["batch_stats"],
        rngs={"dropout": rng_do} if train else None,
    )
    reg_sel = select_class_deltas(reg_out, lab_t2)
    head_reg_loss = losses.loc_loss(reg_sel, reg_t2, lab_t2, sigma, axis_name)
    head_cls_loss = losses.ignore_cross_entropy(cls_out, lab_t2, axis_name)

    w1, w2, w3, w4 = config.train.loss_weights
    total = (
        w1 * rpn_cls_loss + w2 * rpn_reg_loss + w3 * head_cls_loss + w4 * head_reg_loss
    )
    metrics = {
        "loss": total,
        "rpn_cls_loss": rpn_cls_loss,
        "rpn_reg_loss": rpn_reg_loss,
        "head_cls_loss": head_cls_loss,
        "head_reg_loss": head_reg_loss,
        "n_pos_rpn": (lab_t == 1).sum().astype(jnp.float32),
        "n_pos_head": (lab_t2 > 0).sum().astype(jnp.float32),
    }
    return total, (metrics, mut2.get("batch_stats", {}))


def quantize_grads(grads: Any, dtype_str: str) -> Any:
    """Round-trip the gradient tree through ``dtype_str`` (no-op for
    "float32").

    This is the numerics of `train.grad_allreduce_dtype`: the explicit
    shard_map backend casts before its `lax.psum` so the collective
    itself moves half the bytes (`parallel/spmd.py`); under jit
    auto-partitioning the all-reduces are fused inside the backward where
    their dtype cannot be chosen from here, so the same quantization is
    applied to the summed grads — both backends then apply the optimizer
    to identically-rounded gradients.
    """
    if dtype_str == "float32":
        return grads
    dt = jnp.dtype(dtype_str)
    return jax.tree_util.tree_map(
        lambda g: g.astype(dt).astype(g.dtype)
        if jnp.issubdtype(g.dtype, jnp.floating)
        else g,
        grads,
    )


def make_train_step(
    model: FasterRCNN,
    config: FasterRCNNConfig,
    tx: optax.GradientTransformation,
    train_resolution=None,
):
    """Build the jittable (state, batch) -> (state, metrics) function.

    Jit it with donate_argnums=(0,) and sharded batch inputs; parameters
    stay replicated and gradients allreduce via XLA.

    ``train_resolution`` bakes one multi-scale bucket's static (h, w)
    into the trace (see ``compute_losses``); None is the single-scale
    program, byte-identical to the pre-bucket build.
    """

    def train_step(state: TrainState, batch: Dict[str, Array]):
        step_rng = jax.random.fold_in(state.rng, state.step)

        def loss_fn(params):
            return compute_losses(
                model, config, params, state.batch_stats, batch, step_rng,
                True, train_resolution=train_resolution,
            )

        (_, (metrics, new_stats)), grads = jax.value_and_grad(
            loss_fn, has_aux=True
        )(state.params)
        grads = quantize_grads(grads, config.train.grad_allreduce_dtype)
        # guarded update: under nonfinite_policy skip|halt a gradient tree
        # with any NaN/Inf withholds the whole update (params, opt state,
        # BN stats carried through bit-identical) and flags skipped=1 in
        # the health scalars, which ride the metrics transfer as before
        new_state, health = fault.guarded_update(
            tx, state, grads, new_stats, config.train.nonfinite_policy
        )
        metrics.update(health)
        return new_state, metrics

    return train_step


def make_cached_train_step(
    model: FasterRCNN,
    config: FasterRCNNConfig,
    tx: optax.GradientTransformation,
    train_resolution=None,
):
    """The device-cache variant: (state, cache, sel) -> (state, metrics).

    ``cache`` is a :class:`data.device_cache.DeviceCache`'s array dict
    (device-resident, replicated); ``sel`` the per-step batch selection
    (indices + augmentation decisions, ~bytes). Batch materialization
    (`data/device_cache.py::materialize_batch`) runs inside the same
    compiled program as the step, so the host->device traffic per step is
    the selection alone — the answer to the measured feed-bound trainer
    (11 vs 215 img/s, `benchmarks/loader_throughput.json`).

    Jit with donate_argnums=(0,) ONLY — the cache must NOT be donated.
    """
    base = make_train_step(model, config, tx, train_resolution=train_resolution)

    def cached_step(state, cache: Dict[str, Array], sel: Dict[str, Array]):
        from replication_faster_rcnn_tpu.data.device_cache import (
            materialize_batch,
        )

        return base(state, materialize_batch(cache, sel))

    return cached_step


def fused_scan_unroll(k: int) -> int:
    """Unroll factor for the fused multi-step `lax.scan`.

    XLA:CPU compiles a while-loop body without the top-level conv/fusion
    treatment — measured 4.5x slower per step than the same step outside
    the loop — so on CPU the scan is fully unrolled into straight-line
    code (compile time grows ~linearly with k). On TPU the loop body
    compiles at full quality and the compact scan keeps the executable
    small and the (tunnel-fragile) compile short, so it stays a real loop.
    """
    return k if jax.default_backend() == "cpu" else 1


def build_multi_step(step_fn, k: int):
    """Fuse ``k`` steps of a (state, batch) -> (state, metrics) step into
    ONE jittable call via `lax.scan` over batches stacked on a new leading
    [K] axis.

    One dispatch then trains k steps: the carry (TrainState) stays on
    device between the fused iterations (donate it when jitting) and the
    per-step metrics come back stacked [K, ...], read by the host only at
    log boundaries. The scan body IS the single step — same fold_in(rng,
    step) keying, same optimizer — so a fused run is step-for-step
    identical to k sequential dispatches (pinned by
    tests/test_multi_step.py).
    """
    if k < 1:
        raise ValueError(f"steps_per_dispatch must be >= 1, got {k}")

    def multi_step(state: TrainState, batches: Dict[str, Array]):
        def body(s, b):
            return step_fn(s, b)

        return jax.lax.scan(
            body, state, batches, length=k, unroll=fused_scan_unroll(k)
        )

    return multi_step


def make_cached_multi_step(
    model: FasterRCNN,
    config: FasterRCNNConfig,
    tx: optax.GradientTransformation,
    k: int,
    train_resolution=None,
):
    """Fused device-cache variant: (state, cache, sels) -> (state, metrics)
    where ``sels`` holds k per-step selections stacked to [K, B, ...]
    (`data.device_cache.stack_selections`). Each scan iteration gathers +
    augments its batch from the cache and trains one step; the host ships
    only the stacked selection bytes per k steps.

    Jit with donate_argnums=(0,) ONLY — the cache must NOT be donated.
    """
    if k < 1:
        raise ValueError(f"steps_per_dispatch must be >= 1, got {k}")
    base = make_train_step(model, config, tx, train_resolution=train_resolution)

    def fused(state: TrainState, cache: Dict[str, Array], sels: Dict[str, Array]):
        from replication_faster_rcnn_tpu.data.device_cache import (
            materialize_batch,
        )

        def body(s, sel):
            return base(s, materialize_batch(cache, sel))

        return jax.lax.scan(
            body, state, sels, length=k, unroll=fused_scan_unroll(k)
        )

    return fused


def _schedule_knobs(config: FasterRCNNConfig, steps_per_epoch: int):
    """(peak_lr, warmup_steps) shared by the jnp and host schedules.

    The large-batch recipe of arXiv:1711.04325: under
    ``lr_scaling='linear'`` the peak lr scales by
    ``batch_size / base_batch_size`` (scaling out the data axis keeps the
    per-example update magnitude), and ``warmup_epochs`` ramps linearly
    from ~0 to that peak before the cosine decay takes over.
    """
    tc = config.train
    scale = (
        tc.batch_size / tc.base_batch_size if tc.lr_scaling == "linear" else 1.0
    )
    warmup_steps = int(round(tc.warmup_epochs * max(steps_per_epoch, 1)))
    return tc.lr * scale, warmup_steps


def scale_by_sharded_trust_ratio(
    axis_name=None,
    param_dims=None,
) -> optax.GradientTransformation:
    """LAMB's per-layer trust ratio (arXiv:1904.00962), exact under
    ZeRO-1 weight-update sharding.

    ``optax.scale_by_trust_ratio`` rescales each layer's update by
    |param| / |update| — leaf-global norms, which is why the spmd+ZeRO
    backend rejects LARS (``parallel/mesh.py::validate_parallel``):
    inside the shard_map's per-shard update every sharded leaf is a 1/N
    slice and its local norm is wrong.  This variant computes both norms
    from the local slice's sum of squares and completes them with a
    ``lax.psum`` over ``axis_name`` for the leaves ``param_dims`` marks
    sharded (dim >= 0) — ``|x|^2 == sum_shards |x_s|^2`` exactly, so the
    trust ratio matches the unsharded math while each shard only ever
    touches its own slice.  Replicated leaves (dim == -1) are full on
    every shard and use their local norm directly (a psum there would
    overcount by N).  With ``axis_name=None`` (the default) no psum is
    emitted and the transform is numerically identical to
    ``optax.scale_by_trust_ratio()`` with its default knobs.
    """

    def init_fn(params):
        del params
        return optax.EmptyState()

    def update_fn(updates, state, params=None):
        if params is None:
            raise ValueError("scale_by_sharded_trust_ratio requires params")

        def _norm(x, dim):
            s = jnp.sum(jnp.square(x.astype(jnp.float32)))
            if axis_name is not None and dim >= 0:
                s = jax.lax.psum(s, axis_name)
            return jnp.sqrt(s)

        def _scale(u, p, dim=-1):
            pn = _norm(p, dim)
            un = _norm(u, dim)
            # zero param (fresh bias) or zero update -> ratio 1 (optax's
            # min_norm=0 convention): never stall a layer on a 0/0.
            ratio = jnp.where((pn == 0.0) | (un == 0.0), 1.0, pn / un)
            return (u.astype(jnp.float32) * ratio).astype(u.dtype)

        if param_dims is None:
            scaled = jax.tree_util.tree_map(_scale, updates, params)
        else:
            scaled = jax.tree_util.tree_map(
                _scale, updates, params, param_dims
            )
        return scaled, state

    return optax.GradientTransformation(init_fn, update_fn)


def lamb_param_dims(config: FasterRCNNConfig, n_shards: int):
    """Per-leaf ZeRO-1 slice dims for the model's parameter tree.

    Derived from abstract shapes only (``jax.eval_shape`` — no FLOPs, no
    parameter memory) with the same ``parallel.zero.shard_dim`` rule the
    spmd backend uses to place its hand-written collectives, so the
    trust ratio's psum'd norms line up leaf-for-leaf with the slices
    ``tx.update`` actually receives inside the per-shard ZeRO update.
    """
    # Deferred import: parallel/__init__ -> spmd -> this module.  At call
    # time (trainer/warmup construction) both are fully imported.
    from replication_faster_rcnn_tpu.parallel.zero import shard_dim

    model = FasterRCNN(config)
    h, w = config.data.image_size

    def _init():
        return model.init(
            {"params": jax.random.PRNGKey(0)},
            jnp.zeros((1, h, w, 3), jnp.float32),
            train=False,
        )

    variables = jax.eval_shape(_init)
    return jax.tree_util.tree_map(
        lambda leaf: shard_dim(leaf.shape, n_shards), variables["params"]
    )


def make_optimizer(
    config: FasterRCNNConfig, steps_per_epoch: int, n_shards: int = 0
):
    """Adam + per-epoch cosine annealing (reference `train.py:139-140`:
    Adam(lr, weight_decay=5e-6) + CosineAnnealingLR(T_max=n_epoch)),
    with the optional large-batch recipe on top (`_schedule_knobs`;
    ``train.lars`` adds LAMB-style layer-wise trust-ratio scaling after
    Adam, ``train.optimizer='lamb'`` selects first-class LAMB whose
    trust ratio stays exact under ZeRO-1 sharding — see
    ``scale_by_sharded_trust_ratio``).

    ``n_shards`` is the size of the data axis the spmd backend's
    per-shard ZeRO update runs over (the trainer passes its mesh size).
    It only matters for LAMB with ``backend='spmd'`` +
    ``shard_opt_state``; every other caller can leave the default and
    gets the plain (unsharded) chain, so existing adam/lars program
    fingerprints are bitwise unchanged.

    The cosine is evaluated per step but changes value once per epoch,
    matching the reference's epoch-granular scheduler.step()
    (`train.py:148`); the warmup ramp, when enabled, is per-step.
    """
    tc = config.train
    peak, warmup_steps = _schedule_knobs(config, steps_per_epoch)

    def schedule(step):
        epoch = jnp.minimum(step // max(steps_per_epoch, 1), tc.n_epoch)
        lr = peak * 0.5 * (1.0 + jnp.cos(jnp.pi * epoch / tc.n_epoch))
        if warmup_steps > 0:
            warm = peak * (jnp.asarray(step, jnp.float32) + 1.0) / warmup_steps
            lr = jnp.where(step < warmup_steps, warm, lr)
        return lr

    # torch Adam's weight_decay is L2-added-to-grad, not decoupled AdamW.
    parts = [
        optax.add_decayed_weights(tc.weight_decay),
        optax.scale_by_adam(mu_dtype=jnp.dtype(tc.adam_mu_dtype)),
    ]
    if tc.lars:
        # trust-ratio AFTER the Adam preconditioner (LAMB's placement):
        # per-leaf |param|/|update| rescaling bounds the relative step.
        # Leaf-global norms — the shard_map ZeRO backend rejects the combo
        # (parallel/mesh.py::validate_parallel) since slices would see
        # partial norms; the jit backend's GSPMD inserts the reductions.
        parts.append(optax.scale_by_trust_ratio())
    if tc.optimizer == "lamb":
        # First-class LAMB: Adam preconditioner + trust ratio.  The
        # sharded variant is used ONLY where tx.update really runs on
        # slices — the spmd backend's per-shard ZeRO update (axis bound
        # inside shard_map).  The auto backend traces full logical
        # shapes (GSPMD inserts the reductions itself) and non-ZeRO spmd
        # updates full replicated leaves, so both get the plain variant.
        if tc.backend == "spmd" and tc.shard_opt_state and n_shards > 1:
            parts.append(
                scale_by_sharded_trust_ratio(
                    axis_name=config.mesh.data_axis,
                    param_dims=lamb_param_dims(config, n_shards),
                )
            )
        else:
            parts.append(scale_by_sharded_trust_ratio())
    parts.append(optax.scale_by_learning_rate(schedule))
    tx = optax.chain(*parts)
    return tx, schedule


def host_schedule(config: FasterRCNNConfig, steps_per_epoch: int):
    """Host-math twin of ``make_optimizer``'s schedule.

    The jnp schedule inside the optimizer is correct under jit, but
    evaluating it on the host (the per-step log path) builds a device
    scalar and ``float()`` then forces an implicit device sync — a
    jaxlint JX001 hit and a transfer-guard violation under strict mode.
    Same formula (cosine + linear warmup + large-batch peak scaling) in
    pure Python for host callers; keep the two in sync.
    """
    tc = config.train
    peak, warmup_steps = _schedule_knobs(config, steps_per_epoch)

    def schedule(step: int) -> float:
        epoch = min(int(step) // max(steps_per_epoch, 1), tc.n_epoch)
        lr = peak * 0.5 * (1.0 + math.cos(math.pi * epoch / tc.n_epoch))
        if warmup_steps > 0 and int(step) < warmup_steps:
            lr = peak * (int(step) + 1.0) / warmup_steps
        return float(lr)

    return schedule
