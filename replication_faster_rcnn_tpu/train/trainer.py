"""Training orchestration — capability parity with reference ``trainer``
(`train.py:13-151`), rebuilt around one jitted SPMD step:

  * mesh setup + batch sharding (reference: none — single device)
  * Adam + per-epoch cosine schedule (reference `train.py:139-140,148`)
  * per-step scalar metrics incl. images/sec (reference prints raw losses
    every step, `train.py:124`)
  * orbax checkpointing of params + BN stats + optimizer state + step with
    resume (the reference saves params-only every 10 epochs and restarts
    the schedule on load, `train.py:132-133,149-150` — SURVEY.md §5 flags
    this; here resume is exact)
  * optional pretrained-backbone graft (reference `resnet_torch.py:392-409`)
"""

from __future__ import annotations

import contextlib
import dataclasses
import os
import sys
import time
from typing import Dict, Optional

import jax
import numpy as np

from replication_faster_rcnn_tpu.config import FasterRCNNConfig
from replication_faster_rcnn_tpu.data import DataLoader, make_dataset
from replication_faster_rcnn_tpu.faultlib import failpoints
from replication_faster_rcnn_tpu.data.prefetch_device import (
    STAGED,
    DevicePrefetcher,
)
from replication_faster_rcnn_tpu.parallel import (
    Plan,
    compile_step_with_plan,
    fit_data_parallelism,
    is_coordinator,
    make_mesh,
    gather_replicated,
    replicate_tree,
    stage_to_devices,
    validate_parallel,
)
from replication_faster_rcnn_tpu.parallel import elastic as elastic_fleet
from replication_faster_rcnn_tpu.train import fault
from replication_faster_rcnn_tpu.train.async_checkpoint import (
    AsyncCheckpointWriter,
)
from replication_faster_rcnn_tpu.train.warmup import maybe_enable_compile_cache
from replication_faster_rcnn_tpu.train.train_step import (
    TrainState,
    build_multi_step,
    create_train_state,
    host_schedule,
    make_cached_multi_step,
    make_optimizer,
    make_train_step,
)
from replication_faster_rcnn_tpu.telemetry import spans as tspans
from replication_faster_rcnn_tpu.telemetry.watchdog import StallWatchdog
from replication_faster_rcnn_tpu.utils.logging import MetricLogger


def load_eval_variables(
    config: FasterRCNNConfig,
    workdir: str,
    step: Optional[int] = None,
):
    """(model, variables) for inference: fresh init, then the latest (or
    given) checkpoint restored if one exists. Avoids constructing a Trainer
    — eval must not require the train split or an optimizer."""
    import orbax.checkpoint as ocp

    from replication_faster_rcnn_tpu.models.faster_rcnn import FasterRCNN  # noqa: F401

    tx, _ = make_optimizer(config, steps_per_epoch=1)
    model, state = create_train_state(
        config, jax.random.PRNGKey(config.train.seed), tx
    )
    if os.path.isdir(workdir):
        mgr = ocp.CheckpointManager(os.path.abspath(workdir))
        try:
            if mgr.all_steps():
                # manifest-verified restore with latest-good fallback: a
                # torn newest step must not make eval unrecoverable either
                with tspans.current_tracer().span(
                    "checkpoint/restore", cat="checkpoint"
                ):
                    template = jax.device_get(state)
                result = fault.verified_restore(
                    mgr, template, os.path.abspath(workdir), step=step,
                )
                if result.state is not None:
                    state = result.state
        finally:
            mgr.close()
    return model, {"params": state.params, "batch_stats": state.batch_stats}


class Trainer:
    def __init__(
        self,
        config: FasterRCNNConfig,
        workdir: str = "checkpoints",
        dataset=None,
        devices=None,
        telemetry_dir: Optional[str] = None,
        stall_timeout_s: float = 300.0,
    ) -> None:
        self.config = config
        self.workdir = workdir
        # persistent XLA compilation cache (compile.cache_dir): must be
        # enabled before the first jitted call traces — jit is lazy, so
        # doing it here covers every program this trainer compiles
        maybe_enable_compile_cache(config)
        validate_parallel(
            config, len(devices) if devices is not None else None
        )
        if config.mesh.num_data <= 0:
            # fit the data axis to the batch (a non-dividing batch fails in
            # jit with an opaque sharding error — e.g. the reference's
            # default batch 2 on an 8-chip host), leaving room for any
            # model-parallel axis
            n_dev = len(devices) if devices is not None else len(jax.devices())
            n_dev //= max(1, config.mesh.num_model)
            config = config.replace(
                mesh=dataclasses.replace(
                    config.mesh,
                    num_data=fit_data_parallelism(config.train.batch_size, n_dev),
                )
            )
            self.config = config
        self.mesh = make_mesh(config.mesh, devices)
        # multi-process identity: the coordinator (process 0) owns the
        # checkpoint store, manifests and the canonical telemetry files;
        # every other rank writes rank-suffixed telemetry files so
        # `frcnn telemetry` can merge and group per-rank traces
        self._rank = jax.process_index()
        self._process_count = jax.process_count()

        # --- telemetry: span tracer + JSONL metrics + stall watchdog.
        # With no telemetry_dir everything collapses to no-ops (NULL
        # tracer spans, stream-only logger, no watchdog thread).
        self.telemetry_dir = telemetry_dir
        self.watchdog: Optional[StallWatchdog] = None
        if telemetry_dir:
            os.makedirs(telemetry_dir, exist_ok=True)
            rank = self._rank if self._process_count > 1 else None

            def _rank_file(name: str) -> str:
                # trace.json -> trace.rank1.json on non-coordinator ranks
                if not rank:
                    return os.path.join(telemetry_dir, name)
                stem, ext = os.path.splitext(name)
                return os.path.join(telemetry_dir, f"{stem}.rank{rank}{ext}")

            self.tracer = tspans.SpanTracer(
                _rank_file("trace.json"),
                rank=rank,
                max_events=config.telemetry.trace_max_events,
            )
            # install process-wide so the loader/evaluator/device-cache
            # span call sites (which take no tracer parameter) attach here
            tspans.set_tracer(self.tracer)
            self.logger = MetricLogger(
                jsonl_path=_rank_file("metrics.jsonl"), rank=rank
            )
            self.watchdog = StallWatchdog(
                timeout_s=stall_timeout_s,
                snapshot_path=_rank_file("watchdog.jsonl"),
                progress_path=_rank_file("progress.json"),
                tracer=self.tracer,
                rank=rank,
                on_stall=lambda snap: self.logger.event(
                    "stall",
                    elapsed_s=snap.get("elapsed_since_progress_s"),
                    last_step=snap.get("last_step"),
                    last_phase=snap.get("last_phase"),
                ),
            )
        else:
            self.tracer = tspans.NULL_TRACER
            self.logger = MetricLogger()

        # fault-tolerance plumbing (train/fault.py): consecutive-skip
        # escalation for the guarded update's `skipped` flags, and the
        # dispatch-boundary shutdown flag train() installs
        self.skip_monitor = fault.SkipMonitor(
            policy=config.train.nonfinite_policy,
            max_consecutive=config.train.max_consecutive_skips,
            on_escalate=self._fault_incident,
        )
        self._host_step = 0  # host mirror of state.step: no sync to read
        self._shutdown: Optional[fault.GracefulShutdown] = None

        # chaos runs: every injected fault lands in the metric stream and
        # the watchdog incident log, so a post-mortem can line up observed
        # failures against the schedule that caused them
        if failpoints.armed():
            failpoints.set_sink(self._chaos_sink)

        self.dataset = dataset if dataset is not None else make_dataset(
            config.data, "train"
        )
        self.device_cache = None
        self.sampler = None
        if config.data.cache_device:
            # device-resident feed: dataset lives in HBM, the step gathers
            # and augments on device, the host ships only per-step indices
            # (data/device_cache.py — the route past a transfer-bound
            # loader). The jitter resample necessarily runs on device in
            # this mode, the path already proven at training quality
            # (0.591 vs host 0.592 val mAP, PARITY.md). Feed/backend
            # compatibility (cache×spmd, cache×multiprocess, ...) was
            # already rejected above by the Plan.validate decision table.
            from replication_faster_rcnn_tpu.data.device_cache import (
                CachedSampler,
                DeviceCache,
            )

            self.device_cache = DeviceCache(self.dataset, mesh=self.mesh)
            self.sampler = CachedSampler(
                len(self.dataset),
                self.device_cache.image_hw,
                batch_size=config.train.batch_size,
                seed=config.train.seed,
                hflip=config.data.augment_hflip,
                scale_range=config.data.augment_scale,
                process_index=self._rank,
                process_count=self._process_count,
                train_resolutions=config.data.train_resolutions,
                bucket_chunk=max(1, config.train.steps_per_dispatch),
            )
            self.loader = None
            steps_per_epoch = max(len(self.sampler), 1)
        else:
            # each process loads only its contiguous block of every global
            # batch (loader.py); batch_size stays GLOBAL so schedules and
            # step counts are topology-invariant
            self.loader = DataLoader(
                self.dataset,
                batch_size=config.train.batch_size,
                shuffle=True,
                seed=config.train.seed,
                prefetch=config.data.loader_prefetch,
                num_workers=config.data.loader_workers,
                worker_mode=config.data.loader_mode,
                augment_hflip=config.data.augment_hflip,
                augment_scale=config.data.augment_scale,
                augment_scale_device=config.data.augment_scale_device,
                augment_device=config.data.augment_device,
                augment_translate=config.data.augment_translate,
                cache_ram=config.data.loader_cache_ram,
                process_index=self._rank,
                process_count=self._process_count,
                train_resolutions=config.data.train_resolutions,
                bucket_chunk=max(1, config.train.steps_per_dispatch),
            )
            steps_per_epoch = max(len(self.loader), 1)
        # n_shards sizes LAMB's psum'd trust-ratio norms to the data axis
        # the per-shard ZeRO update runs over; inert for adam/lars.
        self.tx, self.schedule = make_optimizer(
            config,
            steps_per_epoch,
            n_shards=self.mesh.shape[config.mesh.data_axis],
        )
        # host-math twin for log rows: evaluating the jnp schedule on the
        # host would build + sync a device scalar every logged step
        self.host_schedule = host_schedule(config, steps_per_epoch)
        self.model, state = create_train_state(
            config, jax.random.PRNGKey(config.train.seed), self.tx
        )
        from replication_faster_rcnn_tpu.parallel.zero import (
            place_train_state,
            train_state_shardings,
        )

        # params/BN replicated (params mp-sharded over the model axis
        # under mesh.param_sharding); Adam moments sharded over the data
        # axis when ZeRO-1 weight-update sharding is on (`parallel/zero.py`)
        self._state_shardings = train_state_shardings(
            state, self.mesh, config.mesh, config.train.shard_opt_state
        )
        self._mp = (
            config.mesh.param_sharding
            and self.mesh.shape[config.mesh.model_axis] > 1
        )
        self.state: TrainState = place_train_state(state, self._state_shardings)

        # --- dispatch: every train program compiles through ONE layer,
        # parallel/plan.py::compile_step_with_plan. The shard_map backend
        # builds its own Plan (in/out specs) inside
        # make_shard_map_train_step; the jit auto-partitioning feeds share
        # this pjit plan — donated state, out_shardings pinning the
        # (possibly mp-sharded) state layout stable across steps.
        self._step_plan = Plan(
            mesh=self.mesh,
            donate_argnums=(0,),
            out_shardings=(self._state_shardings, None),
            param_specs=jax.tree_util.tree_map(
                lambda s: s.spec, self._state_shardings.params
            ),
            label="train_step",
        )
        if config.train.backend == "spmd":
            from replication_faster_rcnn_tpu.parallel import make_shard_map_train_step

            # explicit-collective step (psum allreduce + sync-BN); the
            # parameter tree is identical, so eval/checkpoints are unchanged.
            # state_template carries full leaf shapes so the ZeRO variant
            # (train.shard_opt_state) can derive shard dims outside the body
            self.jitted_step, _ = make_shard_map_train_step(
                config, self.tx, self.mesh, state_template=self.state
            )
        elif config.data.cache_device:
            from replication_faster_rcnn_tpu.train.train_step import (
                make_cached_train_step,
            )

            # (state, cache, sel) step; the cache argument is the same
            # device-resident buffers every call — never donated
            self.jitted_step = compile_step_with_plan(
                make_cached_train_step(self.model, config, self.tx),
                self._step_plan,
            )
        else:
            self.jitted_step = compile_step_with_plan(
                make_train_step(self.model, config, self.tx),
                self._step_plan,
            )
        # fused multi-step dispatch (train.steps_per_dispatch > 1): one
        # jitted call trains K steps via lax.scan (train_chunk). The plain
        # per-step function above stays — jit compiles lazily, so it only
        # costs a compile if an epoch tail (steps_per_epoch % K != 0) or a
        # direct train_one_batch caller actually runs it.
        self.steps_per_dispatch = max(1, config.train.steps_per_dispatch)
        self.jitted_multi_step = None
        if self.steps_per_dispatch > 1:
            k = self.steps_per_dispatch
            multi_plan = dataclasses.replace(
                self._step_plan, label=f"multi_step_k{k}"
            )
            if config.train.backend == "spmd":
                from replication_faster_rcnn_tpu.parallel import (
                    make_shard_map_train_step,
                )

                self.jitted_multi_step, _ = make_shard_map_train_step(
                    config, self.tx, self.mesh, steps_per_dispatch=k,
                    state_template=self.state,
                )
            elif config.data.cache_device:
                self.jitted_multi_step = compile_step_with_plan(
                    make_cached_multi_step(self.model, config, self.tx, k),
                    multi_plan,
                )
            else:
                self.jitted_multi_step = compile_step_with_plan(
                    build_multi_step(
                        make_train_step(self.model, config, self.tx), k
                    ),
                    multi_plan,
                )
        # ops.backend=pallas: pin the backend scope around every trace of
        # the step programs (jit is lazy — without this the first dispatch
        # would trace the default XLA ops; see train/warmup.py). xla
        # configs get the jit objects back unchanged.
        from replication_faster_rcnn_tpu import ops as ops_pkg
        from replication_faster_rcnn_tpu.train.warmup import scope_jitted

        if ops_pkg.resolve_backend(config) == "pallas":
            self.jitted_step = scope_jitted(self.jitted_step, config)
            if self.jitted_multi_step is not None:
                self.jitted_multi_step = scope_jitted(
                    self.jitted_multi_step, config
                )
        # multi-scale resolution buckets (data.train_resolutions): one
        # compiled program per bucket, each baking the bucket's static
        # (h, w) on-device resample into the trace (compute_losses) under
        # its own Plan label — the serving-bucket pattern applied to
        # training, so the strict harness, warmup registry and HLO audit
        # all see per-bucket programs as first-class citizens. The
        # unbucketed programs above stay (jit is lazy; they only compile
        # if dispatched). Buckets compose with every backend — the only
        # genuine constraint (spatial row divisibility per resolution) was
        # already checked by the Plan.validate decision table.
        self._bucket_resolutions = tuple(config.data.train_resolutions)
        self.jitted_bucket_steps = None
        self.jitted_bucket_multi_steps = None
        if self._bucket_resolutions:
            from replication_faster_rcnn_tpu.train.train_step import (
                make_cached_train_step,
            )

            pallas = ops_pkg.resolve_backend(config) == "pallas"
            k = self.steps_per_dispatch
            steps, multis = [], []
            for bh, bw in self._bucket_resolutions:
                if config.train.backend == "spmd":
                    # per-bucket shard_map program: the in/out specs shard
                    # batch dims only (resolution-independent), so each
                    # bucket reuses the same Plan shape with the bucket's
                    # resample traced into the per-shard body — bucketed
                    # multi-scale composes with spmd and ZeRO-1 unchanged
                    from replication_faster_rcnn_tpu.parallel import (
                        make_shard_map_train_step,
                    )

                    jitted, _ = make_shard_map_train_step(
                        config, self.tx, self.mesh,
                        state_template=self.state,
                        train_resolution=(bh, bw),
                    )
                    steps.append(
                        scope_jitted(jitted, config) if pallas else jitted
                    )
                    if k > 1:
                        mj, _ = make_shard_map_train_step(
                            config, self.tx, self.mesh,
                            steps_per_dispatch=k,
                            state_template=self.state,
                            train_resolution=(bh, bw),
                        )
                        multis.append(
                            scope_jitted(mj, config) if pallas else mj
                        )
                    continue
                plan = dataclasses.replace(
                    self._step_plan, label=f"train_step_{bh}x{bw}"
                )
                if config.data.cache_device:
                    fn = make_cached_train_step(
                        self.model, config, self.tx, train_resolution=(bh, bw)
                    )
                else:
                    fn = make_train_step(
                        self.model, config, self.tx, train_resolution=(bh, bw)
                    )
                jitted = compile_step_with_plan(fn, plan)
                steps.append(scope_jitted(jitted, config) if pallas else jitted)
                if k > 1:
                    mplan = dataclasses.replace(
                        self._step_plan, label=f"multi_step_k{k}_{bh}x{bw}"
                    )
                    if config.data.cache_device:
                        mfn = make_cached_multi_step(
                            self.model, config, self.tx, k,
                            train_resolution=(bh, bw),
                        )
                    else:
                        mfn = build_multi_step(
                            make_train_step(
                                self.model, config, self.tx,
                                train_resolution=(bh, bw),
                            ),
                            k,
                        )
                    mj = compile_step_with_plan(mfn, mplan)
                    multis.append(scope_jitted(mj, config) if pallas else mj)
            self.jitted_bucket_steps = steps
            if multis:
                self.jitted_bucket_multi_steps = multis
        # runtime hygiene gate (debug.strict / --strict): transfer guard +
        # recompile detector around every dispatch, armed after warmup
        self.strict = None
        if config.debug.strict:
            from replication_faster_rcnn_tpu.analysis.strict import StrictHarness

            self.strict = StrictHarness(
                warmup_dispatches=config.debug.strict_warmup
            )
        self._ckpt_mgr = None
        # topology provenance stamped into every checkpoint manifest:
        # restore on a DIFFERENT topology is supported (checkpoints are
        # saved fully replicated; fault.verified_restore re-places), the
        # stamp just makes a cross-topology resume visible in the logs
        self._topology = fault.run_topology(config, self.mesh)
        # elastic fleet membership (parallel/elastic.py): when a
        # supervisor exported the fleet dir and we have peers to watch,
        # run the heartbeat/lease agent. It is STARTED lazily at the
        # first dispatch boundary (_check_fleet) — leases during the
        # multi-minute compile window would read as dead ranks. The
        # agent's watchdog thread writes the durable shrink intent and
        # hard-exits EXIT_FLEET_SHRINK if the main thread is stuck in a
        # dead fleet's collective; the on_lost hook records the incident
        # before that exit.
        fleet_dir, fleet_gen = elastic_fleet.fleet_env()
        self._fleet_generation = fleet_gen
        self.elastic_agent: Optional[elastic_fleet.ElasticAgent] = None
        if fleet_dir and self._process_count > 1:
            el = config.elastic
            self.elastic_agent = elastic_fleet.ElasticAgent(
                fleet_dir,
                fleet_gen,
                self._rank,
                self._process_count,
                heartbeat_interval_s=el.heartbeat_interval_s,
                lease_timeout_s=el.lease_timeout_s,
                on_lost=lambda lost, survivors: self._fault_incident(
                    "fleet_rank_lost",
                    generation=fleet_gen,
                    lost=lost,
                    survivors=survivors,
                ),
            )
        # background scheduled-checkpoint writer (train.async_checkpoint).
        # Single-process: the writer serializes a host numpy snapshot.
        # Multi-process: EVERY rank runs a writer thread and the snapshot
        # stays on device (fresh replicated buffers via gather_replicated,
        # so donation can't delete them mid-write); the writer threads run
        # the collective orbax save in lockstep, preserving orbax's
        # replica/writer election, and only the coordinator writes the
        # manifest.
        self._async_writer: Optional[AsyncCheckpointWriter] = None
        if config.train.async_checkpoint:
            self._async_writer = AsyncCheckpointWriter()

    # ---------------------------------------------------------- checkpoints

    @property
    def checkpoint_manager(self):
        if self._ckpt_mgr is None:
            import orbax.checkpoint as ocp

            self._ckpt_mgr = ocp.CheckpointManager(
                os.path.abspath(self.workdir),
                options=ocp.CheckpointManagerOptions(max_to_keep=3, create=True),
            )
        return self._ckpt_mgr

    def _replicated_state(self) -> TrainState:
        """State with every leaf fully replicated on the mesh. Sharded
        optimizer state (ZeRO-1) is all-gathered via a compiled identity
        (`gather_replicated`) — a plain device_put cannot reshard leaves
        whose shards live on other processes' chips (multi-host)."""
        state = self.state
        if self._mp:
            # model-parallel weights live 1/mp per chip; checkpoints stay
            # fully replicated (topology-portable), so gather them back
            state = state.replace(
                params=gather_replicated(state.params, self.mesh)
            )
        if self.config.train.shard_opt_state:
            # gather ONLY the sharded subtrees: BN stats (and params
            # outside mp mode) are already replicated, and a jitted
            # identity (unlike device_put) always materializes fresh
            # output buffers — gathering the whole state would transiently
            # hold a second copy of the model at every checkpoint event
            state = state.replace(
                opt_state=gather_replicated(state.opt_state, self.mesh)
            )
        return state

    def _host_state(self):
        """Full state on host (numpy)."""
        with self.tracer.span("state/host_fetch", cat="sync"):
            return jax.device_get(self._replicated_state())

    def _chaos_sink(self, event) -> None:
        """Record one injected fault as a ``chaos_injected`` incident (the
        event's own ``kind`` — the fault kind — is renamed so it can't
        collide with the incident kind)."""
        fields = dict(event)
        fields["fault_kind"] = fields.pop("kind", None)
        self._fault_incident("chaos_injected", **fields)

    def _fault_incident(self, kind: str, **fields) -> None:
        """Route a fault event to the JSONL metric stream AND the watchdog
        incident log, so `telemetry report` and post-mortems both see it."""
        self.logger.event(kind, **fields)
        if self.watchdog is not None:
            self.watchdog.incident(kind, **fields)

    def _handle_async_error(self, err) -> None:
        """Containment for a failed BACKGROUND scheduled save, surfaced at
        a drain point: same policy as a failed synchronous scheduled save
        (stderr warning + incident, training continues, next interval
        retries). Never raises — only scheduled saves ride the writer."""
        if err is None:
            return
        err_step, exc = err
        print(
            f"warning: async scheduled checkpoint at step {err_step} failed "
            f"({type(exc).__name__}: {exc}); training continues",
            file=sys.stderr,
        )
        self._fault_incident(
            "checkpoint_save_failed",
            step=err_step,
            ckpt_kind="scheduled",
            writer="async",
            error=f"{type(exc).__name__}: {exc}"[:300],
        )

    def _drain_async_saves(self) -> None:
        """Wait out any in-flight background save (handling its error, if
        any). Called before every synchronous save, before restore, and at
        train() exit, so the checkpoint store is never touched from two
        threads and the newest scheduled save is on disk before anything
        that depends on it runs."""
        if self._async_writer is not None:
            self._handle_async_error(self._async_writer.wait())

    def _save_async(self, step: int) -> bool:
        """Scheduled save via the background writer: the trainer thread
        pays only the snapshot — serialize + manifest + prune run on the
        writer thread (train/async_checkpoint.py). Blocks only while the
        PREVIOUS save is still in flight.

        The snapshot is a host device_get in a single-process run (byte-
        identical to the pre-multi-host path). In a multi-process run the
        snapshot instead stays ON DEVICE as fresh replicated buffers
        (`gather_replicated` — a jitted identity always materializes new
        output buffers, so the training loop's donation cannot delete them
        mid-write): orbax's multi-process replica election needs live
        jax.Arrays, and every rank's writer thread runs the collective
        save in lockstep while only the coordinator writes the manifest."""
        import orbax.checkpoint as ocp

        writer = self._async_writer
        multiproc = self._process_count > 1
        # bound in-flight depth at one; a prior failure surfaces here with
        # scheduled-save containment semantics
        self._handle_async_error(writer.wait())
        try:
            # the writer is drained, so a successful save at `step` is
            # visible via latest_step(); a FAILED one is not, and falls
            # through to a retry here
            if self.checkpoint_manager.latest_step() == step:
                return True
            with self.tracer.span(
                "checkpoint/snapshot", cat="checkpoint", step=step
            ):
                if multiproc:
                    # fresh replicated device buffers, donation-safe
                    snapshot = gather_replicated(self.state, self.mesh)
                else:
                    snapshot = jax.device_get(self._replicated_state())
        except Exception as e:
            print(
                f"warning: scheduled checkpoint at step {step} failed "
                f"({type(e).__name__}: {e}); training continues",
                file=sys.stderr,
            )
            self._fault_incident(
                "checkpoint_save_failed",
                step=step,
                ckpt_kind="scheduled",
                writer="async",
                error=f"{type(e).__name__}: {e}"[:300],
            )
            return False

        mgr = self.checkpoint_manager
        workdir, config = self.workdir, self.config
        topology = self._topology
        tracer = self.tracer

        def _write() -> None:
            # failpoint: ioerror raises on the writer thread and surfaces
            # at the next drain point via _handle_async_error; torn_write/
            # crc_corrupt damage the finished step dir below so restore's
            # manifest verification must walk back past it
            inj = failpoints.fire("checkpoint.write", step=step, writer="async")
            mgr.save(step, args=ocp.args.StandardSave(snapshot))
            mgr.wait_until_finished()
            if not is_coordinator():
                return
            # same manifest writer as the sync path: restore-side
            # verification and the fallback walk stay bit-for-bit
            if multiproc:
                with tracer.span("checkpoint/manifest", cat="checkpoint"):
                    host_state = jax.device_get(snapshot)
            else:
                host_state = snapshot
            fault.write_manifest(
                workdir, step, host_state, config,
                kind="scheduled", writer="async", topology=topology,
            )
            # rollout feed: announce the new version to serving-side
            # watchers (serving/rollout/) AFTER the manifest is durable
            fault.publish_manifest_event(
                workdir, step, kind="scheduled", writer="async"
            )
            fault.prune_manifests(workdir, mgr.all_steps())
            if inj is not None and inj.kind in ("torn_write", "crc_corrupt"):
                failpoints.apply_file_fault(
                    inj,
                    failpoints.find_step_dir(
                        workdir, step, exclude=(fault.MANIFEST_DIRNAME,)
                    ),
                )

        self._handle_async_error(writer.submit(step, _write))
        return True

    def save(
        self,
        step: Optional[int] = None,
        kind: str = "scheduled",
        required: Optional[bool] = None,
    ) -> bool:
        """Checkpoint the full state, plus a sidecar manifest (step, config
        hash, per-leaf checksums, save ``kind``) that restore() verifies.

        A ``scheduled`` (periodic) save that fails is contained: watchdog
        incident + warning, training continues and the next interval
        retries — a full disk mid-run should cost a checkpoint, not the
        run. ``emergency``/``final`` saves (or ``required=True``) raise,
        because they are the last chance to persist anything. Returns
        True when a checkpoint for ``step`` is on disk (for async
        scheduled saves: submitted to the background writer).

        With ``train.async_checkpoint`` on, scheduled saves go through
        :meth:`_save_async`; emergency/final/required saves stay
        synchronous here — they are the last write before the process
        exits and must complete, so they first drain the writer."""
        import orbax.checkpoint as ocp

        if required is None:
            required = kind in ("emergency", "final")
        step = int(self.state.step) if step is None else step
        if (
            self._async_writer is not None
            and kind == "scheduled"
            and not required
        ):
            return self._save_async(step)
        # synchronous save: the store must be quiet first
        self._drain_async_saves()
        try:
            if self.checkpoint_manager.latest_step() == step:
                return True  # already checkpointed (orbax raises on dupes)
            # failpoint: ioerror raises here, riding the scheduled-save
            # containment below (or the required-save raise); torn_write/
            # crc_corrupt damage the finished step dir after the write
            inj = failpoints.fire("checkpoint.write", step=step, writer="sync")
            # Hand orbax the REPLICATED jax arrays, not host numpy: with
            # jax.Array inputs orbax's replica logic makes process 0 the
            # only writer in a multi-process run; a device_get'd numpy tree
            # loses that information and every process tries to write the
            # same files (observed as a deadlock inside save() in the
            # 2-process test).
            rep_state = self._replicated_state()
            self.checkpoint_manager.save(
                step, args=ocp.args.StandardSave(rep_state)
            )
            self.checkpoint_manager.wait_until_finished()
            if is_coordinator():
                with self.tracer.span("checkpoint/manifest", cat="checkpoint"):
                    host_state = jax.device_get(rep_state)
                fault.write_manifest(
                    self.workdir, step, host_state, self.config, kind=kind,
                    topology=self._topology,
                )
                # rollout feed: announce the new version to serving-side
                # watchers once the manifest is durable
                fault.publish_manifest_event(
                    self.workdir, step, kind=kind, writer="sync"
                )
                fault.prune_manifests(
                    self.workdir, self.checkpoint_manager.all_steps()
                )
                if inj is not None and inj.kind in (
                    "torn_write", "crc_corrupt",
                ):
                    failpoints.apply_file_fault(
                        inj,
                        failpoints.find_step_dir(
                            self.workdir, step,
                            exclude=(fault.MANIFEST_DIRNAME,),
                        ),
                    )
        except Exception as e:
            if required:
                raise
            print(
                f"warning: {kind} checkpoint at step {step} failed "
                f"({type(e).__name__}: {e}); training continues",
                file=sys.stderr,
            )
            self._fault_incident(
                "checkpoint_save_failed",
                step=step,
                ckpt_kind=kind,
                error=f"{type(e).__name__}: {e}"[:300],
            )
            return False
        return True

    def restore(
        self, step: Optional[int] = None, directory: Optional[str] = None
    ) -> int:
        """Exact resume: params, BN stats, optimizer state AND step —
        manifest-verified, falling back to the newest verifiable step when
        the latest is torn (fault.verified_restore). Discarded steps are
        logged, recorded as an incident, and deleted from this trainer's
        own store so future saves at those steps don't collide.

        ``directory`` restores from a different checkpoint dir WITHOUT
        changing where this trainer saves (warm-start semantics; treated
        read-only — nothing is deleted there)."""
        import orbax.checkpoint as ocp

        self._drain_async_saves()  # never read a store mid-write
        ephemeral = directory is not None
        dirpath = os.path.abspath(directory if ephemeral else self.workdir)
        if ephemeral:
            mgr = ocp.CheckpointManager(dirpath)
        else:
            mgr = self.checkpoint_manager
        try:
            if not mgr.all_steps():
                return 0
            template = self._host_state()
            result = fault.verified_restore(
                mgr, template, dirpath, step=step
            )
            if result.discarded:
                if not ephemeral:
                    for bad_step, _ in result.discarded:
                        try:
                            mgr.delete(bad_step)
                        except Exception:
                            pass  # a torn step may resist deletion too
                self._fault_incident(
                    "checkpoint_fallback",
                    restored_step=result.step,
                    discarded={s: why for s, why in result.discarded},
                )
        finally:
            if ephemeral:
                mgr.close()
        if result.state is None:
            return 0
        from replication_faster_rcnn_tpu.parallel.zero import place_train_state

        self.state = place_train_state(result.state, self._state_shardings)
        self._host_step = int(self.state.step)
        return self._host_step

    def load_pretrained_backbone(self, pth_path: str) -> None:
        """Graft a torch resnet checkpoint into trunk + head tail."""
        from replication_faster_rcnn_tpu.models import convert

        with self.tracer.span("checkpoint/graft", cat="checkpoint"):
            variables = {
                "params": jax.device_get(self.state.params),
                "batch_stats": jax.device_get(self.state.batch_stats),
            }
        grafted = convert.graft_into_variables(variables, pth_path)
        from replication_faster_rcnn_tpu.parallel.mesh import put_host_tree

        # params go back onto their plan layout (mp-sharded under
        # mesh.param_sharding, replicated otherwise); BN stats replicate
        self.state = self.state.replace(
            params=put_host_tree(
                grafted["params"], self._state_shardings.params
            ),
            batch_stats=replicate_tree(grafted["batch_stats"], self.mesh),
        )

    # ---------------------------------------------------------------- train

    def _stage_batch(
        self, batch: Dict[str, np.ndarray], wait: bool = False
    ) -> Dict[str, jax.Array]:
        """One host batch (or --cache-device selection dict) -> sharded
        device arrays: the ``data/device_put`` half of a step. ``wait``
        blocks until the transfer lands — used by the device stager's
        producer thread so the copy itself is off the critical path."""
        feed = "device_cache" if self.device_cache is not None else "loader"
        with self.tracer.span("data/device_put", cat="data", feed=feed):
            return stage_to_devices(
                batch, self.mesh, self.config.mesh, wait=wait
            )

    def _stage_chunk(self, batches, wait: bool = False) -> Dict[str, jax.Array]:
        """K host batches -> one stacked [K, B, ...] sharded device chunk
        for the fused dispatch (stack_selections in --cache-device mode,
        np.stack otherwise)."""
        k = len(batches)
        if self.device_cache is not None:
            from replication_faster_rcnn_tpu.data.device_cache import (
                stack_selections,
            )

            stacked = stack_selections(batches)
            feed = "device_cache"
        else:
            stacked = {
                key: np.stack([b[key] for b in batches]) for key in batches[0]
            }
            feed = "loader"
        with self.tracer.span(
            "data/device_put", cat="data", feed=feed, steps=k
        ):
            return stage_to_devices(
                stacked, self.mesh, self.config.mesh, stacked=True, wait=wait
            )

    def train_one_batch(
        self,
        batch: Optional[Dict[str, np.ndarray]] = None,
        staged: Optional[Dict[str, jax.Array]] = None,
        bucket: Optional[int] = None,
    ) -> Dict[str, float]:
        """One optimizer step. Callers pass either a host ``batch`` (staged
        here, the synchronous pre-PR-4 path) or an already device-resident
        ``staged`` batch from the DevicePrefetcher. ``bucket`` selects one
        multi-scale resolution bucket's compiled program (the feed's
        ``bucket_of`` assignment); None dispatches the single-scale
        program."""
        tracer = self.tracer
        if staged is None:
            # in --cache-device mode `batch` is a selection dict (idx/flip/
            # jitter — bytes, not megabytes); the images never leave device
            staged = self._stage_batch(batch)
        step_fn = self.jitted_step
        program = "train_step"
        if bucket is not None and self.jitted_bucket_steps is not None:
            bh, bw = self._bucket_resolutions[bucket]
            step_fn = self.jitted_bucket_steps[bucket]
            program = f"train_step_{bh}x{bw}"
        strict = self._strict_dispatch(program, step_fn)
        if self.device_cache is not None:
            with tracer.span("step/dispatch", cat="step"), strict:
                self.state, metrics = step_fn(
                    self.state, self.device_cache.arrays, staged
                )
        else:
            with tracer.span("step/dispatch", cat="step"), strict:
                self.state, metrics = step_fn(self.state, staged)
        self._host_step += 1
        # hand the monitor this step's `skipped` flag as a DEVICE scalar —
        # it syncs only at drain points, preserving dispatch overlap
        self.skip_monitor.observe(self._host_step, metrics)
        return metrics

    def train_chunk(
        self,
        batches=None,
        staged: Optional[Dict[str, jax.Array]] = None,
        bucket: Optional[int] = None,
    ) -> Dict[str, np.ndarray]:
        """Train ``steps_per_dispatch`` steps in ONE fused jitted dispatch.

        ``batches`` must hold exactly ``steps_per_dispatch`` host batches
        (selection dicts in --cache-device mode) — the fused program was
        compiled for that K. Alternatively ``staged`` is a pre-staged
        stacked device chunk from the DevicePrefetcher (already sharded,
        transfer landed). Returns stacked [K, ...] metrics, still on
        device: callers sync them only at log boundaries so the whole
        chunk's dispatch overlaps device compute.
        """
        k = self.steps_per_dispatch
        if staged is None:
            if len(batches) != k:
                raise ValueError(
                    f"train_chunk got {len(batches)} batches; the fused step "
                    f"was compiled for steps_per_dispatch={k}"
                )
            staged = self._stage_chunk(batches)
        tracer = self.tracer
        step_fn = self.jitted_multi_step
        program = f"multi_step_k{k}"
        if bucket is not None and self.jitted_bucket_multi_steps is not None:
            bh, bw = self._bucket_resolutions[bucket]
            step_fn = self.jitted_bucket_multi_steps[bucket]
            program = f"multi_step_k{k}_{bh}x{bw}"
        strict = self._strict_dispatch(program, step_fn)
        if self.device_cache is not None:
            with tracer.span("step/dispatch", cat="step", steps=k), strict:
                self.state, metrics = step_fn(
                    self.state, self.device_cache.arrays, staged
                )
        else:
            with tracer.span("step/dispatch", cat="step", steps=k), strict:
                self.state, metrics = step_fn(
                    self.state, staged
                )
        first = self._host_step + 1
        self._host_step += k
        self.skip_monitor.observe(first, metrics)  # stacked [K] device flags
        return metrics

    def _strict_dispatch(self, program: str, fn):
        """Strict-mode gate for one dispatch of ``program`` (no-op context
        when strict mode is off)."""
        if self.strict is None:
            return contextlib.nullcontext()
        return self.strict.dispatch(program, fn)

    def strict_session(self):
        """Transfer-guard session for the whole loop (no-op when off).
        Callers driving :meth:`train_one_batch` directly (the CLI bounded
        --steps loop) wrap their loop in this."""
        if self.strict is None:
            return contextlib.nullcontext()
        return self.strict.session()

    def flush_telemetry(self) -> None:
        """Write the trace file and stop the watchdog. For callers driving
        :meth:`train_one_batch` directly without :meth:`telemetry_session`."""
        if self.watchdog is not None:
            self.watchdog.stop()
        self.tracer.flush()

    @contextlib.contextmanager
    def telemetry_session(self):
        """Watchdog running inside, tracer flushed + watchdog stopped on ANY
        exit — including KeyboardInterrupt and crashes, which additionally
        record an ``abnormal_exit`` incident so the post-mortem doesn't
        start from a silently-truncated trace."""
        if self.watchdog is not None:
            if self.loader is not None:
                self.watchdog.providers.setdefault(
                    "loader_queue_depth", self.loader.queue_depth
                )
            self.watchdog.start()
        try:
            yield self
        except BaseException as e:
            if self.watchdog is not None:
                self.watchdog.incident(
                    "abnormal_exit", error=f"{type(e).__name__}: {e}"[:500]
                )
            raise
        finally:
            if self.watchdog is not None:
                self.watchdog.stop()
            self.tracer.flush()

    def _check_preemption(self, step: int) -> None:
        """Dispatch-boundary shutdown check: on a pending SIGTERM/SIGINT,
        save a verified emergency checkpoint, record the incident, and
        leave via :class:`fault.Preempted` (CLI exit code EXIT_PREEMPTED)."""
        sd = self._shutdown
        if sd is None or not sd.requested:
            return
        reason = sd.reason or "signal"
        self._fault_incident("preempted", step=step, reason=reason)
        with self.tracer.span(
            "checkpoint/save", cat="checkpoint", kind="emergency"
        ):
            self.save(kind="emergency")
        raise fault.Preempted(step, reason)

    def _check_fleet(self, step: int) -> None:
        """Dispatch-boundary elastic check: start the heartbeat/watchdog
        agent lazily on the first call (a dispatch retired, so compile is
        over and lease cadence is trustworthy), then surface any
        watchdog-detected rank loss as :class:`fault.FleetShrink`.
        Deliberately NO emergency checkpoint here — saves are
        cross-process collectives and would hang on the dead peer;
        survivors fall back to the last CRC-verified step
        (``train.checkpoint_every_steps`` bounds the rollback). The
        incident and the durable shrink intent were already recorded by
        the agent when it detected the loss."""
        agent = self.elastic_agent
        if agent is None:
            return
        agent.start()
        lost = agent.check()
        if lost:
            raise fault.FleetShrink(step, lost, agent.survivors(lost))

    def _maybe_step_checkpoint(self, step: int) -> None:
        """Scheduled mid-epoch save every ``train.checkpoint_every_steps``
        optimizer steps (0 = epoch-boundary saves only). Boundary-crossing
        logic (not ``step % every``) so fused K-step dispatches cannot
        jump over a save point. Deterministic across ranks — every rank
        sees the same step sequence, so the collective save stays in
        lockstep."""
        every = self.config.train.checkpoint_every_steps
        if not every or step - self._last_step_ckpt < every:
            return
        self._last_step_ckpt = step
        if self.watchdog is not None:
            self.watchdog.beat(phase="checkpoint")
        with self.tracer.span(
            "checkpoint/save", cat="checkpoint", boundary="step"
        ):
            self.save()

    def evaluate(self, max_images: Optional[int] = None) -> Dict[str, float]:
        """mAP on the val split with the CURRENT training parameters
        (reference: impossible — its eval was never written, SURVEY §2.1 #15).

        The val dataset and the Evaluator (whose inference fn is jitted)
        are built once and cached, so per-epoch eval pays no recompile."""
        if getattr(self, "_evaluator", None) is None:
            from replication_faster_rcnn_tpu.eval import Evaluator

            self._val_dataset = make_dataset(self.config.data, "val")
            self._evaluator = Evaluator(self.config, self.model)
            # under strict mode the epoch-end eval runs inside the train
            # session's transfer guard: the evaluator needs the harness so
            # its first infer dispatch gets a warmup allowance
            self._evaluator.strict = self.strict
        variables = {
            "params": self.state.params,
            "batch_stats": self.state.batch_stats,
        }
        with self.tracer.span("eval/evaluate", cat="eval"):
            return self._evaluator.evaluate(
                variables, self._val_dataset,
                batch_size=self.config.train.batch_size,
                max_images=max_images,
            )

    def _log_step(
        self, step: int, metrics, log_every: int
    ) -> Optional[Dict[str, float]]:
        """Per-step log cadence: when ``step`` is a log boundary, sync the
        metrics (fail fast on NaN/inf unless the guarded update already
        withheld the step — fault.check_step_metrics), log, and drain the
        skip monitor. The sync span is where async dispatch drains, i.e.
        device compute time for the interval. Returns the logged row, or
        None off-boundary."""
        if step % log_every != 0:
            return None
        with self.tracer.span("step/sync", cat="sync"):
            host_metrics = jax.device_get(metrics)
        row = fault.check_step_metrics(host_metrics, step)
        row["lr"] = self.host_schedule(step)
        self.logger.log(step, row)
        self.skip_monitor.drain()
        return row

    def _log_chunk(
        self, first: int, step: int, metrics, log_every: int
    ) -> Optional[Dict[str, float]]:
        """Chunk-aware log cadence: sync the stacked [K] metrics only when
        a log boundary falls inside [``first``, ``step``], and log that
        boundary's own row. Returns the logged row, or None."""
        boundary = (step // log_every) * log_every
        if boundary < first:
            return None
        with self.tracer.span("step/sync", cat="sync"):
            host_metrics = jax.device_get(metrics)
        row = {key: v[boundary - first] for key, v in host_metrics.items()}
        row = fault.check_step_metrics(row, boundary)
        row["lr"] = self.host_schedule(boundary)
        self.logger.log(boundary, row)
        self.skip_monitor.drain()
        return row

    def train(self, log_every: int = 10, resume: bool = False) -> Dict[str, float]:
        """Run cfg.train.n_epoch epochs. The epoch count lives in the config
        (not a parameter) because the cosine schedule was built from it —
        an ad-hoc override would train on a mismatched LR curve.
        """
        cfg = self.config.train
        start_step = self.restore() if resume else 0
        steps_per_epoch = max(
            len(self.sampler if self.device_cache is not None else self.loader), 1
        )
        start_epoch = start_step // steps_per_epoch
        # mid-epoch resume (emergency/step-interval checkpoints land at
        # arbitrary steps): consume the resumed epoch from its global-order
        # OFFSET — set_epoch(epoch, start_batch=replay) re-derives the
        # epoch's deterministic batch order and starts the iterator at the
        # first untrained batch, so the already-consumed prefix never
        # reaches the loader and the loss trajectory still matches an
        # uninterrupted run step-for-step. Under an elastic re-formation
        # the same offset re-partitions the epoch's unconsumed suffix
        # disjointly across the NEW world size (each rank takes its
        # contiguous block of every remaining global batch).
        replay = start_step - start_epoch * steps_per_epoch
        step = start_step  # host-side mirror: no device sync to read it
        self._host_step = start_step
        self._last_step_ckpt = start_step
        if self._fleet_generation > 0:
            # step-free fields: same-seed replays of a shrink produce the
            # identical incident regardless of wall clock or rollback depth
            self._fault_incident(
                "fleet_reformed",
                generation=self._fleet_generation,
                world_size=self._process_count,
                survivors=list(range(self._process_count)),
            )

        last: Dict[str, float] = {}
        eval_result: Dict[str, float] = {}
        feed = self.sampler if self.device_cache is not None else self.loader
        tracer = self.tracer

        def cur_bucket() -> Optional[int]:
            # resolution bucket of the NEXT batch to train: a pure
            # function of (seed, epoch, position-in-epoch) via the feed's
            # bucket_of, so resume/replay and every rank agree. `step` and
            # `epoch` are read at call time (closure over the loop vars);
            # all K batches of one fused dispatch share a bucket by
            # construction (bucket_chunk = steps_per_dispatch).
            if self.jitted_bucket_steps is None:
                return None
            return feed.bucket_of(step - epoch * steps_per_epoch)

        self._shutdown = fault.GracefulShutdown()
        try:
            with self.telemetry_session(), self.strict_session(), self._shutdown:
                k = self.steps_per_dispatch
                prefetch = self.config.data.prefetch_device
                for epoch in range(start_epoch, cfg.n_epoch):
                    feed.set_epoch(epoch, start_batch=replay)
                    replay = 0
                    t_epoch = time.time()
                    n_images = 0
                    if prefetch > 0:
                        # overlap path (data.prefetch_device): a producer
                        # thread collates + stages batch K+1's device
                        # transfer while dispatch K runs, so the consumer
                        # loop below only dequeues resident buffers. A
                        # resumed epoch's trained prefix never reaches the
                        # producer — the feed itself starts at the resume
                        # offset (set_epoch start_batch above).
                        stage = (
                            (lambda bs: self._stage_chunk(bs, wait=True))
                            if k > 1
                            else (lambda bs: self._stage_batch(bs[0], wait=True))
                        )
                        stager = DevicePrefetcher(
                            iter(feed), stage,
                            depth=prefetch, chunk=k,
                        )
                        if self.watchdog is not None:
                            self.watchdog.providers["staged_queue_depth"] = (
                                stager.queue_depth
                            )
                        try:
                            for item in stager:
                                if item[0] == STAGED and k > 1:
                                    metrics = self.train_chunk(
                                        staged=item[1], bucket=cur_bucket()
                                    )
                                    first = step + 1
                                    step += k
                                    n_images += item[3]
                                    if self.watchdog is not None:
                                        self.watchdog.beat(
                                            step=step, phase="train"
                                        )
                                    row = self._log_chunk(
                                        first, step, metrics, log_every
                                    )
                                    if row is not None:
                                        last = row
                                elif item[0] == STAGED:
                                    metrics = self.train_one_batch(
                                        staged=item[1], bucket=cur_bucket()
                                    )
                                    step += 1
                                    n_images += item[3]
                                    if self.watchdog is not None:
                                        self.watchdog.beat(
                                            step=step, phase="train"
                                        )
                                    row = self._log_step(
                                        step, metrics, log_every
                                    )
                                    if row is not None:
                                        last = row
                                else:
                                    # HOST item: epoch tail (< K pending
                                    # batches) through the per-step path
                                    batch = item[1]
                                    metrics = self.train_one_batch(
                                        batch, bucket=cur_bucket()
                                    )
                                    step += 1
                                    n_images += batch[
                                        "idx" if "idx" in batch else "image"
                                    ].shape[0]
                                    if self.watchdog is not None:
                                        self.watchdog.beat(
                                            step=step, phase="train"
                                        )
                                    row = self._log_step(
                                        step, metrics, log_every
                                    )
                                    if row is not None:
                                        last = row
                                self._check_preemption(step)
                                self._check_fleet(step)
                                self._maybe_step_checkpoint(step)
                        finally:
                            # drops staged-but-untrained buffers; resume
                            # replay regenerates them deterministically
                            stager.close()
                    else:
                        it = iter(feed)
                        chunk = []  # pending batches of a partial dispatch
                        while True:
                            # the fetch span covers host-side batch
                            # production (decode/collate or selection draw)
                            # — the feed half of feed-vs-compute
                            with tracer.span("data/fetch", cat="data"):
                                try:
                                    batch = next(it)
                                except StopIteration:
                                    break
                            if k > 1:
                                chunk.append(batch)
                                if len(chunk) < k:
                                    continue
                                metrics = self.train_chunk(
                                    chunk, bucket=cur_bucket()
                                )
                                first = step + 1
                                step += k
                                n_images += sum(
                                    b["idx" if "idx" in b else "image"].shape[0]
                                    for b in chunk
                                )
                                chunk = []
                                if self.watchdog is not None:
                                    self.watchdog.beat(step=step, phase="train")
                                row = self._log_chunk(
                                    first, step, metrics, log_every
                                )
                                if row is not None:
                                    last = row
                                self._check_preemption(step)
                                self._check_fleet(step)
                                self._maybe_step_checkpoint(step)
                                continue
                            metrics = self.train_one_batch(
                                batch, bucket=cur_bucket()
                            )
                            n_images += batch[
                                "idx" if "idx" in batch else "image"
                            ].shape[0]
                            step += 1
                            if self.watchdog is not None:
                                self.watchdog.beat(step=step, phase="train")
                            row = self._log_step(step, metrics, log_every)
                            if row is not None:
                                last = row
                            self._check_preemption(step)
                            self._check_fleet(step)
                            self._maybe_step_checkpoint(step)
                        # epoch tail: a feed length not divisible by K
                        # leaves <K batches pending — run them through the
                        # per-step path (its jit compiles lazily, only when
                        # a tail exists)
                        for batch in chunk:
                            metrics = self.train_one_batch(
                                batch, bucket=cur_bucket()
                            )
                            n_images += batch[
                                "idx" if "idx" in batch else "image"
                            ].shape[0]
                            step += 1
                            if self.watchdog is not None:
                                self.watchdog.beat(step=step, phase="train")
                            row = self._log_step(step, metrics, log_every)
                            if row is not None:
                                last = row
                            self._check_preemption(step)
                            self._check_fleet(step)
                            self._maybe_step_checkpoint(step)
                    # epoch-boundary sync for an honest throughput number
                    with tracer.span("step/sync", cat="sync", boundary="epoch"):
                        jax.device_get(
                            jax.tree_util.tree_leaves(self.state.params)[0]
                        )
                    self.skip_monitor.drain()
                    dt = time.time() - t_epoch
                    # n_images counted LOCAL rows; report global throughput
                    n_images *= self._process_count
                    self.logger.log_epoch(epoch, n_images / dt if dt > 0 else 0.0)
                    if cfg.eval_every_epochs and (
                        epoch + 1
                    ) % cfg.eval_every_epochs == 0:
                        if self.watchdog is not None:
                            self.watchdog.beat(phase="eval")
                        from replication_faster_rcnn_tpu.eval.evaluator import (
                            summary_scalars,
                        )

                        # flat scalar schema shared by the voc and coco
                        # metrics: aggregates + per-class AP/<name> rows
                        eval_result = summary_scalars(
                            self.evaluate(), self.config.model.num_classes
                        )
                        self.logger.log(step, eval_result)
                    if (epoch + 1) % cfg.checkpoint_every_epochs == 0:
                        if self.watchdog is not None:
                            self.watchdog.beat(phase="checkpoint")
                        with tracer.span("checkpoint/save", cat="checkpoint"):
                            # periodic saves are contained (kind="scheduled"):
                            # a failed one logs an incident and the next
                            # interval retries
                            self.save()
                    self._check_preemption(step)
                    self._check_fleet(step)
        finally:
            self._shutdown = None
            # stop the heartbeat thread on a HEALTHY exit only: after a
            # detected rank loss it stays armed, so its EXIT_FLEET_SHRINK
            # backstop still fires if teardown wedges on the dead peer
            if self.elastic_agent is not None and not self.elastic_agent.check():
                self.elastic_agent.stop()
            # the last scheduled save must be on disk before train()
            # returns (callers immediately save(kind="final") or exit)
            self._drain_async_saves()
        if last:
            last = {k: float(v) for k, v in last.items()}
        # merged last so step-metric logging cannot wipe the eval result
        last.update(eval_result)
        return last
