"""Backbone classifier pretraining — capability parity with the reference's
CIFAR pretraining path (`nets/resnet.py:163-292` ``__main__``: a ResNet18
trained on CIFAR10 to ~0.93 top-1, `readme.md:15`, whose trunk/tail split
then seeds the detector).

A jitted softmax-CE classification step over any (images [N,H,W,3],
labels [N]) arrays. The trained `trunk`/`tail` params drop directly into
FasterRCNN variables (same module names) via :func:`graft_classifier`.
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, Tuple

import jax
import jax.numpy as jnp
import optax

from replication_faster_rcnn_tpu.models.resnet import ResNetClassifier
from replication_faster_rcnn_tpu.telemetry import spans as tspans

Array = jnp.ndarray


def make_classifier(
    arch: str = "resnet18",
    num_classes: int = 10,
    stem: str = "cifar",
    dtype: str = "bfloat16",
    norm: str = "batch",
):
    """``norm="group"`` pretrains the GroupNorm backbone — the only
    pretrained-weight source for a ``norm="group"`` detector (torch BN
    checkpoints are rejected by `models/convert.py`)."""
    return ResNetClassifier(
        arch=arch, num_classes=num_classes, dtype=jnp.dtype(dtype), stem=stem,
        norm=norm,
    )


def make_pretrain_step(model: ResNetClassifier, tx: optax.GradientTransformation):
    """(variables, opt_state, images, labels) -> (variables, opt_state, metrics)."""

    def step(variables, opt_state, images, labels):
        def loss_fn(params):
            logits, mut = model.apply(
                {"params": params, "batch_stats": variables["batch_stats"]},
                images,
                train=True,
                mutable=["batch_stats"],
            )
            ce = optax.softmax_cross_entropy_with_integer_labels(
                logits, labels
            ).mean()
            acc = (jnp.argmax(logits, -1) == labels).mean()
            # norm="group" classifiers carry no batch_stats collection
            return ce, (acc, mut.get("batch_stats", {}))

        (loss, (acc, stats)), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            variables["params"]
        )
        updates, opt_state = tx.update(grads, opt_state, variables["params"])
        params = optax.apply_updates(variables["params"], updates)
        return (
            {"params": params, "batch_stats": stats},
            opt_state,
            {"loss": loss, "accuracy": acc},
        )

    return step


def pretrain(
    model: ResNetClassifier,
    batches: Iterable[Tuple[Any, Any]],
    lr: float = 1e-3,
    weight_decay: float = 5e-4,
    rng: Any = None,
) -> Dict[str, Any]:
    """Train over an iterable of (images, labels) batches; returns final
    variables. Small-scale utility (the reference's CIFAR script analog) —
    full-dataset pretraining would go through Trainer-style sharding."""
    rng = jax.random.PRNGKey(0) if rng is None else rng
    it = iter(batches)
    first_batch = next(it)
    images0 = jnp.asarray(first_batch[0])
    variables = model.init({"params": rng}, images0, train=False)
    variables = {
        "params": variables["params"],
        "batch_stats": variables.get("batch_stats", {}),
    }
    tx = optax.adamw(lr, weight_decay=weight_decay)
    opt_state = tx.init(variables["params"])
    step = jax.jit(make_pretrain_step(model, tx))

    metrics = {}
    for images, labels in [first_batch] + list(it):
        variables, opt_state, metrics = step(
            variables, opt_state, jnp.asarray(images), jnp.asarray(labels)
        )
    with tspans.current_tracer().span("step/sync", cat="sync"):
        host_metrics = jax.device_get(metrics)
    return {"variables": variables, "metrics": host_metrics}


def graft_classifier(detector_variables: Dict[str, Any], classifier_variables: Dict[str, Any]):
    """Copy a pretrained classifier's trunk/tail into FasterRCNN variables
    (single-scale layout: trunk -> `trunk`, tail -> `head.tail`).

    The two sides must use the same normalization: BN and GN backbones
    share param names/shapes at every norm site (scale/bias), so a
    mismatched graft would succeed silently and train badly — the same
    hazard `models/convert.py` guards against for torch checkpoints."""
    out_p = dict(detector_variables["params"])
    out_s = dict(detector_variables.get("batch_stats", {}))
    cp = classifier_variables["params"]
    cs = classifier_variables.get("batch_stats", {})
    det_bn = bool(detector_variables.get("batch_stats", {}).get("trunk"))
    cls_bn = bool(cs.get("trunk"))
    if det_bn != cls_bn:
        raise ValueError(
            "normalization mismatch: the "
            f"{'BatchNorm' if cls_bn else 'GroupNorm'} classifier "
            "checkpoint cannot graft onto a "
            f"{'BatchNorm' if det_bn else 'GroupNorm'} detector — "
            "pretrain with make_classifier(norm=...) matching the "
            "detector's ModelConfig.norm"
        )
    out_p["trunk"] = cp["trunk"]
    out_s["trunk"] = cs.get("trunk", {})
    head = dict(out_p["head"])
    head["tail"] = cp["tail"]
    out_p["head"] = head
    hstats = dict(out_s.get("head", {}))
    hstats["tail"] = cs.get("tail", {})
    out_s["head"] = hstats
    return {"params": out_p, "batch_stats": out_s}
