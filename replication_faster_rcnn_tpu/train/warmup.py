"""Compile warm start: persistent XLA compilation cache + AOT warmup.

Every process start re-pays full XLA compilation of the train step
(minutes for the big presets on TPU) before the first batch dispatches.
Two pieces take that off the startup critical path:

* :func:`enable_compile_cache` — opt into JAX's persistent compilation
  cache (``compile.cache_dir`` in the config / ``--compile-cache`` on the
  CLI). Compiled executables are keyed by HLO + compile options and
  written under the directory; a later process compiling the *same*
  program (same config, same mesh, same jaxlib) deserializes instead of
  re-running XLA.
* :func:`warmup_compile` — AOT-lower and compile the training-step
  program(s) (and optionally the eval inference program) for a config
  WITHOUT building datasets, allocating parameters or running a step:
  inputs are `jax.ShapeDtypeStruct` fixtures with the trainer's own
  shardings attached, so the lowered HLO matches what the real run jits.
  Run via ``cli warmup`` (typically with the cache enabled) to populate
  the cache ahead of a fleet launch; each compile is timed under a
  ``compile/*`` telemetry span.
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import Dict, Optional

import jax

from replication_faster_rcnn_tpu.config import FasterRCNNConfig
from replication_faster_rcnn_tpu.telemetry import spans as tspans


def enable_compile_cache(cache_dir: str) -> str:
    """Point JAX's persistent compilation cache at ``cache_dir``
    (created if missing; ~ expanded). Returns the absolute path.

    The min-compile-time / min-entry-size gates are dropped to zero so
    even cheap programs persist — this cache exists to make *restarts*
    free, and a restart replays every program, not just the slow ones."""
    path = os.path.abspath(os.path.expanduser(cache_dir))
    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    for knob, value in (
        ("jax_persistent_cache_min_compile_time_secs", 0.0),
        ("jax_persistent_cache_min_entry_size_bytes", -1),
    ):
        try:
            jax.config.update(knob, value)
        except Exception:  # pragma: no cover - knob renamed across jax versions
            pass
    return path


def maybe_enable_compile_cache(config: FasterRCNNConfig) -> Optional[str]:
    """Config-driven variant: enable when ``compile.cache_dir`` is set."""
    if config.compile.cache_dir:
        return enable_compile_cache(config.compile.cache_dir)
    return None


def _mesh_for(config: FasterRCNNConfig):
    """The mesh the Trainer would build for this config (fit the data
    axis to the batch the same way Trainer.__init__ does)."""
    from replication_faster_rcnn_tpu.parallel import (
        fit_data_parallelism,
        make_mesh,
    )

    mesh_cfg = config.mesh
    if mesh_cfg.num_data <= 0:
        n_dev = len(jax.devices()) // max(1, mesh_cfg.num_model)
        mesh_cfg = dataclasses.replace(
            mesh_cfg,
            num_data=fit_data_parallelism(config.train.batch_size, n_dev),
        )
    return make_mesh(mesh_cfg), mesh_cfg


def warmup_compile(
    config: FasterRCNNConfig,
    include_eval: bool = True,
) -> Dict[str, float]:
    """AOT-compile the programs a training run of ``config`` would jit.

    Covers the per-step train program, the fused multi-step program when
    ``train.steps_per_dispatch > 1``, and (``include_eval``) the eval
    inference program. Returns {program_name: compile_seconds}; with the
    persistent cache enabled, a warmed second run shows near-zero times
    here and — the point — at real-run startup.

    The abstract inputs carry the trainer's shardings (state via
    `train_state_shardings`, batch via `shard_batch`'s layouts) and the
    trainer's donation/out_shardings, so the compiled executables are
    cache hits for the real run, not merely similar programs."""
    from replication_faster_rcnn_tpu.benchmark import abstract_step_inputs
    from replication_faster_rcnn_tpu.parallel import (
        batch_sharding,
        image_sharding,
        stacked_batch_sharding,
    )
    from replication_faster_rcnn_tpu.parallel.zero import train_state_shardings
    from replication_faster_rcnn_tpu.train.train_step import (
        build_multi_step,
        make_optimizer,
        make_train_step,
    )

    tracer = tspans.current_tracer()
    mesh, mesh_cfg = _mesh_for(config)
    tx, _ = make_optimizer(config, steps_per_epoch=100)
    model, state_abs, batch_abs = abstract_step_inputs(config, tx)
    state_shardings = train_state_shardings(
        state_abs, mesh, mesh_cfg, config.train.shard_opt_state
    )
    state_abs = jax.tree_util.tree_map(
        lambda x, s: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=s),
        state_abs,
        state_shardings,
    )

    def _with_sharding(abs_batch, img_s, other_s):
        return {
            k: jax.ShapeDtypeStruct(
                v.shape, v.dtype, sharding=img_s if k == "image" else other_s
            )
            for k, v in abs_batch.items()
        }

    batch_abs = _with_sharding(
        batch_abs, image_sharding(mesh, mesh_cfg), batch_sharding(mesh, mesh_cfg)
    )

    times: Dict[str, float] = {}

    def _compile(name: str, jitted, *args) -> None:
        with tracer.span(f"compile/{name}", cat="compile"):
            t0 = time.perf_counter()
            jitted.lower(*args).compile()
            times[name] = round(time.perf_counter() - t0, 3)

    step_fn = make_train_step(model, config, tx)
    _compile(
        "train_step",
        jax.jit(
            step_fn, donate_argnums=(0,), out_shardings=(state_shardings, None)
        ),
        state_abs,
        batch_abs,
    )
    k = max(1, config.train.steps_per_dispatch)
    if k > 1:
        stacked_s = stacked_batch_sharding(mesh, mesh_cfg)
        chunk_abs = {
            key: jax.ShapeDtypeStruct(
                (k,) + v.shape, v.dtype, sharding=stacked_s
            )
            for key, v in batch_abs.items()
        }
        _compile(
            "multi_step",
            jax.jit(
                build_multi_step(step_fn, k),
                donate_argnums=(0,),
                out_shardings=(state_shardings, None),
            ),
            state_abs,
            chunk_abs,
        )
    if include_eval:
        from replication_faster_rcnn_tpu.eval import Evaluator

        ev = Evaluator(config, model)
        # mirror Evaluator.evaluate's own placement: its eval mesh (or no
        # sharding on a single device), so the lowered program is the one
        # the real eval sweep jits
        img_s, rep_s = ev._eval_sharding(config.train.batch_size)

        def _abs(x, s):
            if s is None:
                return jax.ShapeDtypeStruct(x.shape, x.dtype)
            return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=s)

        variables_abs = {
            "params": jax.tree_util.tree_map(
                lambda x: _abs(x, rep_s), state_abs.params
            ),
            "batch_stats": jax.tree_util.tree_map(
                lambda x: _abs(x, rep_s), state_abs.batch_stats
            ),
        }
        images_abs = _abs(batch_abs["image"], img_s)
        _compile("eval_infer", ev._jit_infer, variables_abs, images_abs)
    return times
