"""Compile warm start: persistent XLA compilation cache + AOT warmup.

Every process start re-pays full XLA compilation of the train step
(minutes for the big presets on TPU) before the first batch dispatches.
Two pieces take that off the startup critical path:

* :func:`enable_compile_cache` — opt into JAX's persistent compilation
  cache (``compile.cache_dir`` in the config / ``--compile-cache`` on the
  CLI). Compiled executables are keyed by HLO + compile options and
  written under the directory; a later process compiling the *same*
  program (same config, same mesh, same jaxlib) deserializes instead of
  re-running XLA.
* :func:`warmup_compile` — AOT-lower and compile the training-step
  program(s) (and optionally the eval inference program) for a config
  WITHOUT building datasets, allocating parameters or running a step:
  inputs are `jax.ShapeDtypeStruct` fixtures with the trainer's own
  shardings attached, so the lowered HLO matches what the real run jits.
  Run via ``cli warmup`` (typically with the cache enabled) to populate
  the cache ahead of a fleet launch; each compile is timed under a
  ``compile/*`` telemetry span.

Both consumers go through one PROGRAM REGISTRY
(:func:`build_program_specs`): every (feed × K) train program the Trainer
can jit — host loader, ``--cache-device`` selection feed, explicit
shard_map SPMD — plus the eval inference program, each with the exact jit
wrapping (donation, out_shardings) and abstract inputs (trainer
shardings attached) the real run uses. ``warmup_compile`` compiles the
subset its config selects; ``analysis/hlolint.py`` AOT-lowers the full
matrix and audits the artifacts (aliasing, collectives, memory) against
committed fingerprints.
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import jax
import numpy as np

from replication_faster_rcnn_tpu.config import FasterRCNNConfig
from replication_faster_rcnn_tpu.telemetry import spans as tspans


def enable_compile_cache(cache_dir: str) -> str:
    """Point JAX's persistent compilation cache at ``cache_dir``
    (created if missing; ~ expanded). Returns the absolute path.

    The min-compile-time / min-entry-size gates are dropped to zero so
    even cheap programs persist — this cache exists to make *restarts*
    free, and a restart replays every program, not just the slow ones."""
    path = os.path.abspath(os.path.expanduser(cache_dir))
    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    for knob, value in (
        ("jax_persistent_cache_min_compile_time_secs", 0.0),
        ("jax_persistent_cache_min_entry_size_bytes", -1),
    ):
        try:
            jax.config.update(knob, value)
        except Exception:  # pragma: no cover - knob renamed across jax versions
            pass
    return path


def maybe_enable_compile_cache(config: FasterRCNNConfig) -> Optional[str]:
    """Config-driven variant: enable when ``compile.cache_dir`` is set."""
    if config.compile.cache_dir:
        return enable_compile_cache(config.compile.cache_dir)
    return None


def _mesh_for(config: FasterRCNNConfig):
    """The mesh the Trainer would build for this config (fit the data
    axis to the batch the same way Trainer.__init__ does)."""
    from replication_faster_rcnn_tpu.parallel import (
        fit_data_parallelism,
        make_mesh,
    )

    mesh_cfg = config.mesh
    if mesh_cfg.num_data <= 0:
        n_dev = len(jax.devices()) // max(1, mesh_cfg.num_model)
        mesh_cfg = dataclasses.replace(
            mesh_cfg,
            num_data=fit_data_parallelism(config.train.batch_size, n_dev),
        )
    return make_mesh(mesh_cfg), mesh_cfg


@dataclasses.dataclass(frozen=True)
class ProgramSpec:
    """One AOT-compilable program: the trainer-exact jitted callable plus
    the abstract inputs (trainer shardings attached) it lowers against.

    ``arg_roles`` names each positional abstract argument ("state",
    "batch", "cache", "sel", ...) so downstream consumers (the HLO
    auditor's donation rule) can map XLA parameter indices back to the
    Python-level argument they came from. ``build`` is lazy: constructing
    specs costs nothing until a consumer lowers a program.
    """

    name: str
    feed: str  # "loader" | "cached" | "spmd" | "zero" | "zero_lamb" | "eval"
    k: int  # fused steps per dispatch (1 = single step; 0 for eval)
    arg_roles: Tuple[str, ...]
    build: Callable[[], Tuple[Any, Tuple[Any, ...]]]
    meta: Dict[str, Any] = dataclasses.field(default_factory=dict)


# "zero" is the shard_map backend with ZeRO-1 weight-update sharding
# forced on (train.shard_opt_state): same step math as "spmd" but the
# optimizer state is sharded over the data axis and the update is
# reduce-scatter / sharded-Adam / all-gather (parallel/spmd.py).
# "zero_lamb" is the same feed with train.optimizer='lamb' — the chain
# gains the sharded trust ratio (psum'd per-layer norms, see
# train/train_step.py::scale_by_sharded_trust_ratio), a distinct program
# with its own fingerprint.
# "mp" is the jit auto-partitioning backend on a 2D (dp, mp) mesh with
# model-parallel weight sharding (mesh.param_sharding / --mesh-shape):
# params arrive 1/mp per chip and GSPMD inserts the weight all-gathers.
# "mp_zero" additionally shards the optimizer state (ZeRO-1 over dp,
# composed off the mp dim — parallel/zero.py::compose_spec).
TRAIN_FEEDS: Tuple[str, ...] = (
    "loader", "cached", "spmd", "zero", "zero_lamb", "mp", "mp_zero"
)

# the (dp, mp) topology the audited mp programs lower against when the
# config itself is not model-parallel: mp = 4, dp = devices/4 (the audit
# tier runs 8 fake CPU devices -> a (2, 4) mesh)
MP_AUDIT_NUM_MODEL = 4


def mp_audit_config(config: FasterRCNNConfig) -> FasterRCNNConfig:
    """The config the "mp"/"mp_zero" feeds lower: the given config if it
    is already model-parallel, else the audit (dp, mp) topology forced
    onto it (num_model=4, dp = devices/4, param_sharding on)."""
    if config.mesh.param_sharding and config.mesh.num_model > 1:
        return config
    n, m = len(jax.devices()), MP_AUDIT_NUM_MODEL
    if n % m:
        raise ValueError(
            f"the mp audit feeds need a device count divisible by {m}, "
            f"got {n} (run under XLA_FLAGS="
            "--xla_force_host_platform_device_count=8 on CPU)"
        )
    return config.replace(
        mesh=dataclasses.replace(
            config.mesh,
            num_data=n // m,
            num_model=m,
            param_sharding=True,
            spatial=False,
        )
    )


def program_name(feed: str, k: int) -> str:
    return "eval_infer" if feed == "eval" else f"train_{feed}_k{k}"


def bucket_train_program_name(feed: str, k: int, h: int, w: int) -> str:
    """Canonical name of one multi-scale train-bucket program
    (data.train_resolutions): the base (feed x K) name with the bucket's
    static resolution appended, mirroring serve_program_name."""
    return f"{program_name(feed, k)}_{h}x{w}"


def bucket_train_program_names(
    config: FasterRCNNConfig,
    feeds: Sequence[str] = ("loader", "cached"),
    ks: Sequence[int] = (1,),
) -> Tuple[str, ...]:
    """Every per-bucket train program the config's trainer would compile
    (empty when data.train_resolutions is unset). EVERY train feed
    buckets: the shard_map/mp feeds compile one program per resolution
    with the resample traced into the body, the in/out specs unchanged
    (they shard batch dims only, which is resolution-independent)."""
    return tuple(
        bucket_train_program_name(feed, k, h, w)
        for feed in feeds
        for k in ks
        for h, w in config.data.train_resolutions
    )


PALLAS_TWIN_SUFFIX = "__pallas"


def pallas_program_name(base: str) -> str:
    """Registry name of the ops.backend=pallas twin of a base program."""
    return base + PALLAS_TWIN_SUFFIX


class _ScopedLower:
    """Proxy a jitted callable so tracing happens under a pinned
    `ops.backend_scope`.

    jit is lazy: the ops-dispatch decisions (`ops.want_pallas`) run at
    TRACE time, which for a ProgramSpec is inside ``.lower()`` and for the
    Trainer is the first real dispatch. Wrapping the callable — instead of
    asking every call site to remember the scope — guarantees a program
    never half-resolves across backends, and that a pallas-backend program
    is only ever built through this registry (the 431e219 lesson: no lazy
    in-train-step pallas compiles). Everything else (`_clear_cache`, cache
    probes) passes through to the wrapped callable.
    """

    def __init__(self, jitted, backend: str):
        self._jitted = jitted
        self._backend = backend

    def lower(self, *args, **kwargs):
        from replication_faster_rcnn_tpu import ops as ops_pkg

        with ops_pkg.backend_scope(self._backend):
            return self._jitted.lower(*args, **kwargs)

    def __call__(self, *args, **kwargs):
        from replication_faster_rcnn_tpu import ops as ops_pkg

        with ops_pkg.backend_scope(self._backend):
            return self._jitted(*args, **kwargs)

    def __getattr__(self, name):
        return getattr(self._jitted, name)


def scope_jitted(jitted, config=None, backend: Optional[str] = None):
    """Wrap ``jitted`` so it traces under the config's resolved ops
    backend. Returns the callable unchanged for backend=xla — the default
    path must stay the exact jit object (and HLO) it always was."""
    if backend is None:
        from replication_faster_rcnn_tpu import ops as ops_pkg

        backend = ops_pkg.resolve_backend(config)
    if backend == "xla":
        return jitted
    return _ScopedLower(jitted, backend)


def serve_program_name(h: int, w: int, batch: int) -> str:
    """Canonical name of one serving bucket program."""
    return f"serve_{h}x{w}_b{batch}"


def serving_program_names(config: FasterRCNNConfig) -> Tuple[str, ...]:
    """Every serving bucket program the config's engine would compile."""
    return tuple(
        serve_program_name(h, w, n)
        for h, w in config.serving.bucket_resolutions(config.data.image_size)
        for n in sorted(set(config.serving.batch_sizes))
    )


def build_serving_specs(
    config: FasterRCNNConfig, model=None
) -> Dict[str, ProgramSpec]:
    """{program_name: ProgramSpec} for the serving engine's bucket matrix
    (``serving.resolutions × serving.batch_sizes``).

    Each bucket program is the SAME inference function the eval sweep
    jits (`eval/evaluator.py::make_infer_fn`, re-closed over the bucket
    resolution) against abstract inputs with every float variable leaf in
    ``serving.params_dtype`` — the dtype the engine holds its resident
    params in. Routing serving through this registry is what lets the
    persistent compile cache pre-warm `frcnn serve` and `frcnn audit`
    enforce HX001-HX006 on the serving programs.

    Under ``mesh.param_sharding`` with ``num_model > 1`` (``--mesh-shape
    DP,MP``) the abstract params carry `zero.param_shardings` layouts on
    a (1, num_model) serving mesh instead of the implicit single-device
    replication: serving holds ONE model replica, so a model too large
    for one chip's weights stays servable, and the engine's resident
    upload (`serving/engine.py::_build_resident`) places each leaf on
    the sharding banked here. Non-param collections (batch_stats) stay
    replicated. The audited 'ci' matrix runs num_model=1, so the banked
    serve fingerprints are untouched by this path.
    """
    from replication_faster_rcnn_tpu.eval.evaluator import make_infer_fn
    from replication_faster_rcnn_tpu.models.faster_rcnn import FasterRCNN

    if model is None:
        model = FasterRCNN(config)
    dtype = np.dtype(jax.numpy.dtype(config.serving.params_dtype))
    h0, w0 = config.data.image_size
    variables_abs = jax.eval_shape(
        lambda rng, img: model.init({"params": rng}, img, train=False),
        jax.ShapeDtypeStruct((2,), np.uint32),
        jax.ShapeDtypeStruct((1, h0, w0, 3), np.float32),
    )
    variables_abs = jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(
            x.shape, dtype if np.issubdtype(x.dtype, np.floating) else x.dtype
        ),
        variables_abs,
    )
    mesh_meta: Optional[Dict[str, int]] = None
    if config.mesh.param_sharding and max(1, config.mesh.num_model) > 1:
        variables_abs, mesh_meta = _mp_serving_variables(config, variables_abs)

    specs: Dict[str, ProgramSpec] = {}
    for h, w in config.serving.bucket_resolutions(config.data.image_size):
        for n in sorted(set(config.serving.batch_sizes)):
            name = serve_program_name(h, w, n)

            def _build(hh=h, ww=w, nn=n, name_=name):
                from replication_faster_rcnn_tpu.parallel.plan import (
                    Plan,
                    compile_step_with_plan,
                )

                # a bare plan: serving buckets jit plain (single-device
                # inference, params resident, nothing donated)
                jitted = compile_step_with_plan(
                    make_infer_fn(model, config, (hh, ww)),
                    Plan(label=name_),
                )
                images_abs = jax.ShapeDtypeStruct((nn, hh, ww, 3), np.float32)
                return jitted, (variables_abs, images_abs)

            specs[name] = ProgramSpec(
                name=name,
                feed="serve",
                k=0,
                arg_roles=("variables", "images"),
                build=_build,
                meta={
                    "bucket": [h, w],
                    "batch": n,
                    "params_dtype": config.serving.params_dtype,
                    **(
                        {"mesh_shape": mesh_meta, "param_sharding": True}
                        if mesh_meta
                        else {}
                    ),
                },
            )
    return specs


def _mp_serving_variables(config: FasterRCNNConfig, variables_abs):
    """Attach the model-parallel serving layout to the abstract variables:
    params get `zero.param_shardings` over a (1, num_model) mesh, every
    other collection a replicated NamedSharding on the same mesh. Returns
    ``(sharded_variables_abs, mesh_shape_meta)``."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec
    from replication_faster_rcnn_tpu.parallel import zero

    n_model = config.mesh.num_model
    devices = jax.devices()
    if len(devices) < n_model:
        raise ValueError(
            f"mesh.param_sharding serving needs num_model={n_model} "
            f"devices; only {len(devices)} visible"
        )
    grid = np.asarray(devices[:n_model]).reshape(1, n_model)
    mesh = Mesh(grid, (config.mesh.data_axis, config.mesh.model_axis))
    replicated = NamedSharding(mesh, PartitionSpec())
    params_sh = zero.param_shardings(
        variables_abs["params"], mesh, config.mesh
    )
    colls = {
        coll: (
            jax.tree_util.tree_map(
                lambda x, s: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=s),
                variables_abs[coll],
                params_sh,
            )
            if coll == "params"
            else jax.tree_util.tree_map(
                lambda x: jax.ShapeDtypeStruct(
                    x.shape, x.dtype, sharding=replicated
                ),
                variables_abs[coll],
            )
        )
        for coll in variables_abs
    }
    if not isinstance(variables_abs, dict):
        colls = type(variables_abs)(colls)
    mesh_meta = {config.mesh.data_axis: 1, config.mesh.model_axis: n_model}
    return colls, mesh_meta


INT8_TWIN_SUFFIX = "__int8"


def int8_program_name(base: str) -> str:
    """Registry name of the serving.params_dtype=int8 twin of a serve
    bucket program."""
    return base + INT8_TWIN_SUFFIX


def int8_serving_program_names(config: FasterRCNNConfig) -> Tuple[str, ...]:
    """Every int8 serving bucket program the config's engine would
    compile under ``serving.params_dtype="int8"``."""
    return tuple(
        int8_program_name(base) for base in serving_program_names(config)
    )


def int8_program_names(config: FasterRCNNConfig) -> Tuple[str, ...]:
    """The full int8 registry name set `build_int8_program_specs` emits:
    every serving bucket program's int8 twin plus the one
    ops.backend=pallas int8 twin (largest bucket, smallest batch) —
    pure names, no lowering (the audit's expected-set arithmetic)."""
    buckets = config.serving.bucket_resolutions(config.data.image_size)
    batches = sorted(set(config.serving.batch_sizes))
    names = list(int8_serving_program_names(config))
    names.append(
        pallas_program_name(
            int8_program_name(serve_program_name(*buckets[-1], min(batches)))
        )
    )
    return tuple(names)


def make_int8_infer_fn(model, config: FasterRCNNConfig, image_size=None):
    """The int8 serving program body: in-program reconstruction of the
    quantized resident tree (`quant/apply.py::build_infer_variables` —
    per-channel dequantize through the `ops/quant_ops.py` backend seam,
    QuantDense kernels passed through as int8), then the SAME inference
    function every other serve bucket jits."""
    from replication_faster_rcnn_tpu.eval.evaluator import make_infer_fn
    from replication_faster_rcnn_tpu.quant.apply import build_infer_variables

    base = make_infer_fn(model, config, image_size)

    def infer(qvars, images):
        return base(build_infer_variables(qvars, config), images)

    return infer


def build_int8_program_specs(
    config: FasterRCNNConfig, model=None, artifact=None
) -> Dict[str, ProgramSpec]:
    """{name: ProgramSpec} for the ``serve_*__int8`` twin programs — one
    per serving bucket/batch — plus one ops.backend=pallas int8 twin
    (largest bucket, smallest batch, ``serve_*__int8__pallas``) whose
    dequantize routes through `ops/pallas/quant_kernel.py`.

    ``artifact`` defaults to the structure-only synthetic artifact
    (all-int8 plan, `quant/apply.py::synthetic_artifact`): lowering only
    needs the qvars STRUCTURE, and pinning the canonical plan keeps the
    audited program matrix independent of any local calibration run. The
    engine builds the same specs against its real sidecar.
    """
    from replication_faster_rcnn_tpu import ops as ops_pkg
    from replication_faster_rcnn_tpu.models.faster_rcnn import FasterRCNN
    from replication_faster_rcnn_tpu.quant.apply import (
        abstract_quantize_variables,
        synthetic_artifact,
    )

    if model is None:
        model = FasterRCNN(config)
    h0, w0 = config.data.image_size
    variables_abs = jax.eval_shape(
        lambda rng, img: model.init({"params": rng}, img, train=False),
        jax.ShapeDtypeStruct((2,), np.uint32),
        jax.ShapeDtypeStruct((1, h0, w0, 3), np.float32),
    )
    if artifact is None:
        artifact = synthetic_artifact(variables_abs)
    qvars_abs = abstract_quantize_variables(variables_abs, artifact)
    plan = dict(artifact["plan"])
    dense_int8 = "quant" in qvars_abs

    def _spec(base_name: str, h: int, w: int, n: int, backend: str):
        name = int8_program_name(base_name)
        if backend == "pallas":
            name = pallas_program_name(name)

        def _build(hh=h, ww=w, nn=n, name_=name, backend_=backend):
            from replication_faster_rcnn_tpu.parallel.plan import (
                Plan,
                compile_step_with_plan,
            )

            jitted = compile_step_with_plan(
                make_int8_infer_fn(model, config, (hh, ww)),
                Plan(label=name_),
            )
            if backend_ == "pallas":
                jitted = _ScopedLower(jitted, "pallas")
            images_abs = jax.ShapeDtypeStruct((nn, hh, ww, 3), np.float32)
            return jitted, (qvars_abs, images_abs)

        meta = {
            "bucket": [h, w],
            "batch": n,
            "params_dtype": "int8",
            "quant_plan": plan,
            "int8_dense": dense_int8,
            "twin": base_name,
        }
        if backend == "pallas":
            meta.update(
                ops_backend="pallas",
                pallas_interpret=ops_pkg.interpret_mode(),
                twin=int8_program_name(base_name),
            )
        return name, ProgramSpec(
            name=name,
            feed="serve",
            k=0,
            arg_roles=("qvariables", "images"),
            build=_build,
            meta=meta,
        )

    specs: Dict[str, ProgramSpec] = {}
    buckets = config.serving.bucket_resolutions(config.data.image_size)
    batches = sorted(set(config.serving.batch_sizes))
    for h, w in buckets:
        for n in batches:
            name, spec = _spec(serve_program_name(h, w, n), h, w, n, "xla")
            specs[name] = spec
    # one pallas int8 twin, mirroring pallas_twin_base_names' serving
    # choice: largest-area bucket, smallest batch
    ph, pw = buckets[-1]
    pn = min(batches)
    name, spec = _spec(serve_program_name(ph, pw, pn), ph, pw, pn, "pallas")
    specs[name] = spec
    return specs


def build_program_specs(
    config: FasterRCNNConfig,
    feeds: Sequence[str] = ("loader",),
    ks: Sequence[int] = (1,),
    include_eval: bool = True,
    cache_n: Optional[int] = None,
) -> Dict[str, ProgramSpec]:
    """The registry: {program_name: ProgramSpec} for every requested
    (feed × K) train program plus (``include_eval``) the eval inference
    program, all against ONE config.

    Each spec reproduces the Trainer's jit site exactly — loader/cached
    feeds jit with ``donate_argnums=(0,)`` and
    ``out_shardings=(state_shardings, None)``; the spmd feed comes
    pre-jitted from `make_shard_map_train_step` (replicated state,
    donated); eval is `Evaluator._jit_infer` under its own eval-mesh
    placement — so what a consumer lowers is what the real run compiles,
    not a similar program. ``cache_n`` sizes the abstract device cache
    for cached-feed programs (default: two batches — the cache length is
    a free shape parameter, and fingerprints pin it).
    """
    from replication_faster_rcnn_tpu.benchmark import abstract_step_inputs
    from replication_faster_rcnn_tpu.parallel import (
        batch_sharding,
        image_sharding,
        replicated,
        stacked_batch_sharding,
    )
    from replication_faster_rcnn_tpu.parallel.plan import (
        Plan,
        compile_step_with_plan,
    )
    from replication_faster_rcnn_tpu.parallel.zero import train_state_shardings
    from replication_faster_rcnn_tpu.train.train_step import (
        build_multi_step,
        make_cached_multi_step,
        make_cached_train_step,
        make_optimizer,
        make_train_step,
    )

    unknown = set(feeds) - set(TRAIN_FEEDS)
    if unknown:
        raise ValueError(f"unknown feeds {sorted(unknown)}; pick from {TRAIN_FEEDS}")
    if any(k < 1 for k in ks):
        raise ValueError(f"ks must be >= 1, got {tuple(ks)}")

    mesh, mesh_cfg = _mesh_for(config)
    tx, _ = make_optimizer(config, steps_per_epoch=100)
    model, state_raw, batch_raw = abstract_step_inputs(config, tx)
    state_shardings = train_state_shardings(
        state_raw, mesh, mesh_cfg, config.train.shard_opt_state
    )

    def _attach(tree, shardings):
        return jax.tree_util.tree_map(
            lambda x, s: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=s),
            tree,
            shardings,
        )

    state_abs = _attach(state_raw, state_shardings)
    rep = replicated(mesh)
    state_rep = jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=rep), state_raw
    )
    img_s, other_s = image_sharding(mesh, mesh_cfg), batch_sharding(mesh, mesh_cfg)
    batch_abs = {
        k: jax.ShapeDtypeStruct(
            v.shape, v.dtype, sharding=img_s if k == "image" else other_s
        )
        for k, v in batch_raw.items()
    }
    stacked_s = stacked_batch_sharding(mesh, mesh_cfg)

    def _chunk_abs(k: int) -> Dict[str, jax.ShapeDtypeStruct]:
        return {
            key: jax.ShapeDtypeStruct((k,) + v.shape, v.dtype, sharding=stacked_s)
            for key, v in batch_abs.items()
        }

    batch = config.train.batch_size
    n_cache = cache_n if cache_n is not None else 2 * batch
    # the cache holds the collated sample arrays minus per-step jitter
    # geometry (data/device_cache.py: jitter attaches via sel, never the
    # cache), replicated over the mesh like DeviceCache places them
    cache_abs = {
        k: jax.ShapeDtypeStruct((n_cache,) + v.shape[1:], v.dtype, sharding=rep)
        for k, v in batch_raw.items()
        if k != "jitter"
    }

    def _sel_abs(lead: Tuple[int, ...]) -> Dict[str, jax.ShapeDtypeStruct]:
        sel = {"idx": jax.ShapeDtypeStruct(lead + (batch,), np.int32, sharding=rep)}
        if config.data.augment_hflip:
            sel["flip"] = jax.ShapeDtypeStruct(lead + (batch,), np.bool_, sharding=rep)
        if config.data.augment_scale is not None:
            sel["jitter"] = jax.ShapeDtypeStruct(
                lead + (batch, 4), np.int32, sharding=rep
            )
        return sel

    meta = {
        "n_float_grad_leaves": sum(
            1
            for leaf in jax.tree_util.tree_leaves(state_raw.params)
            if np.issubdtype(leaf.dtype, np.floating)
        ),
        "mesh_shape": dict(mesh.shape),
    }

    # the pjit plan every jit auto-partitioning feed compiles through:
    # donated state, out_shardings pinning the state layout across steps
    def _pjit_plan(shardings, mesh_=None):
        return Plan(
            mesh=mesh_ if mesh_ is not None else mesh,
            donate_argnums=(0,),
            out_shardings=(shardings, None),
        )

    def _loader(k: int, res: Optional[Tuple[int, int]] = None):
        step_fn = make_train_step(model, config, tx, train_resolution=res)
        if k == 1:
            fn, args = step_fn, (state_abs, batch_abs)
        else:
            fn, args = build_multi_step(step_fn, k), (state_abs, _chunk_abs(k))
        return compile_step_with_plan(fn, _pjit_plan(state_shardings)), args

    def _cached(k: int, res: Optional[Tuple[int, int]] = None):
        if k == 1:
            fn = make_cached_train_step(model, config, tx, train_resolution=res)
            args = (state_abs, cache_abs, _sel_abs(()))
        else:
            fn = make_cached_multi_step(
                model, config, tx, k, train_resolution=res
            )
            args = (state_abs, cache_abs, _sel_abs((k,)))
        # donate the state ONLY — the cache must survive the dispatch
        # (train/train_step.py::make_cached_train_step)
        return compile_step_with_plan(fn, _pjit_plan(state_shardings)), args

    def _mp(
        k: int,
        shard_opt: bool = False,
        res: Optional[Tuple[int, int]] = None,
    ):
        # model-parallel feed: the mp (dp, mp) mesh, params sharded 1/mp
        # over the model axis in BOTH the abstract inputs and the
        # out_shardings; the step function itself is the plain auto-
        # partitioning one — GSPMD does the rest. ``shard_opt`` composes
        # ZeRO-1 over dp (the "mp_zero" feed).
        mcfg = mp_audit_config(config)
        if shard_opt != mcfg.train.shard_opt_state:
            mcfg = mcfg.replace(
                train=dataclasses.replace(
                    mcfg.train, shard_opt_state=shard_opt
                )
            )
        mesh_mp, mesh_mp_cfg = _mesh_for(mcfg)
        mp_shardings = train_state_shardings(
            state_raw, mesh_mp, mesh_mp_cfg, shard_opt
        )
        state_mp = _attach(state_raw, mp_shardings)
        img_mp = image_sharding(mesh_mp, mesh_mp_cfg)
        other_mp = batch_sharding(mesh_mp, mesh_mp_cfg)
        batch_mp = {
            key: jax.ShapeDtypeStruct(
                v.shape, v.dtype,
                sharding=img_mp if key == "image" else other_mp,
            )
            for key, v in batch_raw.items()
        }
        step_fn = make_train_step(model, mcfg, tx, train_resolution=res)
        if k == 1:
            fn, args = step_fn, (state_mp, batch_mp)
        else:
            stacked_mp = stacked_batch_sharding(mesh_mp, mesh_mp_cfg)
            chunk_mp = {
                key: jax.ShapeDtypeStruct(
                    (k,) + v.shape, v.dtype, sharding=stacked_mp
                )
                for key, v in batch_mp.items()
            }
            fn, args = build_multi_step(step_fn, k), (state_mp, chunk_mp)
        return (
            compile_step_with_plan(fn, _pjit_plan(mp_shardings, mesh_mp)),
            args,
        )

    def _spmd(k: int, res: Optional[Tuple[int, int]] = None):
        from replication_faster_rcnn_tpu.parallel.spmd import (
            make_shard_map_train_step,
        )

        scfg = config.replace(
            train=dataclasses.replace(config.train, shard_opt_state=False)
        )
        jitted, _ = make_shard_map_train_step(
            scfg, tx, mesh, steps_per_dispatch=k, train_resolution=res
        )
        if k == 1:
            return jitted, (state_rep, batch_abs)
        return jitted, (state_rep, _chunk_abs(k))

    def _zero(k: int, res: Optional[Tuple[int, int]] = None):
        from replication_faster_rcnn_tpu.parallel.spmd import (
            make_shard_map_train_step,
        )

        zcfg = config.replace(
            train=dataclasses.replace(config.train, shard_opt_state=True)
        )
        # ZeRO state placement: params/BN replicated, opt state sharded
        # over the data axis — exactly what the Trainer device_puts
        zero_shardings = train_state_shardings(state_raw, mesh, mesh_cfg, True)
        state_zero = _attach(state_raw, zero_shardings)
        jitted, _ = make_shard_map_train_step(
            zcfg, tx, mesh, steps_per_dispatch=k, state_template=state_raw,
            train_resolution=res,
        )
        if k == 1:
            return jitted, (state_zero, batch_abs)
        return jitted, (state_zero, _chunk_abs(k))

    def _zero_lamb(k: int, res: Optional[Tuple[int, int]] = None):
        from replication_faster_rcnn_tpu.parallel.spmd import (
            make_shard_map_train_step,
        )

        lcfg = config.replace(
            train=dataclasses.replace(
                config.train,
                backend="spmd",
                shard_opt_state=True,
                optimizer="lamb",
            )
        )
        # The module-level tx is the config's own chain (adam for the
        # audit config); this feed needs the LAMB chain whose trust
        # ratio psums its norms over the data axis, and a matching
        # state template (the chain's opt_state structure differs).
        ltx, _ = make_optimizer(
            lcfg,
            steps_per_epoch=100,
            n_shards=mesh.shape[mesh_cfg.data_axis],
        )
        _, lstate_raw, _ = abstract_step_inputs(lcfg, ltx)
        lamb_shardings = train_state_shardings(lstate_raw, mesh, mesh_cfg, True)
        state_lamb = _attach(lstate_raw, lamb_shardings)
        jitted, _ = make_shard_map_train_step(
            lcfg, ltx, mesh, steps_per_dispatch=k, state_template=lstate_raw,
            train_resolution=res,
        )
        if k == 1:
            return jitted, (state_lamb, batch_abs)
        return jitted, (state_lamb, _chunk_abs(k))

    def _eval():
        from replication_faster_rcnn_tpu.eval import Evaluator

        ev = Evaluator(config, model)
        # mirror Evaluator.evaluate's own placement: its eval mesh (or no
        # sharding on a single device), so the lowered program is the one
        # the real eval sweep jits
        e_img_s, rep_s = ev._eval_sharding(config.train.batch_size)

        def _abs(x, s):
            if s is None:
                return jax.ShapeDtypeStruct(x.shape, x.dtype)
            return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=s)

        variables_abs = {
            "params": jax.tree_util.tree_map(
                lambda x: _abs(x, rep_s), state_raw.params
            ),
            "batch_stats": jax.tree_util.tree_map(
                lambda x: _abs(x, rep_s), state_raw.batch_stats
            ),
        }
        images_abs = _abs(batch_raw["image"], e_img_s)
        return ev._jit_infer, (variables_abs, images_abs)

    builders = {
        "loader": _loader, "cached": _cached, "spmd": _spmd, "zero": _zero,
        "zero_lamb": _zero_lamb,
        "mp": _mp,
        "mp_zero": (lambda k: _mp(k, shard_opt=True)),
    }
    roles = {
        "loader": ("state", "batch"),
        "cached": ("state", "cache", "sel"),
        "spmd": ("state", "batch"),
        "zero": ("state", "batch"),
        "zero_lamb": ("state", "batch"),
        "mp": ("state", "batch"),
        "mp_zero": ("state", "batch"),
    }
    mp_meta = dict(meta)
    if any(f in ("mp", "mp_zero") for f in feeds):
        # mp programs lower on their own (dp, mp) mesh — stamp ITS shape
        # so the collective-contract rules know the model-axis width
        mp_mesh, _ = _mesh_for(mp_audit_config(config))
        mp_meta["mesh_shape"] = dict(mp_mesh.shape)
    specs: Dict[str, ProgramSpec] = {}
    for feed in feeds:
        for k in ks:
            name = program_name(feed, k)
            specs[name] = ProgramSpec(
                name=name,
                feed=feed,
                k=k,
                arg_roles=roles[feed],
                build=(lambda f=feed, kk=k: builders[f](kk)),
                meta=dict(mp_meta if feed in ("mp", "mp_zero") else meta),
            )
    if config.data.train_resolutions:
        # multi-scale train buckets: one program per (feed x K x bucket)
        # for the bucketable feeds, each baking the bucket's static
        # on-device resample into the trace (the Trainer's own per-bucket
        # jit sites) — registered here so warmup pre-compiles them and
        # the HLO audit banks them exactly like serving buckets.
        bucket_builders = {
            "loader": _loader,
            "cached": _cached,
            "spmd": _spmd,
            "zero": _zero,
            "zero_lamb": _zero_lamb,
            "mp": _mp,
            "mp_zero": (lambda k, res=None: _mp(k, shard_opt=True, res=res)),
        }
        for feed in feeds:
            if feed not in bucket_builders:
                continue
            for k in ks:
                for bh, bw in config.data.train_resolutions:
                    name = bucket_train_program_name(feed, k, bh, bw)
                    specs[name] = ProgramSpec(
                        name=name,
                        feed=feed,
                        k=k,
                        arg_roles=roles[feed],
                        build=(
                            lambda f=feed, kk=k, hh=bh, ww=bw: bucket_builders[
                                f
                            ](kk, res=(hh, ww))
                        ),
                        # mp bucket programs lower on the (dp, mp) mesh —
                        # they need ITS shape for the model-axis
                        # collective classification, same as their
                        # non-bucket rows
                        meta={
                            **(
                                mp_meta
                                if feed in ("mp", "mp_zero")
                                else meta
                            ),
                            "bucket": [bh, bw],
                        },
                    )
    if include_eval:
        specs["eval_infer"] = ProgramSpec(
            name="eval_infer",
            feed="eval",
            k=0,
            arg_roles=("variables", "images"),
            build=_eval,
            meta=dict(meta),
        )
    return specs


def pallas_twin_base_names(config: FasterRCNNConfig) -> Tuple[str, ...]:
    """The base programs that get an ops.backend=pallas twin in the audit
    registry: the canonical k=1 loader train step, the eval inference
    program, and one serving bucket (full-size resolution, batch 1) —
    one program per dispatch seam (targets matching + proposal NMS in the
    train step; NMS + ROIAlign in the inference programs) without
    doubling the whole (feed × K × bucket) matrix.
    """
    buckets = config.serving.bucket_resolutions(config.data.image_size)
    h, w = buckets[-1]  # largest-area bucket = the full-size program
    b = min(config.serving.batch_sizes)
    return (
        program_name("loader", 1),
        "eval_infer",
        serve_program_name(h, w, b),
    )


def build_pallas_program_specs(
    config: FasterRCNNConfig,
) -> Dict[str, ProgramSpec]:
    """{twin_name: ProgramSpec} for the ops.backend=pallas twin programs.

    Each twin is the SAME ProgramSpec as its base — same jit wrapping,
    same abstract inputs — built and lowered under
    ``ops.backend_scope("pallas")`` via :class:`_ScopedLower`, so the ops
    dispatch sites resolve to the `ops/pallas/` kernels at trace time.
    Twin meta records ``ops_backend``/``pallas_interpret``/``twin`` for
    the fingerprint bank and the HX007 hlolint rule; off-TPU the kernels
    lower in interpret mode (plain StableHLO loops, no custom-call), on a
    real TPU they lower through Mosaic custom-calls.
    """
    from replication_faster_rcnn_tpu import ops as ops_pkg

    base_specs = {
        **build_program_specs(
            config, feeds=("loader",), ks=(1,), include_eval=True
        ),
        **build_serving_specs(config),
    }
    interpret = ops_pkg.interpret_mode()
    specs: Dict[str, ProgramSpec] = {}
    for base_name in pallas_twin_base_names(config):
        base = base_specs[base_name]
        name = pallas_program_name(base_name)

        def _build(b=base):
            from replication_faster_rcnn_tpu import ops as ops_pkg

            with ops_pkg.backend_scope("pallas"):
                jitted, args = b.build()
            return _ScopedLower(jitted, "pallas"), args

        meta = dict(base.meta)
        meta.update(
            ops_backend="pallas", pallas_interpret=interpret, twin=base_name
        )
        specs[name] = ProgramSpec(
            name=name,
            feed=base.feed,
            k=base.k,
            arg_roles=base.arg_roles,
            build=_build,
            meta=meta,
        )
    return specs


def warmup_compile(
    config: FasterRCNNConfig,
    include_eval: bool = True,
    cache_n: Optional[int] = None,
    include_serving: bool = False,
) -> Dict[str, float]:
    """AOT-compile the programs a training run of ``config`` would jit.

    Covers the per-step train program of the config's own feed (spmd
    backend, ``--cache-device`` selection feed when ``cache_n`` supplies
    the dataset length, host loader otherwise), the fused multi-step
    program when ``train.steps_per_dispatch > 1``, and (``include_eval``)
    the eval inference program. Returns {program_name: compile_seconds};
    with the persistent cache enabled, a warmed second run shows
    near-zero times here and — the point — at real-run startup.

    Everything comes from :func:`build_program_specs`, so the compiled
    executables are cache hits for the real run, not merely similar
    programs. Cached-feed programs need the cache length ``cache_n``
    (= len(dataset)) to pin shapes; without it the loader program is
    warmed instead (same step math, different feed plumbing)."""
    tracer = tspans.current_tracer()
    if config.mesh.param_sharding and config.mesh.num_model > 1:
        # model-parallel run (--mesh-shape with MP > 1; the decision
        # table already pinned backend='auto' for this combination)
        feed = "mp_zero" if config.train.shard_opt_state else "mp"
    elif config.train.backend == "spmd":
        if config.train.shard_opt_state:
            feed = (
                "zero_lamb" if config.train.optimizer == "lamb" else "zero"
            )
        else:
            feed = "spmd"
    elif config.data.cache_device and cache_n is not None:
        feed = "cached"
    else:
        feed = "loader"
    k = max(1, config.train.steps_per_dispatch)
    ks = (1,) if k == 1 else (1, k)
    specs = build_program_specs(
        config, feeds=(feed,), ks=ks, include_eval=include_eval, cache_n=cache_n
    )
    if include_serving:
        # pre-warm the serving engine's bucket matrix too, so a `frcnn
        # serve` start against the same persistent cache deserializes
        # instead of compiling
        specs = {**specs, **build_serving_specs(config)}

    # report under the registry's canonical feed-qualified names
    # (train_<feed>_k<K> / eval_infer / serve_<HxW>_b<N>) — the same keys
    # `frcnn audit` banks, so the two reports line up program-for-program
    from replication_faster_rcnn_tpu import ops as ops_pkg

    # the config's resolved ops backend pins every program here: for
    # backend=pallas this AOT pass (plus the persistent cache) is the ONLY
    # sanctioned route to an on-chip pallas compile — the trainer and the
    # serving engine trace under the same scope and hit the cache
    backend = ops_pkg.resolve_backend(config)
    times: Dict[str, float] = {}
    for spec in specs.values():
        with tracer.span(f"compile/{spec.name}", cat="compile"):
            t0 = time.perf_counter()
            with ops_pkg.backend_scope(backend):
                jitted, args = spec.build()
                jitted.lower(*args).compile()
            times[spec.name] = round(time.perf_counter() - t0, 3)
    return times
