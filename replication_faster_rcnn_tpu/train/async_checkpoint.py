"""Background scheduled checkpointing — the write off the critical path.

PR 3's verified saves serialize the full train state, CRC every leaf
into the sidecar manifest and fsync/rename — seconds of wall time that
the epoch loop paid synchronously at every checkpoint interval. The
split that fixes it without weakening any fault-tolerance guarantee:

* **Trainer thread (blocking, cheap):** snapshot the replicated state to
  host once (`jax.device_get` — unavoidable, the bytes must leave the
  device) and hand the snapshot to the writer. If the PREVIOUS save is
  still in flight, block until it lands first — in-flight depth is
  bounded at one, so a slow disk applies backpressure instead of
  accumulating full-model snapshots in RAM.
* **Writer thread (slow, off-path):** orbax serialize + fsync, then the
  CRC manifest + atomic rename (`fault.write_manifest`, same function
  the sync path uses — restore-side verification and the fallback walk
  are bit-for-bit unchanged), then manifest pruning.

Only ``scheduled`` saves ride the writer. Emergency (preemption), final
and crash saves stay synchronous on the trainer thread: they are the
last chance to persist anything and must complete before the process
exits. The trainer drains the writer before any synchronous save and
before restore, so the on-disk store is never touched from two threads.

Failure containment matches PR 3's scheduled-save semantics: a writer
failure is recorded and surfaced at the next drain point (incident +
stderr warning, training continues, the next interval retries). The
failed step's manifest was never renamed into place, so a torn orbax
directory is exactly what `verified_restore`'s fallback walk already
handles.

Multi-process runs use the same writer: every rank owns one (the
trainer submits a closure over FRESH device buffers rather than a host
snapshot — see `Trainer._save_async`), the writer threads execute the
collective orbax save in lockstep, and orbax's replica election keeps
process 0 the only byte writer. The drain points are identical on all
ranks, so no rank can start save N+1 while another is still in save N.
"""

from __future__ import annotations

import threading
from typing import Callable, Optional, Tuple


class AsyncCheckpointWriter:
    """Single background writer with an in-flight bound of one save.

    Not a general thread pool: checkpoints must land in submission order
    and two concurrent orbax writers on one store would race, so the
    "queue" is the single in-flight slot — :meth:`submit` first waits
    for the previous save (the only case where the trainer blocks on
    checkpoint I/O at all).

    All methods are intended for ONE controlling thread (the trainer);
    the background thread only runs the submitted work item.
    """

    def __init__(self) -> None:
        self._thread: Optional[threading.Thread] = None
        # _error crosses the writer->trainer boundary: written by the
        # writer on failure, read-and-cleared by the trainer at drain.
        self._lock = threading.Lock()
        self._error: Optional[Tuple[int, BaseException]] = None
        self._last_step: Optional[int] = None

    @property
    def in_flight(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    @property
    def last_submitted_step(self) -> Optional[int]:
        return self._last_step

    def wait(self) -> Optional[Tuple[int, BaseException]]:
        """Block until no save is in flight. Returns (step, exception) of
        a failed background save — once, then the error slot is cleared —
        or None. Never raises: the caller owns containment policy."""
        t = self._thread
        if t is not None:
            t.join()
            self._thread = None
        with self._lock:
            err, self._error = self._error, None
        return err

    def submit(
        self, step: int, work: Callable[[], None]
    ) -> Optional[Tuple[int, BaseException]]:
        """Run ``work`` (the serialize+manifest closure for ``step``) on
        the background writer. Blocks only while a previous save is in
        flight; returns that save's deferred error, if any, exactly like
        :meth:`wait`."""
        err = self.wait()
        self._last_step = int(step)

        def _run() -> None:
            try:
                work()
            except BaseException as e:  # noqa: BLE001 — surfaced at drain
                with self._lock:
                    self._error = (int(step), e)

        # Non-daemon: a checkpoint caught mid-fsync by interpreter exit
        # must finish, not be killed — the thread always terminates once
        # work() returns, so this never wedges shutdown.
        t = threading.Thread(target=_run, name="ckpt-writer", daemon=False)
        self._thread = t
        t.start()
        return err
