"""Fault-tolerance layer: guarded updates, preemption-safe shutdown, and
verified checkpoint restore with latest-good fallback.

PR 1 built the *eyes* (health metrics count nonfinite grad entries per
step, the watchdog records stall incidents); this module closes the
observe→react loop for the three failure modes that dominate long
schedules on preemptible capacity:

* **Poisoned gradients** — :func:`guarded_update` gates the optimizer
  update on ``nonfinite_count == 0`` inside the jitted step, so one NaN
  batch skips the update (params, Adam moments AND BatchNorm stats carry
  through unchanged) instead of silently poisoning Adam's moments for
  the rest of the run. The host-side :class:`SkipMonitor` turns the
  per-step ``skipped`` flags into consecutive-skip escalation: a
  transient blow-up costs one step, a persistently-NaN run halts with a
  descriptive error instead of burning an epoch of wasted compute.
* **Preemption** — :class:`GracefulShutdown` converts SIGTERM/SIGINT
  into a flag the trainer checks at each dispatch boundary; the loop
  saves an emergency checkpoint (tagged in the manifest) and exits via
  :class:`Preempted` with a distinct exit code so a supervisor can tell
  "preempted, resume me" from "crashed".
* **Torn checkpoints** — every save writes a sidecar manifest (step,
  config hash, leaf count, per-leaf CRC32); :func:`verified_restore`
  checks the restored tree against it and, on corruption or load
  failure, walks back to the newest step that verifies, logging what
  was discarded — a truncated latest directory costs one checkpoint
  interval, not the run.

Everything device-side is a scalar predicate + per-leaf selects, so the
guarded step is bit-identical to the unguarded one on clean gradients
and identical across all three feeds (host loader, ``--cache-device``,
shard_map) and across fused ``steps_per_dispatch`` chunks — the gate
lives in the two step bodies everything else composes from.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import signal
import threading
import zlib
from datetime import datetime, timezone
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from replication_faster_rcnn_tpu.faultlib import failpoints
from replication_faster_rcnn_tpu.telemetry import spans as tspans
from replication_faster_rcnn_tpu.telemetry.health import health_metrics

# Distinct exit code for "preempted with a verified emergency checkpoint;
# restart me with --resume" — EX_TEMPFAIL in sysexits.h, i.e. transient,
# retry. Crashes keep their tracebacks and nonzero codes; a supervisor
# branching on 75 can requeue instead of paging.
EXIT_PREEMPTED = 75

# Exit code for "a peer rank was lost; re-form the fleet at the surviving
# world size and resume me" — the elastic supervisor (parallel/elastic.py)
# branches on it (or on the durable shrink-intent file, for the watchdog
# path that must os._exit before the JAX coordination service's ~10s
# SIGABRT) to respawn the child at the next generation.
EXIT_FLEET_SHRINK = 76

NONFINITE_POLICIES = ("apply", "skip", "halt")

MANIFEST_DIRNAME = "manifests"
MANIFEST_SCHEMA = "ckpt_manifest/v1"


class Preempted(RuntimeError):
    """Raised by the trainer after a graceful-shutdown request has been
    honored: the emergency checkpoint is on disk and verified."""

    def __init__(self, step: int, reason: str = "signal"):
        super().__init__(
            f"training preempted ({reason}) at step {step}; emergency "
            "checkpoint saved — restart with --resume"
        )
        self.step = int(step)
        self.reason = reason


class NonFiniteEscalation(FloatingPointError):
    """Raised when nonfinite-gradient skips exceed the configured budget
    (or immediately under ``nonfinite_policy='halt'``)."""


class FleetShrink(RuntimeError):
    """Raised at a dispatch boundary when a peer rank's heartbeat lease
    has expired: this rank must exit (EXIT_FLEET_SHRINK) so the elastic
    supervisor can re-form the fleet at the surviving world size. No
    emergency checkpoint is attempted — every save is a cross-process
    collective that would hang on the dead peer — so resume falls back to
    the last CRC-verified step (bound the window with
    ``train.checkpoint_every_steps``)."""

    def __init__(self, step: int, lost, survivors):
        self.step = int(step)
        self.lost = sorted(int(r) for r in lost)
        self.survivors = sorted(int(r) for r in survivors)
        super().__init__(
            f"fleet shrink at step {self.step}: rank(s) {self.lost} lost "
            f"heartbeat lease; survivors {self.survivors} re-form at world "
            f"size {len(self.survivors)}"
        )


# --------------------------------------------------------------- jitted gate


def guarded_update(
    tx: optax.GradientTransformation,
    state,
    grads: Any,
    new_stats: Any,
    policy: str = "skip",
) -> Tuple[Any, Dict[str, jnp.ndarray]]:
    """Optimizer update gated on gradient finiteness, inside the jitted step.

    Returns ``(new_state, health)`` where ``health`` is the standard
    health-metric dict plus a ``skipped`` flag (1.0 when the update was
    withheld). Under ``policy='apply'`` the update is unconditional (the
    pre-guard behavior). Under ``'skip'``/``'halt'`` a gradient tree with
    any NaN/Inf entry leaves params, optimizer state AND BatchNorm stats
    bit-identical to their pre-step values — the gate is a scalar
    predicate feeding per-leaf selects, so a clean step is bit-identical
    to the unguarded one, and the same code composes unchanged under
    `lax.scan` (fused multi-step) and `shard_map` (call it on post-psum
    grads so every shard takes the same branch). ``step`` advances either
    way: it counts dispatched batches, and the fold_in(rng, step) keying
    must keep moving so the next batch draws fresh sampling randomness.

    ``'halt'`` gates exactly like ``'skip'`` — params must be clean when
    the host-side :class:`SkipMonitor` raises on the flag.
    """
    if policy not in NONFINITE_POLICIES:
        raise ValueError(
            f"nonfinite_policy must be one of {NONFINITE_POLICIES}, got {policy!r}"
        )
    updates, new_opt = tx.update(grads, state.opt_state, state.params)
    new_params = optax.apply_updates(state.params, updates)
    health = health_metrics(grads, state.params, updates)
    if policy == "apply":
        health["skipped"] = jnp.zeros((), jnp.float32)
        return (
            state.replace(
                step=state.step + 1,
                params=new_params,
                batch_stats=new_stats,
                opt_state=new_opt,
            ),
            health,
        )
    ok = health["nonfinite_count"] == 0

    def keep(new, old):
        # select, not arithmetic masking: NaNs on the untaken side must
        # not propagate, and the taken side must pass through bitwise
        return jnp.where(ok, new, old)

    new_state = state.replace(
        step=state.step + 1,
        params=jax.tree_util.tree_map(keep, new_params, state.params),
        batch_stats=jax.tree_util.tree_map(keep, new_stats, state.batch_stats),
        opt_state=jax.tree_util.tree_map(keep, new_opt, state.opt_state),
    )
    health["skipped"] = 1.0 - ok.astype(jnp.float32)
    return new_state, health


def check_step_metrics(metrics: Dict[str, Any], step: int) -> Dict[str, float]:
    """Log-boundary metric validation, guard-aware: a row whose update was
    withheld (``skipped > 0``) is allowed to carry non-finite diagnostics
    (the NaN loss/grad_norm of the poisoned batch ARE the evidence); any
    other row fails fast exactly like :func:`utils.debug.finite_or_raise`.
    """
    from replication_faster_rcnn_tpu.utils.debug import finite_or_raise

    vals = {k: float(v) for k, v in metrics.items()}
    if vals.get("skipped", 0.0) > 0.0:
        return vals
    return finite_or_raise(vals, step)


# ------------------------------------------------------- host-side monitor


class SkipMonitor:
    """Consecutive-skip escalation from the per-step ``skipped`` flags.

    The trainer feeds every dispatch's flag in via :meth:`observe` (a
    scalar, or a stacked ``[K]`` array from a fused chunk) WITHOUT
    forcing a device sync — flags are retained as device arrays and only
    fetched in :meth:`drain`, which the trainer calls where it already
    syncs (log boundaries, epoch ends). Under ``policy='halt'`` observe
    drains immediately: promptness over pipelining is the point of that
    policy.

    Escalation (``consecutive >= max_consecutive``, or any skip under
    ``halt``) calls ``on_escalate(kind, **fields)`` — the trainer routes
    it to the watchdog incident log — then raises
    :class:`NonFiniteEscalation` with a descriptive message.
    """

    # auto-drain threshold: pending flags this old are long computed, so
    # fetching them cannot stall the pipeline; bounds memory for callers
    # that never hit a log boundary (direct train_one_batch loops)
    _AUTO_DRAIN = 512

    def __init__(
        self,
        policy: str = "skip",
        max_consecutive: int = 10,
        on_escalate: Optional[Callable[..., None]] = None,
    ):
        if policy not in NONFINITE_POLICIES:
            raise ValueError(
                f"nonfinite_policy must be one of {NONFINITE_POLICIES}, "
                f"got {policy!r}"
            )
        self.policy = policy
        self.max_consecutive = int(max_consecutive)
        self.on_escalate = on_escalate
        self.consecutive = 0
        self.total_skipped = 0
        self.last_skipped_step: Optional[int] = None
        self._pending: List[Tuple[int, Any]] = []

    def observe(self, first_step: int, metrics: Dict[str, Any]) -> None:
        """Record one dispatch's ``skipped`` flag(s); ``first_step`` is the
        1-indexed global step of the dispatch's first fused step."""
        if self.policy == "apply" or "skipped" not in metrics:
            return
        self._pending.append((int(first_step), metrics["skipped"]))
        if self.policy == "halt" or len(self._pending) >= self._AUTO_DRAIN:
            self.drain()

    def drain(self) -> None:
        """Fetch pending flags and update the consecutive counter; raises
        :class:`NonFiniteEscalation` past the budget."""
        pending, self._pending = self._pending, []
        for first, flags in pending:
            with tspans.current_tracer().span("fault/skip_drain", cat="sync"):
                flags = jax.device_get(flags)
            arr = np.atleast_1d(np.asarray(flags, np.float64))
            for off, flag in enumerate(arr):
                if flag > 0:
                    self.consecutive += 1
                    self.total_skipped += 1
                    self.last_skipped_step = first + off
                    if self.policy == "halt":
                        self._escalate(
                            "nonfinite_gradient halted training "
                            f"(nonfinite_policy='halt') at step {first + off}: "
                            "the update was withheld and params are clean; "
                            "inspect the batch, or train with "
                            "nonfinite_policy='skip' to ride through "
                            "transients"
                        )
                    if self.consecutive >= self.max_consecutive:
                        self._escalate(
                            f"{self.consecutive} consecutive nonfinite-"
                            "gradient skips (>= train.max_consecutive_skips="
                            f"{self.max_consecutive}, last at step "
                            f"{first + off}, {self.total_skipped} skipped "
                            "total): gradients are persistently non-finite, "
                            "not a transient — lower the lr, check the data, "
                            "or enable --debug-nans to pinpoint the op"
                        )
                else:
                    self.consecutive = 0

    def _escalate(self, message: str) -> None:
        if self.on_escalate is not None:
            try:
                self.on_escalate(
                    "nonfinite_escalation",
                    policy=self.policy,
                    consecutive=self.consecutive,
                    total_skipped=self.total_skipped,
                    last_skipped_step=self.last_skipped_step,
                )
            except Exception:  # incident recording must not mask the error
                pass
        raise NonFiniteEscalation(message)


# ----------------------------------------------------------- shutdown flag


class GracefulShutdown:
    """Convert SIGTERM/SIGINT into a flag checked at dispatch boundaries.

    Context manager: on enter, installs handlers that set
    :attr:`requested` (first signal) — the training loop then saves an
    emergency checkpoint and raises :class:`Preempted` at the next
    boundary. A second delivery of the same signal restores the previous
    handler and re-raises it, so a stuck save can still be killed. On
    exit, previous handlers are restored.

    Installed best-effort: off the main thread (where ``signal.signal``
    raises) the flag remains programmatically settable via
    :meth:`request` but no handlers are bound.
    """

    def __init__(self, signals=(signal.SIGTERM, signal.SIGINT)):
        self.signals = tuple(signals)
        self._prev: Dict[int, Any] = {}
        self._requested = threading.Event()
        self.reason: Optional[str] = None

    @property
    def requested(self) -> bool:
        return self._requested.is_set()

    def request(self, reason: str = "manual") -> None:
        if not self._requested.is_set():
            self.reason = reason
            self._requested.set()

    def _handle(self, signum, frame) -> None:
        if self._requested.is_set():
            # second signal: give up gracefulness, fall back to the
            # previous disposition and re-deliver
            prev = self._prev.get(signum, signal.SIG_DFL)
            signal.signal(signum, prev)
            os.kill(os.getpid(), signum)
            return
        try:
            name = signal.Signals(signum).name
        except ValueError:  # pragma: no cover - unknown signal number
            name = f"signal {signum}"
        self.request(name)

    def __enter__(self) -> "GracefulShutdown":
        for sig in self.signals:
            try:
                self._prev[sig] = signal.signal(sig, self._handle)
            except (ValueError, OSError):  # not the main thread
                pass
        return self

    def __exit__(self, *exc: Any) -> bool:
        for sig, prev in self._prev.items():
            try:
                signal.signal(sig, prev)
            except (ValueError, OSError):  # pragma: no cover - defensive
                pass
        self._prev.clear()
        return False


# ------------------------------------------------------ checkpoint manifest


def config_hash(config) -> str:
    """Stable short hash of a (dataclass) config — manifest provenance."""
    payload = json.dumps(
        dataclasses.asdict(config), sort_keys=True, default=str
    )
    return hashlib.sha256(payload.encode()).hexdigest()[:16]


def _leaf_records(tree: Any) -> Dict[str, Dict[str, Any]]:
    leaves: Dict[str, Dict[str, Any]] = {}
    flat = jax.tree_util.tree_flatten_with_path(tree)[0]
    with tspans.current_tracer().span("checkpoint/manifest", cat="checkpoint"):
        host_leaves = [jax.device_get(leaf) for _path, leaf in flat]
    for (path, _leaf), fetched in zip(flat, host_leaves):
        arr = np.asarray(fetched)
        leaves[jax.tree_util.keystr(path)] = {
            "crc32": zlib.crc32(np.ascontiguousarray(arr).tobytes()),
            "shape": list(arr.shape),
            "dtype": str(arr.dtype),
        }
    return leaves


def manifest_path(workdir: str, step: int) -> str:
    return os.path.join(
        os.path.abspath(workdir), MANIFEST_DIRNAME, f"{int(step)}.json"
    )


def run_topology(config=None, mesh=None) -> Dict[str, Any]:
    """The runtime topology a checkpoint was saved under: process count,
    global device count, mesh shape, and whether the optimizer state was
    ZeRO-sharded at save time. Provenance, not a restore constraint —
    checkpoints are saved fully replicated (host-gathered), so
    `verified_restore` re-places them onto whatever mesh the restoring
    run built (a preempted 2-proc×4-dev run resumes on 1-proc×8-dev and
    vice versa); the CRCs are computed on the gathered host tree and are
    therefore topology-invariant."""
    topo: Dict[str, Any] = {
        "process_count": jax.process_count(),
        "device_count": jax.device_count(),
        # fleet generation (elastic training): 0 for a static fleet; the
        # elastic supervisor bumps it per re-formation via the child env
        "generation": int(os.environ.get("FRCNN_FLEET_GENERATION", "0") or 0),
    }
    if mesh is not None:
        topo["mesh_shape"] = {
            str(name): int(size) for name, size in mesh.shape.items()
        }
    if config is not None:
        topo["shard_opt_state"] = bool(config.train.shard_opt_state)
    return topo


def write_manifest(
    workdir: str,
    step: int,
    state: Any,
    config=None,
    kind: str = "scheduled",
    writer: str = "sync",
    topology: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    """Sidecar manifest for the checkpoint at ``step``: leaf count +
    per-leaf CRC32/shape/dtype of the saved tree, the config hash, the
    save ``kind`` (scheduled | emergency | crash | final), and the saving
    run's topology (:func:`run_topology` unless passed explicitly).
    Written atomically next to — not inside — the orbax step directory,
    so orbax never sees a foreign file and a manifest for a
    garbage-collected step is merely stale, not corrupting.

    ``writer`` records whether the save ran on the trainer thread
    ("sync") or the background checkpoint writer ("async",
    train/async_checkpoint.py) — provenance for post-mortems; restore
    verification treats both identically."""
    leaves = _leaf_records(state)
    manifest = {
        "schema": MANIFEST_SCHEMA,
        "step": int(step),
        "kind": kind,
        "writer": writer,
        "saved_utc": datetime.now(timezone.utc).isoformat(),
        "config_hash": config_hash(config) if config is not None else None,
        "topology": topology if topology is not None else run_topology(config),
        "leaf_count": len(leaves),
        "leaves": leaves,
    }
    # failpoint: ioerror raises before any bytes land; torn_write /
    # crc_corrupt hit the tmp file so the published manifest is damaged
    # (load_manifest treats unreadable JSON as missing → step discarded)
    inj = failpoints.fire("checkpoint.manifest", step=int(step), kind=kind)
    path = manifest_path(workdir, step)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(manifest, f, indent=1)
    if inj is not None and inj.kind in ("torn_write", "crc_corrupt"):
        failpoints.apply_file_fault(inj, tmp)
    os.replace(tmp, path)
    return manifest


def load_manifest(workdir: str, step: int) -> Optional[Dict[str, Any]]:
    path = manifest_path(workdir, step)
    try:
        with open(path) as f:
            manifest = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    if manifest.get("schema") != MANIFEST_SCHEMA:
        return None
    return manifest


FEED_BASENAME = "feed.jsonl"


def feed_path(workdir: str) -> str:
    return os.path.join(
        os.path.abspath(workdir), MANIFEST_DIRNAME, FEED_BASENAME
    )


def publish_manifest_event(
    workdir: str, step: int, kind: str = "scheduled", writer: str = "sync"
) -> None:
    """Append one line to ``manifests/feed.jsonl`` — the rollout feed.

    The manifest files themselves are the versions; this append-only log
    records *publication order* so the serving-side watcher
    (serving/rollout/) can tail it instead of re-scanning and
    re-validating every manifest per poll, and so a step that is later
    pruned still leaves a publication record. Best-effort: a failed
    append never fails the save that produced the checkpoint (the
    watcher falls back to directory scans)."""
    event = {
        "step": int(step),
        "kind": kind,
        "writer": writer,
        "published_utc": datetime.now(timezone.utc).isoformat(),
    }
    try:
        path = feed_path(workdir)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "a") as f:
            f.write(json.dumps(event, sort_keys=True) + "\n")
    except OSError:  # pragma: no cover - best-effort publication
        pass


def prune_manifests(workdir: str, live_steps) -> None:
    """Drop manifests whose checkpoints orbax has garbage-collected."""
    d = os.path.join(os.path.abspath(workdir), MANIFEST_DIRNAME)
    if not os.path.isdir(d):
        return
    keep = {f"{int(s)}.json" for s in live_steps}
    for name in os.listdir(d):
        if name.endswith(".json") and name not in keep:
            try:
                os.remove(os.path.join(d, name))
            except OSError:  # pragma: no cover - best-effort housekeeping
                pass


def verify_state(
    manifest: Dict[str, Any], state: Any, expected_config_hash: Optional[str] = None
) -> List[str]:
    """Integrity problems (empty list = verified). Config-hash drift is
    reported but integrity is judged on the tree alone — warm-starting
    under an edited config is legitimate; restoring torn bytes is not."""
    problems: List[str] = []
    got = _leaf_records(state)
    want = manifest.get("leaves", {})
    if len(got) != manifest.get("leaf_count"):
        problems.append(
            f"leaf count {len(got)} != manifest {manifest.get('leaf_count')}"
        )
    for key, rec in want.items():
        if key not in got:
            problems.append(f"missing leaf {key}")
        elif got[key]["crc32"] != rec["crc32"]:
            problems.append(
                f"checksum mismatch at {key} "
                f"(crc32 {got[key]['crc32']} != {rec['crc32']})"
            )
    for key in got:
        if key not in want:
            problems.append(f"unexpected leaf {key}")
    if (
        expected_config_hash is not None
        and manifest.get("config_hash") not in (None, expected_config_hash)
    ):
        # provenance note, not an integrity failure
        problems = problems  # no-op: documented decision point
    return problems


@dataclasses.dataclass
class RestoreResult:
    step: Optional[int]
    state: Any
    manifest: Optional[Dict[str, Any]]
    discarded: List[Tuple[int, str]]


def verified_restore(
    mgr,
    template: Any,
    workdir: str,
    step: Optional[int] = None,
    log: Callable[[str], None] = print,
) -> RestoreResult:
    """Restore the newest checkpoint that loads AND matches its manifest.

    ``mgr`` is an orbax CheckpointManager, ``template`` the host-side
    tree to restore into. With an explicit ``step`` there is no walking:
    a corrupt requested step raises (silently handing back older weights
    than asked for would be worse than failing). With ``step=None`` the
    steps are tried newest→oldest; every discard (load failure or
    checksum mismatch) is logged and returned so the caller can delete
    the torn directories. A checkpoint with no manifest (pre-manifest
    legacy) restores unverified, with a log line saying so.
    """
    import orbax.checkpoint as ocp

    steps = sorted(int(s) for s in mgr.all_steps())
    if step is not None:
        steps = [s for s in steps if s == int(step)]
        if not steps:
            raise ValueError(
                f"checkpoint step {step} not found in {workdir} "
                f"(available: {sorted(mgr.all_steps())})"
            )
    discarded: List[Tuple[int, str]] = []
    for s in reversed(steps):
        try:
            restored = mgr.restore(s, args=ocp.args.StandardRestore(template))
        except Exception as e:  # torn/truncated step dir, orbax metadata, ...
            why = f"restore failed: {type(e).__name__}: {str(e)[:200]}"
            if step is not None:
                raise RuntimeError(
                    f"checkpoint step {s} in {workdir} is unrecoverable "
                    f"({why}); drop --checkpoint-step to fall back to the "
                    "newest verifiable step"
                ) from e
            discarded.append((s, why))
            log(f"fault: discarding checkpoint step {s} — {why}")
            continue
        manifest = load_manifest(workdir, s)
        if manifest is None:
            log(
                f"fault: checkpoint step {s} has no manifest "
                "(pre-manifest save) — restoring unverified"
            )
            return RestoreResult(s, restored, None, discarded)
        problems = verify_state(manifest, restored)
        if problems:
            why = "; ".join(problems[:3]) + (
                f" (+{len(problems) - 3} more)" if len(problems) > 3 else ""
            )
            if step is not None:
                raise RuntimeError(
                    f"checkpoint step {s} in {workdir} failed manifest "
                    f"verification: {why}"
                )
            discarded.append((s, why))
            log(f"fault: discarding checkpoint step {s} — {why}")
            continue
        if discarded:
            log(
                f"fault: fell back to verified step {s} after discarding "
                f"{[d[0] for d in discarded]}"
            )
        saved_topo = manifest.get("topology") or {}
        current = run_topology()
        drift = {
            k: (saved_topo[k], current[k])
            for k in ("process_count", "device_count")
            if k in saved_topo and saved_topo[k] != current[k]
        }
        if drift:
            # informational: state is saved fully replicated, so the
            # caller re-places it onto the current mesh bit-identically
            log(
                f"fault: checkpoint step {s} was saved on a different "
                f"topology ({', '.join(f'{k} {a}->{b}' for k, (a, b) in drift.items())}); "
                "re-placing the replicated state onto the current mesh"
            )
        return RestoreResult(s, restored, manifest, discarded)
    return RestoreResult(None, None, None, discarded)
