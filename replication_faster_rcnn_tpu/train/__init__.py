from replication_faster_rcnn_tpu.train import losses  # noqa: F401
from replication_faster_rcnn_tpu.train.train_step import (  # noqa: F401
    TrainState,
    build_multi_step,
    compute_losses,
    create_train_state,
    make_cached_multi_step,
    make_cached_train_step,
    make_optimizer,
    make_train_step,
)
from replication_faster_rcnn_tpu.train.trainer import Trainer  # noqa: F401
