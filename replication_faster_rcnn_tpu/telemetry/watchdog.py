"""Heartbeat watchdog for wedged devices and tunnels.

Round 5's bench had to *guess* "device unresponsive >180s, tunnel
wedged" because nothing recorded where the process was when it stopped
making progress. This watchdog turns that guess into a recorded root
cause: the training loop calls :meth:`StallWatchdog.beat` once per step,
a daemon thread checks elapsed-since-beat against a timeout, and on a
stall it appends a diagnostic snapshot — last beat's step/phase, the
tracer's last-entered span, and whatever live gauges (prefetch queue
depth, ...) the caller registered — to a JSONL incident file.

Semantics are fire-then-recover, not fire-and-kill: a stall fires once
per episode, the next beat records a ``recovered`` incident and re-arms.
Killing the process is the *caller's* policy (the bench has its own
``os._exit`` guards); the watchdog's job is evidence.

A monotonic progress file (atomic replace) mirrors the latest beat to
disk so an *external* supervisor — or a human over a flaky tunnel — can
check liveness without attaching to the process.
"""

from __future__ import annotations

import json
import os
import threading
import time
from datetime import datetime, timezone
from typing import Any, Callable, Dict, Optional

from replication_faster_rcnn_tpu.telemetry.spans import NULL_TRACER


class StallWatchdog:
    """Daemon-thread stall detector.

    Args:
        timeout_s: elapsed-since-last-beat that counts as a stall.
        snapshot_path: JSONL file appended with stall/recovered incidents.
        progress_path: JSON file atomically rewritten on each beat.
        tracer: span tracer whose ``last_span`` goes into snapshots.
        providers: name → zero-arg callable of live gauges to sample at
            snapshot time (errors are captured per-provider, never raised
            — a snapshot of a sick process must not die on a sick gauge).
        on_stall: optional callback invoked with the snapshot dict.
        poll_s: check interval; defaults to ``timeout_s / 4`` capped to 5s.
        rank: process_index of a multi-process run — stamped on every
            incident and progress payload so merged per-rank incident
            streams stay attributable (None on single-process runs).
    """

    def __init__(
        self,
        timeout_s: float = 300.0,
        snapshot_path: Optional[str] = None,
        progress_path: Optional[str] = None,
        tracer: Any = NULL_TRACER,
        providers: Optional[Dict[str, Callable[[], Any]]] = None,
        on_stall: Optional[Callable[[Dict[str, Any]], None]] = None,
        poll_s: Optional[float] = None,
        clock: Callable[[], float] = time.monotonic,
        rank: Optional[int] = None,
    ):
        self.timeout_s = timeout_s
        self.rank = rank
        self.snapshot_path = snapshot_path
        self.progress_path = progress_path
        self.tracer = tracer
        self.providers: Dict[str, Callable[[], Any]] = dict(providers or {})
        self.on_stall = on_stall
        self.poll_s = poll_s if poll_s is not None else min(timeout_s / 4.0, 5.0)
        self._clock = clock
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._last_beat = self._clock()
        self._last_step: Optional[int] = None
        self._last_phase: Optional[str] = None
        self._beats = 0
        self._in_stall = False
        self.fired_count = 0
        self.recovered_count = 0
        self.last_snapshot: Optional[Dict[str, Any]] = None

    # -- heartbeat ---------------------------------------------------------

    def beat(self, step: Optional[int] = None, phase: Optional[str] = None) -> None:
        """Record progress. Called from the training loop, once per step
        (or per long operation like eval/checkpoint via ``phase``)."""
        now = self._clock()
        with self._lock:
            self._last_beat = now
            self._beats += 1
            if step is not None:
                self._last_step = step
            if phase is not None:
                self._last_phase = phase
            recovered = self._in_stall
            self._in_stall = False
        if recovered:
            self.recovered_count += 1
            self._record_incident(self.snapshot(reason="recovered"))
        self._write_progress()

    def _write_progress(self) -> None:
        if self.progress_path is None:
            return
        payload = {
            "utc": datetime.now(timezone.utc).isoformat(),
            "step": self._last_step,
            "phase": self._last_phase,
            "beats": self._beats,
            "pid": os.getpid(),
        }
        if self.rank is not None:
            payload["process_index"] = self.rank
        tmp = f"{self.progress_path}.tmp.{os.getpid()}"
        try:
            with open(tmp, "w") as f:
                json.dump(payload, f)
            os.replace(tmp, self.progress_path)
        except OSError:
            pass  # a full/readonly disk must not take down training

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "StallWatchdog":
        if self._thread is not None:
            return self
        self._last_beat = self._clock()  # arm from start, not construction
        self._thread = threading.Thread(
            target=self._run, name="telemetry-watchdog", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=max(1.0, self.poll_s * 2))
            self._thread = None

    def _run(self) -> None:
        while not self._stop.wait(self.poll_s):
            with self._lock:
                elapsed = self._clock() - self._last_beat
                should_fire = elapsed > self.timeout_s and not self._in_stall
                if should_fire:
                    self._in_stall = True
            if should_fire:
                self.fired_count += 1
                snap = self.snapshot(reason="stall", elapsed_s=elapsed)
                stacks = self._thread_stacks()
                if stacks is not None:
                    snap["threads"] = stacks
                with self._lock:  # raced by incident() on the main thread
                    self.last_snapshot = snap
                self._record_incident(snap)
                if self.on_stall is not None:
                    try:
                        self.on_stall(snap)
                    except Exception:
                        pass

    # -- diagnostics -------------------------------------------------------

    def incident(self, kind: str, **fields: Any) -> Dict[str, Any]:
        """Record a non-stall incident (nonfinite-skip escalation,
        preemption, checkpoint-save failure, abnormal exit, ...) in the
        same JSONL stream as stall snapshots: one file answers "what went
        wrong and where was the process when it did". ``fields`` are
        merged over the snapshot; the snapshot's standard keys win only
        for ``kind``."""
        snap = self.snapshot(reason=kind)
        for key, value in fields.items():
            if key != "kind":
                snap[key] = value
        with self._lock:  # raced by the watchdog thread's stall path
            self.last_snapshot = snap
        self._record_incident(snap)
        return snap

    def snapshot(self, reason: str = "manual", elapsed_s: Optional[float] = None) -> Dict[str, Any]:
        """Diagnostic snapshot: what was the process doing, and for how
        long has it not moved."""
        with self._lock:
            elapsed = elapsed_s if elapsed_s is not None else self._clock() - self._last_beat
            snap: Dict[str, Any] = {
                "kind": reason,
                "utc": datetime.now(timezone.utc).isoformat(),
                "elapsed_since_progress_s": round(elapsed, 3),
                "timeout_s": self.timeout_s,
                "last_step": self._last_step,
                "last_phase": self._last_phase,
                "beats": self._beats,
                "pid": os.getpid(),
            }
            if self.rank is not None:
                snap["process_index"] = self.rank
        try:
            snap["last_span"] = self.tracer.last_span
        except Exception as e:  # pragma: no cover - defensive
            snap["last_span"] = f"error: {e!r}"
        gauges: Dict[str, Any] = {}
        for name, fn in self.providers.items():
            try:
                gauges[name] = fn()
            except Exception as e:
                gauges[name] = f"error: {e!r}"
        if gauges:
            snap["gauges"] = gauges
        return snap

    @staticmethod
    def _thread_stacks() -> Optional[list]:
        """All-thread tracebacks as a list of lines, so a hung prefetch or
        serving thread is diagnosable from the incident file post-mortem.
        Uses faulthandler (C-level frame walk, no per-thread cooperation
        needed) through a spooled temp file — it only writes to fds."""
        try:
            import faulthandler
            import tempfile

            with tempfile.TemporaryFile(mode="w+") as f:
                faulthandler.dump_traceback(file=f, all_threads=True)
                f.seek(0)
                return f.read().rstrip("\n").split("\n")
        except Exception:  # pragma: no cover - diagnostics must not raise
            return None

    def _record_incident(self, snap: Dict[str, Any]) -> None:
        if self.snapshot_path is None:
            return
        try:
            with open(self.snapshot_path, "a") as f:
                f.write(json.dumps(snap) + "\n")
        except OSError:
            pass
