"""Multi-window error-budget burn-rate accounting.

The SRE formulation: an SLO target (say 99.9% availability) leaves an
error *budget* of ``1 - target`` (0.1%).  The burn rate over a window
is ``observed error rate / budget`` — burn 1.0 spends the budget
exactly at the rate it refills, burn 14 exhausts a 30-day budget in
about 2 days.  A single window either pages too slowly (long window)
or flaps on every blip (short window); the standard fix is the
multi-window AND rule: alarm only while BOTH the short (default 5 m)
and long (default 1 h) windows burn above threshold.  The long window
makes the alarm meaningful, the short window lets it CLEAR as soon as
the incident actually stops — which is exactly what the fleet needs to
re-admit a rejoined replica or a demoted canary.

:class:`BurnRateTracker` is the dependency-free core: outcomes are
folded into fixed-width interval buckets (memory is O(window /
resolution), never O(events)), the clock is injectable so tests and
the simulated fleet benchmark drive it deterministically, and an
outcome counts against the budget if it failed OR (when a latency
target is set) succeeded too slowly — the latency SLO and the
availability SLO share one budget, per the user's experience of "my
request did not come back in time".
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable, Deque, Dict, List, Optional, Tuple

__all__ = ["BurnRateTracker"]


class BurnRateTracker:
    """Rolling multi-window burn-rate over request/attempt outcomes."""

    def __init__(
        self,
        availability_target: float = 0.999,
        latency_target_s: float = 0.0,
        short_window_s: float = 300.0,
        long_window_s: float = 3600.0,
        alarm_burn: float = 1.0,
        clock: Callable[[], float] = time.monotonic,
        resolution_s: Optional[float] = None,
    ):
        if not 0.0 < availability_target < 1.0:
            raise ValueError(
                f"availability_target must be in (0, 1), got {availability_target}"
            )
        if latency_target_s < 0:
            raise ValueError(f"latency_target_s must be >= 0, got {latency_target_s}")
        if not 0 < short_window_s < long_window_s:
            raise ValueError(
                "need 0 < short_window_s < long_window_s, got "
                f"{short_window_s} / {long_window_s}"
            )
        if alarm_burn <= 0:
            raise ValueError(f"alarm_burn must be > 0, got {alarm_burn}")
        self.availability_target = availability_target
        self.latency_target_s = latency_target_s
        self.short_window_s = short_window_s
        self.long_window_s = long_window_s
        self.alarm_burn = alarm_burn
        self.budget = 1.0 - availability_target
        self._clock = clock
        # bucket width: fine enough that the short window has ~20 slots
        self._res = (
            float(resolution_s) if resolution_s else max(short_window_s / 20.0, 1e-6)
        )
        self._lock = threading.Lock()
        # (bucket_start_time, ok_count, err_count); append-right, expire-left
        self._buckets: Deque[Tuple[float, int, int]] = deque()
        self._total_ok = 0
        self._total_err = 0

    def _bucket_start(self, now: float) -> float:
        return now - (now % self._res)

    def record(self, ok: bool, latency_s: Optional[float] = None) -> None:
        """Fold one outcome in. ``ok=True`` with a latency above the
        target still burns budget — a too-slow success is an SLO miss."""
        err = (not ok) or (
            self.latency_target_s > 0
            and latency_s is not None
            and latency_s > self.latency_target_s
        )
        now = self._clock()
        start = self._bucket_start(now)
        with self._lock:
            if self._buckets and self._buckets[-1][0] == start:
                t, o, e = self._buckets[-1]
                self._buckets[-1] = (t, o + (0 if err else 1), e + (1 if err else 0))
            else:
                self._buckets.append(
                    (start, 0 if err else 1, 1 if err else 0)
                )
            if err:
                self._total_err += 1
            else:
                self._total_ok += 1
            self._expire_locked(now)

    def _expire_locked(self, now: float) -> None:
        horizon = now - self.long_window_s - self._res
        while self._buckets and self._buckets[0][0] < horizon:
            self._buckets.popleft()

    def _window_rate(self, window_s: float, now: float) -> Tuple[float, int]:
        """(error rate, sample count) over the trailing ``window_s``;
        caller holds no lock (we take it)."""
        cutoff = now - window_s
        ok = err = 0
        with self._lock:
            buckets: List[Tuple[float, int, int]] = list(self._buckets)
        for start, o, e in reversed(buckets):
            # a bucket belongs to the window if any of it overlaps
            if start + self._res <= cutoff:
                break
            ok += o
            err += e
        n = ok + err
        return (err / n if n else 0.0), n

    def burn_rates(self) -> Dict[str, float]:
        """Current ``{"short": burn, "long": burn}``."""
        now = self._clock()
        short_rate, _ = self._window_rate(self.short_window_s, now)
        long_rate, _ = self._window_rate(self.long_window_s, now)
        return {
            "short": short_rate / self.budget,
            "long": long_rate / self.budget,
        }

    def alarm(self) -> bool:
        """Multi-window AND rule: burning above threshold on BOTH
        windows right now."""
        rates = self.burn_rates()
        return (
            rates["short"] > self.alarm_burn and rates["long"] > self.alarm_burn
        )

    def snapshot(self) -> Dict[str, Any]:
        now = self._clock()
        short_rate, short_n = self._window_rate(self.short_window_s, now)
        long_rate, long_n = self._window_rate(self.long_window_s, now)
        with self._lock:
            total_ok, total_err = self._total_ok, self._total_err
        burn_short = short_rate / self.budget
        burn_long = long_rate / self.budget
        return {
            "availability_target": self.availability_target,
            "latency_target_s": self.latency_target_s,
            "budget": self.budget,
            "windows_s": {
                "short": self.short_window_s,
                "long": self.long_window_s,
            },
            "samples": {"short": short_n, "long": long_n},
            "error_rates": {"short": short_rate, "long": long_rate},
            "burn_rates": {"short": burn_short, "long": burn_long},
            "alarm": burn_short > self.alarm_burn and burn_long > self.alarm_burn,
            "total_ok": total_ok,
            "total_err": total_err,
        }
