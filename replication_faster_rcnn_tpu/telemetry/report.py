"""Summarize a telemetry run directory into a phase-time + health report.

Input is whatever a telemetry-enabled run left behind:
``trace.json`` (Chrome-trace spans), ``metrics.jsonl`` (MetricLogger
rows, now including the health scalars), ``watchdog.jsonl`` (stall
incidents), ``progress.json`` (last heartbeat), ``fleet.jsonl``
(fleet-router snapshots from ``frcnn fleet --telemetry``). All
optional — the
report covers what exists. Pure stdlib on purpose: the ``telemetry``
CLI subcommand must work on a laptop holding only the artifacts,
without importing jax.
"""

from __future__ import annotations

import glob
import json
import os
import re
from typing import Any, Dict, List, Optional, Tuple

TRACE_FILE = "trace.json"
METRICS_FILE = "metrics.jsonl"
WATCHDOG_FILE = "watchdog.jsonl"
PROGRESS_FILE = "progress.json"
FLEET_FILE = "fleet.jsonl"

# Multi-process runs write the coordinator's artifacts under the plain
# names above and every other rank's under ``<stem>.rank<N>.<ext>``
# (trainer.py::_rank_file). The report merges all of them.
_RANK_RE = re.compile(r"\.rank(\d+)\.[^.]+$")


def rank_variants(run_dir: str, name: str) -> List[Tuple[int, str]]:
    """(rank, path) for every per-rank variant of ``name`` present in
    ``run_dir``: the plain file is rank 0 (the coordinator), plus any
    ``stem.rankN.ext`` siblings, sorted by rank."""
    out: List[Tuple[int, str]] = []
    base = os.path.join(run_dir, name)
    if os.path.exists(base):
        out.append((0, base))
    stem, ext = os.path.splitext(name)
    for path in glob.glob(os.path.join(run_dir, f"{stem}.rank*{ext}")):
        m = _RANK_RE.search(path)
        if m:
            out.append((int(m.group(1)), path))
    out.sort()
    return out

# Health/throughput keys worth surfacing from the JSONL, in display order.
_HEALTH_KEYS = (
    "loss",
    "rpn_cls_loss",
    "rpn_reg_loss",
    "head_cls_loss",
    "head_reg_loss",
    "grad_norm",
    "param_norm",
    "update_norm",
    "update_ratio",
    "nonfinite_count",
    "skipped",
)

# Incident kinds the fault layer records (train/fault.py + trainer) beyond
# the watchdog's own stall/recovered pair.
_FAULT_KINDS = (
    "nonfinite_escalation",
    "preempted",
    "checkpoint_save_failed",
    "checkpoint_fallback",
    "abnormal_exit",
)


def load_trace_events(path: str) -> List[Dict[str, Any]]:
    with open(path) as f:
        doc = json.load(f)
    if isinstance(doc, list):  # bare-array Chrome-trace variant
        return doc
    return doc.get("traceEvents", [])


def load_jsonl(path: str) -> List[Dict[str, Any]]:
    rows = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rows.append(json.loads(line))
            except json.JSONDecodeError:
                continue  # a torn tail line from a killed run is expected
    return rows


def phase_table(events: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Aggregate complete (ph=X) spans by name: count / total / mean / max
    ms, sorted by total time descending."""
    agg: Dict[str, Dict[str, float]] = {}
    for ev in events:
        if ev.get("ph") != "X":
            continue
        name = ev.get("name", "?")
        dur_ms = float(ev.get("dur", 0.0)) / 1e3
        row = agg.setdefault(name, {"count": 0, "total_ms": 0.0, "max_ms": 0.0})
        row["count"] += 1
        row["total_ms"] += dur_ms
        row["max_ms"] = max(row["max_ms"], dur_ms)
    out = []
    for name, row in agg.items():
        out.append(
            {
                "name": name,
                "count": int(row["count"]),
                "total_ms": round(row["total_ms"], 3),
                "mean_ms": round(row["total_ms"] / row["count"], 3),
                "max_ms": round(row["max_ms"], 3),
            }
        )
    out.sort(key=lambda r: -r["total_ms"])
    return out


def trace_timeline(
    events: List[Dict[str, Any]], trace_id: str
) -> Optional[Dict[str, Any]]:
    """One request's hop timeline from the merged Chrome trace.

    Every hop span the serving stack emits (``fleet/request``,
    ``fleet/attempt``, ``serve/request``, ``serve/queue_wait``,
    ``serve/dispatch``) stamps its ``trace_id``/``span_id``/
    ``parent_span_id`` into the event args; filtering on one trace id
    reconstructs the request's path across router and replicas —
    including hedged and abandoned attempts, which share the trace id
    with distinct span ids.  For each ``fleet/attempt`` whose replica-
    side ``serve/request`` child is present, the non-replica remainder
    is reported as ``network_ms``.  Returns None when the trace id
    matches nothing."""
    spans = [
        ev
        for ev in events
        if ev.get("ph") == "X"
        and isinstance(ev.get("args"), dict)
        and ev["args"].get("trace_id") == trace_id
    ]
    if not spans:
        return None
    t0 = min(float(ev.get("ts", 0.0)) for ev in spans)
    rows: List[Dict[str, Any]] = []
    for ev in sorted(spans, key=lambda e: float(e.get("ts", 0.0))):
        args = ev["args"]
        row: Dict[str, Any] = {
            "name": ev.get("name", "?"),
            "start_ms": round((float(ev.get("ts", 0.0)) - t0) / 1e3, 3),
            "dur_ms": round(float(ev.get("dur", 0.0)) / 1e3, 3),
            "span_id": args.get("span_id"),
            "parent_span_id": args.get("parent_span_id"),
            "pid": ev.get("pid"),
            "tid": ev.get("tid"),
        }
        for key in ("replica", "hedge", "ok", "key", "program"):
            if key in args:
                row[key] = args[key]
        rows.append(row)
    for row in rows:
        if row["name"] != "fleet/attempt":
            continue
        child = next(
            (
                r
                for r in rows
                if r["name"] == "serve/request"
                and r["parent_span_id"] == row["span_id"]
            ),
            None,
        )
        if child is not None:
            row["network_ms"] = round(row["dur_ms"] - child["dur_ms"], 3)
    end = max(r["start_ms"] + r["dur_ms"] for r in rows)
    return {
        "trace_id": trace_id,
        "spans": rows,
        "total_ms": round(end, 3),
        "replicas": sorted(
            {r["replica"] for r in rows if "replica" in r}
        ),
    }


def format_trace_timeline(timeline: Dict[str, Any]) -> str:
    """Human-readable rendering of :func:`trace_timeline`."""
    lines = [
        f"trace {timeline['trace_id']}: {len(timeline['spans'])} span(s), "
        f"{timeline['total_ms']:.2f} ms end-to-end"
        + (
            f", replicas: {', '.join(timeline['replicas'])}"
            if timeline["replicas"]
            else ""
        )
    ]
    header = (
        f"  {'start_ms':>9}{'dur_ms':>10}  {'span':<18}"
        f"{'span_id':<18}{'detail'}"
    )
    lines.append(header)
    for row in timeline["spans"]:
        detail = []
        if "replica" in row:
            detail.append(f"replica={row['replica']}")
        if row.get("hedge"):
            detail.append("hedge")
        if "ok" in row:
            detail.append("ok" if row["ok"] else "FAILED")
        if "network_ms" in row:
            detail.append(f"network={row['network_ms']:.2f}ms")
        if "key" in row:
            detail.append(f"bucket={row['key']}")
        lines.append(
            f"  {row['start_ms']:>9.2f}{row['dur_ms']:>10.2f}  "
            f"{row['name']:<18}{str(row['span_id']):<18}{' '.join(detail)}"
        )
    return "\n".join(lines)


def overlap_summary(events: List[Dict[str, Any]]) -> Optional[Dict[str, Any]]:
    """Feed-vs-dispatch overlap from the span stream.

    Data-pipeline spans (``data/fetch``, ``data/device_put``) emitted on
    the thread(s) that also emit ``step/dispatch`` are host-BLOCKED feed
    time — the trainer paid them on the critical path. The same spans on
    any other thread are the device stager's producer doing that work
    overlapped (``data.prefetch_device``). Returns None when the trace
    has no dispatch spans (nothing to be blocked against)."""
    # key threads by (pid, tid): merged per-rank traces can reuse tid
    # values across processes, and a rank's producer thread must not be
    # mistaken for another rank's dispatch thread
    dispatch_tids = set()
    dispatch_ms = 0.0
    for ev in events:
        if ev.get("ph") == "X" and ev.get("name") == "step/dispatch":
            dispatch_tids.add((ev.get("pid"), ev.get("tid")))
            dispatch_ms += float(ev.get("dur", 0.0)) / 1e3
    if not dispatch_tids:
        return None
    blocked_ms = 0.0
    overlapped_ms = 0.0
    for ev in events:
        if ev.get("ph") != "X" or not str(ev.get("name", "")).startswith("data/"):
            continue
        dur_ms = float(ev.get("dur", 0.0)) / 1e3
        if (ev.get("pid"), ev.get("tid")) in dispatch_tids:
            blocked_ms += dur_ms
        else:
            overlapped_ms += dur_ms
    return {
        "dispatch_total_ms": round(dispatch_ms, 3),
        "host_blocked_ms": round(blocked_ms, 3),
        "overlapped_ms": round(overlapped_ms, 3),
        "host_blocked_frac_of_dispatch": (
            round(blocked_ms / dispatch_ms, 4) if dispatch_ms > 0 else None
        ),
    }


def health_summary(rows: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Last value + max of each health key across step rows."""
    step_rows = [r for r in rows if "step" in r]
    out: Dict[str, Any] = {"rows": len(step_rows)}
    if not step_rows:
        return out
    out["first_step"] = step_rows[0].get("step")
    out["last_step"] = step_rows[-1].get("step")
    keys: Dict[str, Dict[str, float]] = {}
    for key in _HEALTH_KEYS:
        vals = [
            float(r[key])
            for r in step_rows
            if isinstance(r.get(key), (int, float))
        ]
        if vals:
            keys[key] = {"last": vals[-1], "max": max(vals), "min": min(vals)}
    out["metrics"] = keys
    return out


def summarize_run(run_dir: str) -> Dict[str, Any]:
    summary: Dict[str, Any] = {"run_dir": run_dir, "artifacts": []}
    ranks_seen: set = set()

    traces = rank_variants(run_dir, TRACE_FILE)
    if traces:
        events: List[Dict[str, Any]] = []
        for rank, path in traces:
            summary["artifacts"].append(os.path.basename(path))
            ranks_seen.add(rank)
            events.extend(load_trace_events(path))
        summary["phases"] = phase_table(events)
        overlap = overlap_summary(events)
        if overlap is not None:
            summary["overlap"] = overlap

    metric_files = rank_variants(run_dir, METRICS_FILE)
    if metric_files:
        rows: List[Dict[str, Any]] = []
        per_rank: Dict[int, Dict[str, Any]] = {}
        for rank, path in metric_files:
            summary["artifacts"].append(os.path.basename(path))
            ranks_seen.add(rank)
            rank_rows = load_jsonl(path)
            rows.extend(rank_rows)
            step_rows = [r for r in rank_rows if "step" in r]
            per_rank[rank] = {
                "rows": len(step_rows),
                "last_step": step_rows[-1].get("step") if step_rows else None,
            }
        summary["health"] = health_summary(rows)
        if len(metric_files) > 1:
            summary["health"]["per_rank"] = per_rank

    wd_files = rank_variants(run_dir, WATCHDOG_FILE)
    if wd_files:
        incidents = []
        for rank, path in wd_files:
            summary["artifacts"].append(os.path.basename(path))
            ranks_seen.add(rank)
            incidents.extend(load_jsonl(path))
        summary["incidents"] = {
            "stalls": sum(1 for i in incidents if i.get("kind") == "stall"),
            "recoveries": sum(1 for i in incidents if i.get("kind") == "recovered"),
            "faults": {
                kind: n
                for kind in _FAULT_KINDS
                if (n := sum(1 for i in incidents if i.get("kind") == kind))
            },
            "events": incidents,
        }

    fleet_path = os.path.join(run_dir, FLEET_FILE)
    if os.path.exists(fleet_path):
        snaps = load_jsonl(fleet_path)
        if snaps:
            summary["artifacts"].append(FLEET_FILE)
            # snapshots append over restarts; the last one is current
            summary["fleet"] = snaps[-1]

    progress_files = rank_variants(run_dir, PROGRESS_FILE)
    if progress_files:
        by_rank: Dict[int, Dict[str, Any]] = {}
        for rank, path in progress_files:
            summary["artifacts"].append(os.path.basename(path))
            ranks_seen.add(rank)
            with open(path) as f:
                by_rank[rank] = json.load(f)
        # the coordinator's heartbeat keeps the historical key; other
        # ranks' heartbeats ride alongside
        summary["progress"] = by_rank.get(0) or by_rank[min(by_rank)]
        if len(by_rank) > 1:
            summary["progress_by_rank"] = by_rank
    if len(ranks_seen) > 1:
        summary["ranks"] = sorted(ranks_seen)
    try:  # a --profile device capture next to the host spans?
        from replication_faster_rcnn_tpu.utils.xplane import has_device_trace

        summary["device_trace"] = has_device_trace(run_dir)
    except Exception:  # pragma: no cover - report must survive without it
        summary["device_trace"] = False
    return summary


def format_report(summary: Dict[str, Any]) -> str:
    """Human-readable rendering of :func:`summarize_run`."""
    lines = [f"telemetry report: {summary['run_dir']}"]
    ranks = summary.get("ranks")
    if ranks:
        lines.append(
            f"  multi-process run: {len(ranks)} ranks "
            f"({', '.join(str(r) for r in ranks)}) — artifacts merged"
        )
    if not summary["artifacts"]:
        lines.append("  no telemetry artifacts found "
                     f"({TRACE_FILE}/{METRICS_FILE}/{WATCHDOG_FILE})")
        return "\n".join(lines)

    phases = summary.get("phases")
    if phases is not None:
        lines.append("")
        lines.append("phase time (from trace.json):")
        header = f"  {'span':<26}{'count':>7}{'total_ms':>12}{'mean_ms':>10}{'max_ms':>10}"
        lines.append(header)
        for row in phases:
            lines.append(
                f"  {row['name']:<26}{row['count']:>7}"
                f"{row['total_ms']:>12.1f}{row['mean_ms']:>10.2f}{row['max_ms']:>10.1f}"
            )

    overlap = summary.get("overlap")
    if overlap is not None:
        frac = overlap.get("host_blocked_frac_of_dispatch")
        lines.append("")
        lines.append(
            "feed overlap: "
            f"{overlap['host_blocked_ms']:.1f} ms data time on the dispatch "
            f"thread ({frac:.1%} of dispatch wall), "
            f"{overlap['overlapped_ms']:.1f} ms overlapped on the stager"
            if frac is not None
            else "feed overlap: no dispatch time recorded"
        )

    health = summary.get("health")
    if health is not None:
        lines.append("")
        lines.append(
            f"train health (from metrics.jsonl, {health['rows']} rows"
            + (
                f", steps {health.get('first_step')}..{health.get('last_step')})"
                if health["rows"]
                else ")"
            )
        )
        for key, vals in health.get("metrics", {}).items():
            lines.append(
                f"  {key:<18} last {vals['last']:<12.5g} "
                f"min {vals['min']:<12.5g} max {vals['max']:<12.5g}"
            )
        for rank, info in sorted(health.get("per_rank", {}).items()):
            lines.append(
                f"  rank {rank}: {info['rows']} step rows, "
                f"last step {info['last_step']}"
            )

    incidents = summary.get("incidents")
    if incidents is not None:
        lines.append("")
        lines.append(
            f"watchdog: {incidents['stalls']} stall(s), "
            f"{incidents['recoveries']} recovery(ies)"
        )
        for kind, n in incidents.get("faults", {}).items():
            lines.append(f"  fault incidents: {n}x {kind}")
        for ev in incidents["events"]:
            if ev.get("kind") != "stall":
                continue
            span = ev.get("last_span") or {}
            lines.append(
                f"  stall at step={ev.get('last_step')} phase={ev.get('last_phase')} "
                f"after {ev.get('elapsed_since_progress_s')}s "
                f"(last span: {span.get('name') if isinstance(span, dict) else span})"
            )

    fleet = summary.get("fleet")
    if fleet is not None:
        router = fleet.get("router", {})
        lines.append("")
        n = router.get("requests", 0)
        lines.append(
            f"fleet router (from {FLEET_FILE}): {n} request(s), "
            f"{router.get('cache_hits', 0)} cache hit(s), "
            f"{router.get('failovers', 0)} failover(s), "
            f"{router.get('hedges', 0)} hedge(s) "
            f"({router.get('hedge_wins', 0)} won), "
            f"{router.get('unavailable', 0)} unavailable"
        )
        for rid, rep in sorted(fleet.get("registry", {}).items()):
            per = fleet.get("replicas", {}).get(rid, {})
            breaker = per.get("breaker", {})
            lines.append(
                f"  {rid:<14} {rep.get('state', '?'):<9} "
                f"role={rep.get('role', '?'):<8} "
                f"ok={per.get('ok', 0):<6} fail={per.get('fail', 0):<5} "
                f"breaker={breaker.get('state', '?')}"
                + (
                    f" ({breaker.get('opens')} open(s))"
                    if breaker.get("opens")
                    else ""
                )
            )

    progress = summary.get("progress")
    if progress is not None:
        lines.append("")
        by_rank = summary.get("progress_by_rank")
        if by_rank:
            for rank, p in sorted(by_rank.items()):
                lines.append(
                    f"last heartbeat (rank {rank}): step={p.get('step')} "
                    f"phase={p.get('phase')} at {p.get('utc')}"
                )
        else:
            lines.append(
                f"last heartbeat: step={progress.get('step')} "
                f"phase={progress.get('phase')} at {progress.get('utc')}"
            )
    if summary.get("device_trace"):
        lines.append("")
        lines.append(
            "device profiler capture present — per-op table: "
            f"cli trace-summary {summary['run_dir']}"
        )
    return "\n".join(lines)
