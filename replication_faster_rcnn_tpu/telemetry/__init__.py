"""Zero-dependency observability layer.

Four pillars, each usable on its own:

- :mod:`.spans` — host-side span tracer emitting Chrome-trace JSON
  (``chrome://tracing`` / Perfetto loadable) so feed-vs-compute time is
  directly visible per pipeline phase.
- :mod:`.health` — on-device train-health metrics (grad/param/update
  norms, update ratio, non-finite counts) folded into the jitted step so
  they ride the existing metrics sync instead of adding one.
- :mod:`.mfu` — model FLOPs utilisation from the step FLOPs the bench
  already derives, with a measured-matmul CPU peak so MFU is non-null
  even off-TPU.
- :mod:`.watchdog` — heartbeat daemon that detects a wedged device or
  tunnel and dumps a diagnostic snapshot (last span, queue depth,
  elapsed-since-progress) instead of leaving a hung process to guess at.
- :mod:`.tracecontext` — W3C-traceparent-style request tracing: trace
  and span ids that propagate across the serving fleet's process hops
  (router → replica HTTP → batcher → engine) so one request's timeline
  is greppable by one id in the merged Chrome trace.
- :mod:`.metrics` — a unified :class:`~.metrics.MetricsRegistry`
  (counters, gauges, fixed-bucket histograms with derived
  p50/p95/p99) that the serving tiers register into; rendered both as
  JSON (``/stats``, ``fleet.jsonl``) and Prometheus text (``/metrics``).
- :mod:`.slo_burn` — multi-window error-budget burn-rate accounting
  feeding the replica ``degraded`` flag and the router's canary
  auto-demote hook.

:mod:`.report` turns a run directory (trace.json + metrics.jsonl +
watchdog.jsonl) into a phase-time and health report; surfaced as the
``telemetry`` CLI subcommand.
"""

from replication_faster_rcnn_tpu.telemetry.metrics import (  # noqa: F401
    MetricsRegistry,
)
from replication_faster_rcnn_tpu.telemetry.slo_burn import (  # noqa: F401
    BurnRateTracker,
)
from replication_faster_rcnn_tpu.telemetry.spans import (  # noqa: F401
    NULL_TRACER,
    SpanTracer,
    current_tracer,
    set_tracer,
)
from replication_faster_rcnn_tpu.telemetry.tracecontext import (  # noqa: F401
    TraceContext,
    bind,
    current_trace,
    new_trace_context,
    parse_traceparent,
)
from replication_faster_rcnn_tpu.telemetry.watchdog import StallWatchdog  # noqa: F401
