"""Host-side span tracer emitting Chrome-trace JSON.

The output is the ``{"traceEvents": [...]}`` object format that
``chrome://tracing`` and Perfetto load directly: complete events
(``ph: "X"``) with microsecond ``ts``/``dur``, instant events
(``ph: "i"``) for marks, and counter events (``ph: "C"``) for gauges
like prefetch-queue depth.

Instrumented code does not take a tracer parameter — it calls
``current_tracer().span("data/fetch", cat="data")`` and gets either the
process-wide active tracer or ``NULL_TRACER``, whose span is a reusable
no-op context manager. That keeps the loader/evaluator/device-cache call
sites unconditional and free when telemetry is off.

A note on what dispatch/sync spans mean under JAX's async dispatch: the
``step/dispatch`` span measures only enqueue time (usually tens of µs
once compiled; the first occurrence absorbs compilation), while the
``step/sync`` span at a log boundary measures the wait for the device to
drain — i.e. device compute time for the interval. Feed-bound runs show
fat ``data/*`` spans and a thin sync; compute-bound runs the reverse.
"""

from __future__ import annotations

import json
import os
import threading
import time
from contextlib import contextmanager
from typing import Any, Dict, Iterator, List, Optional


class _NullSpan:
    """Reusable no-op context manager."""

    __slots__ = ()

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc: Any) -> bool:
        return False


_NULL_SPAN = _NullSpan()


class NullTracer:
    """Inert tracer: every operation is a no-op."""

    enabled = False

    def span(self, name: str, cat: str = "phase", **args: Any) -> _NullSpan:
        return _NULL_SPAN

    def instant(self, name: str, cat: str = "mark", **args: Any) -> None:
        pass

    def counter(self, name: str, value: float, cat: str = "counter") -> None:
        pass

    def now_us(self) -> float:
        return 0.0

    def complete(
        self,
        name: str,
        ts_us: float,
        dur_us: float,
        cat: str = "phase",
        **args: Any,
    ) -> None:
        pass

    def flush(self, path: Optional[str] = None) -> None:
        pass

    @property
    def last_span(self) -> None:
        return None


NULL_TRACER = NullTracer()


class SpanTracer:
    """Thread-safe in-memory Chrome-trace event collector.

    Events are buffered in RAM (bounded by ``max_events``; overflow
    increments a drop counter rather than growing without bound — a
    wedged producer must not OOM the host on top of everything else) and
    written with :meth:`flush`, atomically via a temp file + rename so a
    crash mid-write never leaves a truncated JSON behind.
    """

    enabled = True

    def __init__(
        self,
        path: Optional[str] = None,
        max_events: int = 200_000,
        rank: Optional[int] = None,
    ):
        self.path = path
        self.max_events = max_events
        # process_index of a multi-process run: stamped on every event (so
        # merged per-rank traces stay attributable) and into otherData
        self.rank = rank
        self._events: List[Dict[str, Any]] = []
        self._lock = threading.Lock()
        self._t0 = time.perf_counter()
        self._wall0 = time.time()
        self._pid = os.getpid()
        self._dropped = 0
        # Written lock-free on span entry; the watchdog reads it to report
        # what the process was last doing when a stall fires.
        self._last_span: Optional[Dict[str, Any]] = None

    def _now_us(self) -> float:
        return (time.perf_counter() - self._t0) * 1e6

    def _emit(self, event: Dict[str, Any]) -> None:
        if self.rank is not None:
            event.setdefault("args", {})["process_index"] = self.rank
        with self._lock:
            if len(self._events) < self.max_events:
                self._events.append(event)
            else:
                self._dropped += 1

    @contextmanager
    def span(self, name: str, cat: str = "phase", **args: Any) -> Iterator[None]:
        ts = self._now_us()
        self._last_span = {"name": name, "cat": cat, "started_wall": time.time()}
        try:
            yield
        finally:
            event = {
                "name": name,
                "cat": cat,
                "ph": "X",
                "ts": ts,
                "dur": self._now_us() - ts,
                "pid": self._pid,
                "tid": threading.get_ident(),
            }
            if args:
                event["args"] = args
            self._emit(event)

    def now_us(self) -> float:
        """This tracer's clock, for callers that measure a span whose
        start and end happen on different threads (queue-wait hops) and
        emit it afterwards with :meth:`complete`."""
        return self._now_us()

    def complete(
        self,
        name: str,
        ts_us: float,
        dur_us: float,
        cat: str = "phase",
        **args: Any,
    ) -> None:
        """Emit a complete event with an explicit start/duration — the
        non-contextmanager twin of :meth:`span` for cross-thread hops."""
        event = {
            "name": name,
            "cat": cat,
            "ph": "X",
            "ts": ts_us,
            "dur": dur_us,
            "pid": self._pid,
            "tid": threading.get_ident(),
        }
        if args:
            event["args"] = args
        self._emit(event)

    def instant(self, name: str, cat: str = "mark", **args: Any) -> None:
        event = {
            "name": name,
            "cat": cat,
            "ph": "i",
            "s": "t",
            "ts": self._now_us(),
            "pid": self._pid,
            "tid": threading.get_ident(),
        }
        if args:
            event["args"] = args
        self._emit(event)

    def counter(self, name: str, value: float, cat: str = "counter") -> None:
        self._emit(
            {
                "name": name,
                "cat": cat,
                "ph": "C",
                "ts": self._now_us(),
                "pid": self._pid,
                "args": {"value": value},
            }
        )

    @property
    def last_span(self) -> Optional[Dict[str, Any]]:
        snap = self._last_span
        if snap is None:
            return None
        out = dict(snap)
        out["age_s"] = round(time.time() - out.pop("started_wall"), 3)
        return out

    def to_dict(self) -> Dict[str, Any]:
        with self._lock:
            events = list(self._events)
            dropped = self._dropped
        other: Dict[str, Any] = {
            "start_unix_time": self._wall0,
            "dropped_events": dropped,
        }
        if self.rank is not None:
            other["process_index"] = self.rank
        return {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": other,
        }

    def flush(self, path: Optional[str] = None) -> None:
        path = path or self.path
        if path is None:
            return
        tmp = f"{path}.tmp.{self._pid}"
        with open(tmp, "w") as f:
            json.dump(self.to_dict(), f)
        os.replace(tmp, path)


_active: Any = NULL_TRACER
_active_lock = threading.Lock()


def set_tracer(tracer: Optional[Any]) -> Any:
    """Install ``tracer`` as the process-wide tracer; returns the previous
    one (pass it back, or ``None``, to restore)."""
    global _active
    with _active_lock:
        prev = _active
        _active = NULL_TRACER if tracer is None else tracer
    return prev if prev is not NULL_TRACER else None


def current_tracer() -> Any:
    return _active
