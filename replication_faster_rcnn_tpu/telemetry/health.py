"""On-device train-health metrics, computed inside the jitted step.

Everything here is a handful of reductions over trees the step already
holds (grads, params, optimizer updates), so the scalars ride the
existing metrics device→host transfer at the log boundary — no extra
sync, no extra dispatch.

The signals and why they matter for a multi-loss detector:

- ``grad_norm`` / ``param_norm`` — global L2 norms. A grad norm orders
  of magnitude above its running level is the classic pre-divergence
  signature; Faster R-CNN's four summed losses make it easy for one
  head to blow up while the total loss still looks plausible.
- ``update_norm`` / ``update_ratio`` — the optimizer's actual step size
  and its size relative to the params (``|Δθ| / |θ|``). Healthy training
  sits around 1e-3; ~1 means the optimizer is rewriting the network
  each step, ~1e-7 means it has stalled.
- ``nonfinite_count`` — total NaN/Inf entries across the grad tree.
  Catches the poisoned-gradient case *before* params go NaN, which
  ``finite_or_raise`` on the loss alone only catches one step later.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
import optax

HEALTH_KEYS = (
    "grad_norm",
    "param_norm",
    "update_norm",
    "update_ratio",
    "nonfinite_count",
)


def nonfinite_count(tree: Any) -> jnp.ndarray:
    """Total number of non-finite entries across all leaves of ``tree``."""
    leaves = jax.tree_util.tree_leaves(tree)
    counts = [
        jnp.sum(~jnp.isfinite(leaf)).astype(jnp.int32)
        for leaf in leaves
        if jnp.issubdtype(leaf.dtype, jnp.inexact)
    ]
    if not counts:
        return jnp.int32(0)
    return sum(counts)


def health_metrics(grads: Any, params: Any, updates: Any) -> Dict[str, jnp.ndarray]:
    """Train-health scalars from the trees a step already holds.

    Call after ``tx.update`` with the *global* grads (post-psum under
    shard_map; under jit auto-partitioning the grads are already global)
    so both parallel backends report identical values.
    """
    grad_norm = optax.global_norm(grads)
    param_norm = optax.global_norm(params)
    update_norm = optax.global_norm(updates)
    return {
        "grad_norm": grad_norm,
        "param_norm": param_norm,
        "update_norm": update_norm,
        "update_ratio": update_norm / (param_norm + 1e-12),
        "nonfinite_count": nonfinite_count(grads),
    }
