"""Model FLOPs utilisation (MFU).

MFU = achieved FLOP/s / peak FLOP/s, the canonical "is the chip or the
feed the bottleneck" number. Achieved FLOP/s comes from the per-step
analytical FLOPs the bench already derives (XLA HloCostAnalysis of the
lowered step) times steps/sec; peak comes from one of two bases:

- ``tpu_datasheet`` — published per-chip bf16 peaks times device count,
  keyed off the runtime's own ``device_kind`` string.
- ``cpu_measured_matmul`` — off-TPU there is no meaningful datasheet
  number, so the peak is *measured*: best throughput of a jitted f32
  matmul, cached per process. This fills the ``"mfu": null`` hole in
  CPU-fallback BENCH output; the ``mfu_basis`` field keeps the two
  regimes from being confused (a CPU-basis MFU says how well the
  fallback used the host, not anything about TPU efficiency).
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

# Published per-chip bf16 peak FLOP/s. Matching is substring-based over the
# runtime device_kind string ("TPU v5 lite", "TPU v4", ...), most specific
# first — "v5p" must not fall through to the bare "v5" bucket and vice versa.
TPU_PEAK_BF16_FLOPS = (
    (("v5 lite", "v5e", "v5lite"), 197e12),
    (("v5p", "v5"), 459e12),
    (("v6 lite", "v6e"), 918e12),
    (("v4",), 275e12),
)

_cpu_peak_cache: Optional[float] = None


def tpu_peak_flops_per_sec(device_kind: str, n_dev: int) -> Optional[float]:
    """Aggregate datasheet bf16 peak for ``n_dev`` chips of ``device_kind``,
    or None for an unrecognized generation (a silently-wrong peak would
    distort MFU more than a missing one)."""
    kind = device_kind.lower()
    if not any(g in kind for g in ("v4", "v5", "v6")):
        kind = os.environ.get("PALLAS_AXON_TPU_GEN", "").lower()
    for names, peak in TPU_PEAK_BF16_FLOPS:
        if any(n in kind for n in names):
            return peak * n_dev
    return None


def measured_cpu_peak_flops_per_sec(n: int = 512, iters: int = 4) -> Optional[float]:
    """Best observed FLOP/s of a jitted f32 ``n×n`` matmul, cached per
    process (~0.5 s once). FRCNN_CPU_PEAK_FLOPS overrides the measurement
    entirely — useful for deterministic tests and for hosts where a quick
    matmul under-represents sustained throughput."""
    global _cpu_peak_cache
    override = os.environ.get("FRCNN_CPU_PEAK_FLOPS")
    if override:
        try:
            return float(override)
        except ValueError:
            pass
    if _cpu_peak_cache is not None:
        return _cpu_peak_cache
    try:
        import time

        import jax
        import jax.numpy as jnp

        @jax.jit
        def mm(a, b):
            return a @ b

        a = jnp.ones((n, n), jnp.float32)
        b = jnp.ones((n, n), jnp.float32)
        mm(a, b).block_until_ready()  # compile outside the timed reps
        flops = 2.0 * n * n * n
        best = 0.0
        for _ in range(iters):
            t0 = time.perf_counter()
            mm(a, b).block_until_ready()
            dt = time.perf_counter() - t0
            if dt > 0:
                best = max(best, flops / dt)
        _cpu_peak_cache = best or None
    except Exception:
        _cpu_peak_cache = None
    if _cpu_peak_cache is None:
        # jitted path unavailable (wedged runtime, no jax) — a numpy matmul
        # is a coarser but still *measured* basis, and a measured peak beats
        # shipping "mfu": null (the bench now hard-fails on that for CPU
        # records, so this fallback is what keeps a degraded host honest)
        try:
            import time

            import numpy as np

            a = np.ones((n, n), np.float32)
            b = np.ones((n, n), np.float32)
            a @ b  # first call may pay thread-pool spin-up
            flops = 2.0 * n * n * n
            best = 0.0
            for _ in range(iters):
                t0 = time.perf_counter()
                a @ b
                dt = time.perf_counter() - t0
                if dt > 0:
                    best = max(best, flops / dt)
            _cpu_peak_cache = best or None
        except Exception:
            _cpu_peak_cache = None
    return _cpu_peak_cache


def peak_flops_per_sec(n_dev: Optional[int] = None) -> Tuple[Optional[float], Optional[str]]:
    """(aggregate peak FLOP/s, basis label) for the current backend.

    Basis is ``"tpu_datasheet"`` on TPU, ``"cpu_measured_matmul"`` on CPU,
    and ``(None, None)`` anywhere else (GPU has no table here yet).
    """
    import jax

    dev = jax.devices()[0]
    if n_dev is None:
        n_dev = jax.device_count()
    if dev.platform == "tpu":
        peak = tpu_peak_flops_per_sec(getattr(dev, "device_kind", ""), n_dev)
        return (peak, "tpu_datasheet" if peak else None)
    if dev.platform == "cpu":
        peak = measured_cpu_peak_flops_per_sec()
        return (peak, "cpu_measured_matmul" if peak else None)
    return (None, None)


def compute_mfu(
    flops_per_step: float,
    steps_per_sec: float,
    peak_flops_per_second: Optional[float],
) -> Optional[float]:
    """Achieved / peak. Pure arithmetic, no backend queries — testable
    against a hand-computed value."""
    if not flops_per_step or not steps_per_sec or not peak_flops_per_second:
        return None
    return (flops_per_step * steps_per_sec) / peak_flops_per_second
