"""W3C-traceparent-style request tracing primitives.

A request entering the fleet front door gets a :class:`TraceContext`:
a 128-bit ``trace_id`` naming the request end-to-end and a 64-bit
``span_id`` naming one hop of it.  The context rides across process
boundaries as a ``traceparent`` HTTP header in the W3C Trace Context
wire format::

    00-<32 hex trace-id>-<16 hex span-id>-<2 hex flags>

and across thread boundaries inside a process as a thread-local set
with :func:`bind`.  Instrumented code never takes a trace parameter —
it calls :func:`current_trace` and gets the bound context or ``None``,
exactly the shape of ``spans.current_tracer()``.  That keeps call
signatures stable: the router binds a per-attempt child context on the
dispatching thread, the HTTP client picks it up to stamp the header,
the in-process engine client picks the same thread-local up with no
header involved at all.

Spans form a tree: hedged attempts are *siblings* (same ``trace_id``,
distinct ``span_id``, same parent), a replica-side hop is a *child* of
the attempt that carried it.  The tree is recorded as ``trace_id`` /
``span_id`` / ``parent_span_id`` args on ordinary Chrome-trace events
(:mod:`.spans`), so the merged per-process traces already rendered by
``frcnn telemetry`` become a single cross-process timeline — grep one
``trace_id`` and you hold the whole request.
"""

from __future__ import annotations

import os
import re
import threading
from contextlib import contextmanager
from dataclasses import dataclass, replace
from typing import Iterator, Optional

__all__ = [
    "TRACEPARENT_HEADER",
    "TraceContext",
    "bind",
    "current_trace",
    "new_span_id",
    "new_trace_context",
    "parse_traceparent",
]

# HTTP header carrying the context (W3C Trace Context name).
TRACEPARENT_HEADER = "traceparent"

_TRACEPARENT_RE = re.compile(
    r"^00-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})$"
)


def new_span_id() -> str:
    """A fresh 64-bit span id as 16 lowercase hex chars."""
    return os.urandom(8).hex()


def _new_trace_id() -> str:
    return os.urandom(16).hex()


@dataclass(frozen=True)
class TraceContext:
    """One node of a request's span tree.

    ``parent_span_id`` never crosses the wire (the W3C header has no
    slot for it — the receiver's parent IS the sender's span); it is
    kept in-process so emitted events can record the tree edge.
    """

    trace_id: str
    span_id: str
    flags: str = "01"
    parent_span_id: Optional[str] = None

    def to_traceparent(self) -> str:
        return f"00-{self.trace_id}-{self.span_id}-{self.flags}"

    def child(self) -> "TraceContext":
        """A child span: same trace, fresh span id, this span as parent."""
        return replace(
            self, span_id=new_span_id(), parent_span_id=self.span_id
        )

    def sibling(self) -> "TraceContext":
        """A sibling span (hedged attempt): same trace AND same parent,
        fresh span id."""
        return replace(self, span_id=new_span_id())

    def span_args(self) -> dict:
        """The standard Chrome-trace ``args`` fields for this context."""
        out = {"trace_id": self.trace_id, "span_id": self.span_id}
        if self.parent_span_id is not None:
            out["parent_span_id"] = self.parent_span_id
        return out


def new_trace_context() -> TraceContext:
    """A root context for a request entering the system."""
    return TraceContext(trace_id=_new_trace_id(), span_id=new_span_id())


def parse_traceparent(header: Optional[str]) -> Optional[TraceContext]:
    """Parse a ``traceparent`` header; ``None`` on absent or malformed
    input (a bad header must never fail the request it decorates)."""
    if not header:
        return None
    m = _TRACEPARENT_RE.match(header.strip().lower())
    if m is None:
        return None
    trace_id, span_id, flags = m.groups()
    # all-zero ids are invalid per the W3C spec
    if set(trace_id) == {"0"} or set(span_id) == {"0"}:
        return None
    return TraceContext(trace_id=trace_id, span_id=span_id, flags=flags)


_local = threading.local()


def current_trace() -> Optional[TraceContext]:
    """The context bound to this thread, or ``None``."""
    return getattr(_local, "ctx", None)


@contextmanager
def bind(ctx: Optional[TraceContext]) -> Iterator[Optional[TraceContext]]:
    """Bind ``ctx`` as this thread's current trace for the block."""
    prev = getattr(_local, "ctx", None)
    _local.ctx = ctx
    try:
        yield ctx
    finally:
        _local.ctx = prev
