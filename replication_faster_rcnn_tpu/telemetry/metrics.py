"""Dependency-free unified metrics registry.

One :class:`MetricsRegistry` per process tier (engine, fleet router)
replaces the hand-rolled ``_stats_lock``-guarded dicts that the
batcher, engine, router, replica registry, and breakers each grew on
their own.  Three instrument types:

- :class:`Counter` — monotonically increasing event count;
- :class:`Gauge` — point-in-time level (queue depth, breaker state);
- :class:`Histogram` — fixed-upper-bound buckets with running
  sum/count, from which p50/p95/p99 are derived by linear
  interpolation inside the landing bucket.  Memory is O(buckets)
  forever — unlike the raw-latency lists it replaces, sustained load
  cannot grow it.

Every instrument owns its own lock and never calls out while holding
it; the registry lock only guards the instrument table.  No lock is
ever taken while another is held, so the whole module is clean under
``frcnn check``'s threadlint.

Two render paths, one source of truth: :meth:`MetricsRegistry.snapshot`
feeds the JSON ``/stats`` bodies and ``fleet.jsonl``, and
:meth:`MetricsRegistry.render_prometheus` feeds ``GET /metrics`` in the
Prometheus text exposition format — the numbers cannot disagree
because both walk the same instruments.

Gauges that mirror external state (registry leases, breaker states)
are refreshed by *collectors*: callables registered with
:meth:`register_collector` and invoked at snapshot/render time, so
scrapes always see current state without the owning object pushing on
every transition.
"""

from __future__ import annotations

import bisect
import threading
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "DEFAULT_LATENCY_BUCKETS_S",
    "PROMETHEUS_CONTENT_TYPE",
    "STATS_SCHEMA",
    "stats_payload",
]

# both HTTP tiers serve GET /metrics with this content type
PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

# the unified /stats envelope version (serving/server.py and
# serving/fleet/server.py both emit it; README documents the shape)
STATS_SCHEMA = "frcnn-stats/v1"

# Latency histogram upper bounds in seconds: 1 ms .. 60 s, roughly
# log-spaced (the +Inf bucket is implicit). Chosen so serving-tier
# latencies (single-digit ms to tens of s under chaos) land mid-range.
DEFAULT_LATENCY_BUCKETS_S: Tuple[float, ...] = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
    0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)

_INF = float("inf")


def _label_key(labels: Dict[str, str]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted(labels.items()))


def _format_labels(labels: Dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{_escape_label(v)}"' for k, v in sorted(labels.items())
    )
    return "{" + inner + "}"


def _escape_label(value: Any) -> str:
    return (
        str(value)
        .replace("\\", r"\\")
        .replace('"', r"\"")
        .replace("\n", r"\n")
    )


def _format_value(v: float) -> str:
    if v == _INF:
        return "+Inf"
    if v == -_INF:
        return "-Inf"
    f = float(v)
    return str(int(f)) if f.is_integer() else repr(f)


class Counter:
    """Monotonic event counter."""

    kind = "counter"

    def __init__(self, name: str, help: str = "", labels: Optional[Dict[str, str]] = None):
        self.name = name
        self.help = help
        self.labels = dict(labels or {})
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError(f"counter {self.name} cannot decrease (inc {n})")
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Gauge:
    """Settable point-in-time level."""

    kind = "gauge"

    def __init__(self, name: str, help: str = "", labels: Optional[Dict[str, str]] = None):
        self.name = name
        self.help = help
        self.labels = dict(labels or {})
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    def dec(self, n: float = 1.0) -> None:
        with self._lock:
            self._value -= n

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Histogram:
    """Fixed-bucket histogram with derived percentiles.

    ``buckets`` are inclusive upper bounds in ascending order; the
    ``+Inf`` bucket is implicit.  Percentiles interpolate linearly
    within the landing bucket (the standard Prometheus
    ``histogram_quantile`` estimate), so they are approximations whose
    error is bounded by the bucket width — and whose memory is bounded
    by the bucket COUNT, which is the point.
    """

    kind = "histogram"

    def __init__(
        self,
        name: str,
        help: str = "",
        labels: Optional[Dict[str, str]] = None,
        buckets: Sequence[float] = DEFAULT_LATENCY_BUCKETS_S,
    ):
        if not buckets or list(buckets) != sorted(buckets):
            raise ValueError(f"histogram {name}: buckets must be ascending")
        self.name = name
        self.help = help
        self.labels = dict(labels or {})
        self.bounds: Tuple[float, ...] = tuple(float(b) for b in buckets)
        self._lock = threading.Lock()
        self._counts = [0] * (len(self.bounds) + 1)  # last = +Inf
        self._sum = 0.0
        self._count = 0

    def observe(self, v: float) -> None:
        v = float(v)
        i = bisect.bisect_left(self.bounds, v)
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._count += 1

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def percentile(self, q: float) -> float:
        """The q-th percentile estimate (q in [0, 100]); 0.0 when empty."""
        if not 0.0 <= q <= 100.0:
            raise ValueError(f"percentile q must be in [0, 100], got {q}")
        with self._lock:
            total = self._count
            counts = list(self._counts)
        if total == 0:
            return 0.0
        rank = (q / 100.0) * total
        cum = 0
        for i, c in enumerate(counts):
            prev_cum = cum
            cum += c
            if cum >= rank and c > 0:
                hi = self.bounds[i] if i < len(self.bounds) else self.bounds[-1]
                lo = self.bounds[i - 1] if i > 0 else 0.0
                frac = (rank - prev_cum) / c
                return lo + (hi - lo) * min(max(frac, 0.0), 1.0)
        return self.bounds[-1]

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            counts = list(self._counts)
            total = self._count
            s = self._sum
        cum: Dict[str, int] = {}
        running = 0
        for bound, c in zip(self.bounds, counts[:-1]):
            running += c
            cum[_format_value(bound)] = running
        cum["+Inf"] = total
        return {
            "buckets": cum,
            "sum": s,
            "count": total,
            "p50": self.percentile(50),
            "p95": self.percentile(95),
            "p99": self.percentile(99),
        }


def stats_payload(
    tier: str, registry: "MetricsRegistry", /, **sections: Any
) -> Dict[str, Any]:
    """The unified ``/stats`` envelope both HTTP tiers render::

        {"schema": "frcnn-stats/v1", "tier": <tier>,
         "metrics": <registry snapshot>, ...tier sections}

    ``sections`` carry each tier's structured views (the historical
    keys — ``stats``/``queue_depth`` on a replica, ``router``/
    ``replicas``/``registry``/``slo`` on the fleet front) so existing
    consumers keep working; the ``metrics`` block is the same registry
    that renders ``GET /metrics``, so JSON and Prometheus cannot
    disagree."""
    payload: Dict[str, Any] = {
        "schema": STATS_SCHEMA,
        "tier": tier,
        "metrics": registry.snapshot(),
    }
    payload.update(sections)
    return payload


class MetricsRegistry:
    """Thread-safe instrument table with one get-or-create per type."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._metrics: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], Any] = {}
        self._collectors: List[Callable[[], None]] = []

    def _get_or_create(self, cls, name: str, help: str, labels: Dict[str, str], **kw):
        key = (name, _label_key(labels))
        with self._lock:
            m = self._metrics.get(key)
            if m is None:
                m = cls(name, help=help, labels=labels, **kw)
                self._metrics[key] = m
        if not isinstance(m, cls):
            raise TypeError(
                f"metric {name!r} already registered as {m.kind}, "
                f"requested {cls.kind}"
            )
        return m

    def counter(self, name: str, help: str = "", **labels: str) -> Counter:
        return self._get_or_create(Counter, name, help, labels)

    def gauge(self, name: str, help: str = "", **labels: str) -> Gauge:
        return self._get_or_create(Gauge, name, help, labels)

    def histogram(
        self,
        name: str,
        help: str = "",
        buckets: Sequence[float] = DEFAULT_LATENCY_BUCKETS_S,
        **labels: str,
    ) -> Histogram:
        return self._get_or_create(
            Histogram, name, help, labels, buckets=buckets
        )

    def register_collector(self, fn: Callable[[], None]) -> None:
        """Register a callable run before every snapshot/render; it
        should ``set()`` gauges from current external state."""
        with self._lock:
            self._collectors.append(fn)

    def _run_collectors(self) -> None:
        with self._lock:
            collectors = list(self._collectors)
        for fn in collectors:  # outside the lock: collectors may create gauges
            fn()

    def _instruments(self) -> List[Any]:
        with self._lock:
            return list(self._metrics.values())

    def find(self, name: str) -> List[Any]:
        """Every instrument registered under ``name`` (one per label
        set) — for consumers that rebuild structured views (per-replica
        tables) from labeled counters."""
        return [m for m in self._instruments() if m.name == name]

    def counters_flat(self) -> Dict[str, float]:
        """``{name{labels}: value}`` for counters only — the compat
        surface older ``/stats`` consumers read."""
        out: Dict[str, float] = {}
        for m in self._instruments():
            if m.kind == "counter":
                out[m.name + _format_labels(m.labels)] = m.value
        return out

    def snapshot(self) -> Dict[str, Any]:
        """All instruments as plain JSON-able dicts, grouped by kind."""
        self._run_collectors()
        out: Dict[str, Any] = {"counters": {}, "gauges": {}, "histograms": {}}
        for m in self._instruments():
            key = m.name + _format_labels(m.labels)
            if m.kind == "counter":
                out["counters"][key] = m.value
            elif m.kind == "gauge":
                out["gauges"][key] = m.value
            else:
                out["histograms"][key] = m.snapshot()
        return out

    def render_prometheus(self) -> str:
        """The Prometheus text exposition format (version 0.0.4)."""
        self._run_collectors()
        by_name: Dict[str, List[Any]] = {}
        for m in self._instruments():
            by_name.setdefault(m.name, []).append(m)
        lines: List[str] = []
        for name in sorted(by_name):
            family = sorted(by_name[name], key=lambda m: _label_key(m.labels))
            first = family[0]
            if first.help:
                lines.append(f"# HELP {name} {first.help}")
            lines.append(f"# TYPE {name} {first.kind}")
            for m in family:
                lbl = _format_labels(m.labels)
                if m.kind in ("counter", "gauge"):
                    lines.append(f"{name}{lbl} {_format_value(m.value)}")
                else:
                    snap = m.snapshot()
                    for le, cum in snap["buckets"].items():
                        blabels = dict(m.labels)
                        blabels["le"] = le
                        lines.append(
                            f"{name}_bucket{_format_labels(blabels)} {cum}"
                        )
                    lines.append(
                        f"{name}_sum{lbl} {_format_value(snap['sum'])}"
                    )
                    lines.append(f"{name}_count{lbl} {snap['count']}")
        return "\n".join(lines) + "\n"
