"""Explicit-collective SPMD train step — the hand-written counterpart of
the jit auto-partitioned step in `train/train_step.py`.

The reference has no distributed training at all (SURVEY.md §2.4); the
framework's default path gets data parallelism "for free" from jit
auto-partitioning (annotate shardings, XLA inserts the collectives). This
module is the same training step with every collective PLACED BY HAND via
``jax.shard_map`` — the moral equivalent of writing the DDP/NCCL-allreduce
loop yourself, in XLA collectives:

  * each shard runs forward/backward on its local batch slice;
  * loss normalizers (`#positives`, `#valid labels`) are `lax.psum`'d
    across the ``data`` axis before dividing (train/losses.py
    ``axis_name``), so the objective is the batch-global one;
  * BatchNorm runs in cross-replica (sync) mode — flax's ``axis_name``
    pmean — matching what auto-partitioning computes on a global batch;
  * per-image sampling keys fold in the GLOBAL batch position
    (``lax.axis_index`` offset), so target subsampling draws the same
    randomness as the auto-partitioned step;
  * gradients are `lax.psum`'d, then every shard applies the identical
    optimizer update to its replicated state.

Because of the four properties above, this step computes the same update
as the jit auto-partitioned step up to floating-point reduction order —
asserted by `tests/test_parallel.py`. One documented exception: dropout
(VGG16's fc6/fc7). The jit path draws one mask over the global crop batch;
here each shard draws its own mask (rng_do folds in ``lax.axis_index`` so
shards are decorrelated — statistically equivalent, not bitwise). It
exists (a) as an independent check on the auto path, (b) as the place
where collective placement is explicit and profilable, and (c) as the
template for adding shardings XLA cannot infer (e.g. tensor-parallel heads
over the mesh's ``model`` axis).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, PartitionSpec as P

from replication_faster_rcnn_tpu.config import FasterRCNNConfig
from replication_faster_rcnn_tpu.models.faster_rcnn import FasterRCNN
from replication_faster_rcnn_tpu.train import fault
from replication_faster_rcnn_tpu.train.train_step import TrainState, compute_losses

# jax >= 0.6 promotes shard_map to the top level and renames the
# replication-check kwarg check_rep -> check_vma; 0.4.x only has the
# experimental module. Resolve once at import so the builder below works
# on both.
if hasattr(jax, "shard_map"):  # pragma: no cover - jax >= 0.6 only
    _shard_map = jax.shard_map
    _NO_CHECK = {"check_vma": False}
else:
    from jax.experimental.shard_map import shard_map as _shard_map

    _NO_CHECK = {"check_rep": False}

Array = jnp.ndarray


def make_shard_map_train_step(
    config: FasterRCNNConfig,
    tx: optax.GradientTransformation,
    mesh: Mesh,
    steps_per_dispatch: int = 1,
):
    """Build the explicitly-collectivized (state, batch) -> (state, metrics)
    step. State must be replicated on ``mesh``; batch arrays sharded on
    their leading dim over the data axis (`parallel.shard_batch`).

    ``steps_per_dispatch`` > 1 fuses K steps into the one shard_map call:
    the per-shard body `lax.scan`s over batches stacked on a NEW leading
    [K] axis (shard with `parallel.shard_stacked_batch` — the batch dim is
    then axis 1), psum'ing grads/metrics every fused step; metrics return
    stacked [K, ...]. The carry state never leaves the program between the
    fused steps — one dispatch, K updates.

    ``config.train.grad_allreduce_dtype`` = "bfloat16" casts the gradient
    tree to bf16 BEFORE the psum — THE all-reduce then moves half the
    bytes — and de-casts for the fp32 optimizer math (arXiv:1711.04325's
    half-precision gradient exchange).

    Returns (step_fn, model): the model is constructed with sync-BN bound
    to the data axis; its parameter tree is identical to the default
    model's, so states are interchangeable between the two backends.
    """
    axis = config.mesh.data_axis
    allreduce_dt = jnp.dtype(config.train.grad_allreduce_dtype)
    # sync-BN binds batch statistics to the data axis; GroupNorm is
    # per-sample and needs no axis (the config layer rejects the combo)
    cfg = config.replace(
        model=dataclasses.replace(
            config.model,
            bn_axis=axis if config.model.norm == "batch" else None,
        )
    )
    model = FasterRCNN(cfg)

    def per_shard(
        state: TrainState, batch: Dict[str, Array]
    ) -> Tuple[TrainState, Dict[str, Array]]:
        step_rng = jax.random.fold_in(state.rng, state.step)
        n_local = batch["image"].shape[0]
        positions = jax.lax.axis_index(axis) * n_local + jnp.arange(
            n_local, dtype=jnp.int32
        )

        def loss_fn(params):
            return compute_losses(
                model, cfg, params, state.batch_stats, batch, step_rng,
                True, axis_name=axis, positions=positions,
            )

        (_, (metrics, new_stats)), grads = jax.value_and_grad(
            loss_fn, has_aux=True
        )(state.params)

        # THE allreduce: local grads of (local numerator / global normalizer)
        # sum to the global gradient. grad_allreduce_dtype=bfloat16 halves
        # the bytes this collective moves; the de-cast right after keeps
        # the optimizer math in the params' fp32.
        if allreduce_dt != jnp.float32:
            dtypes = jax.tree_util.tree_map(lambda g: g.dtype, grads)
            grads = jax.tree_util.tree_map(
                lambda g: g.astype(allreduce_dt)
                if jnp.issubdtype(g.dtype, jnp.floating)
                else g,
                grads,
            )
            grads = jax.lax.psum(grads, axis)
            grads = jax.tree_util.tree_map(
                lambda g, dt: g.astype(dt), grads, dtypes
            )
        else:
            grads = jax.lax.psum(grads, axis)
        # loss/count metrics are local-contribution / global-normalizer (or
        # plain local counts), so psum yields the batch-global values.
        metrics = jax.lax.psum(metrics, axis)

        # guarded update AFTER the psum: the nonfinite gate reads the
        # GLOBAL gradient, so every shard takes the same branch and the
        # replicated state stays replicated; health scalars likewise match
        # the auto-partitioned backend's (new_stats are already sync-BN
        # pmean'd, and carry through unchanged on a skipped step)
        new_state, health = fault.guarded_update(
            tx, state, grads, new_stats, config.train.nonfinite_policy
        )
        metrics.update(health)
        return new_state, metrics

    if steps_per_dispatch > 1:
        # fused K-step body: scan INSIDE the shard_map so the psums run
        # once per fused step while the carry state stays in-program. The
        # stacked [K, B, ...] batch shards its axis-1 batch dim over the
        # data axis (P(None, axis)); each scan slice is one local batch.
        def per_shard_multi(state, batches):
            from replication_faster_rcnn_tpu.train.train_step import (
                fused_scan_unroll,
            )

            return jax.lax.scan(
                per_shard, state, batches, length=steps_per_dispatch,
                unroll=fused_scan_unroll(steps_per_dispatch),
            )

        body, batch_spec = per_shard_multi, P(None, axis)
    else:
        body, batch_spec = per_shard, P(axis)

    sharded = _shard_map(
        body,
        mesh=mesh,
        in_specs=(P(), batch_spec),
        out_specs=(P(), P()),
        **_NO_CHECK,
    )
    return jax.jit(sharded, donate_argnums=(0,)), model
