"""Explicit-collective SPMD train step — the hand-written counterpart of
the jit auto-partitioned step in `train/train_step.py`.

The reference has no distributed training at all (SURVEY.md §2.4); the
framework's default path gets data parallelism "for free" from jit
auto-partitioning (annotate shardings, XLA inserts the collectives). This
module is the same training step with every collective PLACED BY HAND via
``jax.shard_map`` — the moral equivalent of writing the DDP/NCCL-allreduce
loop yourself, in XLA collectives:

  * each shard runs forward/backward on its local batch slice;
  * loss normalizers (`#positives`, `#valid labels`) are `lax.psum`'d
    across the ``data`` axis before dividing (train/losses.py
    ``axis_name``), so the objective is the batch-global one;
  * BatchNorm runs in cross-replica (sync) mode — flax's ``axis_name``
    pmean — matching what auto-partitioning computes on a global batch;
  * per-image sampling keys fold in the GLOBAL batch position
    (``lax.axis_index`` offset), so target subsampling draws the same
    randomness as the auto-partitioned step;
  * gradients are `lax.psum`'d, then every shard applies the identical
    optimizer update to its replicated state — or, under
    ``train.shard_opt_state`` (ZeRO-1, arXiv:2004.13336), each shard
    `lax.psum_scatter`s the gradients straight into its 1/N slice, updates
    only that slice of the parameters against its local slice of the Adam
    moments, and `lax.all_gather`s the updated slices back to full
    parameters. Same bytes on the wire as the allreduce it replaces, 1/N
    of the update FLOPs and moment memory per shard; the per-leaf slice
    layout is `parallel/zero.py`'s ``shard_dim`` rule, shared with the jit
    auto-partitioning backend so checkpoints move freely between the two.

Because of the four properties above, this step computes the same update
as the jit auto-partitioned step up to floating-point reduction order —
asserted by `tests/test_parallel.py`. One documented exception: dropout
(VGG16's fc6/fc7). The jit path draws one mask over the global crop batch;
here each shard draws its own mask (rng_do folds in ``lax.axis_index`` so
shards are decorrelated — statistically equivalent, not bitwise). It
exists (a) as an independent check on the auto path, (b) as the place
where collective placement is explicit and profilable, and (c) as the
template for adding shardings XLA cannot infer (e.g. tensor-parallel heads
over the mesh's ``model`` axis).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, PartitionSpec as P

from replication_faster_rcnn_tpu.config import FasterRCNNConfig
from replication_faster_rcnn_tpu.models.faster_rcnn import FasterRCNN
from replication_faster_rcnn_tpu.parallel import zero
from replication_faster_rcnn_tpu.parallel.plan import Plan, compile_step_with_plan
from replication_faster_rcnn_tpu.train import fault
from replication_faster_rcnn_tpu.train.train_step import TrainState, compute_losses

Array = jnp.ndarray


def make_shard_map_train_step(
    config: FasterRCNNConfig,
    tx: optax.GradientTransformation,
    mesh: Mesh,
    steps_per_dispatch: int = 1,
    state_template: TrainState = None,
    train_resolution=None,
):
    """Build the explicitly-collectivized (state, batch) -> (state, metrics)
    step. State must be replicated on ``mesh``; batch arrays sharded on
    their leading dim over the data axis (`parallel.shard_batch`).

    Under ``config.train.shard_opt_state`` (ZeRO-1) the state is instead
    placed with `parallel.zero.train_state_shardings(shard_opt=True)` —
    optimizer-state leaves arrive as this shard's 1/N slice — and
    ``state_template`` (the TrainState, concrete or abstract: only leaf
    shapes are read, at trace time) is required to derive the per-leaf
    slice layout. The step then reduce-scatters gradients, updates slices,
    and all-gathers the updated parameters; in/out state shardings match
    the jit backend's, so the two ZeRO implementations are checkpoint- and
    placement-compatible.

    ``steps_per_dispatch`` > 1 fuses K steps into the one shard_map call:
    the per-shard body `lax.scan`s over batches stacked on a NEW leading
    [K] axis (shard with `parallel.shard_stacked_batch` — the batch dim is
    then axis 1), psum'ing grads/metrics every fused step; metrics return
    stacked [K, ...]. The carry state never leaves the program between the
    fused steps — one dispatch, K updates.

    ``train_resolution`` (STATIC ``(h, w)`` or None) builds the step for
    ONE multi-scale training bucket: the resample to the bucket's shape
    is traced into the per-shard body (`compute_losses`), so each bucket
    is its own shard_map program. The in/out specs are untouched — they
    shard only batch dims (``P(axis)`` / ``P(None, axis)``), which is
    resolution-independent; only the traced body and the Plan label
    (``train_step_{h}x{w}``) differ between buckets.

    ``config.train.grad_allreduce_dtype`` = "bfloat16" casts the gradient
    tree to bf16 BEFORE the psum — THE all-reduce then moves half the
    bytes — and de-casts for the fp32 optimizer math (arXiv:1711.04325's
    half-precision gradient exchange).

    Returns (step_fn, model): the model is constructed with sync-BN bound
    to the data axis; its parameter tree is identical to the default
    model's, so states are interchangeable between the two backends.
    """
    axis = config.mesh.data_axis
    allreduce_dt = jnp.dtype(config.train.grad_allreduce_dtype)
    # sync-BN binds batch statistics to the data axis; GroupNorm is
    # per-sample and needs no axis (the config layer rejects the combo)
    cfg = config.replace(
        model=dataclasses.replace(
            config.model,
            bn_axis=axis if config.model.norm == "batch" else None,
        )
    )
    model = FasterRCNN(cfg)

    def per_shard(
        state: TrainState, batch: Dict[str, Array]
    ) -> Tuple[TrainState, Dict[str, Array]]:
        step_rng = jax.random.fold_in(state.rng, state.step)
        n_local = batch["image"].shape[0]
        positions = jax.lax.axis_index(axis) * n_local + jnp.arange(
            n_local, dtype=jnp.int32
        )

        def loss_fn(params):
            return compute_losses(
                model, cfg, params, state.batch_stats, batch, step_rng,
                True, axis_name=axis, positions=positions,
                train_resolution=train_resolution,
            )

        (_, (metrics, new_stats)), grads = jax.value_and_grad(
            loss_fn, has_aux=True
        )(state.params)

        # THE allreduce: local grads of (local numerator / global normalizer)
        # sum to the global gradient. grad_allreduce_dtype=bfloat16 halves
        # the bytes this collective moves; the de-cast right after keeps
        # the optimizer math in the params' fp32.
        if allreduce_dt != jnp.float32:
            dtypes = jax.tree_util.tree_map(lambda g: g.dtype, grads)
            grads = jax.tree_util.tree_map(
                lambda g: g.astype(allreduce_dt)
                if jnp.issubdtype(g.dtype, jnp.floating)
                else g,
                grads,
            )
            grads = jax.lax.psum(grads, axis)
            grads = jax.tree_util.tree_map(
                lambda g, dt: g.astype(dt), grads, dtypes
            )
        else:
            grads = jax.lax.psum(grads, axis)
        # loss/count metrics are local-contribution / global-normalizer (or
        # plain local counts), so psum yields the batch-global values.
        metrics = jax.lax.psum(metrics, axis)

        # guarded update AFTER the psum: the nonfinite gate reads the
        # GLOBAL gradient, so every shard takes the same branch and the
        # replicated state stays replicated; health scalars likewise match
        # the auto-partitioned backend's (new_stats are already sync-BN
        # pmean'd, and carry through unchanged on a skipped step)
        new_state, health = fault.guarded_update(
            tx, state, grads, new_stats, config.train.nonfinite_policy
        )
        metrics.update(health)
        return new_state, metrics

    n_shards = mesh.shape[axis]
    shard_opt = bool(config.train.shard_opt_state) and n_shards > 1
    if shard_opt and state_template is None:
        raise ValueError(
            "shard_opt_state on the shard_map backend needs a "
            "state_template (the TrainState, concrete or abstract) to "
            "derive the per-leaf ZeRO-1 slice layout"
        )
    if shard_opt:
        # ZeRO-1 by hand. Per-leaf slice dims come from the FULL shapes of
        # the template (inside the body every sharded leaf is local, so
        # the layout must be closed over, never recomputed from local
        # shapes). -1 marks a leaf the layout rule keeps replicated.
        param_dims = jax.tree_util.tree_map(
            lambda leaf: zero.shard_dim(np.shape(leaf), n_shards),
            state_template.params,
        )
        state_specs = jax.tree_util.tree_map(lambda _: P(), state_template)
        state_specs = state_specs.replace(
            opt_state=jax.tree_util.tree_map(
                lambda leaf: zero.shard_spec(np.shape(leaf), n_shards, axis),
                state_template.opt_state,
            )
        )

        def _reduce_grad(g, d):
            # the restructured allreduce: shardable leaves reduce-scatter
            # straight into this shard's slice (same wire bytes, 1/N the
            # output); unshardable ones keep the plain psum
            if d >= 0:
                return jax.lax.psum_scatter(
                    g, axis, scatter_dimension=d, tiled=True
                )
            return jax.lax.psum(g, axis)

        def _slice(leaf, d):
            if d < 0:
                return leaf
            size = leaf.shape[d] // n_shards
            start = jax.lax.axis_index(axis) * size
            return jax.lax.dynamic_slice_in_dim(leaf, start, size, d)

        def _gather(leaf, d):
            if d < 0:
                return leaf
            return jax.lax.all_gather(leaf, axis, axis=d, tiled=True)

        def _sharded_sumsq(tree, dims, local_fn):
            # sum(local_fn over sliced leaves) psums to the global value;
            # replicated leaves contribute theirs directly on every shard
            xs = jax.tree_util.tree_leaves(tree)
            ds = jax.tree_util.tree_leaves(dims)
            zero_ = jnp.zeros((), jnp.float32)
            local = sum(
                (local_fn(x) for x, d in zip(xs, ds) if d >= 0), zero_
            )
            repl = sum(
                (local_fn(x) for x, d in zip(xs, ds) if d < 0), zero_
            )
            return jax.lax.psum(local, axis) + repl

        def _sumsq(x):
            return jnp.sum(jnp.square(x.astype(jnp.float32)))

        def _nonfin(x):
            if not jnp.issubdtype(x.dtype, jnp.inexact):
                return jnp.zeros((), jnp.float32)
            return jnp.sum(~jnp.isfinite(x)).astype(jnp.float32)

        def per_shard_zero(
            state: TrainState, batch: Dict[str, Array]
        ) -> Tuple[TrainState, Dict[str, Array]]:
            # identical forward/backward to per_shard; params arrive full
            # (replicated), opt-state leaves arrive as this shard's slice
            step_rng = jax.random.fold_in(state.rng, state.step)
            n_local = batch["image"].shape[0]
            positions = jax.lax.axis_index(axis) * n_local + jnp.arange(
                n_local, dtype=jnp.int32
            )

            def loss_fn(params):
                return compute_losses(
                    model, cfg, params, state.batch_stats, batch, step_rng,
                    True, axis_name=axis, positions=positions,
                    train_resolution=train_resolution,
                )

            (_, (metrics, new_stats)), grads = jax.value_and_grad(
                loss_fn, has_aux=True
            )(state.params)
            metrics = jax.lax.psum(metrics, axis)

            if allreduce_dt != jnp.float32:
                dtypes = jax.tree_util.tree_map(lambda g: g.dtype, grads)
                grads = jax.tree_util.tree_map(
                    lambda g: g.astype(allreduce_dt)
                    if jnp.issubdtype(g.dtype, jnp.floating)
                    else g,
                    grads,
                )
                grads = jax.tree_util.tree_map(_reduce_grad, grads, param_dims)
                grads = jax.tree_util.tree_map(
                    lambda g, dt: g.astype(dt), grads, dtypes
                )
            else:
                grads = jax.tree_util.tree_map(_reduce_grad, grads, param_dims)

            # this shard's parameter slices; the optimizer chain is
            # elementwise (add_decayed_weights / scale_by_adam / lr), so
            # updating slices against the local moment slices computes
            # exactly the slice of the full update
            param_sl = jax.tree_util.tree_map(_slice, state.params, param_dims)
            updates, new_opt = tx.update(grads, state.opt_state, param_sl)
            new_param_sl = optax.apply_updates(param_sl, updates)

            # health on sharded trees: psum'd sums-of-squares reproduce the
            # replicated backend's global norms (same numbers, modulo
            # reduction order) and the nonfinite gate stays GLOBAL — every
            # shard takes the same branch below
            grad_norm = jnp.sqrt(_sharded_sumsq(grads, param_dims, _sumsq))
            update_norm = jnp.sqrt(_sharded_sumsq(updates, param_dims, _sumsq))
            param_norm = optax.global_norm(state.params)
            nonfinite = _sharded_sumsq(grads, param_dims, _nonfin)
            health = {
                "grad_norm": grad_norm,
                "param_norm": param_norm,
                "update_norm": update_norm,
                "update_ratio": update_norm / (param_norm + 1e-12),
                "nonfinite_count": nonfinite,
            }
            if config.train.nonfinite_policy == "apply":
                health["skipped"] = jnp.zeros((), jnp.float32)
                sel_p, sel_opt, sel_stats = new_param_sl, new_opt, new_stats
            else:
                ok = nonfinite == 0

                def keep(new, old):
                    # select BEFORE the gather: on a skipped step every
                    # shard contributes its OLD slice, so the gathered
                    # params are bit-identical to the pre-step tree
                    return jnp.where(ok, new, old)

                sel_p = jax.tree_util.tree_map(keep, new_param_sl, param_sl)
                sel_opt = jax.tree_util.tree_map(keep, new_opt, state.opt_state)
                sel_stats = jax.tree_util.tree_map(
                    keep, new_stats, state.batch_stats
                )
                health["skipped"] = 1.0 - ok.astype(jnp.float32)
            metrics.update(health)

            new_params = jax.tree_util.tree_map(_gather, sel_p, param_dims)
            new_state = state.replace(
                step=state.step + 1,
                params=new_params,
                batch_stats=sel_stats,
                opt_state=sel_opt,
            )
            return new_state, metrics

        step_body, state_spec = per_shard_zero, state_specs
    else:
        step_body, state_spec = per_shard, P()

    if steps_per_dispatch > 1:
        # fused K-step body: scan INSIDE the shard_map so the psums run
        # once per fused step while the carry state stays in-program. The
        # stacked [K, B, ...] batch shards its axis-1 batch dim over the
        # data axis (P(None, axis)); each scan slice is one local batch.
        def per_shard_multi(state, batches):
            from replication_faster_rcnn_tpu.train.train_step import (
                fused_scan_unroll,
            )

            # the carry keeps the step body's state layout (sliced opt
            # leaves under ZeRO), so K-step fusion composes unchanged
            return jax.lax.scan(
                step_body, state, batches, length=steps_per_dispatch,
                unroll=fused_scan_unroll(steps_per_dispatch),
            )

        body, batch_spec = per_shard_multi, P(None, axis)
    else:
        body, batch_spec = step_body, P(axis)

    label = (
        "train_step"
        if steps_per_dispatch <= 1
        else f"multi_step_k{steps_per_dispatch}"
    )
    if train_resolution is not None:
        # per-bucket program: same label convention as the trainer's
        # cached/loader bucket steps, so strict dispatch accounting and
        # the warmup registry agree on names across backends
        label = f"{label}_{int(train_resolution[0])}x{int(train_resolution[1])}"
    plan = Plan(
        mesh=mesh,
        in_specs=(state_spec, batch_spec),
        out_specs=(state_spec, P()),
        donate_argnums=(0,),
        param_specs=state_spec,
        label=label,
    )
    return compile_step_with_plan(body, plan), model
