"""Elastic fleet training: survive rank loss mid-epoch (ROADMAP item).

A fixed-world data-parallel fleet dies with its weakest member: when one
rank is lost (spot reclaim, OOM kill, hardware fault), every surviving
rank's next collective simply never completes.  On the gloo/CPU backend
there is *no catchable error* — the survivor's psum blocks in C until the
JAX coordination service declares the fleet unhealthy and force-aborts
the process with SIGABRT roughly 10 seconds later.  Nothing downstream
of the collective ever runs again, so recovery cannot live on the main
thread and cannot assume a clean Python exit.

This module is the whole recovery story, split across the two processes
that survive a rank loss:

In the **training child** (one per rank, spawned by the supervisor):

  :class:`ElasticAgent` — a daemon thread that doubles as heartbeat
  writer and collective watchdog.  Every ``heartbeat_interval_s`` it
  (a) consults the ``heartbeat.beat`` failpoint — a seeded ``drop``
  whose ``arg`` equals this rank's index kills the process mid-lease,
  the deterministic stand-in for a real rank loss — then (b) renews
  this rank's lease file and (c) checks every peer's lease age.  A peer
  whose lease is older than ``lease_timeout_s`` is declared lost: the
  agent records the incident, writes a durable *shrink intent* file,
  and after a short grace (giving the main thread a chance to surface
  :class:`~replication_faster_rcnn_tpu.train.fault.FleetShrink` at a
  dispatch boundary) hard-exits with ``EXIT_FLEET_SHRINK`` — beating
  the coordination service's ~10s abort, which is why
  ``lease_timeout_s`` must stay well under 10 seconds.  The trainer
  starts the agent lazily at the *first* dispatch boundary so the
  multi-minute compile window cannot produce false lease expiries.

In the **per-host supervisor** (:func:`run_supervisor`, entered via
``frcnn train --elastic``):

  A generation loop that spawns the training child and branches on how
  it died.  Exit 0 / ``EXIT_PREEMPTED`` propagate; a child that exited
  ``EXIT_FLEET_SHRINK`` (or left a shrink intent naming this rank a
  survivor) triggers **re-formation**: each surviving supervisor writes
  a claim file for the next generation, waits ``settle_s`` for the
  claim set to quiesce, the lowest-ranked claimant arbitrates the plan
  (survivor list, new world size), and every planned-in host respawns
  the child at its new contiguous rank with ``--resume``, a bumped
  coordinator port (``base_port + generation``) and the fleet
  generation exported in ``FRCNN_FLEET_GENERATION``.  Any other exit
  code means *this* host is the casualty: its supervisor leaves the
  fleet without claiming, which is exactly how the injected-dead rank's
  side of the protocol resolves.

There is deliberately **no emergency checkpoint** on the shrink path:
checkpoint saves are themselves cross-process collectives and would
hang on the dead peer.  Survivors fall back to the last CRC-verified
step (``train.checkpoint_every_steps`` bounds the rollback) and resume
*inside the same epoch* — the loader's offset-based ``set_epoch``
re-partitions the unconsumed suffix of the epoch's global sample order
disjointly across the shrunken world, and ZeRO-1 optimizer shards are
re-sliced for the new topology by the existing cross-topology restore.

All fleet state is plain JSON files under one ``fleet_dir`` (atomic
tmp + ``os.replace`` writes), which must be visible to every host of
the fleet — the same shared-filesystem assumption the multi-host
checkpoint layer already makes.  Same-seed runs reproduce the identical
incident sequence: the heartbeat drop is decided by the failpoint
registry's pure hash, and the ``fleet_reformed`` incident fields are
step-free (generation, world size, survivors).
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

from replication_faster_rcnn_tpu.faultlib import failpoints

# Environment contract between supervisor and training child. The child
# reads these to find the fleet dir (enables the in-child ElasticAgent)
# and to stamp the checkpoint manifest's topology with the generation.
ENV_FLEET_DIR = "FRCNN_FLEET_DIR"
ENV_GENERATION = "FRCNN_FLEET_GENERATION"


def fleet_env(env=os.environ):
    """(fleet_dir | None, generation) from the supervisor-exported env."""
    fleet_dir = env.get(ENV_FLEET_DIR) or None
    try:
        generation = int(env.get(ENV_GENERATION, "0") or 0)
    except ValueError:
        generation = 0
    return fleet_dir, generation


def child_env(env, fleet_dir: str, generation: int) -> Dict[str, str]:
    """The training child's environment: parent env + fleet exports."""
    out = dict(env)
    out[ENV_FLEET_DIR] = fleet_dir
    out[ENV_GENERATION] = str(generation)
    return out


# ------------------------------------------------------------ fleet files
#
# One flat directory of small JSON files; every write is atomic
# (tmp + os.replace) so a reader never sees a torn record. Names encode
# generation + rank so successive generations never collide.


def lease_path(fleet_dir: str, generation: int, rank: int) -> str:
    return os.path.join(fleet_dir, f"hb_gen{generation}_rank{rank}.json")


def intent_path(fleet_dir: str, generation: int) -> str:
    return os.path.join(fleet_dir, f"shrink_gen{generation}.json")


def claim_path(fleet_dir: str, generation: int, rank: int) -> str:
    return os.path.join(fleet_dir, f"claim_gen{generation}_rank{rank}.json")


def plan_path(fleet_dir: str, generation: int) -> str:
    return os.path.join(fleet_dir, f"plan_gen{generation}.json")


def _write_json_atomic(path: str, payload: Dict[str, Any]) -> None:
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(payload, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def _read_json(path: str) -> Optional[Dict[str, Any]]:
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def read_intent(fleet_dir: str, generation: int) -> Optional[Dict[str, Any]]:
    """The shrink-intent record for ``generation``, if any survivor of
    that generation declared one (None otherwise)."""
    return _read_json(intent_path(fleet_dir, generation))


def read_plan(fleet_dir: str, generation: int) -> Optional[Dict[str, Any]]:
    return _read_json(plan_path(fleet_dir, generation))


# --------------------------------------------------------- in-child agent


class ElasticAgent:
    """Heartbeat writer + peer-lease watchdog for one training rank.

    One daemon thread per rank does three things every
    ``heartbeat_interval_s``:

      1. consults the ``heartbeat.beat`` failpoint (a ``drop`` whose
         ``arg`` equals this rank kills the process via ``on_drop`` —
         default ``os._exit(1)``, the sudden-death a real reclaim looks
         like; drops naming other ranks are ignored here and land on
         their target's own registry, which replays the same seeded
         decision stream),
      2. renews this rank's lease file, and
      3. checks every peer lease's age.

    A peer lease older than ``lease_timeout_s`` declares that rank lost:
    ``on_lost`` fires once (the trainer logs the ``fleet_rank_lost``
    incident there), the durable shrink intent is written, and the lost
    set becomes visible to the main thread via :meth:`check`. If
    ``exit_on_shrink`` is set (the production wiring), the thread then
    waits ``exit_grace_s`` for the main thread to exit cleanly and
    hard-exits with ``EXIT_FLEET_SHRINK`` — the main thread is usually
    blocked inside the doomed collective and will never run again, and
    the coordination service would SIGABRT us at ~10s, so the watchdog
    cannot wait politely.

    A peer with *no* lease file yet is considered alive: leases start
    lazily at the first dispatch boundary, and compile skew between
    ranks must not read as death.

    ``clock`` and manual :meth:`beat` calls make the whole protocol
    drivable single-threaded (``start_thread=False``) — the chaos
    harness's fleet leg replays rank loss in-process with a fake clock
    and asserts the same seed yields the identical event log.
    """

    def __init__(
        self,
        fleet_dir: str,
        generation: int,
        rank: int,
        world: int,
        *,
        heartbeat_interval_s: float = 0.5,
        lease_timeout_s: float = 5.0,
        exit_grace_s: float = 2.0,
        clock: Callable[[], float] = time.time,
        on_drop: Optional[Callable[[], None]] = None,
        on_lost: Optional[Callable[[List[int], List[int]], None]] = None,
        exit_on_shrink: bool = True,
    ) -> None:
        self.fleet_dir = fleet_dir
        self.generation = int(generation)
        self.rank = int(rank)
        self.world = int(world)
        self.heartbeat_interval_s = float(heartbeat_interval_s)
        self.lease_timeout_s = float(lease_timeout_s)
        self.exit_grace_s = float(exit_grace_s)
        self.clock = clock
        self.on_drop = on_drop
        self.on_lost = on_lost
        self.exit_on_shrink = exit_on_shrink
        self._beats = 0
        self._lost: List[int] = []
        self._lost_lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        os.makedirs(fleet_dir, exist_ok=True)

    # -- heartbeat side

    def beat(self) -> None:
        """One lease renewal: consult the failpoint, then write the lease."""
        n = self._beats
        self._beats = n + 1
        inj = failpoints.fire(
            "heartbeat.beat",
            rank=self.rank,
            generation=self.generation,
            beat=n,
        )
        if inj is not None and inj.kind == "drop" and int(inj.arg) == self.rank:
            if self.on_drop is not None:
                self.on_drop()
                return  # dead ranks do not renew their lease
            # sudden death: no cleanup, no atexit — what a reclaimed
            # host actually looks like from the peers' side
            os._exit(1)
        _write_json_atomic(
            lease_path(self.fleet_dir, self.generation, self.rank),
            {
                "rank": self.rank,
                "generation": self.generation,
                "beat": n,
                "t": self.clock(),
            },
        )

    # -- watchdog side

    def lost_ranks(self, now: Optional[float] = None) -> List[int]:
        """Peers whose lease age exceeds the timeout (missing = alive)."""
        if now is None:
            now = self.clock()
        lost = []
        for r in range(self.world):
            if r == self.rank:
                continue
            lease = _read_json(lease_path(self.fleet_dir, self.generation, r))
            if lease is None:
                continue
            if now - float(lease.get("t", now)) > self.lease_timeout_s:
                lost.append(r)
        return lost

    def survivors(self, lost: Sequence[int]) -> List[int]:
        return [r for r in range(self.world) if r not in set(lost)]

    def declare_shrink(self, lost: Sequence[int], step: int = -1) -> List[int]:
        """Write the durable shrink intent (idempotent: last write wins,
        every survivor writes the same survivor set). Returns survivors."""
        survivors = self.survivors(lost)
        _write_json_atomic(
            intent_path(self.fleet_dir, self.generation),
            {
                "generation": self.generation,
                "lost": sorted(int(r) for r in lost),
                "survivors": survivors,
                "step": int(step),
                "detected_by": self.rank,
            },
        )
        return survivors

    def check(self) -> List[int]:
        """Main-thread view of the watchdog: ranks declared lost so far
        (empty while the fleet is healthy). Non-blocking."""
        with self._lost_lock:
            return list(self._lost)

    # -- thread lifecycle

    def start(self) -> None:
        """Start the heartbeat/watchdog thread (idempotent)."""
        if self._thread is not None or self._stop.is_set():
            return
        self._thread = threading.Thread(
            target=self._run, name="elastic-heartbeat", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2.0)

    def _run(self) -> None:
        while not self._stop.is_set():
            self.beat()
            lost = self.lost_ranks()
            if lost:
                self._on_peer_lost(lost)
                return
            self._stop.wait(self.heartbeat_interval_s)

    def _on_peer_lost(self, lost: List[int]) -> None:
        survivors = self.survivors(lost)
        if self.on_lost is not None:
            try:
                self.on_lost(sorted(lost), survivors)
            except Exception:  # noqa: BLE001 - observer must not block recovery
                pass
        self.declare_shrink(lost)
        with self._lost_lock:
            self._lost = sorted(lost)
        if not self.exit_on_shrink:
            return
        # grace window: if the main thread is between dispatches it will
        # see check() != [] and raise FleetShrink -> clean exit 76. If it
        # is blocked inside the dead fleet's collective it never returns,
        # and the coordination service aborts us at ~10s — exit first.
        self._stop.wait(self.exit_grace_s)
        if self._stop.is_set():
            return  # stop() won the race (tests); let the caller decide
        sys.stderr.write(
            f"elastic: rank(s) {sorted(lost)} lost lease "
            f"(gen {self.generation}); exiting for re-formation\n"
        )
        sys.stderr.flush()
        from replication_faster_rcnn_tpu.train.fault import EXIT_FLEET_SHRINK

        os._exit(EXIT_FLEET_SHRINK)


# ------------------------------------------------------ re-form protocol


def write_claim(fleet_dir: str, generation: int, rank: int) -> None:
    """Claim membership in ``generation`` (rank = the claimant's rank in
    the PREVIOUS generation; the plan maps these to new contiguous ranks)."""
    _write_json_atomic(
        claim_path(fleet_dir, generation, rank),
        {"rank": int(rank), "pid": os.getpid()},
    )


def read_claims(fleet_dir: str, generation: int, world: int) -> List[int]:
    """Sorted previous-generation ranks that claimed ``generation``."""
    return sorted(
        r for r in range(world)
        if os.path.exists(claim_path(fleet_dir, generation, r))
    )


def write_plan(fleet_dir: str, generation: int, survivors: Sequence[int]) -> None:
    survivors = sorted(int(r) for r in survivors)
    _write_json_atomic(
        plan_path(fleet_dir, generation),
        {
            "generation": int(generation),
            "survivors": survivors,
            "world": len(survivors),
        },
    )


def wait_plan(
    fleet_dir: str,
    generation: int,
    timeout_s: float,
    poll_s: float = 0.05,
) -> Optional[Dict[str, Any]]:
    """Poll for the generation's plan file (None on timeout)."""
    deadline = time.monotonic() + timeout_s
    while True:
        plan = read_plan(fleet_dir, generation)
        if plan is not None:
            return plan
        if time.monotonic() >= deadline:
            return None
        time.sleep(poll_s)


# ------------------------------------------------------------- supervisor


def child_argv(
    argv: Sequence[str],
    *,
    generation: int,
    rank: int,
    world: int,
    coordinator: Optional[str],
) -> List[str]:
    """Rewrite the supervisor's own ``train ... --elastic`` argv into the
    per-generation child argv: ``--elastic`` is stripped (the child runs
    the plain trainer), the distributed flags are replaced with this
    generation's topology (omitted entirely at world 1, so a fully
    shrunken fleet runs single-process with no gloo at all), and
    re-formed generations force ``--resume`` (a user-passed ``--resume``
    is preserved for generation 0)."""
    drop_with_value = {"--num-processes", "--process-id", "--coordinator"}
    out: List[str] = []
    skip = False
    for a in argv:
        if skip:
            skip = False
            continue
        key = a.split("=", 1)[0]
        if key in drop_with_value:
            skip = "=" not in a
            continue
        if key == "--elastic":
            continue
        out.append(a)
    if world > 1:
        if not coordinator:
            raise ValueError("world > 1 needs a coordinator address")
        out += [
            "--num-processes", str(world),
            "--process-id", str(rank),
            "--coordinator", coordinator,
        ]
    if generation > 0 and "--resume" not in out:
        out.append("--resume")
    return out


def clear_fleet_dir(fleet_dir: str) -> None:
    """Drop stale lease/claim/plan/intent files from a previous run (the
    coordinator-rank supervisor calls this before generation 0 so a
    reused workdir cannot replay an old fleet's shrink protocol).

    Safe against concurrent supervisors without locking: the clear runs
    before rank 0 spawns its generation-0 child, no peer child can exit
    before that child joins the collective bring-up (or bring-up itself
    fails, which is a fleet-leaving exit, not a shrink), and supervisors
    only write fleet files while re-forming after a child exit — so no
    live fleet file can be mid-write while this runs."""
    if not os.path.isdir(fleet_dir):
        return
    for name in os.listdir(fleet_dir):
        if name.startswith(("hb_gen", "shrink_gen", "claim_gen", "plan_gen")):
            try:
                os.remove(os.path.join(fleet_dir, name))
            except OSError:
                pass


def run_supervisor(
    spawn: Callable[..., Any],
    *,
    fleet_dir: str,
    rank: int,
    world: int,
    host: str,
    base_port: int,
    settle_s: float = 2.0,
    max_generations: int = 8,
    plan_timeout_s: Optional[float] = None,
    log: Callable[[str], None] = lambda m: print(m, file=sys.stderr),
) -> int:
    """Per-host generation loop: spawn the training child, branch on how
    it exits, re-form the fleet at the surviving world size.

    ``spawn(generation=, rank=, world=, coordinator=)`` must start the
    training child and return an object with ``wait() -> int`` (a
    ``subprocess.Popen`` in production; tests substitute their own).
    ``rank``/``world`` are this host's generation-0 identity; across
    re-formations the supervisor tracks its current rank (survivors are
    renumbered contiguously by the plan). Returns the process exit code
    the CLI should propagate: 0 done (or planned out of the fleet),
    ``EXIT_PREEMPTED`` passthrough, the child's own code on a non-shrink
    failure or when ``max_generations`` is exhausted, 1 when the re-form
    protocol itself times out.
    """
    from replication_faster_rcnn_tpu.train.fault import (
        EXIT_FLEET_SHRINK,
        EXIT_PREEMPTED,
    )

    if plan_timeout_s is None:
        plan_timeout_s = 5.0 * settle_s + 10.0
    os.makedirs(fleet_dir, exist_ok=True)
    if rank == 0:
        clear_fleet_dir(fleet_dir)
    generation = 0
    cur_rank, cur_world = int(rank), int(world)
    while True:
        coordinator = (
            f"{host}:{base_port + generation}" if cur_world > 1 else None
        )
        log(
            f"elastic: gen {generation} starting child "
            f"rank {cur_rank}/{cur_world}"
            + (f" coordinator {coordinator}" if coordinator else "")
        )
        proc = spawn(
            generation=generation,
            rank=cur_rank,
            world=cur_world,
            coordinator=coordinator,
        )
        rc = proc.wait()
        if rc == 0:
            return 0
        if rc == EXIT_PREEMPTED:
            return EXIT_PREEMPTED
        intent = read_intent(fleet_dir, generation)
        shrink = rc == EXIT_FLEET_SHRINK or (
            intent is not None and cur_rank in intent.get("survivors", ())
        )
        if not shrink:
            # this host is the casualty (or a real crash): leave the
            # fleet without claiming — the survivors re-form without us
            log(f"elastic: gen {generation} child exited {rc}; leaving fleet")
            return rc
        if generation + 1 >= max_generations:
            log(
                f"elastic: max_generations={max_generations} exhausted "
                f"at gen {generation}"
            )
            return rc or 1
        generation += 1
        write_claim(fleet_dir, generation, cur_rank)
        time.sleep(settle_s)
        claims = read_claims(fleet_dir, generation, cur_world)
        if claims and claims[0] == cur_rank:
            write_plan(fleet_dir, generation, claims)
        plan = wait_plan(fleet_dir, generation, timeout_s=plan_timeout_s)
        if plan is None:
            log(f"elastic: gen {generation} plan never appeared; giving up")
            return 1
        survivors = [int(r) for r in plan.get("survivors", ())]
        if cur_rank not in survivors:
            log(f"elastic: gen {generation} plan excludes rank {cur_rank}")
            return 0
        new_rank = survivors.index(cur_rank)
        log(
            f"elastic: re-forming gen {generation}: survivors {survivors} "
            f"-> rank {new_rank}/{len(survivors)}"
        )
        cur_rank, cur_world = new_rank, int(plan["world"])
