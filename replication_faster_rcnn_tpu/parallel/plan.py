"""Declarative compile plans — ONE dispatch layer for every jitted program.

Before this module the jit wrapping of each program was hand-threaded at
its call site: the Trainer picked donation/out_shardings per feed, the
shard_map backend wrapped its own body, the warmup registry duplicated
both, and the serving engine jitted bare. A :class:`Plan` captures that
choice declaratively — mesh, shard_map in/out specs OR jit out-shardings,
donation, per-module parameter PartitionSpecs, warmup policy, the
strict-mode dispatch label — and :func:`compile_step_with_plan` is the
single place that turns (step_fn, plan) into the jitted callable:

  * ``in_specs``/``out_specs`` present  -> ``jax.jit(shard_map(fn, ...))``
    (the explicit-collective backend, `parallel/spmd.py`);
  * ``out_shardings`` present           -> ``jax.jit`` with donation +
    out-shardings (jit auto-partitioning, GSPMD inserts collectives);
  * neither                             -> plain ``jax.jit`` (inference:
    eval sweep, serving buckets).

The wrappings are byte-identical to the pre-Plan call sites — the
committed HLO fingerprints (`analysis/fingerprints/ci_cpu.json`) pin
that.

:meth:`Plan.validate` is the companion DECISION TABLE: every
feed × backend × optimizer compatibility rule that used to live scattered
across `Trainer.__init__` and `parallel/mesh.py`, one cell per rule, each
cell unit-testable in isolation (tests/test_plan.py).

This module deliberately imports nothing from the config layer, so the
config module stays jax-free (the elastic supervisor and `frcnn audit`
rely on configuring XLA_FLAGS before jax loads) — and it imports jax
lazily, so the decision table and the sharding-intent declarations below
are readable by the jax-free static gates (`frcnn check` runs shardlint
over the fingerprint bank without initializing a backend).
"""

from __future__ import annotations

import dataclasses
import warnings
from typing import Any, Callable, Dict, Optional, Tuple


def _resolve_shard_map():
    """jax >= 0.6 promotes shard_map to the top level and renames the
    replication-check kwarg check_rep -> check_vma; 0.4.x only has the
    experimental module. Resolved lazily so importing this module (for
    the decision table / intent declarations) needs no jax."""
    import jax

    if hasattr(jax, "shard_map"):  # pragma: no cover - jax >= 0.6 only
        return jax.shard_map, {"check_vma": False}
    from jax.experimental.shard_map import shard_map

    return shard_map, {"check_rep": False}


# ------------------------------------------------ declarative sharding intent
#
# What each train/serve feed DECLARES about the state tree's placement —
# the single source shardlint (analysis/shardlint.py) audits the banked
# program fingerprints against, and the prose the Plan docstrings tell.
# Axes name the mesh axes a role's leaves shard over when a divisible dim
# exists (`parallel/zero.py::shard_dim` / `compose_spec`); an empty tuple
# means the role is replicated by design on that feed.

# feeds whose optimizer state is ZeRO-1 sharded (train.shard_opt_state)
ZERO_INTENT_FEEDS: Tuple[str, ...] = ("zero", "zero_lamb", "mp_zero")
# feeds that shard parameters over the model axis (mesh.param_sharding)
MP_INTENT_FEEDS: Tuple[str, ...] = ("mp", "mp_zero")

FEED_STATE_INTENT: Dict[str, Dict[str, Tuple[str, ...]]] = {
    "loader": {"params": (), "opt_state": ()},
    "cached": {"params": (), "opt_state": ()},
    "spmd": {"params": (), "opt_state": ()},
    "zero": {"params": (), "opt_state": ("data",)},
    "zero_lamb": {"params": (), "opt_state": ("data",)},
    "mp": {"params": ("model",), "opt_state": ()},
    "mp_zero": {"params": ("model",), "opt_state": ("model", "data")},
    "eval": {"params": (), "opt_state": ()},
    # serving under an mp mesh routes params through zero.param_shardings
    # (train/warmup.py::build_serving_specs); on a 1-device/dp-only
    # serving mesh the engine keeps them replicated
    "serve": {"params": ("model",), "opt_state": ()},
}


@dataclasses.dataclass(frozen=True)
class Plan:
    """How one program compiles: the mesh it runs on, the partitioning
    mode (shard_map specs, jit out-shardings, or neither), donation, and
    the metadata its consumers read (per-module param specs for the
    model-parallel axis, the strict-mode dispatch label, whether AOT
    warmup should pre-compile it).

    Exactly one partitioning mode may be populated:
    ``in_specs``/``out_specs`` (shard_map) or ``out_shardings`` (jit
    auto-partitioning); with neither the program jits plain (single-
    device inference). ``param_specs`` is documentation-grade truth for
    the (dp, mp) layout — the pytree of `PartitionSpec`s the state
    placement used — not an input to compilation (the shardings ride the
    abstract inputs / out_shardings)."""

    mesh: Any = None
    # explicit shard_map mode (both or neither)
    in_specs: Any = None
    out_specs: Any = None
    # jit auto-partitioning mode
    out_shardings: Any = None
    donate_argnums: Tuple[int, ...] = ()
    # metadata
    param_specs: Any = None
    label: Optional[str] = None
    warmup: bool = True

    @property
    def mode(self) -> str:
        """"shard_map" | "pjit" | "jit" — what compile_step_with_plan does."""
        if self.in_specs is not None or self.out_specs is not None:
            return "shard_map"
        if self.out_shardings is not None:
            return "pjit"
        return "jit"

    @classmethod
    def validate(
        cls,
        config,
        n_devices: Optional[int] = None,
        process_count: Optional[int] = None,
    ) -> None:
        """Run the full decision table against a FasterRCNNConfig, raising
        ValueError on the first failing cell (and warning on warn-severity
        cells). The one entry point behind `parallel.validate_parallel`
        and `Trainer.__init__`."""
        ctx = PlanContext.from_config(
            config, n_devices=n_devices, process_count=process_count
        )
        apply_table(ctx)


def compile_step_with_plan(step_fn: Callable, plan: Plan):
    """(step_fn, plan) -> the jitted callable, via the plan's mode.

    The three wrappings reproduce the historical call sites byte-for-byte
    (fingerprint-pinned): shard_map plans wrap the per-shard body first;
    pjit plans jit with donation + out_shardings; bare plans jit plain.
    Empty donation / absent out_shardings are NOT passed through, so a
    bare plan lowers the identical program a bare ``jax.jit`` did."""
    if plan.mode == "shard_map":
        if plan.mesh is None:
            raise ValueError("a shard_map plan needs a mesh")
        if plan.in_specs is None or plan.out_specs is None:
            raise ValueError(
                "a shard_map plan needs both in_specs and out_specs"
            )
        shard_map_fn, no_check = _resolve_shard_map()
        step_fn = shard_map_fn(
            step_fn,
            mesh=plan.mesh,
            in_specs=plan.in_specs,
            out_specs=plan.out_specs,
            **no_check,
        )
    import jax

    kwargs = {}
    if plan.donate_argnums:
        kwargs["donate_argnums"] = plan.donate_argnums
    if plan.mode == "pjit":
        kwargs["out_shardings"] = plan.out_shardings
    return jax.jit(step_fn, **kwargs)


# --------------------------------------------------------- decision table


@dataclasses.dataclass(frozen=True)
class PlanContext:
    """The flattened inputs the compatibility table reads — a plain value
    object so every cell is testable without building a full config or
    initializing jax."""

    backend: str = "auto"
    optimizer: str = "adam"
    lars: bool = False
    shard_opt_state: bool = False
    cache_device: bool = False
    spatial: bool = False
    param_sharding: bool = False
    num_data: int = -1
    num_model: int = 1
    image_rows: int = 0
    batch_size: int = 0
    n_devices: int = 1
    process_count: int = 1
    train_buckets: int = 0  # len(data.train_resolutions); 0 = off
    # the actual bucket resolutions, for per-resolution cells (empty when
    # multi-scale is off; kept alongside train_buckets so cells that only
    # need the count stay constructible without inventing shapes)
    train_resolutions: Tuple[Tuple[int, int], ...] = ()

    @property
    def n_model(self) -> int:
        return max(1, self.num_model)

    @classmethod
    def from_config(
        cls,
        config,
        n_devices: Optional[int] = None,
        process_count: Optional[int] = None,
    ) -> "PlanContext":
        if n_devices is None or process_count is None:
            import jax

            if n_devices is None:
                n_devices = len(jax.devices())
            if process_count is None:
                process_count = jax.process_count()
        return cls(
            backend=config.train.backend,
            optimizer=config.train.optimizer,
            lars=config.train.lars,
            shard_opt_state=config.train.shard_opt_state,
            cache_device=config.data.cache_device,
            spatial=config.mesh.spatial,
            param_sharding=config.mesh.param_sharding,
            num_data=config.mesh.num_data,
            num_model=config.mesh.num_model,
            image_rows=config.data.image_size[0],
            batch_size=config.train.batch_size,
            n_devices=n_devices,
            process_count=process_count,
            train_buckets=len(config.data.train_resolutions),
            train_resolutions=tuple(
                tuple(r) for r in config.data.train_resolutions
            ),
        )


@dataclasses.dataclass(frozen=True)
class Cell:
    """One row of the table: a named predicate over PlanContext plus the
    uniform error (or warning) it produces when it fires."""

    name: str
    severity: str  # "error" | "warn"
    applies: Callable[[PlanContext], bool]
    message: Callable[[PlanContext], str]


# Ordered: earlier cells win when several fire (the order the scattered
# checks historically ran in: spatial, optimizer, multiprocess, mesh fit,
# model parallelism, device-cache feed). Messages are pinned by tests —
# change them only with their tests.
DECISION_TABLE: Tuple[Cell, ...] = (
    Cell(
        "model_axis_unused",
        "warn",
        lambda c: (
            not c.spatial and not c.param_sharding and c.num_model > 1
        ),
        lambda c: (
            f"mesh.num_model={c.num_model} with spatial=False: the model "
            f"axis carries no sharding, so {c.num_model - 1} of every "
            f"{c.num_model} chips duplicate work; pass --spatial or drop "
            "--num-model"
        ),
    ),
    Cell(
        "spatial_backend",
        "error",
        lambda c: c.spatial and c.backend == "spmd",
        lambda c: (
            "spatial partitioning requires the jit auto-partitioning "
            "backend (GSPMD places the conv halo exchanges); the "
            "explicit shard_map backend shards batch dims only"
        ),
    ),
    Cell(
        "spatial_num_model",
        "error",
        lambda c: c.spatial and c.num_model < 2,
        lambda c: (
            "spatial partitioning shards image rows over the model "
            "axis; set mesh.num_model >= 2 (--num-model), got "
            f"{c.num_model}"
        ),
    ),
    Cell(
        "spatial_rows",
        "error",
        lambda c: (
            c.spatial and c.num_model >= 2 and c.image_rows % c.num_model != 0
        ),
        lambda c: (
            "spatial partitioning needs image rows "
            f"({c.image_rows}) divisible by the model "
            f"axis ({c.num_model})"
        ),
    ),
    Cell(
        "lamb_lars",
        "error",
        lambda c: c.optimizer == "lamb" and c.lars,
        lambda c: (
            "optimizer='lamb' already applies the per-layer trust "
            "ratio after Adam; combining it with lars=True would "
            "rescale twice — drop one"
        ),
    ),
    Cell(
        "lars_sharded_spmd",
        "error",
        lambda c: c.shard_opt_state and c.backend == "spmd" and c.lars,
        lambda c: (
            "lars trust ratios need full-leaf norms, but the shard_map "
            "ZeRO-1 backend updates 1/N parameter slices (partial norms); "
            "use the jit auto-partitioning backend (backend='auto') for "
            "lars + shard_opt_state"
        ),
    ),
    Cell(
        "spatial_multiprocess",
        "error",
        lambda c: c.process_count > 1 and c.spatial,
        lambda c: (
            "spatial partitioning is single-process only: the "
            "per-process feed ships batch rows, not image-row shards"
        ),
    ),
    Cell(
        "multiprocess_batch",
        "error",
        lambda c: c.process_count > 1 and c.batch_size % c.process_count != 0,
        lambda c: (
            f"global batch_size={c.batch_size} must divide "
            f"evenly over {c.process_count} processes (each feeds "
            "its own contiguous rows of the global batch)"
        ),
    ),
    Cell(
        "mesh_fit",
        "error",
        lambda c: c.num_data > 0 and c.num_data * c.n_model > c.n_devices,
        lambda c: (
            f"mesh {c.num_data}x{c.n_model} needs "
            f"{c.num_data * c.n_model} "
            f"device(s) but only {c.n_devices} are available"
        ),
    ),
    Cell(
        "model_axis_width",
        "error",
        lambda c: c.num_data <= 0 and c.n_model > c.n_devices,
        lambda c: (
            f"num_model={c.n_model} exceeds the {c.n_devices} available "
            "device(s); the model axis cannot be wider than the mesh"
        ),
    ),
    Cell(
        "model_axis_divide",
        "error",
        lambda c: c.num_data <= 0 and c.n_devices % c.n_model != 0,
        lambda c: (
            f"{c.n_devices} device(s) cannot be split evenly into model "
            f"groups of {c.n_model}; pick num_model dividing {c.n_devices}"
        ),
    ),
    Cell(
        "mp_backend",
        "error",
        lambda c: c.param_sharding and c.backend == "spmd",
        lambda c: (
            "model-parallel parameter sharding (mesh.param_sharding / "
            "--mesh-shape) requires the jit auto-partitioning backend "
            "(GSPMD places the weight all-gathers); the explicit "
            "shard_map backend shards batch dims only"
        ),
    ),
    Cell(
        "mp_spatial",
        "error",
        lambda c: c.param_sharding and c.spatial,
        lambda c: (
            "param_sharding and spatial both claim the model axis; "
            "pick ONE sharding story per mesh axis (--mesh-shape for "
            "weights, --spatial for image rows)"
        ),
    ),
    Cell(
        "mp_cache",
        "error",
        lambda c: c.param_sharding and c.cache_device,
        lambda c: (
            "cache_device pairs with replicated parameters; the "
            "model-parallel feed (--mesh-shape with MP > 1) uses the "
            "host loader — drop --cache-device or --mesh-shape"
        ),
    ),
    Cell(
        "cache_backend",
        "error",
        lambda c: c.cache_device and c.backend == "spmd",
        lambda c: (
            "cache_device currently pairs with the jit auto-"
            "partitioned backend only (train.backend='auto'); the "
            "explicit shard_map backend feeds host batches"
        ),
    ),
    # Bucketed multi-scale composes with every backend: the shard_map
    # in/out specs shard batch dims only, so they are resolution-
    # independent, and each bucket compiles its own program with the
    # resample traced into the body (train/warmup.py bucket builders).
    # The only genuine constraint is spatial row divisibility, checked
    # PER RESOLUTION below — a bucket set is rejected only when a named
    # resolution actually violates it.
    Cell(
        "buckets_spatial_rows",
        "error",
        lambda c: (
            c.train_buckets > 0
            and c.spatial
            and c.num_model >= 2
            and any(r[0] % c.num_model != 0 for r in c.train_resolutions)
        ),
        lambda c: (
            "spatial partitioning needs every bucket's image rows "
            f"divisible by the model axis ({c.num_model}); offending "
            "data.train_resolutions: "
            + ", ".join(
                f"{r[0]}x{r[1]} ({r[0]} rows)"
                for r in c.train_resolutions
                if r[0] % c.num_model != 0
            )
        ),
    ),
    Cell(
        "cache_multiprocess",
        "error",
        lambda c: c.cache_device and c.process_count > 1,
        lambda c: (
            "cache_device requires a single-process runtime: "
            "DeviceCache device_puts the full dataset from this "
            "host to a replicated sharding, which one process "
            "cannot place across a multi-host mesh. Drop "
            "--cache-device (use the host loader, optionally with "
            "device_normalize) on multi-host runs."
        ),
    ),
)


def check_cells(ctx: PlanContext, names: Optional[Tuple[str, ...]] = None):
    """Every firing cell (optionally restricted to ``names``), in table
    order, as (cell, message) pairs. Pure — no raising, no warning."""
    out = []
    for cell in DECISION_TABLE:
        if names is not None and cell.name not in names:
            continue
        if cell.applies(ctx):
            out.append((cell, cell.message(ctx)))
    return out


def apply_table(
    ctx: PlanContext, names: Optional[Tuple[str, ...]] = None
) -> None:
    """Evaluate the table: warn on warn-severity cells, raise ValueError
    on the first error cell (table order)."""
    for cell, message in check_cells(ctx, names):
        if cell.severity == "warn":
            warnings.warn(message, stacklevel=3)
        else:
            raise ValueError(message)


SPATIAL_CELLS: Tuple[str, ...] = (
    "model_axis_unused",
    "spatial_backend",
    "spatial_num_model",
    "spatial_rows",
    "buckets_spatial_rows",
)
