from replication_faster_rcnn_tpu.parallel.mesh import (  # noqa: F401
    batch_sharding,
    fit_data_parallelism,
    gather_replicated,
    image_sharding,
    initialize_distributed,
    is_coordinator,
    make_mesh,
    replicate_tree,
    replicated,
    shard_batch,
    shard_stacked_batch,
    stacked_batch_sharding,
    stage_to_devices,
    validate_parallel,
    validate_spatial,
)
from replication_faster_rcnn_tpu.parallel.plan import (  # noqa: F401
    Plan,
    PlanContext,
    compile_step_with_plan,
)
from replication_faster_rcnn_tpu.parallel.spmd import (  # noqa: F401
    make_shard_map_train_step,
)
