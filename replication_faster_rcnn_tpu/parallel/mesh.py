"""Device mesh + sharding utilities — the framework's distributed backbone.

The reference has no distributed support at all (SURVEY.md §2.4: no DDP, no
torch.distributed, no NCCL); this module provides the TPU-native equivalent
the BASELINE north star names: a `jax.sharding.Mesh` over the chips, batch
dimensions sharded over the ``data`` axis, parameters replicated, and
gradient all-reduce carried by XLA collectives over ICI/DCN. Everything
goes through `jax.jit` auto-partitioning: we annotate shardings,
XLA inserts the psums (the scaling-book recipe).

A ``model`` axis exists in the mesh so tensor-parallel shardings can be
introduced without re-plumbing (MeshConfig.num_model > 1); the detection
workload itself is data-parallel.

Multi-host: `initialize_distributed()` wraps `jax.distributed.initialize`,
after which `jax.devices()` spans all hosts and the same mesh/sharding code
scales out over DCN unchanged. Each process feeds only its own rows of the
global batch (the loaders shard deterministically by ``process_index``) and
`shard_batch`/`shard_stacked_batch` assemble them into one global array via
`jax.make_array_from_process_local_data`; job-wide writes (checkpoints,
manifests, telemetry) are gated on `is_coordinator()`.
"""

from __future__ import annotations

import functools
import os
from typing import Any, Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from replication_faster_rcnn_tpu.config import MeshConfig
from replication_faster_rcnn_tpu.faultlib import failpoints


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Multi-host setup (XLA collectives over DCN). Single-host runs skip
    this — jax.devices() already shows every local chip."""
    # failpoint: a chaos schedule can fail or delay collective bring-up
    # (the classic flaky-coordinator scenario) before any JAX state
    # exists; a ``drop`` whose arg names this rank kills it at bring-up
    # (seeded rank loss — the elastic supervisor's casualty path)
    inj = failpoints.fire(
        "collective.init",
        num_processes=num_processes,
        process_id=process_id,
    )
    if (
        inj is not None
        and inj.kind == "drop"
        and process_id is not None
        and int(inj.arg) == int(process_id)
    ):
        os._exit(1)
    if num_processes is None:
        num_processes = int(os.environ.get("NUM_PROCESSES", "1"))
    if num_processes > 1:
        # the CPU backend ships no cross-process collectives by default
        # ("Multiprocess computations aren't implemented on the CPU
        # backend"); gloo is the supported implementation and a no-op on
        # accelerator platforms, where collectives ride ICI/DCN
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except Exception:
            pass  # older/newer jaxlib without the option: keep defaults
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )


def is_coordinator() -> bool:
    """True on the process that owns job-wide side effects: checkpoint
    manifests, metric/telemetry files, progress logs. THE guard for
    multi-process writes — route every ``process_index() == 0`` check
    through here so the coordinator policy has one definition."""
    return jax.process_index() == 0


def fit_data_parallelism(batch_size: int, n_devices: int) -> int:
    """Largest data-parallel degree <= n_devices that divides batch_size.

    A batch that does not divide over the mesh fails inside jit with an
    opaque sharding error (the reference's default batch of 2 on an 8-chip
    host, for instance); shrinking the data axis to the largest usable
    divisor keeps small-batch runs working, at reduced parallelism.
    """
    for d in range(min(batch_size, n_devices), 0, -1):
        if batch_size % d == 0:
            return d
    return 1


def validate_spatial(config) -> None:
    """Reject configs where spatial partitioning would silently do nothing
    or cannot work (shared by the Trainer and the benchmark so every
    entry point fails the same way). The spatial rows of the
    `parallel/plan.py` decision table.

    Args: config — a full FasterRCNNConfig.
    """
    from replication_faster_rcnn_tpu.parallel.plan import (
        SPATIAL_CELLS,
        PlanContext,
        apply_table,
    )

    apply_table(PlanContext.from_config(config), names=SPATIAL_CELLS)


def validate_parallel(config, n_devices: Optional[int] = None) -> None:
    """All parallelism config checks shared by every entry point (Trainer,
    benchmark): spatial partitioning constraints, backend/feed/optimizer
    conflicts, model-parallel constraints, and mesh-vs-device-count fit —
    the full `parallel/plan.py` decision table (``Plan.validate``).
    ``n_devices`` defaults to every visible device; pass the size of an
    explicit device subset if using one."""
    from replication_faster_rcnn_tpu.parallel.plan import Plan

    Plan.validate(config, n_devices=n_devices)


def make_mesh(cfg: MeshConfig, devices: Optional[Sequence[Any]] = None) -> Mesh:
    """Build the (data, model) mesh. num_data == -1 uses every device."""
    devices = list(devices if devices is not None else jax.devices())
    num_model = max(1, cfg.num_model)
    num_data = cfg.num_data if cfg.num_data > 0 else len(devices) // num_model
    if num_data * num_model > len(devices):
        raise ValueError(
            f"mesh {num_data}x{num_model} needs more than {len(devices)} devices"
        )
    grid = np.asarray(devices[: num_data * num_model]).reshape(num_data, num_model)
    return Mesh(grid, (cfg.data_axis, cfg.model_axis))


def batch_sharding(mesh: Mesh, cfg: MeshConfig) -> NamedSharding:
    """Leading (batch) dim sharded over the data axis."""
    return NamedSharding(mesh, P(cfg.data_axis))


def image_sharding(mesh: Mesh, cfg: MeshConfig) -> NamedSharding:
    """Sharding for NHWC image tensors. With ``cfg.spatial`` the row (H)
    dimension is additionally sharded over the ``model`` axis — spatial
    partitioning, the detector's analogue of sequence parallelism (see
    MeshConfig). GSPMD then partitions every conv in the trunk spatially,
    inserting halo exchanges (ICI collective-permutes of the boundary rows)
    where a kernel window crosses shards."""
    if cfg.spatial and mesh.shape[cfg.model_axis] > 1:
        return NamedSharding(mesh, P(cfg.data_axis, cfg.model_axis))
    return batch_sharding(mesh, cfg)


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def stacked_batch_sharding(mesh: Mesh, cfg: MeshConfig) -> NamedSharding:
    """Sharding for a fused-dispatch chunk stacked [K, B, ...]: the NEW
    leading step axis is replicated (every chip runs all K fused steps),
    the axis-1 batch dim shards over the data axis."""
    return NamedSharding(mesh, P(None, cfg.data_axis))


def _put_sharded(x: np.ndarray, sharding: NamedSharding, batch_dim: int) -> jax.Array:
    """Stage one host array onto a batch-sharded layout.

    Single-process: a plain ``device_put``. Multi-process: ``x`` holds only
    THIS process's contiguous rows of the global batch (the loaders shard
    by ``process_index``), and `jax.make_array_from_process_local_data`
    assembles the global array — each process's rows land on its own
    addressable devices, matching the mesh's process-contiguous device
    order, with no cross-host data movement."""
    if jax.process_count() == 1:
        return jax.device_put(x, sharding)
    x = np.ascontiguousarray(x)
    shape = list(np.shape(x))
    shape[batch_dim] *= jax.process_count()
    return jax.make_array_from_process_local_data(sharding, x, tuple(shape))


def shard_stacked_batch(
    batch: Dict[str, np.ndarray], mesh: Mesh, cfg: MeshConfig
) -> Dict[str, jax.Array]:
    """`shard_batch` for a K-step fused-dispatch chunk: host arrays are
    stacked [K, B, ...] (K per-step batches or device-cache selections),
    so the batch dim to shard is axis 1, not the leading axis. Image
    tensors additionally shard rows (now axis 2) over the model axis when
    spatial partitioning is on."""
    sharding = stacked_batch_sharding(mesh, cfg)
    if cfg.spatial and mesh.shape[cfg.model_axis] > 1:
        img_sharding = NamedSharding(
            mesh, P(None, cfg.data_axis, cfg.model_axis)
        )
    else:
        img_sharding = sharding

    def put(k: str, x: np.ndarray) -> jax.Array:
        return _put_sharded(x, img_sharding if k == "image" else sharding, 1)

    return {k: put(k, v) for k, v in batch.items()}


def shard_batch(
    batch: Dict[str, np.ndarray], mesh: Mesh, cfg: MeshConfig
) -> Dict[str, jax.Array]:
    """Host batch -> device arrays with the batch dim laid out over the data
    axis (each chip receives only its shard; XLA's equivalent of DDP's
    per-rank loader). Image tensors additionally shard rows over the model
    axis when spatial partitioning is on (`image_sharding`). Multi-process,
    each process passes its local rows only (`_put_sharded`)."""
    sharding = batch_sharding(mesh, cfg)
    img_sharding = image_sharding(mesh, cfg)

    def put(k: str, x: np.ndarray) -> jax.Array:
        return _put_sharded(x, img_sharding if k == "image" else sharding, 0)

    return {k: put(k, v) for k, v in batch.items()}


def stage_to_devices(
    batch: Dict[str, np.ndarray],
    mesh: Mesh,
    cfg: MeshConfig,
    stacked: bool = False,
    wait: bool = False,
) -> Dict[str, jax.Array]:
    """Ship a host batch to the mesh (`shard_batch`, or
    `shard_stacked_batch` for a ``stacked`` [K, B, ...] fused-dispatch
    chunk), optionally blocking until the transfer has landed.

    ``jax.device_put`` only *enqueues* the copy; with ``wait=True`` the
    call returns once every leaf is device-resident. That is the overlap
    primitive for the double-buffered stager (data/prefetch_device.py):
    the producer thread pays the H2D wait, so by the time the trainer
    dequeues the batch its dispatch consumes resident buffers and the
    transfer is fully off the critical path."""
    out = (shard_stacked_batch if stacked else shard_batch)(batch, mesh, cfg)
    if wait:
        for leaf in jax.tree_util.tree_leaves(out):
            leaf.block_until_ready()
    return out


def put_host_tree(tree: Any, shardings: Any) -> Any:
    """Place host values onto (possibly cross-process) shardings.

    Single-process: one batched ``device_put``. Multi-process: a plain
    ``device_put`` onto shardings that span other processes issues
    untagged gloo collectives whose per-leaf order differs between ranks
    (observed as `op.preamble.length <= op.nbytes` aborts in the
    2-process ZeRO preemption test); `jax.make_array_from_callback`
    instead builds every leaf from THIS process's slice of the host copy
    — purely local, no wire traffic, identical on every topology.
    ``shardings`` is a matching pytree of shardings or one sharding for
    the whole tree."""
    if jax.process_count() == 1:
        return jax.device_put(tree, shardings)
    if isinstance(shardings, jax.sharding.Sharding):
        shardings = jax.tree_util.tree_map(lambda _: shardings, tree)

    def put(leaf, sharding):
        arr = np.asarray(leaf)
        return jax.make_array_from_callback(
            arr.shape, sharding, lambda idx, a=arr: a[idx]
        )

    return jax.tree_util.tree_map(put, tree, shardings)


def replicate_tree(tree: Any, mesh: Mesh) -> Any:
    """Place a pytree fully-replicated on the mesh (params, opt state)."""
    return put_host_tree(tree, replicated(mesh))


@functools.lru_cache(maxsize=None)
def _gather_fn(sharding: NamedSharding):
    # one stable jit instance per target sharding, so repeated checkpoint
    # events hit the jit cache instead of re-tracing a fresh lambda
    return jax.jit(lambda t: t, out_shardings=sharding)


def gather_replicated(tree: Any, mesh: Mesh) -> Any:
    """All-gather a (possibly cross-process sharded) pytree to fully
    replicated via a compiled identity.

    `jax.device_put` resharding works within one process but DEADLOCKS
    when the source shards live on other processes' devices (observed in
    the 2-process ZeRO checkpoint test: both workers hung inside
    `_host_state`); a jitted identity with replicated out_shardings
    compiles to an explicit all-gather that every process executes
    collectively, which is the supported cross-process path."""
    return _gather_fn(replicated(mesh))(tree)
