"""Cross-replica weight-update (optimizer-state) sharding — ZeRO-1 on XLA.

The technique of "Automatic Cross-Replica Sharding of Weight Update in
Data-Parallel Training" (Xu et al., arXiv:2004.13336, developed for TPUs and
cited in PAPERS.md): in data-parallel training every replica holds a full
copy of the Adam moments and performs the identical weight update. Sharding
the optimizer state over the ``data`` axis removes that redundancy — each
chip stores and updates only its 1/N slice of mu/nu and of the updated
parameters, and GSPMD turns the gradient allreduce into
reduce-scatter + all-gather around the update (same bytes on the wire as a
plain allreduce, 1/N of the update FLOPs and moment memory per chip).

Two implementations share the leaf layout below (`shard_dim`):

* **jit auto-partitioning backend** — expressed purely through sharding
  annotations (the GSPMD recipe, no manual collectives): optimizer-state
  leaves get a ``NamedSharding`` that splits their largest
  evenly-divisible dimension over the data axis; parameters stay
  replicated in the step's out_shardings, so the forward pass is
  unchanged. ``jax.jit`` then places the reduce-scatter/all-gather
  automatically.
* **explicit shard_map backend** — `parallel/spmd.py` places the same
  collectives BY HAND (`lax.psum_scatter` of the gradients into per-shard
  slices, sliced Adam update, `lax.all_gather` of the updated parameter
  slices), against per-leaf shard_map in/out_specs built from the same
  `shard_dim` rule, so a checkpoint moves between backends without
  re-sharding.

Enabled by ``train.shard_opt_state`` / CLI ``--shard-opt`` on either
backend.
"""

from __future__ import annotations

from typing import Any, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from replication_faster_rcnn_tpu.config import MeshConfig


def shard_dim(shape: Sequence[int], n: int) -> int:
    """The dimension ZeRO-1 splits over an ``n``-way data axis: the
    largest dim divisible by ``n``, or -1 when the leaf must stay
    replicated (scalars, indivisible shapes, n <= 1). Single source of
    the layout rule — both the GSPMD annotations here and the shard_map
    backend's hand-placed collectives (`parallel/spmd.py`) key off it."""
    if n <= 1 or not shape:
        return -1
    divisible = [d for d, s in enumerate(shape) if s % n == 0 and s >= n]
    if not divisible:
        return -1
    return max(divisible, key=lambda d: shape[d])


def shard_spec(shape: Sequence[int], n: int, axis_name: str) -> P:
    """`shard_dim` as a PartitionSpec (replicated P() when unshardable)."""
    d = shard_dim(shape, n)
    if d < 0:
        return P()
    spec = [None] * len(shape)
    spec[d] = axis_name
    return P(*spec)


def compose_spec(
    shape: Sequence[int],
    n_data: int,
    n_model: int,
    data_axis: str,
    model_axis: str,
) -> P:
    """The (dp, mp)-composed PartitionSpec for one leaf: the model axis
    takes the leaf's `shard_dim` under ``n_model`` (the mp weight layout),
    then the data axis takes the largest remaining dim divisible by
    ``n_data`` (ZeRO-1 over dp, displaced off the mp dim). With
    ``n_model <= 1`` this degenerates EXACTLY to `shard_spec` over the
    data axis — the dp-only layout every committed fingerprint pins."""
    mp_d = shard_dim(shape, n_model)
    spec = [None] * len(shape)
    if mp_d >= 0:
        spec[mp_d] = model_axis
    if n_data > 1:
        cands = [
            d
            for d, s in enumerate(shape)
            if d != mp_d and s % n_data == 0 and s >= n_data
        ]
        if cands:
            spec[max(cands, key=lambda d: shape[d])] = data_axis
    if not any(spec):
        return P()
    return P(*spec)


def _leaf_sharding(leaf: Any, mesh: Mesh, cfg: MeshConfig) -> NamedSharding:
    """Shard the largest dim divisible by the data-axis size; scalars and
    indivisible shapes stay replicated. Under ``param_sharding`` the model
    axis claims its dim first (`compose_spec`) so the moments mirror the
    mp weight layout and ZeRO-dp moves to a remaining dim."""
    n = mesh.shape[cfg.data_axis]
    n_mp = mesh.shape[cfg.model_axis] if cfg.param_sharding else 1
    return NamedSharding(
        mesh,
        compose_spec(
            np.shape(leaf), n, n_mp, cfg.data_axis, cfg.model_axis
        ),
    )


def opt_state_shardings(opt_state: Any, mesh: Mesh, cfg: MeshConfig) -> Any:
    """Pytree of shardings for the optimizer state (leafwise rule above)."""
    return jax.tree_util.tree_map(
        lambda leaf: _leaf_sharding(leaf, mesh, cfg), opt_state
    )


def param_shardings(params: Any, mesh: Mesh, cfg: MeshConfig) -> Any:
    """Model-parallel per-module parameter shardings: every leaf splits
    its largest mp-divisible dim over the ``model`` axis (the same
    `shard_dim` rule ZeRO-1 applies on the data axis), indivisible leaves
    stay replicated. This is the (dp, mp) tentpole's weight layout — each
    chip holds ~1/num_model of the backbone/head weights and GSPMD
    inserts the all-gathers the forward needs."""
    n = mesh.shape[cfg.model_axis]
    return jax.tree_util.tree_map(
        lambda leaf: NamedSharding(
            mesh, shard_spec(np.shape(leaf), n, cfg.model_axis)
        ),
        params,
    )


def train_state_shardings(
    state: Any, mesh: Mesh, cfg: MeshConfig, shard_opt: bool
) -> Any:
    """Shardings for a full TrainState: BN stats/step/rng replicated,
    params replicated (or mp-sharded over the model axis under
    ``cfg.param_sharding``), optimizer state leafwise-sharded when
    ``shard_opt``. Usable as both the jit in_shardings (via device_put)
    and out_shardings — the state layout is then stable across steps
    under donation."""
    replicated = NamedSharding(mesh, P())
    full = jax.tree_util.tree_map(lambda _: replicated, state)
    if cfg.param_sharding and mesh.shape[cfg.model_axis] > 1:
        full = full.replace(
            params=param_shardings(state.params, mesh, cfg)
        )
    if not shard_opt:
        return full
    return full.replace(opt_state=opt_state_shardings(state.opt_state, mesh, cfg))


def place_train_state(state: Any, shardings: Any) -> Any:
    """Place the whole state pytree onto its target shardings (one batched
    device_put single-process; a local per-shard build on multi-process
    runs — see `mesh.put_host_tree`)."""
    from replication_faster_rcnn_tpu.parallel.mesh import put_host_tree

    return put_host_tree(state, shardings)
