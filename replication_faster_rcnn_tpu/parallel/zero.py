"""Cross-replica weight-update (optimizer-state) sharding — ZeRO-1 on XLA.

The technique of "Automatic Cross-Replica Sharding of Weight Update in
Data-Parallel Training" (Xu et al., arXiv:2004.13336, developed for TPUs and
cited in PAPERS.md): in data-parallel training every replica holds a full
copy of the Adam moments and performs the identical weight update. Sharding
the optimizer state over the ``data`` axis removes that redundancy — each
chip stores and updates only its 1/N slice of mu/nu and of the updated
parameters, and GSPMD turns the gradient allreduce into
reduce-scatter + all-gather around the update (same bytes on the wire as a
plain allreduce, 1/N of the update FLOPs and moment memory per chip).

Two implementations share the leaf layout below (`shard_dim`):

* **jit auto-partitioning backend** — expressed purely through sharding
  annotations (the GSPMD recipe, no manual collectives): optimizer-state
  leaves get a ``NamedSharding`` that splits their largest
  evenly-divisible dimension over the data axis; parameters stay
  replicated in the step's out_shardings, so the forward pass is
  unchanged. ``jax.jit`` then places the reduce-scatter/all-gather
  automatically.
* **explicit shard_map backend** — `parallel/spmd.py` places the same
  collectives BY HAND (`lax.psum_scatter` of the gradients into per-shard
  slices, sliced Adam update, `lax.all_gather` of the updated parameter
  slices), against per-leaf shard_map in/out_specs built from the same
  `shard_dim` rule, so a checkpoint moves between backends without
  re-sharding.

Enabled by ``train.shard_opt_state`` / CLI ``--shard-opt`` on either
backend.
"""

from __future__ import annotations

from typing import Any, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from replication_faster_rcnn_tpu.config import MeshConfig


def shard_dim(shape: Sequence[int], n: int) -> int:
    """The dimension ZeRO-1 splits over an ``n``-way data axis: the
    largest dim divisible by ``n``, or -1 when the leaf must stay
    replicated (scalars, indivisible shapes, n <= 1). Single source of
    the layout rule — both the GSPMD annotations here and the shard_map
    backend's hand-placed collectives (`parallel/spmd.py`) key off it."""
    if n <= 1 or not shape:
        return -1
    divisible = [d for d, s in enumerate(shape) if s % n == 0 and s >= n]
    if not divisible:
        return -1
    return max(divisible, key=lambda d: shape[d])


def shard_spec(shape: Sequence[int], n: int, axis_name: str) -> P:
    """`shard_dim` as a PartitionSpec (replicated P() when unshardable)."""
    d = shard_dim(shape, n)
    if d < 0:
        return P()
    spec = [None] * len(shape)
    spec[d] = axis_name
    return P(*spec)


def _leaf_sharding(leaf: Any, mesh: Mesh, cfg: MeshConfig) -> NamedSharding:
    """Shard the largest dim divisible by the data-axis size; scalars and
    indivisible shapes stay replicated."""
    n = mesh.shape[cfg.data_axis]
    return NamedSharding(mesh, shard_spec(np.shape(leaf), n, cfg.data_axis))


def opt_state_shardings(opt_state: Any, mesh: Mesh, cfg: MeshConfig) -> Any:
    """Pytree of shardings for the optimizer state (leafwise rule above)."""
    return jax.tree_util.tree_map(
        lambda leaf: _leaf_sharding(leaf, mesh, cfg), opt_state
    )


def train_state_shardings(
    state: Any, mesh: Mesh, cfg: MeshConfig, shard_opt: bool
) -> Any:
    """Shardings for a full TrainState: params/BN stats/step/rng replicated,
    optimizer state leafwise-sharded when ``shard_opt``. Usable as both the
    jit in_shardings (via device_put) and out_shardings — the state layout
    is then stable across steps under donation."""
    replicated = NamedSharding(mesh, P())
    full = jax.tree_util.tree_map(lambda _: replicated, state)
    if not shard_opt:
        return full
    return full.replace(opt_state=opt_state_shardings(state.opt_state, mesh, cfg))


def place_train_state(state: Any, shardings: Any) -> Any:
    """Place the whole state pytree onto its target shardings (one batched
    device_put single-process; a local per-shard build on multi-process
    runs — see `mesh.put_host_tree`)."""
    from replication_faster_rcnn_tpu.parallel.mesh import put_host_tree

    return put_host_tree(state, shardings)
