"""RPN (first-stage) target assignment — device-side, fixed-shape.

Capability parity with reference ``AnchorTargetCreator``
(`utils/utils.py:122-204`), redesigned to run inside the jitted train step
(the reference runs it per-image in host numpy inside the training loop,
`train.py:71-79` — SURVEY.md layering violation #1):

  * label -1 = ignore (default), 0 = negative (max IoU < neg_thresh),
    1 = positive (max IoU >= pos_thresh)           (`utils/utils.py:181-189`)
  * each gt's best-overlapping anchor is force-positive, and its regression
    target points at that gt                        (`utils/utils.py:169-173,187-189`)
  * random subsample: at most pos_ratio * n_sample positives, negatives
    fill the rest of n_sample                       (`utils/utils.py:190-202`)
  * regression targets encode(anchor, matched gt) for ALL anchors; zeros
    when the image has no gt                        (`utils/utils.py:145-150,162-163`)

GT boxes arrive padded to a fixed max count with a validity mask (the data
pipeline pads with -1 labels, reference `utils/data_loader.py:88-89`).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from replication_faster_rcnn_tpu.config import RPNTargetConfig
from replication_faster_rcnn_tpu.ops import boxes as box_ops
from replication_faster_rcnn_tpu.targets.sampling import random_subset_mask

Array = jnp.ndarray


def anchor_targets(
    rng: Array,
    gt_boxes: Array,
    gt_mask: Array,
    anchors: Array,
    cfg: RPNTargetConfig,
) -> Tuple[Array, Array]:
    """Per-image RPN targets.

    Args:
      rng: PRNG key (subsampling).
      gt_boxes: [G, 4] padded gt boxes; gt_mask: [G] bool validity.
      anchors: [A, 4].
      cfg: thresholds/budgets.

    Returns:
      (reg_targets [A, 4] float32, labels [A] int32 in {-1, 0, 1}).
    """
    a = anchors.shape[0]
    has_gt = jnp.any(gt_mask)

    from replication_faster_rcnn_tpu import ops as ops_pkg

    if ops_pkg.want_pallas("anchor_match"):
        # the fused matching kernel: same ious/argmax/max/column-argmax as
        # the jnp lines below (tests/test_pallas_iou.py pins all four)
        from replication_faster_rcnn_tpu.ops.pallas import match_boxes_pallas

        ious, argmax, max_iou, gt_best_anchor = match_boxes_pallas(
            anchors, gt_boxes, gt_mask, interpret=ops_pkg.interpret_mode()
        )
    else:
        ious = box_ops.iou(anchors, gt_boxes)  # [A, G]
        ious = jnp.where(gt_mask[None, :], ious, -1.0)  # never match padded gt

        argmax = jnp.argmax(ious, axis=1)  # [A] best gt per anchor
        max_iou = jnp.max(jnp.maximum(ious, 0.0), axis=1)  # [A]

        # Force-positive each gt's best anchor and redirect its match to
        # that gt (`utils/utils.py:169-173`).
        gt_best_anchor = jnp.argmax(ious, axis=0)  # [G]

    scatter_rows = jnp.where(gt_mask, gt_best_anchor, a)  # a = dropped
    argmax = argmax.at[scatter_rows].set(
        jnp.arange(gt_boxes.shape[0], dtype=jnp.int32), mode="drop"
    )
    forced = jnp.zeros((a,), bool).at[scatter_rows].set(True, mode="drop")

    labels = jnp.full((a,), -1, jnp.int32)
    labels = jnp.where(max_iou < cfg.neg_iou_thresh, 0, labels)
    labels = jnp.where(max_iou >= cfg.pos_iou_thresh, 1, labels)
    labels = jnp.where(forced & has_gt, 1, labels)

    # Subsample (`utils/utils.py:190-202`): cap positives at n_pos, then
    # negatives fill to n_sample.
    n_pos = int(cfg.pos_ratio * cfg.n_sample)
    rng_pos, rng_neg = jax.random.split(rng)
    pos_keep = random_subset_mask(rng_pos, labels == 1, n_pos, k_max=n_pos)
    labels = jnp.where((labels == 1) & ~pos_keep, -1, labels)
    n_neg = cfg.n_sample - jnp.sum(labels == 1)
    neg_keep = random_subset_mask(rng_neg, labels == 0, n_neg, k_max=cfg.n_sample)
    labels = jnp.where((labels == 0) & ~neg_keep, -1, labels)

    reg = box_ops.encode(anchors, gt_boxes[argmax])
    reg = jnp.where(has_gt, reg, 0.0)  # empty-gt path (`utils/utils.py:162-163`)
    labels = jnp.where(has_gt, labels, jnp.where(labels == 1, -1, labels))
    return reg.astype(jnp.float32), labels


def batched_anchor_targets(
    rng: Array,
    gt_boxes: Array,
    gt_mask: Array,
    anchors: Array,
    cfg: RPNTargetConfig,
    positions: Array = None,
) -> Tuple[Array, Array]:
    """vmap over the batch: gt_boxes [N, G, 4], gt_mask [N, G] ->
    (reg [N, A, 4], labels [N, A]).

    ``positions`` (global batch positions, [N] int) makes the per-image
    keys sharding-invariant — fold_in(rng, position) gives each image the
    same key whether the batch is whole (jit auto-partitioning) or a
    shard_map slice (`parallel/spmd.py`). Without it, keys are split by
    local batch size (fine when every caller sees the full batch).
    """
    if positions is None:
        keys = jax.random.split(rng, gt_boxes.shape[0])
    else:
        keys = jax.vmap(lambda p: jax.random.fold_in(rng, p))(positions)
    return jax.vmap(lambda k, b, m: anchor_targets(k, b, m, anchors, cfg))(
        keys, gt_boxes, gt_mask
    )
