"""Second-stage (head) target assignment — device-side, fixed-shape.

Capability parity with reference ``ProposalTargetCreator``
(`utils/utils.py:207-276`), redesigned to run inside the jitted train step
(the reference syncs rois to host numpy per image, `utils/utils.py:230`,
`train.py:91-104`):

  * gt boxes join the roi pool ("add the true boxes to the rois",
    `utils/utils.py:229-230`)
  * positives: IoU >= pos_iou_thresh, capped at round(n_sample * pos_ratio)
    by uniform subsampling                          (`utils/utils.py:248-251`)
  * negatives: neg_low <= IoU < neg_high, fill to n_sample
                                                    (`utils/utils.py:253-258`)
  * sampled negative labels are background 0        (`utils/utils.py:275`)
  * regression targets encode(sample_roi, matched gt), normalized by
    (mean, std)                                     (`utils/utils.py:269-272`)

Deliberate fix (SURVEY.md §2.1 #5): the reference's output length is
whatever the sampling produced, while its trainer assumes exactly n_sample
(`train.py:102`) — a latent shape bug. Here the output is always exactly
``n_sample`` slots, packed positives-first, negatives next, and any deficit
filled with label -1 (ignored by the loss) and zero rois.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from replication_faster_rcnn_tpu.config import ROITargetConfig
from replication_faster_rcnn_tpu.ops import boxes as box_ops
from replication_faster_rcnn_tpu.targets.sampling import (
    pack_by_priority,
    random_subset_mask,
    topk_subset_mask,
)

Array = jnp.ndarray


def proposal_targets(
    rng: Array,
    rois: Array,
    roi_valid: Array,
    gt_boxes: Array,
    gt_labels: Array,
    gt_mask: Array,
    cfg: ROITargetConfig,
    strategy: str = "random",
) -> Tuple[Array, Array, Array]:
    """Per-image head targets.

    Args:
      rois: [R, 4] proposals (padded); roi_valid: [R] bool.
      gt_boxes: [G, 4]; gt_labels: [G] int (1..C-1, 0/-1 pad); gt_mask: [G].
      strategy: region-sampling strategy (train.sampling_strategy, a
        STATIC trace-time choice): "random" draws the quotas uniformly
        (the reference recipe — this path is byte-identical to the
        pre-knob programs); "topk_iou" keeps the highest-IoU positives
        and the hardest (highest-IoU-below-threshold) negatives
        deterministically (arXiv:1702.02138 biased sampling).

    Returns:
      sample_rois [n_sample, 4], reg_targets [n_sample, 4] (normalized),
      labels [n_sample] int32 — gt class for positives, 0 for sampled
      negatives, -1 for filler slots (loss-ignored).
    """
    n_sample = cfg.n_sample

    cand = jnp.concatenate([rois, gt_boxes], axis=0)  # [R+G, 4]
    cand_valid = jnp.concatenate([roi_valid, gt_mask], axis=0)

    from replication_faster_rcnn_tpu import ops as ops_pkg

    if ops_pkg.want_pallas("proposal_match"):
        # fused IoU + row reductions (no column argmax needed here); same
        # values as the jnp lines below (tests/test_pallas_iou.py)
        from replication_faster_rcnn_tpu.ops.pallas import iou_matrix_pallas

        ious, assignment, max_iou = iou_matrix_pallas(
            cand, gt_boxes, gt_mask, interpret=ops_pkg.interpret_mode()
        )
    else:
        ious = box_ops.iou(cand, gt_boxes)  # [R+G, G]
        ious = jnp.where(gt_mask[None, :], ious, -1.0)
        assignment = jnp.argmax(ious, axis=1)
        max_iou = jnp.max(jnp.maximum(ious, 0.0), axis=1)
    max_iou = jnp.where(cand_valid, max_iou, -1.0)  # padded rois match nothing

    is_pos = cand_valid & (max_iou >= cfg.pos_iou_thresh)
    is_neg = (
        cand_valid
        & (max_iou < cfg.neg_iou_thresh_high)
        & (max_iou >= cfg.neg_iou_thresh_low)
    )

    rng_pos, rng_neg, rng_pack = jax.random.split(rng, 3)
    if strategy == "topk_iou":
        # biased sampling: rank by overlap instead of a uniform draw —
        # highest-IoU positives, hardest negatives. rng_pos/rng_neg stay
        # split (identical key schedule to the random path) so the pack
        # tiebreak below consumes the same rng_pack either way.
        pos_keep = topk_subset_mask(
            is_pos, max_iou, cfg.n_pos_max, k_max=cfg.n_pos_max
        )
        n_pos = jnp.sum(pos_keep)
        neg_keep = topk_subset_mask(
            is_neg, max_iou, n_sample - n_pos, k_max=n_sample
        )
    else:
        pos_keep = random_subset_mask(
            rng_pos, is_pos, cfg.n_pos_max, k_max=cfg.n_pos_max
        )
        n_pos = jnp.sum(pos_keep)
        neg_keep = random_subset_mask(
            rng_neg, is_neg, n_sample - n_pos, k_max=n_sample
        )

    # Pack kept positives (priority 0), kept negatives (1), filler (2) into
    # exactly n_sample slots.
    priority = jnp.where(pos_keep, 0, jnp.where(neg_keep, 1, 2))
    idx = pack_by_priority(rng_pack, priority, n_sample)  # [n_sample]

    slot_pos = pos_keep[idx]
    slot_neg = neg_keep[idx]
    sample_rois = cand[idx] * (slot_pos | slot_neg)[:, None]

    matched_gt = gt_boxes[assignment[idx]]
    reg = box_ops.encode(sample_rois, matched_gt)
    mean = jnp.asarray(cfg.reg_mean, jnp.float32)
    std = jnp.asarray(cfg.reg_std, jnp.float32)
    reg = (reg - mean) / std
    reg = jnp.where(slot_pos[:, None], reg, 0.0)

    gt_cls = gt_labels[assignment[idx]].astype(jnp.int32)
    labels = jnp.where(slot_pos, gt_cls, jnp.where(slot_neg, 0, -1))
    return sample_rois.astype(jnp.float32), reg.astype(jnp.float32), labels


def batched_proposal_targets(
    rng: Array,
    rois: Array,
    roi_valid: Array,
    gt_boxes: Array,
    gt_labels: Array,
    gt_mask: Array,
    cfg: ROITargetConfig,
    positions: Array = None,
    strategy: str = "random",
) -> Tuple[Array, Array, Array]:
    """vmap over the batch: rois [N, R, 4] -> (sample_rois [N, S, 4],
    reg [N, S, 4], labels [N, S]).

    ``positions`` makes per-image keys sharding-invariant (global
    fold_in instead of local split — see batched_anchor_targets).
    """
    if positions is None:
        keys = jax.random.split(rng, rois.shape[0])
    else:
        keys = jax.vmap(lambda p: jax.random.fold_in(rng, p))(positions)
    return jax.vmap(
        lambda k, r, v, b, lbl, m: proposal_targets(
            k, r, v, b, lbl, m, cfg, strategy=strategy
        )
    )(keys, rois, roi_valid, gt_boxes, gt_labels, gt_mask)
