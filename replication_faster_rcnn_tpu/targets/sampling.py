"""Jit-able masked random subsampling.

The reference subsamples with ``np.random.choice(index, size, replace=False)``
on host (`utils/utils.py:192-202,248-258`) — dynamic-size, host-side, and
unjittable. The XLA-native equivalent: draw a uniform priority per element,
and keep an element iff it is a member AND its priority ranks inside the
budget. The budget may be a traced scalar (e.g. "n_sample minus however many
positives were kept"), which a fixed-size sort handles where ``top_k`` with a
dynamic k could not.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

Array = jnp.ndarray


def random_subset_mask(
    rng: Array, member: Array, k: Array, k_max: int | None = None
) -> Array:
    """Uniformly choose min(k, member.sum()) elements of a masked set.

    Args:
      rng: PRNG key.
      member: [N] bool — the candidate set.
      k: scalar int (python or traced) — max elements to keep.
      k_max: optional STATIC upper bound on ``k``. When given, the cut
        point comes from ``lax.top_k(score, k_max)`` instead of a full
        descending sort — on TPU a top-256 over 90k anchors is far
        cheaper than sorting all 90k (the two full sorts were the bulk
        of anchor_targets' 10.4 ms at the FPN anchor count). Same
        selection: both find the kk-th largest score. A concrete
        ``k > k_max`` raises; a traced ``k`` is clamped to ``k_max``
        (the bound is the caller's contract).

    Returns: [N] bool mask, a uniform random subset of ``member`` with
    ``min(k, member.sum())`` True entries.
    """
    r = jax.random.uniform(rng, member.shape)
    score = jnp.where(member, r, -jnp.inf)
    n_member = jnp.sum(member)
    kk = jnp.minimum(jnp.asarray(k, jnp.int32), n_member.astype(jnp.int32))
    if k_max is not None:
        if not isinstance(k, jax.core.Tracer) and int(k) > k_max:
            raise ValueError(f"k={int(k)} exceeds the static bound k_max={k_max}")
        if k_max <= 0:
            return jnp.zeros_like(member)
        kk = jnp.minimum(kk, k_max)
        top = jax.lax.top_k(score, min(int(k_max), member.shape[-1]))[0]
    else:
        top = jnp.sort(score)[::-1]  # descending
    # kk-th largest score is the cut; kk == 0 keeps nothing.
    cut = top[jnp.maximum(kk - 1, 0)]
    return member & (score >= cut) & (kk > 0)


def topk_subset_mask(
    member: Array, score: Array, k: Array, k_max: int | None = None
) -> Array:
    """Deterministically keep the min(k, member.sum()) HIGHEST-scoring
    elements of a masked set — the biased-sampling counterpart of
    :func:`random_subset_mask` (arXiv:1702.02138's region-sampling study:
    rank candidates by overlap instead of drawing uniformly).

    Same cut-point machinery as random_subset_mask with ``score`` in
    place of the uniform draw, so the two strategies are drop-in
    exchangeable at every call site. Exact ties at the cut score keep
    every tied element (the caller's fixed-size packing bounds the
    final sample, so over-keeping only widens the pool the pack's
    tiebreak chooses from).
    """
    s = jnp.where(member, score, -jnp.inf)
    n_member = jnp.sum(member)
    kk = jnp.minimum(jnp.asarray(k, jnp.int32), n_member.astype(jnp.int32))
    if k_max is not None:
        if not isinstance(k, jax.core.Tracer) and int(k) > k_max:
            raise ValueError(f"k={int(k)} exceeds the static bound k_max={k_max}")
        if k_max <= 0:
            return jnp.zeros_like(member)
        kk = jnp.minimum(kk, k_max)
        top = jax.lax.top_k(s, min(int(k_max), member.shape[-1]))[0]
    else:
        top = jnp.sort(s)[::-1]  # descending
    cut = top[jnp.maximum(kk - 1, 0)]
    return member & (s >= cut) & (kk > 0)


def pack_by_priority(rng: Array, priority: Array, n_out: int) -> Array:
    """Order indices by (priority, random tiebreak) and take the first n_out.

    priority: [N] small non-negative ints; lower packs first. Returns
    [n_out] int32 indices. Used to lay out "positives first, then negatives,
    then filler" into a fixed-size sample block.
    """
    r = jax.random.uniform(rng, priority.shape)
    key = priority.astype(jnp.float32) + r  # r < 1 preserves class ordering
    order = jnp.argsort(key)
    return order[:n_out].astype(jnp.int32)
