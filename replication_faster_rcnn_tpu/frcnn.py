"""``FRCNN`` facade — API parity with the reference's user-facing wrapper
(`frcnn.py:14-35`): construct by mode, `get_data_loader()`, `get_network()`,
`load_param()` / `save_param()`.

A reference user's entry points map directly:

    reference                               here
    ---------                               ----
    FRCNN('train')                          FRCNN('train')
    .get_data_loader(root_dir, bs, shuffle) .get_data_loader(root_dir, bs, shuffle)
    .get_network()                          .get_network() -> (model, variables)
    .load_param(path) / .save_param(path)   same names (orbax under the hood;
                                            fixes the reference's save_param,
                                            which calls a nonexistent
                                            self.net.save — `frcnn.py:33-35`)

plus `.train(lr, n_epoch, ...)`, mirroring reference `trainer.train`
(`train.py:130-151`), built on the SPMD Trainer.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

from replication_faster_rcnn_tpu.config import FasterRCNNConfig, get_config


class FRCNN:
    """Thin convenience wrapper over config + Trainer + model."""

    def __init__(self, mode: str = "train", config: Optional[FasterRCNNConfig] = None):
        if mode not in ("train", "test"):
            raise ValueError("mode should be train or test")  # ref frcnn.py:15
        self.mode = mode
        self.config = config if config is not None else get_config("voc_resnet18")
        self._trainer = None

    # -- reference API ------------------------------------------------------

    def get_data_loader(
        self,
        root_dir: Optional[str] = None,
        batch_size: int = 2,
        shuffle: bool = True,
    ):
        """Build the dataset+loader (reference `frcnn.py:19-23`; its default
        batch_size=2 and VOC root are kept)."""
        from replication_faster_rcnn_tpu.data import DataLoader, make_dataset

        cfg = self.config
        if root_dir is not None:
            cfg = cfg.replace(data=dataclasses.replace(cfg.data, root_dir=root_dir))
            self.config = cfg
        split = "train" if self.mode == "train" else "val"
        dataset = make_dataset(cfg.data, split)
        return DataLoader(
            dataset, batch_size=batch_size, shuffle=shuffle,
            seed=cfg.train.seed,
            prefetch=cfg.data.loader_prefetch,
            num_workers=cfg.data.loader_workers,
            worker_mode=cfg.data.loader_mode,
            augment_hflip=cfg.data.augment_hflip and self.mode == "train",
            cache_ram=cfg.data.loader_cache_ram,
        )

    def get_network(self) -> Tuple[object, dict]:
        """(model, variables) — reference `frcnn.py:25-27` wires
        backbone+RPN+head; here the assembly is one flax module."""
        import jax

        from replication_faster_rcnn_tpu.models import faster_rcnn

        model, variables = faster_rcnn.init_variables(
            self.config, jax.random.PRNGKey(self.config.train.seed)
        )
        self.model, self.variables = model, variables
        return model, variables

    @property
    def trainer(self):
        if self._trainer is None:
            from replication_faster_rcnn_tpu.train import Trainer

            self._trainer = Trainer(self.config)
        return self._trainer

    def load_param(self, load_path: str) -> None:
        """Warm-start from a checkpoint dir (reference `frcnn.py:29-31`
        loads a torch state_dict; torch resnet ``.pth`` files are also
        accepted and grafted into the backbone). The trainer's save
        directory is left untouched — loading must not redirect where new
        checkpoints go."""
        if load_path.endswith((".pth", ".pt")):
            self.trainer.load_pretrained_backbone(load_path)
        else:
            self.trainer.restore(directory=load_path)

    def save_param(self, save_path: str) -> None:
        """Save a checkpoint (fixes reference `frcnn.py:33-35`, which calls
        the nonexistent ``self.net.save``)."""
        self.trainer.workdir = save_path
        self.trainer._ckpt_mgr = None
        self.trainer.save()
        print(f"parameters saved to {save_path}")  # ref prints too (frcnn.py:35)

    def train(
        self,
        lr: Optional[float] = None,
        n_epoch: Optional[int] = None,
        save_folder: Optional[str] = None,
        load_path: Optional[str] = None,
    ):
        """Mirror of reference `trainer.train(lr, n_epoch, save_folder,
        load_path)` (`train.py:130-151`) on the SPMD trainer."""
        cfg = self.config
        kw = {}
        if lr is not None:
            kw["lr"] = lr
        if n_epoch is not None:
            kw["n_epoch"] = n_epoch
        if kw:
            cfg = cfg.replace(train=dataclasses.replace(cfg.train, **kw))
            self.config = cfg
            self._trainer = None
        if save_folder is not None:
            from replication_faster_rcnn_tpu.train import Trainer

            self._trainer = Trainer(cfg, workdir=save_folder)
        if load_path is not None:
            self.load_param(load_path)
        return self.trainer.train()
