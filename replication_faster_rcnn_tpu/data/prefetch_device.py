"""Double-buffered device staging — host batch work off the critical path.

The trainer's dispatch loop is asynchronous on the device side (`jit`
enqueues and returns), but host-side batch production was serialized
WITH it: collate/stack (`data/fetch` + the np.stack in `train_chunk`)
and the host→device transfer (`data/device_put`) ran between dispatches,
so every step paid the feed on the critical path. This module moves that
work to a producer thread: while dispatch K executes on device, the
producer assembles batch K+1, starts its `device_put` and *waits for the
transfer to land* (`parallel.stage_to_devices(wait=True)`), then parks
the device-resident buffer in a bounded queue. The trainer's next
dispatch dequeues an already-resident buffer — the host-blocked cost per
step collapses to a queue pop (measured in benchmarks/step_profile.py's
``overlap`` section).

Semantics the trainer depends on:

* **Deterministic order.** The producer consumes the feed iterator in
  exactly the order a synchronous loop would, so training consumes the
  same batches in the same order — bitwise parity with prefetch off.
* **Replay skip.** ``skip`` batches are drawn from the feed and
  discarded WITHOUT staging (mid-epoch resume replays the interrupted
  epoch's prefix; staged work for already-trained batches would be
  wasted H2D traffic). The skipped draws still advance the feed's
  deterministic order, which is the point.
* **No batch consumed twice.** "Consumed" means trained on. On
  preemption (`fault.Preempted` at a dispatch boundary) staged-but-
  undequeued buffers are dropped by :meth:`close`; resume re-derives
  them from the feed replay. The stager never re-emits an item.
* **Bounded depth.** At most ``depth`` staged items exist at once
  (each holds a full batch/chunk in HBM); the producer blocks when the
  queue is full, providing backpressure.
* **Error transparency.** A producer-side exception (feed or staging)
  re-raises in the consumer at the point of the failed item, not as a
  silent end-of-epoch.

The stager is chunk-aware: with ``chunk=K`` (fused multi-step dispatch)
it stages full K-batch chunks through ``stage`` and hands an epoch tail
shorter than K back as raw host batches, mirroring the trainer's
per-step tail path.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Callable, Iterable, Iterator, List, Optional, Tuple

from replication_faster_rcnn_tpu.faultlib import failpoints
from replication_faster_rcnn_tpu.telemetry import spans as tspans

# queue item kinds (first tuple element)
STAGED = "staged"  # (STAGED, staged_obj, n_steps, n_images)
HOST = "host"  # (HOST, raw_host_batch) — epoch tail shorter than `chunk`
_END = ("__end__",)
_ERROR = "__error__"  # (_ERROR, exception)


def _batch_images(batch) -> int:
    """Image count of one host batch (selection dicts carry `idx`)."""
    return int(batch["idx" if "idx" in batch else "image"].shape[0])


class DevicePrefetcher:
    """Iterator over staged device batches produced by a background thread.

    Parameters
    ----------
    source:
        Iterable of host batches (loader batches or device-cache
        selection dicts) in deterministic epoch order.
    stage:
        Callable mapping a list of ``chunk`` host batches to a
        device-resident object (e.g. stacked + sharded + transfer-waited;
        the trainer passes a closure that also owns the
        ``data/device_put`` telemetry span). For ``chunk == 1`` it is
        called with a single-element list.
    depth:
        Maximum staged items buffered ahead (>= 1).
    chunk:
        Batches per staged item (the trainer's ``steps_per_dispatch``).
    skip:
        Leading batches to draw-and-discard (mid-epoch resume replay).
    """

    def __init__(
        self,
        source: Iterable[Any],
        stage: Callable[[List[Any]], Any],
        depth: int = 2,
        chunk: int = 1,
        skip: int = 0,
    ) -> None:
        if depth < 1:
            raise ValueError(f"prefetch depth must be >= 1, got {depth}")
        if chunk < 1:
            raise ValueError(f"chunk must be >= 1, got {chunk}")
        if skip < 0:
            raise ValueError(f"skip must be >= 0, got {skip}")
        self._source = iter(source)
        self._stage = stage
        self._chunk = chunk
        self._skip = skip
        self._q: "queue.Queue[Tuple]" = queue.Queue(maxsize=depth)
        self._stop = threading.Event()
        self._done = False
        # the producer inherits the caller's process-wide tracer: spans
        # are thread-safe and carry tids, so `data/fetch`/`data/device_put`
        # emitted here still land in the same trace (now overlapping the
        # consumer's `step/dispatch` spans instead of serializing with them)
        self._tracer = tspans.current_tracer()
        self._thread = threading.Thread(
            target=self._produce, name="device-prefetch", daemon=True
        )
        self._thread.start()

    # ------------------------------------------------------------ producer

    def _put(self, item: Tuple) -> bool:
        """Blocking put with stop-responsiveness; False once stopped."""
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def _produce(self) -> None:
        tracer = self._tracer
        skip = self._skip
        pending: List[Any] = []
        try:
            while not self._stop.is_set():
                with tracer.span("data/fetch", cat="data"):
                    try:
                        batch = next(self._source)
                    except StopIteration:
                        break
                if skip > 0:
                    skip -= 1
                    continue
                pending.append(batch)
                if len(pending) < self._chunk:
                    continue
                n_images = sum(_batch_images(b) for b in pending)
                # failpoint: ioerror raises here and relays to the consumer
                # via the _ERROR item (error-transparency contract above)
                inj = failpoints.fire("prefetch.stage", n_batches=len(pending))
                if inj is not None and inj.kind == "nan":
                    pending = [failpoints.poison_batch(b) for b in pending]
                staged = self._stage(pending)
                if not self._put((STAGED, staged, len(pending), n_images)):
                    return
                pending = []
            # epoch tail (< chunk batches): hand back raw host batches for
            # the trainer's per-step path — its fused program was compiled
            # for exactly `chunk` steps
            for batch in pending:
                if not self._put((HOST, batch)):
                    return
            self._put(_END)
        except BaseException as e:  # noqa: BLE001 — relay to the consumer
            self._put((_ERROR, e))

    # ------------------------------------------------------------ consumer

    def __iter__(self) -> Iterator[Tuple]:
        return self

    def __next__(self) -> Tuple:
        if self._done:
            raise StopIteration
        item = self._q.get()
        if item[0] == _END[0]:
            self._done = True
            raise StopIteration
        if item[0] == _ERROR:
            self._done = True
            raise item[1]
        return item

    def queue_depth(self) -> Optional[int]:
        """Staged items currently buffered (telemetry provider)."""
        return self._q.qsize()

    def close(self) -> None:
        """Stop the producer and drop staged-but-unconsumed buffers.

        Safe to call at any point (preemption, crash, normal epoch end);
        idempotent. Dropped items are NOT consumed — on resume the feed
        replay regenerates them deterministically."""
        self._stop.set()
        # drain so a producer blocked on a full queue observes the stop
        while True:
            try:
                self._q.get_nowait()
            except queue.Empty:
                break
        self._thread.join(timeout=30.0)
        self._done = True
