"""Synthetic detection dataset — deterministic random images with planted
boxes, in the exact sample format of :class:`~.voc.VOCDataset`.

The reference has no equivalent (it assumes VOC on disk); this exists so
tests, benchmarks and the overfit integration check (SURVEY.md §4f) run in
environments with no dataset. Images contain actual bright rectangles at
the box locations so a detector can genuinely fit the data, not just the
shapes.
"""

from __future__ import annotations

from typing import Dict

import numpy as np

from replication_faster_rcnn_tpu.config import DataConfig


class SyntheticDataset:
    """Deterministic per-index random samples (same idx -> same sample)."""

    def __init__(
        self,
        cfg: DataConfig,
        split: str = "train",
        length: int = 64,
        num_classes: int = 21,
        max_objects: int = 4,
        seed: int = 0,
    ) -> None:
        self.cfg = cfg
        self.length = length
        self.num_classes = num_classes
        self.max_objects = min(max_objects, cfg.max_boxes)
        # different splits get disjoint streams
        self.seed = seed + {"train": 0, "val": 1 << 20, "test": 2 << 20}.get(split, 0)

    def __len__(self) -> int:
        return self.length

    def __getitem__(self, idx: int) -> Dict[str, np.ndarray]:
        if not 0 <= idx < self.length:
            raise IndexError(idx)
        rng = np.random.RandomState(self.seed + idx)
        h, w = self.cfg.image_size
        m = self.cfg.max_boxes

        image = rng.uniform(0.0, 0.15, (h, w, 3)).astype(np.float32)
        n_obj = rng.randint(1, self.max_objects + 1)
        labels = np.full((m,), -1, np.int32)
        boxes = np.full((m, 4), -1.0, np.float32)
        for i in range(n_obj):
            bh = rng.randint(h // 8, h // 2)
            bw = rng.randint(w // 8, w // 2)
            r1 = rng.randint(0, h - bh)
            c1 = rng.randint(0, w - bw)
            cls = rng.randint(1, self.num_classes)
            boxes[i] = [r1, c1, r1 + bh, c1 + bw]
            labels[i] = cls
            # paint the object: class-dependent color block + noise
            color = 0.3 + 0.7 * np.asarray(
                [(cls % 3) / 2.0, ((cls // 3) % 3) / 2.0, ((cls // 9) % 3) / 2.0],
                np.float32,
            )
            image[r1 : r1 + bh, c1 : c1 + bw] = color + rng.uniform(
                -0.05, 0.05, (bh, bw, 3)
            ).astype(np.float32)

        if self.cfg.device_normalize:
            # raw pixels in [0, 1] -> uint8; the model's on-device
            # preprocess applies /255 + mean/std (so the u8 and f32 paths
            # see the same image up to 1/255 quantization)
            image = np.clip(np.rint(image * 255.0), 0, 255).astype(np.uint8)
        else:
            mean = np.asarray(self.cfg.pixel_mean, np.float32)
            std = np.asarray(self.cfg.pixel_std, np.float32)
            image = (image - mean) / std
        return {
            "image": image,  # uint8 or float32 per the branch above
            "boxes": boxes,
            "labels": labels,
            "mask": labels >= 0,
            "difficult": np.zeros((m,), bool),
        }
