"""ctypes bindings for the native host-side kernels (native/frcnn_native.cpp)
with exact-equivalent numpy fallbacks.

The native library replaces, in the framework's own code, the compiled host
kernels the reference borrows from skimage/torchvision (SURVEY.md §2.3):
fused bilinear-resize+normalize for the data pipeline and greedy NMS for
CPU-side post-processing. If the ``.so`` is absent, a best-effort ``make``
builds it; failing that, the numpy fallbacks keep everything working (the
fallbacks ARE the behavioral spec — parity is tested both ways).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

_REPO = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
_SO_PATH = os.path.join(_REPO, "native", "build", "libfrcnn_native.so")

_lib: Optional[ctypes.CDLL] = None
_lib_checked = False
_lib_lock = threading.Lock()  # loader threads race here on first batch


def _try_build(rebuild: bool = False) -> bool:
    """Best-effort make, degrading through host capabilities: full build,
    then without -march=native (older gcc), then without libjpeg (missing
    jpeglib.h — the JPEG entry points are simply absent), then both."""
    flag_sets = [[], ["MARCH="], ["JPEG=0"], ["MARCH=", "JPEG=0"]]
    base = ["make", "-C", os.path.join(_REPO, "native")]
    if rebuild:
        base.insert(1, "-B")
    for flags in flag_sets:
        try:
            subprocess.run(
                base + flags, check=True, capture_output=True, timeout=120
            )
            return True
        except Exception:
            continue
    return False


def _rebuild_and_reload() -> Optional[ctypes.CDLL]:
    """Rebuild the .so and dlopen it under a fresh unique pathname (glibc
    caches dlopen by path, so reloading _SO_PATH would return the old
    handle). Returns None if the rebuild or reload fails, or if the
    rebuilt library still lacks the JPEG entry points (JPEG=0 fallback
    build) — callers then keep whatever library they already have."""
    import shutil
    import tempfile

    if not _try_build(rebuild=True):
        return None
    try:
        fd, tmp = tempfile.mkstemp(suffix=".so", prefix="frcnn_native_")
        os.close(fd)
        shutil.copy2(_SO_PATH, tmp)
        lib = ctypes.CDLL(tmp)
        os.unlink(tmp)  # the mapping survives the unlink
    except Exception:
        return None
    return lib if hasattr(lib, "decode_jpeg_resize_normalize") else None


def _load_lib() -> Optional[ctypes.CDLL]:
    global _lib, _lib_checked
    if _lib_checked:
        return _lib
    with _lib_lock:
        return _load_lib_locked()


def _load_lib_locked() -> Optional[ctypes.CDLL]:
    global _lib, _lib_checked
    if _lib_checked:
        return _lib
    _lib_checked = True
    if not os.path.exists(_SO_PATH):
        if not _try_build():
            return None  # numpy fallbacks cover everything
    try:
        lib = ctypes.CDLL(_SO_PATH)
    except OSError:
        return None
    if not hasattr(lib, "decode_jpeg_resize_normalize"):
        # stale .so from before the JPEG kernels. Rebuild, then load the
        # fresh file through a unique temp copy: re-dlopening the same
        # pathname would return the cached stale handle (ctypes never
        # dlcloses). On any failure keep the stale-but-working library —
        # resize/NMS/scale_boxes don't need libjpeg.
        lib = _rebuild_and_reload() or lib
    f32p = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")
    u8p = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")
    i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
    lib.resize_bilinear_normalize.argtypes = [
        u8p, ctypes.c_int, ctypes.c_int, f32p, ctypes.c_int, ctypes.c_int,
        f32p, f32p,
    ]
    lib.resize_bilinear_normalize.restype = None
    lib.nms_greedy.argtypes = [
        f32p, f32p, ctypes.c_int, ctypes.c_float, i32p, ctypes.c_int,
    ]
    lib.nms_greedy.restype = ctypes.c_int
    lib.scale_boxes.argtypes = [
        f32p, i32p, ctypes.c_int, ctypes.c_float, ctypes.c_float,
    ]
    lib.scale_boxes.restype = None
    if hasattr(lib, "decode_jpeg_resize_normalize"):  # absent in JPEG=0 builds
        lib.decode_jpeg_resize_normalize.argtypes = [
            u8p, ctypes.c_int64, f32p, ctypes.c_int, ctypes.c_int,
            f32p, f32p, ctypes.c_int, i32p, i32p,
        ]
        lib.decode_jpeg_resize_normalize.restype = ctypes.c_int
    _lib = lib
    return _lib


def native_available() -> bool:
    return _load_lib() is not None


def _resize_normalize_numpy(
    img: np.ndarray, out_hw: Tuple[int, int], mean: np.ndarray, std: np.ndarray
) -> np.ndarray:
    """The behavioral spec of the C++ kernel: bilinear with
    align_corners=False sampling, fused /255 + mean/std normalization."""
    sh, sw = img.shape[:2]
    dh, dw = out_hw
    sr = np.clip((np.arange(dh) + 0.5) * (sh / dh) - 0.5, 0, sh - 1)
    sc = np.clip((np.arange(dw) + 0.5) * (sw / dw) - 0.5, 0, sw - 1)
    r0 = sr.astype(np.int32)
    c0 = sc.astype(np.int32)
    r1 = np.minimum(r0 + 1, sh - 1)
    c1 = np.minimum(c0 + 1, sw - 1)
    fr = (sr - r0).astype(np.float32)[:, None, None]
    fc = (sc - c0).astype(np.float32)[None, :, None]
    im = img.astype(np.float32)
    top = im[r0][:, c0] * (1 - fc) + im[r0][:, c1] * fc
    bot = im[r1][:, c0] * (1 - fc) + im[r1][:, c1] * fc
    out = top * (1 - fr) + bot * fr
    return ((out / 255.0 - mean) / std).astype(np.float32)


def resize_normalize(
    img: np.ndarray,
    out_hw: Tuple[int, int],
    mean,
    std,
) -> np.ndarray:
    """uint8 HWC RGB -> normalized float32 [out_h, out_w, 3]."""
    img = np.ascontiguousarray(img, np.uint8)
    mean = np.asarray(mean, np.float32)
    std = np.asarray(std, np.float32)
    lib = _load_lib()
    if lib is None:
        return _resize_normalize_numpy(img, out_hw, mean, std)
    dst = np.empty((out_hw[0], out_hw[1], 3), np.float32)
    lib.resize_bilinear_normalize(
        img, img.shape[0], img.shape[1], dst, out_hw[0], out_hw[1], mean, std
    )
    return dst


def scale_boxes(
    boxes: np.ndarray,
    labels: np.ndarray,
    row_scale: float,
    col_scale: float,
) -> np.ndarray:
    """Scale + round padded [m, 4] boxes to resized-image coords, leaving
    entries with label < 0 untouched (reference
    `utils/data_loader.py:66-69,115` semantics)."""
    boxes = np.ascontiguousarray(boxes, np.float32).copy()
    labels = np.ascontiguousarray(labels, np.int32)
    lib = _load_lib()
    if lib is None:
        real = labels >= 0
        scale = np.asarray([row_scale, col_scale, row_scale, col_scale], np.float32)
        return np.where(real[:, None], np.round(boxes * scale), boxes)
    lib.scale_boxes(boxes, labels, len(boxes), row_scale, col_scale)
    return boxes


def decode_jpeg_resize_normalize(
    data: bytes,
    out_hw: Tuple[int, int],
    mean,
    std,
    fast_scale: bool = True,
) -> Optional[Tuple[np.ndarray, int, int]]:
    """JPEG bytes -> (normalized float32 [out_h, out_w, 3], orig_h, orig_w).

    The whole loader hot path — decode, RGB conversion, bilinear resize,
    /255 + mean/std — in one native call. ``fast_scale`` enables libjpeg's
    DCT-domain 1/2..1/8 prescaling when the source is at least 2x the
    target in both dims (large decode savings, sub-bilinear-error quality
    difference). Returns None when the native library is unavailable or
    the bytes don't decode (caller falls back to PIL — which also covers
    non-JPEG files like the occasional PNG-in-.jpg).
    """
    lib = _load_lib()
    if lib is None or not hasattr(lib, "decode_jpeg_resize_normalize"):
        return None
    buf = np.frombuffer(data, np.uint8)
    dims = np.empty((2,), np.int32)
    dst = np.empty((out_hw[0], out_hw[1], 3), np.float32)
    rc = lib.decode_jpeg_resize_normalize(
        buf,
        buf.size,
        dst,
        out_hw[0],
        out_hw[1],
        np.asarray(mean, np.float32),
        np.asarray(std, np.float32),
        1 if fast_scale else 0,
        dims[0:1],
        dims[1:2],
    )
    if rc != 0:
        return None
    return dst, int(dims[0]), int(dims[1])


def _nms_numpy(
    boxes: np.ndarray, scores: np.ndarray, thresh: float, max_keep: int
) -> np.ndarray:
    order = np.argsort(-scores, kind="stable")
    dead = np.zeros(len(boxes), bool)
    area = (boxes[:, 2] - boxes[:, 0]) * (boxes[:, 3] - boxes[:, 1])
    keep = []
    for i in order:
        if dead[i] or len(keep) >= max_keep:
            if len(keep) >= max_keep:
                break
            continue
        keep.append(int(i))
        tl = np.maximum(boxes[i, :2], boxes[:, :2])
        br = np.minimum(boxes[i, 2:], boxes[:, 2:])
        wh = np.clip(br - tl, 0, None)
        inter = wh[:, 0] * wh[:, 1]
        union = area[i] + area - inter
        iou = np.where(union > 0, inter / np.maximum(union, 1e-12), 0.0)
        dead |= iou > thresh
    return np.asarray(keep, np.int32)


def nms(
    boxes: np.ndarray, scores: np.ndarray, thresh: float, max_keep: int = 1 << 30
) -> np.ndarray:
    """Greedy NMS on host; returns kept indices in descending score order."""
    boxes = np.ascontiguousarray(boxes, np.float32)
    scores = np.ascontiguousarray(scores, np.float32)
    max_keep = int(min(max_keep, len(boxes)))
    lib = _load_lib()
    if lib is None:
        return _nms_numpy(boxes, scores, thresh, max_keep)
    keep = np.empty((max(max_keep, 1),), np.int32)
    n = lib.nms_greedy(boxes, scores, len(boxes), thresh, keep, max_keep)
    return keep[:n]


# --- uint8 (device-normalize) variants -----------------------------------
# With mean=0 and std=1/255 the fused kernel's (x/255 - mean)/std affine
# is the identity on pixel values, so the SAME native code yields the
# resized image in 0..255 — no second C++ entry point needed. The f32->u8
# rounding costs ~1 ms once per sample (and only once ever with the RAM
# cache); in exchange the sample ships to the device at a quarter of the
# bytes and the normalize runs on-chip fused into the first conv
# (models/faster_rcnn.py::preprocess).

_U8_MEAN = (0.0, 0.0, 0.0)
_U8_STD = (1.0 / 255.0, 1.0 / 255.0, 1.0 / 255.0)


def _to_u8(arr: np.ndarray) -> np.ndarray:
    return np.clip(np.rint(arr), 0.0, 255.0).astype(np.uint8)


def resize_u8(img: np.ndarray, out_hw: Tuple[int, int]) -> np.ndarray:
    """uint8 HWC RGB -> bilinear-resized uint8 [out_h, out_w, 3]."""
    return _to_u8(resize_normalize(img, out_hw, _U8_MEAN, _U8_STD))


def decode_jpeg_resize_u8(
    data: bytes, out_hw: Tuple[int, int], fast_scale: bool = True
) -> Optional[Tuple[np.ndarray, int, int]]:
    """JPEG bytes -> (resized uint8 [out_h, out_w, 3], orig_h, orig_w);
    None if the native decoder is unavailable (caller falls back)."""
    res = decode_jpeg_resize_normalize(
        data, out_hw, _U8_MEAN, _U8_STD, fast_scale
    )
    if res is None:
        return None
    out, orig_h, orig_w = res
    return _to_u8(out), orig_h, orig_w
