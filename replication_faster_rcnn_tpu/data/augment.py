"""Train-time augmentation — deterministic, resume-reproducible.

The reference trains with NO augmentation (`utils/data_loader.py:56-79`
resizes and normalizes only); the original Faster R-CNN recipe uses
horizontal flips as its sole augmentation, so VOC-parity training wants
it available. Everything here is pure numpy on host samples (the fixed
sample dict of `data/voc.py`), decided by a counter-based per-(seed,
epoch, index) RNG — no global state, so the same epoch re-yields the
same flips after a checkpoint resume, identical under thread and
fork-process loader workers.
"""

from __future__ import annotations

from typing import Dict

import numpy as np


def hflip_sample(sample: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
    """Mirror a sample left-right: image columns reversed, each real
    box's x-span reflected ((y1,x1,y2,x2) -> (y1, W-x2, y2, W-x1));
    padded (-1) rows stay untouched.

    Keyed on ``labels >= 0``, not the training ``mask``: difficult
    objects keep their geometry consistent with the mirrored pixels even
    when masked out of training (they are ignore-regions at eval time)."""
    image = sample["image"][:, ::-1, :]
    w = float(image.shape[1])
    boxes = sample["boxes"].copy()
    valid = np.asarray(sample["labels"] >= 0, bool)
    flipped = boxes[valid]
    boxes[valid] = np.stack(
        [flipped[:, 0], w - flipped[:, 3], flipped[:, 2], w - flipped[:, 1]],
        axis=1,
    )
    out = dict(sample)
    # negative-stride view, no copy: collate's np.stack materializes it
    out["image"] = image
    out["boxes"] = boxes
    return out


class AugmentedView:
    """Map-style view applying a 50% per-sample horizontal flip.

    The coin for (seed, epoch, idx) is a small counter-based mix — not
    Python ``hash`` (salted for some types) and not a shared RNG stream
    (order-dependent) — so any worker, process or thread, computes the
    same decision for the same sample.
    """

    def __init__(self, dataset, seed: int, epoch: int) -> None:
        self.dataset = dataset
        self.seed = int(seed)
        self.epoch = int(epoch)

    def __len__(self) -> int:
        return len(self.dataset)

    def __getitem__(self, idx: int):
        sample = self.dataset[idx]
        # splitmix64 finalizer on the (seed, epoch, idx) mix; one output
        # bit is the coin — no per-sample Mersenne Twister construction
        # on the ingest hot path
        z = (
            self.seed * 0x9E3779B97F4A7C15
            + self.epoch * 0xBF58476D1CE4E5B9
            + idx * 0x94D049BB133111EB
        ) & 0xFFFFFFFFFFFFFFFF
        z = ((z ^ (z >> 30)) * 0xBF58476D1CE4E5B9) & 0xFFFFFFFFFFFFFFFF
        z = ((z ^ (z >> 27)) * 0x94D049BB133111EB) & 0xFFFFFFFFFFFFFFFF
        z ^= z >> 31
        if z & 1:
            return hflip_sample(sample)
        return sample
