"""Train-time augmentation — deterministic, resume-reproducible.

The reference trains with NO augmentation (`utils/data_loader.py:56-79`
resizes and normalizes only); the original Faster R-CNN recipe uses
horizontal flips as its sole augmentation, so VOC-parity training wants
it available. Everything here is pure numpy on host samples (the fixed
sample dict of `data/voc.py`), decided by a counter-based per-(seed,
epoch, index) RNG — no global state, so the same epoch re-yields the
same flips after a checkpoint resume, identical under thread and
fork-process loader workers.
"""

from __future__ import annotations

from typing import Dict

import numpy as np


def hflip_sample(sample: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
    """Mirror a sample left-right: image columns reversed, each real
    box's x-span reflected ((y1,x1,y2,x2) -> (y1, W-x2, y2, W-x1));
    padded (-1) rows stay untouched.

    Keyed on ``labels >= 0``, not the training ``mask``: difficult
    objects keep their geometry consistent with the mirrored pixels even
    when masked out of training (they are ignore-regions at eval time)."""
    # C-contiguous copy, NOT the negative-stride view `[:, ::-1, :]`:
    # consumers that stage samples individually (device_put, per-sample
    # caches) would silently re-copy a strided view per image; collate's
    # np.stack hid that for the batch path only
    image = np.ascontiguousarray(sample["image"][:, ::-1, :])
    w = float(image.shape[1])
    boxes = sample["boxes"].copy()
    valid = np.asarray(sample["labels"] >= 0, bool)
    flipped = boxes[valid]
    boxes[valid] = np.stack(
        [flipped[:, 0], w - flipped[:, 3], flipped[:, 2], w - flipped[:, 1]],
        axis=1,
    )
    out = dict(sample)
    out["image"] = image
    out["boxes"] = boxes
    return out


def _resize_bilinear(image: np.ndarray, oh: int, ow: int) -> np.ndarray:
    """Half-pixel-center bilinear resize, pure vectorized numpy.

    Matches the continuous-coordinate model the box transform assumes:
    a point at continuous x maps to x * ow/w exactly."""
    h, w = image.shape[:2]
    im = image.astype(np.float32)
    ys = (np.arange(oh, dtype=np.float32) + 0.5) * (h / oh) - 0.5
    xs = (np.arange(ow, dtype=np.float32) + 0.5) * (w / ow) - 0.5
    y0 = np.floor(ys).astype(np.int64)
    x0 = np.floor(xs).astype(np.int64)
    wy = (ys - y0)[:, None, None]
    wx = (xs - x0)[None, :, None]
    y0c, y1c = np.clip(y0, 0, h - 1), np.clip(y0 + 1, 0, h - 1)
    x0c, x1c = np.clip(x0, 0, w - 1), np.clip(x0 + 1, 0, w - 1)
    top = im[y0c][:, x0c] * (1 - wx) + im[y0c][:, x1c] * wx
    bot = im[y1c][:, x0c] * (1 - wx) + im[y1c][:, x1c] * wx
    out = top * (1 - wy) + bot * wy
    if image.dtype == np.uint8:
        return np.clip(np.rint(out), 0, 255).astype(np.uint8)
    return out.astype(image.dtype)


def jitter_geometry(
    h: int, w: int, scale: float, off_y: float, off_x: float
) -> tuple:
    """(ch, cw, shift_y, shift_x): the integer jitter geometry shared by
    the host resample below and the on-device one (`ops/image.py`) —
    both sides consume the SAME rounded integers, so they can never
    disagree about sub-pixel placement."""
    ch, cw = max(1, int(round(h * scale))), max(1, int(round(w * scale)))
    shift_y = int(round((ch - h) * float(np.clip(off_y, 0.0, 1.0))))
    shift_x = int(round((cw - w) * float(np.clip(off_x, 0.0, 1.0))))
    return ch, cw, shift_y, shift_x


def jitter_boxes(
    sample: Dict[str, np.ndarray], geom: tuple, h: int, w: int
) -> Dict[str, np.ndarray]:
    """Box/label/mask half of the jitter (image untouched): the affine
    b*s - shift with canvas clipping; collapsed rows take the padded-row
    convention (label -1, mask False, -1 geometry)."""
    ch, cw, shift_y, shift_x = geom
    sy, sx = ch / h, cw / w
    boxes = sample["boxes"].copy()
    labels = sample["labels"].copy()
    mask = sample["mask"].copy() if "mask" in sample else None
    valid = np.asarray(labels >= 0, bool)
    if valid.any():
        b = boxes[valid]
        b = np.stack(
            [
                b[:, 0] * sy - shift_y,
                b[:, 1] * sx - shift_x,
                b[:, 2] * sy - shift_y,
                b[:, 3] * sx - shift_x,
            ],
            axis=1,
        )
        b[:, 0::2] = np.clip(b[:, 0::2], 0.0, float(h))
        b[:, 1::2] = np.clip(b[:, 1::2], 0.0, float(w))
        collapsed = ((b[:, 2] - b[:, 0]) < 1.0) | ((b[:, 3] - b[:, 1]) < 1.0)
        b[collapsed] = -1.0
        boxes[valid] = b
        vi = np.flatnonzero(valid)[collapsed]
        labels[vi] = -1
        if mask is not None:
            mask[vi] = False
    out = dict(sample)
    out["boxes"] = boxes
    out["labels"] = labels
    if mask is not None:
        out["mask"] = mask
    return out


def scale_jitter_sample(
    sample: Dict[str, np.ndarray],
    scale: float,
    off_y: float,
    off_x: float,
) -> Dict[str, np.ndarray]:
    """Random-scale view on a FIXED canvas (jit shapes never change).

    The image content is resized by ``scale``; zoom-out (<1) pads the
    canvas with the image's channel means (the normalization's zero in
    f32 samples, a neutral gray for uint8 device-normalize samples),
    zoom-in (>1) crops a canvas-sized window. ``off_y``/``off_x`` in
    [0, 1] place the content/window (0.5 = centered). Boxes follow the
    same continuous-coordinate affine (b*s - shift), are clipped to the
    canvas, and rows that collapse below 1px get label -1 / mask False /
    -1-filled geometry — identical to the loader's padded-row
    convention, so downstream target assignment and eval are unaffected.

    Reference parity note: the reference has no augmentation at all
    (`utils/data_loader.py:56-79`); multi-scale training is standard in
    descendants of the original recipe.
    """
    image = sample["image"]
    h, w = image.shape[:2]
    geom = jitter_geometry(h, w, scale, off_y, off_x)
    ch, cw, shift_y, shift_x = geom
    if image.dtype == np.uint8:
        # the repo's canonical u8 resize (fused C++ kernel when built,
        # same half-pixel spec as the numpy fallback) — keeps the
        # device-normalize ingest path off the slow pure-numpy gather
        from replication_faster_rcnn_tpu.data.native_ops import resize_u8

        content = resize_u8(image, (ch, cw))
    else:
        content = _resize_bilinear(image, ch, cw)

    canvas = np.empty_like(image)
    if ch < h or cw < w:  # zoom-in content covers the whole canvas
        fill = image.mean(axis=(0, 1))
        if image.dtype == np.uint8:
            fill = np.clip(np.rint(fill), 0, 255)
        canvas[:] = fill.astype(image.dtype)[None, None, :]
    # content-placement shift: out = in*s - shift (negative = padding)
    src_y0, dst_y0 = max(0, shift_y), max(0, -shift_y)
    src_x0, dst_x0 = max(0, shift_x), max(0, -shift_x)
    span_y = min(ch - src_y0, h - dst_y0)
    span_x = min(cw - src_x0, w - dst_x0)
    canvas[dst_y0 : dst_y0 + span_y, dst_x0 : dst_x0 + span_x] = content[
        src_y0 : src_y0 + span_y, src_x0 : src_x0 + span_x
    ]

    out = jitter_boxes(sample, geom, h, w)
    out["image"] = canvas
    return out


def _splitmix(z: int) -> int:
    z = ((z ^ (z >> 30)) * 0xBF58476D1CE4E5B9) & 0xFFFFFFFFFFFFFFFF
    z = ((z ^ (z >> 27)) * 0x94D049BB133111EB) & 0xFFFFFFFFFFFFFFFF
    return z ^ (z >> 31)


def draw_decisions(seed: int, epoch: int, idx: int, scale_range=None):
    """The per-(seed, epoch, idx) augmentation draws: (flip_bit, scale,
    off_y, off_x); the last three are None without a scale range.

    Shared by :class:`AugmentedView` (host pipeline) and the
    device-resident cache sampler (`data/device_cache.py`) so both feed
    paths make IDENTICAL decisions for the same sample — the counter-mix
    is order-free, so any worker/process/backend agrees."""
    z = _splitmix(
        (
            seed * 0x9E3779B97F4A7C15
            + epoch * 0xBF58476D1CE4E5B9
            + idx * 0x94D049BB133111EB
        )
        & 0xFFFFFFFFFFFFFFFF
    )
    flip = bool(z & 1)
    if scale_range is None:
        return flip, None, None, None
    lo, hi = scale_range
    z2 = _splitmix(z + 0x9E3779B97F4A7C15)
    z3 = _splitmix(z2 + 0x9E3779B97F4A7C15)
    z4 = _splitmix(z3 + 0x9E3779B97F4A7C15)
    u = (z2 >> 11) / float(1 << 53)
    scale = lo + (hi - lo) * u
    off_y = (z3 >> 11) / float(1 << 53)
    off_x = (z4 >> 11) / float(1 << 53)
    return flip, scale, off_y, off_x


def device_decisions(seed: int, epoch: int, idx: int):
    """Host-numpy oracle for the ON-DEVICE draw stream
    (`ops/image.py::augment_draws`): (flip, u_scale, u_off_y, u_off_x,
    u_translate_y, u_translate_x), the uniforms as exact np.float32.

    Same splitmix64 counter-mix as :func:`draw_decisions`, but the +GAMMA
    chain wraps at 64 bits (the device limbs must) and each uniform takes
    the TOP 24 bits scaled by 2^-24 — both exactly representable in f32,
    so device and host compute bit-identical values. A separate stream on
    purpose: the legacy host draws burn 53-bit f64 uniforms that f32
    can't reproduce."""
    z = _splitmix(
        (
            seed * 0x9E3779B97F4A7C15
            + epoch * 0xBF58476D1CE4E5B9
            + idx * 0x94D049BB133111EB
        )
        & 0xFFFFFFFFFFFFFFFF
    )
    flip = bool(z & 1)
    us = []
    for _ in range(5):
        z = _splitmix((z + 0x9E3779B97F4A7C15) & 0xFFFFFFFFFFFFFFFF)
        us.append(np.float32(z >> 40) * np.float32(1.0 / (1 << 24)))
    return (flip, *us)


def translate_sample(
    sample: Dict[str, np.ndarray], dy: int, dx: int
) -> Dict[str, np.ndarray]:
    """Host-numpy oracle for `ops/image.py::translate_batch_with_boxes`:
    output pixel (y, x) reads input (y + dy, x + dx), out-of-range reads
    take the channel-mean fill; real boxes move by (-dy, -dx) with canvas
    clipping, sub-1px rows collapse to the padded-row convention."""
    image = sample["image"]
    h, w = image.shape[:2]
    iy = np.arange(h) + int(dy)
    ix = np.arange(w) + int(dx)
    out_img = image[np.clip(iy, 0, h - 1)][:, np.clip(ix, 0, w - 1)].copy()
    fill = image.astype(np.float32).mean(axis=(0, 1))
    if image.dtype == np.uint8:
        fill = np.clip(np.rint(fill), 0, 255)
    fill = fill.astype(image.dtype)
    invalid = ~(
        ((iy >= 0) & (iy < h))[:, None] & ((ix >= 0) & (ix < w))[None, :]
    )
    out_img[invalid] = fill

    boxes = sample["boxes"].copy()
    labels = sample["labels"].copy()
    mask = sample["mask"].copy() if "mask" in sample else None
    valid = np.asarray(labels >= 0, bool)
    if valid.any():
        b = boxes[valid] - np.asarray(
            [dy, dx, dy, dx], boxes.dtype
        )
        b[:, 0::2] = np.clip(b[:, 0::2], 0.0, float(h))
        b[:, 1::2] = np.clip(b[:, 1::2], 0.0, float(w))
        collapsed = ((b[:, 2] - b[:, 0]) < 1.0) | ((b[:, 3] - b[:, 1]) < 1.0)
        b[collapsed] = -1.0
        boxes[valid] = b
        vi = np.flatnonzero(valid)[collapsed]
        labels[vi] = -1
        if mask is not None:
            mask[vi] = False
    out = dict(sample)
    out["image"] = out_img
    out["boxes"] = boxes
    out["labels"] = labels
    if mask is not None:
        out["mask"] = mask
    return out


def bucket_index(
    seed: int, epoch: int, batch: int, n_buckets: int, chunk: int = 1
) -> int:
    """Deterministic resolution-bucket assignment for one GLOBAL batch.

    Multi-scale bucketed training (data.train_resolutions) keys the
    bucket on (seed, epoch, batch // chunk) through the same splitmix
    counter-mix as :func:`draw_decisions` — a pure function of the
    global batch position, so a `set_epoch(epoch, start_batch=)` resume
    replays the identical bucket sequence, every process of a multi-host
    run agrees on each batch's bucket, and all ``chunk`` batches of one
    fused K-step dispatch (train.steps_per_dispatch) land in the SAME
    bucket (one fused program per dispatch). A distinct salt keeps the
    bucket stream independent of the per-sample augmentation draws.
    """
    if n_buckets <= 1:
        return 0
    z = _splitmix(
        (
            seed * 0x9E3779B97F4A7C15
            + epoch * 0x94D049BB133111EB
            + (batch // max(1, chunk)) * 0xBF58476D1CE4E5B9
            + 0xD1B54A32D192ED03  # bucket-stream salt
        )
        & 0xFFFFFFFFFFFFFFFF
    )
    return int(z % n_buckets)


class AugmentTagView:
    """Device-augmentation feed (data.augment_device): samples pass
    through UNTOUCHED except an attached int32 ``aug = [idx, epoch]`` row.
    The compiled train step draws every augmentation decision from
    (seed, epoch, idx) itself (`ops/image.py::augment_batch`), so the
    host loader stops touching pixels entirely — the last host per-image
    loop of the reference pipeline is gone, not moved."""

    def __init__(self, dataset, epoch: int) -> None:
        self.dataset = dataset
        self.epoch = int(epoch)

    def __len__(self) -> int:
        return len(self.dataset)

    def __getitem__(self, idx: int):
        out = dict(self.dataset[idx])
        out["aug"] = np.asarray([int(idx), self.epoch], np.int32)
        return out


class AugmentedView:
    """Map-style view applying per-sample train augmentations: a 50%
    horizontal flip and/or a scale jitter drawn from ``scale_range``.

    Decisions for (seed, epoch, idx) come from a small counter-based mix
    — not Python ``hash`` (salted for some types) and not a shared RNG
    stream (order-dependent) — so any worker, process or thread,
    computes the same decisions for the same sample.
    """

    def __init__(
        self,
        dataset,
        seed: int,
        epoch: int,
        hflip: bool = True,
        scale_range=None,
        scale_on_device: bool = False,
    ) -> None:
        self.dataset = dataset
        self.seed = int(seed)
        self.epoch = int(epoch)
        self.hflip = bool(hflip)
        if scale_range is not None:
            lo, hi = float(scale_range[0]), float(scale_range[1])
            if not 0.1 <= lo <= hi <= 4.0:
                raise ValueError(
                    f"scale_range must satisfy 0.1 <= lo <= hi <= 4, got {scale_range!r}"
                )
            scale_range = (lo, hi)
        self.scale_range = scale_range
        # device mode: the host transforms boxes only and attaches the
        # integer jitter geometry as sample["jitter"]; the image resample
        # runs on-chip (`ops/image.py::batched_scale_jitter`), so the
        # ~27 ms/600x600 host resample cost disappears from ingest
        self.scale_on_device = bool(scale_on_device) and scale_range is not None

    def __len__(self) -> int:
        return len(self.dataset)

    def __getitem__(self, idx: int):
        sample = self.dataset[idx]
        # splitmix64 finalizer chain on the (seed, epoch, idx) mix; one
        # output bit is the flip coin, further outputs drive the jitter —
        # no per-sample Mersenne Twister construction on the ingest path
        flip, scale, off_y, off_x = draw_decisions(
            self.seed, self.epoch, idx, self.scale_range
        )
        # Order is mode-dependent ON PURPOSE. Host mode keeps the
        # original jitter-then-flip so a fixed (seed, epoch, idx) still
        # byte-reproduces the committed evidence runs
        # (benchmarks/map_overfit_result_aug_scale.json). Device mode is
        # flip-then-jitter: the flip must land before collate (it is a
        # host view), so the on-chip resample always acts on the flipped
        # frame. The two orders are distributionally identical (the
        # placement offsets are uniform and mirror-symmetric).
        if self.scale_on_device and self.hflip and flip:
            sample = hflip_sample(sample)
        if self.scale_range is not None:
            # "did this draw move any pixels?" is decided by the ROUNDED
            # integer geometry, not a deadband on the continuous scale: a
            # scale of 1.0009 at 600 px rounds to a 601-px canvas and IS a
            # jitter, while 1.0004 rounds back to identity
            h, w = sample["image"].shape[:2]
            geom = jitter_geometry(h, w, scale, off_y, off_x)
            jittered = geom != (h, w, 0, 0)
            if self.scale_on_device:
                if jittered:
                    sample = jitter_boxes(sample, geom, h, w)
                out = dict(sample)
                out["jitter"] = np.asarray(geom, np.int32)
                sample = out
            elif jittered:
                sample = scale_jitter_sample(sample, scale, off_y, off_x)
        if not self.scale_on_device and self.hflip and flip:
            sample = hflip_sample(sample)
        return sample
