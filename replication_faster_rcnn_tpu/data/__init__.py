from replication_faster_rcnn_tpu.data.loader import DataLoader, collate, make_dataset  # noqa: F401
from replication_faster_rcnn_tpu.data.prefetch_device import DevicePrefetcher  # noqa: F401
from replication_faster_rcnn_tpu.data.synthetic import SyntheticDataset  # noqa: F401
from replication_faster_rcnn_tpu.data.voc import VOCDataset  # noqa: F401
