"""Device-resident dataset cache: the TPU-native answer to a feed-bound
trainer.

Why: the measured loader-fed trainer at 600x600 b16 runs at ~11 img/s on
the remote v5e while the same step on device-resident tensors runs at
~215 img/s (`benchmarks/loader_throughput.json`, `mfu_experiments.json`)
— the host->device image transfer (69 MB/step f32, 17 MB u8) dwarfs the
74 ms step. The reference has no answer to this: its torch DataLoader
re-decodes and re-ships every image every epoch (`frcnn.py:19-23`,
`utils/data_loader.py:42-48`).

Design (upload once, then index): the whole fixed-shape dataset is
stacked into four contiguous arrays (image [N,H,W,3] uint8/f32, boxes
[N,M,4] f32, labels [N,M] i32, mask [N,M] bool) and placed in HBM once —
VOC2007 trainval at 600x600 uint8 is ~5.4 GB against a v5e's 16 GB.
Every step the host ships ONLY the batch selection (`sel`): indices,
flip bits, jitter geometry — a few hundred bytes. Batch materialization
(gather + hflip + jitter box transform) runs INSIDE the jitted train
step (`train/train_step.py::make_cached_train_step`), where XLA fuses it
with the on-chip normalize (`models/faster_rcnn.py::preprocess`) and the
on-chip scale-jitter resample (`ops/image.py::batched_scale_jitter`).

Augmentation decisions reuse the exact counter-mix the host pipeline
uses (`augment.draw_decisions`), so a cached run and a loader-fed run
with the same (seed, epoch) see identical samples; equivalence is pinned
in `tests/test_device_cache.py`.

Sharding: the cache is REPLICATED over the mesh (every chip holds the
full dataset, each gathers only its batch shard locally — no
collectives). Datasets beyond per-chip HBM need the host loader path or
a sharded cache + local sampling; the byte guard below makes the switch
explicit rather than letting device allocation fail mid-init.
"""

from __future__ import annotations

import os
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from replication_faster_rcnn_tpu.data.augment import (
    draw_decisions,
    jitter_geometry,
)
from replication_faster_rcnn_tpu.data.loader import collate
from replication_faster_rcnn_tpu.telemetry import spans as tspans

# Above this the constructor refuses and points at --cache-ram / the
# host loader instead. v5e-1 has 16 GB HBM; model+optimizer+activations
# for the flagship fit in ~4 GB, so 8 GiB of dataset is a safe default.
DEFAULT_MAX_BYTES = 8 << 30


class DeviceCache:
    """Upload a map-style dataset's samples to device memory once.

    ``mesh`` (optional) replicates the arrays over a `jax.sharding.Mesh`;
    without it the arrays land on the default device.

    ``keep_host_meta`` additionally retains a host-side copy of the small
    non-image arrays (boxes, labels, mask, difficult, ...) as
    ``self.host_meta``. Training never reads ground truth on the host, so
    the trainer leaves this off; the cached-eval path turns it on because
    mAP scoring consumes GT host-side and a second full decode pass to
    re-derive it would defeat the cache.
    """

    def __init__(
        self,
        dataset,
        mesh=None,
        max_bytes: Optional[int] = None,
        keep_host_meta: bool = False,
    ):
        if max_bytes is None:
            max_bytes = int(
                os.environ.get("FRCNN_DEVICE_CACHE_MAX_BYTES", DEFAULT_MAX_BYTES)
            )

        def _over_cap(nbytes: int) -> ValueError:
            return ValueError(
                f"device cache would need {nbytes / 2**30:.2f} GiB "
                f"(> {max_bytes / 2**30:.2f} GiB cap). Use uint8 samples "
                "(data.device_normalize=True / --device-normalize) or fall "
                "back to the host loader (--cache-ram). Override with "
                "FRCNN_DEVICE_CACHE_MAX_BYTES."
            )

        # estimate BEFORE materializing anything: samples are fixed-shape,
        # so sample 0 prices the dataset — an over-cap f32 VOC (~21.6 GB)
        # must hit this error, not the host OOM killer, and must not pay
        # a full decode pass first
        first = {
            k: v for k, v in dataset[0].items() if k != "jitter"
        }
        est = sum(np.asarray(v).nbytes for v in first.values()) * len(dataset)
        if est > max_bytes:
            raise _over_cap(est)
        with tspans.current_tracer().span(
            "data/cache_upload", cat="data", n=len(dataset)
        ):
            stacked = collate([dataset[i] for i in range(len(dataset))])
            # jitter geometry attaches per-step via sel, never via the cache
            stacked.pop("jitter", None)
            nbytes = sum(v.nbytes for v in stacked.values())
            if nbytes > max_bytes:  # exact check (paranoia; shapes are fixed)
                raise _over_cap(nbytes)
            self.nbytes = nbytes
            self.n = len(dataset)
            self.image_hw = tuple(stacked["image"].shape[1:3])
            self.host_meta = (
                {k: v for k, v in stacked.items() if k != "image"}
                if keep_host_meta
                else None
            )
            if mesh is not None:
                from replication_faster_rcnn_tpu.parallel.mesh import replicated

                self.arrays = {
                    k: jax.device_put(v, replicated(mesh))
                    for k, v in stacked.items()
                }
            else:
                self.arrays = {k: jax.device_put(v) for k, v in stacked.items()}

    def __len__(self) -> int:
        return self.n


class CachedSampler:
    """Per-epoch batch selections for a :class:`DeviceCache`.

    Mirrors the host pipeline exactly: the epoch order is the
    DataLoader's ``np.random.RandomState(seed + epoch).permutation``
    (`data/loader.py::DataLoader._order`) and per-sample flip/jitter
    decisions come from the shared `augment.draw_decisions` counter-mix,
    so swapping feed paths changes NOTHING about what the model sees.

    Yields ``sel`` dicts: ``idx`` [B] i32, plus ``flip`` [B] bool when
    hflip is on and ``jitter`` [B,4] i32 when a scale range is set.
    """

    def __init__(
        self,
        n: int,
        image_hw,
        batch_size: int,
        seed: int,
        hflip: bool = False,
        scale_range=None,
        shuffle: bool = True,
        drop_last: bool = True,
        process_index: int = 0,
        process_count: int = 1,
        train_resolutions=(),
        bucket_chunk: int = 1,
    ):
        if scale_range is not None:
            lo, hi = float(scale_range[0]), float(scale_range[1])
            if not 0.1 <= lo <= hi <= 4.0:
                raise ValueError(
                    "scale_range must satisfy 0.1 <= lo <= hi <= 4, "
                    f"got {scale_range!r}"
                )
            scale_range = (lo, hi)
        self.n = int(n)
        self.h, self.w = int(image_hw[0]), int(image_hw[1])
        self.batch_size = int(batch_size)
        self.seed = int(seed)
        self.hflip = bool(hflip)
        self.scale_range = scale_range
        self.shuffle = bool(shuffle)
        self.drop_last = bool(drop_last)
        # Multi-process: each process draws the SAME global epoch order (same
        # seed) and keeps only its contiguous row block — matching the
        # process-contiguous device order of `mesh.make_mesh` so
        # `make_array_from_process_local_data` assembles the intended global
        # batch. draw_decisions is keyed on the GLOBAL sample index, so
        # augmentation is identical across topologies.
        if not 0 <= int(process_index) < int(process_count):
            raise ValueError(
                f"process_index {process_index} out of range for "
                f"process_count {process_count}"
            )
        if self.batch_size % int(process_count) != 0:
            raise ValueError(
                f"batch_size {batch_size} must divide evenly across "
                f"{process_count} processes"
            )
        self.process_index = int(process_index)
        self.process_count = int(process_count)
        # multi-scale buckets: same assignment contract as
        # `DataLoader.bucket_of` — the sel dicts are shape-invariant, the
        # bucket only selects WHICH compiled program consumes them.
        self.train_resolutions = tuple(
            (int(r[0]), int(r[1])) for r in (train_resolutions or ())
        )
        self.bucket_chunk = max(1, int(bucket_chunk))
        self.epoch = 0
        self.start_batch = 0  # mid-epoch offset (set_epoch)

    def set_epoch(self, epoch: int, start_batch: int = 0) -> None:
        """Select the epoch, optionally resuming at a mid-epoch global
        batch offset — same contract as ``DataLoader.set_epoch``: the
        consumed prefix of the deterministic global order is skipped
        without being drawn, and the suffix re-partitions disjointly if
        ``process_count`` changed (elastic fleet shrink)."""
        if start_batch < 0:
            raise ValueError(f"start_batch must be >= 0, got {start_batch}")
        self.epoch = int(epoch)
        self.start_batch = int(start_batch)

    def bucket_of(self, batch_pos: int) -> int:
        """Resolution-bucket index for the global batch at ``batch_pos``
        — identical contract to ``DataLoader.bucket_of`` (pure function
        of seed/epoch/position; 0 when bucketing is off)."""
        if len(self.train_resolutions) <= 1:
            return 0
        from replication_faster_rcnn_tpu.data.augment import bucket_index

        return bucket_index(
            self.seed,
            self.epoch,
            int(batch_pos),
            len(self.train_resolutions),
            chunk=self.bucket_chunk,
        )

    def __len__(self) -> int:
        if self.drop_last:
            return self.n // self.batch_size
        return -(-self.n // self.batch_size)

    def selection(self, idxs: np.ndarray) -> Dict[str, np.ndarray]:
        """The sel dict for explicit sample indices (any feed order)."""
        sel: Dict[str, np.ndarray] = {"idx": np.asarray(idxs, np.int32)}
        if self.hflip:
            sel["flip"] = np.array(
                [
                    draw_decisions(self.seed, self.epoch, int(i),
                                   self.scale_range)[0]
                    for i in idxs
                ],
                dtype=bool,
            )
        if self.scale_range is not None:
            geoms = []
            for i in idxs:
                _, scale, off_y, off_x = draw_decisions(
                    self.seed, self.epoch, int(i), self.scale_range
                )
                geoms.append(
                    jitter_geometry(self.h, self.w, scale, off_y, off_x)
                )
            sel["jitter"] = np.asarray(geoms, np.int32)
        return sel

    def __iter__(self):
        if self.shuffle:
            order = np.random.RandomState(self.seed + self.epoch).permutation(
                self.n
            )
        else:
            order = np.arange(self.n)
        bs = self.batch_size
        local = bs // self.process_count
        lo = self.process_index * local
        end = len(order) - (len(order) % bs if self.drop_last else 0)
        for i in range(self.start_batch * bs, end, bs):
            yield self.selection(order[i + lo : i + lo + local])


def stack_selections(sels) -> Dict[str, np.ndarray]:
    """Stack K per-step selection dicts into one [K, B, ...] chunk for the
    fused multi-step dispatch (`train/train_step.py::make_cached_multi_step`
    scans over the leading axis). All selections must carry the same keys —
    they come from one `CachedSampler`, so they do."""
    if not sels:
        raise ValueError("stack_selections needs at least one selection")
    return {k: np.stack([s[k] for s in sels]) for k in sels[0]}


def materialize_batch(
    cache: Dict[str, jax.Array], sel: Dict[str, jax.Array]
) -> Dict[str, jax.Array]:
    """Device-side batch assembly: gather + hflip + jitter box affine.

    Runs inside the jitted step. Reproduces the host device-mode pipeline
    (`augment.AugmentedView` with ``scale_on_device``) op for op:
    flip-then-jitter, flips keyed on ``labels >= 0``, jitter box collapse
    to the padded-row convention. The image's jitter RESAMPLE is not done
    here — the ``jitter`` key passes through to `compute_losses`, which
    feeds `ops/image.py::batched_scale_jitter` exactly as the host
    device-jitter path does.
    """
    idx = sel["idx"]
    gathered = {k: jnp.take(v, idx, axis=0) for k, v in cache.items()}
    images = gathered["image"]
    boxes = gathered["boxes"]
    labels = gathered["labels"]
    mask = gathered["mask"]
    h = float(cache["image"].shape[1])
    w = float(cache["image"].shape[2])

    if "flip" in sel:
        flip = sel["flip"]
        images = jnp.where(flip[:, None, None, None], images[:, :, ::-1, :], images)
        valid = labels >= 0
        flipped_boxes = jnp.stack(
            [boxes[..., 0], w - boxes[..., 3], boxes[..., 2], w - boxes[..., 1]],
            axis=-1,
        )
        boxes = jnp.where((flip[:, None] & valid)[..., None], flipped_boxes, boxes)

    if "jitter" in sel:
        geom = sel["jitter"].astype(jnp.float32)  # [B, 4] (ch, cw, sy, sx)
        sy = (geom[:, 0] / h)[:, None]
        sx = (geom[:, 1] / w)[:, None]
        shift_y = geom[:, 2][:, None]
        shift_x = geom[:, 3][:, None]
        valid = labels >= 0
        # Per-row identity guard: the host path (`AugmentedView.__getitem__`)
        # skips jitter_boxes entirely when the rounded geometry is
        # (h, w, 0, 0) — a draw that resolves to no-op. Without the same
        # skip here the <1px collapse below would kill a raw GT box that is
        # already sub-pixel, even though no geometry was applied to it.
        identity = (
            (geom[:, 0] == h)
            & (geom[:, 1] == w)
            & (geom[:, 2] == 0.0)
            & (geom[:, 3] == 0.0)
        )[:, None]
        applied = valid & ~identity
        jb = jnp.stack(
            [
                boxes[..., 0] * sy - shift_y,
                boxes[..., 1] * sx - shift_x,
                boxes[..., 2] * sy - shift_y,
                boxes[..., 3] * sx - shift_x,
            ],
            axis=-1,
        )
        jb = jb.at[..., 0::2].set(jnp.clip(jb[..., 0::2], 0.0, h))
        jb = jb.at[..., 1::2].set(jnp.clip(jb[..., 1::2], 0.0, w))
        collapsed = ((jb[..., 2] - jb[..., 0]) < 1.0) | (
            (jb[..., 3] - jb[..., 1]) < 1.0
        )
        dead = applied & collapsed
        jb = jnp.where(dead[..., None], -1.0, jb)
        boxes = jnp.where(applied[..., None], jb, boxes)
        labels = jnp.where(dead, -1, labels)
        mask = jnp.where(dead, False, mask)

    batch = dict(gathered)  # pass-through keys (e.g. 'difficult') ride along
    batch.update(image=images, boxes=boxes, labels=labels, mask=mask)
    if "jitter" in sel:
        batch["jitter"] = sel["jitter"]
    return batch
