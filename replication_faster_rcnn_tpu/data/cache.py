"""Decoded-sample RAM cache for the host input pipeline.

Why: on a single-core host the JPEG decode + resize/normalize hot path
(one native call per sample, `data/native_ops.py`) tops out well below one
chip's ingest demand (`benchmarks/loader_throughput.json`: 86 img/s thread
loader vs ~210 img/s device demand at 600x600 b16) and no worker count can
change that — there is one core. Decode cost is per *epoch* though, and a
Faster R-CNN sample is small and fixed-shape (600x600x3 f32 image + a few
KB of boxes/labels ≈ 4.3 MB), so the whole of VOC trainval (~5k images ≈
22 GB) fits comfortably in host RAM. Caching the decoded sample dict makes
every epoch after the first a memcpy, which a single core sustains at
GB/s — orders of magnitude above device demand.

This replaces what the reference leaves on the table: its torch DataLoader
(`frcnn.py:19-23`) re-decodes every image every epoch.

Placement: the cache wraps the *base* dataset, below `AugmentedView`
(`data/augment.py`) — flips stay per-(seed, epoch, index) on top of cached
decodes, and `hflip_sample` copies instead of mutating, so cached arrays
are never written. Consumers must treat samples as read-only (`collate`'s
np.stack copies).
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

import numpy as np


class CachedView:
    """Map-style view memoizing ``dataset[i]`` sample dicts in RAM.

    First access per index pays the full decode; later accesses return the
    stored dict (shallow-copied so callers replacing keys — e.g.
    ``hflip_sample`` — never touch the cache entry).

    Thread-safety: the hot (cached) path is a lock-free dict read; the
    cold path takes a lock around insert+byte-accounting only, so two
    threads racing on the same cold index may both decode (wasted work)
    but charge the byte budget exactly once.

    Fork-process workers: a child populates its *own* copy-on-write cache,
    discarded when the worker exits (each epoch forks fresh workers). Call
    :meth:`warm` in the parent first if process mode must share the cache;
    on the one-core hosts this cache targets, thread mode is the right
    mode anyway.

    ``max_bytes`` (default 64 GiB, env ``FRCNN_CACHE_MAX_BYTES``) bounds
    the cache: once the running total of stored sample bytes would exceed
    it, further samples pass through uncached (no eviction — epoch access
    is uniform, so evicting one entry to admit another buys nothing).
    """

    def __init__(self, dataset, max_bytes: Optional[int] = None) -> None:
        import os

        self.dataset = dataset
        if max_bytes is None:
            max_bytes = int(
                os.environ.get("FRCNN_CACHE_MAX_BYTES", str(64 << 30))
            )
        self.max_bytes = int(max_bytes)
        self._cache: Dict[int, Dict[str, np.ndarray]] = {}
        self._bytes = 0
        self._full = False
        self._lock = threading.Lock()

    def __len__(self) -> int:
        return len(self.dataset)

    def __getattr__(self, name):
        # delegate dataset metadata (class names, ids, ...) transparently
        return getattr(self.dataset, name)

    @property
    def nbytes(self) -> int:
        """Bytes currently held by cached samples."""
        return self._bytes

    def warm(self) -> None:
        """Decode every sample into the cache (first-epoch cost, paid
        up front — e.g. in a fork-mode parent before workers fork)."""
        for i in range(len(self)):
            self[i]

    def __getitem__(self, idx: int) -> Dict[str, np.ndarray]:
        idx = int(idx)
        hit = self._cache.get(idx)
        if hit is not None:
            return dict(hit)
        sample = self.dataset[idx]
        if not self._full:
            size = sum(
                v.nbytes for v in sample.values() if isinstance(v, np.ndarray)
            )
            with self._lock:
                if idx not in self._cache:
                    if self._bytes + size <= self.max_bytes:
                        self._cache[idx] = sample
                        self._bytes += size
                    else:
                        self._full = True
        return dict(sample)
