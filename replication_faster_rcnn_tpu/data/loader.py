"""Batching + background prefetch — the TPU-native replacement for
``torch.utils.data.DataLoader`` (reference `frcnn.py:19-23`, SURVEY.md §2.3
"host-side input pipeline ... feeding device").

Design: the dataset's __getitem__ is pure numpy on host; a background
thread pool assembles fixed-shape batches ahead of the training loop into a
bounded queue, so the host pipeline overlaps device step time (SURVEY.md §7
hard part #4 — input-bound chips waste the 6x target). Batches are plain
dicts of stacked numpy arrays; the trainer moves them to device (sharded
`jax.device_put`) itself, keeping this module framework-free.

Epoch semantics mirror the reference trainer: sequential or seeded-shuffle
order, drop_last (the fixed-shape train step wants full batches).

Threads (not processes) are enough to scale ingest across cores: the
sample hot path — JPEG decode + fused resize/normalize — is one ctypes
call into native/frcnn_native.cpp, and ctypes releases the GIL for the
call's duration, so ``num_workers`` decode threads genuinely run in
parallel (the torch DataLoader needs worker *processes* because its
Python-side transforms hold the GIL).
"""

from __future__ import annotations

import multiprocessing
import queue
import threading
import time
import traceback
from concurrent import futures
from typing import Dict, Iterator, Optional, Sequence

import numpy as np

from replication_faster_rcnn_tpu.faultlib import failpoints
from replication_faster_rcnn_tpu.telemetry import spans as tspans


def collate(samples: Sequence[Dict[str, np.ndarray]]) -> Dict[str, np.ndarray]:
    """Stack per-sample dicts into one batch dict."""
    return {k: np.stack([s[k] for s in samples]) for k in samples[0]}


def fetch_sample(ds, idx: int, on_skip=None):
    """``ds[idx]`` with fault containment: one retry (truncated reads and
    NFS hiccups are transient), then deterministic substitution by the
    nearest following index that decodes — one rotten JPEG two hours into
    an epoch must cost one sample, not the run.

    ``on_skip(idx, exc)`` is called once per abandoned index (after the
    failed retry) and may raise to enforce a skip budget — substitution
    without a cap would silently train on a collapsing dataset. With no
    ``on_skip`` the substitution is unbudgeted. Raises the last error only
    if every index in the dataset fails.

    The ``loader.fetch`` failpoint wraps every dataset access (the
    original, the retry, and each substitution probe), so an injected
    IOError rides exactly this containment and an injected ``nan`` fault
    poisons the decoded sample the way a corrupt image would.
    """

    def _get(i: int):
        inj = failpoints.fire("loader.fetch", index=int(i))  # ioerror raises
        sample = ds[int(i)]
        if inj is not None and inj.kind == "nan":
            sample = failpoints.poison_batch(sample)
        return sample

    try:
        return _get(idx)
    except Exception:
        try:
            return _get(idx)  # the one retry
        except Exception as exc:
            if on_skip is not None:
                on_skip(int(idx), exc)
            n = len(ds)
            for delta in range(1, n):
                j = (int(idx) + delta) % n
                try:
                    return _get(j)
                except Exception:
                    continue
            raise


def _mp_worker(dataset, task_q, result_q, skip_budget: int = 0) -> None:
    """Worker-process loop: build collated batches for index lists.

    Runs only dataset/numpy code — no jax, no device ops (a forked child
    must never touch the TPU tunnel). Errors are shipped back as
    formatted tracebacks: exception objects aren't reliably picklable.

    Failing samples get the same retry-then-substitute treatment as the
    thread path (``fetch_sample``), with a per-worker skip budget —
    worker counters can't be shared cheaply across processes, and since
    workers are re-forked each epoch a per-worker cap is the per-epoch
    cap divided by the worker count, same order of protection.
    """
    skips = 0

    def on_skip(idx, exc):
        nonlocal skips
        skips += 1
        if skip_budget and skips > skip_budget:
            raise RuntimeError(
                f"loader worker sample-skip budget exhausted: {skips} "
                f"failed samples (> {skip_budget}); last at index {idx}: "
                f"{exc!r}"
            )

    while True:
        item = task_q.get()
        if item is None:
            return
        seq, idxs = item
        try:
            if skip_budget:
                batch = collate([fetch_sample(dataset, i, on_skip) for i in idxs])
            else:  # containment disabled
                batch = collate([dataset[int(i)] for i in idxs])
            result_q.put((seq, batch))
        except BaseException:  # noqa: BLE001 — report, don't kill the worker
            result_q.put((seq, ("__error__", traceback.format_exc())))


class DataLoader:
    """Iterable over fixed-shape batches with background prefetch.

    Args:
      dataset: map-style dataset (len + __getitem__ -> dict of numpy).
      batch_size: per-iteration global batch.
      shuffle: seeded reshuffle each epoch (seed + epoch), deterministic —
        required for checkpoint-resume reproducibility (SURVEY.md §5).
      drop_last: drop the trailing partial batch (default True: the jitted
        step is compiled for exactly batch_size).
      prefetch: max batches buffered ahead (0 disables threading).
      num_workers: workers assembling samples within a batch; negative
        means auto — min(4, schedulable cores): on a 1-core host a
        4-thread pool measured SLOWER than single-thread ingest
        (benchmarks/loader_throughput.json).
      cache_ram: memoize decoded samples in host RAM (`data/cache.py`):
        epoch 1 pays the decode, every later epoch is a memcpy. The
        single-core answer to an input-bound chip — decode throughput
        can't be scaled by workers when there is one core. Bounded by
        FRCNN_CACHE_MAX_BYTES (default 64 GiB).
      worker_mode: "thread" (default — the native decode path releases
        the GIL, so threads scale it across cores) or "process" —
        fork-based worker processes, one whole batch per task, results
        re-ordered to the deterministic epoch order. Use "process" when
        the per-sample work is GIL-bound Python (the numpy fallback
        decode path, heavy augmentation), where threads serialize
        (VERDICT r2 weak #3: the thread loader was GIL-capped at 1x).
        Fork (not spawn) on purpose: a spawned child re-imports through
        sitecustomize and would register the TPU plugin — a forked one
        inherits the parent's modules and runs only numpy code.
    """

    def __init__(
        self,
        dataset,
        batch_size: int,
        shuffle: bool = True,
        drop_last: bool = True,
        prefetch: int = 2,
        num_workers: int = 4,
        seed: int = 0,
        worker_mode: str = "thread",
        augment_hflip: bool = False,
        augment_scale=None,
        augment_scale_device: bool = False,
        augment_device: bool = False,
        augment_translate: float = 0.0,
        stall_timeout: float = 120.0,
        cache_ram: bool = False,
        sample_skip_budget: int = 8,
        process_index: int = 0,
        process_count: int = 1,
        train_resolutions=(),
        bucket_chunk: int = 1,
    ) -> None:
        if worker_mode not in ("thread", "process"):
            raise ValueError(f"worker_mode must be thread|process, got {worker_mode!r}")
        if process_count < 1 or not 0 <= process_index < process_count:
            raise ValueError(
                f"process_index={process_index} out of range for "
                f"process_count={process_count}"
            )
        if batch_size % process_count:
            raise ValueError(
                f"global batch_size={batch_size} must divide evenly over "
                f"{process_count} processes"
            )
        # multi-process data sharding: every process draws the SAME
        # deterministic global epoch order (seed + epoch), then each keeps
        # only its contiguous rows of every global batch — matching the
        # mesh's process-contiguous device order, so
        # `parallel.shard_batch` can assemble the global array from local
        # rows with zero cross-host traffic. Augment draws key on the
        # GLOBAL sample index, so the global batch content is independent
        # of the process count (topology-change-tolerant resume).
        self.process_index = int(process_index)
        self.process_count = int(process_count)
        self.stall_timeout = float(stall_timeout)
        self.augment_hflip = augment_hflip
        self.augment_scale = augment_scale
        self.augment_scale_device = augment_scale_device
        self.augment_device = augment_device
        self.augment_translate = float(augment_translate)
        if cache_ram:
            from replication_faster_rcnn_tpu.data.cache import CachedView

            dataset = CachedView(dataset)
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.prefetch = prefetch
        if num_workers < 0:  # auto: scale with the host, never beyond 4
            import os

            try:  # cores this process may RUN on (cgroup/taskset-aware)
                avail = len(os.sched_getaffinity(0))
            except (AttributeError, OSError):
                avail = os.cpu_count() or 1
            num_workers = min(4, avail)
        self.num_workers = max(1, num_workers)
        self.seed = seed
        self.worker_mode = worker_mode
        self.epoch = 0
        self.start_batch = 0  # mid-epoch offset (set_epoch)
        self._q: Optional["queue.Queue"] = None  # live prefetch queue
        # sample fault containment (fetch_sample): failed samples are
        # retried once then substituted, up to this many per epoch — past
        # it the epoch errors out (a collapsing dataset must not be
        # silently papered over). 0 disables containment entirely.
        self.sample_skip_budget = int(sample_skip_budget)
        self._epoch_skips = 0
        self._skip_lock = threading.Lock()
        # multi-scale buckets (data.train_resolutions): the feed only
        # ASSIGNS each global batch to a bucket (bucket_of); the resample
        # to the bucket's shape runs on device inside that bucket's
        # compiled program. bucket_chunk = train.steps_per_dispatch so all
        # K batches of one fused dispatch share a bucket.
        self.train_resolutions = tuple(
            (int(r[0]), int(r[1])) for r in (train_resolutions or ())
        )
        self.bucket_chunk = max(1, int(bucket_chunk))

    def set_epoch(self, epoch: int, start_batch: int = 0) -> None:
        """Select the epoch — and optionally a mid-epoch offset.

        ``start_batch`` resumes iteration at that global batch index of
        the epoch's deterministic order: the consumed prefix is never
        decoded or collated (unlike draw-and-discard replay), and the
        remaining suffix is bitwise identical to an uninterrupted epoch —
        the global order is a pure function of (seed, epoch), so slicing
        it is exact. Elastic fleet shrink leans on the same property: a
        re-formed feed at a NEW process_count and the same ``start_batch``
        re-partitions the unconsumed suffix disjointly across the new
        world size."""
        if start_batch < 0:
            raise ValueError(f"start_batch must be >= 0, got {start_batch}")
        self.epoch = epoch
        self.start_batch = int(start_batch)
        with self._skip_lock:  # pool workers bump the counter concurrently
            self._epoch_skips = 0  # the skip budget is per-epoch

    def _on_sample_skip(self, idx: int, exc: Exception) -> None:
        """Budget + telemetry for one abandoned sample (thread path; pool
        workers land here concurrently, hence the lock)."""
        with self._skip_lock:
            self._epoch_skips += 1
            skips = self._epoch_skips
        if skips > self.sample_skip_budget:
            raise RuntimeError(
                f"loader sample-skip budget exhausted: {skips} failed "
                f"samples this epoch (> {self.sample_skip_budget}); last "
                f"at index {idx}: {exc!r}"
            )
        import sys

        print(
            f"warning: sample {idx} failed twice, substituting neighbor "
            f"({skips}/{self.sample_skip_budget} skips this epoch): {exc!r}",
            file=sys.stderr,
        )
        tspans.current_tracer().instant(
            "data/sample_skipped", cat="data", idx=int(idx),
            skips=skips, error=repr(exc)[:200],
        )

    def bucket_of(self, batch_pos: int) -> int:
        """Resolution-bucket index for the GLOBAL batch at ``batch_pos``
        of the current epoch — a pure function of (seed, epoch,
        batch_pos // bucket_chunk), so every process agrees, a
        ``set_epoch(epoch, start_batch=)`` resume replays the identical
        sequence, and the local row-block sharding keeps each bucket's
        shards disjoint exactly like the unbucketed feed. Returns 0 when
        bucketing is off."""
        if len(self.train_resolutions) <= 1:
            return 0
        from replication_faster_rcnn_tpu.data.augment import bucket_index

        return bucket_index(
            self.seed,
            self.epoch,
            int(batch_pos),
            len(self.train_resolutions),
            chunk=self.bucket_chunk,
        )

    def queue_depth(self) -> Optional[int]:
        """Batches currently buffered ahead of the consumer (thread-mode
        prefetch only; None before iteration or in process mode). A depth
        pinned at 0 under load means the feed can't keep up — the number
        the watchdog snapshots to tell feed-starvation from a wedged
        device."""
        q = self._q
        return q.qsize() if q is not None else None

    def _order(self) -> np.ndarray:
        n = len(self.dataset)
        if not self.shuffle:
            return np.arange(n)
        rng = np.random.RandomState(self.seed + self.epoch)
        return rng.permutation(n)

    def __len__(self) -> int:
        n = len(self.dataset)
        return n // self.batch_size if self.drop_last else -(-n // self.batch_size)

    def _batches(self) -> Iterator[np.ndarray]:
        order = self._order()
        bs = self.batch_size
        end = len(order) - (len(order) % bs if self.drop_last else 0)
        local = bs // self.process_count
        lo = self.process_index * local
        for i in range(self.start_batch * bs, end, bs):
            # this process's contiguous block of the global batch (the
            # whole batch in single-process runs: lo=0, local=bs)
            yield order[i + lo : i + lo + local]

    def _epoch_dataset(self):
        """The dataset view for the current epoch: identity, or the
        deterministic hflip/scale-jitter augmentations keyed on
        (seed, epoch, idx) — computed per-iteration so set_epoch()
        re-rolls the draws while resume replays them exactly."""
        if self.augment_device and (
            self.augment_hflip or self.augment_scale or self.augment_translate
        ):
            # fully on-device mode: the host ships raw pixels plus the
            # int32 (idx, epoch) row the compiled step's splitmix draws
            # key on — no host flip, no host box affine, no host resample
            from replication_faster_rcnn_tpu.data.augment import AugmentTagView

            return AugmentTagView(self.dataset, self.epoch)
        if not (self.augment_hflip or self.augment_scale):
            return self.dataset
        from replication_faster_rcnn_tpu.data.augment import AugmentedView

        return AugmentedView(
            self.dataset,
            self.seed,
            self.epoch,
            hflip=self.augment_hflip,
            scale_range=self.augment_scale,
            scale_on_device=self.augment_scale_device,
        )

    def _build(
        self, idxs: np.ndarray, pool: Optional[futures.ThreadPoolExecutor], ds
    ) -> Dict[str, np.ndarray]:
        # decode+augment+collate for one batch; runs on the producer thread,
        # so under healthy prefetch these spans OVERLAP step spans in the
        # trace — visibly parallel lanes, not a serial pipeline
        with tspans.current_tracer().span(
            "data/build", cat="data", batch=len(idxs)
        ):
            if not self.sample_skip_budget:  # containment disabled
                if pool is None or len(idxs) == 1:
                    return collate([ds[int(i)] for i in idxs])
                return collate(list(pool.map(lambda i: ds[int(i)], idxs)))
            on_skip = self._on_sample_skip
            if pool is None or len(idxs) == 1:
                return collate([fetch_sample(ds, i, on_skip) for i in idxs])
            return collate(
                list(pool.map(lambda i: fetch_sample(ds, i, on_skip), idxs))
            )

    def _iter_processes(self) -> Iterator[Dict[str, np.ndarray]]:
        """Process-worker iteration: whole batches farmed to forked
        workers, yielded strictly in epoch order (a reorder buffer keyed
        on sequence number — checkpoint-resume reproducibility must not
        depend on worker scheduling). In-flight tasks are bounded so the
        result queue never holds more than workers+prefetch batches."""
        from replication_faster_rcnn_tpu.data.cache import CachedView

        if isinstance(self.dataset, CachedView):
            # forked workers fill copy-on-write caches that die with them
            # (workers are re-forked each epoch) — warming in the parent
            # FIRST makes the cache genuinely shared; without this,
            # cache_ram + process mode silently re-decodes every epoch
            self.dataset.warm()
        ctx = multiprocessing.get_context("fork")
        task_q = ctx.Queue()
        result_q = ctx.Queue()
        ds = self._epoch_dataset()
        procs = [
            ctx.Process(
                target=_mp_worker,
                args=(ds, task_q, result_q, self.sample_skip_budget),
                daemon=True,
            )
            for _ in range(self.num_workers)
        ]
        for p in procs:
            p.start()
        try:
            batches = list(self._batches())
            cap = self.num_workers + max(self.prefetch, 1)
            next_submit = next_yield = 0
            buf: Dict[int, object] = {}
            while next_yield < len(batches):
                while next_submit < len(batches) and next_submit - next_yield < cap:
                    task_q.put((next_submit, batches[next_submit]))
                    next_submit += 1
                # per-wait clock: time spent *waiting on this batch*, not
                # time since the last receipt — consumer time at yield
                # (train steps, compiles) must not count toward the
                # deadline; a truly deadlocked worker still never delivers
                last_progress = time.monotonic()
                while next_yield not in buf:
                    try:
                        seq, payload = result_q.get(
                            timeout=min(5.0, self.stall_timeout)
                        )
                    except queue.Empty:
                        # a forked worker can die without reporting (OOM
                        # kill, native-decode segfault) — fail loudly
                        # instead of blocking forever on a batch that
                        # will never arrive
                        dead = [p for p in procs if not p.is_alive()]
                        if dead:
                            codes = [p.exitcode for p in dead]
                            raise RuntimeError(
                                f"{len(dead)} loader worker(s) died "
                                f"(exitcodes {codes}) before batch "
                                f"{next_yield} arrived"
                            )
                        # liveness isn't progress: a fork-inherited lock
                        # deadlock (the primary risk of forking a
                        # multithreaded JAX parent) leaves workers alive
                        # but forever silent — an overall no-progress
                        # deadline turns that silent hang into an error
                        if time.monotonic() - last_progress > self.stall_timeout:
                            raise RuntimeError(
                                "loader made no progress for "
                                f"{self.stall_timeout:.0f}s waiting on batch "
                                f"{next_yield} with all {len(procs)} workers "
                                "alive — likely a fork-inherited lock "
                                "deadlock; use worker_mode='thread' or "
                                "raise stall_timeout"
                            )
                        continue
                    buf[seq] = payload
                    last_progress = time.monotonic()
                payload = buf.pop(next_yield)
                next_yield += 1
                if isinstance(payload, tuple) and payload and payload[0] == "__error__":
                    raise RuntimeError(f"loader worker failed:\n{payload[1]}")
                yield payload
        finally:
            for _ in procs:
                try:
                    task_q.put_nowait(None)
                except Exception:  # noqa: BLE001 — teardown best-effort
                    pass
            for p in procs:
                p.join(timeout=2)
                if p.is_alive():
                    p.terminate()

    def __iter__(self) -> Iterator[Dict[str, np.ndarray]]:
        if self.worker_mode == "process" and self.num_workers > 1:
            yield from self._iter_processes()
            return
        # one pool per iteration, reused across every batch (pool
        # creation/teardown per batch is measurable on the hot input path)
        pool: Optional[futures.ThreadPoolExecutor] = None
        if self.num_workers > 1:
            pool = futures.ThreadPoolExecutor(self.num_workers)
        ds = self._epoch_dataset()

        if self.prefetch <= 0:
            try:
                for idxs in self._batches():
                    yield self._build(idxs, pool, ds)
            finally:
                if pool is not None:
                    pool.shutdown(wait=False)
            return

        q: "queue.Queue" = queue.Queue(maxsize=self.prefetch)
        self._q = q
        stop = threading.Event()
        err: list = []

        def put_unless_stopped(item) -> bool:
            """Bounded put that gives up once the consumer is gone — a plain
            q.put could block forever on an abandoned iterator."""
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def producer() -> None:
            try:
                for idxs in self._batches():
                    if stop.is_set():
                        return
                    if not put_unless_stopped(self._build(idxs, pool, ds)):
                        return
            except BaseException as e:  # surface worker errors to the consumer
                err.append(e)
            finally:
                put_unless_stopped(None)
                if pool is not None:
                    pool.shutdown(wait=False)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        tracer = tspans.current_tracer()
        try:
            while True:
                batch = q.get()
                if batch is None:
                    if err:
                        raise err[0]
                    return
                tracer.counter("loader/queue_depth", q.qsize())
                yield batch
        finally:
            stop.set()
            self._q = None
            while not q.empty():
                q.get_nowait()


def make_dataset(cfg, split: str = "train", **kwargs):
    """Dataset factory keyed on DataConfig.dataset."""
    from replication_faster_rcnn_tpu.config import DataConfig  # noqa: F401

    kind = cfg.dataset
    if kind == "voc":
        from replication_faster_rcnn_tpu.data.voc import VOCDataset

        return VOCDataset(cfg, split, **kwargs)
    if kind == "coco":
        from replication_faster_rcnn_tpu.data.coco import COCODataset

        split_map = {"train": "train2017", "val": "val2017"}
        return COCODataset(cfg, split_map.get(split, split), **kwargs)
    if kind == "synthetic":
        from replication_faster_rcnn_tpu.data.synthetic import SyntheticDataset

        return SyntheticDataset(cfg, split, **kwargs)
    raise ValueError(f"unknown dataset kind {kind!r}")
