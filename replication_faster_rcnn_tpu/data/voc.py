"""Pascal VOC dataset — capability parity with reference
`utils/data_loader.py:17-117` (``voc_data``), rebuilt for fixed-shape TPU
feeding:

  * JPEG via PIL, XML via stdlib ``xml.etree`` (the reference uses
    skimage + xmltodict, neither of which this image ships).
  * Resize to a fixed ``image_size`` (reference ``new_size=(600,600)``,
    `data_loader.py:21`), scale boxes by new/old dims and round
    (`data_loader.py:66-69,115`).
  * Boxes are row-major ``[ymin, xmin, ymax, xmax]`` — the reference swaps
    xml's (xmin, ymin) into this order at `data_loader.py:105`.
  * Labels/boxes padded to ``max_boxes`` with -1 (`data_loader.py:88-89`);
    ``difficult`` objects get label -1 unless enabled (`data_loader.py:108-109`).
  * ImageNet mean/std normalization (`data_loader.py:38`).

Deliberate fixes vs the reference (SURVEY.md §5 "failure detection"): XML
parse errors raise instead of being silently converted to -1 labels by a
broad ``except``; 1-based inclusive XML coords are converted to the
package-wide 0-based continuous convention (mins - 1; the reference keeps
them raw at `data_loader.py:105`, leaving a latent 1px skew under any
geometric transform); and the split file defaults to the full ``{split}.txt``
imageset rather than the aeroplane-only file hard-coded at
`data_loader.py:48` (whose per-class ±1 flags the reference ignores anyway
— it reads only the id column; pass ``image_set='aeroplane'`` for strict
reference behavior).
"""

from __future__ import annotations

import os
import xml.etree.ElementTree as ET
from typing import Dict, List, Optional

import numpy as np

from replication_faster_rcnn_tpu.config import DataConfig, VOC_CLASSES
from replication_faster_rcnn_tpu.data import native_ops


def _load_image(path: str, image_size, pixel_mean, pixel_std,
                device_normalize: bool = False):
    """JPEG -> normalized float32 [H, W, 3] + original size — or, with
    ``device_normalize``, resized uint8 (normalization deferred to the
    model's on-device preprocess, a quarter of the host->device bytes).

    Fast path: one native C++ call does decode + RGB conversion + bilinear
    resize + normalize (native/frcnn_native.cpp, libjpeg with DCT-domain
    prescaling) — the fused host-side pipeline standing in for the
    reference's skimage resize + torch Normalize
    (`utils/data_loader.py:38,72`). Fallback (no native lib, or the file
    isn't a decodable JPEG): PIL decode + the resize_normalize kernel.
    """
    with open(path, "rb") as f:
        data = f.read()
    native = (
        native_ops.decode_jpeg_resize_u8(data, image_size)
        if device_normalize
        else native_ops.decode_jpeg_resize_normalize(
            data, image_size, pixel_mean, pixel_std
        )
    )
    if native is not None:
        return native
    import io

    from PIL import Image

    with Image.open(io.BytesIO(data)) as im:
        im = im.convert("RGB")
        orig_w, orig_h = im.size
        arr = np.asarray(im, np.uint8)
    if device_normalize:
        return native_ops.resize_u8(arr, image_size), orig_h, orig_w
    out = native_ops.resize_normalize(arr, image_size, pixel_mean, pixel_std)
    return out, orig_h, orig_w


class VOCDataset:
    """Map-style dataset yielding fixed-shape numpy samples.

    __getitem__ -> {'image' [H,W,3] f32 normalized, 'boxes' [M,4] f32,
                    'labels' [M] i32 (class id, -1 pad; difficult objects
                    KEEP their class label — 'difficult'/'mask' carry the
                    distinction, and augmentation keys geometry on
                    labels >= 0),
                    'mask' [M] bool}
    """

    classes = VOC_CLASSES

    def __init__(
        self,
        cfg: DataConfig,
        split: str = "train",
        image_set: Optional[str] = None,
    ) -> None:
        if split not in ("train", "val", "trainval", "test"):
            raise ValueError(f"bad split {split!r}")
        self.cfg = cfg
        self.split = split
        self.root = cfg.root_dir
        self.class_to_id = {c: i for i, c in enumerate(self.classes)}

        name = f"{image_set}_{split}.txt" if image_set else f"{split}.txt"
        list_path = os.path.join(self.root, "ImageSets", "Main", name)
        with open(list_path) as f:
            self.ids: List[str] = [ln.split()[0] for ln in f if ln.strip()]

    def __len__(self) -> int:
        return len(self.ids)

    def _parse_annotation(self, xml_path: str):
        """XML -> (labels [M], boxes [M, 4], difficult [M]) padded with -1.

        Labels always carry the class (also for difficult objects); the
        ``difficult`` flags let training mask them out (reference behavior,
        `data_loader.py:108-109`) while evaluation treats them as
        ignore-regions per the official VOC protocol."""
        m = self.cfg.max_boxes
        labels = np.full((m,), -1, np.int32)
        boxes = np.full((m, 4), -1.0, np.float32)
        difficult = np.zeros((m,), bool)
        root = ET.parse(xml_path).getroot()
        i = 0
        for obj in root.iter("object"):
            if i >= m:  # reference caps at n_obj (`data_loader.py:97-99`)
                break
            name = obj.findtext("name")
            if name not in self.class_to_id:
                raise ValueError(f"unknown class {name!r} in {xml_path}")
            bnd = obj.find("bndbox")
            # VOC XML coords are 1-based inclusive pixel indices; convert
            # to the 0-based continuous convention used everywhere else in
            # this package (a pixel span [i..j] inclusive is [i-1, j) + 1
            # = [i-1, j] continuous): subtract 1 from the mins, keep the
            # maxes. This makes hflip's x' = W - x reflection exact and
            # keeps width = xmax - xmin equal to the inclusive pixel count.
            boxes[i] = [
                float(bnd.findtext("ymin")) - 1.0,
                float(bnd.findtext("xmin")) - 1.0,
                float(bnd.findtext("ymax")),
                float(bnd.findtext("xmax")),
            ]
            labels[i] = self.class_to_id[name]
            difficult[i] = obj.findtext("difficult", default="0").strip() == "1"
            i += 1
        return labels, boxes, difficult

    def __getitem__(self, idx: int) -> Dict[str, np.ndarray]:
        img_id = self.ids[idx]
        img_path = os.path.join(self.root, "JPEGImages", img_id + ".jpg")
        xml_path = os.path.join(self.root, "Annotations", img_id + ".xml")

        image, orig_h, orig_w = _load_image(
            img_path, self.cfg.image_size, self.cfg.pixel_mean,
            self.cfg.pixel_std, self.cfg.device_normalize,
        )
        labels, boxes, difficult = self._parse_annotation(xml_path)
        real = labels >= 0
        new_h, new_w = self.cfg.image_size
        boxes = native_ops.scale_boxes(
            boxes, labels, new_h / orig_h, new_w / orig_w
        )

        # training mask excludes difficult objects unless enabled (reference
        # `data_loader.py:108-109`); eval reads `difficult` to ignore them
        mask = real if self.cfg.use_difficult else (real & ~difficult)
        return {
            # _load_image returns float32 (host-normalized) or uint8
            # (device_normalize) — either is the contract dtype already
            "image": image,
            "boxes": boxes.astype(np.float32),
            "labels": labels,
            "mask": mask,
            "difficult": difficult & real,
        }
