"""COCO-2017 dataset — BASELINE.json config #5 ("COCO-2017 80-class").

No reference equivalent exists (the reference is VOC-only; its prototxt
docs describe the original COCO py-faster-rcnn, `reference/
train_frcnn.prototxt:410-417`). Annotation parsing uses stdlib json —
pycocotools is not in this image and is only needed for COCO's own eval
metric, not for training.

Samples come out in the same fixed-shape format as VOCDataset: row-major
[ymin, xmin, ymax, xmax] boxes scaled to the resized image, labels 1..80
(contiguous, background 0), -1 padding.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List

import numpy as np

from replication_faster_rcnn_tpu.config import DataConfig
from replication_faster_rcnn_tpu.data.voc import _load_image


class COCODataset:
    """Map-style COCO detection dataset.

    Expects the standard layout: {root}/annotations/instances_{split}.json
    and {root}/{split}/ images (split like 'train2017'/'val2017').

    ``keep_empty=True`` keeps images whose every annotation was filtered
    (crowd-only or degenerate-only) or that have none at all; they come
    out as valid samples with all -1 padding (every detection on them
    scores as a false positive). Default False: train on images with at
    least one target, like py-faster-rcnn.
    """

    def __init__(
        self, cfg: DataConfig, split: str = "train2017",
        keep_empty: bool = False,
    ) -> None:
        self.cfg = cfg
        self.split = split
        ann_path = os.path.join(
            cfg.root_dir, "annotations", f"instances_{split}.json"
        )
        with open(ann_path) as f:
            ann = json.load(f)

        # category ids are sparse (1..90 with gaps); remap to contiguous 1..80
        cat_ids = sorted(c["id"] for c in ann["categories"])
        self.cat_to_label = {cid: i + 1 for i, cid in enumerate(cat_ids)}
        self.classes = ["__background__"] + [
            c["name"] for c in sorted(ann["categories"], key=lambda c: c["id"])
        ]

        self.images = {im["id"]: im for im in ann["images"]}
        self.anns_by_image: Dict[int, List[dict]] = {}
        for a in ann["annotations"]:
            if a.get("iscrowd", 0):
                continue  # crowd regions are not box targets
            self.anns_by_image.setdefault(a["image_id"], []).append(a)
        self.ids = [
            i for i in self.images
            if keep_empty or self.anns_by_image.get(i)
        ]
        self.ids.sort()

    def __len__(self) -> int:
        return len(self.ids)

    def __getitem__(self, idx: int) -> Dict[str, np.ndarray]:
        img_id = self.ids[idx]
        info = self.images[img_id]
        path = os.path.join(self.cfg.root_dir, self.split, info["file_name"])
        image, orig_h, orig_w = _load_image(
            path, self.cfg.image_size, self.cfg.pixel_mean,
            self.cfg.pixel_std, self.cfg.device_normalize,
        )

        m = self.cfg.max_boxes
        labels = np.full((m,), -1, np.int32)
        boxes = np.full((m, 4), -1.0, np.float32)
        new_h, new_w = self.cfg.image_size
        n = 0
        for a in self.anns_by_image.get(img_id, ()):
            if n == m:
                break
            x, y, w, h = a["bbox"]  # COCO xywh, column-major
            # clamp to the resized canvas (real COCO boxes overhang the
            # image edge by a pixel or two) and drop what degenerates to
            # zero extent — a zero-area target would poison the IoU
            # matching and the regression targets downstream
            r1 = min(max(y * new_h / orig_h, 0.0), new_h)
            c1 = min(max(x * new_w / orig_w, 0.0), new_w)
            r2 = min(max((y + h) * new_h / orig_h, 0.0), new_h)
            c2 = min(max((x + w) * new_w / orig_w, 0.0), new_w)
            if r2 - r1 <= 0.0 or c2 - c1 <= 0.0:
                continue
            boxes[n] = [r1, c1, r2, c2]
            labels[n] = self.cat_to_label[a["category_id"]]
            n += 1

        return {
            "image": image.astype(np.float32),
            "boxes": boxes,
            "labels": labels,
            "mask": labels >= 0,
            # COCO has no 'difficult' notion; uniform key for collate
            "difficult": np.zeros((m,), bool),
        }
