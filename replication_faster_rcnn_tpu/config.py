"""Central configuration registry.

The reference scatters every hyperparameter across module constants and
hard-coded literals (SURVEY.md §2.2; reference `utils/utils.py:6-21`,
`train.py:139-159`, `utils/data_loader.py:21,81`, `nets/heads.py:8,21-22`,
`nets/faster_rcnn.py:4-5`). This module centralizes all of them as frozen
dataclasses so configs are hashable (usable as jit static args) and the five
BASELINE.json configs are expressible as presets.

Box convention used throughout the framework (matches the reference's
row-major convention, reference `nets/faster_rcnn.py:10`,
`utils/data_loader.py:104-105`): boxes are ``[r1, c1, r2, c2]`` where ``r``
indexes image rows (height) and ``c`` image columns (width). Regression
deltas are ``[dr, dc, dh, dw]`` with ``h`` = row extent, ``w`` = col extent
(reference `utils/utils.py:47-100`, which calls the row axis "x").
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional, Tuple

VOC_CLASSES: Tuple[str, ...] = (
    "__background__",
    "aeroplane", "bicycle", "bird", "boat",
    "bottle", "bus", "car", "cat", "chair",
    "cow", "diningtable", "dog", "horse",
    "motorbike", "person", "pottedplant",
    "sheep", "sofa", "train", "tvmonitor",
)
VOC_NUM_CLASSES = len(VOC_CLASSES)  # 21 incl. background (reference utils/utils.py:15-21)

# COCO-2017 "thing" classes for the BASELINE config #5 (80 + background).
COCO_CLASSES: Tuple[str, ...] = (
    "__background__",
    "person", "bicycle", "car", "motorcycle", "airplane", "bus", "train",
    "truck", "boat", "traffic light", "fire hydrant", "stop sign",
    "parking meter", "bench", "bird", "cat", "dog", "horse", "sheep", "cow",
    "elephant", "bear", "zebra", "giraffe", "backpack", "umbrella", "handbag",
    "tie", "suitcase", "frisbee", "skis", "snowboard", "sports ball", "kite",
    "baseball bat", "baseball glove", "skateboard", "surfboard",
    "tennis racket", "bottle", "wine glass", "cup", "fork", "knife", "spoon",
    "bowl", "banana", "apple", "sandwich", "orange", "broccoli", "carrot",
    "hot dog", "pizza", "donut", "cake", "chair", "couch", "potted plant",
    "bed", "dining table", "toilet", "tv", "laptop", "mouse", "remote",
    "keyboard", "cell phone", "microwave", "oven", "toaster", "sink",
    "refrigerator", "book", "clock", "vase", "scissors", "teddy bear",
    "hair drier", "toothbrush",
)
COCO_NUM_CLASSES = len(COCO_CLASSES)  # 81 incl. background


@dataclasses.dataclass(frozen=True)
class AnchorConfig:
    """Anchor grid definition (reference `utils/anchors.py:5-61`,
    `nets/faster_rcnn.py:4-5`)."""

    base_size: int = 16
    ratios: Tuple[float, ...] = (0.5, 1.0, 2.0)
    scales: Tuple[float, ...] = (8.0, 16.0, 32.0)
    feat_stride: int = 16

    @property
    def num_base_anchors(self) -> int:
        return len(self.ratios) * len(self.scales)


@dataclasses.dataclass(frozen=True)
class ProposalConfig:
    """Proposal-layer budgets (reference `utils/utils.py:7-12`,
    `nets/rpn.py:20-79`). Fixed-shape on TPU: outputs are padded to
    ``post_nms`` with a validity mask."""

    nms_thresh: float = 0.7
    pre_nms_train: int = 12000
    post_nms_train: int = 600
    pre_nms_test: int = 3000
    post_nms_test: int = 300
    min_size: float = 16.0

    def pre_nms(self, train: bool) -> int:
        return self.pre_nms_train if train else self.pre_nms_test

    def post_nms(self, train: bool) -> int:
        return self.post_nms_train if train else self.post_nms_test


@dataclasses.dataclass(frozen=True)
class RPNTargetConfig:
    """RPN (first-stage) target sampling (reference `utils/utils.py:122-204`,
    `train.py:24-25`)."""

    n_sample: int = 256
    pos_iou_thresh: float = 0.7
    neg_iou_thresh: float = 0.3
    pos_ratio: float = 0.5


@dataclasses.dataclass(frozen=True)
class ROITargetConfig:
    """Second-stage (head) target sampling (reference
    `utils/utils.py:207-276`, `train.py:26`). Output is a deterministic,
    padded ``n_sample`` rois per image (fixing the reference's latent
    variable-length bug, SURVEY.md §2.1 #5)."""

    n_sample: int = 128
    pos_ratio: float = 0.5
    pos_iou_thresh: float = 0.5
    neg_iou_thresh_high: float = 0.5
    neg_iou_thresh_low: float = 0.0
    reg_mean: Tuple[float, float, float, float] = (0.0, 0.0, 0.0, 0.0)
    reg_std: Tuple[float, float, float, float] = (0.1, 0.1, 0.2, 0.2)

    @property
    def n_pos_max(self) -> int:
        return int(round(self.n_sample * self.pos_ratio))


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    """Network architecture (reference `nets/` — resnet_torch.py:392-409 split,
    rpn.py:82-100, heads.py:7-26)."""

    # any arch from the reference's constructor table (`nets/resnet_torch.py:
    # 271-390`): resnet18/34/50/101/152, resnext50_32x4d, resnext101_32x8d,
    # wide_resnet50_2, wide_resnet101_2
    backbone: str = "resnet18"
    num_classes: int = VOC_NUM_CLASSES
    rpn_mid_channels: int = 256
    roi_size: int = 7
    roi_op: str = "align"  # "align" (bilinear ROIAlign) | "pool" (quantized ROIPool)
    roi_sampling_ratio: int = 2  # ROIAlign samples per bin side
    fpn: bool = False  # FPN neck (BASELINE config #3)
    fpn_channels: int = 256  # P-level width (FPN paper)
    # compute dtype for conv stacks; params/losses stay float32
    compute_dtype: str = "bfloat16"
    # jax.checkpoint each residual block in the trunk: the backward pass
    # recomputes block activations instead of holding them in HBM — ~1/3
    # more FLOPs for large activation-memory savings (bigger batches /
    # deeper backbones at 600x600). Parameter trees are unchanged.
    remat: bool = False
    # mesh axis name for cross-replica (sync) BatchNorm — set ONLY when the
    # model runs inside shard_map (`parallel/spmd.py`); under jit
    # auto-partitioning the global-batch BN reduction happens automatically
    # and a named axis here would be unbound.
    bn_axis: Optional[str] = None
    # freeze BatchNorm STATISTICS during training (the detection-
    # fine-tuning practice torchvision implements as FrozenBatchNorm2d):
    # every BN applies its stored running stats, becoming a fusable
    # affine — no batch-stats reductions in the step. Deliberate
    # deviation from torchvision: the affine scale/bias stay trainable
    # (identical param/opt trees with the flag on or off); torchvision
    # freezes those too. Off by default: the reference trains BN in
    # batch-stats mode (torch modules default to train())
    frozen_bn: bool = False
    # normalization at the backbone's BN sites: "batch" (reference
    # semantics) or "group" (GroupNorm(32), the BN-free structural lever
    # from the MFU attribution — no batch-stats reductions/fusion breaks,
    # shard-invariant, but torch-pretrained BN checkpoints don't convert;
    # see models/resnet.py::_norm). VGG16 has no norm layers; the flag is
    # a no-op there.
    norm: str = "batch"

    def __post_init__(self):
        if self.roi_op not in ("align", "pool"):
            raise ValueError(f"roi_op must be 'align' or 'pool', got {self.roi_op!r}")
        if self.norm not in ("batch", "group"):
            raise ValueError(f"norm must be 'batch' or 'group', got {self.norm!r}")
        if self.norm == "group" and self.frozen_bn:
            raise ValueError(
                "frozen_bn freezes BatchNorm statistics; GroupNorm has none "
                "— the combination is meaningless, pick one"
            )
        if self.norm == "group" and self.bn_axis is not None:
            raise ValueError(
                "bn_axis configures cross-replica sync-BN; GroupNorm "
                "normalizes within each sample and needs no axis"
            )

    @property
    def backbone_channels(self) -> int:
        """Feature channels out of the stride-16 trunk (conv1..layer3, or
        conv5_3 for VGG16). Delegates to the model layer's arch tables so
        unknown names fail fast here (at config time) rather than deep
        inside model init."""
        if self.backbone == "vgg16":
            from replication_faster_rcnn_tpu.models.vgg import VGG16_TRUNK_CHANNELS

            return VGG16_TRUNK_CHANNELS
        from replication_faster_rcnn_tpu.models.resnet import trunk_channels

        return trunk_channels(self.backbone)

    @property
    def head_channels(self) -> int:
        """Channels out of the classifier tail (layer4+avgpool, or fc7)."""
        if self.backbone == "vgg16":
            from replication_faster_rcnn_tpu.models.vgg import VGG16_TAIL_CHANNELS

            return VGG16_TAIL_CHANNELS
        from replication_faster_rcnn_tpu.models.resnet import tail_channels

        return tail_channels(self.backbone)


@dataclasses.dataclass(frozen=True)
class DataConfig:
    """Data pipeline (reference `utils/data_loader.py:17-117`)."""

    root_dir: str = "data/voc/VOCdevkit/VOC2012"
    dataset: str = "voc"  # voc | coco | synthetic
    image_size: Tuple[int, int] = (600, 600)
    max_boxes: int = 32
    use_difficult: bool = False
    # ImageNet normalization (reference utils/data_loader.py:38)
    pixel_mean: Tuple[float, float, float] = (0.485, 0.456, 0.406)
    pixel_std: Tuple[float, float, float] = (0.229, 0.224, 0.225)
    # host input pipeline (replaces the reference's torch DataLoader,
    # frcnn.py:19-23): worker count and kind. "thread" scales the
    # GIL-releasing native decode; "process" (fork) scales GIL-bound
    # Python sample work across cores
    # -1 = auto: min(4, host cores). Measured on a 1-core host the
    # 4-thread pool was SLOWER than single-thread ingest (pool overhead
    # with nothing to parallelize: 61-86 vs 108-123 img/s,
    # benchmarks/loader_throughput.json) — worker count must follow the
    # host, not a fixed default
    loader_workers: int = -1
    loader_mode: str = "thread"  # thread | process
    loader_prefetch: int = 2
    # memoize decoded samples in host RAM (data/cache.py): epoch 1 pays
    # the decode, later epochs are memcpy — the single-core host's only
    # route past the decode-bound ingest ceiling
    loader_cache_ram: bool = False
    # ship uint8 images to the device and normalize on-chip (the model's
    # preprocess, fused by XLA into the first conv): 4x less host->device
    # transfer, 4x smaller RAM cache, 4x cheaper collate. Off by default:
    # the f32 path matches the reference bit-for-bit
    device_normalize: bool = False
    # 50% horizontal-flip train augmentation (the original Faster R-CNN
    # recipe's only augmentation; the reference trains with none —
    # utils/data_loader.py:56-79 resizes+normalizes only). Deterministic
    # per (seed, epoch, index): resume replays the same flips.
    augment_hflip: bool = False
    # random scale jitter (lo, hi), e.g. (0.75, 1.25): fixed-canvas
    # zoom in/out with random placement, boxes tracked and collapsed
    # rows masked (data/augment.py::scale_jitter_sample). None = off.
    # Same deterministic (seed, epoch, index) keying as the flip.
    augment_scale: Optional[Tuple[float, float]] = None
    # run the jitter's image resample ON DEVICE (ops/image.py): the host
    # transforms boxes only and ships integer jitter geometry with the
    # batch — removes the ~27 ms/600x600 host resample from ingest
    # (measured 37 samples/s host-side on one core vs the 210 img/s
    # one-chip demand). Requires augment_scale.
    augment_scale_device: bool = False
    # FULLY on-device augmentation (ops/image.py::augment_batch): the
    # host loader ships raw samples plus an int32 [idx, epoch] row, and
    # the compiled train step draws every decision (flip coin, scale
    # geometry, translation offsets) from the splitmix hash of
    # (seed, epoch, idx) and applies flip/translate/scale-jitter as one
    # fused batch transform ahead of the bucket resample — the host
    # stops touching pixels entirely. Supersedes augment_scale_device
    # (which still ran the flip and the box affine on host). Composes
    # with every train backend: the draws are a pure function of
    # per-sample metadata, so all ranks and any resume agree with zero
    # communication. Requires augment_hflip, augment_scale, or
    # augment_translate; incompatible with cache_device (the device
    # cache already augments inside its gather).
    augment_device: bool = False
    # translation jitter amplitude as a fraction of the canvas: each
    # sample's content shifts by integer (dy, dx) drawn uniformly from
    # [-t*h, t*h] x [-t*w, t*w], channel-mean fill, boxes tracked and
    # collapsed rows masked. 0 = off. Device-mode only (augment_device):
    # the legacy host pipeline never had this op, so there is no host
    # path to keep parity with — the numpy oracle lives in
    # data/augment.py::translate_sample.
    augment_translate: float = 0.0
    # device-resident dataset cache (data/device_cache.py): upload every
    # sample to HBM once, then each step ships only indices + augment
    # decisions and the batch is gathered/flipped/jittered INSIDE the
    # jitted step. The route past a transfer-bound feed (measured 11 vs
    # 215 img/s over the remote tunnel at 600x600 b16). Needs the dataset
    # to fit HBM — pair with device_normalize for uint8 samples (VOC
    # trainval ~5.4 GB vs 21.6 GB f32).
    cache_device: bool = False
    # double-buffered DEVICE staging (data/prefetch_device.py): a producer
    # thread assembles batch K+1 (stack + shard + device_put) while
    # dispatch K runs, so the trainer's next dispatch consumes an already
    # device-resident buffer instead of paying collate+transfer on the
    # critical path. Value = number of staged batches/chunks held ahead
    # (2 = classic double buffering; each buffered chunk holds a full
    # batch in HBM, so keep it small). 0 = off (default): staging happens
    # synchronously between dispatches, the pre-PR-4 behavior.
    prefetch_device: int = 0
    # multi-scale bucketed training: 2-3 (h, w) resolution buckets. Each
    # global batch is deterministically assigned one bucket (a splitmix
    # hash of seed/epoch/dispatch-chunk — data/augment.py::bucket_index,
    # so `set_epoch(epoch, start_batch=)` resume replays the identical
    # bucket sequence) and trained through that bucket's own compiled
    # program: the step resamples the base-resolution batch to the bucket
    # shape on device and scales the boxes (ops/image.py), composing with
    # K-step fusion (all K batches of a fused dispatch share a bucket),
    # the DevicePrefetcher, and the on-chip scale jitter. The bucket
    # programs register through the warmup ProgramSpec registry, so
    # `frcnn audit` banks one fingerprint per bucket like the serving
    # buckets. () = off (default): the single-resolution path, bitwise
    # identical to before this knob existed.
    train_resolutions: Tuple[Tuple[int, int], ...] = ()

    def __post_init__(self):
        if self.prefetch_device < 0:
            raise ValueError(
                f"prefetch_device must be >= 0, got {self.prefetch_device}"
            )
        if self.augment_scale is not None:
            lo, hi = self.augment_scale
            # fail at config build, not at the first training epoch
            if not 0.1 <= lo <= hi <= 4.0:
                raise ValueError(
                    "augment_scale must satisfy 0.1 <= lo <= hi <= 4.0, "
                    f"got {self.augment_scale!r}"
                )
            # coerce list inputs (dict/JSON config paths) to a tuple so the
            # frozen dataclass stays hashable like its other tuple fields
            object.__setattr__(self, "augment_scale", (float(lo), float(hi)))
        if self.augment_scale_device and self.augment_scale is None:
            raise ValueError(
                "augment_scale_device requires augment_scale to be set"
            )
        if not 0.0 <= self.augment_translate < 1.0:
            raise ValueError(
                "augment_translate must be in [0, 1), got "
                f"{self.augment_translate!r}"
            )
        if self.augment_translate and not self.augment_device:
            raise ValueError(
                "augment_translate is a device-mode op: set "
                "data.augment_device=True (the host pipeline has no "
                "translation path)"
            )
        if self.augment_device:
            if not (
                self.augment_hflip
                or self.augment_scale is not None
                or self.augment_translate
            ):
                raise ValueError(
                    "augment_device is set but no augmentation op is "
                    "enabled (augment_hflip / augment_scale / "
                    "augment_translate)"
                )
            if self.augment_scale_device:
                raise ValueError(
                    "augment_device supersedes augment_scale_device — "
                    "set only one"
                )
            if self.cache_device:
                raise ValueError(
                    "augment_device is incompatible with cache_device: "
                    "the device cache already flips/jitters inside its "
                    "gather (data/device_cache.py)"
                )
        if self.train_resolutions:
            res = tuple(
                (int(r[0]), int(r[1])) for r in self.train_resolutions
            )
            for h, w in res:
                if h < 1 or w < 1:
                    raise ValueError(
                        "data.train_resolutions entries must be positive "
                        f"(h, w) pairs, got {(h, w)}"
                    )
            if len(set(res)) != len(res):
                raise ValueError(
                    f"data.train_resolutions has duplicates: {res!r}"
                )
            # canonical smallest-area-first order (same rule as
            # serving.bucket_resolutions): bucket INDEX is part of the
            # deterministic assignment, so the order must not depend on
            # how the user happened to spell the list
            object.__setattr__(
                self,
                "train_resolutions",
                tuple(sorted(res, key=lambda r: (r[0] * r[1], r))),
            )
        else:
            # coerce None/[] (JSON round-trips) to the canonical empty tuple
            object.__setattr__(self, "train_resolutions", ())


@dataclasses.dataclass(frozen=True)
class TrainConfig:
    """Optimization (reference `train.py:139-159`)."""

    lr: float = 1e-4
    # The reference's __main__ uses lr=0.01 with Adam, which diverges in
    # practice; 1e-4 is the stable default. `--lr` restores any value.
    weight_decay: float = 5e-6
    # optimizer family: "adam" (the reference's choice) or "lamb" —
    # Adam preconditioning + per-layer trust-ratio rescaling
    # (arXiv:1904.00962 via the You et al. large-batch line; see
    # train/train_step.py::make_optimizer). Unlike the `lars` flag below,
    # LAMB composes with ZeRO-1 sharded optimizer state on the shard_map
    # backend: its per-layer norms are computed from shard-local partial
    # sums psummed over the data axis (scale_by_sharded_trust_ratio).
    optimizer: str = "adam"  # adam | lamb
    n_epoch: int = 50
    batch_size: int = 8  # per-step global batch (reference default 2)
    smooth_l1_sigma: float = 1.0
    checkpoint_every_epochs: int = 10
    # additional dispatch-boundary scheduled saves every N global steps
    # (0 = off, the default: epoch-granular saves only). Elastic fleets
    # want this tight — a surviving rank resumes from the last verified
    # step, so this knob bounds the re-trained window after a shrink.
    # Step counts are deterministic across ranks, so multi-process saves
    # stay lockstep collectives.
    checkpoint_every_steps: int = 0
    seed: int = 0
    # loss weights: the reference sums the 4 losses unweighted (train.py:123)
    loss_weights: Tuple[float, float, float, float] = (1.0, 1.0, 1.0, 1.0)
    # SPMD backend: "auto" = jit auto-partitioning (XLA places collectives),
    # "spmd" = explicit shard_map step with hand-placed psums + sync-BN
    # (`parallel/spmd.py`); both compute the same update (tested).
    backend: str = "auto"
    # ZeRO-1 / cross-replica weight-update sharding (arXiv:2004.13336,
    # `parallel/zero.py`): shard Adam moments over the data axis; each chip
    # updates 1/N of the weights (reduce-scatter + all-gather — inserted by
    # GSPMD on the auto-partitioning backend, hand-placed in
    # `parallel/spmd.py` on the explicit shard_map backend; both share the
    # per-leaf layout so checkpoints move freely between them).
    shard_opt_state: bool = False
    # large-batch LR recipe ("Extremely Large Minibatch SGD",
    # arXiv:1711.04325). "linear" scales the schedule's peak lr by
    # batch_size / base_batch_size, so scaling out the data axis keeps
    # the per-example update magnitude — set base_batch_size to the batch
    # the configured lr was tuned at. "none" = lr used as-is (default).
    lr_scaling: str = "none"  # none | linear
    base_batch_size: int = 8
    # linear LR warmup over the first warmup_epochs (fractional ok): ramps
    # from ~0 to the (scaled) peak before the cosine schedule takes over —
    # the large-batch stabilizer from arXiv:1711.04325. 0 = off (default).
    warmup_epochs: float = 0.0
    # layer-wise trust-ratio scaling (LARS-style, applied after Adam as in
    # LAMB): each leaf's update is rescaled by |param| / |update|, bounding
    # the per-layer relative step at very large batch. Adds an (empty)
    # optax state entry, so flipping it invalidates optimizer checkpoints.
    lars: bool = False
    # run the mAP evaluator on the val split every N epochs (0 = off)
    eval_every_epochs: int = 0
    # dtype for Adam's first moment (mu). bfloat16 halves the moment
    # buffer traffic in the update phase — the v5e breakdown puts
    # backward+update at >50% of the step (VERDICT r2 weak #2); nu and
    # the params stay float32 (nu's magnitudes underflow bf16)
    adam_mu_dtype: str = "float32"  # float32 | bfloat16
    # fused multi-step dispatch: one jitted call trains K steps via
    # lax.scan over K device-resident batches (train/train_step.py::
    # build_multi_step, parallel/spmd.py), amortizing per-step Python
    # dispatch + pytree flattening. Metrics come back stacked [K, ...];
    # the Trainer reads them on host only at log boundaries, so async
    # dispatch overlaps across the whole chunk. 1 = the plain per-step
    # path (default).
    steps_per_dispatch: int = 1
    # dtype the gradient all-reduce rides in ("Extremely Large Minibatch
    # SGD", arXiv:1711.04325 — half-precision gradient exchange). On the
    # explicit shard_map backend grads are cast to this dtype BEFORE the
    # lax.psum and de-cast for the fp32 optimizer math, halving
    # all-reduce bytes; on the auto-partitioning backend (where XLA's
    # all-reduces live inside the fused backward and cannot be re-dtyped
    # from here) the summed grads take the same bf16 round-trip, keeping
    # the two backends within bf16 rounding of each other (pre- vs
    # post-sum quantization). float32 = off (default).
    grad_allreduce_dtype: str = "float32"  # float32 | bfloat16
    # what the jitted step does with a non-finite gradient tree
    # (train/fault.py::guarded_update): "skip" (default) withholds the
    # optimizer update — params, Adam moments and BN stats carry through
    # bit-identical, the step's metrics carry skipped=1 — so one poisoned
    # batch costs one step instead of NaN'ing Adam's moments for the rest
    # of the run; "halt" gates the same way but the trainer raises on the
    # first skip; "apply" is the unguarded pre-fault-tolerance behavior.
    nonfinite_policy: str = "skip"  # apply | skip | halt
    # consecutive skipped steps before the trainer raises a descriptive
    # error (and records a watchdog incident) instead of free-running on
    # a divergent model: transients cost 1-2 steps, persistent NaNs are
    # a bug to surface, not ride through.
    max_consecutive_skips: int = 10
    # background scheduled checkpointing (train/async_checkpoint.py): a
    # scheduled save snapshots state to host once (the only blocking
    # part), then serialization + CRC manifest + atomic rename run on a
    # single background writer; the epoch loop blocks only if the
    # PREVIOUS save is still in flight. Emergency/final/crash saves stay
    # synchronous, and restore-side manifest verification is unchanged.
    # Single-process runtimes only (the writer hands orbax a host-numpy
    # snapshot, which has no multi-host replica story).
    async_checkpoint: bool = False
    # second-stage region sampling strategy (targets/proposal_targets.py):
    # "random" (default) draws the positive/negative ROI quotas uniformly
    # at random among the eligible candidates — the reference recipe,
    # byte-identical to the pre-knob programs; "topk_iou" ranks the
    # eligible candidates by their max IoU with ground truth and keeps
    # the top-K of each quota deterministically — the biased sampling
    # family of arXiv:1702.02138 ("An Implementation of Faster RCNN with
    # Study for Region Sampling"): highest-overlap positives plus
    # hardest (highest-IoU-below-threshold) negatives.
    sampling_strategy: str = "random"  # random | topk_iou

    def __post_init__(self):
        if self.backend not in ("auto", "spmd"):
            raise ValueError(f"backend must be 'auto' or 'spmd', got {self.backend!r}")
        if self.optimizer not in ("adam", "lamb"):
            raise ValueError(
                f"optimizer must be 'adam' or 'lamb', got {self.optimizer!r}"
            )
        if self.optimizer == "lamb" and self.lars:
            raise ValueError(
                "optimizer='lamb' already applies the per-layer trust "
                "ratio after Adam; combining it with lars=True would "
                "rescale twice — drop one"
            )
        if self.checkpoint_every_steps < 0:
            raise ValueError(
                "checkpoint_every_steps must be >= 0 (0 = off), got "
                f"{self.checkpoint_every_steps}"
            )
        if self.adam_mu_dtype not in ("float32", "bfloat16"):
            raise ValueError(
                f"adam_mu_dtype must be float32|bfloat16, got {self.adam_mu_dtype!r}"
            )
        if self.grad_allreduce_dtype not in ("float32", "bfloat16"):
            raise ValueError(
                "grad_allreduce_dtype must be float32|bfloat16, got "
                f"{self.grad_allreduce_dtype!r}"
            )
        if self.steps_per_dispatch < 1:
            raise ValueError(
                f"steps_per_dispatch must be >= 1, got {self.steps_per_dispatch}"
            )
        if self.nonfinite_policy not in ("apply", "skip", "halt"):
            raise ValueError(
                "nonfinite_policy must be apply|skip|halt, got "
                f"{self.nonfinite_policy!r}"
            )
        if self.max_consecutive_skips < 1:
            raise ValueError(
                "max_consecutive_skips must be >= 1, got "
                f"{self.max_consecutive_skips}"
            )
        if self.lr_scaling not in ("none", "linear"):
            raise ValueError(
                f"lr_scaling must be 'none' or 'linear', got {self.lr_scaling!r}"
            )
        if self.base_batch_size < 1:
            raise ValueError(
                f"base_batch_size must be >= 1, got {self.base_batch_size}"
            )
        if self.warmup_epochs < 0:
            raise ValueError(
                f"warmup_epochs must be >= 0, got {self.warmup_epochs}"
            )
        if self.sampling_strategy not in ("random", "topk_iou"):
            raise ValueError(
                "sampling_strategy must be 'random' or 'topk_iou', got "
                f"{self.sampling_strategy!r}"
            )


@dataclasses.dataclass(frozen=True)
class EvalConfig:
    """Inference decode + mAP. The reference never wrote its eval path
    (`test_eval.py` is empty, SURVEY.md §3.2) so these are our own choices."""

    score_thresh: float = 0.05
    nms_thresh: float = 0.3
    max_detections: int = 100
    iou_thresh: float = 0.5  # mAP@0.5
    use_07_metric: bool = False  # area-under-PR by default; True = 11-point
    metric: str = "voc"  # "voc" (mAP@iou_thresh) | "coco" (mAP@[.50:.95])
    # flip test-time augmentation: a second forward on the mirrored
    # image, candidates reflected back and merged before the shared
    # per-class NMS (eval/detect.py::decode_detections_tta). ~2x eval
    # compute for a small mAP gain; off by default
    tta_hflip: bool = False

    def __post_init__(self):
        if self.metric not in ("voc", "coco"):
            raise ValueError(f"metric must be 'voc' or 'coco', got {self.metric!r}")


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Device mesh for SPMD parallelism (SURVEY.md §2.4). The workload is
    data-parallel; the `model` axis exists so tensor-parallel shardings can
    be introduced without changing the mesh plumbing.

    ``spatial`` turns on spatial partitioning over the ``model`` axis: each
    image's row (H) dimension is sharded across it, the vision analogue of
    sequence/context parallelism (there is no sequence axis in a detector —
    SURVEY.md §5 — the long axis is image extent). GSPMD inserts the halo
    exchanges every conv needs at shard boundaries; one image then spans
    ``num_model`` chips, so images larger than a single chip's HBM budget
    still train. Requires the default jit auto-partitioning backend.

    ``param_sharding`` turns on model parallelism over the same ``model``
    axis: every conv kernel / head weight is sharded on its largest
    mp-divisible dimension (the `parallel/zero.py` ``shard_dim`` rule,
    pointed at the model axis), so each chip holds ~1/num_model of the
    parameters and GSPMD inserts the weight all-gathers / gradient
    reductions the forward/backward needs. The CLI spelling is
    ``--mesh-shape DP,MP`` (sets num_data=DP, num_model=MP and flips this
    flag when MP > 1). Composes with ZeRO-1 (``train.shard_opt_state``)
    over the ``data`` axis; requires the jit auto-partitioning backend,
    and is mutually exclusive with ``spatial`` (one sharding story per
    model axis)."""

    data_axis: str = "data"
    model_axis: str = "model"
    num_data: int = -1  # -1: all available devices
    num_model: int = 1
    spatial: bool = False  # shard image rows over the model axis
    param_sharding: bool = False  # shard weights over the model axis (mp)


@dataclasses.dataclass(frozen=True)
class CompileConfig:
    """Compilation warm start (train/warmup.py).

    ``cache_dir`` opts into JAX's persistent XLA compilation cache: every
    compiled program is keyed by its HLO + compile options and written
    under the directory, so a SECOND process start for the same config
    deserializes executables instead of re-running XLA (minutes on the
    big presets). Empty string = off (default; compilation stays
    per-process). The ``warmup`` CLI subcommand AOT-compiles the
    train/eval programs for a config to populate the cache ahead of the
    real run."""

    cache_dir: str = ""  # "" = persistent compilation cache off

    def __post_init__(self):
        if not isinstance(self.cache_dir, str):
            raise ValueError(
                "compile.cache_dir must be a string path, got "
                f"{self.cache_dir!r}"
            )


@dataclasses.dataclass(frozen=True)
class DebugConfig:
    """Runtime hygiene checks (analysis/strict.py).

    ``strict`` engages jax.transfer_guard("disallow") for the whole
    training session plus a per-program recompile gate around every
    dispatch: after each program's first (warmup) dispatch, any implicit
    host<->device transfer or recompilation raises instead of silently
    eating throughput. Costs nothing per step beyond a counter compare;
    intended for CI and bringup, safe to leave on for real runs.

    ``strict_warmup`` is the number of dispatches per program allowed to
    compile (and stage constants) before the gate arms; ≥ 1.

    ``threadsan`` engages the runtime lock sanitizer
    (analysis/threadsan.py): package-created locks and queues are
    instrumented, lock-order inversions raise, and held-duration /
    queue-depth gauges feed the telemetry watchdog. The runtime half of
    the threadlint static gate; CI-tier cost, not for production serving.
    """

    strict: bool = False
    strict_warmup: int = 1
    threadsan: bool = False
    # seeded fault-injection schedule (faultlib/failpoints.py):
    # "site:kind:prob:seed[:arg[:max_fires[:after]]],..." or a JSON schedule
    # path. Empty = disarmed (the failpoints are zero-overhead no-ops).
    # Armed by the CLI entry points from --chaos-spec.
    chaos_spec: str = ""

    def __post_init__(self):
        if not isinstance(self.strict_warmup, int) or self.strict_warmup < 1:
            raise ValueError(
                "debug.strict_warmup must be an int >= 1, got "
                f"{self.strict_warmup!r}"
            )


@dataclasses.dataclass(frozen=True)
class AnalysisConfig:
    """Static-analysis gates (analysis/hlolint.py).

    ``hbm_budget_bytes`` bounds the HLO auditor's compiled peak-memory
    estimate per program (rule HX004); the default is one v5e chip's
    16 GiB HBM. ``fingerprint_dir`` overrides where `frcnn audit` reads
    and re-banks compiled-program fingerprints; empty string (default)
    uses the committed bank under the package's ``analysis/fingerprints``.

    ``replicated_bytes_threshold`` is shardlint's SL001 floor: an arg
    buffer at least this large, replicated over a >1 model axis despite a
    divisible dim, is a finding (default 1 MiB — batch-norm vectors pass,
    conv kernels and optimizer moments do not). ``comm_budget_bytes``
    caps any one program's statically-priced collective wire bytes per
    device per step (shardlint SL005 / `frcnn audit`); the default is
    ~2x the largest banked CI program, so growth trips the gate before
    it doubles a step's interconnect traffic.
    """

    hbm_budget_bytes: int = 16 << 30
    fingerprint_dir: str = ""
    replicated_bytes_threshold: int = 1 << 20
    comm_budget_bytes: int = 512 << 20

    def __post_init__(self):
        if not isinstance(self.hbm_budget_bytes, int) or self.hbm_budget_bytes <= 0:
            raise ValueError(
                "analysis.hbm_budget_bytes must be a positive int, got "
                f"{self.hbm_budget_bytes!r}"
            )
        if not isinstance(self.fingerprint_dir, str):
            raise ValueError(
                "analysis.fingerprint_dir must be a string path, got "
                f"{self.fingerprint_dir!r}"
            )
        if (
            not isinstance(self.replicated_bytes_threshold, int)
            or self.replicated_bytes_threshold <= 0
        ):
            raise ValueError(
                "analysis.replicated_bytes_threshold must be a positive "
                f"int, got {self.replicated_bytes_threshold!r}"
            )
        if not isinstance(self.comm_budget_bytes, int) or self.comm_budget_bytes <= 0:
            raise ValueError(
                "analysis.comm_budget_bytes must be a positive int, got "
                f"{self.comm_budget_bytes!r}"
            )


@dataclasses.dataclass(frozen=True)
class ElasticConfig:
    """Elastic fleet training (parallel/elastic.py, `frcnn train --elastic`).

    A per-host supervisor process spawns the training child once per fleet
    *generation*; inside the child a heartbeat thread renews this rank's
    lease file every ``heartbeat_interval_s`` and the trainer checks peer
    leases at dispatch boundaries. A peer whose lease is older than
    ``lease_timeout_s`` is declared lost: the survivor exits with
    ``EXIT_FLEET_SHRINK`` (falling back to its last CRC-verified
    checkpoint) and the supervisors re-form the fleet at the surviving
    world size on a bumped coordinator port — resuming INSIDE the same
    epoch via the offset-based feeds.

    ``lease_timeout_s`` must stay well under ~10 s: the JAX coordination
    service force-aborts (SIGABRT) a process whose peers stop heartbeating
    after about that long, and the survivor must detect the loss, persist
    its shrink intent, and exit cleanly BEFORE that abort lands — there is
    no catchable error path once a gloo collective hangs on a dead peer.
    """

    heartbeat_interval_s: float = 0.5
    lease_timeout_s: float = 5.0
    # how long re-forming supervisors wait for survivor claims before the
    # lowest surviving rank writes the generation plan
    settle_s: float = 2.0
    # supervisor gives up after this many re-formations (a fleet that
    # shrinks every few steps has an environment problem, not a rank loss)
    max_generations: int = 8

    def __post_init__(self):
        if self.heartbeat_interval_s <= 0:
            raise ValueError(
                "elastic.heartbeat_interval_s must be > 0, got "
                f"{self.heartbeat_interval_s}"
            )
        if self.lease_timeout_s <= self.heartbeat_interval_s:
            raise ValueError(
                "elastic.lease_timeout_s must exceed heartbeat_interval_s "
                f"({self.heartbeat_interval_s}), got {self.lease_timeout_s}"
            )
        if self.settle_s <= 0:
            raise ValueError(
                f"elastic.settle_s must be > 0, got {self.settle_s}"
            )
        if self.max_generations < 1:
            raise ValueError(
                "elastic.max_generations must be >= 1, got "
                f"{self.max_generations}"
            )


@dataclasses.dataclass(frozen=True)
class ServingConfig:
    """Bucketed AOT inference serving (serving/engine.py).

    The engine compiles one inference program per (resolution bucket ×
    batch size) at startup, holds the inference params device-resident in
    ``params_dtype``, and coalesces concurrent requests into bucket-sized
    micro-batches (flush on size OR ``max_delay_ms``). Requests larger
    than every bucket follow ``oversize``: "downscale" routes them to the
    largest bucket (the one-shot ``predict_image`` behavior), "reject"
    raises so a front-end can shed them instead of silently degrading.
    """

    # () = derived: the configured train/eval resolution plus its half —
    # two buckets cover "full-size" and "thumbnail" traffic without any
    # per-deployment tuning. Explicit tuples override, smallest-area
    # bucket tried first.
    resolutions: Tuple[Tuple[int, int], ...] = ()
    # compiled batch sizes per bucket; a flush picks the smallest
    # compiled batch >= the number of waiting requests and pads to it
    batch_sizes: Tuple[int, ...] = (1, 8)
    # deadline trigger: a waiting request is never delayed longer than
    # this hoping for batch-mates (0 = flush whenever the queue idles)
    max_delay_ms: float = 10.0
    # bounded submission queue depth — backpressure, same discipline as
    # data/prefetch_device.py (submit blocks/raises rather than queueing
    # unboundedly while the device falls behind)
    queue_depth: int = 64
    # dtype the resident inference params are held in on upload. bf16
    # halves HBM residency (the flax modules cast per-layer anyway);
    # "int8" halves it again: planned layer groups stay device-resident
    # as int8 weights + per-channel scales (quant/ sidecar artifact
    # required, see `frcnn quantize`), the rest fall back to bf16
    params_dtype: str = "bfloat16"  # float32 | bfloat16 | int8
    oversize: str = "downscale"  # downscale | reject
    # per-request deadline, end to end: the HTTP handler's future wait
    # times out to 504 after this many seconds, and an entry whose
    # deadline passes while it waits in the queue is dropped at flush
    # time (never dispatched). 0 disables deadlines (unbounded waits).
    request_timeout_s: float = 0.0
    # SLO-driven micro-batch deadlines (serving/slo.py): when enabled,
    # each bucket's max_delay_ms self-tunes from the observed queue-wait
    # p99 — one bounded multiplicative step (x/÷ adaptive_delay_step) per
    # adaptation, clamped to [delay_floor_ms, delay_ceiling_ms]. Wait p99
    # near adaptive_slo_ms shortens the deadline (stop holding requests
    # the SLO can't afford); a comfortably-met SLO with partial flushes
    # lengthens it (wait for batch-mates, amortize dispatch).
    adaptive_delay: bool = False
    adaptive_slo_ms: float = 100.0  # target queue-wait p99 per request
    delay_floor_ms: float = 1.0
    delay_ceiling_ms: float = 100.0
    adaptive_delay_step: float = 1.25

    def __post_init__(self):
        object.__setattr__(
            self,
            "resolutions",
            tuple(
                (int(r[0]), int(r[1])) for r in self.resolutions
            ),
        )
        object.__setattr__(
            self, "batch_sizes", tuple(int(b) for b in self.batch_sizes)
        )
        for h, w in self.resolutions:
            if h < 1 or w < 1:
                raise ValueError(
                    f"serving.resolutions entries must be positive, got {(h, w)}"
                )
        if not self.batch_sizes or any(b < 1 for b in self.batch_sizes):
            raise ValueError(
                "serving.batch_sizes must be a non-empty tuple of ints >= 1, "
                f"got {self.batch_sizes!r}"
            )
        if self.max_delay_ms < 0:
            raise ValueError(
                f"serving.max_delay_ms must be >= 0, got {self.max_delay_ms}"
            )
        if self.queue_depth < 1:
            raise ValueError(
                f"serving.queue_depth must be >= 1, got {self.queue_depth}"
            )
        if self.params_dtype not in ("float32", "bfloat16", "int8"):
            raise ValueError(
                "serving.params_dtype must be float32|bfloat16|int8, got "
                f"{self.params_dtype!r}"
            )
        if self.oversize not in ("downscale", "reject"):
            raise ValueError(
                "serving.oversize must be 'downscale' or 'reject', got "
                f"{self.oversize!r}"
            )
        if self.request_timeout_s < 0:
            raise ValueError(
                "serving.request_timeout_s must be >= 0 (0 = no deadline), "
                f"got {self.request_timeout_s}"
            )
        if self.adaptive_slo_ms <= 0:
            raise ValueError(
                "serving.adaptive_slo_ms must be > 0, got "
                f"{self.adaptive_slo_ms}"
            )
        if not 0 < self.delay_floor_ms <= self.delay_ceiling_ms:
            raise ValueError(
                "serving delay bounds need 0 < delay_floor_ms <= "
                f"delay_ceiling_ms, got floor={self.delay_floor_ms} "
                f"ceiling={self.delay_ceiling_ms}"
            )
        if self.adaptive_delay_step <= 1.0:
            raise ValueError(
                "serving.adaptive_delay_step is multiplicative and must be "
                f"> 1.0, got {self.adaptive_delay_step}"
            )

    def bucket_resolutions(
        self, image_size: Tuple[int, int]
    ) -> Tuple[Tuple[int, int], ...]:
        """The resolved bucket list, smallest area first."""
        if self.resolutions:
            res = set(self.resolutions)
        else:
            h, w = image_size
            res = {(max(1, h // 2), max(1, w // 2)), (h, w)}
        return tuple(sorted(res, key=lambda r: (r[0] * r[1], r)))


@dataclasses.dataclass(frozen=True)
class FleetConfig:
    """Multi-replica serving fleet (serving/fleet/, `frcnn fleet`).

    A front router owns a health-checked replica registry (periodic
    ``/healthz`` probes with lease-style staleness, the PR 11 heartbeat
    discipline applied to serving), dispatches by consistent hash over
    (content-hash, bucket), and self-heals: per-replica circuit breakers,
    failover re-dispatch, hedged retries after a p99-derived delay, and
    probe-driven drain/rejoin so a restarted replica re-enters rotation
    without dropped traffic.
    """

    # ---- registry / prober
    probe_interval_s: float = 0.5  # /healthz probe cadence per replica
    # a replica whose last successful probe is older than this is DEAD
    # (lease staleness — missing probes age the lease out, exactly like
    # elastic.lease_timeout_s ages out training heartbeats)
    lease_timeout_s: float = 3.0
    # consecutive successful probes a DEAD/JOINING replica needs before
    # it re-enters rotation (a flapping replica can't bounce in and out)
    rejoin_probes: int = 2
    # ---- circuit breaker (per replica)
    breaker_threshold: int = 3  # consecutive dispatch failures to open
    breaker_cooldown_s: float = 1.0  # open -> half-open probe delay
    # ---- dispatch
    max_attempts: int = 3  # primary + failover re-dispatches per request
    request_timeout_s: float = 30.0  # per-attempt replica call deadline
    vnodes: int = 64  # consistent-hash ring points per replica
    # content-hash result cache entries (duplicate images are answered
    # from the router without touching a replica; 0 disables)
    cache_entries: int = 256
    # ---- hedging: after hedge_multiplier x observed p99 (clamped to
    # [hedge_floor_ms, hedge_ceiling_ms]) with no primary response, a
    # second copy goes to the next ring replica; first result wins
    hedge: bool = True
    hedge_multiplier: float = 1.5
    hedge_floor_ms: float = 5.0
    hedge_ceiling_ms: float = 2000.0
    latency_window: int = 128  # per-router latency samples for the p99
    # ---- canary / shadow
    # fraction of requests routed to the canary replica first (decided
    # by content hash, so the split is deterministic per image)
    canary_fraction: float = 0.05
    # ---- replica-side drain: how long a SIGTERMed `frcnn serve
    # --replica-id` advertises draining=true in /healthz (so the router
    # stops routing to it) before it stops accepting connections
    drain_grace_s: float = 1.0
    # ---- SLO error-budget burn-rate (telemetry/slo_burn.py): every
    # dispatch ATTEMPT outcome (not just final request outcomes — with
    # failover a dying replica barely dents request availability, but
    # its failed attempts are the leading indicator) feeds multi-window
    # burn accounting; the alarm (burn > 1 on BOTH windows) surfaces in
    # /stats and auto-demotes an alarming canary back to serving role
    slo_availability_target: float = 0.999  # error budget = 1 - target
    slo_latency_target_ms: float = 0.0  # 0 = availability-only budget
    slo_short_window_s: float = 300.0  # alarm-clearing window (5 m)
    slo_long_window_s: float = 3600.0  # alarm-meaning window (1 h)

    def __post_init__(self):
        if self.probe_interval_s <= 0:
            raise ValueError(
                f"fleet.probe_interval_s must be > 0, got {self.probe_interval_s}"
            )
        if self.lease_timeout_s <= self.probe_interval_s:
            raise ValueError(
                "fleet.lease_timeout_s must exceed probe_interval_s "
                f"({self.probe_interval_s}), got {self.lease_timeout_s}"
            )
        if self.rejoin_probes < 1:
            raise ValueError(
                f"fleet.rejoin_probes must be >= 1, got {self.rejoin_probes}"
            )
        if self.breaker_threshold < 1:
            raise ValueError(
                "fleet.breaker_threshold must be >= 1, got "
                f"{self.breaker_threshold}"
            )
        if self.breaker_cooldown_s <= 0:
            raise ValueError(
                "fleet.breaker_cooldown_s must be > 0, got "
                f"{self.breaker_cooldown_s}"
            )
        if self.max_attempts < 1:
            raise ValueError(
                f"fleet.max_attempts must be >= 1, got {self.max_attempts}"
            )
        if self.request_timeout_s <= 0:
            raise ValueError(
                "fleet.request_timeout_s must be > 0, got "
                f"{self.request_timeout_s}"
            )
        if self.vnodes < 1:
            raise ValueError(f"fleet.vnodes must be >= 1, got {self.vnodes}")
        if self.cache_entries < 0:
            raise ValueError(
                f"fleet.cache_entries must be >= 0, got {self.cache_entries}"
            )
        if self.hedge_multiplier <= 0:
            raise ValueError(
                "fleet.hedge_multiplier must be > 0, got "
                f"{self.hedge_multiplier}"
            )
        if not 0 < self.hedge_floor_ms <= self.hedge_ceiling_ms:
            raise ValueError(
                "fleet hedge bounds need 0 < hedge_floor_ms <= "
                f"hedge_ceiling_ms, got floor={self.hedge_floor_ms} "
                f"ceiling={self.hedge_ceiling_ms}"
            )
        if self.latency_window < 1:
            raise ValueError(
                f"fleet.latency_window must be >= 1, got {self.latency_window}"
            )
        if not 0.0 <= self.canary_fraction <= 1.0:
            raise ValueError(
                "fleet.canary_fraction must be in [0, 1], got "
                f"{self.canary_fraction}"
            )
        if self.drain_grace_s < 0:
            raise ValueError(
                f"fleet.drain_grace_s must be >= 0, got {self.drain_grace_s}"
            )
        if not 0.0 < self.slo_availability_target < 1.0:
            raise ValueError(
                "fleet.slo_availability_target must be in (0, 1), got "
                f"{self.slo_availability_target}"
            )
        if self.slo_latency_target_ms < 0:
            raise ValueError(
                "fleet.slo_latency_target_ms must be >= 0, got "
                f"{self.slo_latency_target_ms}"
            )
        if not 0 < self.slo_short_window_s < self.slo_long_window_s:
            raise ValueError(
                "fleet SLO windows need 0 < slo_short_window_s < "
                f"slo_long_window_s, got short={self.slo_short_window_s} "
                f"long={self.slo_long_window_s}"
            )


@dataclasses.dataclass(frozen=True)
class OpsConfig:
    """Detection-op kernel backend (ops/__init__.py::resolve_backend).

    ``backend`` selects the implementation family for the detection hot
    ops — greedy NMS, ROIAlign, and the IoU/anchor-matching pass:

    * ``"xla"`` (default): the pure-XLA tilings (`ops/nms_tiled.py`,
      `ops/roi_ops.py`, `ops/boxes.py`). Compiled programs are
      byte-identical to every committed fingerprint bank.
    * ``"pallas"``: the Pallas kernels in `ops/pallas/` — interpret-mode
      (pure JAX) off-TPU so the same kernel code is parity-tested on CPU,
      Mosaic-compiled on a real TPU, and only ever compiled on-chip
      through the warmup ProgramSpec registry.

    The env var ``FRCNN_OPS_BACKEND`` overrides this key at process level
    (resolved once, at the first dispatch); `ops.backend_scope` overrides
    it lexically for a single trace.
    """

    backend: str = "xla"

    def __post_init__(self):
        if self.backend not in ("xla", "pallas"):
            raise ValueError(
                f"ops.backend must be 'xla' or 'pallas', got {self.backend!r}"
            )


@dataclasses.dataclass(frozen=True)
class QuantConfig:
    """Post-training int8 quantization (quant/, `frcnn quantize`).

    Calibration collects per-channel symmetric int8 weight scales plus
    per-layer-group activation ranges from a small sweep through the
    Evaluator inference path, and writes them as a CRC-manifested
    sidecar artifact next to the checkpoint. The optional sensitivity
    sweep (`frcnn quantize --sweep`) quantizes one layer group at a
    time, measures response-reconstruction error (arXiv:1806.00370) and
    the mAP delta on a mini eval set, and records a per-group dtype
    plan: groups whose solo-quantization cost exceeds the thresholds
    fall back to bf16 at serve time instead of int8.
    """

    # sidecar artifact path used by `serving.params_dtype="int8"`; ""
    # means "<checkpoint_dir>/quant_artifact.json" (the default written
    # by `frcnn quantize`)
    artifact: str = ""
    # calibration sweep size: batches x batch_size images drawn in
    # dataset order (deterministic — same order => bit-identical scales)
    calib_batches: int = 2
    calib_batch_size: int = 2
    # sensitivity sweep fallback thresholds, per layer group: a group
    # whose solo-int8 mAP drop exceeds `sensitivity_map_drop_pt` mAP
    # points OR whose response-reconstruction relative error exceeds
    # `sensitivity_recon_rel_err` is planned as bf16, not int8
    sensitivity_map_drop_pt: float = 0.1
    sensitivity_recon_rel_err: float = 0.25

    def __post_init__(self):
        if self.calib_batches < 1:
            raise ValueError(
                f"quant.calib_batches must be >= 1, got {self.calib_batches}"
            )
        if self.calib_batch_size < 1:
            raise ValueError(
                "quant.calib_batch_size must be >= 1, got "
                f"{self.calib_batch_size}"
            )
        if self.sensitivity_map_drop_pt < 0:
            raise ValueError(
                "quant.sensitivity_map_drop_pt must be >= 0, got "
                f"{self.sensitivity_map_drop_pt}"
            )
        if self.sensitivity_recon_rel_err <= 0:
            raise ValueError(
                "quant.sensitivity_recon_rel_err must be > 0, got "
                f"{self.sensitivity_recon_rel_err}"
            )


@dataclasses.dataclass(frozen=True)
class TelemetryConfig:
    """Observability layer knobs (telemetry/).

    The serving tiers are instrumented unconditionally through
    ``current_tracer()`` / ``MetricsRegistry`` — these knobs govern the
    cross-process pieces: whether trace context crosses HTTP hops, how
    large a per-process trace buffer may grow, and the latency
    histogram bucket grid both tiers register with.
    """

    # inject/extract the W3C traceparent header across fleet HTTP hops;
    # off = spans still record locally but requests don't correlate
    trace_propagation: bool = True
    # SpanTracer in-memory event bound for serving-tier tracers
    # (overflow drops events and counts them, never grows)
    trace_max_events: int = 200_000
    # latency histogram upper bounds in ms; () = the built-in
    # log-spaced 1 ms .. 60 s grid (telemetry/metrics.py)
    latency_buckets_ms: Tuple[float, ...] = ()

    def __post_init__(self):
        if self.trace_max_events < 1:
            raise ValueError(
                "telemetry.trace_max_events must be >= 1, got "
                f"{self.trace_max_events}"
            )
        b = list(self.latency_buckets_ms)
        if b and (sorted(b) != b or b[0] <= 0):
            raise ValueError(
                "telemetry.latency_buckets_ms must be ascending and "
                f"positive, got {self.latency_buckets_ms}"
            )

    def buckets_s(self) -> Optional[Tuple[float, ...]]:
        """The configured grid in seconds, or ``None`` for the default."""
        if not self.latency_buckets_ms:
            return None
        return tuple(ms / 1000.0 for ms in self.latency_buckets_ms)


@dataclasses.dataclass(frozen=True)
class RolloutConfig:
    """Rolling weight rollout control plane (serving/rollout/).

    The trainer's ``workdir/manifests/`` feed publishes CRC-manifested
    checkpoint versions; the rollout controller validates eligibility
    (manifest CRC + topology + quant sidecar, *before* any replica
    drains), then drives a rolling fleet upgrade through the registry:
    drain one replica (DRAINING keeps the lease), hot-swap its params,
    re-admit on `fleet.rejoin_probes` consecutive OKs at the new
    version. The first upgraded replica lands as CANARY; a windowed
    burn-rate + shadow-diff gate decides promote vs rollback, and
    rollback is a first-class reverse rollout.
    """

    # watcher poll interval over workdir/manifests/
    poll_interval_s: float = 2.0
    # how long the controller waits for a held replica's queues to
    # drain before swapping (simulated clocks make this cheap in tests)
    drain_timeout_s: float = 10.0
    # per-replica budget for the swap RPC itself
    swap_timeout_s: float = 30.0
    # budget for a swapped replica to re-reach HEALTHY at the new
    # version before the wave is declared failed and rolled back
    rejoin_timeout_s: float = 10.0
    # canary gate: minimum routed canary requests before the windowed
    # decision may *promote* (rollback triggers need no minimum)
    canary_min_requests: int = 0
    # how long the new version must hold CANARY before promotion
    canary_hold_s: float = 5.0
    # rollback if shadow_diffs / shadow_requests exceeds this fraction
    # during the hold window (only when shadow traffic exists)
    max_shadow_diff_fraction: float = 0.25
    # require the manifest's config hash to match the serving config
    # (disable when rolling between intentionally different configs)
    require_config_hash: bool = True
    # auto-reverse the wave on canary alarm/demotion; False = hold as
    # CANARY and leave the decision to the operator
    auto_rollback: bool = True

    def __post_init__(self):
        for name in ("poll_interval_s", "drain_timeout_s",
                     "swap_timeout_s", "rejoin_timeout_s",
                     "canary_hold_s"):
            v = getattr(self, name)
            if v <= 0:
                raise ValueError(f"rollout.{name} must be > 0, got {v}")
        if self.canary_min_requests < 0:
            raise ValueError(
                "rollout.canary_min_requests must be >= 0, got "
                f"{self.canary_min_requests}"
            )
        if not (0.0 <= self.max_shadow_diff_fraction <= 1.0):
            raise ValueError(
                "rollout.max_shadow_diff_fraction must be in [0, 1], "
                f"got {self.max_shadow_diff_fraction}"
            )


@dataclasses.dataclass(frozen=True)
class FasterRCNNConfig:
    anchors: AnchorConfig = dataclasses.field(default_factory=AnchorConfig)
    proposals: ProposalConfig = dataclasses.field(default_factory=ProposalConfig)
    rpn_targets: RPNTargetConfig = dataclasses.field(default_factory=RPNTargetConfig)
    roi_targets: ROITargetConfig = dataclasses.field(default_factory=ROITargetConfig)
    model: ModelConfig = dataclasses.field(default_factory=ModelConfig)
    data: DataConfig = dataclasses.field(default_factory=DataConfig)
    train: TrainConfig = dataclasses.field(default_factory=TrainConfig)
    eval: EvalConfig = dataclasses.field(default_factory=EvalConfig)
    mesh: MeshConfig = dataclasses.field(default_factory=MeshConfig)
    compile: CompileConfig = dataclasses.field(default_factory=CompileConfig)
    debug: DebugConfig = dataclasses.field(default_factory=DebugConfig)
    analysis: AnalysisConfig = dataclasses.field(default_factory=AnalysisConfig)
    serving: ServingConfig = dataclasses.field(default_factory=ServingConfig)
    fleet: FleetConfig = dataclasses.field(default_factory=FleetConfig)
    elastic: ElasticConfig = dataclasses.field(default_factory=ElasticConfig)
    ops: OpsConfig = dataclasses.field(default_factory=OpsConfig)
    quant: QuantConfig = dataclasses.field(default_factory=QuantConfig)
    telemetry: TelemetryConfig = dataclasses.field(
        default_factory=TelemetryConfig
    )
    rollout: RolloutConfig = dataclasses.field(default_factory=RolloutConfig)

    def feature_size(self, image_size: Optional[Tuple[int, int]] = None) -> Tuple[int, int]:
        """Spatial size of the stride-16 feature map for a given image size.

        The ResNet trunk applies four stride-2 stages, each of which maps
        ``n -> ceil(n / 2)`` under the reference's torch padding
        (conv 7x7/s2/p3, maxpool 3x3/s2/p1, two 3x3/s2/p1 convs) — e.g.
        600 -> 300 -> 150 -> 75 -> 38.
        """
        h, w = image_size if image_size is not None else self.data.image_size
        for _ in range(4):
            h = math.ceil(h / 2)
            w = math.ceil(w / 2)
        return h, w

    def num_anchors(self, image_size: Optional[Tuple[int, int]] = None) -> int:
        fh, fw = self.feature_size(image_size)
        return fh * fw * self.anchors.num_base_anchors

    def replace(self, **kwargs) -> "FasterRCNNConfig":
        return dataclasses.replace(self, **kwargs)


def _cfg(**kw) -> FasterRCNNConfig:
    return FasterRCNNConfig(**kw)


def _voc_data(**kw) -> DataConfig:
    """Shared VOC-preset data pipeline. The 50% horizontal flip is ON by
    default since round 4: measured on the shared 48/256 overfit fixture
    it buys val mAP 0.527 vs 0.407 at train 0.910 vs 0.959
    (benchmarks/map_overfit_result_aug.json) — the original Faster R-CNN
    recipe's augmentation, which the reference omits. Opt out with
    `cli ... --no-augment-hflip`, or in code
    `cfg.replace(data=dataclasses.replace(cfg.data, augment_hflip=False))`.
    """
    kw.setdefault("augment_hflip", True)
    return DataConfig(**kw)


# The five BASELINE.json configs.
CONFIGS = {
    # 1. ResNet18 + RPN + ROIPool on VOC07 (the reference's train.py defaults,
    #    pointed at the VOC2007 devkit per the BASELINE.json metric; the
    #    reference itself hard-codes VOC2012, `frcnn.py:19`)
    "voc_resnet18": _cfg(
        model=ModelConfig(backbone="resnet18", roi_op="pool"),
        data=_voc_data(root_dir="data/voc/VOCdevkit/VOC2007"),
    ),
    # 2. ResNet50 backbone on VOC07
    "voc_resnet50": _cfg(
        model=ModelConfig(backbone="resnet50", roi_op="pool"),
        data=_voc_data(root_dir="data/voc/VOCdevkit/VOC2007"),
    ),
    # 3. FPN neck over ResNet50 + multi-scale anchors
    "voc_resnet50_fpn": _cfg(
        model=ModelConfig(backbone="resnet50", roi_op="align", fpn=True),
        anchors=AnchorConfig(scales=(8.0,)),  # one scale per FPN level
        data=_voc_data(),
    ),
    # 4. ROIAlign head on VOC12
    "voc12_resnet18_align": _cfg(
        model=ModelConfig(backbone="resnet18", roi_op="align"),
        data=_voc_data(root_dir="data/voc/VOCdevkit/VOC2012"),
    ),
    # 5. COCO-2017 80-class, batch 32, data-parallel v5e-8. COCO presets
    #    also flip by default: measured on the COCO-format overfit fixture
    #    val AP50 0.476 vs 0.426, val coco-mAP 0.194 vs 0.177
    #    (benchmarks/coco_overfit_result_aug.json, round 4)
    "coco_resnet50": _cfg(
        model=ModelConfig(backbone="resnet50", num_classes=COCO_NUM_CLASSES, roi_op="align"),
        data=DataConfig(
            dataset="coco", root_dir="data/coco", max_boxes=100,
            augment_hflip=True,
        ),
        train=TrainConfig(batch_size=32),
        eval=EvalConfig(metric="coco"),
    ),
    # 6. The py-faster-rcnn VGG16 COCO net the reference documents via its
    #    checked-in Caffe prototxt (`reference/train_frcnn.prototxt`: VGG16
    #    features, 512-wide RPN conv, 12 anchors = 3 ratios x 4 scales
    #    [num_output 48 = 4*12 at :410-417], RoIPool 7x7, 81 classes).
    "coco_vgg16": _cfg(
        model=ModelConfig(
            backbone="vgg16",
            num_classes=COCO_NUM_CLASSES,
            roi_op="pool",
            rpn_mid_channels=512,
        ),
        anchors=AnchorConfig(scales=(4.0, 8.0, 16.0, 32.0)),
        data=DataConfig(
            dataset="coco", root_dir="data/coco", max_boxes=100,
            augment_hflip=True,
        ),
        eval=EvalConfig(metric="coco"),
    ),
}


def get_config(name: str = "voc_resnet18", **overrides) -> FasterRCNNConfig:
    """Look up a preset config by name, optionally replacing top-level fields."""
    if name not in CONFIGS:
        raise KeyError(f"unknown config {name!r}; choices: {sorted(CONFIGS)}")
    cfg = CONFIGS[name]
    return cfg.replace(**overrides) if overrides else cfg


def config_from_dict(d: dict) -> FasterRCNNConfig:
    """Rebuild a :class:`FasterRCNNConfig` from ``dataclasses.asdict``
    output, e.g. after a JSON round-trip (lists re-become tuples). Used to
    ship a config to a subprocess (benchmark FLOPs analysis)."""
    import typing

    def deep_tuple(v):
        return tuple(deep_tuple(x) for x in v) if isinstance(v, list) else v

    def build(cls, dd):
        hints = typing.get_type_hints(cls)
        kw = {}
        for f in dataclasses.fields(cls):
            if f.name not in dd:
                continue  # dict from an older binary (e.g. pre-`compile`
                # section): absent fields keep their defaults
            v = dd[f.name]
            t = hints.get(f.name)
            if dataclasses.is_dataclass(t) and isinstance(v, dict):
                v = build(t, v)
            else:
                v = deep_tuple(v)
            kw[f.name] = v
        return cls(**kw)

    return build(FasterRCNNConfig, d)
