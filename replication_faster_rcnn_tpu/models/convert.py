"""torch -> flax checkpoint conversion for pretrained ResNet backbones.

The reference warm-starts from a torchvision resnet18 ``.pth`` loaded off
disk (`nets/resnet_torch.py:392-409`, path conventions `readme.md:10-12`)
and splits it into `features` (conv1..layer3) and `classifier` (layer4 +
avgpool). This module performs the equivalent one-time conversion into the
flax parameter trees of :class:`~replication_faster_rcnn_tpu.models.resnet`
— a pure name/layout mapping, since the flax modules mirror the torch
module names.

Layout rules:
  * torch conv weight [O, I, kh, kw]  -> flax kernel [kh, kw, I, O]
  * torch linear weight [O, I]        -> flax kernel [I, O]
  * torch BN {weight, bias} -> params {scale, bias};
    {running_mean, running_var} -> batch_stats {mean, var}
"""

from __future__ import annotations

from typing import Any, Dict, Mapping, Tuple

import numpy as np

# torch is an optional dependency (CPU-only in this image); import lazily so
# the framework itself never requires it.


def _to_np(t: Any) -> np.ndarray:
    return np.asarray(t.detach().cpu().numpy() if hasattr(t, "detach") else t)


def _conv_kernel(w: Any) -> np.ndarray:
    return _to_np(w).transpose(2, 3, 1, 0)  # OIHW -> HWIO


def _split_state_dict(
    state: Mapping[str, Any]
) -> Tuple[Dict[str, Any], Dict[str, Any], Dict[str, Any]]:
    """Split a torchvision resnet state_dict into (trunk, tail, fc) groups,
    mirroring the reference's features/classifier split
    (`nets/resnet_torch.py:399-403`)."""
    trunk: Dict[str, Any] = {}
    tail: Dict[str, Any] = {}
    fc: Dict[str, Any] = {}
    for k, v in state.items():
        if k.startswith("fc."):
            fc[k] = v
        elif k.startswith("layer4."):
            tail[k] = v
        else:
            trunk[k] = v
    return trunk, tail, fc


def _bn_entries(prefix: str, state: Mapping[str, Any]):
    params = {
        "scale": _to_np(state[f"{prefix}.weight"]),
        "bias": _to_np(state[f"{prefix}.bias"]),
    }
    stats = {
        "mean": _to_np(state[f"{prefix}.running_mean"]),
        "var": _to_np(state[f"{prefix}.running_var"]),
    }
    return params, stats


def _convert_block(prefix: str, state: Mapping[str, Any]):
    """One BasicBlock/Bottleneck: torch `layerL.B.*` -> flax `layerL.B` dict."""
    params: Dict[str, Any] = {}
    stats: Dict[str, Any] = {}
    i = 1
    while f"{prefix}.conv{i}.weight" in state:
        params[f"conv{i}"] = {"kernel": _conv_kernel(state[f"{prefix}.conv{i}.weight"])}
        p, s = _bn_entries(f"{prefix}.bn{i}", state)
        params[f"bn{i}"] = p
        stats[f"bn{i}"] = s
        i += 1
    if f"{prefix}.downsample.0.weight" in state:
        params["downsample_conv"] = {
            "kernel": _conv_kernel(state[f"{prefix}.downsample.0.weight"])
        }
        p, s = _bn_entries(f"{prefix}.downsample.1", state)
        params["downsample_bn"] = p
        stats["downsample_bn"] = s
    return params, stats


def _convert_stage(name: str, state: Mapping[str, Any]):
    params: Dict[str, Any] = {}
    stats: Dict[str, Any] = {}
    b = 0
    while f"{name}.{b}.conv1.weight" in state:
        p, s = _convert_block(f"{name}.{b}", state)
        params[f"{name}.{b}"] = p
        stats[f"{name}.{b}"] = s
        b += 1
    return params, stats


def convert_trunk(state: Mapping[str, Any]):
    """torch state_dict (full resnet) -> (params, batch_stats) for ResNetTrunk."""
    params: Dict[str, Any] = {"conv1": {"kernel": _conv_kernel(state["conv1.weight"])}}
    stats: Dict[str, Any] = {}
    p, s = _bn_entries("bn1", state)
    params["bn1"] = p
    stats["bn1"] = s
    for layer in ("layer1", "layer2", "layer3"):
        p, s = _convert_stage(layer, state)
        params.update(p)
        stats.update(s)
    return params, stats


def convert_tail(state: Mapping[str, Any]):
    """torch state_dict (full resnet) -> (params, batch_stats) for ResNetTail."""
    return _convert_stage("layer4", state)


# torchvision vgg16 `features` Sequential index -> our conv name
# (reference documents this net via `reference/train_frcnn.prototxt`)
_VGG16_FEATURE_IDX = {
    0: "conv1_1", 2: "conv1_2",
    5: "conv2_1", 7: "conv2_2",
    10: "conv3_1", 12: "conv3_2", 14: "conv3_3",
    17: "conv4_1", 19: "conv4_2", 21: "conv4_3",
    24: "conv5_1", 26: "conv5_2", 28: "conv5_3",
}


def _fc_kernel_from_chw(w: Any, c: int, h: int, ww: int) -> np.ndarray:
    """torch Linear weight [O, c*h*w] consuming a CHW-flattened input ->
    flax kernel [h*w*c, O] consuming our HWC flatten."""
    wn = _to_np(w)
    return wn.reshape(-1, c, h, ww).transpose(2, 3, 1, 0).reshape(h * ww * c, -1)


def convert_vgg16(state: Mapping[str, Any], roi_size: int = 7):
    """torchvision vgg16 state_dict -> (trunk_params, tail_params) for
    VGG16Trunk / VGG16Tail. fc6's kernel is re-laid-out from torch's
    CHW-flatten to our NHWC-flatten; fc8 (ImageNet logits) is dropped."""
    trunk = {
        name: {
            "kernel": _conv_kernel(state[f"features.{idx}.weight"]),
            "bias": _to_np(state[f"features.{idx}.bias"]),
        }
        for idx, name in _VGG16_FEATURE_IDX.items()
    }
    tail = {
        "fc6": {
            "kernel": _fc_kernel_from_chw(
                state["classifier.0.weight"], 512, roi_size, roi_size
            ),
            "bias": _to_np(state["classifier.0.bias"]),
        },
        "fc7": {
            "kernel": _to_np(state["classifier.3.weight"]).T,
            "bias": _to_np(state["classifier.3.bias"]),
        },
    }
    return trunk, tail


def _load_state_dict(pth_path: str) -> Mapping[str, Any]:
    import torch

    return torch.load(pth_path, map_location="cpu", weights_only=True)


def load_pretrained_backbone(pth_path: str):
    """Load a torchvision resnet ``.pth`` and return flax-ready trees:
    ((trunk_params, trunk_stats), (tail_params, tail_stats)).

    Equivalent of reference ``resnet_backbone`` (`nets/resnet_torch.py:392-409`).
    """
    state = _load_state_dict(pth_path)
    return convert_trunk(state), convert_tail(state)


def graft_into_variables(variables: Dict[str, Any], pth_path: str) -> Dict[str, Any]:
    """Return a copy of FasterRCNN `variables` with the pretrained weights
    grafted in, preserving the pytree structure (so optimizer state built
    from the original params stays valid).

    Two layouts exist:
      * single-scale: conv1..layer3 under `trunk`, layer4 under `head.tail`
        (the reference's features/classifier split);
      * FPN: the whole resnet incl. layer4 under `trunk` (ResNetFeatures);
        the two-fc head has no pretrained counterpart.
    The layout is detected from the variables themselves.
    """
    import jax

    variables = jax.tree_util.tree_map(lambda x: x, variables)  # shallow copy
    params = dict(variables["params"])
    stats = dict(variables.get("batch_stats", {}))

    if "conv1_1" in params.get("trunk", {}):  # VGG16 layout (no BN stats)
        # derive the model's roi_size from its fc6 kernel so a non-7x7
        # configuration fails fast here instead of as an XLA shape error
        fc6_rows = params["head"]["tail"]["fc6"]["kernel"].shape[0]
        roi_size = int(round((fc6_rows // 512) ** 0.5))
        if roi_size * roi_size * 512 != fc6_rows:
            raise ValueError(f"unexpected fc6 in-features {fc6_rows}")
        state = _load_state_dict(pth_path)
        # validate the CHECKPOINT side before reshaping: a mismatched
        # roi_size would otherwise fold silently into the output dim
        ckpt_in = state["classifier.0.weight"].shape[1]
        if ckpt_in != fc6_rows:
            raise ValueError(
                f"pretrained fc6 consumes {ckpt_in} in-features but the "
                f"model was built with {fc6_rows} (roi_size {roi_size}) — "
                "torchvision vgg16 checkpoints require roi_size=7"
            )
        tp, lp = convert_vgg16(state, roi_size=roi_size)
        params["trunk"] = {**params["trunk"], **tp}
        head = dict(params.get("head", {}))
        head["tail"] = {**head.get("tail", {}), **lp}
        params["head"] = head
        out = dict(variables)
        out["params"] = params
        return out

    # a norm="group" model has the same param names/shapes at every BN
    # site (scale/bias) but NO batch_stats collection — a torch BN
    # checkpoint would graft silently and apply BN-calibrated affines to
    # group-normalized activations. Fail fast instead (the GN preset
    # trains from scratch or from a GN-pretrained checkpoint via
    # train/pretrain.py).
    if "bn1" in params.get("trunk", {}) and not stats.get("trunk"):
        raise ValueError(
            "model has no BatchNorm statistics (norm='group'?) — "
            "torch-pretrained BN checkpoints do not convert onto a "
            "GroupNorm backbone"
        )

    (tp, ts), (lp, ls) = load_pretrained_backbone(pth_path)

    fpn = "layer4.0" in params.get("trunk", {})
    params["trunk"] = {**params.get("trunk", {}), **tp}
    stats["trunk"] = {**stats.get("trunk", {}), **ts}
    if fpn:
        params["trunk"].update(lp)
        stats["trunk"].update(ls)
    else:
        head = dict(params.get("head", {}))
        head["tail"] = {**head.get("tail", {}), **lp}
        params["head"] = head
        hstats = dict(stats.get("head", {}))
        hstats["tail"] = {**hstats.get("tail", {}), **ls}
        stats["head"] = hstats
    out = dict(variables)
    out["params"] = params
    out["batch_stats"] = stats
    return out
