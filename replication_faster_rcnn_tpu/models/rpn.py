"""Region Proposal Network — flax head + fixed-shape proposal selection.

Capability parity with reference `nets/rpn.py:82-138` (RPN module) and
`nets/rpn.py:20-79` (`region_proposal` layer), redesigned for XLA:

  * The head is a 3x3 conv + ReLU and two 1x1 convs (cls: K*2 channels,
    reg: K*4 channels), all gaussian-init sigma 0.01 (reference
    `nets/rpn.py:93-100`). NHWC; outputs are reshaped to [N, H*W*K, .]
    position-major, matching the anchor grid ordering in
    `ops/anchors.grid_anchors`.
  * Proposal selection — decode, clip, min-size filter, top-pre_nms by
    score, NMS, keep post_nms (reference `nets/rpn.py:47-78`) — is a pure
    fixed-shape function vmapped over the batch instead of a per-image
    Python loop (`nets/rpn.py:131-136`). The reference's data-dependent
    output length (SURVEY.md §2.1 #10) becomes a padded [post_nms] roi
    array plus a validity mask.
"""

from __future__ import annotations

from typing import Any, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from replication_faster_rcnn_tpu.config import ProposalConfig
from replication_faster_rcnn_tpu.ops import boxes as box_ops

Array = jnp.ndarray


def _gaussian_conv(
    features: int, kernel: int, padding: int, dtype: Any, name: str
) -> nn.Conv:
    """Conv with N(0, 0.01) weight init and zero bias (reference
    `nets/rpn.py:11-17` ``normal_init`` with stddev=0.01, truncated=False)."""
    return nn.Conv(
        features=features,
        kernel_size=(kernel, kernel),
        strides=(1, 1),
        padding=((padding, padding), (padding, padding)),
        kernel_init=nn.initializers.normal(stddev=0.01),
        bias_init=nn.initializers.zeros,
        dtype=dtype,
        param_dtype=jnp.float32,
        name=name,
    )


class RPNHead(nn.Module):
    """Conv heads producing per-anchor objectness logits and box deltas.

    Input: trunk features NHWC [N, H, W, C].
    Output: (logits [N, H*W*K, 2], deltas [N, H*W*K, 4]) in float32,
    position-major to align with the [H*W*K, 4] anchor grid.
    """

    num_anchors: int  # K
    mid_channels: int = 256
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, feat: Array) -> Tuple[Array, Array]:
        n = feat.shape[0]
        x = _gaussian_conv(self.mid_channels, 3, 1, self.dtype, "conv1")(feat)
        x = nn.relu(x)
        logits = _gaussian_conv(self.num_anchors * 2, 1, 0, self.dtype, "cls")(x)
        deltas = _gaussian_conv(self.num_anchors * 4, 1, 0, self.dtype, "reg")(x)
        # [N, H, W, K*d] -> [N, H*W*K, d]: position-major flatten matches
        # the reference's permute(0,2,3,1).view(N,-1,d) (`nets/rpn.py:117-124`)
        # and ops.anchors' flat index = (r*W + c)*K + k.
        logits = logits.reshape(n, -1, 2).astype(jnp.float32)
        deltas = deltas.reshape(n, -1, 4).astype(jnp.float32)
        return logits, deltas


def select_proposals(
    anchors: Array,
    fg_scores: Array,
    deltas: Array,
    img_h: float,
    img_w: float,
    cfg: ProposalConfig,
    train: bool,
) -> Tuple[Array, Array]:
    """Per-image proposal selection (reference `nets/rpn.py:47-78`), fixed-shape.

    Args:
      anchors: [A, 4]; fg_scores: [A] foreground softmax scores;
      deltas: [A, 4] predicted regression.
    Returns:
      (rois [post_nms, 4], valid [post_nms] bool). Invalid slots are zeros.
    """
    pre_nms = min(cfg.pre_nms(train), anchors.shape[0])
    post_nms = cfg.post_nms(train)

    props = box_ops.decode(anchors, deltas)
    props = box_ops.clip(props, img_h, img_w)

    # min-size filter as a mask (reference `nets/rpn.py:65-68` drops rows)
    hs = props[:, 2] - props[:, 0]
    ws = props[:, 3] - props[:, 1]
    keep = (hs >= cfg.min_size) & (ws >= cfg.min_size)
    scores = jnp.where(keep, fg_scores, -jnp.inf)

    # top-pre_nms by score (reference sorts then truncates, `nets/rpn.py:70-72`).
    # One stable argsort serves BOTH the truncation and the NMS's
    # descending-order requirement (assume_sorted below) — top_k followed
    # by the NMS-internal argsort sorted ~12k candidates twice per image.
    # lax.top_k and stable argsort(-s) break ties identically (lowest
    # original index first), so this is bit-identical to the old pipeline.
    order = jnp.argsort(-scores)
    top_idx = jax.lax.slice_in_dim(order, 0, pre_nms)
    top_scores = scores[top_idx]
    top_boxes = props[top_idx]

    # tiled exact NMS by default; ops.backend=pallas (or FRCNN_NMS=pallas)
    # swaps in the bit-identical ops/pallas kernel, FRCNN_NMS=loop the
    # serial selection loop — see nms_fixed_auto
    from replication_faster_rcnn_tpu.ops.nms import nms_fixed_auto

    idx, valid = nms_fixed_auto(
        top_boxes,
        top_scores,
        cfg.nms_thresh,
        post_nms,
        mask=jnp.isfinite(top_scores),
        assume_sorted=True,
    )
    rois = top_boxes[idx] * valid[:, None]
    return rois, valid


def batched_proposals(
    anchors: Array,
    logits: Array,
    deltas: Array,
    img_h: float,
    img_w: float,
    cfg: ProposalConfig,
    train: bool,
) -> Tuple[Array, Array]:
    """Batch proposal selection: logits [N, A, 2], deltas [N, A, 4] ->
    (rois [N, post_nms, 4], valid [N, post_nms]).

    The foreground score is softmax(logits)[..., 1] (reference
    `nets/rpn.py:119-121`). rois carry no gradient — the reference detaches
    them before head sampling (`train.py:94`); here the stop_gradient makes
    that contract explicit at the source.
    """
    fg = jax.nn.softmax(logits, axis=-1)[..., 1]
    fg = jax.lax.stop_gradient(fg)
    deltas = jax.lax.stop_gradient(deltas)
    return jax.vmap(
        lambda s, d: select_proposals(anchors, s, d, img_h, img_w, cfg, train)
    )(fg, deltas)
