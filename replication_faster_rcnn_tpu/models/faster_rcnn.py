"""Faster R-CNN assembly — trunk + RPN + detection head as one flax module.

Capability parity with reference `nets/faster_rcnn.py:7-34` (``FasterRCNN``)
— and a working version of its combined forward, which in the reference is
broken (calls the head without its required img_h/img_w args,
`nets/faster_rcnn.py:31` vs `nets/heads.py:27`; SURVEY.md §3.2).

The trainer needs to run target assignment between the RPN and the head
(reference `train.py:63-110` bypasses the combined forward for exactly this
reason). Rather than bypassing the module, the stages are exposed as flax
methods — ``extract_features`` / ``rpn_forward`` / ``head_forward`` — which
`apply(..., method=...)` can call separately inside the one jitted train
step; ``__call__`` composes them for inference.

Anchors are a compile-time constant: the feature map shape is static under
jit, so the full [H*W*K, 4] grid is baked into the XLA program instead of
being regenerated from numpy on every forward (reference `nets/rpn.py:126-127`,
a host-device boundary in the reference's hot loop).
"""

from __future__ import annotations

from typing import Any, Tuple

import flax.linen as nn
import jax.numpy as jnp

from replication_faster_rcnn_tpu.config import FasterRCNNConfig
from replication_faster_rcnn_tpu.models.head import DetectionHead
from replication_faster_rcnn_tpu.models.resnet import ResNetTrunk
from replication_faster_rcnn_tpu.models.rpn import RPNHead, batched_proposals
from replication_faster_rcnn_tpu.ops import anchors as anchor_ops

Array = jnp.ndarray


class FasterRCNN(nn.Module):
    """The full two-stage detector.

    Submodule layout (names matter for checkpoint conversion):
      trunk — ResNetTrunk (conv1..layer3)
      rpn   — RPNHead
      head  — DetectionHead (contains the layer4 tail)
    """

    config: FasterRCNNConfig

    def setup(self) -> None:
        cfg = self.config
        dtype = jnp.dtype(cfg.model.compute_dtype)
        if cfg.model.fpn:
            from replication_faster_rcnn_tpu.models.fpn import FPNNeck, ResNetFeatures
            from replication_faster_rcnn_tpu.models.head import FPNDetectionHead

            self.trunk = ResNetFeatures(
                cfg.model.backbone, dtype, bn_axis=cfg.model.bn_axis,
                remat=cfg.model.remat, frozen_bn=cfg.model.frozen_bn,
                norm=cfg.model.norm,
            )
            self.neck = FPNNeck(cfg.model.fpn_channels, dtype)
            self.rpn = RPNHead(
                num_anchors=cfg.anchors.num_base_anchors,
                mid_channels=cfg.model.fpn_channels,
                dtype=dtype,
            )
            self.head = FPNDetectionHead(
                num_classes=cfg.model.num_classes,
                roi_size=cfg.model.roi_size,
                sampling_ratio=cfg.model.roi_sampling_ratio,
                dtype=dtype,
            )
        else:
            if cfg.model.backbone == "vgg16":
                from replication_faster_rcnn_tpu.models.vgg import VGG16Trunk

                self.trunk = VGG16Trunk(dtype, remat=cfg.model.remat)
            else:
                self.trunk = ResNetTrunk(
                    cfg.model.backbone, dtype, bn_axis=cfg.model.bn_axis,
                    remat=cfg.model.remat, frozen_bn=cfg.model.frozen_bn,
                    norm=cfg.model.norm,
                )
            # the head dispatches internally on arch (VGG16 fc6/fc7 tail
            # vs ResNet layer4 tail)
            self.rpn = RPNHead(
                num_anchors=cfg.anchors.num_base_anchors,
                mid_channels=cfg.model.rpn_mid_channels,
                dtype=dtype,
            )
            self.head = DetectionHead(
                arch=cfg.model.backbone,
                num_classes=cfg.model.num_classes,
                roi_size=cfg.model.roi_size,
                roi_op=cfg.model.roi_op,
                sampling_ratio=cfg.model.roi_sampling_ratio,
                dtype=dtype,
                bn_axis=cfg.model.bn_axis,
                frozen_bn=cfg.model.frozen_bn,
                norm=cfg.model.norm,
            )

    # --- stage methods (used individually by the trainer) ---

    def preprocess(self, images: Array) -> Array:
        """uint8 NHWC -> normalized float32, on device.

        With ``data.device_normalize`` the host ships raw bytes (a quarter
        of the f32 transfer volume — the tunnel/PCIe hop is the fed
        trainer's bottleneck, not the chip) and this affine runs on-chip,
        where XLA fuses it into the first conv's input. float32 input
        passes through untouched (the host already normalized it)."""
        if images.dtype == jnp.uint8:
            mean = jnp.asarray(self.config.data.pixel_mean, jnp.float32)
            std = jnp.asarray(self.config.data.pixel_std, jnp.float32)
            images = (images.astype(jnp.float32) / 255.0 - mean) / std
        return images

    def extract_features(self, images: Array, train: bool = False):
        """images NHWC [N, H, W, 3] -> shared features.

        Single-scale: one [N, H/16, W/16, C] map. FPN: list [P2..P6]."""
        images = self.preprocess(images)
        if self.config.model.fpn:
            return self.neck(self.trunk(images, train))
        return self.trunk(images, train)

    def rpn_forward(self, feat) -> Tuple[Array, Array, Array]:
        """features -> (logits [N, A, 2], deltas [N, A, 4], anchors [A, 4]).

        FPN: the SAME RPN head runs on every level (FPN paper: shared
        heads); per-level outputs and anchors concatenate fine->coarse, so
        downstream proposal/target code is level-agnostic.
        """
        if self.config.model.fpn:
            from replication_faster_rcnn_tpu.models.fpn import FPN_STRIDES

            logits_l, deltas_l, anchors_l = [], [], []
            for level, stride in zip(feat, FPN_STRIDES):
                lg, dl = self.rpn(level)
                logits_l.append(lg)
                deltas_l.append(dl)
                base = anchor_ops.anchor_base(
                    stride, self.config.anchors.ratios, self.config.anchors.scales
                )
                anchors_l.append(
                    anchor_ops.grid_anchors(
                        base, stride, level.shape[1], level.shape[2]
                    )
                )
            import numpy as np

            return (
                jnp.concatenate(logits_l, axis=1),
                jnp.concatenate(deltas_l, axis=1),
                jnp.asarray(
                    np.concatenate(anchors_l, axis=0), dtype=jnp.float32
                ),
            )
        logits, deltas = self.rpn(feat)
        anchors = jnp.asarray(
            anchor_ops.make_anchors(
                self.config.anchors, (feat.shape[1], feat.shape[2])
            ),
            dtype=jnp.float32,
        )
        return logits, deltas, anchors

    def propose(
        self,
        logits: Array,
        deltas: Array,
        anchors: Array,
        img_h: float,
        img_w: float,
        train: bool,
    ) -> Tuple[Array, Array]:
        """(rois [N, post_nms, 4], valid [N, post_nms]) — fixed shape."""
        return batched_proposals(
            anchors, logits, deltas, img_h, img_w, self.config.proposals, train
        )

    def head_forward(
        self,
        feat,
        rois: Array,
        img_h: float,
        img_w: float,
        train: bool = False,
    ) -> Tuple[Array, Array]:
        """(cls [N, R, num_classes], reg [N, R, num_classes*4])."""
        return self.head(feat, rois, img_h, img_w, train)

    # --- combined forward (inference path) ---

    def __call__(
        self, images: Array, train: bool = False
    ) -> Tuple[Array, Array, Array, Array, Array, Array, Array]:
        """Full forward (reference `nets/faster_rcnn.py:24-34`, fixed).

        Returns (rpn_logits, rpn_deltas, rois, roi_valid, cls, reg, anchors).
        """
        img_h, img_w = float(images.shape[1]), float(images.shape[2])
        feat = self.extract_features(images, train)
        logits, deltas, anchors = self.rpn_forward(feat)
        rois, valid = self.propose(logits, deltas, anchors, img_h, img_w, train)
        cls, reg = self.head_forward(feat, rois, img_h, img_w, train)
        return logits, deltas, rois, valid, cls, reg, anchors


def create(config: FasterRCNNConfig) -> FasterRCNN:
    return FasterRCNN(config)


def init_variables(config: FasterRCNNConfig, rng: Any, batch_size: int = 1):
    """Initialize parameters/batch stats with a dummy batch."""
    model = FasterRCNN(config)
    h, w = config.data.image_size
    dummy = jnp.zeros((batch_size, h, w, 3), jnp.float32)
    return model, model.init({"params": rng}, dummy, train=False)
