"""Second-stage detection head — ROI feature extraction + ResNet tail + fc.

Capability parity with reference `nets/heads.py:7-59` (``ResnetHead``),
redesigned fixed-shape:

  * ROIs arrive batched [N, R, 4] in image coordinates with a validity mask
    (instead of the reference's flat [N*R, 4] + batch-index column,
    `nets/heads.py:47`); extraction vmaps the ROIAlign/ROIPool op over the
    batch.
  * ROIs are scaled image->feature by dividing by the image size and
    multiplying by the feature size, exactly the reference's arithmetic
    (`nets/heads.py:42-44` — equivalent to 1/feat_stride).
  * The pooled crops run through the backbone tail (layer4 + avgpool — the
    reference's `classifier`, `nets/heads.py:51-52`) then two Linear heads:
    reg -> num_classes*4, cls -> num_classes (`nets/heads.py:21-22`), with
    in-features derived from the tail (fixing the hard-coded 512 that broke
    resnet50 in the reference, SURVEY.md §2.1 #11).
  * Invalid (padded) rois produce outputs as normal; callers mask the loss.
"""

from __future__ import annotations

from typing import Any, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from replication_faster_rcnn_tpu.models.resnet import ResNetTail
from replication_faster_rcnn_tpu.ops import roi_ops

Array = jnp.ndarray


class QuantDense(nn.Module):
    """int8 twin of the cls/reg Dense: same param names/shapes ("kernel"
    int8 [in, out], "bias" f32), computed as a true int8 GEMM through
    `ops/quant_ops.py::quant_dense` with the calibrated activation scale.
    Only ever instantiated when the serve path supplies a ``"quant"``
    collection entry — the f32/bf16 trace never reaches this class, so
    the fingerprint-banked programs are untouched."""

    features: int

    @nn.compact
    def __call__(self, x: Array, qinfo) -> Array:
        from replication_faster_rcnn_tpu.ops import quant_ops

        kernel = self.param(
            "kernel",
            lambda rng, shape: jnp.zeros(shape, jnp.int8),
            (x.shape[-1], self.features),
        )
        bias = self.param(
            "bias", nn.initializers.zeros, (self.features,), jnp.float32
        )
        return quant_ops.quant_dense(
            x, kernel, qinfo["w_scale"], qinfo["x_scale"], bias
        )


def _head_dense(mod: nn.Module, x: Array, features: int, stddev: float, name: str) -> Array:
    """cls/reg projection: the banked nn.Dense, or its QuantDense twin
    when the caller passed quantization info for this layer."""
    if mod.has_variable("quant", name):
        return QuantDense(features, name=name)(x, mod.get_variable("quant", name))
    return nn.Dense(
        features,
        kernel_init=nn.initializers.normal(stddev=stddev),
        param_dtype=jnp.float32,
        name=name,
    )(x)


class DetectionHead(nn.Module):
    """ROI extract + tail + cls/reg Linear heads.

    __call__(feat [N, H, W, C], rois [N, R, 4], img_h, img_w, train)
      -> (cls_logits [N, R, num_classes], reg [N, R, num_classes*4]) float32.
    """

    arch: str = "resnet18"
    num_classes: int = 21
    roi_size: int = 7
    roi_op: str = "align"  # "align" | "pool"
    sampling_ratio: int = 2
    dtype: Any = jnp.bfloat16
    bn_axis: Any = None  # sync-BN axis for the ResNet tail under shard_map
    frozen_bn: bool = False  # see ResNetTrunk.frozen_bn (applies to the tail)
    norm: str = "batch"  # see ResNetTrunk.norm (applies to the tail)

    @nn.compact
    def __call__(
        self,
        feat: Array,
        rois: Array,
        img_h: float,
        img_w: float,
        train: bool = False,
    ) -> Tuple[Array, Array]:
        n, r = rois.shape[0], rois.shape[1]
        fh, fw = feat.shape[1], feat.shape[2]

        # image -> feature coordinates (reference `nets/heads.py:42-44`)
        scale = jnp.array(
            [fh / img_h, fw / img_w, fh / img_h, fw / img_w], rois.dtype
        )
        feat_rois = rois * scale

        def extract(f: Array, rb: Array) -> Array:
            return roi_ops.extract_roi_features(
                f,
                rb,
                op=self.roi_op,
                out_size=self.roi_size,
                sampling_ratio=self.sampling_ratio,
            )

        crops = jax.vmap(extract)(feat, feat_rois)  # [N, R, s, s, C]
        crops = crops.reshape((n * r,) + crops.shape[2:])

        # Backbone tail: layer4+avgpool for ResNets (the reference's
        # `classifier`, `nets/heads.py:51-52`); fc6/fc7 for the
        # prototxt-documented VGG16 (models/vgg.py).
        if self.arch == "vgg16":
            from replication_faster_rcnn_tpu.models.vgg import VGG16Tail

            embed = VGG16Tail(self.dtype, name="tail")(crops, train)
        else:
            embed = ResNetTail(
                self.arch, self.dtype, bn_axis=self.bn_axis,
                frozen_bn=self.frozen_bn, norm=self.norm, name="tail"
            )(crops, train)
        embed = embed.astype(jnp.float32)  # [N*R, C_tail]

        # Paper-standard inits the reference leaves at torch defaults:
        # cls N(0, 0.01), reg N(0, 0.001).
        cls = _head_dense(self, embed, self.num_classes, 0.01, "cls")
        reg = _head_dense(self, embed, self.num_classes * 4, 0.001, "reg")
        return cls.reshape(n, r, -1), reg.reshape(n, r, -1)


class FPNDetectionHead(nn.Module):
    """FPN variant of the detection head: multilevel ROIAlign + the paper's
    two-fc (1024-1024) box head instead of the ResNet layer4 tail (which the
    FPN backbone consumes as C5).

    __call__(feats [P2..P6 list], rois [N, R, 4], img_h, img_w, train)
      -> (cls_logits [N, R, num_classes], reg [N, R, num_classes*4]).
    """

    num_classes: int = 21
    roi_size: int = 7
    sampling_ratio: int = 2
    mlp_dim: int = 1024
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(
        self,
        feats,
        rois: Array,
        img_h: float,
        img_w: float,
        train: bool = False,
    ) -> Tuple[Array, Array]:
        from replication_faster_rcnn_tpu.models.fpn import multilevel_roi_align

        n, r = rois.shape[0], rois.shape[1]
        crops = multilevel_roi_align(
            feats, rois, img_h, img_w, self.roi_size, self.sampling_ratio
        )  # [N, R, s, s, C]
        x = crops.reshape(n * r, -1).astype(self.dtype)
        # dtype=self.dtype keeps the two big matmuls on the MXU in bf16
        # (param_dtype stays f32; flax would otherwise promote to f32).
        x = nn.relu(
            nn.Dense(self.mlp_dim, dtype=self.dtype, param_dtype=jnp.float32, name="fc6")(x)
        )
        x = nn.relu(
            nn.Dense(self.mlp_dim, dtype=self.dtype, param_dtype=jnp.float32, name="fc7")(x)
        )
        x = x.astype(jnp.float32)  # cls/reg logits in f32
        cls = _head_dense(self, x, self.num_classes, 0.01, "cls")
        reg = _head_dense(self, x, self.num_classes * 4, 0.001, "reg")
        return cls.reshape(n, r, -1), reg.reshape(n, r, -1)


def select_class_deltas(reg: Array, labels: Array) -> Array:
    """Pick each roi's box deltas for a given class id.

    reg: [..., R, num_classes*4]; labels: [..., R] int -> [..., R, 4].
    The reference does this with gather over computed flat indices
    label*4 + {0..3} (`train.py:112-117`); here it is a take_along_axis
    over the class axis.
    """
    shape = reg.shape[:-1] + (-1, 4)
    per_class = reg.reshape(shape)  # [..., R, C, 4]
    idx = labels[..., None, None].astype(jnp.int32)
    idx = jnp.clip(idx, 0, per_class.shape[-2] - 1)
    return jnp.take_along_axis(per_class, jnp.broadcast_to(idx, shape[:-2] + (1, 4)), axis=-2)[
        ..., 0, :
    ]
