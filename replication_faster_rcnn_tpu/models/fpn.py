"""Feature Pyramid Network — BASELINE.json config #3 ("FPN neck over
ResNet50 + multi-scale anchors").

No reference implementation exists (the reference is single-scale C4;
its `utils/anchors.py` multi-scale anchors are scale-multiples at one
stride). This follows the FPN paper (Lin et al., arXiv:1612.03144) with the
standard Faster-R-CNN-FPN wiring, built fixed-shape for XLA:

  * backbone exposes C2..C5 (strides 4/8/16/32);
  * 1x1 lateral convs + nearest top-down upsample + 3x3 smoothing -> P2..P5,
    plus P6 = stride-2 subsample of P5 (RPN-only level);
  * the RPN head is ONE set of convs shared across levels;
  * anchors use one scale per level (AnchorConfig.scales=(8,)) over
    per-level strides (4, 8, 16, 32, 64);
  * ROIs are assigned to levels by the paper's k = k0 + log2(sqrt(area)/224)
    rule. On TPU the pyramid is flattened into one [N, sum(Hl*Wl), C]
    buffer and each roi does a single 4-corner gather at level-offset flat
    indices — fully static shapes, no sorting/regrouping, one backward
    scatter (see multilevel_roi_align).

All spatial tensors are NHWC; levels are a list ordered fine -> coarse.
"""

from __future__ import annotations

from typing import Any, List, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from replication_faster_rcnn_tpu.models.resnet import _WIDTHS, _conv, _norm, _spec, _stage
from replication_faster_rcnn_tpu.ops import roi_ops

Array = jnp.ndarray

FPN_STRIDES: Tuple[int, ...] = (4, 8, 16, 32, 64)  # P2..P6


class ResNetFeatures(nn.Module):
    """ResNet trunk exposing every stage: [C2, C3, C4, C5]
    (strides 4/8/16/32; channels x1 for BasicBlock, x4 for Bottleneck).

    Same parameter naming/layout as ResNetTrunk+ResNetTail so pretrained
    torch checkpoints convert identically (layer4 lives here, not in the
    head, when FPN is on)."""

    arch: str = "resnet50"
    dtype: Any = jnp.bfloat16
    bn_axis: Any = None
    remat: bool = False  # jax.checkpoint each residual block
    frozen_bn: bool = False  # see ResNetTrunk.frozen_bn
    norm: str = "batch"  # see ResNetTrunk.norm

    @nn.compact
    def __call__(self, x: Array, train: bool = False) -> List[Array]:
        depths = _spec(self.arch)[1]
        train = train and not self.frozen_bn  # `train` only gates BN here
        ax, rm, nm = self.bn_axis, self.remat, self.norm
        x = x.astype(self.dtype)
        x = _conv(64, 7, 2, 3, self.dtype, "conv1")(x)
        x = _norm(self.dtype, train, "bn1", ax, nm)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))
        c2 = _stage(self.arch, x, _WIDTHS[0], depths[0], 1, self.dtype, train, "layer1", ax, rm, nm)
        c3 = _stage(self.arch, c2, _WIDTHS[1], depths[1], 2, self.dtype, train, "layer2", ax, rm, nm)
        c4 = _stage(self.arch, c3, _WIDTHS[2], depths[2], 2, self.dtype, train, "layer3", ax, rm, nm)
        c5 = _stage(self.arch, c4, _WIDTHS[3], depths[3], 2, self.dtype, train, "layer4", ax, rm, nm)
        return [c2, c3, c4, c5]


def _upsample_nearest(x: Array, target_hw: Tuple[int, int]) -> Array:
    """2x nearest upsample cropped to the (possibly odd) finer shape."""
    n, h, w, c = x.shape
    y = jnp.repeat(jnp.repeat(x, 2, axis=1), 2, axis=2)
    return y[:, : target_hw[0], : target_hw[1], :]


class FPNNeck(nn.Module):
    """[C2..C5] -> [P2..P6], all ``channels`` wide."""

    channels: int = 256
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, feats: Sequence[Array]) -> List[Array]:
        c2, c3, c4, c5 = feats
        laterals = [
            _conv(self.channels, 1, 1, 0, self.dtype, f"lateral{i}")(c)
            for i, c in enumerate((c2, c3, c4, c5))
        ]
        # top-down pathway
        tds = [laterals[3]]
        for i in (2, 1, 0):
            finer = laterals[i]
            tds.insert(
                0, finer + _upsample_nearest(tds[0], finer.shape[1:3])
            )
        outs = [
            _conv(self.channels, 3, 1, 1, self.dtype, f"smooth{i}")(t)
            for i, t in enumerate(tds)
        ]
        # P6: stride-2 subsample of P5 (maxpool k=1 s=2, Detectron convention)
        p6 = outs[3][:, ::2, ::2, :]
        return outs + [p6]


def roi_levels(rois: Array, k0: int = 4, canonical: float = 224.0) -> Array:
    """FPN paper level assignment: [..., 4] rois -> int level index 0..3
    (P2..P5; P6 is RPN-only). k = k0 + log2(sqrt(area)/canonical)."""
    h = jnp.maximum(rois[..., 2] - rois[..., 0], 1e-6)
    w = jnp.maximum(rois[..., 3] - rois[..., 1], 1e-6)
    k = jnp.floor(k0 + jnp.log2(jnp.sqrt(h * w) / canonical))
    return jnp.clip(k, 2, 5).astype(jnp.int32) - 2


def multilevel_roi_align(
    feats: Sequence[Array],
    rois: Array,
    img_h: float,
    img_w: float,
    out_size: int = 7,
    sampling_ratio: int = 2,
    method: str = "flat",
) -> Array:
    """ROIAlign across P2..P5 with level assignment, fixed-shape.

    feats: 4 arrays [N, Hl, Wl, C]; rois: [N, R, 4] image coords.
    Returns [N, R, out, out, C].

    ``method="flat"`` (default): all four levels are flattened into ONE
    [N, sum(Hl*Wl), C] buffer and every roi does a single 4-corner
    bilinear gather with level-offset flat indices (index = level_offset +
    r * Wl + c, computed from the roi's assigned level). One gather pass
    and one backward scatter for the whole pyramid — measured 3.4x the
    blend path on v5e (50.3 -> 14.6 ms at b8, 128 rois; see
    benchmarks/bench_v5e_round2.json).

    ``method="blend"``: the original formulation — every roi is aligned on
    EVERY level (gather roi_align per level) and the results combined with
    a one-hot level mask. 4x the gathers and a 4x backward scatter; kept
    as the oracle for the flat path's parity test. The two are the same
    math (the blended sum adds exact zeros) but not bitwise: the sample
    coordinate r1 + pts*bin feeds floor(), and XLA's FMA choice can shift
    the fractional part (the bilinear weight) by ~eps(coordinate).

    The einsum (MXU) roi_align formulation is deliberately not used here:
    its dense [R, P, H] weight matmul is a win on the stride-16
    single-scale map but scales with H*W, which at P2 (stride 4, 150x150
    for 600 input) costs ~10x the whole backbone.
    """
    levels = roi_levels(rois)  # [N, R]
    if method == "blend":
        out = None
        for li, feat in enumerate(feats[:4]):
            scale_r = feat.shape[1] / img_h
            scale_c = feat.shape[2] / img_w
            scale = jnp.asarray([scale_r, scale_c, scale_r, scale_c], rois.dtype)

            def align_one(f: Array, rb: Array) -> Array:
                return roi_ops.roi_align(
                    f,
                    rb * scale,
                    out_size=out_size,
                    sampling_ratio=sampling_ratio,
                    method="gather",
                )

            crops = jax.vmap(align_one)(feat, rois)  # [N, R, s, s, C]
            mask = (levels == li).astype(crops.dtype)[..., None, None, None]
            out = crops * mask if out is None else out + crops * mask
        return out
    if method != "flat":
        raise ValueError(f"unknown multilevel_roi_align method {method!r}")

    import numpy as np

    n, r_cnt = rois.shape[0], rois.shape[1]
    c_dim = feats[0].shape[-1]
    hs = [int(f.shape[1]) for f in feats[:4]]
    ws = [int(f.shape[2]) for f in feats[:4]]
    offs = np.concatenate([[0], np.cumsum([h * w for h, w in zip(hs, ws)])[:-1]])
    flat = jnp.concatenate([f.reshape(n, -1, c_dim) for f in feats[:4]], axis=1)

    dt = rois.dtype
    h_l = jnp.asarray(hs, dt)[levels]  # [N, R] assigned-level extents
    w_l = jnp.asarray(ws, dt)[levels]
    w_li = jnp.asarray(ws, jnp.int32)[levels]
    off_l = jnp.asarray(offs, jnp.int32)[levels]

    # roi coords scaled into assigned-level units (blend path: rb * scale)
    sr = h_l / img_h
    sc = w_l / img_w
    r1, c1 = rois[..., 0] * sr, rois[..., 1] * sc
    r2, c2 = rois[..., 2] * sr, rois[..., 3] * sc

    # sample grid (roi_ops._sample_grid semantics: 1px minimum extent,
    # sample centers at (p + .5)/s bin units)
    s = sampling_ratio
    bin_h = jnp.maximum(r2 - r1, 1.0) / out_size  # [N, R]
    bin_w = jnp.maximum(c2 - c1, 1.0) / out_size
    pts = (jnp.arange(out_size * s, dtype=dt) + 0.5) / s  # [S]
    rr = r1[..., None] + pts * bin_h[..., None]  # [N, R, S]
    cc = c1[..., None] + pts * bin_w[..., None]

    # 4-corner bilinear on the [N, R, S, S] grid, extents per assigned
    # level (roi_ops._bilinear_gather border rule: outside [-1, H]x[-1, W]
    # contributes zero; in-range clamps to the valid window)
    rg = rr[..., :, None] * jnp.ones_like(cc)[..., None, :]
    cg = cc[..., None, :] * jnp.ones_like(rr)[..., :, None]
    hb = h_l[..., None, None]
    wb = w_l[..., None, None]
    in_range = (rg >= -1.0) & (rg <= hb) & (cg >= -1.0) & (cg <= wb)
    rg = jnp.clip(rg, 0.0, hb - 1.0)
    cg = jnp.clip(cg, 0.0, wb - 1.0)
    r0 = jnp.floor(rg)
    c0 = jnp.floor(cg)
    r0i = r0.astype(jnp.int32)
    c0i = c0.astype(jnp.int32)
    r1i = jnp.minimum(r0i + 1, hb.astype(jnp.int32) - 1)
    c1i = jnp.minimum(c0i + 1, wb.astype(jnp.int32) - 1)
    ar = rg - r0
    ac = cg - c0

    base = off_l[..., None, None]
    wrow = w_li[..., None, None]

    def corner(ri: Array, ci: Array) -> Array:
        idx = (base + ri * wrow + ci).reshape(n, -1)  # [N, R*S*S]
        return jnp.take_along_axis(flat, idx[..., None], axis=1)  # [N, K, C]

    def w3(w: Array) -> Array:
        return w.reshape(n, -1, 1)

    sampled = (
        corner(r0i, c0i) * w3((1 - ar) * (1 - ac))
        + corner(r0i, c1i) * w3((1 - ar) * ac)
        + corner(r1i, c0i) * w3(ar * (1 - ac))
        + corner(r1i, c1i) * w3(ar * ac)
    )
    sampled = sampled * w3(in_range.astype(sampled.dtype))
    sampled = sampled.reshape(n, r_cnt, out_size, s, out_size, s, c_dim)
    return sampled.mean(axis=(3, 5))
