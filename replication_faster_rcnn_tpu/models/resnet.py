"""ResNet backbones in flax — TPU-native (NHWC, bfloat16 compute).

Capability parity with the reference's three backbone files
(`nets/resnet_torch.py` — the one actually used; `nets/resnet50.py`;
`nets/resnet.py` unused CIFAR variant): BasicBlock/Bottleneck residual
stacks with the Faster-R-CNN split of reference `nets/resnet_torch.py:392-409`
—  a stride-16 **trunk** (conv1..layer3) producing the shared feature map,
and a **tail** (layer4 + global average pool) reused as the detection head's
feature extractor on pooled ROI crops (reference `nets/heads.py:51-52`).

TPU-first design choices (not translations):
  * NHWC layout throughout — XLA's native conv layout on TPU; the MXU tiles
    [spatial, C_in] x [C_in, C_out] matmuls directly.
  * bfloat16 activations/conv compute with float32 params and BatchNorm
    statistics — the v5e MXU's native mixed precision.
  * Padding tuples mirror torch's exact arithmetic (7x7/s2/p3 stem,
    3x3/s2/p1 maxpool and downsample convs) so a converted torch checkpoint
    reproduces reference features and shapes (600 -> 38 at stride 16).
  * Parameter tree names mirror the torch module names (conv1, bn1,
    layer1.0.conv2, ...) so the torch->flax weight converter
    (`models/convert.py`) is a pure name mapping.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp

Array = jnp.ndarray


def _norm(
    dtype: Any,
    train: bool,
    name: str,
    axis_name: Any = None,
    kind: str = "batch",
):
    """Normalization layer at the reference's BN sites.

    ``kind='batch'`` (default): BatchNorm matching torch defaults (eps
    1e-5, momentum 0.1 — i.e. running = 0.9 * running + 0.1 * batch).
    Stats/scale kept in float32. ``axis_name`` enables cross-replica
    (sync) BN under the explicit shard_map backend: batch statistics
    pmean over that mesh axis, matching what jit auto-partitioning
    computes on a globally-sharded batch.

    ``kind='group'``: GroupNorm(32) — the BN-free structural lever from
    the MFU attribution (STAGE_BREAKDOWN.md: the measured-vs-ceiling gap
    ranking tracks BatchNorm density; train-mode BN's batch-stats
    reductions are fusion breaks + HBM round-trips XLA cannot elide,
    while GN normalizes within each sample — no mutable state, no
    cross-batch coupling, shard-invariant by construction). Parameter
    names stay at the BN sites' names (scale/bias under e.g. 'bn1') so
    the tree layout is stable; there are no running statistics, so
    torch-pretrained BN checkpoints do NOT convert onto a GN model."""
    if kind == "group":
        return nn.GroupNorm(
            num_groups=32,
            epsilon=1e-5,
            dtype=dtype,
            param_dtype=jnp.float32,
            name=name,
        )
    return nn.BatchNorm(
        use_running_average=not train,
        momentum=0.9,
        epsilon=1e-5,
        dtype=dtype,
        param_dtype=jnp.float32,
        axis_name=axis_name,
        name=name,
    )


class GroupedConv(nn.Module):
    """Grouped KxK conv as patch extraction + per-group batched einsum.

    ResNeXt's grouped 3x3 (reference `nets/resnet_torch.py:10-12,100`,
    torch ``groups=``) cannot use ``feature_group_count`` here: XLA's TPU
    grouped-convolution lowering stalls on this backend for any group count
    > 1. The TPU-native formulation is a grouped GEMM: unroll the KxK taps
    into shifted slices (9 static slices — no gather), then contract each
    group's ``[HW, K*K*I/g] x [K*K*I/g, O/g]`` block as one batched einsum,
    which XLA maps straight onto the MXU. FLOPs are the true grouped count
    (1/g of dense).

    The parameter keeps nn.Conv's grouped-HWIO kernel shape
    ``[K, K, I/g, O]`` (torch layout transposed), so `models/convert.py`
    converts torch grouped weights with the same pure transpose it uses for
    dense convs, and fan-in (K*K*I/g) matches for initialization.
    """

    features: int
    kernel: int
    stride: int
    padding: int
    groups: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x: Array) -> Array:
        g, k, s, p = self.groups, self.kernel, self.stride, self.padding
        in_ch = x.shape[-1]
        assert in_ch % g == 0 and self.features % g == 0
        w = self.param(
            "kernel",
            nn.initializers.lecun_normal(),
            (k, k, in_ch // g, self.features),
            jnp.float32,
        )
        x = x.astype(self.dtype)
        w = w.astype(self.dtype)
        xp = jnp.pad(x, ((0, 0), (p, p), (p, p), (0, 0)))
        out_h = (x.shape[1] + 2 * p - k) // s + 1
        out_w = (x.shape[2] + 2 * p - k) // s + 1
        # taps: [N, out_h, out_w, k*k, in_ch] from k*k static strided slices
        taps = jnp.stack(
            [
                xp[:, dr : dr + (out_h - 1) * s + 1 : s, dc : dc + (out_w - 1) * s + 1 : s, :]
                for dr in range(k)
                for dc in range(k)
            ],
            axis=3,
        )
        taps = taps.reshape(*taps.shape[:4], g, in_ch // g)
        # kernel [k,k,I/g,O] -> [k*k, I/g, g, O/g]; output groups are
        # contiguous blocks of O/g channels (torch grouped-conv semantics)
        wg = w.reshape(k * k, in_ch // g, g, self.features // g)
        y = jnp.einsum("nhwpgi,pigo->nhwgo", taps, wg)
        return y.reshape(y.shape[0], out_h, out_w, self.features)


def _conv(
    features: int,
    kernel: int,
    stride: int,
    padding: int,
    dtype: Any,
    name: str,
    groups: int = 1,
):
    """Bias-free conv with explicit torch-style symmetric padding."""
    if groups > 1:
        return GroupedConv(
            features=features,
            kernel=kernel,
            stride=stride,
            padding=padding,
            groups=groups,
            dtype=dtype,
            name=name,
        )
    return nn.Conv(
        features=features,
        kernel_size=(kernel, kernel),
        strides=(stride, stride),
        padding=((padding, padding), (padding, padding)),
        use_bias=False,
        dtype=dtype,
        param_dtype=jnp.float32,
        name=name,
    )


class BasicBlock(nn.Module):
    """Two 3x3 convs + identity (reference `nets/resnet_torch.py:35-75`)."""

    features: int
    stride: int = 1
    downsample: bool = False
    dtype: Any = jnp.bfloat16
    bn_axis: Any = None
    norm: str = "batch"

    @nn.compact
    def __call__(self, x: Array, train: bool) -> Array:
        identity = x
        out = _conv(self.features, 3, self.stride, 1, self.dtype, "conv1")(x)
        out = _norm(self.dtype, train, "bn1", self.bn_axis, self.norm)(out)
        out = nn.relu(out)
        out = _conv(self.features, 3, 1, 1, self.dtype, "conv2")(out)
        out = _norm(self.dtype, train, "bn2", self.bn_axis, self.norm)(out)
        if self.downsample:
            identity = _conv(self.features, 1, self.stride, 0, self.dtype, "downsample_conv")(x)
            identity = _norm(self.dtype, train, "downsample_bn", self.bn_axis, self.norm)(identity)
        return nn.relu(out + identity)


class Bottleneck(nn.Module):
    """1x1 -> 3x3 -> 1x1(x4) bottleneck (reference `nets/resnet_torch.py:78-123`;
    torchvision-style stride on the 3x3). ``groups``/``base_width`` give the
    ResNeXt / wide-ResNet variants of the reference's constructor table
    (`nets/resnet_torch.py:13-23,299-390`): the inner width is
    ``features * base_width/64 * groups`` and the 3x3 is grouped; the block
    output stays ``features * 4`` for every variant."""

    features: int  # bottleneck planes; output is features * 4
    stride: int = 1
    downsample: bool = False
    dtype: Any = jnp.bfloat16
    groups: int = 1
    base_width: int = 64
    bn_axis: Any = None
    expansion: int = 4
    norm: str = "batch"

    @nn.compact
    def __call__(self, x: Array, train: bool) -> Array:
        identity = x
        width = int(self.features * (self.base_width / 64.0)) * self.groups
        out = _conv(width, 1, 1, 0, self.dtype, "conv1")(x)
        out = _norm(self.dtype, train, "bn1", self.bn_axis, self.norm)(out)
        out = nn.relu(out)
        out = _conv(width, 3, self.stride, 1, self.dtype, "conv2", self.groups)(out)
        out = _norm(self.dtype, train, "bn2", self.bn_axis, self.norm)(out)
        out = nn.relu(out)
        out = _conv(self.features * self.expansion, 1, 1, 0, self.dtype, "conv3")(out)
        out = _norm(self.dtype, train, "bn3", self.bn_axis, self.norm)(out)
        if self.downsample:
            identity = _conv(
                self.features * self.expansion, 1, self.stride, 0, self.dtype, "downsample_conv"
            )(x)
            identity = _norm(self.dtype, train, "downsample_bn", self.bn_axis, self.norm)(identity)
        return nn.relu(out + identity)


# name -> (block class, blocks per stage, groups, width_per_group) — the full
# constructor table of reference `nets/resnet_torch.py:271-390` (resnet152 at
# :313, resnext50_32x4d/resnext101_32x8d at :327-350, wide_resnet50_2/101_2
# at :353-390).
_SPECS = {
    "resnet18": (BasicBlock, (2, 2, 2, 2), 1, 64),
    "resnet34": (BasicBlock, (3, 4, 6, 3), 1, 64),
    "resnet50": (Bottleneck, (3, 4, 6, 3), 1, 64),
    "resnet101": (Bottleneck, (3, 4, 23, 3), 1, 64),
    "resnet152": (Bottleneck, (3, 8, 36, 3), 1, 64),
    "resnext50_32x4d": (Bottleneck, (3, 4, 6, 3), 32, 4),
    "resnext101_32x8d": (Bottleneck, (3, 4, 23, 3), 32, 8),
    "wide_resnet50_2": (Bottleneck, (3, 4, 6, 3), 1, 128),
    "wide_resnet101_2": (Bottleneck, (3, 4, 23, 3), 1, 128),
}
_WIDTHS = (64, 128, 256, 512)


def _stage(
    arch: str,
    x: Array,
    features: int,
    n_blocks: int,
    stride: int,
    dtype: Any,
    train: bool,
    name: str,
    bn_axis: Any = None,
    remat: bool = False,
    norm: str = "batch",
) -> Array:
    block, _, groups, base_width = _spec(arch)
    # per-block jax.checkpoint: the backward pass recomputes each residual
    # block's activations instead of keeping them in HBM — trades ~1/3 more
    # FLOPs for activation memory, buying batch/backbone headroom at 600x600.
    # Parameter trees are unchanged (remat is a lifted transform).
    cls = nn.remat(block, static_argnums=(2,)) if remat else block
    out_ch = features * (4 if block is Bottleneck else 1)
    for i in range(n_blocks):
        s = stride if i == 0 else 1
        down = s != 1 or x.shape[-1] != out_ch
        kw = {"groups": groups, "base_width": base_width} if block is Bottleneck else {}
        x = cls(
            features=features,
            stride=s,
            downsample=down,
            dtype=dtype,
            name=f"{name}.{i}",
            bn_axis=bn_axis,
            norm=norm,
            **kw,
        )(x, train)
    return x


class ResNetTrunk(nn.Module):
    """conv1..layer3: the shared stride-16 feature extractor
    (reference split at `nets/resnet_torch.py:399-401`).

    Input NHWC [N, H, W, 3]; output [N, ceil(H/16), ceil(W/16), C] with
    C = 256 (resnet18/34) or 1024 (resnet50/101).

    ``stem='cifar'`` swaps the 7x7/s2 + maxpool ImageNet stem for a 3x3/s1
    conv — the reference's hand-written CIFAR variant (`nets/resnet.py:
    109-114`), used for small-image backbone pretraining; output stride is
    then 4 instead of 16.
    """

    arch: str = "resnet18"
    dtype: Any = jnp.bfloat16
    stem: str = "imagenet"  # "imagenet" | "cifar"
    bn_axis: Any = None  # mesh axis for sync-BN under shard_map
    remat: bool = False  # jax.checkpoint each residual block
    # run every BN with its stored statistics even in train mode (no
    # batch-stats reductions: each BN becomes a fusable affine).
    # DELIBERATE deviation from torchvision's FrozenBatchNorm2d: the
    # affine scale/bias stay TRAINABLE here (torchvision freezes them as
    # buffers); this is the affine-fine-tuning variant, chosen so the
    # optimizer/param tree is identical with the flag on or off.
    frozen_bn: bool = False
    norm: str = "batch"  # "batch" | "group" — see _norm

    @nn.compact
    def __call__(self, x: Array, train: bool = False) -> Array:
        depths = _spec(self.arch)[1]
        train = train and not self.frozen_bn  # `train` only gates BN here
        x = x.astype(self.dtype)
        if self.stem == "cifar":
            x = _conv(64, 3, 1, 1, self.dtype, "conv1")(x)
            x = _norm(self.dtype, train, "bn1", self.bn_axis, self.norm)(x)
            x = nn.relu(x)
        else:
            x = _conv(64, 7, 2, 3, self.dtype, "conv1")(x)
            x = _norm(self.dtype, train, "bn1", self.bn_axis, self.norm)(x)
            x = nn.relu(x)
            x = nn.max_pool(
                x, window_shape=(3, 3), strides=(2, 2), padding=((1, 1), (1, 1))
            )
        ax, rm, nm = self.bn_axis, self.remat, self.norm
        x = _stage(self.arch, x, _WIDTHS[0], depths[0], 1, self.dtype, train, "layer1", ax, rm, nm)
        x = _stage(self.arch, x, _WIDTHS[1], depths[1], 2, self.dtype, train, "layer2", ax, rm, nm)
        x = _stage(self.arch, x, _WIDTHS[2], depths[2], 2, self.dtype, train, "layer3", ax, rm, nm)
        return x


class ResNetTail(nn.Module):
    """layer4 + global average pool: the reference's `classifier`
    (`nets/resnet_torch.py:403`), applied to pooled ROI crops by the
    detection head (`nets/heads.py:51-52`).

    Input NHWC [R, h, w, C_trunk]; output [R, C_out] with C_out = 512
    (resnet18/34) or 2048 (resnet50/101).
    """

    arch: str = "resnet18"
    dtype: Any = jnp.bfloat16
    bn_axis: Any = None
    frozen_bn: bool = False  # see ResNetTrunk.frozen_bn
    norm: str = "batch"  # see ResNetTrunk.norm

    @nn.compact
    def __call__(self, x: Array, train: bool = False) -> Array:
        depths = _spec(self.arch)[1]
        train = train and not self.frozen_bn  # `train` only gates BN here
        x = x.astype(self.dtype)
        x = _stage(
            self.arch, x, _WIDTHS[3], depths[3], 2, self.dtype, train, "layer4",
            self.bn_axis, norm=self.norm,
        )
        return jnp.mean(x, axis=(1, 2))  # global avg pool == AdaptiveAvgPool2d(1)


class ResNetClassifier(nn.Module):
    """Full classifier (trunk + tail + fc) — capability parity with the
    reference's standalone ResNets: the torchvision-style ImageNet model
    (`nets/resnet_torch.py:126-258`) with the default stem, and the
    hand-written CIFAR variant the author pretrained to ~0.93 on CIFAR10
    (`nets/resnet.py`, `readme.md:15`) with ``stem='cifar'``. Used for
    backbone pretraining/verification rather than detection; the
    trunk/tail split matches the detector's, so pretrained weights carry
    over directly."""

    arch: str = "resnet18"
    num_classes: int = 1000
    dtype: Any = jnp.bfloat16
    stem: str = "imagenet"
    norm: str = "batch"  # see ResNetTrunk.norm — "group" pretrains the
    # GN backbone whose checkpoint grafts onto a norm="group" detector

    @nn.compact
    def __call__(self, x: Array, train: bool = False) -> Array:
        x = ResNetTrunk(
            self.arch, self.dtype, self.stem, norm=self.norm, name="trunk"
        )(x, train)
        x = ResNetTail(self.arch, self.dtype, norm=self.norm, name="tail")(x, train)
        return nn.Dense(self.num_classes, param_dtype=jnp.float32, name="fc")(
            x.astype(jnp.float32)
        )


def _spec(arch: str):
    try:
        return _SPECS[arch]
    except KeyError:
        raise ValueError(f"unknown resnet arch {arch!r}; choices: {sorted(_SPECS)}") from None


def trunk_channels(arch: str) -> int:
    return 256 * (4 if _spec(arch)[0] is Bottleneck else 1)


def tail_channels(arch: str) -> int:
    return 512 * (4 if _spec(arch)[0] is Bottleneck else 1)
