"""ResNet backbones in flax — TPU-native (NHWC, bfloat16 compute).

Capability parity with the reference's three backbone files
(`nets/resnet_torch.py` — the one actually used; `nets/resnet50.py`;
`nets/resnet.py` unused CIFAR variant): BasicBlock/Bottleneck residual
stacks with the Faster-R-CNN split of reference `nets/resnet_torch.py:392-409`
—  a stride-16 **trunk** (conv1..layer3) producing the shared feature map,
and a **tail** (layer4 + global average pool) reused as the detection head's
feature extractor on pooled ROI crops (reference `nets/heads.py:51-52`).

TPU-first design choices (not translations):
  * NHWC layout throughout — XLA's native conv layout on TPU; the MXU tiles
    [spatial, C_in] x [C_in, C_out] matmuls directly.
  * bfloat16 activations/conv compute with float32 params and BatchNorm
    statistics — the v5e MXU's native mixed precision.
  * Padding tuples mirror torch's exact arithmetic (7x7/s2/p3 stem,
    3x3/s2/p1 maxpool and downsample convs) so a converted torch checkpoint
    reproduces reference features and shapes (600 -> 38 at stride 16).
  * Parameter tree names mirror the torch module names (conv1, bn1,
    layer1.0.conv2, ...) so the torch->flax weight converter
    (`models/convert.py`) is a pure name mapping.
"""

from __future__ import annotations

from typing import Any, Callable, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

Array = jnp.ndarray


def _norm(dtype: Any, train: bool, name: str) -> nn.BatchNorm:
    """BatchNorm matching torch defaults (eps 1e-5, momentum 0.1 — i.e.
    running = 0.9 * running + 0.1 * batch). Stats/scale kept in float32."""
    return nn.BatchNorm(
        use_running_average=not train,
        momentum=0.9,
        epsilon=1e-5,
        dtype=dtype,
        param_dtype=jnp.float32,
        name=name,
    )


def _conv(
    features: int,
    kernel: int,
    stride: int,
    padding: int,
    dtype: Any,
    name: str,
) -> nn.Conv:
    """Bias-free conv with explicit torch-style symmetric padding."""
    return nn.Conv(
        features=features,
        kernel_size=(kernel, kernel),
        strides=(stride, stride),
        padding=((padding, padding), (padding, padding)),
        use_bias=False,
        dtype=dtype,
        param_dtype=jnp.float32,
        name=name,
    )


class BasicBlock(nn.Module):
    """Two 3x3 convs + identity (reference `nets/resnet_torch.py:35-75`)."""

    features: int
    stride: int = 1
    downsample: bool = False
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x: Array, train: bool) -> Array:
        identity = x
        out = _conv(self.features, 3, self.stride, 1, self.dtype, "conv1")(x)
        out = _norm(self.dtype, train, "bn1")(out)
        out = nn.relu(out)
        out = _conv(self.features, 3, 1, 1, self.dtype, "conv2")(out)
        out = _norm(self.dtype, train, "bn2")(out)
        if self.downsample:
            identity = _conv(self.features, 1, self.stride, 0, self.dtype, "downsample_conv")(x)
            identity = _norm(self.dtype, train, "downsample_bn")(identity)
        return nn.relu(out + identity)


class Bottleneck(nn.Module):
    """1x1 -> 3x3 -> 1x1(x4) bottleneck (reference `nets/resnet_torch.py:78-123`;
    torchvision-style stride on the 3x3)."""

    features: int  # bottleneck width; output is features * 4
    stride: int = 1
    downsample: bool = False
    dtype: Any = jnp.bfloat16
    expansion: int = 4

    @nn.compact
    def __call__(self, x: Array, train: bool) -> Array:
        identity = x
        out = _conv(self.features, 1, 1, 0, self.dtype, "conv1")(x)
        out = _norm(self.dtype, train, "bn1")(out)
        out = nn.relu(out)
        out = _conv(self.features, 3, self.stride, 1, self.dtype, "conv2")(out)
        out = _norm(self.dtype, train, "bn2")(out)
        out = nn.relu(out)
        out = _conv(self.features * self.expansion, 1, 1, 0, self.dtype, "conv3")(out)
        out = _norm(self.dtype, train, "bn3")(out)
        if self.downsample:
            identity = _conv(
                self.features * self.expansion, 1, self.stride, 0, self.dtype, "downsample_conv"
            )(x)
            identity = _norm(self.dtype, train, "downsample_bn")(identity)
        return nn.relu(out + identity)


# name -> (block class, blocks per stage, stage base widths)
_SPECS = {
    "resnet18": (BasicBlock, (2, 2, 2, 2)),
    "resnet34": (BasicBlock, (3, 4, 6, 3)),
    "resnet50": (Bottleneck, (3, 4, 6, 3)),
    "resnet101": (Bottleneck, (3, 4, 23, 3)),
}
_WIDTHS = (64, 128, 256, 512)


def _stage(
    block: Callable[..., nn.Module],
    x: Array,
    features: int,
    n_blocks: int,
    stride: int,
    dtype: Any,
    train: bool,
    name: str,
) -> Array:
    expansion = getattr(block, "expansion", 1) if block is Bottleneck else 1
    for i in range(n_blocks):
        s = stride if i == 0 else 1
        in_ch = x.shape[-1]
        out_ch = features * (4 if block is Bottleneck else 1)
        down = s != 1 or in_ch != out_ch
        x = block(
            features=features,
            stride=s,
            downsample=down,
            dtype=dtype,
            name=f"{name}.{i}",
        )(x, train)
    del expansion
    return x


class ResNetTrunk(nn.Module):
    """conv1..layer3: the shared stride-16 feature extractor
    (reference split at `nets/resnet_torch.py:399-401`).

    Input NHWC [N, H, W, 3]; output [N, ceil(H/16), ceil(W/16), C] with
    C = 256 (resnet18/34) or 1024 (resnet50/101).

    ``stem='cifar'`` swaps the 7x7/s2 + maxpool ImageNet stem for a 3x3/s1
    conv — the reference's hand-written CIFAR variant (`nets/resnet.py:
    109-114`), used for small-image backbone pretraining; output stride is
    then 4 instead of 16.
    """

    arch: str = "resnet18"
    dtype: Any = jnp.bfloat16
    stem: str = "imagenet"  # "imagenet" | "cifar"

    @nn.compact
    def __call__(self, x: Array, train: bool = False) -> Array:
        block, depths = _SPECS[self.arch]
        x = x.astype(self.dtype)
        if self.stem == "cifar":
            x = _conv(64, 3, 1, 1, self.dtype, "conv1")(x)
            x = _norm(self.dtype, train, "bn1")(x)
            x = nn.relu(x)
        else:
            x = _conv(64, 7, 2, 3, self.dtype, "conv1")(x)
            x = _norm(self.dtype, train, "bn1")(x)
            x = nn.relu(x)
            x = nn.max_pool(
                x, window_shape=(3, 3), strides=(2, 2), padding=((1, 1), (1, 1))
            )
        x = _stage(block, x, _WIDTHS[0], depths[0], 1, self.dtype, train, "layer1")
        x = _stage(block, x, _WIDTHS[1], depths[1], 2, self.dtype, train, "layer2")
        x = _stage(block, x, _WIDTHS[2], depths[2], 2, self.dtype, train, "layer3")
        return x


class ResNetTail(nn.Module):
    """layer4 + global average pool: the reference's `classifier`
    (`nets/resnet_torch.py:403`), applied to pooled ROI crops by the
    detection head (`nets/heads.py:51-52`).

    Input NHWC [R, h, w, C_trunk]; output [R, C_out] with C_out = 512
    (resnet18/34) or 2048 (resnet50/101).
    """

    arch: str = "resnet18"
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x: Array, train: bool = False) -> Array:
        block, depths = _SPECS[self.arch]
        x = x.astype(self.dtype)
        x = _stage(block, x, _WIDTHS[3], depths[3], 2, self.dtype, train, "layer4")
        return jnp.mean(x, axis=(1, 2))  # global avg pool == AdaptiveAvgPool2d(1)


class ResNetClassifier(nn.Module):
    """Full classifier (trunk + tail + fc) — capability parity with the
    reference's standalone ResNets: the torchvision-style ImageNet model
    (`nets/resnet_torch.py:126-258`) with the default stem, and the
    hand-written CIFAR variant the author pretrained to ~0.93 on CIFAR10
    (`nets/resnet.py`, `readme.md:15`) with ``stem='cifar'``. Used for
    backbone pretraining/verification rather than detection; the
    trunk/tail split matches the detector's, so pretrained weights carry
    over directly."""

    arch: str = "resnet18"
    num_classes: int = 1000
    dtype: Any = jnp.bfloat16
    stem: str = "imagenet"

    @nn.compact
    def __call__(self, x: Array, train: bool = False) -> Array:
        x = ResNetTrunk(self.arch, self.dtype, self.stem, name="trunk")(x, train)
        x = ResNetTail(self.arch, self.dtype, name="tail")(x, train)
        return nn.Dense(self.num_classes, param_dtype=jnp.float32, name="fc")(
            x.astype(jnp.float32)
        )


def trunk_channels(arch: str) -> int:
    block, _ = _SPECS[arch]
    return 256 * (4 if block is Bottleneck else 1)


def tail_channels(arch: str) -> int:
    block, _ = _SPECS[arch]
    return 512 * (4 if block is Bottleneck else 1)
