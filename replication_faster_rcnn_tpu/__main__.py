"""``python -m replication_faster_rcnn_tpu`` — same entry as ``frcnn``.

The elastic fleet supervisor (``frcnn train --elastic``) respawns its
per-generation training child through this module path, so children
work in environments where the console script is not on PATH (test
venvs, bare checkouts).
"""

import sys

from replication_faster_rcnn_tpu.cli import main

if __name__ == "__main__":
    sys.exit(main())
