"""SLO-driven micro-batch deadlines — adapt per-bucket ``max_delay_ms``
from observed queue waits (ROADMAP item 5(b)).

The static ``serving.max_delay_ms`` knob is one global answer to a
per-bucket question: how long may a request sit in the micro-batch queue
before we flush a partial batch?  Under load a bucket fills its batch
before the deadline and the knob is irrelevant; idle buckets pay the
full deadline on every request.  The :class:`DeadlineController` closes
the loop: it watches the per-flush queue-wait samples the MicroBatcher
already emits (``on_flush_stats``) and nudges each bucket's deadline
with ONE bounded multiplicative step per adaptation window —

- wait p99 above ``SHRINK_AT`` x ``adaptive_slo_ms``: divide the
  deadline by ``adaptive_delay_step`` (stop holding requests we are
  about to miss the SLO on);
- wait p99 below ``GROW_BELOW`` x the SLO *and* flushes are going out
  partially filled: multiply by the step (there is SLO headroom to
  amortize dispatches better);
- always clamped to ``[delay_floor_ms, delay_ceiling_ms]``.

Multiplicative-with-clamp makes the controller self-limiting: it cannot
run away, and a misbehaving p99 estimate costs at most one step per
window.  The controller is pure bookkeeping — no thread of its own; the
MicroBatcher worker drives it via the ``on_flush_stats`` hook and reads
the result back through the ``key -> seconds`` callable seam
(``delay_s``), so adaptation is as deterministic as the flush sequence.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

__all__ = ["DeadlineController"]

# Hysteresis band around the SLO target, in fractions of adaptive_slo_ms.
# Shrink when the observed wait p99 crosses 0.8x the SLO (we are close to
# missing it); grow only when p99 is under 0.4x (comfortable headroom).
# The dead zone between them keeps the deadline stable under steady load.
SHRINK_AT = 0.8
GROW_BELOW = 0.4


def _p99(samples: List[float]) -> float:
    """p99 by nearest-rank on a sorted copy (small fixed windows — exact
    beats clever here)."""
    s = sorted(samples)
    idx = min(len(s) - 1, int(0.99 * (len(s) - 1) + 0.5))
    return s[idx]


class DeadlineController:
    """Per-key adaptive flush deadline with bounded multiplicative steps.

    ``delay_s`` is the callable handed to :class:`MicroBatcher` as
    ``max_delay_s``; ``on_flush`` is wired to ``on_flush_stats``.  Both
    run on the batcher worker thread; ``delays_ms`` snapshots from HTTP
    handler threads, hence the lock.  ``max_batch`` (optional,
    ``key -> int``) lets the grow rule require partially-filled flushes:
    if every flush already fills the batch, a longer deadline buys
    nothing and only adds latency.
    """

    def __init__(
        self,
        slo_ms: float,
        floor_ms: float,
        ceiling_ms: float,
        step: float,
        initial_ms: float,
        max_batch: Optional[Callable[[Any], int]] = None,
        window: int = 16,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        if not 0 < floor_ms <= ceiling_ms:
            raise ValueError(
                f"need 0 < floor_ms <= ceiling_ms, got {floor_ms}/{ceiling_ms}"
            )
        if step <= 1.0:
            raise ValueError(f"step must be > 1.0, got {step}")
        if slo_ms <= 0:
            raise ValueError(f"slo_ms must be > 0, got {slo_ms}")
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        self._slo_ms = slo_ms
        self._floor_ms = floor_ms
        self._ceiling_ms = ceiling_ms
        self._step = step
        self._initial_ms = min(max(initial_ms, floor_ms), ceiling_ms)
        self._max_batch = max_batch
        self._window = window
        self._clock = clock  # reserved seam: time-based cadence in tests
        self._lock = threading.Lock()
        self._delay_ms: Dict[Any, float] = {}
        # per-key accumulation since the last adaptation:
        # (wait samples, n_flushes, n_partial_flushes)
        self._acc: Dict[Any, Tuple[List[float], int, int]] = {}
        self._adaptations = 0  # total steps taken (introspection/tests)

    @classmethod
    def from_config(cls, serving, max_batch=None, **kw) -> "DeadlineController":
        """Build from a ``ServingConfig`` (`adaptive_*`/`delay_*` knobs)."""
        return cls(
            slo_ms=serving.adaptive_slo_ms,
            floor_ms=serving.delay_floor_ms,
            ceiling_ms=serving.delay_ceiling_ms,
            step=serving.adaptive_delay_step,
            initial_ms=serving.max_delay_ms,
            max_batch=max_batch,
            **kw,
        )

    # ---------------------------------------------------------------- reads

    def delay_s(self, key: Any) -> float:
        """Current flush deadline for ``key``, in seconds (the MicroBatcher
        ``max_delay_s`` callable)."""
        with self._lock:
            return self._delay_ms.get(key, self._initial_ms) / 1000.0

    def delays_ms(self) -> Dict[str, float]:
        """``str(key) -> current delay_ms`` for every adapted key (the
        /stats gauge; keys still at the initial value are omitted)."""
        with self._lock:
            return {str(k): v for k, v in self._delay_ms.items()}

    @property
    def adaptations(self) -> int:
        with self._lock:
            return self._adaptations

    # ---------------------------------------------------------------- hook

    def on_flush(self, key: Any, waits_s: List[float]) -> None:
        """Record one flush's queue waits; adapt once per ``window``
        accumulated samples.  Wired to ``MicroBatcher(on_flush_stats=...)``
        (worker thread; must stay cheap and non-raising)."""
        if not waits_s:
            return
        partial = 0
        if self._max_batch is not None:
            partial = int(len(waits_s) < self._max_batch(key))
        with self._lock:
            samples, flushes, partials = self._acc.get(key, ([], 0, 0))
            samples = samples + [w * 1000.0 for w in waits_s]
            flushes += 1
            partials += partial
            if len(samples) < self._window:
                self._acc[key] = (samples, flushes, partials)
                return
            # adaptation point: one bounded multiplicative step
            self._acc.pop(key, None)  # absent when one flush fills the window
            cur = self._delay_ms.get(key, self._initial_ms)
            p99_ms = _p99(samples)
            new = cur
            if p99_ms > SHRINK_AT * self._slo_ms:
                new = cur / self._step
            elif p99_ms < GROW_BELOW * self._slo_ms and (
                self._max_batch is None or partials > 0
            ):
                new = cur * self._step
            new = min(max(new, self._floor_ms), self._ceiling_ms)
            if new != cur or key not in self._delay_ms:
                self._delay_ms[key] = new
            if new != cur:
                self._adaptations += 1
