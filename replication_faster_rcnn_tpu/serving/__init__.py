"""Bucketed AOT inference serving.

`engine.InferenceEngine` compiles one inference program per
(resolution bucket × batch size) ahead of time, keeps the inference
params device-resident, and coalesces concurrent requests into
bucket-sized micro-batches through `batcher.MicroBatcher` — the
Fast R-CNN amortization argument applied to the serving tier: one
dispatch's fixed cost (Python dispatch, program launch, transfers)
shared across every request in the flush.
"""

from replication_faster_rcnn_tpu.serving.batcher import (
    DeadlineExceeded,
    MicroBatcher,
)
from replication_faster_rcnn_tpu.serving.engine import (
    InferenceEngine,
    OversizedImageError,
    get_engine,
    select_bucket,
)

__all__ = [
    "DeadlineExceeded",
    "InferenceEngine",
    "MicroBatcher",
    "OversizedImageError",
    "get_engine",
    "select_bucket",
]
