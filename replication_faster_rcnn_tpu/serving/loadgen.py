"""In-process load generator for the serving engine.

Two canonical load shapes (the serving-benchmark literature's pair):

* **closed loop** — submit every request as fast as the engine's bounded
  queue accepts them; measures capacity (max throughput) and the latency
  distribution under saturation. With a deadline-triggered micro-batcher
  this is the regime where flushes run at full bucket batch size.
* **open loop** — submit at a fixed offered rate regardless of
  completions (sleep-paced); measures the latency a user sees at a given
  traffic level, including queueing. Offered > capacity shows up as
  latency blowing past ``max_delay_ms`` — the signature of an overloaded
  tier, which a closed loop structurally cannot show.

Latency is measured per request from submit to future resolution
(``Future.add_done_callback`` stamps completion on the worker thread),
so it includes queue wait + batching delay + dispatch + de-normalization
— the full engine-side request path.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

__all__ = ["percentile_ms", "run_closed_loop", "run_open_loop"]


def percentile_ms(latencies_s: Sequence[float], q: float) -> float:
    """q-th percentile (0..100) of a latency list, in milliseconds."""
    if not latencies_s:
        return 0.0
    return float(np.percentile(np.asarray(latencies_s, np.float64), q) * 1e3)


def _summarize(
    latencies_s: List[float], wall_s: float, n: int, **extra: Any
) -> Dict[str, Any]:
    return {
        "n_requests": n,
        "wall_s": round(wall_s, 4),
        "images_per_sec": round(n / wall_s, 3) if wall_s > 0 else 0.0,
        "p50_ms": round(percentile_ms(latencies_s, 50), 3),
        "p99_ms": round(percentile_ms(latencies_s, 99), 3),
        "mean_ms": round(float(np.mean(latencies_s)) * 1e3, 3)
        if latencies_s
        else 0.0,
        **extra,
    }


def _submit_timed(engine, image, latencies: List[float], lock: threading.Lock):
    t0 = time.monotonic()

    def _done(_fut) -> None:
        dt = time.monotonic() - t0
        with lock:
            latencies.append(dt)

    fut = engine.submit(image)
    fut.add_done_callback(_done)
    return fut


def run_closed_loop(
    engine, images: Sequence[np.ndarray], n_requests: int
) -> Dict[str, Any]:
    """Saturation: fire ``n_requests`` submits back-to-back (the bounded
    queue throttles the producer) and wait for all results."""
    latencies: List[float] = []
    lock = threading.Lock()
    t0 = time.monotonic()
    futures = [
        _submit_timed(engine, images[i % len(images)], latencies, lock)
        for i in range(n_requests)
    ]
    for f in futures:
        f.result()
    wall = time.monotonic() - t0
    return _summarize(latencies, wall, n_requests, mode="closed")


def run_open_loop(
    engine,
    images: Sequence[np.ndarray],
    offered_rate: float,
    n_requests: Optional[int] = None,
    duration_s: Optional[float] = None,
) -> Dict[str, Any]:
    """Fixed offered load: one submit every ``1/offered_rate`` seconds
    (absolute schedule, so a slow submit doesn't silently lower the
    offered rate), for ``n_requests`` or ``duration_s``."""
    if offered_rate <= 0:
        raise ValueError(f"offered_rate must be > 0, got {offered_rate}")
    if n_requests is None:
        if duration_s is None:
            raise ValueError("need n_requests or duration_s")
        n_requests = max(1, int(offered_rate * duration_s))
    latencies: List[float] = []
    lock = threading.Lock()
    interval = 1.0 / offered_rate
    t0 = time.monotonic()
    futures = []
    for i in range(n_requests):
        target = t0 + i * interval
        delay = target - time.monotonic()
        if delay > 0:
            time.sleep(delay)
        futures.append(
            _submit_timed(engine, images[i % len(images)], latencies, lock)
        )
    for f in futures:
        f.result()
    wall = time.monotonic() - t0
    return _summarize(
        latencies, wall, n_requests, mode="open", offered_rate=offered_rate
    )
