"""In-process load generator for the serving engine.

Two canonical load shapes (the serving-benchmark literature's pair):

* **closed loop** — submit every request as fast as the engine's bounded
  queue accepts them; measures capacity (max throughput) and the latency
  distribution under saturation. With a deadline-triggered micro-batcher
  this is the regime where flushes run at full bucket batch size.
* **open loop** — submit at a fixed offered rate regardless of
  completions (sleep-paced); measures the latency a user sees at a given
  traffic level, including queueing. Offered > capacity shows up as
  latency blowing past ``max_delay_ms`` — the signature of an overloaded
  tier, which a closed loop structurally cannot show.

Latency is measured per request from submit to future resolution
(``Future.add_done_callback`` stamps completion on the worker thread),
so it includes queue wait + batching delay + dispatch + de-normalization
— the full engine-side request path.

Client-side hardening (so a wedged or overloaded server costs the
benchmark a bounded wait, never a hang):

* every result wait carries a deadline (``timeout_s``, default 120 s);
  expired waits are counted and reported as ``timeout_fraction``;
* with ``admission=True`` submits are non-blocking with seeded jittered
  exponential backoff (serving/overload.py); requests still shed after
  the retry budget are counted as ``shed`` instead of blocking forever.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import TimeoutError as FutureTimeoutError
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from replication_faster_rcnn_tpu.serving.overload import (
    DeadlineExceeded,
    backoff_delays,
)
from replication_faster_rcnn_tpu.telemetry import tracecontext

__all__ = [
    "percentile_ms",
    "run_closed_loop",
    "run_fleet_loop",
    "run_open_loop",
]

# generous per-request result deadline: far above any sane serving
# latency, small enough that a wedged engine fails the run in minutes
DEFAULT_TIMEOUT_S = 120.0


def percentile_ms(latencies_s: Sequence[float], q: float) -> float:
    """q-th percentile (0..100) of a latency list, in milliseconds."""
    if not latencies_s:
        return 0.0
    return float(np.percentile(np.asarray(latencies_s, np.float64), q) * 1e3)


def _summarize(
    latencies_s: List[float], wall_s: float, n: int, **extra: Any
) -> Dict[str, Any]:
    return {
        "n_requests": n,
        "wall_s": round(wall_s, 4),
        "images_per_sec": round(n / wall_s, 3) if wall_s > 0 else 0.0,
        "p50_ms": round(percentile_ms(latencies_s, 50), 3),
        "p99_ms": round(percentile_ms(latencies_s, 99), 3),
        "mean_ms": round(float(np.mean(latencies_s)) * 1e3, 3)
        if latencies_s
        else 0.0,
        **extra,
    }


class _Counters:
    """Shed/retry/timeout/error tallies shared with done-callbacks."""

    def __init__(self) -> None:
        self.lock = threading.Lock()
        self.latencies: List[float] = []
        self.shed = 0
        self.retries = 0
        self.timeouts = 0
        self.errors = 0


def _submit_timed(engine, image, counters: _Counters):
    t0 = time.monotonic()

    def _done(fut) -> None:
        dt = time.monotonic() - t0
        with counters.lock:
            if fut.exception() is None:
                counters.latencies.append(dt)

    fut = engine.submit(image)
    fut.add_done_callback(_done)
    return fut


def _submit_admission(engine, image, counters: _Counters, seed: int):
    """Non-blocking submit with jittered-backoff retries; returns the
    Future or None once the retry budget sheds the request."""
    import queue

    attempt = 0
    while True:
        try:
            t0 = time.monotonic()
            fut = engine.submit(image, timeout=0)
        except queue.Full:
            delays = list(backoff_delays(seed=seed))
            if attempt >= len(delays):
                with counters.lock:
                    counters.shed += 1
                return None
            with counters.lock:
                counters.retries += 1
            time.sleep(delays[attempt])
            attempt += 1
            continue

        def _done(f, t0=t0) -> None:
            dt = time.monotonic() - t0
            with counters.lock:
                if f.exception() is None:
                    counters.latencies.append(dt)

        fut.add_done_callback(_done)
        return fut


def _await_all(
    futures: Sequence, timeout_s: Optional[float], counters: _Counters
) -> None:
    """Wait for every future, bounding each wait by ``timeout_s``;
    timeouts and per-request errors are counted, not raised — the
    summary is the report."""
    for f in futures:
        if f is None:
            continue
        try:
            f.result(timeout=timeout_s)
        except (FutureTimeoutError, TimeoutError, DeadlineExceeded):
            with counters.lock:
                counters.timeouts += 1
        except Exception:  # noqa: BLE001 - tallied in the summary
            with counters.lock:
                counters.errors += 1


def _extra(counters: _Counters, n: int) -> Dict[str, Any]:
    with counters.lock:
        return {
            "timeouts": counters.timeouts,
            "timeout_fraction": round(counters.timeouts / n, 4) if n else 0.0,
            "shed": counters.shed,
            "submit_retries": counters.retries,
            "errors": counters.errors,
        }


def run_closed_loop(
    engine,
    images: Sequence[np.ndarray],
    n_requests: int,
    timeout_s: Optional[float] = DEFAULT_TIMEOUT_S,
    admission: bool = False,
    seed: int = 0,
) -> Dict[str, Any]:
    """Saturation: fire ``n_requests`` submits back-to-back (the bounded
    queue throttles the producer — or sheds, with ``admission=True``)
    and wait for all results under the per-request deadline."""
    counters = _Counters()
    t0 = time.monotonic()
    futures = []
    for i in range(n_requests):
        image = images[i % len(images)]
        if admission:
            futures.append(_submit_admission(engine, image, counters, seed + i))
        else:
            futures.append(_submit_timed(engine, image, counters))
    _await_all(futures, timeout_s, counters)
    wall = time.monotonic() - t0
    return _summarize(
        counters.latencies, wall, n_requests, mode="closed",
        **_extra(counters, n_requests),
    )


def run_fleet_loop(
    dispatch,
    requests: Sequence,
    concurrency: int = 4,
    timeout_s: float = DEFAULT_TIMEOUT_S,
) -> Dict[str, Any]:
    """Closed-loop load over a fleet router: ``concurrency`` client
    threads each walk their static share of ``requests`` (worker ``k``
    takes indices ``k, k+K, ...`` — deterministic partition, no shared
    iterator) calling ``dispatch(payload, content_hash)`` synchronously.

    The headline number is **availability** — the fraction of requests
    that returned a result, which is what the fleet's failover/hedging
    machinery is supposed to hold through a replica kill; throughput and
    latency percentiles ride along.  ``timeout_s`` bounds each worker
    join, so a wedged fleet costs the run a bounded wait (workers still
    stuck at the deadline are counted as hung and their remaining
    requests as failures).

    Each request runs under its own root trace context (the way a real
    client front door would mint one), so with a tracer installed the
    router's attempt spans group per request in the merged timeline;
    the first few failed requests' trace ids come back under
    ``failed_trace_ids`` — paste one into
    ``frcnn telemetry --trace-id`` to see where the request died.
    """
    if concurrency < 1:
        raise ValueError(f"concurrency must be >= 1, got {concurrency}")
    counters = _Counters()
    n = len(requests)
    failed_traces: List[str] = []

    def _worker(start: int) -> None:
        for i in range(start, n, concurrency):
            payload, content_hash = requests[i]
            trace = tracecontext.new_trace_context()
            t0 = time.monotonic()
            try:
                with tracecontext.bind(trace):
                    dispatch(payload, content_hash)
            except Exception:  # noqa: BLE001 - tallied as unavailability
                with counters.lock:
                    counters.errors += 1
                    if len(failed_traces) < 16:
                        failed_traces.append(trace.trace_id)
                continue
            dt = time.monotonic() - t0
            with counters.lock:
                counters.latencies.append(dt)

    threads = [
        threading.Thread(
            target=_worker, args=(k,), name=f"fleet-loadgen-{k}"
        )
        for k in range(concurrency)
    ]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    hung = 0
    for t in threads:
        t.join(timeout=timeout_s)
        if t.is_alive():
            hung += 1
    wall = time.monotonic() - t0
    with counters.lock:
        ok = len(counters.latencies)
        errors = counters.errors
    summary = _summarize(
        list(counters.latencies), wall, n, mode="fleet",
        concurrency=concurrency, errors=errors, hung_workers=hung,
    )
    summary["ok"] = ok
    summary["availability"] = round(ok / n, 6) if n else 0.0
    with counters.lock:
        summary["failed_trace_ids"] = list(failed_traces)
    return summary


def run_open_loop(
    engine,
    images: Sequence[np.ndarray],
    offered_rate: float,
    n_requests: Optional[int] = None,
    duration_s: Optional[float] = None,
    timeout_s: Optional[float] = DEFAULT_TIMEOUT_S,
    admission: bool = False,
    seed: int = 0,
) -> Dict[str, Any]:
    """Fixed offered load: one submit every ``1/offered_rate`` seconds
    (absolute schedule, so a slow submit doesn't silently lower the
    offered rate), for ``n_requests`` or ``duration_s``."""
    if offered_rate <= 0:
        raise ValueError(f"offered_rate must be > 0, got {offered_rate}")
    if n_requests is None:
        if duration_s is None:
            raise ValueError("need n_requests or duration_s")
        n_requests = max(1, int(offered_rate * duration_s))
    counters = _Counters()
    interval = 1.0 / offered_rate
    t0 = time.monotonic()
    futures = []
    for i in range(n_requests):
        target = t0 + i * interval
        delay = target - time.monotonic()
        if delay > 0:
            time.sleep(delay)
        image = images[i % len(images)]
        if admission:
            futures.append(_submit_admission(engine, image, counters, seed + i))
        else:
            futures.append(_submit_timed(engine, image, counters))
    _await_all(futures, timeout_s, counters)
    wall = time.monotonic() - t0
    return _summarize(
        counters.latencies, wall, n_requests, mode="open",
        offered_rate=offered_rate, **_extra(counters, n_requests),
    )
