"""The fleet dispatcher: consistent hashing, result cache, breakers,
failover, hedging, canary and shadow traffic.

One request's path through :meth:`FleetRouter.dispatch`:

1. **Cache** — the content hash answers exact-duplicate images from the
   router's LRU without touching a replica.
2. **Placement** — the request's ring key ``content_hash:bucket`` walks
   the consistent-hash ring (``fleet.vnodes`` points per replica) over
   the replicas currently in rotation; the ordered walk IS the failover
   order, so retries of the same image hit the same replicas in the
   same order while membership is stable, and membership changes move
   only ~1/N of the keyspace.  A deterministic ``canary_fraction``
   slice of the hash space tries the canary replica first.
3. **Dispatch** — attempts run against the walk order, skipping
   replicas whose circuit breaker refuses.  Every attempt consults the
   ``router.dispatch`` failpoint: an injected ``drop`` invokes the
   router's kill hook (the chaos/benchmark seam that makes the selected
   replica actually die) and then fails the attempt as a dropped
   connection — which the machinery below must absorb.
4. **Failover** — a failed attempt records into that replica's breaker
   and re-dispatches to the next replica in the walk, up to
   ``fleet.max_attempts``.
5. **Hedging** — with ``fleet.hedge``, if the primary attempt has not
   resolved after ``hedge_multiplier x observed p99`` (clamped to the
   configured floor/ceiling), a second copy goes to the next replica
   and the first result wins — tail tolerance against a slow-but-alive
   replica, which failover alone cannot see.
6. **Shadow** — successful responses are mirrored to shadow replicas
   and diffed (counters only; the client's response is already gone).

Hedging needs real concurrency, so it runs attempts on a thread pool;
with ``hedge=False`` (or no pool) dispatch is strictly sequential and
single-threaded — the mode the chaos leg replays deterministically.
"""

from __future__ import annotations

import hashlib
import json
import threading
import time
from bisect import bisect_right
from collections import OrderedDict, deque
from concurrent.futures import FIRST_COMPLETED, ThreadPoolExecutor
from concurrent.futures import wait as futures_wait
from typing import Any, Callable, Dict, List, Optional, Set, Tuple

from replication_faster_rcnn_tpu.config import FleetConfig
from replication_faster_rcnn_tpu.faultlib import failpoints
from replication_faster_rcnn_tpu.serving.fleet.breaker import CircuitBreaker
from replication_faster_rcnn_tpu.serving.fleet.client import ReplicaDown
from replication_faster_rcnn_tpu.serving.fleet.registry import (
    CANARY,
    SHADOW,
    ReplicaRegistry,
)

__all__ = ["FleetRouter", "FleetUnavailable", "HashRing", "content_key"]


class FleetUnavailable(ConnectionError):
    """Every eligible replica refused or failed the request."""


def _hash64(s: str) -> int:
    return int.from_bytes(hashlib.sha256(s.encode()).digest()[:8], "big")


def content_key(data: bytes) -> str:
    """Stable content hash for a request payload (cache + ring key)."""
    return hashlib.sha256(data).hexdigest()


class HashRing:
    """Consistent hash ring with virtual nodes.

    ``ordered(key)`` walks clockwise from the key's position and returns
    every distinct node once — position 0 is the owner, the rest are the
    failover/hedge order.  Stateless w.r.t. membership: build one per
    membership set (cheap — ``vnodes x N`` hashes) and cache by set.
    """

    def __init__(self, nodes: List[str], vnodes: int = 64) -> None:
        if vnodes < 1:
            raise ValueError(f"vnodes must be >= 1, got {vnodes}")
        points: List[Tuple[int, str]] = []
        for node in nodes:
            for v in range(vnodes):
                points.append((_hash64(f"{node}#{v}"), node))
        points.sort()
        self._points = points
        self._hashes = [h for h, _ in points]
        self._n_nodes = len(set(nodes))

    def ordered(self, key: str) -> List[str]:
        if not self._points:
            return []
        start = bisect_right(self._hashes, _hash64(key))
        seen: Set[str] = set()
        out: List[str] = []
        for i in range(len(self._points)):
            _, node = self._points[(start + i) % len(self._points)]
            if node not in seen:
                seen.add(node)
                out.append(node)
                if len(out) == self._n_nodes:
                    break
        return out


class FleetRouter:
    """Self-healing dispatcher over a :class:`ReplicaRegistry`.

    ``kill_hook(replica_id)`` is called when a ``router.dispatch`` drop
    fault selects a replica — the chaos leg and fleet_profile benchmark
    wire it to ``LocalReplicaClient.kill`` so the injected death is
    real for every subsequent attempt and probe.
    """

    def __init__(
        self,
        registry: ReplicaRegistry,
        config: FleetConfig,
        clock: Callable[[], float] = time.monotonic,
        kill_hook: Optional[Callable[[str], None]] = None,
    ) -> None:
        self._registry = registry
        self._config = config
        self._clock = clock
        self._kill_hook = kill_hook
        # guards stats, cache, latency window, breakers table, ring cache
        # — written from dispatch callers (HTTP handler threads) AND the
        # hedge pool's attempt/shadow tasks
        self._lock = threading.Lock()
        self._breakers: Dict[str, CircuitBreaker] = {}
        self._cache: "OrderedDict[str, Any]" = OrderedDict()
        self._latency_s: deque = deque(maxlen=config.latency_window)
        self._ring_cache: Tuple[Tuple[str, ...], Optional[HashRing]] = ((), None)
        self._replica_stats: Dict[str, Dict[str, int]] = {}
        self.stats: Dict[str, int] = {
            "requests": 0,
            "cache_hits": 0,
            "attempts": 0,
            "failed_attempts": 0,
            "failovers": 0,
            "hedges": 0,
            "hedge_wins": 0,
            "canary_requests": 0,
            "shadow_requests": 0,
            "shadow_diffs": 0,
            "unavailable": 0,
        }
        # hedging needs attempts in flight concurrently; sequential mode
        # (hedge=False) never touches the pool
        self._pool: Optional[ThreadPoolExecutor] = None
        if config.hedge:
            self._pool = ThreadPoolExecutor(
                max_workers=max(4, 2 * config.max_attempts),
                thread_name_prefix="fleet-hedge",
            )

    # ---------------------------------------------------------------- reads

    def breaker(self, replica_id: str) -> CircuitBreaker:
        with self._lock:
            b = self._breakers.get(replica_id)
            if b is None:
                b = CircuitBreaker(
                    threshold=self._config.breaker_threshold,
                    cooldown_s=self._config.breaker_cooldown_s,
                    clock=self._clock,
                )
                self._breakers[replica_id] = b
            return b

    def hedge_delay_s(self) -> float:
        """``hedge_multiplier x observed p99`` clamped to the configured
        floor/ceiling; before any samples exist, the ceiling (hedge
        conservatively until there is evidence of the tail)."""
        cfg = self._config
        with self._lock:
            samples = sorted(self._latency_s)
        if not samples:
            return cfg.hedge_ceiling_ms / 1000.0
        idx = min(len(samples) - 1, int(0.99 * (len(samples) - 1) + 0.5))
        raw = samples[idx] * cfg.hedge_multiplier
        return min(
            max(raw, cfg.hedge_floor_ms / 1000.0),
            cfg.hedge_ceiling_ms / 1000.0,
        )

    def snapshot(self) -> Dict[str, Any]:
        """Router + per-replica gauges for /stats and telemetry."""
        with self._lock:
            stats = dict(self.stats)
            per_replica = {
                rid: dict(c) for rid, c in self._replica_stats.items()
            }
            breakers = list(self._breakers.items())
            cache_size = len(self._cache)
        for rid, b in breakers:
            per_replica.setdefault(rid, {"ok": 0, "fail": 0})["breaker"] = (
                b.snapshot()
            )
        return {
            "router": {
                **stats,
                "cache_size": cache_size,
                "hedge_delay_ms": round(self.hedge_delay_s() * 1e3, 3),
            },
            "replicas": per_replica,
            "registry": self._registry.snapshot(),
        }

    # ------------------------------------------------------------ placement

    def _ring(self) -> HashRing:
        members = tuple(self._registry.in_rotation())
        with self._lock:
            cached_members, ring = self._ring_cache
            if ring is not None and cached_members == members:
                return ring
        ring = HashRing(list(members), vnodes=self._config.vnodes)
        with self._lock:
            self._ring_cache = (members, ring)
        return ring

    def _canary_first(self, content_hash: str) -> List[str]:
        """The canary replicas this request should try first: a stable
        ``canary_fraction`` slice of the content-hash space (the same
        image always lands on the same side of the split)."""
        cfg = self._config
        if cfg.canary_fraction <= 0:
            return []
        canaries = self._registry.in_rotation(role=CANARY)
        if not canaries:
            return []
        slot = _hash64(f"{content_hash}:canary") / float(1 << 64)
        if slot >= cfg.canary_fraction:
            return []
        return [canaries[_hash64(content_hash) % len(canaries)]]

    def candidates(self, content_hash: str, bucket: str = "") -> List[str]:
        """Dispatch order for a request: optional canary first, then the
        consistent-hash walk over the serving rotation."""
        order = self._canary_first(content_hash)
        for rid in self._ring().ordered(f"{content_hash}:{bucket}"):
            if rid not in order:
                order.append(rid)
        return order

    # ------------------------------------------------------------- dispatch

    def dispatch(
        self, payload: Any, content_hash: str, bucket: str = ""
    ) -> Any:
        """Route one request through cache -> canary/ring -> breakers ->
        failover/hedging.  Raises :class:`FleetUnavailable` when no
        replica could serve it."""
        cfg = self._config
        with self._lock:
            self.stats["requests"] += 1
            if cfg.cache_entries > 0 and content_hash in self._cache:
                self._cache.move_to_end(content_hash)
                self.stats["cache_hits"] += 1
                return self._cache[content_hash]
        order = self.candidates(content_hash, bucket)
        if not order:
            with self._lock:
                self.stats["unavailable"] += 1
            raise FleetUnavailable("no replicas in rotation")
        if order[0] in self._registry.in_rotation(role=CANARY):
            with self._lock:
                self.stats["canary_requests"] += 1
        if self._pool is not None and cfg.hedge:
            result = self._dispatch_hedged(payload, order)
        else:
            result = self._dispatch_sequential(payload, order)
        with self._lock:
            if cfg.cache_entries > 0:
                self._cache[content_hash] = result
                self._cache.move_to_end(content_hash)
                while len(self._cache) > cfg.cache_entries:
                    self._cache.popitem(last=False)
        self._mirror_to_shadows(payload, result)
        return result

    def _next_allowed(
        self, order: List[str], tried: Set[str]
    ) -> Optional[str]:
        for rid in order:
            if rid not in tried and self.breaker(rid).allow():
                return rid
        return None

    def _attempt(self, replica_id: str, payload: Any) -> Any:
        """One replica call: failpoint consult, predict, accounting.
        Runs on the caller thread (sequential mode) or a hedge-pool
        thread — every shared write below is lock-guarded."""
        with self._lock:
            self.stats["attempts"] += 1
        t0 = self._clock()
        try:
            inj = failpoints.fire("router.dispatch", replica=replica_id)
            if inj is not None and inj.kind == "drop":
                # the selected replica dies mid-request: make it real
                # through the kill hook, then fail this attempt the way
                # a dropped TCP connection would
                if self._kill_hook is not None:
                    self._kill_hook(replica_id)
                raise ReplicaDown(
                    f"injected replica kill mid-request on {replica_id!r}"
                )
            client = self._registry.client_of(replica_id)
            result = client.predict(
                payload, timeout_s=self._config.request_timeout_s
            )
        except BaseException:
            self.breaker(replica_id).record_failure()
            with self._lock:
                self.stats["failed_attempts"] += 1
                self._replica_stats.setdefault(
                    replica_id, {"ok": 0, "fail": 0}
                )["fail"] += 1
            raise
        self.breaker(replica_id).record_success()
        dt = self._clock() - t0
        with self._lock:
            self._latency_s.append(dt)
            self._replica_stats.setdefault(
                replica_id, {"ok": 0, "fail": 0}
            )["ok"] += 1
        return result

    def _dispatch_sequential(self, payload: Any, order: List[str]) -> Any:
        """Deterministic failover walk — the chaos-replayable mode."""
        errors: List[str] = []
        tried: Set[str] = set()
        for _ in range(self._config.max_attempts):
            rid = self._next_allowed(order, tried)
            if rid is None:
                break
            tried.add(rid)
            try:
                result = self._attempt(rid, payload)
            except Exception as e:  # noqa: BLE001 - absorbed by failover
                errors.append(f"{rid}: {type(e).__name__}: {e}")
                with self._lock:
                    self.stats["failovers"] += 1
                continue
            return result
        with self._lock:
            self.stats["unavailable"] += 1
        raise FleetUnavailable(
            f"all attempts failed ({len(errors)}): {'; '.join(errors) or 'no eligible replica'}"
        )

    def _dispatch_hedged(self, payload: Any, order: List[str]) -> Any:
        """Concurrent mode: primary attempt, a hedge copy after the
        p99-derived delay, failover relaunch on failures; first success
        wins.  Late losers still resolve on the pool and record into
        their own breakers/stats (all lock-guarded)."""
        cfg = self._config
        errors: List[str] = []
        tried: Set[str] = set()
        inflight: Dict[Any, str] = {}
        hedge_futs: Set[Any] = set()

        def _launch(is_hedge: bool) -> bool:
            rid = self._next_allowed(order, tried)
            if rid is None or len(tried) >= cfg.max_attempts:
                return False
            tried.add(rid)
            fut = self._pool.submit(self._attempt, rid, payload)
            inflight[fut] = rid
            if is_hedge:
                hedge_futs.add(fut)
            return True

        if not _launch(is_hedge=False):
            with self._lock:
                self.stats["unavailable"] += 1
            raise FleetUnavailable("no eligible replica (breakers open)")
        deadline = self._clock() + cfg.request_timeout_s
        hedge_at = self._clock() + self.hedge_delay_s()
        hedged = False
        while inflight:
            now = self._clock()
            if now >= deadline:
                break
            timeout = (deadline if hedged else min(hedge_at, deadline)) - now
            done, _ = futures_wait(
                set(inflight), timeout=max(0.0, timeout),
                return_when=FIRST_COMPLETED,
            )
            if not done:
                if not hedged and self._clock() >= hedge_at:
                    hedged = True
                    if _launch(is_hedge=True):
                        with self._lock:
                            self.stats["hedges"] += 1
                continue
            for fut in done:
                rid = inflight.pop(fut)
                exc = fut.exception()
                if exc is None:
                    if fut in hedge_futs:
                        with self._lock:
                            self.stats["hedge_wins"] += 1
                    return fut.result()
                errors.append(f"{rid}: {type(exc).__name__}: {exc}")
                with self._lock:
                    self.stats["failovers"] += 1
                _launch(is_hedge=False)
        with self._lock:
            self.stats["unavailable"] += 1
        raise FleetUnavailable(
            f"all attempts failed ({len(errors)}): {'; '.join(errors) or 'request deadline exceeded'}"
        )

    # --------------------------------------------------------------- shadow

    def _mirror_to_shadows(self, payload: Any, primary_result: Any) -> None:
        """Mirror a served request to every shadow replica and diff the
        responses — counters only, the client response is unaffected.
        Async on the hedge pool when present, inline otherwise."""
        shadows = self._registry.in_rotation(role=SHADOW)
        for rid in shadows:
            if self._pool is not None:
                self._pool.submit(self._shadow_probe, rid, payload, primary_result)
            else:
                self._shadow_probe(rid, payload, primary_result)

    def _shadow_probe(
        self, replica_id: str, payload: Any, primary_result: Any
    ) -> None:
        with self._lock:
            self.stats["shadow_requests"] += 1
        try:
            client = self._registry.client_of(replica_id)
            shadow_result = client.predict(
                payload, timeout_s=self._config.request_timeout_s
            )
            same = json.dumps(shadow_result, sort_keys=True, default=str) == (
                json.dumps(primary_result, sort_keys=True, default=str)
            )
        except Exception:  # noqa: BLE001 - a failing shadow is a diff
            same = False
        if not same:
            with self._lock:
                self.stats["shadow_diffs"] += 1

    # ------------------------------------------------------------ lifecycle

    def close(self) -> None:
        if self._pool is not None:
            self._pool.shutdown(wait=True)

    def __enter__(self) -> "FleetRouter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
