"""The fleet dispatcher: consistent hashing, result cache, breakers,
failover, hedging, canary and shadow traffic.

One request's path through :meth:`FleetRouter.dispatch`:

1. **Cache** — the content hash answers exact-duplicate images from the
   router's LRU without touching a replica.
2. **Placement** — the request's ring key ``content_hash:bucket`` walks
   the consistent-hash ring (``fleet.vnodes`` points per replica) over
   the replicas currently in rotation; the ordered walk IS the failover
   order, so retries of the same image hit the same replicas in the
   same order while membership is stable, and membership changes move
   only ~1/N of the keyspace.  A deterministic ``canary_fraction``
   slice of the hash space tries the canary replica first.
3. **Dispatch** — attempts run against the walk order, skipping
   replicas whose circuit breaker refuses.  Every attempt consults the
   ``router.dispatch`` failpoint: an injected ``drop`` invokes the
   router's kill hook (the chaos/benchmark seam that makes the selected
   replica actually die) and then fails the attempt as a dropped
   connection — which the machinery below must absorb.
4. **Failover** — a failed attempt records into that replica's breaker
   and re-dispatches to the next replica in the walk, up to
   ``fleet.max_attempts``.
5. **Hedging** — with ``fleet.hedge``, if the primary attempt has not
   resolved after ``hedge_multiplier x observed p99`` (clamped to the
   configured floor/ceiling), a second copy goes to the next replica
   and the first result wins — tail tolerance against a slow-but-alive
   replica, which failover alone cannot see.
6. **Shadow** — successful responses are mirrored to shadow replicas
   and diffed (counters only; the client's response is already gone).

Hedging needs real concurrency, so it runs attempts on a thread pool;
with ``hedge=False`` (or no pool) dispatch is strictly sequential and
single-threaded — the mode the chaos leg replays deterministically.
"""

from __future__ import annotations

import hashlib
import json
import threading
import time
from bisect import bisect_right
from collections import OrderedDict
from concurrent.futures import FIRST_COMPLETED, ThreadPoolExecutor
from concurrent.futures import wait as futures_wait
from typing import Any, Callable, Dict, List, Optional, Set, Tuple

from replication_faster_rcnn_tpu.config import FleetConfig
from replication_faster_rcnn_tpu.faultlib import failpoints
from replication_faster_rcnn_tpu.serving.fleet.breaker import CircuitBreaker
from replication_faster_rcnn_tpu.serving.fleet.client import ReplicaDown
from replication_faster_rcnn_tpu.serving.fleet.registry import (
    CANARY,
    SERVING,
    SHADOW,
    ReplicaRegistry,
)
from replication_faster_rcnn_tpu.telemetry import spans as tspans
from replication_faster_rcnn_tpu.telemetry import tracecontext
from replication_faster_rcnn_tpu.telemetry.metrics import (
    DEFAULT_LATENCY_BUCKETS_S,
    MetricsRegistry,
)
from replication_faster_rcnn_tpu.telemetry.slo_burn import BurnRateTracker

__all__ = ["FleetRouter", "FleetUnavailable", "HashRing", "content_key"]

# a canary's own burn-rate tracker must see at least this many attempt
# outcomes in the long window before its alarm can demote it — a canary
# judged on three requests is an unfair trial
CANARY_SLO_MIN_SAMPLES = 20

# the router's request/attempt counters, in /stats order; each is a
# registry counter named fleet_<key>_total
_STAT_KEYS = (
    "requests",
    "cache_hits",
    "attempts",
    "failed_attempts",
    "failovers",
    "hedges",
    "hedge_wins",
    "canary_requests",
    "canary_demotions",
    "shadow_requests",
    "shadow_diffs",
    "unavailable",
)


class FleetUnavailable(ConnectionError):
    """Every eligible replica refused or failed the request."""


_CACHE_MISS = object()


def _hash64(s: str) -> int:
    return int.from_bytes(hashlib.sha256(s.encode()).digest()[:8], "big")


def content_key(data: bytes) -> str:
    """Stable content hash for a request payload (cache + ring key)."""
    return hashlib.sha256(data).hexdigest()


class HashRing:
    """Consistent hash ring with virtual nodes.

    ``ordered(key)`` walks clockwise from the key's position and returns
    every distinct node once — position 0 is the owner, the rest are the
    failover/hedge order.  Stateless w.r.t. membership: build one per
    membership set (cheap — ``vnodes x N`` hashes) and cache by set.
    """

    def __init__(self, nodes: List[str], vnodes: int = 64) -> None:
        if vnodes < 1:
            raise ValueError(f"vnodes must be >= 1, got {vnodes}")
        points: List[Tuple[int, str]] = []
        for node in nodes:
            for v in range(vnodes):
                points.append((_hash64(f"{node}#{v}"), node))
        points.sort()
        self._points = points
        self._hashes = [h for h, _ in points]
        self._n_nodes = len(set(nodes))

    def ordered(self, key: str) -> List[str]:
        if not self._points:
            return []
        start = bisect_right(self._hashes, _hash64(key))
        seen: Set[str] = set()
        out: List[str] = []
        for i in range(len(self._points)):
            _, node = self._points[(start + i) % len(self._points)]
            if node not in seen:
                seen.add(node)
                out.append(node)
                if len(out) == self._n_nodes:
                    break
        return out


class FleetRouter:
    """Self-healing dispatcher over a :class:`ReplicaRegistry`.

    ``kill_hook(replica_id)`` is called when a ``router.dispatch`` drop
    fault selects a replica — the chaos leg and fleet_profile benchmark
    wire it to ``LocalReplicaClient.kill`` so the injected death is
    real for every subsequent attempt and probe.
    """

    def __init__(
        self,
        registry: ReplicaRegistry,
        config: FleetConfig,
        clock: Callable[[], float] = time.monotonic,
        kill_hook: Optional[Callable[[str], None]] = None,
    ) -> None:
        self._registry = registry
        self._config = config
        self._clock = clock
        self._kill_hook = kill_hook
        # guards cache, breakers table, ring cache, canary-tracker table
        # — written from dispatch callers (HTTP handler threads) AND the
        # hedge pool's attempt/shadow tasks; counters/histograms carry
        # their own registry-internal locks
        self._lock = threading.Lock()
        self._breakers: Dict[str, CircuitBreaker] = {}
        self._cache: "OrderedDict[str, Any]" = OrderedDict()
        self._ring_cache: Tuple[Tuple[str, ...], Optional[HashRing]] = ((), None)
        # unified metrics core: one registry renders /stats JSON,
        # /metrics Prometheus text, and fleet.jsonl snapshots
        self.metrics = MetricsRegistry()
        self._counters = {
            key: self.metrics.counter(f"fleet_{key}_total", help=f"fleet {key}")
            for key in _STAT_KEYS
        }
        # attempt latency histogram: bounded memory under sustained load
        # (the raw-latency deque it replaces kept every sample) AND the
        # p99 source for the hedge delay
        self._attempt_hist = self.metrics.histogram(
            "fleet_attempt_seconds",
            help="replica attempt latency (successful attempts)",
            buckets=DEFAULT_LATENCY_BUCKETS_S,
        )
        self.metrics.register_collector(self._collect_gauges)
        # SLO burn-rate over ATTEMPT outcomes: with failover absorbing
        # most failures before clients see them, attempts — not final
        # request results — are where a dying replica shows up first
        self.slo = BurnRateTracker(
            availability_target=config.slo_availability_target,
            latency_target_s=config.slo_latency_target_ms / 1000.0,
            short_window_s=config.slo_short_window_s,
            long_window_s=config.slo_long_window_s,
            clock=clock,
        )
        # per-canary trackers driving the auto-demote hook
        self._canary_slo: Dict[str, BurnRateTracker] = {}
        # hedging needs attempts in flight concurrently; sequential mode
        # (hedge=False) never touches the pool
        self._pool: Optional[ThreadPoolExecutor] = None
        if config.hedge:
            self._pool = ThreadPoolExecutor(
                max_workers=max(4, 2 * config.max_attempts),
                thread_name_prefix="fleet-hedge",
            )

    @property
    def stats(self) -> Dict[str, int]:
        """The router counters as a plain dict (the historical shape) —
        a registry snapshot, not mutable state."""
        return {k: int(c.value) for k, c in self._counters.items()}

    # ---------------------------------------------------------------- reads

    def breaker(self, replica_id: str) -> CircuitBreaker:
        with self._lock:
            b = self._breakers.get(replica_id)
            if b is None:
                b = CircuitBreaker(
                    threshold=self._config.breaker_threshold,
                    cooldown_s=self._config.breaker_cooldown_s,
                    clock=self._clock,
                )
                self._breakers[replica_id] = b
            return b

    def hedge_delay_s(self) -> float:
        """``hedge_multiplier x observed p99`` clamped to the configured
        floor/ceiling; before any samples exist, the ceiling (hedge
        conservatively until there is evidence of the tail).  The p99
        comes from the attempt histogram — O(buckets) memory however
        long the router runs, unlike the raw-sample list it replaced."""
        cfg = self._config
        if self._attempt_hist.count == 0:
            return cfg.hedge_ceiling_ms / 1000.0
        raw = self._attempt_hist.percentile(99) * cfg.hedge_multiplier
        return min(
            max(raw, cfg.hedge_floor_ms / 1000.0),
            cfg.hedge_ceiling_ms / 1000.0,
        )

    def _collect_gauges(self) -> None:
        with self._lock:
            breakers = list(self._breakers.items())
            cache_size = len(self._cache)
        self.metrics.gauge(
            "fleet_cache_size", help="content-hash result cache entries"
        ).set(cache_size)
        self.metrics.gauge(
            "fleet_hedge_delay_seconds", help="current hedge trigger delay"
        ).set(self.hedge_delay_s())
        state_code = {"closed": 0, "half_open": 1, "open": 2}
        for rid, b in breakers:
            snap = b.snapshot()
            self.metrics.gauge(
                "fleet_breaker_state",
                help="circuit breaker state (0 closed, 1 half-open, 2 open)",
                replica=rid,
            ).set(state_code.get(snap["state"], -1))
        for rates_name, burn in self.slo.burn_rates().items():
            self.metrics.gauge(
                "fleet_slo_burn_rate",
                help="error-budget burn rate per window",
                window=rates_name,
            ).set(burn)
        # info-style gauge: mixed-precision fleets expose each replica's
        # reported residency dtype as a label (value is always 1)
        for rid, info in self._registry.snapshot().items():
            dtype = info.get("params_dtype")
            if dtype:
                self.metrics.gauge(
                    "fleet_replica_params_dtype",
                    help="replica resident params dtype (info gauge)",
                    replica=rid,
                    params_dtype=dtype,
                ).set(1)
            version = info.get("model_version")
            if version:
                # version-skew view during a rolling rollout: each
                # replica's current version as a label series (1 = the
                # version it reports now, stale series drop to 0)
                for c in self.metrics.find("fleet_replica_model_version"):
                    if (
                        c.labels.get("replica") == rid
                        and c.labels.get("model_version") != version
                    ):
                        c.set(0)
                self.metrics.gauge(
                    "fleet_replica_model_version",
                    help="replica resident model version (info gauge)",
                    replica=rid,
                    model_version=version,
                ).set(1)

    def _replica_counter(self, replica_id: str, outcome: str):
        return self.metrics.counter(
            "fleet_replica_attempts_total",
            help="per-replica attempt outcomes",
            replica=replica_id,
            outcome=outcome,
        )

    def snapshot(self) -> Dict[str, Any]:
        """Router + per-replica gauges for /stats and telemetry — every
        number is read back out of the metrics registry, so this JSON
        view and the Prometheus /metrics text cannot disagree."""
        per_replica: Dict[str, Dict[str, Any]] = {}
        for c in self.metrics.find("fleet_replica_attempts_total"):
            entry = per_replica.setdefault(
                c.labels["replica"], {"ok": 0, "fail": 0}
            )
            entry[c.labels["outcome"]] = int(c.value)
        with self._lock:
            breakers = list(self._breakers.items())
        for rid, b in breakers:
            per_replica.setdefault(rid, {"ok": 0, "fail": 0})["breaker"] = (
                b.snapshot()
            )
        return {
            "router": {
                **self.stats,
                "cache_size": self._cache_size(),
                "hedge_delay_ms": round(self.hedge_delay_s() * 1e3, 3),
            },
            "replicas": per_replica,
            "registry": self._registry.snapshot(),
            "slo": self.slo.snapshot(),
        }

    def _cache_size(self) -> int:
        with self._lock:
            return len(self._cache)

    # ------------------------------------------------------------ placement

    def _ring(self) -> HashRing:
        members = tuple(self._registry.in_rotation())
        with self._lock:
            cached_members, ring = self._ring_cache
            if ring is not None and cached_members == members:
                return ring
        ring = HashRing(list(members), vnodes=self._config.vnodes)
        with self._lock:
            self._ring_cache = (members, ring)
        return ring

    def _canary_first(self, content_hash: str) -> List[str]:
        """The canary replicas this request should try first: a stable
        ``canary_fraction`` slice of the content-hash space (the same
        image always lands on the same side of the split)."""
        cfg = self._config
        if cfg.canary_fraction <= 0:
            return []
        canaries = self._registry.in_rotation(role=CANARY)
        if not canaries:
            return []
        slot = _hash64(f"{content_hash}:canary") / float(1 << 64)
        if slot >= cfg.canary_fraction:
            return []
        return [canaries[_hash64(content_hash) % len(canaries)]]

    def candidates(self, content_hash: str, bucket: str = "") -> List[str]:
        """Dispatch order for a request: optional canary first, then the
        consistent-hash walk over the serving rotation."""
        order = self._canary_first(content_hash)
        for rid in self._ring().ordered(f"{content_hash}:{bucket}"):
            if rid not in order:
                order.append(rid)
        return order

    # ------------------------------------------------------------- dispatch

    def dispatch(
        self, payload: Any, content_hash: str, bucket: str = ""
    ) -> Any:
        """Route one request through cache -> canary/ring -> breakers ->
        failover/hedging.  Raises :class:`FleetUnavailable` when no
        replica could serve it.

        The request's trace context is the one already bound on this
        thread (the fleet HTTP front door extracts the caller's
        ``traceparent``) or a fresh root; every attempt below runs as a
        child span of it, so the whole failover/hedge fan-out shares one
        trace id in the merged timeline."""
        cfg = self._config
        trace = tracecontext.current_trace() or tracecontext.new_trace_context()
        tracer = tspans.current_tracer()
        self._counters["requests"].inc()
        hit = _CACHE_MISS
        with self._lock:
            if cfg.cache_entries > 0 and content_hash in self._cache:
                self._cache.move_to_end(content_hash)
                hit = self._cache[content_hash]
        if hit is not _CACHE_MISS:
            self._counters["cache_hits"].inc()
            return hit
        order = self.candidates(content_hash, bucket)
        if not order:
            self._counters["unavailable"].inc()
            raise FleetUnavailable(
                f"no replicas in rotation (trace {trace.trace_id})"
            )
        if order[0] in self._registry.in_rotation(role=CANARY):
            self._counters["canary_requests"].inc()
        t_req = tracer.now_us()
        try:
            with tracecontext.bind(trace):
                if self._pool is not None and cfg.hedge:
                    result = self._dispatch_hedged(payload, order, trace)
                else:
                    result = self._dispatch_sequential(payload, order, trace)
        finally:
            if tracer.enabled:
                tracer.complete(
                    "fleet/request",
                    t_req,
                    tracer.now_us() - t_req,
                    cat="fleet",
                    content_hash=content_hash[:16],
                    **trace.span_args(),
                )
        with self._lock:
            if cfg.cache_entries > 0:
                self._cache[content_hash] = result
                self._cache.move_to_end(content_hash)
                while len(self._cache) > cfg.cache_entries:
                    self._cache.popitem(last=False)
        self._mirror_to_shadows(payload, result)
        return result

    def _next_allowed(
        self, order: List[str], tried: Set[str]
    ) -> Optional[str]:
        for rid in order:
            if rid not in tried and self.breaker(rid).allow():
                return rid
        return None

    def _attempt(
        self,
        replica_id: str,
        payload: Any,
        ctx: Optional[tracecontext.TraceContext] = None,
        hedge: bool = False,
    ) -> Any:
        """One replica call: failpoint consult, predict, accounting.
        Runs on the caller thread (sequential mode) or a hedge-pool
        thread — every shared write below is lock-guarded.

        ``ctx`` is this attempt's span: bound to the executing thread so
        the transport (HTTP traceparent header / in-process thread-local)
        carries it into the replica, and stamped on the attempt's span
        event.  Hedged/failover attempts arrive as siblings — same trace
        id and parent, distinct span ids."""
        self._counters["attempts"].inc()
        tracer = tspans.current_tracer()
        t_us = tracer.now_us()
        t0 = self._clock()
        ok = False
        try:
            with tracecontext.bind(ctx):
                inj = failpoints.fire("router.dispatch", replica=replica_id)
                if inj is not None and inj.kind == "drop":
                    # the selected replica dies mid-request: make it real
                    # through the kill hook, then fail this attempt the way
                    # a dropped TCP connection would
                    if self._kill_hook is not None:
                        self._kill_hook(replica_id)
                    raise ReplicaDown(
                        f"injected replica kill mid-request on {replica_id!r}"
                    )
                client = self._registry.client_of(replica_id)
                result = client.predict(
                    payload, timeout_s=self._config.request_timeout_s
                )
            ok = True
        except BaseException:
            self.breaker(replica_id).record_failure()
            self._counters["failed_attempts"].inc()
            self._replica_counter(replica_id, "fail").inc()
            raise
        finally:
            dt = self._clock() - t0
            self.slo.record(ok, dt)
            self._note_canary_outcome(replica_id, ok, dt)
            if tracer.enabled and ctx is not None:
                tracer.complete(
                    "fleet/attempt",
                    t_us,
                    tracer.now_us() - t_us,
                    cat="fleet",
                    replica=replica_id,
                    hedge=hedge,
                    ok=ok,
                    **ctx.span_args(),
                )
        self.breaker(replica_id).record_success()
        self._attempt_hist.observe(dt)
        self._replica_counter(replica_id, "ok").inc()
        return result

    def _note_canary_outcome(
        self, replica_id: str, ok: bool, latency_s: float
    ) -> None:
        """Feed a canary's private burn tracker; an alarming canary is
        demoted back to plain serving traffic (the auto-demote hook —
        a bad rollout stops taking its deterministic slice without an
        operator in the loop)."""
        try:
            if self._registry.role_of(replica_id) != CANARY:
                return
        except KeyError:
            return
        cfg = self._config
        with self._lock:
            tracker = self._canary_slo.get(replica_id)
            if tracker is None:
                tracker = BurnRateTracker(
                    availability_target=cfg.slo_availability_target,
                    latency_target_s=cfg.slo_latency_target_ms / 1000.0,
                    short_window_s=cfg.slo_short_window_s,
                    long_window_s=cfg.slo_long_window_s,
                    clock=self._clock,
                )
                self._canary_slo[replica_id] = tracker
        tracker.record(ok, latency_s)
        snap = tracker.snapshot()
        if (
            snap["alarm"]
            and snap["samples"]["long"] >= CANARY_SLO_MIN_SAMPLES
        ):
            self._registry.set_role(
                replica_id,
                SERVING,
                reason=(
                    "slo burn-rate alarm: short="
                    f"{snap['burn_rates']['short']:.1f}x long="
                    f"{snap['burn_rates']['long']:.1f}x"
                ),
            )
            self._counters["canary_demotions"].inc()
            tspans.current_tracer().instant(
                "fleet/canary_demoted", cat="fleet", replica=replica_id
            )

    def canary_report(self, replica_id: str) -> Dict[str, Any]:
        """The rollout controller's promote/rollback evidence for one
        canary: its private burn-tracker snapshot (or ``None`` before
        any canary traffic landed), the routed canary request count,
        and the fleet-wide shadow-diff counters over the same period."""
        with self._lock:
            tracker = self._canary_slo.get(replica_id)
        counters = {
            k: int(v)
            for k, v in self.metrics.counters_flat().items()
            if "{" not in k
        }
        return {
            "slo": tracker.snapshot() if tracker is not None else None,
            "canary_requests": counters.get("fleet_canary_requests_total", 0),
            "shadow_requests": counters.get("fleet_shadow_requests_total", 0),
            "shadow_diffs": counters.get("fleet_shadow_diffs_total", 0),
        }

    def _dispatch_sequential(
        self,
        payload: Any,
        order: List[str],
        trace: tracecontext.TraceContext,
    ) -> Any:
        """Deterministic failover walk — the chaos-replayable mode.
        Every attempt is a sibling child span of the request."""
        errors: List[str] = []
        tried: Set[str] = set()
        for _ in range(self._config.max_attempts):
            rid = self._next_allowed(order, tried)
            if rid is None:
                break
            tried.add(rid)
            try:
                result = self._attempt(rid, payload, ctx=trace.child())
            except Exception as e:  # noqa: BLE001 - absorbed by failover
                errors.append(f"{rid}: {type(e).__name__}: {e}")
                self._counters["failovers"].inc()
                continue
            return result
        self._counters["unavailable"].inc()
        raise FleetUnavailable(
            f"all attempts failed ({len(errors)}): "
            f"{'; '.join(errors) or 'no eligible replica'} "
            f"(trace {trace.trace_id})"
        )

    def _dispatch_hedged(
        self,
        payload: Any,
        order: List[str],
        trace: tracecontext.TraceContext,
    ) -> Any:
        """Concurrent mode: primary attempt, a hedge copy after the
        p99-derived delay, failover relaunch on failures; first success
        wins.  Late losers still resolve on the pool and record into
        their own breakers/stats (all lock-guarded).  All attempts —
        winner, loser, abandoned — are sibling spans under one trace
        id, which is what makes a hedge race legible afterwards."""
        cfg = self._config
        errors: List[str] = []
        tried: Set[str] = set()
        inflight: Dict[Any, str] = {}
        hedge_futs: Set[Any] = set()

        def _launch(is_hedge: bool) -> bool:
            rid = self._next_allowed(order, tried)
            if rid is None or len(tried) >= cfg.max_attempts:
                return False
            tried.add(rid)
            fut = self._pool.submit(
                self._attempt, rid, payload,
                ctx=trace.child(), hedge=is_hedge,
            )
            inflight[fut] = rid
            if is_hedge:
                hedge_futs.add(fut)
            return True

        if not _launch(is_hedge=False):
            self._counters["unavailable"].inc()
            raise FleetUnavailable(
                f"no eligible replica (breakers open) (trace {trace.trace_id})"
            )
        deadline = self._clock() + cfg.request_timeout_s
        hedge_at = self._clock() + self.hedge_delay_s()
        hedged = False
        while inflight:
            now = self._clock()
            if now >= deadline:
                break
            timeout = (deadline if hedged else min(hedge_at, deadline)) - now
            done, _ = futures_wait(
                set(inflight), timeout=max(0.0, timeout),
                return_when=FIRST_COMPLETED,
            )
            if not done:
                if not hedged and self._clock() >= hedge_at:
                    hedged = True
                    if _launch(is_hedge=True):
                        self._counters["hedges"].inc()
                continue
            for fut in done:
                rid = inflight.pop(fut)
                exc = fut.exception()
                if exc is None:
                    if fut in hedge_futs:
                        self._counters["hedge_wins"].inc()
                    return fut.result()
                errors.append(f"{rid}: {type(exc).__name__}: {exc}")
                self._counters["failovers"].inc()
                _launch(is_hedge=False)
        self._counters["unavailable"].inc()
        raise FleetUnavailable(
            f"all attempts failed ({len(errors)}): "
            f"{'; '.join(errors) or 'request deadline exceeded'} "
            f"(trace {trace.trace_id})"
        )

    # --------------------------------------------------------------- shadow

    def _mirror_to_shadows(self, payload: Any, primary_result: Any) -> None:
        """Mirror a served request to every shadow replica and diff the
        responses — counters only, the client response is unaffected.
        Async on the hedge pool when present, inline otherwise."""
        shadows = self._registry.in_rotation(role=SHADOW)
        for rid in shadows:
            if self._pool is not None:
                self._pool.submit(self._shadow_probe, rid, payload, primary_result)
            else:
                self._shadow_probe(rid, payload, primary_result)

    def _shadow_probe(
        self, replica_id: str, payload: Any, primary_result: Any
    ) -> None:
        self._counters["shadow_requests"].inc()
        try:
            client = self._registry.client_of(replica_id)
            shadow_result = client.predict(
                payload, timeout_s=self._config.request_timeout_s
            )
            same = json.dumps(shadow_result, sort_keys=True, default=str) == (
                json.dumps(primary_result, sort_keys=True, default=str)
            )
        except Exception:  # noqa: BLE001 - a failing shadow is a diff
            same = False
        if not same:
            self._counters["shadow_diffs"].inc()

    # ------------------------------------------------------------ lifecycle

    def close(self) -> None:
        if self._pool is not None:
            self._pool.shutdown(wait=True)

    def __enter__(self) -> "FleetRouter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
