"""Stdlib HTTP front for the fleet router (`frcnn fleet`).

The same minimal surface as serving/server.py, one level up: handler
threads hash the request content and hand it to the
:class:`~replication_faster_rcnn_tpu.serving.fleet.router.FleetRouter`,
which owns placement, failover, hedging and caching.  Per-path
isolation matches the replica server: one failing path costs that one
entry, the rest of the request still returns detections.

Tracing: an incoming ``traceparent`` header is adopted as the request's
trace (a fresh root otherwise) and bound for the whole handler, so the
router's request/attempt spans — and, through the per-attempt
traceparent the HTTP replica client injects, the replica tier's hop
spans — all share one trace id; error responses carry it too.

Endpoints:
  POST /predict  {"paths": ["a.jpg", ...]} or {"path": "a.jpg"} —
                 per-path detections routed across the fleet; a fleet-
                 wide inability to serve a path returns 503 with a
                 Retry-After derived from the breaker cooldown; error
                 responses carry the request's "trace_id"
  GET  /healthz  fleet liveness: ok while any replica is in rotation,
                 plus the per-replica registry snapshot
  GET  /stats    unified frcnn-stats/v1 envelope: schema/tier/metrics +
                 the fleet's structured sections (router, replicas,
                 registry, slo)
  GET  /metrics  the router's registry in Prometheus text exposition
"""

from __future__ import annotations

import contextlib
import json
import math
import socket
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from replication_faster_rcnn_tpu.faultlib import failpoints
from replication_faster_rcnn_tpu.serving.fleet.router import (
    FleetRouter,
    FleetUnavailable,
    content_key,
)
from replication_faster_rcnn_tpu.telemetry import tracecontext
from replication_faster_rcnn_tpu.telemetry.metrics import (
    PROMETHEUS_CONTENT_TYPE,
    stats_payload,
)

__all__ = ["make_fleet_server"]


class _FleetHandler(BaseHTTPRequestHandler):
    # the router hangs off the server instance (make_fleet_server)

    def _reply(self, code: int, payload: dict, headers: Optional[dict] = None) -> None:
        body = json.dumps(payload, indent=2).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        for k, v in (headers or {}).items():
            self.send_header(k, str(v))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, fmt, *fmt_args):  # quiet: one line per request
        pass  # noqa: D401 - stdlib signature

    def do_GET(self) -> None:  # noqa: N802 - stdlib casing
        router: FleetRouter = self.server.router
        if self.path == "/healthz":
            snap = router.snapshot()
            in_rotation = [
                rid
                for rid, r in snap["registry"].items()
                if r["state"] == "healthy"
            ]
            self._reply(
                200,
                {
                    "ok": bool(in_rotation),
                    "draining": bool(getattr(self.server, "draining", False)),
                    "in_rotation": sorted(in_rotation),
                    "replicas": snap["registry"],
                    # version-skew at a glance: replica -> last reported
                    # model version (None before its first clean probe)
                    "model_versions": {
                        rid: r.get("model_version")
                        for rid, r in snap["registry"].items()
                    },
                },
            )
        elif self.path == "/stats":
            self._reply(
                200, stats_payload("fleet", router.metrics, **router.snapshot())
            )
        elif self.path == "/metrics":
            body = router.metrics.render_prometheus().encode()
            self.send_response(200)
            self.send_header("Content-Type", PROMETHEUS_CONTENT_TYPE)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        else:
            self._reply(404, {"error": f"unknown path {self.path}"})

    def do_POST(self) -> None:  # noqa: N802 - stdlib casing
        if self.path != "/predict":
            self._reply(404, {"error": f"unknown path {self.path}"})
            return
        # adopt the caller's trace or start a root, bound for the whole
        # handler so router.dispatch (and everything under it) joins it
        parent = tracecontext.parse_traceparent(
            self.headers.get(tracecontext.TRACEPARENT_HEADER)
        )
        trace = (
            parent.child()
            if parent is not None
            else tracecontext.new_trace_context()
        )
        with tracecontext.bind(trace):
            self._handle_predict(trace)

    def _handle_predict(self, trace) -> None:
        trace_id = trace.trace_id
        # the front shares the replica tier's handler failpoint site, so
        # one chaos spec can fault either layer of the serving stack
        try:
            inj = failpoints.fire("http.handler", path=self.path, tier="fleet")
        except failpoints.ChaosError as e:
            self._reply(500, {"error": str(e), "trace_id": trace_id})
            return
        if inj is not None and inj.kind == "drop":
            with contextlib.suppress(OSError):
                self.connection.shutdown(socket.SHUT_RDWR)
            return
        router: FleetRouter = self.server.router
        try:
            length = int(self.headers.get("Content-Length", 0))
            req = json.loads(self.rfile.read(length) or b"{}")
            paths = req.get("paths") or ([req["path"]] if "path" in req else [])
            if not paths:
                raise ValueError('need "path" or non-empty "paths"')
        except (ValueError, KeyError, json.JSONDecodeError) as e:
            self._reply(400, {"error": str(e), "trace_id": trace_id})
            return
        results, errors = {}, {}
        unavailable = bad_input = 0
        for p in paths:
            try:
                with open(p, "rb") as fh:  # content hash = file bytes
                    digest = content_key(fh.read())
            except OSError as e:
                bad_input += 1
                errors[p] = f"{type(e).__name__}: {e}"
                continue
            try:
                results[p] = router.dispatch(p, content_hash=digest)
            except FleetUnavailable as e:
                unavailable += 1
                errors[p] = str(e)
            except Exception as e:  # noqa: BLE001 - surfaced per path
                errors[p] = f"{type(e).__name__}: {e}"
        if results:
            payload = {"detections": results}
            if errors:
                payload["errors"] = errors
            self._reply(200, payload)
            return
        if unavailable:
            cooldown = self.server.router._config.breaker_cooldown_s
            self._reply(
                503,
                {
                    "error": "fleet unavailable",
                    "errors": errors,
                    "trace_id": trace_id,
                },
                headers={"Retry-After": max(1, math.ceil(cooldown))},
            )
        elif bad_input == len(paths):
            self._reply(
                400, {"error": "; ".join(errors.values()), "trace_id": trace_id}
            )
        else:
            self._reply(
                500,
                {
                    "error": "all paths failed",
                    "errors": errors,
                    "trace_id": trace_id,
                },
            )


def make_fleet_server(
    router: FleetRouter, host: str = "127.0.0.1", port: int = 8010
) -> ThreadingHTTPServer:
    """A ready-to-``serve_forever`` front server bound to ``router``.
    ``port=0`` binds a free port (read ``server.server_address``)."""
    server = ThreadingHTTPServer((host, port), _FleetHandler)
    server.router = router
    server.draining = False
    return server
