"""Health-checked replica registry with lease-style staleness.

The PR 11 elastic-training heartbeat discipline, applied to serving:
every replica holds a lease that only a SUCCESSFUL ``/healthz`` probe
renews.  A replica that stops answering doesn't need to say goodbye —
its lease ages past ``fleet.lease_timeout_s`` and the registry declares
it DEAD, exactly like a training rank whose heartbeat file goes stale.
Recovery is probe-driven too: a DEAD (or newly added, or formerly
draining) replica must answer ``fleet.rejoin_probes`` consecutive
probes before it re-enters rotation, so a flapping replica cannot
bounce in and out of the serving set.

States::

    JOINING --ok x rejoin_probes--> HEALTHY --probe sees draining--> DRAINING
       ^                            |   ^                              |
       +---- add() ----             |   +----- ok x rejoin_probes -----+
                                    lease ages out
                                    v
                                  DEAD --ok x rejoin_probes--> HEALTHY

A replica probing ``degraded: true`` is parked in DRAINING as well —
alive (its lease renews) but routed around until it reports clean.
The rollout controller (serving/rollout/) parks replicas the same way
via :meth:`ReplicaRegistry.hold`: a *held* replica sits in DRAINING
with a renewing lease and cannot re-enter rotation until
:meth:`ReplicaRegistry.release` — clean probes accumulate but the
HEALTHY promotion is gated on the hold, so a mid-swap replica can
never take traffic no matter how healthy it looks.

The :class:`Prober` drives ``probe_once`` on a cadence from its own
thread (non-daemon, Event-stopped, joined — it may run forever but must
die cleanly); tests and the chaos leg call ``probe_once`` directly with
an injected clock instead.  Each probe consults the ``router.probe``
failpoint first: an injected ioerror is a failed probe (the lease keeps
aging), an injected delay is a stalled one.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional

from replication_faster_rcnn_tpu.config import FleetConfig
from replication_faster_rcnn_tpu.faultlib import failpoints

__all__ = [
    "CANARY",
    "DEAD",
    "DRAINING",
    "HEALTHY",
    "JOINING",
    "Prober",
    "Replica",
    "ReplicaRegistry",
    "SERVING",
    "SHADOW",
]

JOINING = "joining"
HEALTHY = "healthy"
DRAINING = "draining"
DEAD = "dead"

# replica roles: serving replicas take ring traffic; the canary takes a
# deterministic content-hash slice first; shadows get mirrored traffic
# whose responses never reach clients
SERVING = "serving"
CANARY = "canary"
SHADOW = "shadow"


class Replica:
    """One registry entry (mutated only under the registry lock)."""

    def __init__(self, replica_id: str, client, role: str) -> None:
        self.replica_id = replica_id
        self.client = client
        self.role = role
        self.state = JOINING
        self.last_ok = 0.0  # clock() of the last successful probe
        self.consecutive_ok = 0
        self.probes = 0
        self.failed_probes = 0
        self.detail: Optional[str] = None  # why it is out of rotation
        # resident params dtype the replica last reported via /healthz
        # (float32 | bfloat16 | int8) — mixed-precision fleets surface
        # it per replica in /stats and /metrics
        self.params_dtype: Optional[str] = None
        # model version the replica last reported via /healthz — the
        # rollout controller's convergence signal, and the version-skew
        # view in /stats + the fleet_replica_model_version info gauge
        self.model_version: Optional[str] = None
        # a held replica is parked in DRAINING by the rollout controller
        # (registry-side — the replica itself probes healthy) and cannot
        # re-enter rotation until release(), whatever its probes say
        self.held = False


class ReplicaRegistry:
    """Membership + probe-driven state machine for the fleet router."""

    def __init__(
        self,
        config: FleetConfig,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self._config = config
        self._clock = clock
        # mutated from the prober thread, dispatch threads (lease checks)
        # and control code — every touch is under this one lock
        self._lock = threading.Lock()
        self._replicas: Dict[str, Replica] = {}
        self._events: List[Dict[str, Any]] = []

    # ----------------------------------------------------------- membership

    def add(self, replica_id: str, client, role: str = SERVING) -> None:
        """Register a replica in JOINING state; ``rejoin_probes``
        consecutive healthy probes admit it to rotation."""
        if role not in (SERVING, CANARY, SHADOW):
            raise ValueError(f"unknown replica role {role!r}")
        with self._lock:
            if replica_id in self._replicas:
                raise ValueError(f"replica {replica_id!r} already registered")
            rep = Replica(replica_id, client, role)
            rep.last_ok = self._clock()  # the join lease starts fresh
            self._replicas[replica_id] = rep
            self._events.append(
                {"event": "replica_added", "replica": replica_id, "role": role}
            )

    def remove(self, replica_id: str) -> None:
        with self._lock:
            self._replicas.pop(replica_id, None)
            self._events.append(
                {"event": "replica_removed", "replica": replica_id}
            )

    def client_of(self, replica_id: str):
        with self._lock:
            return self._replicas[replica_id].client

    # --------------------------------------------------------------- probing

    def probe_once(self) -> None:
        """Probe every replica once and run the state machine.  Health
        calls happen OUTSIDE the lock (a slow replica must not stall
        registry readers); state updates re-take it per replica."""
        with self._lock:
            targets = [
                (r.replica_id, r.client) for r in self._replicas.values()
            ]
        timeout = self._config.probe_interval_s
        for replica_id, client in targets:
            ok, draining, degraded, detail = False, False, False, None
            params_dtype = None
            model_version = None
            try:
                failpoints.fire("router.probe", replica=replica_id)
                health = client.healthz(timeout_s=timeout)
                ok = bool(health.get("ok", False))
                draining = bool(health.get("draining", False))
                degraded = bool(health.get("degraded", False))
                params_dtype = health.get("params_dtype")
                model_version = health.get("model_version")
                if degraded:
                    detail = health.get("degraded_reason") or "degraded"
                elif draining:
                    detail = "draining"
            except Exception as e:  # noqa: BLE001 - a failed probe is data
                detail = f"probe failed: {type(e).__name__}: {e}"
            self._note_probe(
                replica_id, ok, draining, degraded, detail,
                params_dtype=params_dtype, model_version=model_version,
            )

    def _note_probe(
        self,
        replica_id: str,
        ok: bool,
        draining: bool,
        degraded: bool,
        detail: Optional[str],
        params_dtype: Optional[str] = None,
        model_version: Optional[str] = None,
    ) -> None:
        now = self._clock()
        with self._lock:
            rep = self._replicas.get(replica_id)
            if rep is None:
                return  # removed while we probed
            rep.probes += 1
            rep.detail = detail
            if params_dtype is not None:
                # keep the last reported dtype across failed probes — a
                # dead replica's residency does not change by dying
                rep.params_dtype = str(params_dtype)
            if model_version is not None:
                # same rule: the last reported version sticks until a
                # successful probe reports a different one
                rep.model_version = str(model_version)
            if not ok:
                rep.failed_probes += 1
                rep.consecutive_ok = 0
                # the lease is NOT renewed; staleness below may kill it
            elif draining or degraded:
                # alive (lease renews) but must leave rotation; the way
                # back is the same rejoin_probes gate as a dead replica
                rep.last_ok = now
                rep.consecutive_ok = 0
                if rep.state != DRAINING:
                    self._events.append(
                        {
                            "event": "replica_draining",
                            "replica": replica_id,
                            "detail": detail,
                        }
                    )
                rep.state = DRAINING
            else:
                rep.last_ok = now
                rep.consecutive_ok += 1
                if (
                    rep.state != HEALTHY
                    and not rep.held
                    and rep.consecutive_ok >= self._config.rejoin_probes
                ):
                    self._events.append(
                        {
                            "event": "replica_joined",
                            "replica": replica_id,
                            "from": rep.state,
                        }
                    )
                    rep.state = HEALTHY
            self._expire_locked(rep, now)

    def _expire_locked(self, rep: Replica, now: float) -> None:
        # lock held: lease staleness — the self-healing trigger
        if (
            rep.state != DEAD
            and now - rep.last_ok >= self._config.lease_timeout_s
        ):
            self._events.append(
                {
                    "event": "replica_lease_expired",
                    "replica": rep.replica_id,
                    "from": rep.state,
                }
            )
            rep.state = DEAD
            rep.consecutive_ok = 0

    # ------------------------------------------------------------- rollout

    def hold(self, replica_id: str, reason: Optional[str] = None) -> None:
        """Park a replica in DRAINING under a registry-side hold (the
        rollout controller's drain step). The replica keeps probing
        healthy — its lease renews as usual, DRAINING keeps the lease —
        but it cannot be promoted back to HEALTHY until :meth:`release`,
        however many clean probes it accumulates."""
        with self._lock:
            rep = self._replicas.get(replica_id)
            if rep is None:
                raise KeyError(f"unknown replica {replica_id!r}")
            if rep.held:
                return
            rep.held = True
            rep.consecutive_ok = 0
            rep.detail = reason or "held for rollout"
            if rep.state == HEALTHY:
                rep.state = DRAINING
            self._events.append(
                {
                    "event": "replica_held",
                    "replica": replica_id,
                    "reason": reason,
                }
            )

    def release(self, replica_id: str) -> None:
        """Lift a rollout hold. The replica does NOT re-enter rotation
        here: its consecutive-OK streak restarts, so it must pass the
        same ``rejoin_probes`` gate as any recovering replica — now at
        whatever version it reports post-swap."""
        with self._lock:
            rep = self._replicas.get(replica_id)
            if rep is None:
                raise KeyError(f"unknown replica {replica_id!r}")
            if not rep.held:
                return
            rep.held = False
            rep.consecutive_ok = 0
            self._events.append(
                {"event": "replica_released", "replica": replica_id}
            )

    def model_version_of(self, replica_id: str) -> Optional[str]:
        with self._lock:
            return self._replicas[replica_id].model_version

    def model_versions(self) -> Dict[str, Optional[str]]:
        """``replica_id -> last reported model version`` — the fleet's
        version-skew view during a rolling upgrade."""
        with self._lock:
            return {
                rep.replica_id: rep.model_version
                for rep in self._replicas.values()
            }

    # ---------------------------------------------------------------- reads

    def in_rotation(self, role: str = SERVING) -> List[str]:
        """Replica ids eligible for traffic, sorted for determinism.
        Applies the lease-staleness check inline, so a stalled prober
        thread cannot keep a dead replica in rotation."""
        now = self._clock()
        with self._lock:
            out = []
            for rep in self._replicas.values():
                self._expire_locked(rep, now)
                if rep.role == role and rep.state == HEALTHY:
                    out.append(rep.replica_id)
            return sorted(out)

    def state_of(self, replica_id: str) -> str:
        with self._lock:
            return self._replicas[replica_id].state

    def role_of(self, replica_id: str) -> str:
        with self._lock:
            return self._replicas[replica_id].role

    def set_role(
        self, replica_id: str, role: str, reason: Optional[str] = None
    ) -> None:
        """Re-role a replica in place (the canary auto-demote hook:
        an alarming canary drops back to plain serving traffic without
        leaving rotation). Records a ``replica_role_changed`` event."""
        if role not in (SERVING, CANARY, SHADOW):
            raise ValueError(f"unknown replica role {role!r}")
        with self._lock:
            rep = self._replicas.get(replica_id)
            if rep is None or rep.role == role:
                return
            self._events.append(
                {
                    "event": "replica_role_changed",
                    "replica": replica_id,
                    "from": rep.role,
                    "to": role,
                    "reason": reason,
                }
            )
            rep.role = role

    def events(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._events)

    def snapshot(self) -> Dict[str, Any]:
        """Per-replica gauges for /stats and `frcnn telemetry`."""
        with self._lock:
            return {
                rep.replica_id: {
                    "role": rep.role,
                    "state": rep.state,
                    "probes": rep.probes,
                    "failed_probes": rep.failed_probes,
                    "consecutive_ok": rep.consecutive_ok,
                    "lease_age_s": round(self._clock() - rep.last_ok, 3),
                    "detail": rep.detail,
                    "params_dtype": rep.params_dtype,
                    "model_version": rep.model_version,
                    "held": rep.held,
                }
                for rep in self._replicas.values()
            }


class Prober:
    """Periodic ``probe_once`` driver.

    Non-daemon with an Event-based stop + join: the thread does no
    durable writes, but the fleet contract is that every service thread
    dies cleanly on shutdown rather than being reaped mid-anything at
    interpreter exit.  ``Event.wait(interval)`` paces the loop, so
    ``stop()`` interrupts a sleeping prober immediately.
    """

    def __init__(
        self,
        registry: ReplicaRegistry,
        interval_s: float,
        name: str = "fleet-prober",
    ) -> None:
        if interval_s <= 0:
            raise ValueError(f"interval_s must be > 0, got {interval_s}")
        self._registry = registry
        self._interval_s = interval_s
        self._stop_event = threading.Event()
        self._thread = threading.Thread(target=self._run, name=name)

    def start(self) -> "Prober":
        self._thread.start()
        return self

    def _run(self) -> None:
        # probe immediately on start (a JOINING fleet should not wait a
        # full interval to admit its first replica), then on the cadence
        while True:
            self._registry.probe_once()
            if self._stop_event.wait(self._interval_s):
                return

    def stop(self, join_timeout: float = 10.0) -> None:
        self._stop_event.set()
        if self._thread.is_alive():
            self._thread.join(timeout=join_timeout)

    def __enter__(self) -> "Prober":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
