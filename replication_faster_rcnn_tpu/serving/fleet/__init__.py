"""Self-healing multi-replica serving fleet.

A front router (`frcnn fleet`) over N `frcnn serve` replicas: a
health-checked replica registry with lease-style staleness
(registry.py — the PR 11 elastic-heartbeat discipline applied to
serving), per-replica circuit breakers (breaker.py), and a dispatcher
(router.py) that consistent-hashes requests over (content-hash, bucket),
answers duplicate images from a content-hash result cache, fails over
mid-request deaths, hedges tail latency after a p99-derived delay, and
runs canary/shadow traffic splits.  Clients (client.py) abstract the
replica transport — in-process engines for tests/benchmarks, HTTP for
real fleets — and server.py is the stdlib HTTP front.  Deterministic
drills enter through the ``router.dispatch``/``router.probe`` failpoint
sites (`frcnn chaos --smoke` fleet_router leg, benchmarks/
fleet_profile.py).
"""

from replication_faster_rcnn_tpu.serving.fleet.breaker import CircuitBreaker
from replication_faster_rcnn_tpu.serving.fleet.client import (
    HTTPReplicaClient,
    LocalReplicaClient,
    ReplicaDown,
    engine_client,
)
from replication_faster_rcnn_tpu.serving.fleet.registry import (
    Prober,
    Replica,
    ReplicaRegistry,
)
from replication_faster_rcnn_tpu.serving.fleet.router import (
    FleetRouter,
    FleetUnavailable,
    HashRing,
)
from replication_faster_rcnn_tpu.serving.fleet.server import make_fleet_server

__all__ = [
    "CircuitBreaker",
    "FleetRouter",
    "FleetUnavailable",
    "HTTPReplicaClient",
    "HashRing",
    "LocalReplicaClient",
    "Prober",
    "Replica",
    "ReplicaDown",
    "ReplicaRegistry",
    "engine_client",
    "make_fleet_server",
]
