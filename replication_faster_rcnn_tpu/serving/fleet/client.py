"""Replica transport abstraction for the fleet router.

The router never talks HTTP (or engines) directly — it sees a client
with exactly two calls:

* ``predict(payload, timeout_s)`` — one request, one result (any
  JSON-able object); raises :class:`ReplicaDown` when the replica is
  unreachable, ``TimeoutError`` when it exceeds the deadline, anything
  else for a request-level failure.
* ``healthz(timeout_s)`` — the replica's /healthz dict (must carry
  ``ok``; ``degraded``/``draining`` are honored when present); raises
  on an unreachable replica.

:class:`LocalReplicaClient` wraps plain callables and adds a
``kill()``/``revive()`` switch — the process-death stand-in the chaos
leg and fleet_profile benchmark flip via the router's kill hook
(``router.dispatch`` drop faults), so "replica dies mid-request" is a
deterministic in-process event.  :func:`engine_client` binds one to a
live :class:`~replication_faster_rcnn_tpu.serving.engine.InferenceEngine`.
:class:`HTTPReplicaClient` is the real-fleet transport against
``frcnn serve`` replicas (stdlib urllib, no new dependencies).
"""

from __future__ import annotations

import json
import threading
import urllib.error
import urllib.request
from typing import Any, Callable, Dict, Optional

from replication_faster_rcnn_tpu.telemetry import tracecontext

__all__ = [
    "HTTPReplicaClient",
    "LocalReplicaClient",
    "ReplicaDown",
    "engine_client",
]


class ReplicaDown(ConnectionError):
    """The replica is unreachable (dead process, refused connection) —
    the failure mode failover and lease-staleness exist for."""


class LocalReplicaClient:
    """In-process replica: ``predict_fn(payload) -> result`` plus an
    optional ``health_fn() -> dict``.  ``kill()`` makes every call raise
    :class:`ReplicaDown` until ``revive()`` — a dead process, minus the
    process."""

    def __init__(
        self,
        replica_id: str,
        predict_fn: Callable[[Any], Any],
        health_fn: Optional[Callable[[], Dict[str, Any]]] = None,
        swap_fn: Optional[Callable[[str], Any]] = None,
    ) -> None:
        self.replica_id = replica_id
        self._predict_fn = predict_fn
        self._health_fn = health_fn
        self._swap_fn = swap_fn
        # flipped by the router's kill hook (dispatch threads) and by
        # test/benchmark control code — one lock covers the switch
        self._lock = threading.Lock()
        self._killed = False

    def kill(self) -> None:
        with self._lock:
            self._killed = True

    def revive(self) -> None:
        with self._lock:
            self._killed = False

    @property
    def killed(self) -> bool:
        with self._lock:
            return self._killed

    def _check_alive(self) -> None:
        if self.killed:
            raise ReplicaDown(f"replica {self.replica_id!r} is down")

    def predict(self, payload: Any, timeout_s: float) -> Any:
        self._check_alive()
        return self._predict_fn(payload)

    def healthz(self, timeout_s: float) -> Dict[str, Any]:
        self._check_alive()
        if self._health_fn is None:
            return {"ok": True}
        return self._health_fn()

    def swap(self, version: str, timeout_s: float = 30.0) -> Any:
        """Hot-swap the replica's weights to ``version`` (the rollout
        controller's per-replica RPC). Raises on a replica with no swap
        path — the controller treats that as a failed wave."""
        self._check_alive()
        if self._swap_fn is None:
            raise RuntimeError(
                f"replica {self.replica_id!r} has no swap endpoint"
            )
        return self._swap_fn(str(version))


def engine_client(
    replica_id: str, engine, loader: Optional[Callable[[str], Any]] = None
) -> LocalReplicaClient:
    """A :class:`LocalReplicaClient` over a live InferenceEngine: the
    payload is an image array (the ``engine.submit`` contract), the
    health dict mirrors what server.py's /healthz reports. ``loader``
    maps a model version string to inference variables; when given, the
    client supports ``swap()`` via ``engine.swap_params``."""

    def _predict(payload):
        # bounded end-to-end: admission may block briefly, the result
        # wait is the engine's own request timeout discipline
        fut = engine.submit(payload)
        ttl = engine.config.serving.request_timeout_s
        return fut.result(timeout=ttl if ttl > 0 else None)

    def _health():
        return {
            "ok": True,
            "degraded": engine.degraded,
            "degraded_reason": engine.degraded_reason,
            "uptime_s": engine.uptime_s(),
            "bucket_queue_depths": engine.bucket_queue_depths(),
            "params_dtype": engine.params_dtype,
            "params_bytes": engine.params_bytes,
            "model_version": engine.model_version,
        }

    swap_fn = None
    if loader is not None:
        def swap_fn(version):
            return engine.swap_params(loader(version), version)

    return LocalReplicaClient(replica_id, _predict, _health, swap_fn=swap_fn)


class HTTPReplicaClient:
    """Transport to one ``frcnn serve`` replica.  The payload is an
    image path; the result is that path's detection list from the
    replica's POST /predict response."""

    def __init__(self, replica_id: str, base_url: str) -> None:
        self.replica_id = replica_id
        self.base_url = base_url.rstrip("/")

    def predict(self, payload: Any, timeout_s: float) -> Any:
        body = json.dumps({"paths": [str(payload)]}).encode()
        headers = {"Content-Type": "application/json"}
        # the router binds the attempt's trace context on this thread
        # before calling predict; inject it as the W3C traceparent header
        # so the replica's hop spans join the same trace
        trace = tracecontext.current_trace()
        if trace is not None:
            headers[tracecontext.TRACEPARENT_HEADER] = trace.to_traceparent()
        req = urllib.request.Request(
            f"{self.base_url}/predict",
            data=body,
            headers=headers,
        )
        try:
            with urllib.request.urlopen(req, timeout=timeout_s) as resp:
                out = json.loads(resp.read())
        except urllib.error.HTTPError as e:
            detail = e.read().decode(errors="replace")[:200]
            raise RuntimeError(
                f"replica {self.replica_id!r} returned {e.code}: {detail}"
            ) from e
        except urllib.error.URLError as e:
            if isinstance(getattr(e, "reason", None), TimeoutError):
                raise TimeoutError(
                    f"replica {self.replica_id!r} predict timed out"
                ) from e
            raise ReplicaDown(
                f"replica {self.replica_id!r} unreachable: {e.reason}"
            ) from e
        except TimeoutError as e:  # socket.timeout surfaced directly
            raise TimeoutError(
                f"replica {self.replica_id!r} predict timed out"
            ) from e
        dets = out.get("detections", {})
        if str(payload) not in dets:
            err = out.get("errors", {}).get(str(payload), "no result")
            raise RuntimeError(
                f"replica {self.replica_id!r} failed {payload!r}: {err}"
            )
        return dets[str(payload)]

    def healthz(self, timeout_s: float) -> Dict[str, Any]:
        try:
            with urllib.request.urlopen(
                f"{self.base_url}/healthz", timeout=timeout_s
            ) as resp:
                return json.loads(resp.read())
        except (urllib.error.URLError, TimeoutError, OSError) as e:
            raise ReplicaDown(
                f"replica {self.replica_id!r} healthz unreachable: {e}"
            ) from e

    def swap(self, version: str, timeout_s: float = 30.0) -> Any:
        """POST /swap — ask the replica to hot-swap to ``version``."""
        body = json.dumps({"version": str(version)}).encode()
        req = urllib.request.Request(
            f"{self.base_url}/swap",
            data=body,
            headers={"Content-Type": "application/json"},
        )
        try:
            with urllib.request.urlopen(req, timeout=timeout_s) as resp:
                return json.loads(resp.read())
        except urllib.error.HTTPError as e:
            detail = e.read().decode(errors="replace")[:200]
            raise RuntimeError(
                f"replica {self.replica_id!r} swap returned {e.code}: "
                f"{detail}"
            ) from e
        except (urllib.error.URLError, TimeoutError, OSError) as e:
            raise ReplicaDown(
                f"replica {self.replica_id!r} swap unreachable: {e}"
            ) from e
