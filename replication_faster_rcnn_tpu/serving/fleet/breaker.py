"""Per-replica circuit breaker — fail fast at the router instead of
queueing requests behind a sick replica.

The classic three-state machine:

* **CLOSED** — requests flow; ``threshold`` consecutive failures open
  the breaker (a single success resets the streak, mirroring the
  engine's degraded 3-strike discipline).
* **OPEN** — every request is refused locally for ``cooldown_s``; the
  replica gets zero traffic while it restarts/recovers, and the
  router's failover path never waits on it.
* **HALF_OPEN** — after the cooldown, exactly ONE trial request is let
  through; success closes the breaker, failure re-opens it for another
  cooldown. One probe, not a thundering herd.

``allow()`` is the admission question and CLAIMS the half-open trial
slot (first caller after cooldown gets True, concurrent callers get
False) — callers must report the outcome via ``record_success`` /
``record_failure`` or the trial slot stays spent until the next
cooldown lapses.  All transitions are under one lock with an injectable
clock, so tests and the chaos leg drive the timeline deterministically.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict

__all__ = ["CLOSED", "HALF_OPEN", "OPEN", "CircuitBreaker"]

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"


class CircuitBreaker:
    """Consecutive-failure breaker with half-open probe recovery."""

    def __init__(
        self,
        threshold: int = 3,
        cooldown_s: float = 1.0,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        if threshold < 1:
            raise ValueError(f"threshold must be >= 1, got {threshold}")
        if cooldown_s <= 0:
            raise ValueError(f"cooldown_s must be > 0, got {cooldown_s}")
        self._threshold = threshold
        self._cooldown_s = cooldown_s
        self._clock = clock
        self._lock = threading.Lock()
        self._state = CLOSED
        self._failures = 0  # consecutive, while CLOSED
        self._opened_at = 0.0
        self._trial_inflight = False  # HALF_OPEN probe slot claimed
        self._opens = 0  # lifetime CLOSED/HALF_OPEN -> OPEN transitions

    @property
    def state(self) -> str:
        with self._lock:
            return self._peek_state()

    def _peek_state(self) -> str:
        # lock held. OPEN lazily decays to HALF_OPEN once the cooldown
        # elapses — no timer thread, the next caller observes it.
        if (
            self._state == OPEN
            and self._clock() - self._opened_at >= self._cooldown_s
        ):
            self._state = HALF_OPEN
            self._trial_inflight = False
        return self._state

    def allow(self) -> bool:
        """May a request go to this replica right now?  In HALF_OPEN this
        hands out the single trial slot."""
        with self._lock:
            state = self._peek_state()
            if state == CLOSED:
                return True
            if state == HALF_OPEN and not self._trial_inflight:
                self._trial_inflight = True
                return True
            return False

    def record_success(self) -> None:
        with self._lock:
            self._peek_state()
            self._state = CLOSED
            self._failures = 0
            self._trial_inflight = False

    def record_failure(self) -> None:
        with self._lock:
            state = self._peek_state()
            if state == HALF_OPEN:
                # failed trial: straight back to OPEN, cooldown restarts
                self._state = OPEN
                self._opened_at = self._clock()
                self._trial_inflight = False
                self._opens += 1
                return
            if state == OPEN:
                return  # refused traffic can't deepen the outage
            self._failures += 1
            if self._failures >= self._threshold:
                self._state = OPEN
                self._opened_at = self._clock()
                self._opens += 1

    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            return {
                "state": self._peek_state(),
                "consecutive_failures": self._failures,
                "opens": self._opens,
            }
