"""Bucketed AOT inference engine with continuous micro-batching.

The serving tier's cost model is the Fast R-CNN argument transplanted:
per-request cost = (per-dispatch fixed cost) / (batch size) +
per-image compute. One-shot ``predict_image`` pays the fixed cost alone
on every call; the engine amortizes it by coalescing concurrent
requests into bucket-sized batches against a SMALL, CLOSED set of
pre-compiled programs:

* **Shape buckets.** ``serving.resolutions × serving.batch_sizes``
  programs, built through the ProgramSpec registry
  (`train/warmup.py::build_serving_specs`) so the persistent compile
  cache and `frcnn audit` cover the exact serving programs, and
  AOT-compiled via ``jit(...).lower(args).compile()`` — dispatching the
  returned executable can never retrace or recompile, which is how the
  strict-mode "0 post-warmup recompiles" claim holds by construction.
* **Resident params.** The inference variables are cast to
  ``serving.params_dtype`` (bf16 halves HBM residency; flax modules
  cast to their compute dtype per-layer regardless) and ``device_put``
  once at startup — requests ship images only.
* **Continuous micro-batching.** `batcher.MicroBatcher` (bounded
  producer/consumer, `data/prefetch_device.py` discipline) groups
  requests by bucket and flushes on size or deadline; partial batches
  pad to the smallest compiled batch size and un-pad after, and each
  request's boxes are de-normalized back to its original image
  coordinates before the future resolves.
"""

from __future__ import annotations

import contextlib
import queue as queue_mod
import threading
import time
from concurrent.futures import Future
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

from replication_faster_rcnn_tpu.config import FasterRCNNConfig
from replication_faster_rcnn_tpu.eval.evaluator import Evaluator
from replication_faster_rcnn_tpu.serving.batcher import MicroBatcher
from replication_faster_rcnn_tpu.serving.slo import DeadlineController
from replication_faster_rcnn_tpu.telemetry import spans as tspans
from replication_faster_rcnn_tpu.telemetry import tracecontext
from replication_faster_rcnn_tpu.telemetry.metrics import (
    DEFAULT_LATENCY_BUCKETS_S,
    MetricsRegistry,
)
from replication_faster_rcnn_tpu.telemetry.slo_burn import BurnRateTracker

# consecutive flush failures before /healthz reports degraded; one
# successful flush resets the streak (self-healing, not latched)
DEGRADED_AFTER = 3

# burn-rate alarms need statistics: below this many outcomes in the
# long window the SLO alarm stays quiet (a 3-sample "100% error rate"
# is noise, not an incident) and only the flush-streak path can degrade
SLO_MIN_SAMPLES = 100

# the engine's serving counters, in /stats order; each is a registry
# counter named serve_<key>_total
_STAT_KEYS = (
    "requests",
    "flushes",
    "padded_slots",
    "shed",  # admission-control rejections (queue full)
    "deadline_expired",  # dropped at flush time, never computed
    "timeouts",  # handler-side waits that hit 504
    "flush_errors",  # failed micro-batch dispatches
)

__all__ = [
    "InferenceEngine",
    "OversizedImageError",
    "get_engine",
    "get_evaluator",
    "select_bucket",
]


class OversizedImageError(ValueError):
    """Request larger than every bucket under ``serving.oversize="reject"``."""


def _plain_dicts(tree: Any) -> Any:
    """Recursively coerce Mapping containers (FrozenDict from some
    restore paths) to plain dicts — the `quant/apply.py` walkers key on
    ``dict``."""
    from collections.abc import Mapping

    if isinstance(tree, Mapping):
        return {k: _plain_dicts(v) for k, v in tree.items()}
    return tree


def _batch_target(variables: Any):
    """Placement for a flush batch next to ``variables``: replicated over
    the resident tree's mesh when the mp serving layout sharded it (a
    plain single-device put would put the batch on a device set disjoint
    from the params), else None (default device)."""
    for leaf in jax.tree_util.tree_leaves(variables):
        sharding = getattr(leaf, "sharding", None)
        mesh = getattr(sharding, "mesh", None)
        if mesh is not None and getattr(sharding, "num_devices", 1) > 1:
            from jax.sharding import NamedSharding, PartitionSpec

            return NamedSharding(mesh, PartitionSpec())
    return None


def select_bucket(
    resolutions: Sequence[Tuple[int, int]],
    orig_h: int,
    orig_w: int,
    oversize: str = "downscale",
) -> Tuple[int, int]:
    """The smallest bucket that contains (orig_h, orig_w) — upscaling to
    a snug bucket beats downscaling detail away in a big one. Images
    bigger than every bucket follow the oversize policy: route to the
    largest bucket (lossy downscale) or refuse."""
    ordered = sorted(resolutions, key=lambda r: (r[0] * r[1], r))
    if not ordered:
        raise ValueError("no serving resolutions configured")
    for h, w in ordered:
        if orig_h <= h and orig_w <= w:
            return (h, w)
    if oversize == "reject":
        raise OversizedImageError(
            f"image {orig_h}x{orig_w} exceeds every serving bucket "
            f"{list(ordered)} and serving.oversize='reject'"
        )
    return ordered[-1]


class InferenceEngine:
    """AOT-compiled, micro-batched detector serving for one (config,
    model, variables) triple.

    ``warmup=True`` compiles every bucket program at construction (the
    `frcnn serve` startup contract); otherwise programs compile lazily
    on each bucket's first flush — right for one-shot ``frcnn predict``,
    which should pay for exactly the one program it uses.
    """

    def __init__(
        self,
        config: FasterRCNNConfig,
        model=None,
        variables: Any = None,
        warmup: bool = False,
        artifact_path: Optional[str] = None,
        model_version: str = "0",
    ) -> None:
        from replication_faster_rcnn_tpu.models.faster_rcnn import FasterRCNN
        from replication_faster_rcnn_tpu.train.warmup import (
            build_serving_specs,
            serve_program_name,
        )

        if variables is None:
            raise ValueError("InferenceEngine requires inference variables")
        self.config = config
        self.model = model if model is not None else FasterRCNN(config)
        self.buckets = config.serving.bucket_resolutions(config.data.image_size)
        self.batch_sizes = tuple(sorted(set(config.serving.batch_sizes)))
        self.params_dtype = config.serving.params_dtype
        self.quant_artifact_path: Optional[str] = None

        if self.params_dtype == "int8":
            # Quantized residency: the sidecar artifact (CRC-verified;
            # `frcnn quantize` writes it) drives per-layer int8 vs bf16,
            # the resident tree is the quantized one (weights + scales,
            # ~4x smaller than f32), and every bucket dispatches its
            # ``serve_*__int8`` twin program — which reconstructs bf16
            # weights in-program through the ops backend seam and runs
            # the head cls/reg kernels as true int8 GEMMs.
            from replication_faster_rcnn_tpu.quant import (
                default_artifact_path,
                load_artifact,
                quantize_variables,
            )
            from replication_faster_rcnn_tpu.train.warmup import (
                build_int8_program_specs,
                int8_program_name,
            )

            self.quant_artifact_path = artifact_path or default_artifact_path(
                config
            )
            artifact = load_artifact(self.quant_artifact_path)
            self._specs = build_int8_program_specs(
                config, model=self.model, artifact=artifact
            )
            self._serve_name = lambda h, w, n: int8_program_name(
                serve_program_name(h, w, n)
            )
        else:
            self._specs = build_serving_specs(config, model=self.model)
            self._serve_name = serve_program_name

        # Versioned residency: `_resident` maps model_version -> the
        # device-resident tree for that version, and `model_version`
        # names the version new admissions bind to. `swap_params` stages
        # a second buffer here and flips the pointer — the
        # AsyncCheckpointWriter snapshot discipline in reverse: instead
        # of snapshotting params before the step mutates them, serving
        # pins each micro-batch to the params it was admitted under.
        self.model_version = str(model_version)
        self._version_lock = threading.RLock()
        self._resident: Dict[str, Any] = {
            self.model_version: self._build_resident(_plain_dicts(variables))
        }
        # what actually sits on the device for the CURRENT version
        # (weights + scales in int8 mode) — the /stats `params_bytes`
        # contract
        self.params_bytes = int(
            sum(
                x.nbytes
                for x in jax.tree_util.tree_leaves(
                    self._resident[self.model_version]
                )
            )
        )

        self._programs: Dict[str, Any] = {}
        self._compile_lock = threading.Lock()
        self.compile_seconds: Dict[str, float] = {}
        # optional strict-mode gate (analysis/strict.py), same hook as
        # Evaluator: when set, every flush dispatch runs under its
        # per-program warmup/recompile check
        self.strict = None
        # unified metrics core: every serving counter/gauge/histogram
        # lives in the registry; /stats and /metrics render the same
        # instruments so the numbers cannot disagree
        self.metrics = MetricsRegistry()
        self._counters = {
            key: self.metrics.counter(f"serve_{key}_total", help=f"serving {key}")
            for key in _STAT_KEYS
        }
        buckets = config.telemetry.buckets_s() or DEFAULT_LATENCY_BUCKETS_S
        self._queue_wait_hist = self.metrics.histogram(
            "serve_queue_wait_seconds",
            help="micro-batch queue wait per request",
            buckets=buckets,
        )
        self._flush_hist = self.metrics.histogram(
            "serve_flush_seconds",
            help="micro-batch dispatch latency per flush",
            buckets=buckets,
        )
        self.metrics.register_collector(self._collect_gauges)
        # SLO burn-rate over request outcomes (telemetry/slo_burn.py):
        # the alarm is a second path into `degraded`, statistically gated
        self.slo = BurnRateTracker(
            availability_target=config.fleet.slo_availability_target,
            latency_target_s=config.fleet.slo_latency_target_ms / 1000.0,
            short_window_s=config.fleet.slo_short_window_s,
            long_window_s=config.fleet.slo_long_window_s,
        )
        # degraded-streak state, written by the flush worker and handler
        # threads, read by /healthz — one lock covers it
        self._stats_lock = threading.Lock()
        self._consecutive_flush_errors = 0
        self._last_flush_error: Optional[str] = None
        self._start_time = time.monotonic()
        if warmup:
            for h, w in self.buckets:
                for n in self.batch_sizes:
                    self._program(self._serve_name(h, w, n))
        # SLO-driven deadlines (serving.adaptive_delay): the controller
        # owns per-bucket max_delay and learns from the batcher's flush
        # wait stats; otherwise the static max_delay_ms knob applies.
        self.deadline_controller: Optional[DeadlineController] = None
        if config.serving.adaptive_delay:
            self.deadline_controller = DeadlineController.from_config(
                config.serving, max_batch=lambda key: self.batch_sizes[-1]
            )
        # batcher keys are (model_version, bucket): the admission-time
        # version is part of the flush key, so a micro-batch can only
        # ever contain one version — zero version-mixed batches holds by
        # construction, and a request admitted before a swap is answered
        # entirely by the version it was admitted under
        self._batcher = MicroBatcher(
            self._process_bucket,
            max_batch=lambda key: self.batch_sizes[-1],
            max_delay_s=(
                (lambda key: self.deadline_controller.delay_s(key[1]))
                if self.deadline_controller is not None
                else config.serving.max_delay_ms / 1000.0
            ),
            depth=config.serving.queue_depth,
            name="serving-micro-batcher",
            on_expired=self._note_expired,
            on_flush_result=self._note_flush,
            on_flush_stats=self._note_flush_stats,
        )

    # ---------------------------------------------------- overload accounting

    def _note_expired(self, n: int) -> None:
        self._counters["deadline_expired"].inc(n)
        for _ in range(n):
            self.slo.record(False)

    def _note_flush(self, ok: bool) -> None:
        if not ok:
            self._counters["flush_errors"].inc()
        with self._stats_lock:
            if ok:
                self._consecutive_flush_errors = 0
            else:
                self._consecutive_flush_errors += 1

    def _note_flush_stats(self, key, waits_s) -> None:
        for w in waits_s:
            self._queue_wait_hist.observe(w)
        if self.deadline_controller is not None:
            # the controller learns per BUCKET — strip the version so a
            # swap doesn't reset the learned deadlines
            self.deadline_controller.on_flush(key[1], waits_s)

    def _collect_gauges(self) -> None:
        self.metrics.gauge(
            "serve_queue_depth", help="requests waiting in the batch queue"
        ).set(self.queue_depth())
        self.metrics.gauge(
            "serve_params_bytes",
            help="bytes of the device-resident model (weights + scales)",
            params_dtype=self.params_dtype,
        ).set(self.params_bytes)
        self.metrics.gauge(
            "serve_uptime_seconds", help="seconds since engine construction"
        ).set(self.uptime_s())
        # info gauge: the current version's series reads 1, a staged /
        # draining prior version's reads 0 (retired series stay at 0)
        with self._version_lock:
            versions = {v: int(v == self.model_version) for v in self._resident}
        for v, live in versions.items():
            self.metrics.gauge(
                "serve_model_version",
                help="device-resident model versions (1 = serving now)",
                model_version=v,
            ).set(live)
        for bucket, n in self.bucket_queue_depths().items():
            self.metrics.gauge(
                "serve_bucket_queue_depth",
                help="submitted-but-unflushed requests per bucket",
                bucket=bucket,
            ).set(n)

    @property
    def stats(self) -> Dict[str, int]:
        """The serving counters as a plain dict (the historical ``/stats``
        ``stats`` block) — a registry snapshot, not mutable state."""
        out = {k: 0 for k in _STAT_KEYS}
        for name, v in self.metrics.counters_flat().items():
            if (
                name.startswith("serve_")
                and name.endswith("_total")
                and "{" not in name
            ):
                out[name[len("serve_"): -len("_total")]] = int(v)
        return out

    def incr_stat(self, key: str, n: int = 1) -> None:
        """Bump a serving counter (handler threads record their
        504/shed outcomes here; writes land in the metrics registry).
        A handler timeout is an SLO miss, so it burns error budget."""
        counter = self._counters.get(key)
        if counter is None:
            # get-or-create is the registry's (locked) concern; unknown
            # keys become serve_<key>_total like the built-ins
            counter = self.metrics.counter(
                f"serve_{key}_total", help=f"serving {key}"
            )
        counter.inc(n)
        if key == "timeouts":
            for _ in range(n):
                self.slo.record(False)

    def queue_depth(self) -> int:
        """Requests waiting in the micro-batch queue (public accessor —
        /stats must not reach into the engine's internals)."""
        return self._batcher.queue_depth()

    def bucket_queue_depths(self) -> Dict[str, int]:
        """``"HxW" -> submitted-but-unflushed requests`` per bucket (the
        /healthz per-bucket depth gauge), summed across the version axis
        of the batcher key."""
        out: Dict[str, int] = {}
        for (_, (h, w)), n in self._batcher.key_depths().items():
            k = f"{h}x{w}"
            out[k] = out.get(k, 0) + n
        return out

    def resident_versions(self) -> Dict[str, bool]:
        """``version -> is the version new admissions bind to`` for every
        device-resident buffer (current + any not-yet-retired prior)."""
        with self._version_lock:
            return {v: v == self.model_version for v in self._resident}

    def uptime_s(self) -> float:
        """Seconds since engine construction (surfaced in /healthz)."""
        return time.monotonic() - self._start_time

    def _slo_alarm(self) -> bool:
        """The burn-rate alarm, statistically gated: below
        :data:`SLO_MIN_SAMPLES` outcomes in the long window the alarm
        stays quiet regardless of rate."""
        snap = self.slo.snapshot()
        return bool(snap["alarm"]) and snap["samples"]["long"] >= SLO_MIN_SAMPLES

    @property
    def degraded(self) -> bool:
        """True after :data:`DEGRADED_AFTER` consecutive flush failures
        (one successful flush resets it) OR while the SLO burn-rate
        alarm fires on a statistically meaningful window. Surfaced in
        ``/healthz`` so load balancers can route around a sick replica
        without killing it."""
        with self._stats_lock:
            if self._consecutive_flush_errors >= DEGRADED_AFTER:
                return True
        return self._slo_alarm()

    @property
    def degraded_reason(self) -> Optional[str]:
        """Human-readable cause while degraded, ``None`` when healthy —
        what an operator paging on /healthz sees first."""
        with self._stats_lock:
            n = self._consecutive_flush_errors
            last = self._last_flush_error
        if n >= DEGRADED_AFTER:
            reason = f"{n} consecutive micro-batch flush failures"
            if last:
                reason += f" (last: {last})"
            return reason
        if self._slo_alarm():
            rates = self.slo.burn_rates()
            return (
                "SLO burn-rate alarm: burning error budget at "
                f"{rates['short']:.1f}x (5m) / {rates['long']:.1f}x (1h)"
            )
        return None

    # ------------------------------------------------------- versioned params

    def _build_resident(
        self, variables: Any, artifact_path: Optional[str] = None
    ) -> Any:
        """Validate, cast, and upload one version's parameters against
        the engine's compiled abstract signature.

        Cast float leaves to the serving dtype (the same rule
        build_serving_specs applies to the abstract variables, so
        compiled signatures match), canonicalize the checkpoint's tree
        structure to the registry's (dict vs FrozenDict containers
        differ across restore paths; the leaves are what matters), and
        upload explicitly — a strict-mode transfer guard engaged around
        serving never sees this as implicit. int8 mode re-reads the
        CRC-verified sidecar on every call (``artifact_path`` overrides
        the engine's default), so a corrupt sidecar fails HERE — before
        any serving state is touched — never mid-flush.
        """
        if self.params_dtype == "int8":
            from replication_faster_rcnn_tpu.quant import (
                load_artifact,
                quantize_variables,
            )

            path = artifact_path or self.quant_artifact_path
            artifact = load_artifact(path)
            variables = quantize_variables(_plain_dicts(variables), artifact)
        _, abs_args = self._specs[
            self._serve_name(*self.buckets[0], self.batch_sizes[0])
        ].build()
        abs_leaves, abs_treedef = jax.tree_util.tree_flatten(abs_args[0])
        leaves = jax.tree_util.tree_leaves(variables)
        if len(leaves) != len(abs_leaves):
            raise ValueError(
                f"variables have {len(leaves)} leaves; the serving program "
                f"expects {len(abs_leaves)} — wrong model/config for this "
                "checkpoint?"
            )
        cast = [
            leaf
            if np.dtype(getattr(leaf, "dtype", np.float32)) == a.dtype
            else np.asarray(leaf).astype(a.dtype)
            for leaf, a in zip(leaves, abs_leaves)
        ]
        shardings = [getattr(a, "sharding", None) for a in abs_leaves]
        if any(s is not None for s in shardings):
            # the mp serving layout: each leaf goes to the NamedSharding
            # build_serving_specs banked on its abstract twin (params
            # split over the model axis, batch_stats replicated)
            return jax.tree_util.tree_unflatten(
                abs_treedef,
                [
                    jax.device_put(leaf, s)
                    if s is not None
                    else jax.device_put(leaf)
                    for leaf, s in zip(cast, shardings)
                ],
            )
        return jax.device_put(
            jax.tree_util.tree_unflatten(abs_treedef, cast)
        )

    @property
    def _variables(self) -> Any:
        """The CURRENT version's device tree (legacy accessor — flush
        dispatch resolves per-batch via the version in the flush key)."""
        with self._version_lock:
            return self._resident[self.model_version]

    def swap_params(
        self,
        variables: Any,
        version: str,
        artifact_path: Optional[str] = None,
    ) -> str:
        """Hot-swap serving to ``version``; returns the prior version.

        Stages a second device-resident buffer (validated + uploaded
        BEFORE any serving state changes — a bad checkpoint or corrupt
        int8 sidecar raises here and the engine keeps serving the old
        version untouched), then atomically redirects admission under
        the version lock. In-flight micro-batches drain against the
        buffer named by their flush key: the flip lands exactly at a
        micro-batch flush boundary and no request ever crosses it.

        The prior version's buffer stays resident until the NEXT swap
        (instant rollback target); older drained buffers are retired
        then. Programs are version-independent (same shapes/dtypes), so
        a swap never recompiles and banked fingerprints are unaffected.
        """
        version = str(version)
        staged = self._build_resident(
            _plain_dicts(variables), artifact_path=artifact_path
        )
        with self._version_lock:
            prior = self.model_version
            self._resident[version] = staged
            self.model_version = version
            if artifact_path is not None and self.params_dtype == "int8":
                self.quant_artifact_path = artifact_path
            self.params_bytes = int(
                sum(x.nbytes for x in jax.tree_util.tree_leaves(staged))
            )
            # retire drained buffers — never `prior` (rollback target,
            # and its admitted-but-unflushed batches still name it)
            pending = {k[0] for k in self._batcher.key_depths()}
            for v in [
                v
                for v in self._resident
                if v not in (version, prior) and v not in pending
            ]:
                del self._resident[v]
        return prior

    # ------------------------------------------------------------ programs

    def _program(self, name: str):
        """The AOT-compiled executable for a bucket program (compile on
        first use, under the compile lock — flush worker and warmup may
        race)."""
        prog = self._programs.get(name)
        if prog is not None:
            return prog
        with self._compile_lock:
            prog = self._programs.get(name)
            if prog is not None:
                return prog
            import time

            spec = self._specs[name]
            with tspans.current_tracer().span(f"compile/{name}", cat="compile"):
                t0 = time.perf_counter()
                # trace under the config's resolved ops backend so an
                # ops.backend=pallas deployment serves the pallas kernels
                # (and hits the warmup registry's compile cache entries)
                from replication_faster_rcnn_tpu import ops as ops_pkg

                with ops_pkg.backend_scope(ops_pkg.resolve_backend(self.config)):
                    jitted, args = spec.build()
                    prog = jitted.lower(*args).compile()
                self.compile_seconds[name] = round(time.perf_counter() - t0, 3)
            self._programs[name] = prog
            return prog

    def _strict_dispatch(self, program: str):
        if self.strict is None:
            return contextlib.nullcontext()
        # AOT executables expose no jit cache to probe; the harness still
        # counts backend-compile events across the warm dispatch
        return self.strict.dispatch(program, None)

    # ------------------------------------------------------------- requests

    def submit(
        self,
        image: np.ndarray,
        orig_size: Optional[Tuple[int, int]] = None,
        timeout: Optional[float] = None,
    ) -> Future:
        """Enqueue one image; the Future resolves to a detection dict
        (``boxes`` [D,4] in ORIGINAL image coordinates, ``scores``,
        ``classes``, ``valid``).

        uint8 [H,W,3] input of any size is bucket-routed (oversize policy
        applies) and resized+normalized on the caller's thread — the
        worker thread stays a pure dispatch loop. float32 input must
        already match a bucket resolution exactly (it is taken as
        preprocessed, the `data/voc.py::_load_image` contract);
        ``orig_size`` then records the pre-resize size for box
        de-normalization (default: the bucket size itself).
        """
        from replication_faster_rcnn_tpu.data import native_ops

        image = np.asarray(image)
        if image.ndim != 3 or image.shape[-1] != 3:
            raise ValueError(f"expected [H, W, 3] image, got {image.shape}")
        if image.dtype == np.uint8:
            orig_h, orig_w = image.shape[:2]
            bucket = select_bucket(
                self.buckets, orig_h, orig_w, self.config.serving.oversize
            )
            image = native_ops.resize_normalize(
                image,
                bucket,
                self.config.data.pixel_mean,
                self.config.data.pixel_std,
            )
        else:
            bucket = tuple(image.shape[:2])
            if bucket not in set(self.buckets):
                raise ValueError(
                    f"float image shape {image.shape[:2]} matches no serving "
                    f"bucket {list(self.buckets)}; pass uint8 for automatic "
                    "bucket routing"
                )
            orig_h, orig_w = orig_size if orig_size else bucket
        return self._submit(
            bucket,
            (
                np.asarray(image, np.float32),
                int(orig_h),
                int(orig_w),
                tracecontext.current_trace(),
            ),
            timeout,
        )

    def submit_path(self, path: str, timeout: Optional[float] = None) -> Future:
        """Load an image file, route it to its bucket, enqueue it."""
        from PIL import Image

        from replication_faster_rcnn_tpu.data.voc import _load_image

        # size probe without a full decode (PIL reads the header lazily),
        # so the resize in _load_image targets the right bucket directly
        with Image.open(path) as im:
            orig_w, orig_h = im.size
        bucket = select_bucket(
            self.buckets, orig_h, orig_w, self.config.serving.oversize
        )
        image, orig_h, orig_w = _load_image(
            path, bucket, self.config.data.pixel_mean, self.config.data.pixel_std
        )
        return self._submit(
            bucket,
            (image, int(orig_h), int(orig_w), tracecontext.current_trace()),
            timeout,
        )

    def _submit(self, bucket, entry, timeout: Optional[float]) -> Future:
        """Queue one request: ``serving.request_timeout_s`` becomes the
        entry's time-to-live (expired entries are dropped at flush time,
        and the HTTP handler bounds its wait by the same budget), and an
        admission rejection (``queue.Full`` under ``timeout``) is counted
        as shed before it propagates to the caller's 503."""
        ttl = self.config.serving.request_timeout_s
        with self._version_lock:
            # bind the request to the CURRENT version at admission time;
            # the (version, bucket) key pins its whole micro-batch to
            # that version's resident buffer
            key = (self.model_version, bucket)
        try:
            return self._batcher.submit(
                key,
                entry,
                timeout=timeout,
                deadline_s=ttl if ttl > 0 else None,
            )
        except queue_mod.Full:
            self._counters["shed"].inc()
            self.slo.record(False)
            raise

    def predict_paths(self, paths: Sequence[str]) -> List[Dict[str, np.ndarray]]:
        """Submit many paths (they coalesce into micro-batches) and wait."""
        futures = [self.submit_path(p) for p in paths]
        return [f.result() for f in futures]

    # ---------------------------------------------------------------- flush

    def _process_bucket(self, key, items):
        """One micro-batch: pad to the smallest compiled batch size,
        dispatch the bucket's AOT program against the version the batch
        was admitted under, un-pad, de-normalize boxes."""
        version, bucket = key
        with self._version_lock:
            variables = self._resident.get(version)
        if variables is None:
            raise RuntimeError(
                f"model version {version!r} was retired with requests in "
                f"flight (resident: {sorted(self._resident)})"
            )
        try:
            out = self._process_bucket_inner(bucket, items, variables)
            for _ in items:
                self.slo.record(True)
            return out
        except BaseException as e:  # noqa: BLE001 - recorded, then relayed
            # capture the cause for degraded_reason before the batcher
            # relays the exception through the flush's futures
            with self._stats_lock:
                self._last_flush_error = f"{type(e).__name__}: {e}"
            for _ in items:
                self.slo.record(False)
            raise

    def _process_bucket_inner(self, bucket, items, variables):
        # entries are (image, orig_h, orig_w[, trace]); the trace slot is
        # optional so callers that build items by hand keep working
        h, w = bucket
        n = len(items)
        bn = next((b for b in self.batch_sizes if b >= n), self.batch_sizes[-1])
        batch = np.zeros((bn, h, w, 3), np.float32)
        for i, entry in enumerate(items):
            batch[i] = entry[0]
        name = self._serve_name(h, w, bn)
        program = self._program(name)
        tracer = tspans.current_tracer()
        t_dispatch = tracer.now_us()
        t_wall = time.perf_counter()
        with tracer.span(
            "serve/flush", cat="serve", program=name, n=n, padded=bn - n
        ):
            with self._strict_dispatch(name):
                out = program(
                    variables, jax.device_put(batch, _batch_target(variables))
                )
            out = jax.device_get(out)
        flush_s = time.perf_counter() - t_wall
        dur_dispatch = flush_s * 1e6
        self._flush_hist.observe(flush_s)
        self._counters["requests"].inc(n)
        self._counters["flushes"].inc()
        self._counters["padded_slots"].inc(bn - n)
        results = []
        for i, entry in enumerate(items):
            orig_h, orig_w = entry[1], entry[2]
            trace = entry[3] if len(entry) > 3 else None
            if trace is not None and tracer.enabled:
                # the per-request view of this flush: same wall interval,
                # tagged with the request's trace identity so the merged
                # timeline shows WHICH requests shared the dispatch
                tracer.complete(
                    "serve/dispatch",
                    t_dispatch,
                    dur_dispatch,
                    cat="serve",
                    program=name,
                    **trace.span_args(),
                )
            back = np.asarray(
                [orig_h / h, orig_w / w, orig_h / h, orig_w / w], np.float32
            )
            results.append(
                {
                    "boxes": np.asarray(out["boxes"][i]) * back,
                    "scores": np.asarray(out["scores"][i]),
                    "classes": np.asarray(out["classes"][i]),
                    "valid": np.asarray(out["valid"][i]),
                }
            )
        return results

    # ------------------------------------------------------------ lifecycle

    def close(self) -> None:
        self._batcher.close()

    def __enter__(self) -> "InferenceEngine":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


# One-entry caches for the repeated-call CLI/eval paths. The engine is
# keyed by (config, model identity, variables identity): a new checkpoint
# or model instance gets a fresh engine (and the displaced one's worker
# thread is shut down). The Evaluator cache — formerly module state in
# eval/predict.py — lives here too, so serving owns every "hold the
# compiled inference program warm across calls" concern.
_cached_engine: Optional[InferenceEngine] = None
_cached_engine_key = None
_cached_evaluator: Optional[Evaluator] = None
_cached_evaluator_key = None
_cache_lock = threading.Lock()


def get_engine(
    config: FasterRCNNConfig, model, variables: Any, warmup: bool = False
) -> InferenceEngine:
    """The cached engine for (config, model, variables), built on first
    use. Config is value-hashable (frozen dataclass); model and variables
    key by identity."""
    global _cached_engine, _cached_engine_key
    key = (config, id(model), id(variables))
    with _cache_lock:
        if _cached_engine is None or _cached_engine_key != key:
            if _cached_engine is not None:
                _cached_engine.close()
            _cached_engine = InferenceEngine(
                config, model, variables, warmup=warmup
            )
            _cached_engine_key = key
        return _cached_engine


def get_evaluator(config: FasterRCNNConfig, model) -> Evaluator:
    """The cached Evaluator for (config, model), built on first use.
    Config is a frozen dataclass (value-hashable); the model is keyed by
    identity — a new model instance gets a fresh Evaluator."""
    global _cached_evaluator, _cached_evaluator_key
    key = (config, id(model))
    with _cache_lock:
        if _cached_evaluator is None or _cached_evaluator_key != key:
            _cached_evaluator = Evaluator(config, model)
            _cached_evaluator_key = key
        return _cached_evaluator
