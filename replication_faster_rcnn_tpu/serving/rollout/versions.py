"""Version discovery + eligibility over the trainer's manifest feed.

A *version* is a checkpoint step whose sidecar manifest
(train/fault.py, ``ckpt_manifest/v1``) is readable and internally
consistent. Discovery prefers the append-only ``manifests/feed.jsonl``
publication log (publication order survives pruning) and falls back to
scanning ``manifests/*.json``.

Eligibility is the rollout controller's pre-drain gate: everything that
can be checked WITHOUT touching a replica is checked here, because a
validation failure discovered mid-rollout would strand a drained
replica. In particular, an int8 fleet re-reads the quant sidecar
artifact (CRC per scale record) at validation time — a missing or
corrupt sidecar makes the version ineligible before any drain, instead
of blowing up inside ``swap_params`` on a replica that already left
rotation.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Dict, List, Optional

from replication_faster_rcnn_tpu.faultlib import failpoints
from replication_faster_rcnn_tpu.train import fault

__all__ = ["Eligibility", "VersionFeed"]


@dataclasses.dataclass
class Eligibility:
    """One version's pre-drain verdict. ``reasons`` is empty iff
    ``eligible`` — every entry is one human-readable disqualifier."""

    step: int
    eligible: bool
    reasons: List[str]
    manifest: Optional[Dict[str, Any]] = None

    @property
    def version(self) -> str:
        return str(self.step)


class VersionFeed:
    """Discover and validate checkpoint versions under one workdir.

    ``config`` (a FasterRCNNConfig) enables the config-hash and quant-
    sidecar checks; without it only manifest integrity + topology are
    judged. ``artifact_path`` overrides where the int8 sidecar is
    expected (default: the ``frcnn serve`` resolution —
    ``quant.artifact`` if set, else ``<workdir>/quant_artifact.json``).
    """

    def __init__(
        self,
        workdir: str,
        config: Any = None,
        artifact_path: Optional[str] = None,
    ) -> None:
        self.workdir = os.path.abspath(workdir)
        self.config = config
        self.artifact_path = artifact_path

    # ------------------------------------------------------------ discovery

    def _manifest_dir(self) -> str:
        return os.path.join(self.workdir, fault.MANIFEST_DIRNAME)

    def poll(self) -> List[int]:
        """Published steps in publication order (feed.jsonl), with any
        manifests the feed missed (pre-feed checkpoints, torn appends)
        merged in ascending-step order after."""
        seen: List[int] = []
        try:
            with open(fault.feed_path(self.workdir)) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        event = json.loads(line)
                        step = int(event["step"])
                    except (ValueError, KeyError, json.JSONDecodeError):
                        continue  # a torn append is not a version
                    if step not in seen:
                        seen.append(step)
        except OSError:
            pass
        try:
            names = os.listdir(self._manifest_dir())
        except OSError:
            names = []
        scanned = sorted(
            int(n[: -len(".json")])
            for n in names
            if n.endswith(".json") and n[: -len(".json")].isdigit()
        )
        for step in scanned:
            if step not in seen:
                seen.append(step)
        return seen

    # ----------------------------------------------------------- eligibility

    def validate(self, step: int) -> Eligibility:
        """The pre-drain gate for one version; every check that can run
        without touching a replica runs here."""
        step = int(step)
        reasons: List[str] = []
        manifest = fault.load_manifest(self.workdir, step)
        if manifest is None:
            return Eligibility(
                step,
                False,
                [
                    "manifest missing, unreadable, or wrong schema "
                    f"(want {fault.MANIFEST_SCHEMA})"
                ],
            )
        if int(manifest.get("step", -1)) != step:
            reasons.append(
                f"manifest step {manifest.get('step')} != filename step "
                f"{step}"
            )
        leaves = manifest.get("leaves") or {}
        if not leaves:
            reasons.append("manifest has no leaf records")
        elif manifest.get("leaf_count") != len(leaves):
            reasons.append(
                f"leaf_count {manifest.get('leaf_count')} != "
                f"{len(leaves)} leaf records (torn manifest?)"
            )
        for key, rec in leaves.items():
            if not isinstance(rec, dict) or "crc32" not in rec:
                reasons.append(f"leaf {key} has no crc32 record")
                break
        topo = manifest.get("topology")
        if not isinstance(topo, dict) or not topo.get("device_count"):
            reasons.append("manifest has no saving-run topology")
        if failpoints.find_step_dir(
            self.workdir, step, exclude=(fault.MANIFEST_DIRNAME,)
        ) is None:
            reasons.append(
                f"no checkpoint step directory for step {step} "
                "(pruned after publication?)"
            )
        if self.config is not None:
            reasons.extend(self._config_checks(manifest))
        return Eligibility(
            step, not reasons, reasons, manifest=manifest
        )

    def _config_checks(self, manifest: Dict[str, Any]) -> List[str]:
        reasons: List[str] = []
        cfg = self.config
        if getattr(cfg.rollout, "require_config_hash", True):
            want = fault.config_hash(cfg)
            got = manifest.get("config_hash")
            if got is not None and got != want:
                reasons.append(
                    f"config hash {got} != serving config {want} "
                    "(set rollout.require_config_hash=false to allow)"
                )
        if getattr(cfg.serving, "params_dtype", None) == "int8":
            from replication_faster_rcnn_tpu.quant import (
                QuantArtifactError,
                default_artifact_path,
                load_artifact,
            )

            path = self.artifact_path or default_artifact_path(
                cfg, self.workdir
            )
            try:
                load_artifact(path)  # CRC-verifies every scale record
            except QuantArtifactError as e:
                reasons.append(f"int8 quant sidecar rejected: {e}")
        return reasons

    def latest_eligible(
        self, after: Optional[int] = None
    ) -> Optional[Eligibility]:
        """The newest published version that passes :meth:`validate`
        (restricted to steps > ``after`` when given), or ``None``."""
        steps = [
            s
            for s in self.poll()
            if after is None or int(s) > int(after)
        ]
        for step in sorted(steps, reverse=True):
            verdict = self.validate(step)
            if verdict.eligible:
                return verdict
        return None
