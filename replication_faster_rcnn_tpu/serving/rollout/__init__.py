"""Rolling weight rollout: the versioned train→serve control plane.

The trainer's ``workdir/manifests/`` output is the version feed
(train/fault.py writes one CRC-leaf manifest per checkpoint and appends
to ``manifests/feed.jsonl``); this package closes the loop on the
serving side:

* :mod:`versions` — :class:`~versions.VersionFeed` discovers published
  checkpoint versions and validates eligibility (manifest CRC fields +
  topology + config hash + int8 quant sidecar) BEFORE any replica is
  touched.
* :mod:`controller` — :class:`~controller.RolloutController` drives the
  rolling fleet upgrade through the PR 14 registry (hold → swap →
  rejoin → canary → windowed promote/rollback), and
  :class:`~controller.RolloutWatcher` polls the feed and triggers waves.
"""

from replication_faster_rcnn_tpu.serving.rollout.controller import (
    RolloutController,
    RolloutError,
    RolloutWatcher,
    WaveResult,
)
from replication_faster_rcnn_tpu.serving.rollout.versions import (
    Eligibility,
    VersionFeed,
)

__all__ = [
    "Eligibility",
    "RolloutController",
    "RolloutError",
    "RolloutWatcher",
    "VersionFeed",
    "WaveResult",
]
