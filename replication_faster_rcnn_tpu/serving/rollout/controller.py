"""Rolling rollout controller: hold → swap → rejoin → canary → decide.

One *wave* upgrades the fleet to one version, one replica at a time,
through the registry's probe-driven state machine — never around it:

1. **Pre-drain gate.** The version must already be eligible
   (:class:`~replication_faster_rcnn_tpu.serving.rollout.versions.VersionFeed`):
   manifest readable + internally consistent, topology recorded, config
   hash compatible, int8 quant sidecar CRC-clean. Nothing drains for a
   version that could not be served.
2. **Per-replica swap.** ``registry.hold`` parks the replica in
   DRAINING (the lease keeps renewing — DRAINING keeps the lease), its
   queues drain, the ``rollout.swap`` failpoint fires (chaos drills the
   mid-swap kill), then ``client.swap(version)`` flips the engine's
   double-buffered params. ``registry.release`` restarts the
   consecutive-OK streak, so re-admission is the same
   ``fleet.rejoin_probes`` gate every recovering replica passes — and
   the controller additionally requires the replica to *report* the new
   version before calling it converged.
3. **Gated promotion.** The first upgraded replica takes the CANARY
   role on the router's existing deterministic hash slice. Through the
   hold window the controller watches the router's private canary
   burn tracker, the router's own auto-demote (a demoted canary is a
   rollback verdict, never resurrected), and the fleet shadow-diff
   counters; the ``rollout.promote`` failpoint can force the rollback
   path. Promotion rolls the remaining replicas; rollback is a
   first-class REVERSE rollout through the same hold/swap/rejoin steps.

Determinism seams: ``clock``, ``sleep``, and ``probe`` are injectable —
the chaos leg and unit tests drive a fake clock and call
``registry.probe_once`` by hand, so two passes over the same seed
produce identical event logs.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from replication_faster_rcnn_tpu.faultlib import failpoints
from replication_faster_rcnn_tpu.serving.fleet.registry import (
    CANARY,
    HEALTHY,
    SERVING,
)
from replication_faster_rcnn_tpu.telemetry.metrics import MetricsRegistry

__all__ = ["RolloutController", "RolloutError", "RolloutWatcher", "WaveResult"]


class RolloutError(RuntimeError):
    """A wave step failed (swap RPC, rejoin timeout, injected kill)."""


@dataclass
class WaveResult:
    """What one rollout wave did, for callers and the rollout log."""

    version: str
    # promoted | rolled_back | aborted | ineligible | noop
    outcome: str
    reason: Optional[str] = None
    swapped: List[str] = field(default_factory=list)
    rolled_back: List[str] = field(default_factory=list)
    events: List[Dict[str, Any]] = field(default_factory=list)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "version": self.version,
            "outcome": self.outcome,
            "reason": self.reason,
            "swapped": list(self.swapped),
            "rolled_back": list(self.rolled_back),
        }


class RolloutController:
    """Drives rolling weight rollouts over one fleet.

    ``config`` is the full FasterRCNNConfig — the controller reads
    ``config.rollout`` (wave knobs) and ``config.fleet`` (probe cadence
    + rejoin gate). Counters land in ``metrics`` (default: the router's
    registry, so ``frcnn fleet``'s /metrics exposes them).
    """

    def __init__(
        self,
        registry,
        router,
        config,
        feed=None,
        metrics: Optional[MetricsRegistry] = None,
        clock: Callable[[], float] = time.monotonic,
        sleep: Callable[[float], None] = time.sleep,
        probe: Optional[Callable[[], None]] = None,
    ) -> None:
        self._registry = registry
        self._router = router
        self._config = config
        self._feed = feed
        self._clock = clock
        self._sleep = sleep
        self._probe = probe if probe is not None else registry.probe_once
        self.metrics = metrics if metrics is not None else router.metrics
        self._swaps = self.metrics.counter(
            "rollout_swaps_total", help="successful per-replica hot-swaps"
        )
        self._rollbacks = self.metrics.counter(
            "rollout_rollbacks_total", help="per-replica reverse swaps"
        )
        self._promotions = self.metrics.counter(
            "rollout_promotions_total", help="canaries promoted to serving"
        )
        # one wave at a time: the watcher thread and a CLI `--once` may
        # coexist against one fleet
        self._wave_lock = threading.Lock()
        self.events: List[Dict[str, Any]] = []

    # ------------------------------------------------------------- plumbing

    def _note(self, event: str, **kw: Any) -> Dict[str, Any]:
        entry = {"event": event, **kw}
        self.events.append(entry)
        return entry

    def _tick(self) -> None:
        """One probe round: advance (injected) time by the probe cadence
        and run the registry state machine."""
        self._sleep(self._config.fleet.probe_interval_s)
        self._probe()

    def _await(
        self,
        predicate: Callable[[], bool],
        timeout_s: float,
        what: str,
    ) -> None:
        deadline = self._clock() + timeout_s
        while not predicate():
            if self._clock() >= deadline:
                raise RolloutError(f"timed out waiting for {what}")
            self._tick()

    def _converged(self, replica_id: str, version: Optional[str]) -> bool:
        snap = self._registry.snapshot().get(replica_id)
        if snap is None:
            return False
        if snap["state"] != HEALTHY:
            return False
        return version is None or snap["model_version"] == version

    # ------------------------------------------------------------ wave steps

    def _drain(self, replica_id: str) -> None:
        """Wait for the held replica's queued work to flush (bounded) —
        a swap never races admitted-but-unflushed requests for ordering;
        the engine's version-keyed batches make this a latency nicety,
        not a correctness requirement."""
        client = self._registry.client_of(replica_id)

        def _quiet() -> bool:
            try:
                health = client.healthz(
                    timeout_s=self._config.fleet.probe_interval_s
                )
            except Exception:  # noqa: BLE001 - a dead replica is "quiet"
                return True
            depths = health.get("bucket_queue_depths") or {}
            return sum(depths.values()) == 0

        try:
            self._await(
                _quiet, self._config.rollout.drain_timeout_s, "queue drain"
            )
        except RolloutError:
            # drain is best-effort by design (see docstring): proceed,
            # the leftover entries complete on their admission version
            self._note("drain_timeout", replica=replica_id)

    def _swap_replica(self, replica_id: str, version: str) -> None:
        """hold → drain → swap → release → converge-at-version. Raises
        RolloutError mid-way with the replica still HELD — the caller
        owns recovery (it recorded the prior version before calling)."""
        rcfg = self._config.rollout
        self._registry.hold(replica_id, reason=f"rollout to {version}")
        self._note("replica_hold", replica=replica_id, version=version)
        self._tick()  # propagate DRAINING before judging queue depth
        self._drain(replica_id)
        # chaos: a drop here is the mid-swap kill (controller dies/loses
        # the replica between drain and swap); ioerror raises ChaosError
        inj = failpoints.fire(
            "rollout.swap", replica=replica_id, version=version
        )
        if inj is not None and inj.kind == "drop":
            raise RolloutError(
                f"injected mid-swap kill at replica {replica_id!r}"
            )
        try:
            self._registry.client_of(replica_id).swap(
                version, timeout_s=rcfg.swap_timeout_s
            )
        except Exception as e:
            raise RolloutError(
                f"swap RPC failed at {replica_id!r}: "
                f"{type(e).__name__}: {e}"
            ) from e
        self._swaps.inc()
        self._note("replica_swapped", replica=replica_id, version=version)
        self._registry.release(replica_id)
        self._await(
            lambda: self._converged(replica_id, version),
            rcfg.rejoin_timeout_s,
            f"replica {replica_id!r} to rejoin at version {version}",
        )
        self._note("replica_rejoined", replica=replica_id, version=version)

    def _recover_replica(
        self, replica_id: str, prior: Optional[str]
    ) -> None:
        """Reverse one replica to its prior version after a failed step
        (the replica may or may not have applied the new version — the
        reverse swap is idempotent either way), then re-admit it."""
        try:
            if prior is not None:
                self._registry.client_of(replica_id).swap(
                    prior, timeout_s=self._config.rollout.swap_timeout_s
                )
                self._rollbacks.inc()
                self._note(
                    "replica_rolled_back", replica=replica_id, version=prior
                )
        except Exception as e:  # noqa: BLE001 - recovery is best-effort
            self._note(
                "rollback_swap_failed",
                replica=replica_id,
                error=f"{type(e).__name__}: {e}",
            )
        self._registry.release(replica_id)
        try:
            self._await(
                lambda: self._converged(replica_id, prior),
                self._config.rollout.rejoin_timeout_s,
                f"replica {replica_id!r} to reconverge at {prior}",
            )
        except RolloutError:
            self._note("reconverge_timeout", replica=replica_id)

    # --------------------------------------------------------- canary gate

    def _canary_decision(
        self, replica_id: str, version: str, baseline: Dict[str, Any]
    ) -> Optional[str]:
        """Watch the canary through the hold window; return a rollback
        reason, or ``None`` to promote. The router's own auto-demote is
        a rollback verdict — a demoted role is never resurrected."""
        rcfg = self._config.rollout

        def _verdict() -> Optional[str]:
            if self._registry.role_of(replica_id) != CANARY:
                return "router auto-demoted the canary (burn-rate alarm)"
            report = self._router.canary_report(replica_id)
            slo = report["slo"]
            if slo is not None and slo["alarm"]:
                rates = slo["burn_rates"]
                return (
                    "canary slo burn-rate alarm: "
                    f"short={rates['short']:.1f}x long={rates['long']:.1f}x"
                )
            shadow_n = report["shadow_requests"] - baseline["shadow_requests"]
            shadow_d = report["shadow_diffs"] - baseline["shadow_diffs"]
            if (
                shadow_n > 0
                and shadow_d / shadow_n > rcfg.max_shadow_diff_fraction
            ):
                return (
                    f"shadow diff fraction {shadow_d}/{shadow_n} exceeds "
                    f"{rcfg.max_shadow_diff_fraction}"
                )
            return None

        deadline = self._clock() + rcfg.canary_hold_s
        while self._clock() < deadline:
            bad = _verdict()
            if bad is not None:
                return bad
            self._tick()
        bad = _verdict()
        if bad is not None:
            return bad
        # low-traffic guard: promotion (not rollback) needs evidence —
        # give the slice one extra window to accumulate it
        if rcfg.canary_min_requests > 0:
            extra = self._clock() + rcfg.canary_hold_s

            def _enough() -> bool:
                report = self._router.canary_report(replica_id)
                delta = (
                    report["canary_requests"] - baseline["canary_requests"]
                )
                return delta >= rcfg.canary_min_requests

            while not _enough() and self._clock() < extra:
                bad = _verdict()
                if bad is not None:
                    return bad
                self._tick()
            if not _enough():
                self._note(
                    "canary_low_traffic",
                    replica=replica_id,
                    version=version,
                )
        # chaos: the promote decision itself can be killed — drop and
        # ioerror both force the rollback path
        try:
            inj = failpoints.fire(
                "rollout.promote", replica=replica_id, version=version
            )
        except failpoints.ChaosError as e:
            return f"injected promote failure: {e}"
        if inj is not None and inj.kind == "drop":
            return "injected promote failure: dropped"
        return None

    # --------------------------------------------------------------- waves

    def rollout(self, version: str, verdict=None) -> WaveResult:
        """Run one full wave to ``version``. Returns a
        :class:`WaveResult`; never raises for a failed wave — failure IS
        a result (aborted / rolled_back), with the fleet reconverged on
        the prior version."""
        with self._wave_lock:
            result = self._rollout_locked(str(version), verdict)
        self.metrics.counter(
            "rollout_waves_total",
            help="rollout waves by outcome",
            outcome=result.outcome,
        ).inc()
        return result

    def _rollout_locked(self, version: str, verdict) -> WaveResult:
        events_start = len(self.events)

        def _done(outcome: str, **kw: Any) -> WaveResult:
            self._note("wave_done", version=version, outcome=outcome)
            res = WaveResult(version=version, outcome=outcome, **kw)
            res.events = self.events[events_start:]
            return res

        # pre-drain eligibility gate
        if verdict is None and self._feed is not None:
            verdict = self._feed.validate(int(version))
        if verdict is not None and not verdict.eligible:
            self._note(
                "wave_ineligible", version=version, reasons=verdict.reasons
            )
            return _done("ineligible", reason="; ".join(verdict.reasons))
        self._note("wave_started", version=version)

        snap = self._registry.snapshot()
        targets = sorted(
            rid
            for rid, info in snap.items()
            if info["role"] in (SERVING, CANARY)
            and info["model_version"] != version
        )
        if not targets:
            return _done("noop", reason="fleet already at version")

        swapped: List[str] = []
        priors: Dict[str, Optional[str]] = {}

        # ---- first replica: the canary slot
        first = targets[0]
        orig_role = self._registry.role_of(first)
        baseline = self._router.canary_report(first)
        priors[first] = self._registry.model_version_of(first)
        try:
            self._swap_replica(first, version)
        except (RolloutError, failpoints.ChaosError) as e:
            self._note("wave_aborted", version=version, error=str(e))
            self._recover_replica(first, priors.get(first))
            return _done(
                "aborted",
                reason=str(e),
                rolled_back=[first] if priors.get(first) else [],
            )
        swapped.append(first)
        self._registry.set_role(
            first, CANARY, reason=f"rollout {version} canary"
        )
        bad = self._canary_decision(first, version, baseline)
        if bad is not None:
            self._note("wave_rollback", version=version, reason=bad)
            if not self._config.rollout.auto_rollback:
                return _done("aborted", reason=bad, swapped=swapped)
            self._rollback_wave(swapped, priors, first, orig_role)
            return _done(
                "rolled_back", reason=bad, swapped=swapped,
                rolled_back=list(reversed(swapped)),
            )
        self._promotions.inc()
        self._note("canary_promoted", replica=first, version=version)
        self._registry.set_role(
            first, orig_role, reason=f"rollout {version} promoted"
        )

        # ---- remaining replicas, one at a time
        for rid in targets[1:]:
            priors[rid] = self._registry.model_version_of(rid)
            try:
                self._swap_replica(rid, version)
                swapped.append(rid)
            except (RolloutError, failpoints.ChaosError) as e:
                self._note("wave_rollback", version=version, error=str(e))
                self._recover_replica(rid, priors.get(rid))
                if not self._config.rollout.auto_rollback:
                    return _done("aborted", reason=str(e), swapped=swapped)
                self._rollback_wave(swapped, priors, first, orig_role)
                rolled = list(reversed(swapped))
                if priors.get(rid):
                    rolled.insert(0, rid)
                return _done(
                    "rolled_back", reason=str(e), swapped=swapped,
                    rolled_back=rolled,
                )
        return _done("promoted", swapped=swapped)

    def _rollback_wave(
        self,
        swapped: List[str],
        priors: Dict[str, Optional[str]],
        canary: str,
        orig_role: str,
    ) -> None:
        """The reverse rollout: walk the swapped replicas newest-first
        back to their prior versions through the same hold/swap/rejoin
        discipline (best-effort per replica — one stuck replica must
        not stop the others from reverting)."""
        if self._registry.role_of(canary) == CANARY:
            # the canary slice must stop before its weights revert; if
            # the router already demoted it, leave the demotion alone
            self._registry.set_role(
                canary, orig_role, reason="rollout rolled back"
            )
        for rid in reversed(swapped):
            prior = priors.get(rid)
            try:
                self._registry.hold(rid, reason="rollout rollback")
                self._tick()
                self._recover_replica(rid, prior)
            except Exception as e:  # noqa: BLE001 - keep reverting others
                self._note(
                    "rollback_failed",
                    replica=rid,
                    error=f"{type(e).__name__}: {e}",
                )


class RolloutWatcher:
    """Polls a :class:`VersionFeed` and triggers waves on new versions.

    Same thread discipline as the fleet Prober: NON-daemon, Event-paced,
    joined in ``stop()`` — the watcher appends durable rollout records
    (``rollout.jsonl`` under the workdir) and a daemon thread doing
    durable writes is exactly the pattern threadlint's TL006 exists to
    reject, so this thread must die cleanly instead."""

    def __init__(
        self,
        feed,
        controller: RolloutController,
        poll_interval_s: Optional[float] = None,
        log_path: Optional[str] = None,
        name: str = "rollout-watcher",
    ) -> None:
        interval = (
            poll_interval_s
            if poll_interval_s is not None
            else controller._config.rollout.poll_interval_s
        )
        if interval <= 0:
            raise ValueError(f"poll_interval_s must be > 0, got {interval}")
        self._feed = feed
        self._controller = controller
        self._interval_s = interval
        self._log_path = log_path
        self._stop_event = threading.Event()
        self._thread = threading.Thread(target=self._run, name=name)
        self._last_step: Optional[int] = None
        self.results: List[WaveResult] = []

    def start(self) -> "RolloutWatcher":
        self._thread.start()
        return self

    def poll_once(self) -> Optional[WaveResult]:
        """One poll → at most one wave (also the test seam)."""
        verdict = self._feed.latest_eligible(after=self._last_step)
        if verdict is None:
            return None
        self._last_step = verdict.step
        result = self._controller.rollout(verdict.version, verdict=verdict)
        self.results.append(result)
        self._log(result)
        return result

    def _log(self, result: WaveResult) -> None:
        if self._log_path is None:
            return
        import json

        try:
            with open(self._log_path, "a") as f:
                f.write(json.dumps(result.to_dict(), sort_keys=True) + "\n")
        except OSError:  # pragma: no cover - the log is advisory
            pass

    def _run(self) -> None:
        while True:
            try:
                self.poll_once()
            except Exception:  # noqa: BLE001 - a failed poll is survivable
                pass
            if self._stop_event.wait(self._interval_s):
                return

    def stop(self, join_timeout: float = 10.0) -> None:
        self._stop_event.set()
        if self._thread.is_alive():
            self._thread.join(timeout=join_timeout)

    def __enter__(self) -> "RolloutWatcher":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
