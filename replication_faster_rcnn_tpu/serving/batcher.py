"""Continuous micro-batching queue — bounded producer/consumer with
size- and deadline-triggered flushes.

Same discipline as `data/prefetch_device.py::DevicePrefetcher`: a
bounded ``queue.Queue`` between submitters and one worker thread (so the
queue itself is the backpressure — a full queue makes ``submit`` block
or raise instead of buffering unboundedly), a sentinel-driven clean
shutdown that drains everything already accepted, and error
transparency (a failing ``process`` call fails exactly the requests in
that flush, through their futures, and the worker keeps serving).

The worker groups waiting requests by ``key`` (the engine keys by
resolution bucket) and flushes a group when it reaches ``max_batch(key)``
requests OR when its oldest request has waited ``max_delay_s`` — the
classic continuous-batching tradeoff knob between per-request latency
and per-dispatch amortization. ``max_delay_s=0`` degrades to greedy
batching: flush whatever has accumulated the moment the queue idles.
``max_delay_s`` may be a ``key -> seconds`` callable, re-read at every
deadline decision — the seam the SLO-driven deadline controller
(serving/slo.py) tunes per-bucket deadlines through while the worker
runs.
"""

from __future__ import annotations

import queue
import threading
import time
from concurrent.futures import Future
from typing import Any, Callable, Dict, List, Optional, Tuple

from replication_faster_rcnn_tpu.faultlib import failpoints
from replication_faster_rcnn_tpu.telemetry import spans as tspans
from replication_faster_rcnn_tpu.telemetry import tracecontext

__all__ = ["DeadlineExceeded", "MicroBatcher"]

_CLOSE = object()  # shutdown sentinel; queue order guarantees drain


class DeadlineExceeded(TimeoutError):
    """A request's deadline passed while it waited in the queue; it was
    dropped at flush time instead of being dispatched (abandoned work is
    never computed)."""


class MicroBatcher:
    """Coalesce ``submit`` calls into batched ``process`` calls.

    ``process(key, items) -> results`` runs on the worker thread with
    ``len(results) == len(items)``; result ``i`` resolves the future of
    item ``i``. ``max_batch`` is an int or a ``key -> int`` callable.
    """

    def __init__(
        self,
        process: Callable[[Any, List[Any]], List[Any]],
        max_batch,
        max_delay_s: float = 0.01,
        depth: int = 64,
        name: str = "micro-batcher",
        clock: Callable[[], float] = time.monotonic,
        start: bool = True,
        poll_hook: Optional[Callable[[], None]] = None,
        on_expired: Optional[Callable[[int], None]] = None,
        on_flush_result: Optional[Callable[[bool], None]] = None,
        on_flush_stats: Optional[Callable[[Any, List[float]], None]] = None,
    ) -> None:
        """``clock``, ``start`` and ``poll_hook`` are test seams:
        ``clock`` replaces ``time.monotonic`` for deadline math (inject
        scheduler delay without sleeping), ``start=False`` skips the
        worker thread so tests drive :meth:`_service_once` directly, and
        ``poll_hook`` runs at the top of every worker iteration (an
        Event-based rendezvous point — deterministic, no sleep races).

        ``on_expired(n)`` is called on the worker thread each time a
        flush drops ``n`` deadline-expired entries; ``on_flush_result(ok)``
        after every processed flush — the engine's hooks for its shed /
        degraded-health accounting (both must be cheap and non-raising).
        ``on_flush_stats(key, waits_s)`` fires before each dispatched
        flush with every live entry's queue-wait seconds — the gauge feed
        for the SLO deadline controller and /healthz depth reporting."""
        if not callable(max_batch):
            if max_batch < 1:
                raise ValueError(f"max_batch must be >= 1, got {max_batch}")
            _n = int(max_batch)
            max_batch = lambda key: _n  # noqa: E731
        if not callable(max_delay_s):
            if max_delay_s < 0:
                raise ValueError(
                    f"max_delay_s must be >= 0, got {max_delay_s}"
                )
            _d = float(max_delay_s)
            max_delay_s = lambda key: _d  # noqa: E731
        if depth < 1:
            raise ValueError(f"depth must be >= 1, got {depth}")
        self._process = process
        self._max_batch = max_batch
        self._max_delay_s = max_delay_s
        self._clock = clock
        self._poll_hook = poll_hook
        self._queue: "queue.Queue" = queue.Queue(maxsize=depth)
        self._closed = False
        self._on_expired = on_expired
        self._on_flush_result = on_flush_result
        self._on_flush_stats = on_flush_stats
        # worker appends while flush_log snapshots from other threads
        self._log_lock = threading.Lock()
        self._flushes: List[Tuple[Any, int]] = []  # (key, size) history
        self._expired_total = 0  # deadline-dropped entries, ever
        # submitted-but-not-yet-flushed entries per key: incremented by
        # submitter threads, decremented by the worker's flush — both
        # under _log_lock (the /healthz per-bucket depth gauge)
        self._key_depth: Dict[Any, int] = {}
        # worker-loop state; touched by the controlling thread only in
        # the threadless (start=False) test mode.
        # entries: (item, future, submit_time, absolute_deadline|None,
        #           trace_context|None)
        self._pending: Dict[Any, List[Tuple[Any, Future, float, Optional[float], Any]]] = {}
        self._thread: Optional[threading.Thread] = None
        if start:
            self._thread = threading.Thread(
                target=self._run, daemon=True, name=name
            )
            self._thread.start()

    # ------------------------------------------------------------- producer

    def submit(
        self,
        key: Any,
        item: Any,
        timeout: Optional[float] = None,
        deadline_s: Optional[float] = None,
    ) -> Future:
        """Enqueue one request; returns its Future.

        Blocks while the queue is at depth (bounded-queue backpressure);
        with ``timeout`` raises ``queue.Full`` instead of waiting
        forever (``timeout=0`` is pure admission control: accept or shed,
        never wait). ``deadline_s`` is a time-to-live from now: if the
        entry is still queued when its deadline passes, the flush drops
        it with :class:`DeadlineExceeded` instead of computing it.
        Raises ``RuntimeError`` once closed.
        """
        if self._closed:
            raise RuntimeError("MicroBatcher is closed")
        fut: Future = Future()
        now = self._clock()
        deadline = None if deadline_s is None else now + deadline_s
        # the submitter's trace context rides the entry so the worker can
        # attribute the queue-wait hop to the request that paid it
        trace = tracecontext.current_trace()
        self._queue.put((key, item, fut, now, deadline, trace), timeout=timeout)
        with self._log_lock:
            self._key_depth[key] = self._key_depth.get(key, 0) + 1
        return fut

    def close(self, join_timeout: float = 60.0) -> None:
        """Drain-and-stop: everything accepted before close is processed
        (partial groups flush), then the worker exits. Idempotent."""
        if self._closed:
            if self._thread is not None:
                self._thread.join(timeout=join_timeout)
            return
        self._closed = True
        # the sentinel rides the same queue, so FIFO order guarantees the
        # worker sees every accepted request first; put() may need to wait
        # for the worker to free a slot, in a loop that notices worker death
        while True:
            try:
                self._queue.put(_CLOSE, timeout=0.1)
                break
            except queue.Full:
                if self._thread is None:
                    # threadless test mode: make room inline
                    self._service_once(block=False)
                elif not self._thread.is_alive():  # pragma: no cover - crashed
                    break
        if self._thread is not None:
            self._thread.join(timeout=join_timeout)
        else:
            # threadless test mode: run the drain loop to the sentinel
            # (each iteration consumes one queue entry, so this terminates)
            while self._service_once(block=False):
                pass
        # requests that raced past the closed flag after the sentinel: fail
        # them explicitly rather than leaving their futures pending forever
        while True:
            try:
                entry = self._queue.get_nowait()
            except queue.Empty:
                break
            if entry is not _CLOSE:
                entry[2].set_exception(
                    RuntimeError("MicroBatcher closed before processing")
                )
                with self._log_lock:
                    self._key_depth[entry[0]] = (
                        self._key_depth.get(entry[0], 0) - 1
                    )

    def __enter__(self) -> "MicroBatcher":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    @property
    def flush_log(self) -> List[Tuple[Any, int]]:
        """(key, n_requests) per flush, oldest first (introspection/tests)."""
        with self._log_lock:
            return list(self._flushes)

    def queue_depth(self) -> int:
        return self._queue.qsize()

    def key_depths(self) -> Dict[Any, int]:
        """Submitted-but-unflushed entry count per key (queued + grouped
        on the worker) — the per-bucket depth gauge /healthz reports."""
        with self._log_lock:
            return {k: n for k, n in self._key_depth.items() if n > 0}

    def delay_s(self, key: Any) -> float:
        """The currently-effective flush deadline for ``key`` (resolves
        the callable form — introspection for /stats and tests)."""
        return self._max_delay_s(key)

    @property
    def expired_total(self) -> int:
        """Entries dropped at flush time because their deadline passed."""
        with self._log_lock:
            return self._expired_total

    # --------------------------------------------------------------- worker

    def _run(self) -> None:
        while self._service_once(block=True):
            pass

    def _service_once(self, block: bool = True) -> bool:
        """One worker iteration: take at most one queue entry (waiting up
        to the nearest group deadline when ``block``), then flush every
        size-complete or deadline-expired group. Returns False once the
        close sentinel has been processed (pending fully drained).

        The deadline scan runs EVERY iteration, not only when the get
        times out: under a sustained backlog the get always returns an
        entry immediately, and a scan gated on ``queue.Empty`` (the
        original shape of this loop) never runs — one hot key's arrivals
        starve every other key's deadline flush indefinitely.
        """
        if self._poll_hook is not None:
            self._poll_hook()
        # _pending preserves insertion order (dict), so deadline scans
        # see oldest groups first
        pending = self._pending
        timeout = None
        if pending:
            nearest = min(
                group[0][2] + self._max_delay_s(key)
                for key, group in pending.items()
            )
            timeout = max(0.0, nearest - self._clock())
        try:
            if block:
                entry = self._queue.get(timeout=timeout)
            else:
                entry = self._queue.get_nowait()
        except queue.Empty:
            entry = None  # a group's deadline expired (or nothing queued)
        if entry is _CLOSE:
            for key in list(pending):
                self._flush(key, pending)
            return False
        if entry is not None:
            key, item, fut, t0, deadline, trace = entry
            group = pending.setdefault(key, [])
            group.append((item, fut, t0, deadline, trace))
            if len(group) >= self._max_batch(key):
                self._flush(key, pending)
        now = self._clock()
        for key in list(pending):
            group = pending[key]
            if group and now >= group[0][2] + self._max_delay_s(key):
                self._flush(key, pending)
        return True

    def _flush(
        self,
        key: Any,
        pending: Dict[Any, List[Tuple[Any, Future, float, Optional[float], Any]]],
    ) -> None:
        group = pending.pop(key)
        with self._log_lock:
            self._key_depth[key] = self._key_depth.get(key, 0) - len(group)
        # deadline-expired entries are dropped HERE, before any compute:
        # the waiter that owned the request has already timed out, so
        # dispatching its slot would burn accelerator time on abandoned
        # work (and delay the live requests batched behind it)
        now = self._clock()
        live = []
        expired = 0
        for item, fut, t0, deadline, trace in group:
            if deadline is not None and now > deadline:
                expired += 1
                fut.set_exception(
                    DeadlineExceeded(
                        f"request deadline expired after {now - t0:.3f}s in "
                        f"queue (key={key!r}); dropped before dispatch"
                    )
                )
            else:
                live.append((item, fut, t0, deadline, trace))
        if expired:
            with self._log_lock:
                self._expired_total += expired
            if self._on_expired is not None:
                self._on_expired(expired)
        if not live:
            return
        with self._log_lock:
            self._flushes.append((key, len(live)))
        if self._on_flush_stats is not None:
            self._on_flush_stats(key, [now - t0 for _, _, t0, _, _ in live])
        # queue-wait hop spans: the wait started on the submitter's
        # thread and ended here, so the event is emitted retroactively
        # (ts = flush time - wait) with the request's trace identity
        tracer = tspans.current_tracer()
        if tracer.enabled:
            end_us = tracer.now_us()
            for _, _, t0, _, trace in live:
                if trace is not None:
                    dur_us = max(0.0, (now - t0) * 1e6)
                    tracer.complete(
                        "serve/queue_wait",
                        end_us - dur_us,
                        dur_us,
                        cat="serve",
                        key=str(key),
                        **trace.span_args(),
                    )
        try:
            failpoints.fire("batcher.flush", key=str(key), n=len(live))
            results = self._process(key, [item for item, _, _, _, _ in live])
            if len(results) != len(live):
                raise RuntimeError(
                    f"process returned {len(results)} results for "
                    f"{len(live)} items (key={key!r})"
                )
        except BaseException as e:  # noqa: BLE001 - relayed through futures
            for _, fut, _, _, _ in live:
                fut.set_exception(e)
            if self._on_flush_result is not None:
                self._on_flush_result(False)
            return
        if self._on_flush_result is not None:
            self._on_flush_result(True)
        for (_, fut, _, _, _), res in zip(live, results):
            fut.set_result(res)
