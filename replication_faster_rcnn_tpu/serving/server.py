"""Minimal stdlib HTTP front-end over the inference engine.

Each request-handler thread submits its images to the engine and blocks
on the futures — so concurrent clients' requests coalesce into shared
micro-batches inside the engine (ThreadingHTTPServer gives one thread
per connection; the engine's bounded queue is the backpressure).

Endpoints:
  POST /predict  {"paths": ["a.jpg", ...]} or {"path": "a.jpg"}, optional
                 "score_thresh" — detections per image (boxes in original
                 image coordinates, row-major [r1, c1, r2, c2])
  GET  /healthz  liveness + bucket inventory
  GET  /stats    request/flush/padding counters + queue depth
"""

from __future__ import annotations

import json
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from replication_faster_rcnn_tpu.config import VOC_CLASSES

__all__ = ["make_server"]


def _detections_json(config, out, thresh: float):
    names = (
        VOC_CLASSES
        if config.model.num_classes == len(VOC_CLASSES)
        else [str(i) for i in range(config.model.num_classes)]
    )
    dets = []
    for i in range(out["valid"].shape[0]):
        if not out["valid"][i] or out["scores"][i] < thresh:
            continue
        cls = int(out["classes"][i])
        dets.append(
            {
                "box": out["boxes"][i].tolist(),
                "score": float(out["scores"][i]),
                "class_id": cls,
                "class_name": names[cls],
            }
        )
    dets.sort(key=lambda d: -d["score"])
    return dets


class _Handler(BaseHTTPRequestHandler):
    # the engine/config/default threshold hang off the server instance

    def _reply(self, code: int, payload: dict) -> None:
        body = json.dumps(payload, indent=2).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, fmt, *fmt_args):  # quiet: one line per request
        pass  # noqa: D401 - stdlib signature

    def do_GET(self) -> None:  # noqa: N802 - stdlib casing
        engine = self.server.engine
        if self.path == "/healthz":
            self._reply(
                200,
                {
                    "ok": True,
                    "buckets": [list(b) for b in engine.buckets],
                    "batch_sizes": list(engine.batch_sizes),
                },
            )
        elif self.path == "/stats":
            self._reply(
                200,
                {
                    "stats": dict(engine.stats),
                    "queue_depth": engine._batcher.queue_depth(),
                    "compile_seconds": dict(engine.compile_seconds),
                },
            )
        else:
            self._reply(404, {"error": f"unknown path {self.path}"})

    def do_POST(self) -> None:  # noqa: N802 - stdlib casing
        if self.path != "/predict":
            self._reply(404, {"error": f"unknown path {self.path}"})
            return
        engine = self.server.engine
        try:
            length = int(self.headers.get("Content-Length", 0))
            req = json.loads(self.rfile.read(length) or b"{}")
            paths = req.get("paths") or ([req["path"]] if "path" in req else [])
            if not paths:
                raise ValueError('need "path" or non-empty "paths"')
            thresh = float(req.get("score_thresh", self.server.score_thresh))
        except (ValueError, KeyError, json.JSONDecodeError) as e:
            self._reply(400, {"error": str(e)})
            return
        try:
            # submit everything first: same-bucket paths coalesce into
            # shared flushes (also across concurrent handler threads)
            futures = [engine.submit_path(p) for p in paths]
            results = {
                p: _detections_json(engine.config, f.result(), thresh)
                for p, f in zip(paths, futures)
            }
        except FileNotFoundError as e:
            self._reply(400, {"error": str(e)})
            return
        except Exception as e:  # noqa: BLE001 - surfaced to the client
            self._reply(500, {"error": f"{type(e).__name__}: {e}"})
            return
        self._reply(200, {"detections": results})


def make_server(
    engine,
    host: str = "127.0.0.1",
    port: int = 8008,
    score_thresh: Optional[float] = None,
) -> ThreadingHTTPServer:
    """A ready-to-``serve_forever`` HTTP server bound to ``engine``.
    ``port=0`` binds a free port (read ``server.server_address``)."""
    server = ThreadingHTTPServer((host, port), _Handler)
    server.engine = engine
    server.score_thresh = (
        engine.config.eval.score_thresh if score_thresh is None else score_thresh
    )
    return server
