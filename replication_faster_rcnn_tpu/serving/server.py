"""Minimal stdlib HTTP front-end over the inference engine.

Each request-handler thread submits its images to the engine and waits
on the futures — so concurrent clients' requests coalesce into shared
micro-batches inside the engine (ThreadingHTTPServer gives one thread
per connection; the engine's bounded queue is the backpressure).

Overload contract (serving/overload.py):

* submits are ADMISSION-CONTROLLED (``timeout=0`` against the bounded
  queue): a full queue sheds the request with **503** + ``Retry-After``
  instead of parking the handler thread;
* ``serving.request_timeout_s`` bounds each request end-to-end: the
  handler's wait times out to **504**, and entries whose deadline passed
  while queued are dropped at flush time, never dispatched;
* multi-path requests are isolated per path: one failing image costs
  that one entry an ``"error"`` value, the rest still return detections.

Tracing: an incoming W3C ``traceparent`` header (the fleet tier's
client injects one per attempt) is adopted as the request's trace —
the handler binds a child context for the duration, so the engine's
queue-wait/dispatch hop spans and any error response share the
caller's trace id.  Requests arriving without the header get a fresh
root trace.

Endpoints:
  POST /predict  {"paths": ["a.jpg", ...]} or {"path": "a.jpg"}, optional
                 "score_thresh" — detections per image (boxes in original
                 image coordinates, row-major [r1, c1, r2, c2]); per-path
                 failures come back under "errors"; error responses carry
                 the request's "trace_id"
  GET  /healthz  liveness + bucket inventory + degraded flag
  GET  /stats    unified frcnn-stats/v1 envelope: schema/tier/metrics +
                 the replica's structured sections (stats, queue depths,
                 compile_seconds, slo)
  GET  /metrics  the same registry in Prometheus text exposition format
  POST /swap     {"version": "<step>"} — hot-swap weights to a checkpoint
                 version via the server's swap_handler (501 without one);
                 in-flight requests finish on their admission version
"""

from __future__ import annotations

import contextlib
import json
import queue
import socket
from concurrent.futures import TimeoutError as FutureTimeoutError
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from replication_faster_rcnn_tpu.config import VOC_CLASSES
from replication_faster_rcnn_tpu.faultlib import failpoints
from replication_faster_rcnn_tpu.serving.overload import (
    DeadlineExceeded,
    retry_after_s,
)
from replication_faster_rcnn_tpu.telemetry import spans as tspans
from replication_faster_rcnn_tpu.telemetry import tracecontext
from replication_faster_rcnn_tpu.telemetry.metrics import (
    PROMETHEUS_CONTENT_TYPE,
    stats_payload,
)

__all__ = ["make_server"]


def _detections_json(config, out, thresh: float):
    names = (
        VOC_CLASSES
        if config.model.num_classes == len(VOC_CLASSES)
        else [str(i) for i in range(config.model.num_classes)]
    )
    dets = []
    for i in range(out["valid"].shape[0]):
        if not out["valid"][i] or out["scores"][i] < thresh:
            continue
        cls = int(out["classes"][i])
        dets.append(
            {
                "box": out["boxes"][i].tolist(),
                "score": float(out["scores"][i]),
                "class_id": cls,
                "class_name": names[cls],
            }
        )
    dets.sort(key=lambda d: -d["score"])
    return dets


class _Handler(BaseHTTPRequestHandler):
    # the engine/config/default threshold hang off the server instance

    def _reply(self, code: int, payload: dict, headers: Optional[dict] = None) -> None:
        body = json.dumps(payload, indent=2).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        for k, v in (headers or {}).items():
            self.send_header(k, str(v))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, fmt, *fmt_args):  # quiet: one line per request
        pass  # noqa: D401 - stdlib signature

    def do_GET(self) -> None:  # noqa: N802 - stdlib casing
        engine = self.server.engine
        if self.path == "/healthz":
            self._reply(
                200,
                {
                    "ok": True,
                    "degraded": engine.degraded,
                    "degraded_reason": engine.degraded_reason,
                    "draining": bool(getattr(self.server, "draining", False)),
                    "replica_id": getattr(self.server, "replica_id", None),
                    "uptime_s": round(engine.uptime_s(), 3),
                    "bucket_queue_depths": engine.bucket_queue_depths(),
                    "buckets": [list(b) for b in engine.buckets],
                    "batch_sizes": list(engine.batch_sizes),
                    "params_dtype": engine.params_dtype,
                    "params_bytes": engine.params_bytes,
                    "model_version": engine.model_version,
                },
            )
        elif self.path == "/stats":
            sections = {
                "stats": dict(engine.stats),
                "queue_depth": engine.queue_depth(),
                "bucket_queue_depths": engine.bucket_queue_depths(),
                "compile_seconds": dict(engine.compile_seconds),
                "params_dtype": engine.params_dtype,
                "params_bytes": engine.params_bytes,
                "model_version": engine.model_version,
                "resident_versions": engine.resident_versions(),
                "slo": engine.slo.snapshot(),
            }
            if engine.deadline_controller is not None:
                sections["adaptive_delay_ms"] = (
                    engine.deadline_controller.delays_ms()
                )
            self._reply(200, stats_payload("replica", engine.metrics, **sections))
        elif self.path == "/metrics":
            body = engine.metrics.render_prometheus().encode()
            self.send_response(200)
            self.send_header("Content-Type", PROMETHEUS_CONTENT_TYPE)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        else:
            self._reply(404, {"error": f"unknown path {self.path}"})

    def do_POST(self) -> None:  # noqa: N802 - stdlib casing
        if self.path == "/swap":
            self._handle_swap()
            return
        if self.path != "/predict":
            self._reply(404, {"error": f"unknown path {self.path}"})
            return
        engine = self.server.engine
        # adopt the caller's trace (e.g. the fleet client's traceparent
        # header) as this request's parent, or start a fresh root; the
        # context is BOUND for the whole handler body so the engine's
        # hop spans, chaos events and error replies share the trace id
        trace = None
        if engine.config.telemetry.trace_propagation:
            parent = tracecontext.parse_traceparent(
                self.headers.get(tracecontext.TRACEPARENT_HEADER)
            )
            trace = (
                parent.child()
                if parent is not None
                else tracecontext.new_trace_context()
            )
        tracer = tspans.current_tracer()
        t_req = tracer.now_us()
        try:
            with tracecontext.bind(trace):
                self._handle_predict(trace)
        finally:
            if tracer.enabled and trace is not None:
                tracer.complete(
                    "serve/request",
                    t_req,
                    tracer.now_us() - t_req,
                    cat="serve",
                    **trace.span_args(),
                )

    def _handle_swap(self) -> None:
        """POST /swap {"version": "<step>"} — hot-swap the engine to a
        new model version via the server's ``swap_handler`` (wired by
        `frcnn serve --workdir`; 501 when the replica has no checkpoint
        source to swap from). In-flight requests finish on the version
        they were admitted under; the response reports both versions."""
        engine = self.server.engine
        handler = getattr(self.server, "swap_handler", None)
        if handler is None:
            self._reply(
                501, {"error": "this replica has no swap handler configured"}
            )
            return
        try:
            length = int(self.headers.get("Content-Length", 0))
            req = json.loads(self.rfile.read(length) or b"{}")
            version = str(req["version"])
        except (ValueError, KeyError, json.JSONDecodeError) as e:
            self._reply(400, {"error": f"need a \"version\": {e}"})
            return
        try:
            prior = handler(version)
        except Exception as e:  # noqa: BLE001 - surfaced to the controller
            self._reply(
                500, {"error": f"swap failed: {type(e).__name__}: {e}"}
            )
            return
        self._reply(
            200,
            {
                "ok": True,
                "model_version": engine.model_version,
                "prior_version": prior,
            },
        )

    def _handle_predict(self, trace) -> None:
        engine = self.server.engine
        trace_id = trace.trace_id if trace is not None else None
        try:
            inj = failpoints.fire("http.handler", path=self.path)
        except failpoints.ChaosError as e:
            self._reply(500, {"error": str(e), "trace_id": trace_id})
            return
        if inj is not None and inj.kind == "drop":
            # simulate a dropped connection: shut the socket with no
            # response bytes; the keep-alive loop then reads EOF and exits
            with contextlib.suppress(OSError):
                self.connection.shutdown(socket.SHUT_RDWR)
            return
        try:
            length = int(self.headers.get("Content-Length", 0))
            req = json.loads(self.rfile.read(length) or b"{}")
            paths = req.get("paths") or ([req["path"]] if "path" in req else [])
            if not paths:
                raise ValueError('need "path" or non-empty "paths"')
            thresh = float(req.get("score_thresh", self.server.score_thresh))
        except (ValueError, KeyError, json.JSONDecodeError) as e:
            self._reply(400, {"error": str(e), "trace_id": trace_id})
            return

        # submit everything first: same-bucket paths coalesce into shared
        # flushes (also across concurrent handler threads). timeout=0 is
        # the admission decision — accept or shed, never block the thread.
        timeout_s = engine.config.serving.request_timeout_s
        futures = []  # (path, future | None)
        shed = timed_out = bad_input = 0
        results, errors = {}, {}
        for p in paths:
            try:
                futures.append((p, engine.submit_path(p, timeout=0)))
            except queue.Full:
                shed += 1
                errors[p] = "shed: serving queue is full"
                futures.append((p, None))
            except (FileNotFoundError, OSError, ValueError) as e:
                bad_input += 1
                errors[p] = f"{type(e).__name__}: {e}"
                futures.append((p, None))

        # per-path isolation: one bad image costs one entry, not the wave
        for p, fut in futures:
            if fut is None:
                continue
            try:
                out = fut.result(timeout=timeout_s if timeout_s > 0 else None)
                results[p] = _detections_json(engine.config, out, thresh)
            except (FutureTimeoutError, DeadlineExceeded):
                timed_out += 1
                engine.incr_stat("timeouts")
                errors[p] = (
                    f"deadline exceeded (request_timeout_s={timeout_s})"
                )
            except Exception as e:  # noqa: BLE001 - surfaced per path
                errors[p] = f"{type(e).__name__}: {e}"

        if results:
            payload = {"detections": results}
            if errors:
                payload["errors"] = errors
            self._reply(200, payload)
            return
        # nothing succeeded: the status reflects the dominant failure
        if shed:
            self._reply(
                503,
                {
                    "error": "serving queue is full",
                    "errors": errors,
                    "trace_id": trace_id,
                },
                headers={
                    "Retry-After": retry_after_s(
                        engine.config.serving.max_delay_ms
                    )
                },
            )
        elif timed_out:
            # 504 carries Retry-After too: a deadline miss means the
            # replica is saturated right now, same as a shed — tell the
            # client when the queue should have turned over
            self._reply(
                504,
                {
                    "error": "request deadline exceeded",
                    "errors": errors,
                    "trace_id": trace_id,
                },
                headers={
                    "Retry-After": retry_after_s(
                        engine.config.serving.max_delay_ms
                    )
                },
            )
        elif bad_input == len(paths):
            self._reply(
                400, {"error": "; ".join(errors.values()), "trace_id": trace_id}
            )
        else:
            self._reply(
                500,
                {
                    "error": "all paths failed",
                    "errors": errors,
                    "trace_id": trace_id,
                },
            )


def make_server(
    engine,
    host: str = "127.0.0.1",
    port: int = 8008,
    score_thresh: Optional[float] = None,
    replica_id: Optional[str] = None,
    swap_handler=None,
) -> ThreadingHTTPServer:
    """A ready-to-``serve_forever`` HTTP server bound to ``engine``.
    ``port=0`` binds a free port (read ``server.server_address``).
    ``replica_id`` names this replica in /healthz for fleet membership;
    setting ``server.draining = True`` (the SIGTERM grace window) makes
    /healthz advertise it so the fleet router stops routing here before
    the listener closes. ``swap_handler(version) -> prior_version``
    enables POST /swap (rolling weight rollout); without one the
    endpoint answers 501."""
    server = ThreadingHTTPServer((host, port), _Handler)
    server.engine = engine
    server.replica_id = replica_id
    server.draining = False
    server.swap_handler = swap_handler
    server.score_thresh = (
        engine.config.eval.score_thresh if score_thresh is None else score_thresh
    )
    return server
