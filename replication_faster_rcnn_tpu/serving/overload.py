"""Overload-hardening primitives for the serving tier.

The HTTP front-end turns these into its response contract:

* **Admission control** — the engine submits with ``timeout=0`` against
  the bounded micro-batch queue; ``queue.Full`` becomes **503** with a
  ``Retry-After`` hint instead of a blocked handler thread. Under 2x
  capacity the tier sheds load; it never queues unboundedly or hangs.
* **Per-request deadlines** (``serving.request_timeout_s``) — a request
  carries an absolute deadline from submit time. The handler's future
  wait times out to **504**; entries whose deadline passed while they
  waited in the queue are dropped AT FLUSH TIME with
  :class:`DeadlineExceeded`, so abandoned work is never dispatched to
  the accelerator.
* **Jittered backoff** — the loadgen client's retry schedule for shed
  submissions (decorrelated exponential backoff), seeded so benchmark
  runs are reproducible.
"""

from __future__ import annotations

import math
import random
from typing import Iterator

from replication_faster_rcnn_tpu.serving.batcher import DeadlineExceeded

__all__ = ["DeadlineExceeded", "backoff_delays", "retry_after_s"]


def retry_after_s(max_delay_ms: float) -> int:
    """The ``Retry-After`` header value for a shed request: at least a
    second, at least one micro-batch deadline window — by then the queue
    has had a full flush cycle to drain."""
    return max(1, int(math.ceil(max_delay_ms / 1000.0)))


def backoff_delays(
    base_s: float = 0.005,
    max_s: float = 0.25,
    retries: int = 8,
    seed: int = 0,
) -> Iterator[float]:
    """Jittered exponential backoff delays for submit retries:
    ``U(0.5, 1.5) * base * 2^attempt`` capped at ``max_s``. Seeded so a
    loadgen run's retry schedule is reproducible."""
    rng = random.Random(seed)
    for attempt in range(retries):
        yield min(max_s, base_s * (2.0**attempt) * (0.5 + rng.random()))
