"""Pallas NMS kernel parity tests (interpret mode — the suite runs on the
CPU backend; the compiled path is exercised on TPU by bench/verify runs).

The XLA `nms_fixed` is the behavioral reference: same selection set, same
order, same lowest-index tie-breaking.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from replication_faster_rcnn_tpu.ops.nms import nms_fixed
from replication_faster_rcnn_tpu.ops.nms_pallas import nms_fixed_auto, nms_fixed_pallas

pallas_interp = functools.partial(nms_fixed_pallas, interpret=True)


def _case(n, seed=0, img=600.0):
    rng = np.random.RandomState(seed)
    r1 = rng.uniform(0, img * 0.9, (n, 1))
    c1 = rng.uniform(0, img * 0.9, (n, 1))
    boxes = np.concatenate(
        [r1, c1, r1 + rng.uniform(5, img / 2, (n, 1)), c1 + rng.uniform(5, img / 2, (n, 1))],
        axis=1,
    ).astype(np.float32)
    scores = rng.uniform(size=n).astype(np.float32)
    return jnp.asarray(boxes), jnp.asarray(scores)


def _assert_parity(boxes, scores, thresh, max_out, mask=None):
    ip, vp = pallas_interp(boxes, scores, thresh, max_out, mask=mask)
    ix, vx = nms_fixed(boxes, scores, thresh, max_out, mask=mask)
    ip, vp, ix, vx = map(np.asarray, (ip, vp, ix, vx))
    np.testing.assert_array_equal(vp, vx)
    np.testing.assert_array_equal(ip[vp], ix[vx])


class TestPallasNMSParity:
    @pytest.mark.parametrize("n", [7, 128, 500, 1000])
    def test_sizes(self, n):
        boxes, scores = _case(n, seed=n)
        _assert_parity(boxes, scores, 0.5, 50)

    @pytest.mark.parametrize("thresh", [0.3, 0.7, 0.95])
    def test_thresholds(self, thresh):
        boxes, scores = _case(300, seed=1)
        _assert_parity(boxes, scores, thresh, 64)

    def test_mask(self):
        boxes, scores = _case(200, seed=2)
        mask = jnp.asarray(np.arange(200) % 3 != 0)
        _assert_parity(boxes, scores, 0.5, 40, mask=mask)

    def test_nan_scores(self):
        boxes, scores = _case(100, seed=3)
        scores = scores.at[0].set(jnp.nan).at[50].set(jnp.inf)
        ip, vp = pallas_interp(boxes, scores, 0.5, 20)
        # NaN never selected; inf handled as masked-out too (both map to _NEG)
        kept = np.asarray(ip)[np.asarray(vp)]
        assert 0 not in kept and 50 not in kept

    def test_fewer_survivors_than_slots(self):
        # all boxes identical: exactly one survives, rest of slots invalid
        boxes = jnp.tile(jnp.asarray([[10.0, 10, 50, 50]]), (64, 1))
        scores = jnp.linspace(0.1, 0.9, 64)
        ip, vp = pallas_interp(boxes, scores, 0.5, 10)
        assert int(np.asarray(vp).sum()) == 1
        assert int(np.asarray(ip)[0]) == 63  # highest score

    def test_selection_order_is_score_order(self):
        boxes, scores = _case(400, seed=4)
        ip, vp = pallas_interp(boxes, scores, 0.6, 30)
        kept = np.asarray(ip)[np.asarray(vp)]
        s = np.asarray(scores)[kept]
        assert (np.diff(s) <= 0).all()


class TestAutoDispatch:
    """nms_fixed_auto routing: tiled is the default on every backend; an
    explicit FRCNN_NMS always beats the legacy FRCNN_PALLAS_NMS=1."""

    def _spies(self, monkeypatch):
        from replication_faster_rcnn_tpu.ops import nms as nms_mod
        from replication_faster_rcnn_tpu.ops import nms_tiled as tiled_mod

        calls = []
        real_loop, real_tiled = nms_mod.nms_fixed, tiled_mod.nms_fixed_tiled
        monkeypatch.setattr(
            nms_mod,
            "nms_fixed",
            lambda *a, **k: calls.append("loop") or real_loop(*a, **k),
        )
        monkeypatch.setattr(
            tiled_mod,
            "nms_fixed_tiled",
            lambda *a, **k: calls.append("tiled") or real_tiled(*a, **k),
        )
        return calls

    def test_default_is_tiled_and_agrees_with_loop(self, monkeypatch):
        monkeypatch.delenv("FRCNN_NMS", raising=False)
        monkeypatch.delenv("FRCNN_PALLAS_NMS", raising=False)
        calls = self._spies(monkeypatch)
        boxes, scores = _case(100, seed=5)
        ia, va = nms_fixed_auto(boxes, scores, 0.5, 20)
        assert calls == ["tiled"]
        ix, vx = nms_fixed(boxes, scores, 0.5, 20)
        np.testing.assert_array_equal(np.asarray(ia), np.asarray(ix))
        np.testing.assert_array_equal(np.asarray(va), np.asarray(vx))

    def test_explicit_choice_beats_legacy_pallas_var(self, monkeypatch):
        monkeypatch.setenv("FRCNN_NMS", "loop")
        monkeypatch.setenv("FRCNN_PALLAS_NMS", "1")
        calls = self._spies(monkeypatch)
        boxes, scores = _case(64, seed=6)
        nms_fixed_auto(boxes, scores, 0.5, 10)
        assert calls == ["loop"]

    def test_legacy_pallas_var_alone_falls_back_off_tpu(self, monkeypatch):
        monkeypatch.delenv("FRCNN_NMS", raising=False)
        monkeypatch.setenv("FRCNN_PALLAS_NMS", "1")
        calls = self._spies(monkeypatch)
        boxes, scores = _case(64, seed=7)
        with pytest.warns(UserWarning, match="needs a TPU backend"):
            nms_fixed_auto(boxes, scores, 0.5, 10)
        # falls back to the DEFAULT (tiled), not the slowest backend
        assert calls == ["tiled"]

    def test_unknown_choice_warns_and_uses_default(self, monkeypatch):
        monkeypatch.setenv("FRCNN_NMS", "bogus")
        monkeypatch.delenv("FRCNN_PALLAS_NMS", raising=False)
        calls = self._spies(monkeypatch)
        boxes, scores = _case(64, seed=8)
        with pytest.warns(UserWarning, match="unknown FRCNN_NMS"):
            nms_fixed_auto(boxes, scores, 0.5, 10)
        assert calls == ["tiled"]
