"""Model-layer tests: shapes/jit invariants (SURVEY.md §4d) and torch->flax
conversion layout parity against torch functional ops as oracles."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from replication_faster_rcnn_tpu.config import (
    DataConfig,
    FasterRCNNConfig,
    ModelConfig,
)
from replication_faster_rcnn_tpu.models import convert, faster_rcnn
from replication_faster_rcnn_tpu.models.head import select_class_deltas
from replication_faster_rcnn_tpu.models.resnet import (
    ResNetTail,
    ResNetTrunk,
    tail_channels,
    trunk_channels,
)


def _small_cfg(backbone="resnet18", **model_kw):
    return FasterRCNNConfig(
        model=ModelConfig(backbone=backbone, compute_dtype="float32", **model_kw),
        data=DataConfig(image_size=(96, 96)),
    )


class TestResNet:
    @pytest.mark.parametrize("arch", ["resnet18", "resnet50"])
    def test_trunk_stride16_and_channels(self, arch):
        trunk = ResNetTrunk(arch, jnp.float32)
        x = jnp.zeros((1, 96, 96, 3))
        vars_ = trunk.init(jax.random.PRNGKey(0), x, train=False)
        y = trunk.apply(vars_, x, train=False)
        assert y.shape == (1, 6, 6, trunk_channels(arch))

    def test_trunk_odd_size_matches_torch_ceil(self):
        # 600 -> 38 through four ceil-halvings (reference resnet50.py:64-71)
        trunk = ResNetTrunk("resnet18", jnp.float32)
        x = jnp.zeros((1, 112, 150, 3))
        vars_ = trunk.init(jax.random.PRNGKey(0), x, train=False)
        y = trunk.apply(vars_, x, train=False)
        assert y.shape[1:3] == (7, 10)  # ceil(112/16), ceil(150/16)

    @pytest.mark.parametrize("arch", ["resnet18", "resnet50"])
    def test_tail_pools_to_vector(self, arch):
        tail = ResNetTail(arch, jnp.float32)
        x = jnp.zeros((4, 7, 7, trunk_channels(arch)))
        vars_ = tail.init(jax.random.PRNGKey(0), x, train=False)
        y = tail.apply(vars_, x, train=False)
        assert y.shape == (4, tail_channels(arch))

    def test_batchnorm_stats_update_in_train(self):
        trunk = ResNetTrunk("resnet18", jnp.float32)
        x = jax.random.normal(jax.random.PRNGKey(0), (2, 64, 64, 3))
        vars_ = trunk.init(jax.random.PRNGKey(1), x, train=False)
        _, updates = trunk.apply(
            vars_, x, train=True, mutable=["batch_stats"]
        )
        before = vars_["batch_stats"]["bn1"]["mean"]
        after = updates["batch_stats"]["bn1"]["mean"]
        assert not np.allclose(np.asarray(before), np.asarray(after))


class TestFasterRCNNAssembly:
    def test_forward_shapes_fixed(self):
        cfg = _small_cfg()
        model, variables = faster_rcnn.init_variables(cfg, jax.random.PRNGKey(0))
        imgs = jnp.zeros((2, 96, 96, 3))
        logits, deltas, rois, valid, cls, reg, anchors = model.apply(
            variables, imgs, train=False
        )
        A = cfg.num_anchors()
        P = cfg.proposals.post_nms(False)
        C = cfg.model.num_classes
        assert logits.shape == (2, A, 2)
        assert deltas.shape == (2, A, 4)
        assert rois.shape == (2, P, 4)
        assert valid.shape == (2, P)
        assert cls.shape == (2, P, C)
        assert reg.shape == (2, P, C * 4)
        assert anchors.shape == (A, 4)

    def test_forward_is_jittable(self):
        cfg = _small_cfg()
        model, variables = faster_rcnn.init_variables(cfg, jax.random.PRNGKey(0))

        @jax.jit
        def fwd(v, x):
            return model.apply(v, x, train=False)

        out = fwd(variables, jnp.zeros((1, 96, 96, 3)))
        assert len(out) == 7

    def test_stage_methods_compose(self):
        cfg = _small_cfg(roi_op="pool")
        model, variables = faster_rcnn.init_variables(cfg, jax.random.PRNGKey(0))
        imgs = jnp.zeros((1, 96, 96, 3))
        feat = model.apply(variables, imgs, False, method="extract_features")
        logits, deltas, anchors = model.apply(variables, feat, method="rpn_forward")
        rois, valid = model.apply(
            variables, logits, deltas, anchors, 96.0, 96.0, True, method="propose"
        )
        cls, reg = model.apply(
            variables, feat, rois, 96.0, 96.0, False, method="head_forward"
        )
        assert rois.shape == (1, cfg.proposals.post_nms_train, 4)
        assert cls.shape[2] == cfg.model.num_classes

    def test_select_class_deltas(self):
        reg = jnp.arange(2 * 3 * 8, dtype=jnp.float32).reshape(2, 3, 8)  # 2 classes
        labels = jnp.asarray([[0, 1, 1], [1, 0, 0]])
        out = select_class_deltas(reg, labels)
        assert out.shape == (2, 3, 4)
        np.testing.assert_array_equal(np.asarray(out[0, 0]), np.asarray(reg[0, 0, 0:4]))
        np.testing.assert_array_equal(np.asarray(out[0, 1]), np.asarray(reg[0, 1, 4:8]))


class TestTorchConversion:
    """Layout rules validated against torch functional ops directly."""

    torch = pytest.importorskip("torch")

    def test_conv_kernel_layout(self):
        import torch
        import torch.nn.functional as F

        w = torch.randn(8, 3, 3, 3)
        x = torch.randn(1, 3, 16, 16)
        ref = F.conv2d(x, w, stride=2, padding=1).permute(0, 2, 3, 1).numpy()

        kernel = convert._conv_kernel(w)
        y = jax.lax.conv_general_dilated(
            jnp.asarray(x.numpy()).transpose(0, 2, 3, 1),
            jnp.asarray(kernel),
            window_strides=(2, 2),
            padding=((1, 1), (1, 1)),
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )
        np.testing.assert_allclose(np.asarray(y), ref, rtol=1e-4, atol=1e-5)

    def test_bn_entries_semantics(self):
        import torch

        bn = torch.nn.BatchNorm2d(4)
        bn.running_mean += torch.randn(4)
        bn.running_var += torch.rand(4)
        bn.weight.data = torch.randn(4)
        bn.bias.data = torch.randn(4)
        bn.eval()
        x = torch.randn(2, 4, 5, 5)
        ref = bn(x).detach().permute(0, 2, 3, 1).numpy()

        state = {f"b.{k}": v for k, v in bn.state_dict().items()}
        params, stats = convert._bn_entries("b", state)
        xn = jnp.asarray(x.numpy()).transpose(0, 2, 3, 1)
        y = (xn - stats["mean"]) / jnp.sqrt(stats["var"] + 1e-5) * params[
            "scale"
        ] + params["bias"]
        np.testing.assert_allclose(np.asarray(y), ref, rtol=1e-4, atol=1e-5)

    def test_trunk_tree_structure_matches_flax_init(self):
        import torch

        # Build a state_dict with resnet18's exact key/shape inventory from
        # the flax init (reverse-mapped), then convert and compare trees.
        trunk = ResNetTrunk("resnet18", jnp.float32)
        vars_ = trunk.init(jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)), train=False)

        state = {}

        def add_conv(tname, kernel):
            kh, kw, i, o = kernel.shape
            state[f"{tname}.weight"] = torch.randn(o, i, kh, kw)

        def add_bn(tname, n):
            state[f"{tname}.weight"] = torch.randn(n)
            state[f"{tname}.bias"] = torch.randn(n)
            state[f"{tname}.running_mean"] = torch.randn(n)
            state[f"{tname}.running_var"] = torch.rand(n)

        params = vars_["params"]
        add_conv("conv1", params["conv1"]["kernel"])
        add_bn("bn1", 64)
        for key, block in params.items():
            if not key.startswith("layer"):
                continue
            for sub, leaf in block.items():
                tname = f"{key}.{sub}"
                if sub.startswith("conv"):
                    add_conv(tname, leaf["kernel"])
                elif sub == "downsample_conv":
                    add_conv(f"{key}.downsample.0", leaf["kernel"])
                elif sub == "downsample_bn":
                    add_bn(f"{key}.downsample.1", leaf["scale"].shape[0])
                else:
                    add_bn(tname, leaf["scale"].shape[0])

        cp, cs = convert.convert_trunk(state)
        # Identical tree structure and per-leaf shapes (tree_map raises on
        # structure mismatch).
        same_p = jax.tree_util.tree_map(
            lambda a, b: tuple(a.shape) == tuple(np.shape(b)), params, cp
        )
        assert all(jax.tree_util.tree_leaves(same_p))
        same_s = jax.tree_util.tree_map(
            lambda a, b: tuple(a.shape) == tuple(np.shape(b)),
            vars_["batch_stats"],
            cs,
        )
        assert all(jax.tree_util.tree_leaves(same_s))
