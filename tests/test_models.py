"""Model-layer tests: shapes/jit invariants (SURVEY.md §4d) and torch->flax
conversion layout parity against torch functional ops as oracles."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from replication_faster_rcnn_tpu.config import (
    DataConfig,
    FasterRCNNConfig,
    ModelConfig,
)
from replication_faster_rcnn_tpu.models import convert, faster_rcnn
from replication_faster_rcnn_tpu.models.head import select_class_deltas
from replication_faster_rcnn_tpu.models.resnet import (
    ResNetTail,
    ResNetTrunk,
    tail_channels,
    trunk_channels,
)


def _small_cfg(backbone="resnet18", **model_kw):
    return FasterRCNNConfig(
        model=ModelConfig(backbone=backbone, compute_dtype="float32", **model_kw),
        data=DataConfig(image_size=(96, 96)),
    )


class TestResNet:
    @pytest.mark.parametrize("arch", ["resnet18", "resnet50"])
    def test_trunk_stride16_and_channels(self, arch):
        trunk = ResNetTrunk(arch, jnp.float32)
        x = jnp.zeros((1, 96, 96, 3))
        vars_ = trunk.init(jax.random.PRNGKey(0), x, train=False)
        y = trunk.apply(vars_, x, train=False)
        assert y.shape == (1, 6, 6, trunk_channels(arch))

    def test_trunk_odd_size_matches_torch_ceil(self):
        # 600 -> 38 through four ceil-halvings (reference resnet50.py:64-71)
        trunk = ResNetTrunk("resnet18", jnp.float32)
        x = jnp.zeros((1, 112, 150, 3))
        vars_ = trunk.init(jax.random.PRNGKey(0), x, train=False)
        y = trunk.apply(vars_, x, train=False)
        assert y.shape[1:3] == (7, 10)  # ceil(112/16), ceil(150/16)

    @pytest.mark.parametrize("arch", ["resnet18", "resnet50"])
    def test_tail_pools_to_vector(self, arch):
        tail = ResNetTail(arch, jnp.float32)
        x = jnp.zeros((4, 7, 7, trunk_channels(arch)))
        vars_ = tail.init(jax.random.PRNGKey(0), x, train=False)
        y = tail.apply(vars_, x, train=False)
        assert y.shape == (4, tail_channels(arch))

    @pytest.mark.parametrize("arch", ["resnet152", "resnext50_32x4d", "wide_resnet50_2"])
    def test_variant_trunk_channels(self, arch):
        # the full constructor table of reference nets/resnet_torch.py:271-390
        trunk = ResNetTrunk(arch, jnp.float32)
        x = jnp.zeros((1, 32, 32, 3))
        vars_ = trunk.init(jax.random.PRNGKey(0), x, train=False)
        y = trunk.apply(vars_, x, train=False)
        assert y.shape == (1, 2, 2, trunk_channels(arch))

    def test_resnext_grouped_conv_shapes(self):
        # torchvision width formula: planes * base_width/64 * groups; the 3x3
        # is grouped, so its kernel holds in_channels/groups input channels.
        trunk = ResNetTrunk("resnext50_32x4d", jnp.float32)
        vars_ = trunk.init(jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)), train=False)
        k = vars_["params"]["layer1.0"]["conv2"]["kernel"]
        assert k.shape == (3, 3, 128 // 32, 128)  # width=64*(4/64)*32=128, groups=32
        k_wide = ResNetTrunk("wide_resnet50_2", jnp.float32).init(
            jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)), train=False
        )["params"]["layer1.0"]["conv2"]["kernel"]
        assert k_wide.shape == (3, 3, 128, 128)  # width=64*(128/64)=128

    @pytest.mark.parametrize("stride", [1, 2])
    def test_grouped_conv_matches_xla_grouped(self, stride):
        # the einsum formulation (TPU path) vs XLA's native grouped conv,
        # which works on CPU and serves as the oracle
        from replication_faster_rcnn_tpu.models.resnet import GroupedConv

        g, in_ch, out_ch = 4, 16, 24
        mod = GroupedConv(
            features=out_ch, kernel=3, stride=stride, padding=1, groups=g,
            dtype=jnp.float32,
        )
        x = jax.random.normal(jax.random.PRNGKey(0), (2, 9, 11, in_ch))
        vars_ = mod.init(jax.random.PRNGKey(1), x)
        y = mod.apply(vars_, x)
        ref = jax.lax.conv_general_dilated(
            x,
            vars_["params"]["kernel"],
            window_strides=(stride, stride),
            padding=((1, 1), (1, 1)),
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            feature_group_count=g,
        )
        assert y.shape == ref.shape
        np.testing.assert_allclose(np.asarray(y), np.asarray(ref), rtol=1e-5, atol=1e-5)

    def test_unknown_arch_fails_fast(self):
        with pytest.raises(ValueError, match="unknown resnet arch"):
            trunk_channels("resnext50_32x8d")  # typo'd mix of two valid names
        with pytest.raises(ValueError, match="unknown resnet arch"):
            ModelConfig(backbone="resnet19").backbone_channels

    def test_batchnorm_stats_update_in_train(self):
        trunk = ResNetTrunk("resnet18", jnp.float32)
        x = jax.random.normal(jax.random.PRNGKey(0), (2, 64, 64, 3))
        vars_ = trunk.init(jax.random.PRNGKey(1), x, train=False)
        _, updates = trunk.apply(
            vars_, x, train=True, mutable=["batch_stats"]
        )
        before = vars_["batch_stats"]["bn1"]["mean"]
        after = updates["batch_stats"]["bn1"]["mean"]
        assert not np.allclose(np.asarray(before), np.asarray(after))


class TestVGG16:
    """The py-faster-rcnn VGG16 net the reference documents via its
    checked-in prototxt (`reference/train_frcnn.prototxt`)."""

    def test_trunk_stride16_and_channels(self):
        from replication_faster_rcnn_tpu.models.vgg import VGG16Trunk

        trunk = VGG16Trunk(jnp.float32)
        x = jnp.zeros((1, 112, 150, 3))
        vars_ = trunk.init(jax.random.PRNGKey(0), x, train=False)
        y = trunk.apply(vars_, x, train=False)
        # ceil pooling: 150 -> 75 -> 38 -> 19 -> 10 (Caffe rounding)
        assert y.shape == (1, 7, 10, 512)

    def test_trunk_remat_preserves_params_and_grads(self):
        """remat=True must keep the flat conv1_1.. parameter names (the
        converter contract) and compute identical outputs/gradients."""
        from replication_faster_rcnn_tpu.models.vgg import VGG16Trunk

        x = jnp.ones((1, 48, 48, 3))
        m0 = VGG16Trunk(jnp.float32)
        m1 = VGG16Trunk(jnp.float32, remat=True)
        v0 = m0.init(jax.random.PRNGKey(0), x)
        v1 = m1.init(jax.random.PRNGKey(0), x)
        assert jax.tree_util.tree_structure(v0) == jax.tree_util.tree_structure(v1)
        np.testing.assert_allclose(
            np.asarray(m0.apply(v0, x)), np.asarray(m1.apply(v0, x)), rtol=1e-6
        )
        g0 = jax.grad(lambda v: m0.apply(v, x).sum())(v0)
        g1 = jax.grad(lambda v: m1.apply(v, x).sum())(v0)
        for a, b in zip(
            jax.tree_util.tree_leaves(g0), jax.tree_util.tree_leaves(g1)
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6
            )

    def test_tail_embeds_and_dropout_gates(self):
        from replication_faster_rcnn_tpu.models.vgg import VGG16Tail

        tail = VGG16Tail(jnp.float32)
        x = jax.random.normal(jax.random.PRNGKey(0), (3, 7, 7, 512))
        vars_ = tail.init(jax.random.PRNGKey(1), x, train=False)
        y = tail.apply(vars_, x, train=False)
        assert y.shape == (3, 4096)
        # train mode: dropout active, needs rng, output differs from eval
        y_tr = tail.apply(vars_, x, train=True, rngs={"dropout": jax.random.PRNGKey(2)})
        assert not np.allclose(np.asarray(y), np.asarray(y_tr))

    def test_assembly_forward(self):
        cfg = _small_cfg(backbone="vgg16", roi_op="pool")
        model, variables = faster_rcnn.init_variables(cfg, jax.random.PRNGKey(0))
        out = model.apply(variables, jnp.zeros((1, 96, 96, 3)), train=False)
        logits, deltas, rois, valid, cls, reg, anchors = out
        assert cls.shape == (1, cfg.proposals.post_nms_test, cfg.model.num_classes)

    def test_fc6_kernel_layout_matches_torch_flatten(self):
        import torch
        import torch.nn.functional as F

        c, h, w_, o = 5, 2, 3, 4
        wt = torch.randn(o, c * h * w_)
        x = torch.randn(2, c, h, w_)
        ref = F.linear(x.flatten(1), wt).numpy()

        kernel = convert._fc_kernel_from_chw(wt, c, h, w_)
        x_hwc = jnp.asarray(x.numpy()).transpose(0, 2, 3, 1).reshape(2, -1)
        y = x_hwc @ jnp.asarray(kernel)
        np.testing.assert_allclose(np.asarray(y), ref, rtol=1e-5, atol=1e-5)

    def test_convert_vgg16_tree_matches_flax_init(self):
        import torch
        from replication_faster_rcnn_tpu.models.vgg import VGG16Trunk

        trunk = VGG16Trunk(jnp.float32)
        vars_ = trunk.init(jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)), train=False)

        # synthesize a torchvision-shaped state_dict from the flax shapes
        state = {}
        for idx, name in convert._VGG16_FEATURE_IDX.items():
            kh, kw, i, o = vars_["params"][name]["kernel"].shape
            state[f"features.{idx}.weight"] = torch.randn(o, i, kh, kw)
            state[f"features.{idx}.bias"] = torch.randn(o)
        state["classifier.0.weight"] = torch.randn(8, 512 * 2 * 2)
        state["classifier.0.bias"] = torch.randn(8)
        state["classifier.3.weight"] = torch.randn(8, 8)
        state["classifier.3.bias"] = torch.randn(8)

        tp, _ = convert.convert_vgg16(state, roi_size=2)
        same = jax.tree_util.tree_map(
            lambda a, b: tuple(a.shape) == tuple(np.shape(b)), vars_["params"], tp
        )
        assert all(jax.tree_util.tree_leaves(same))

    @pytest.mark.slow
    def test_convert_vgg16_numeric_forward_parity(self):
        """End-to-end converter numerics (the resnet18 equivalent of this
        test exists in TestConvertNumerics): a torchvision-layout VGG16
        state_dict pushed through convert_vgg16 must make the flax trunk
        and tail reproduce the torch forward. torchvision isn't installed,
        so the oracle is the same Sequential layout built from torch.nn
        (feature indices match convert._VGG16_FEATURE_IDX by
        construction)."""
        import torch
        from replication_faster_rcnn_tpu.models.vgg import VGG16Tail, VGG16Trunk

        torch.manual_seed(0)
        plan = [64, 64, "M", 128, 128, "M", 256, 256, 256, "M",
                512, 512, 512, "M", 512, 512, 512]
        layers, in_c = [], 3
        for v in plan:
            if v == "M":
                layers.append(torch.nn.MaxPool2d(2, 2))
            else:
                layers.append(torch.nn.Conv2d(in_c, v, 3, padding=1))
                layers.append(torch.nn.ReLU())
                in_c = v
        features = torch.nn.Sequential(*layers)
        rs, width = 2, 4096  # fc widths are fixed by VGG16Tail's Dense decl
        classifier = torch.nn.Sequential(
            torch.nn.Linear(512 * rs * rs, width),
            torch.nn.ReLU(),
            torch.nn.Dropout(),
            torch.nn.Linear(width, width),
            torch.nn.ReLU(),  # torchvision classifier.4; no params
        )
        state = {f"features.{k}": v for k, v in features.state_dict().items()}
        state.update(
            {f"classifier.{k}": v for k, v in classifier.state_dict().items()}
        )
        tp, lp = convert.convert_vgg16(state, roi_size=rs)

        # trunk: 64x64 input (multiple of 16 -> ceil pooling == torch floor)
        x = torch.randn(2, 3, 64, 64)
        with torch.no_grad():
            ref_feat = features(x).numpy()  # [2, 512, 4, 4]
        trunk = VGG16Trunk(jnp.float32)
        got_feat = np.asarray(
            trunk.apply({"params": tp}, jnp.asarray(x.numpy().transpose(0, 2, 3, 1)))
        )
        np.testing.assert_allclose(
            got_feat, ref_feat.transpose(0, 2, 3, 1), rtol=1e-4, atol=1e-4
        )

        # tail: torch flattens CHW, ours flattens HWC — the converted fc6
        # kernel must absorb the layout difference
        crop = torch.randn(3, 512, rs, rs)
        classifier.eval()  # torch Dropout is active by default
        with torch.no_grad():
            ref_emb = classifier(crop.flatten(1)).numpy()
        tail = VGG16Tail(jnp.float32)
        got_emb = np.asarray(
            tail.apply(
                {"params": lp},
                jnp.asarray(crop.numpy().transpose(0, 2, 3, 1)),
                train=False,
            )
        )
        np.testing.assert_allclose(got_emb, ref_emb, rtol=1e-4, atol=1e-4)


class TestFasterRCNNAssembly:
    def test_forward_shapes_fixed(self):
        cfg = _small_cfg()
        model, variables = faster_rcnn.init_variables(cfg, jax.random.PRNGKey(0))
        imgs = jnp.zeros((2, 96, 96, 3))
        logits, deltas, rois, valid, cls, reg, anchors = model.apply(
            variables, imgs, train=False
        )
        A = cfg.num_anchors()
        P = cfg.proposals.post_nms(False)
        C = cfg.model.num_classes
        assert logits.shape == (2, A, 2)
        assert deltas.shape == (2, A, 4)
        assert rois.shape == (2, P, 4)
        assert valid.shape == (2, P)
        assert cls.shape == (2, P, C)
        assert reg.shape == (2, P, C * 4)
        assert anchors.shape == (A, 4)

    def test_forward_is_jittable(self):
        cfg = _small_cfg()
        model, variables = faster_rcnn.init_variables(cfg, jax.random.PRNGKey(0))

        @jax.jit
        def fwd(v, x):
            return model.apply(v, x, train=False)

        out = fwd(variables, jnp.zeros((1, 96, 96, 3)))
        assert len(out) == 7

    def test_stage_methods_compose(self):
        cfg = _small_cfg(roi_op="pool")
        model, variables = faster_rcnn.init_variables(cfg, jax.random.PRNGKey(0))
        imgs = jnp.zeros((1, 96, 96, 3))
        feat = model.apply(variables, imgs, False, method="extract_features")
        logits, deltas, anchors = model.apply(variables, feat, method="rpn_forward")
        rois, valid = model.apply(
            variables, logits, deltas, anchors, 96.0, 96.0, True, method="propose"
        )
        cls, reg = model.apply(
            variables, feat, rois, 96.0, 96.0, False, method="head_forward"
        )
        assert rois.shape == (1, cfg.proposals.post_nms_train, 4)
        assert cls.shape[2] == cfg.model.num_classes

    def test_select_class_deltas(self):
        reg = jnp.arange(2 * 3 * 8, dtype=jnp.float32).reshape(2, 3, 8)  # 2 classes
        labels = jnp.asarray([[0, 1, 1], [1, 0, 0]])
        out = select_class_deltas(reg, labels)
        assert out.shape == (2, 3, 4)
        np.testing.assert_array_equal(np.asarray(out[0, 0]), np.asarray(reg[0, 0, 0:4]))
        np.testing.assert_array_equal(np.asarray(out[0, 1]), np.asarray(reg[0, 1, 4:8]))


class TestTorchConversion:
    """Layout rules validated against torch functional ops directly."""

    torch = pytest.importorskip("torch")

    def test_conv_kernel_layout(self):
        import torch
        import torch.nn.functional as F

        w = torch.randn(8, 3, 3, 3)
        x = torch.randn(1, 3, 16, 16)
        ref = F.conv2d(x, w, stride=2, padding=1).permute(0, 2, 3, 1).numpy()

        kernel = convert._conv_kernel(w)
        y = jax.lax.conv_general_dilated(
            jnp.asarray(x.numpy()).transpose(0, 2, 3, 1),
            jnp.asarray(kernel),
            window_strides=(2, 2),
            padding=((1, 1), (1, 1)),
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )
        np.testing.assert_allclose(np.asarray(y), ref, rtol=1e-4, atol=1e-5)

    def test_grouped_conv_kernel_layout(self):
        # resnext's grouped 3x3: the OIHW->HWIO transpose is group-agnostic,
        # but verify end-to-end against torch's groups= semantics.
        import torch
        import torch.nn.functional as F

        groups = 4
        w = torch.randn(16, 8 // groups * 2, 3, 3)  # out=16, in/groups=4
        x = torch.randn(1, 16, 10, 10)
        ref = F.conv2d(x, w, padding=1, groups=groups).permute(0, 2, 3, 1).numpy()

        kernel = convert._conv_kernel(w)
        y = jax.lax.conv_general_dilated(
            jnp.asarray(x.numpy()).transpose(0, 2, 3, 1),
            jnp.asarray(kernel),
            window_strides=(1, 1),
            padding=((1, 1), (1, 1)),
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            feature_group_count=groups,
        )
        np.testing.assert_allclose(np.asarray(y), ref, rtol=1e-4, atol=1e-5)

    def test_bn_entries_semantics(self):
        import torch

        bn = torch.nn.BatchNorm2d(4)
        bn.running_mean += torch.randn(4)
        bn.running_var += torch.rand(4)
        bn.weight.data = torch.randn(4)
        bn.bias.data = torch.randn(4)
        bn.eval()
        x = torch.randn(2, 4, 5, 5)
        ref = bn(x).detach().permute(0, 2, 3, 1).numpy()

        state = {f"b.{k}": v for k, v in bn.state_dict().items()}
        params, stats = convert._bn_entries("b", state)
        xn = jnp.asarray(x.numpy()).transpose(0, 2, 3, 1)
        y = (xn - stats["mean"]) / jnp.sqrt(stats["var"] + 1e-5) * params[
            "scale"
        ] + params["bias"]
        np.testing.assert_allclose(np.asarray(y), ref, rtol=1e-4, atol=1e-5)

    def test_trunk_tree_structure_matches_flax_init(self):
        import torch

        # Build a state_dict with resnet18's exact key/shape inventory from
        # the flax init (reverse-mapped), then convert and compare trees.
        trunk = ResNetTrunk("resnet18", jnp.float32)
        vars_ = trunk.init(jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)), train=False)

        state = {}

        def add_conv(tname, kernel):
            kh, kw, i, o = kernel.shape
            state[f"{tname}.weight"] = torch.randn(o, i, kh, kw)

        def add_bn(tname, n):
            state[f"{tname}.weight"] = torch.randn(n)
            state[f"{tname}.bias"] = torch.randn(n)
            state[f"{tname}.running_mean"] = torch.randn(n)
            state[f"{tname}.running_var"] = torch.rand(n)

        params = vars_["params"]
        add_conv("conv1", params["conv1"]["kernel"])
        add_bn("bn1", 64)
        for key, block in params.items():
            if not key.startswith("layer"):
                continue
            for sub, leaf in block.items():
                tname = f"{key}.{sub}"
                if sub.startswith("conv"):
                    add_conv(tname, leaf["kernel"])
                elif sub == "downsample_conv":
                    add_conv(f"{key}.downsample.0", leaf["kernel"])
                elif sub == "downsample_bn":
                    add_bn(f"{key}.downsample.1", leaf["scale"].shape[0])
                else:
                    add_bn(tname, leaf["scale"].shape[0])

        cp, cs = convert.convert_trunk(state)
        # Identical tree structure and per-leaf shapes (tree_map raises on
        # structure mismatch).
        same_p = jax.tree_util.tree_map(
            lambda a, b: tuple(a.shape) == tuple(np.shape(b)), params, cp
        )
        assert all(jax.tree_util.tree_leaves(same_p))
        same_s = jax.tree_util.tree_map(
            lambda a, b: tuple(a.shape) == tuple(np.shape(b)),
            vars_["batch_stats"],
            cs,
        )
        assert all(jax.tree_util.tree_leaves(same_s))


class TestTorchCheckpointNumericParity:
    """A REAL converted checkpoint's numerics, end-to-end — not just layout.

    Builds the reference's own resnet18 (`/root/reference/nets/resnet_torch.py`
    is importable with the image's torch CPU), populates nontrivial BN
    running statistics with train-mode forwards, saves the state_dict as the
    `.pth` the reference warm-starts from (`nets/resnet_torch.py:392-409`,
    `readme.md:10-12`), converts it with `models/convert.py`, and asserts
    the flax trunk/tail reproduce the torch features/classifier outputs.
    """

    torch = pytest.importorskip("torch")

    @pytest.fixture(scope="class")
    def reference_split(self, tmp_path_factory):
        import sys

        import torch

        sys.path.insert(0, "/root/reference")
        try:
            from nets.resnet_torch import resnet18, resnet_backbone
        except ImportError:
            # torch may be installed without the reference checkout
            pytest.skip("reference repo not available at /root/reference")
        finally:
            sys.path.pop(0)

        torch.manual_seed(0)
        model = resnet18()
        # a few train-mode forwards so running_mean/var move off their 0/1
        # init — otherwise stat conversion isn't actually exercised
        model.train()
        with torch.no_grad():
            for i in range(3):
                model(torch.randn(4, 3, 64, 64, generator=torch.Generator().manual_seed(i)))
        model.eval()

        pth = tmp_path_factory.mktemp("ckpt") / "resnet18-5c106cde.pth"
        torch.save(model.state_dict(), str(pth))

        features, classifier = resnet_backbone(resnet18, str(pth))
        features.eval()
        classifier.eval()

        x = torch.randn(2, 3, 96, 96, generator=torch.Generator().manual_seed(42))
        with torch.no_grad():
            feats_t = features(x)            # [2, 256, 6, 6]
            tail_t = classifier(feats_t)     # [2, 512, 1, 1]
        return {
            "pth": str(pth),
            "x": x.numpy(),
            "feats": feats_t.permute(0, 2, 3, 1).numpy(),
            "tail": tail_t.flatten(1).numpy(),
        }

    def test_trunk_features_match_f32(self, reference_split):
        (tp, ts), _ = convert.load_pretrained_backbone(reference_split["pth"])
        trunk = ResNetTrunk("resnet18", jnp.float32)
        y = trunk.apply(
            {"params": tp, "batch_stats": ts},
            jnp.asarray(reference_split["x"].transpose(0, 2, 3, 1)),
            train=False,
        )
        assert y.shape == reference_split["feats"].shape
        np.testing.assert_allclose(
            np.asarray(y), reference_split["feats"], rtol=1e-3, atol=1e-4
        )

    def test_tail_features_match_f32(self, reference_split):
        _, (lp, ls) = convert.load_pretrained_backbone(reference_split["pth"])
        tail = ResNetTail("resnet18", jnp.float32)
        y = tail.apply(
            {"params": lp, "batch_stats": ls},
            jnp.asarray(reference_split["feats"]),
            train=False,
        )
        assert y.shape == reference_split["tail"].shape
        np.testing.assert_allclose(
            np.asarray(y), reference_split["tail"], rtol=1e-3, atol=1e-4
        )

    def test_trunk_features_match_bf16(self, reference_split):
        """The production compute dtype: bf16 activations over the same
        converted f32 params must track the torch f32 features to within
        bf16-appropriate error (~0.4% relative mantissa step, accumulated
        over the 3-stage trunk)."""
        (tp, ts), _ = convert.load_pretrained_backbone(reference_split["pth"])
        trunk = ResNetTrunk("resnet18", jnp.bfloat16)
        y = np.asarray(
            trunk.apply(
                {"params": tp, "batch_stats": ts},
                jnp.asarray(reference_split["x"].transpose(0, 2, 3, 1)),
                train=False,
            )
        ).astype(np.float32)
        ref = reference_split["feats"]
        rel = np.abs(y - ref).mean() / (np.abs(ref).mean() + 1e-12)
        assert rel < 0.05, f"mean relative error {rel:.4f}"

    def test_graft_into_full_detector_changes_forward(self, reference_split):
        """graft_into_variables on a full FasterRCNN variables tree: the
        grafted params must be the converted ones (spot-checked leaf) and
        the detector forward must still run."""
        cfg = _small_cfg()
        model, variables = faster_rcnn.init_variables(cfg, jax.random.PRNGKey(0))
        grafted = convert.graft_into_variables(variables, reference_split["pth"])
        (tp, _), _ = convert.load_pretrained_backbone(reference_split["pth"])
        np.testing.assert_array_equal(
            np.asarray(grafted["params"]["trunk"]["conv1"]["kernel"]),
            np.asarray(tp["conv1"]["kernel"]),
        )
        out = model.apply(grafted, jnp.zeros((1, 96, 96, 3)), train=False)
        assert all(np.isfinite(np.asarray(o)).all() for o in out)
