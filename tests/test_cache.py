"""RAM sample-cache tests (`data/cache.py`): hit/miss semantics, the
byte bound, isolation of cached arrays, and composition with the hflip
augmentation view and the DataLoader."""

import numpy as np

from replication_faster_rcnn_tpu.config import DataConfig
from replication_faster_rcnn_tpu.data import DataLoader, SyntheticDataset
from replication_faster_rcnn_tpu.data.augment import AugmentedView
from replication_faster_rcnn_tpu.data.cache import CachedView


def _cfg(**kw):
    defaults = dict(dataset="synthetic", image_size=(32, 32), max_boxes=4)
    defaults.update(kw)
    return DataConfig(**defaults)


class _Counting:
    """Dataset wrapper counting real __getitem__ decodes."""

    def __init__(self, ds):
        self.ds = ds
        self.calls = 0

    def __len__(self):
        return len(self.ds)

    def __getitem__(self, i):
        self.calls += 1
        return self.ds[i]


class TestCachedView:
    def test_decodes_once_and_returns_equal_samples(self):
        base = _Counting(SyntheticDataset(_cfg(), length=6))
        cv = CachedView(base)
        first = [cv[i] for i in range(6)]
        again = [cv[i] for i in range(6)]
        assert base.calls == 6
        for a, b in zip(first, again):
            for k in a:
                np.testing.assert_array_equal(a[k], b[k])
        assert cv.nbytes > 0

    def test_byte_bound_passes_through_uncached(self):
        base = _Counting(SyntheticDataset(_cfg(), length=4))
        cv = CachedView(base, max_bytes=0)
        s0 = cv[0]
        s0b = cv[0]
        assert base.calls == 2  # nothing cached
        assert cv.nbytes == 0
        np.testing.assert_array_equal(s0["image"], s0b["image"])

    def test_caller_key_replacement_does_not_poison_cache(self):
        cv = CachedView(SyntheticDataset(_cfg(), length=2))
        s = cv[0]
        orig = s["image"].copy()
        s["image"] = np.zeros_like(s["image"])  # replace a key, as hflip does
        np.testing.assert_array_equal(cv[0]["image"], orig)

    def test_delegates_metadata(self):
        ds = SyntheticDataset(_cfg(), length=2)
        cv = CachedView(ds)
        assert len(cv) == 2
        # attribute delegation: anything the base dataset exposes
        assert cv.cfg is ds.cfg

    def test_composes_with_augmented_view(self):
        base = _Counting(SyntheticDataset(_cfg(), length=16))
        cv = CachedView(base)
        e0 = [AugmentedView(cv, seed=0, epoch=0)[i] for i in range(16)]
        e1 = [AugmentedView(cv, seed=0, epoch=1)[i] for i in range(16)]
        # decode cost paid once, not per epoch
        assert base.calls == 16
        # flips re-roll across epochs on top of the cache
        differs = [
            not np.array_equal(a["image"], b["image"]) for a, b in zip(e0, e1)
        ]
        assert any(differs)


class TestLoaderCacheRam:
    def test_same_batches_with_and_without_cache(self):
        ds = SyntheticDataset(_cfg(), length=12)
        mk = lambda cache: DataLoader(  # noqa: E731
            ds, batch_size=4, shuffle=True, seed=3, prefetch=0,
            num_workers=1, cache_ram=cache,
        )
        plain, cached = mk(False), mk(True)
        for epoch in range(2):
            plain.set_epoch(epoch)
            cached.set_epoch(epoch)
            for a, b in zip(plain, cached):
                for k in a:
                    np.testing.assert_array_equal(a[k], b[k])

    def test_process_mode_warms_parent_cache(self):
        # fork workers die each epoch, taking their CoW caches with
        # them — the loader must warm the parent cache first so epoch 2
        # costs the parent zero decodes
        base = _Counting(SyntheticDataset(_cfg(), length=8))
        dl = DataLoader(
            base, batch_size=4, shuffle=False, prefetch=1, num_workers=2,
            worker_mode="process", cache_ram=True,
        )
        list(dl)
        assert base.calls == 8  # warm() in the parent, children hit CoW
        dl.set_epoch(1)
        list(dl)
        assert base.calls == 8

    def test_second_epoch_hits_cache(self):
        base = _Counting(SyntheticDataset(_cfg(), length=8))
        dl = DataLoader(
            base, batch_size=4, shuffle=False, prefetch=0, num_workers=1,
            cache_ram=True,
        )
        list(dl)
        assert base.calls == 8
        dl.set_epoch(1)
        list(dl)
        assert base.calls == 8


def test_evaluator_reuses_cache_across_evaluate_calls():
    import jax

    from replication_faster_rcnn_tpu.config import (
        EvalConfig,
        FasterRCNNConfig,
        ModelConfig,
    )
    from replication_faster_rcnn_tpu.eval import Evaluator
    from replication_faster_rcnn_tpu.models import faster_rcnn

    cfg = FasterRCNNConfig(
        model=ModelConfig(
            backbone="resnet18", roi_op="align", compute_dtype="float32"
        ),
        data=DataConfig(
            dataset="synthetic", image_size=(64, 64), max_boxes=8,
            loader_cache_ram=True,
        ),
        eval=EvalConfig(max_detections=20),
    )
    model, variables = faster_rcnn.init_variables(cfg, jax.random.PRNGKey(0))
    base = _Counting(
        SyntheticDataset(
            _cfg(image_size=(64, 64), max_boxes=8), split="val", length=4
        )
    )
    ev = Evaluator(cfg, model)
    ev.evaluate(variables, base, batch_size=2)
    assert base.calls == 4
    # in-training eval calls evaluate() repeatedly with the SAME dataset:
    # the decoded-sample cache must persist across calls
    ev.evaluate(variables, base, batch_size=2)
    assert base.calls == 4
