"""Pallas NMS kernel (`ops/pallas/nms_kernel.py`, ISSUE 13): selections
must be BIT-IDENTICAL to the tiled XLA backend (`ops/nms_tiled.py`) — the
same tile/fixpoint recurrence, so parity is exact equality of the
(idx, valid) outputs, not a tolerance. All tests run the kernel in
interpret mode (pure JAX): the numerics tier-1 gates here are exactly
what Mosaic compiles on a TPU, minus the codegen — which is why the
wrapper pins strict-IEEE float behavior (runtime-zero products + an
optimization_barrier on the kernel inputs; see `_iou_cols`)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from replication_faster_rcnn_tpu.ops.nms_tiled import nms_fixed_tiled
from replication_faster_rcnn_tpu.ops.pallas import nms_fixed_pallas
from tests import oracles
from tests.test_boxes import rand_boxes

pytestmark = pytest.mark.pallas_interpret


def _pair(boxes, scores, thresh, max_out, mask=None, tile=64, sorted_=False):
    """(idx, valid) from both backends; asserts bitwise equality."""
    m = None if mask is None else jnp.asarray(mask)
    b, s = jnp.asarray(boxes), jnp.asarray(scores)
    t_idx, t_val = nms_fixed_tiled(
        b, s, thresh, max_out, mask=m, tile=tile, assume_sorted=sorted_
    )
    p_idx, p_val = nms_fixed_pallas(
        b, s, thresh, max_out, mask=m, tile=tile, assume_sorted=sorted_,
        interpret=True,
    )
    np.testing.assert_array_equal(np.asarray(p_idx), np.asarray(t_idx))
    np.testing.assert_array_equal(np.asarray(p_val), np.asarray(t_val))
    return np.asarray(p_idx), np.asarray(p_val)


def test_bit_identical_across_sizes_and_tiles():
    rng = np.random.default_rng(3)
    for n in [1, 63, 65, 200, 700]:
        boxes = rand_boxes(n, rng, size=60.0)
        scores = rng.uniform(0, 1, n).astype(np.float32)
        for tile in [33, 512]:
            _pair(boxes, scores, 0.5, 50, tile=tile)


def test_matches_numpy_oracle_dense_overlaps():
    rng = np.random.default_rng(4)
    boxes = rand_boxes(300, rng, size=40.0)
    scores = rng.uniform(0, 1, 300).astype(np.float32)
    idx, val = _pair(boxes, scores, 0.5, 300, tile=64)
    assert list(idx[val]) == oracles.nms_np(boxes, scores, 0.5)[:300]


def test_score_ties_break_on_index():
    rng = np.random.default_rng(5)
    boxes = rand_boxes(160, rng, size=30.0)
    scores = (rng.integers(0, 4, 160) / 4.0).astype(np.float32)
    _pair(boxes, scores, 0.5, 80, tile=32)


def test_mask_and_nonfinite_scores():
    # the proposal path masks -inf (min-size-filtered) candidates; NaN
    # scores must also stay suppressed through both backends identically
    rng = np.random.default_rng(6)
    n = 120
    boxes = rand_boxes(n, rng, size=50.0)
    scores = rng.uniform(0, 1, n).astype(np.float32)
    scores[::7] = -np.inf
    scores[::11] = np.nan
    _pair(boxes, scores, 0.5, 60, mask=np.isfinite(scores), tile=48)


def test_assume_sorted_and_max_out_exceeding_n():
    rng = np.random.default_rng(7)
    n = 90
    boxes = rand_boxes(n, rng, size=45.0)
    scores = np.sort(rng.uniform(0, 1, n).astype(np.float32))[::-1].copy()
    idx, val = _pair(boxes, scores, 0.6, n + 7, tile=32, sorted_=True)
    # validity is a prefix; invalid slots are zeroed
    if not val.all():
        first = int(np.argmin(val))
        assert not val[first:].any()
        assert (idx[~val] == 0).all()


def test_vmap_matches_per_image():
    rng = np.random.default_rng(8)
    batch, n, out = 3, 150, 40
    boxes = np.stack([rand_boxes(n, rng, size=50.0) for _ in range(batch)])
    scores = rng.uniform(0, 1, (batch, n)).astype(np.float32)

    fn = jax.jit(
        jax.vmap(
            lambda b, s: nms_fixed_pallas(b, s, 0.5, out, interpret=True)
        )
    )
    v_idx, v_val = fn(jnp.asarray(boxes), jnp.asarray(scores))
    for i in range(batch):
        e_idx, e_val = _pair(boxes[i], scores[i], 0.5, out, tile=512)
        np.testing.assert_array_equal(np.asarray(v_idx[i]), e_idx)
        np.testing.assert_array_equal(np.asarray(v_val[i]), e_val)
