import numpy as np
import jax
import jax.numpy as jnp

from replication_faster_rcnn_tpu.ops import roi_ops
from tests import oracles


def _rand_feat_rois(rng, h=12, w=14, c=5, n=6):
    feat = rng.normal(0, 1, (h, w, c)).astype(np.float32)
    p1 = rng.uniform(0, h - 2, (n, 1)), rng.uniform(0, w - 2, (n, 1))
    hh = rng.uniform(1, h / 2, (n, 1))
    ww = rng.uniform(1, w / 2, (n, 1))
    rois = np.concatenate([p1[0], p1[1], p1[0] + hh, p1[1] + ww], axis=1).astype(
        np.float32
    )
    return feat, rois


def test_roi_pool_matches_oracle():
    rng = np.random.default_rng(0)
    feat, rois = _rand_feat_rois(rng)
    got = np.asarray(roi_ops.roi_pool(jnp.array(feat), jnp.array(rois), 7))
    want = oracles.roi_pool_np(feat, rois, 7)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_roi_pool_tiny_roi_nonempty():
    feat = np.arange(36, dtype=np.float32).reshape(6, 6, 1)
    rois = np.array([[2.2, 2.2, 2.4, 2.4]], np.float32)  # sub-pixel roi
    out = np.asarray(roi_ops.roi_pool(jnp.array(feat), jnp.array(rois), 7))
    want = oracles.roi_pool_np(feat, rois, 7)
    np.testing.assert_allclose(out, want, rtol=1e-6)
    assert np.isfinite(out).all()


def test_roi_align_matches_oracle():
    rng = np.random.default_rng(1)
    feat, rois = _rand_feat_rois(rng)
    got = np.asarray(
        roi_ops.roi_align(jnp.array(feat), jnp.array(rois), 7, sampling_ratio=2)
    )
    want = oracles.roi_align_np(feat, rois, 7, sampling=2)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_roi_align_border_rois():
    """Rois touching / slightly crossing the border must stay finite and
    match the oracle's zero-outside rule."""
    rng = np.random.default_rng(2)
    feat = rng.normal(0, 1, (8, 8, 3)).astype(np.float32)
    rois = np.array(
        [[-0.5, -0.5, 4.0, 4.0], [0, 0, 8, 8], [6.5, 6.5, 9.0, 9.0]], np.float32
    )
    got = np.asarray(roi_ops.roi_align(jnp.array(feat), jnp.array(rois), 4))
    want = oracles.roi_align_np(feat, rois, 4)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_roi_align_einsum_matches_gather():
    """The MXU formulation (separable tent-weight matmuls) must reproduce
    the direct 4-corner-gather implementation exactly, including rois
    crossing the border and degenerate (sub-pixel) rois."""
    rng = np.random.default_rng(3)
    feat, rois = _rand_feat_rois(rng, h=11, w=9, c=4, n=8)
    rois = np.concatenate(
        [
            rois,
            np.array(
                [[-0.9, -0.9, 3.0, 3.0], [8.0, 6.0, 12.0, 10.0], [2.2, 2.2, 2.3, 2.3]],
                np.float32,
            ),
        ]
    )
    a = np.asarray(
        roi_ops.roi_align(jnp.array(feat), jnp.array(rois), 7, 2, method="einsum")
    )
    b = np.asarray(
        roi_ops.roi_align(jnp.array(feat), jnp.array(rois), 7, 2, method="gather")
    )
    np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


def test_roi_align_einsum_grads_match_gather():
    rng = np.random.default_rng(4)
    feat, rois = _rand_feat_rois(rng, h=10, w=10, c=3, n=5)

    def loss(f, method):
        return (
            roi_ops.roi_align(f, jnp.array(rois), 5, 2, method=method) ** 2
        ).sum()

    ga = jax.grad(lambda f: loss(f, "einsum"))(jnp.array(feat))
    gb = jax.grad(lambda f: loss(f, "gather"))(jnp.array(feat))
    np.testing.assert_allclose(np.asarray(ga), np.asarray(gb), rtol=1e-4, atol=1e-5)


def test_roi_ops_vmap_over_batch():
    rng = np.random.default_rng(3)
    feats = np.stack([_rand_feat_rois(rng)[0] for _ in range(3)])
    rois = np.stack([_rand_feat_rois(rng)[1] for _ in range(3)])
    out = jax.vmap(lambda f, r: roi_ops.roi_align(f, r, 7))(
        jnp.array(feats), jnp.array(rois)
    )
    assert out.shape == (3, rois.shape[1], 7, 7, feats.shape[-1])


def test_roi_align_grad_flows_to_features():
    rng = np.random.default_rng(4)
    feat, rois = _rand_feat_rois(rng, h=8, w=8, c=2, n=3)

    def loss(f):
        return roi_ops.roi_align(f, jnp.array(rois), 4).sum()

    g = jax.grad(loss)(jnp.array(feat))
    assert np.abs(np.asarray(g)).sum() > 0


def test_roi_pool_grad_flows_to_features():
    rng = np.random.default_rng(5)
    feat, rois = _rand_feat_rois(rng, h=8, w=8, c=2, n=3)

    def loss(f):
        return roi_ops.roi_pool(f, jnp.array(rois), 4).sum()

    g = jax.grad(loss)(jnp.array(feat))
    assert np.abs(np.asarray(g)).sum() > 0
