"""Observability core (ISSUE 16 tentpole): W3C-style trace contexts,
the unified MetricsRegistry with Prometheus text exposition, SLO
error-budget burn-rate accounting, and the OB001 unified-metrics lint.

Everything here is pure host code — no JAX, no sockets.  The Prometheus
renderer is checked with a test-side text-format parser (the acceptance
criterion: ``/metrics`` must expose the SAME counter values the
``/stats`` JSON reports), and the burn tracker runs on an injected
clock so window expiry is deterministic.
"""

import json
import threading

import pytest

from replication_faster_rcnn_tpu.telemetry import tracecontext
from replication_faster_rcnn_tpu.telemetry.metrics import (
    PROMETHEUS_CONTENT_TYPE,
    STATS_SCHEMA,
    MetricsRegistry,
    stats_payload,
)
from replication_faster_rcnn_tpu.telemetry.slo_burn import BurnRateTracker

# ------------------------------------------------------------ trace context


class TestTraceContext:
    def test_new_context_shape(self):
        ctx = tracecontext.new_trace_context()
        assert len(ctx.trace_id) == 32 and len(ctx.span_id) == 16
        int(ctx.trace_id, 16)  # hex or raise
        int(ctx.span_id, 16)
        assert ctx.parent_span_id is None

    def test_traceparent_roundtrip(self):
        ctx = tracecontext.new_trace_context()
        header = ctx.to_traceparent()
        assert header == f"00-{ctx.trace_id}-{ctx.span_id}-01"
        back = tracecontext.parse_traceparent(header)
        assert back.trace_id == ctx.trace_id
        assert back.span_id == ctx.span_id

    @pytest.mark.parametrize("bad", [
        None,
        "",
        "garbage",
        "00-zz-zz-01",
        "00-" + "a" * 31 + "-" + "b" * 16 + "-01",   # short trace id
        "01-" + "a" * 32 + "-" + "b" * 16 + "-01",   # unknown version
        "00-" + "0" * 32 + "-" + "b" * 16 + "-01",   # all-zero trace id
        "00-" + "a" * 32 + "-" + "0" * 16 + "-01",   # all-zero span id
    ])
    def test_malformed_headers_parse_to_none(self, bad):
        assert tracecontext.parse_traceparent(bad) is None

    def test_child_and_sibling_semantics(self):
        root = tracecontext.new_trace_context()
        child = root.child()
        assert child.trace_id == root.trace_id
        assert child.span_id != root.span_id
        assert child.parent_span_id == root.span_id
        # hedged attempts: same trace AND same parent, fresh span id
        a, b = root.child(), root.child()
        assert a.span_id != b.span_id
        assert a.parent_span_id == b.parent_span_id == root.span_id
        sib = child.sibling()
        assert sib.trace_id == child.trace_id
        assert sib.parent_span_id == child.parent_span_id
        assert sib.span_id != child.span_id

    def test_span_args_carry_tree_edge(self):
        root = tracecontext.new_trace_context()
        assert root.span_args() == {
            "trace_id": root.trace_id, "span_id": root.span_id
        }
        child = root.child()
        assert child.span_args()["parent_span_id"] == root.span_id

    def test_bind_is_thread_local(self):
        assert tracecontext.current_trace() is None
        ctx = tracecontext.new_trace_context()
        seen_in_thread = []

        def other():
            seen_in_thread.append(tracecontext.current_trace())

        with tracecontext.bind(ctx):
            assert tracecontext.current_trace() is ctx
            t = threading.Thread(target=other)
            t.start()
            t.join()
            with tracecontext.bind(ctx.child()) as inner:
                assert tracecontext.current_trace() is inner
            assert tracecontext.current_trace() is ctx  # restored
        assert tracecontext.current_trace() is None
        assert seen_in_thread == [None]  # never leaks across threads


# -------------------------------------------------------- metrics registry


def parse_prometheus(text: str):
    """Minimal Prometheus text-format 0.0.4 parser: returns
    ({series -> value}, {family -> type}).  A series key is
    ``name{label="v",...}`` exactly as rendered."""
    values, types = {}, {}
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("# TYPE"):
            _, _, family, kind = line.split(None, 3)
            types[family] = kind
            continue
        if line.startswith("#"):
            continue
        series, value = line.rsplit(None, 1)
        assert series not in values, f"duplicate series {series}"
        values[series] = float(value)
    return values, types


class TestMetricsRegistry:
    def test_counter_monotonic(self):
        reg = MetricsRegistry()
        c = reg.counter("requests_total", "requests")
        c.inc()
        c.inc(2)
        assert c.value == 3
        with pytest.raises(ValueError, match="cannot decrease"):
            c.inc(-1)
        # same (name, labels) returns the same instrument
        assert reg.counter("requests_total", "requests") is c

    def test_gauge_moves_both_ways(self):
        g = MetricsRegistry().gauge("depth", "queue depth")
        g.set(5)
        g.dec(2)
        g.inc(1)
        assert g.value == 4

    def test_kind_mismatch_is_a_type_error(self):
        reg = MetricsRegistry()
        reg.counter("x_total", "x")
        with pytest.raises(TypeError, match="x_total"):
            reg.gauge("x_total", "x")

    def test_labels_make_distinct_series(self):
        reg = MetricsRegistry()
        a = reg.counter("attempts_total", "per replica", replica="r0")
        b = reg.counter("attempts_total", "per replica", replica="r1")
        assert a is not b
        a.inc(3)
        b.inc()
        flat = reg.counters_flat()
        assert flat['attempts_total{replica="r0"}'] == 3
        assert flat['attempts_total{replica="r1"}'] == 1

    def test_histogram_percentiles_and_snapshot(self):
        reg = MetricsRegistry()
        h = reg.histogram("lat_seconds", "latency",
                          buckets=(0.01, 0.1, 1.0, 10.0))
        assert h.percentile(99) == 0.0  # empty: defined, not an error
        for _ in range(100):
            h.observe(0.05)
        p50, p99 = h.percentile(50), h.percentile(99)
        # every sample landed in the (0.01, 0.1] bucket: interpolated
        # percentiles stay inside it and are monotone
        assert 0.01 <= p50 <= p99 <= 0.1
        snap = h.snapshot()
        assert snap["count"] == 100
        assert snap["sum"] == pytest.approx(5.0)
        assert snap["p50"] == pytest.approx(p50)
        # cumulative buckets end at the total count
        assert snap["buckets"]["+Inf"] == 100
        assert snap["buckets"]["0.1"] == 100

    def test_histogram_rejects_bad_buckets(self):
        reg = MetricsRegistry()
        with pytest.raises(ValueError):
            reg.histogram("h", "h", buckets=(1.0, 0.5))

    def test_collectors_refresh_gauges_on_snapshot(self):
        reg = MetricsRegistry()
        g = reg.gauge("depth", "sampled lazily")
        state = {"depth": 7}
        reg.register_collector(lambda: g.set(state["depth"]))
        assert reg.snapshot()["gauges"]["depth"] == 7
        state["depth"] = 9
        assert reg.snapshot()["gauges"]["depth"] == 9

    def test_prometheus_exposition_matches_snapshot(self):
        """The acceptance criterion at registry level: the text format
        parses and every counter value equals the JSON snapshot's."""
        reg = MetricsRegistry()
        reg.counter("requests_total", "total requests").inc(12)
        reg.counter("attempts_total", "per replica", replica="r0").inc(5)
        reg.counter("attempts_total", "per replica", replica="r1").inc(2)
        reg.gauge("depth", "queue depth").set(3)
        h = reg.histogram("lat_seconds", "latency", buckets=(0.1, 1.0))
        h.observe(0.05)
        h.observe(0.5)

        assert "version=0.0.4" in PROMETHEUS_CONTENT_TYPE
        values, types = parse_prometheus(reg.render_prometheus())
        assert types["requests_total"] == "counter"
        assert types["depth"] == "gauge"
        assert types["lat_seconds"] == "histogram"
        for series, value in reg.counters_flat().items():
            assert values[series] == value, series
        assert values["depth"] == 3
        # histogram: cumulative buckets, +Inf == count, sum matches
        assert values['lat_seconds_bucket{le="0.1"}'] == 1
        assert values['lat_seconds_bucket{le="1"}'] == 2
        assert values['lat_seconds_bucket{le="+Inf"}'] == 2
        assert values["lat_seconds_count"] == 2
        assert values["lat_seconds_sum"] == pytest.approx(0.55)

    def test_registry_is_thread_safe_under_contention(self):
        reg = MetricsRegistry()
        c = reg.counter("n_total", "contended")

        def worker():
            for _ in range(1000):
                c.inc()

        threads = [threading.Thread(target=worker) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert c.value == 8000


class TestStatsPayload:
    def test_envelope_shape(self):
        reg = MetricsRegistry()
        reg.counter("x_total", "x").inc()
        payload = stats_payload("replica", reg, stats={"x": 1})
        assert payload["schema"] == STATS_SCHEMA
        assert payload["tier"] == "replica"
        assert payload["metrics"]["counters"]["x_total"] == 1
        assert payload["stats"] == {"x": 1}

    def test_section_names_cannot_collide_with_parameters(self):
        # router.snapshot() has "registry"/"router" sections; the
        # positional-only signature must accept them as kwargs
        payload = stats_payload(
            "fleet", MetricsRegistry(), registry={"r0": {}}, tier_x=1
        )
        assert payload["registry"] == {"r0": {}}


# ----------------------------------------------------------- SLO burn rate


class TestBurnRateTracker:
    def _tracker(self, **kw):
        now = [0.0]
        kw.setdefault("availability_target", 0.999)
        kw.setdefault("short_window_s", 10.0)
        kw.setdefault("long_window_s", 100.0)
        return BurnRateTracker(clock=lambda: now[0], **kw), now

    def test_burn_is_error_rate_over_budget(self):
        tr, _ = self._tracker()
        for _ in range(99):
            tr.record(True)
        tr.record(False)  # 1% error rate against a 0.1% budget
        burns = tr.burn_rates()
        assert burns["short"] == pytest.approx(10.0)
        assert burns["long"] == pytest.approx(10.0)

    def test_alarm_requires_both_windows(self):
        """The multi-window AND rule: a burst that has already aged out
        of the short window must not alarm on the long window alone."""
        tr, now = self._tracker(alarm_burn=1.0)
        for _ in range(10):
            tr.record(False)
        assert tr.alarm()  # burst is in both windows
        now[0] = 50.0  # past the short window, inside the long one
        for _ in range(1000):
            tr.record(True)  # short window now clean
        assert tr.burn_rates()["long"] > 1.0
        assert tr.burn_rates()["short"] < 1.0
        assert not tr.alarm()

    def test_burn_clears_when_windows_age_out(self):
        tr, now = self._tracker()
        for _ in range(10):
            tr.record(False)
        assert tr.alarm()
        now[0] = 200.0  # everything expired
        assert tr.burn_rates() == {"short": 0.0, "long": 0.0}
        assert not tr.alarm()

    def test_latency_slo_counts_slow_successes_as_errors(self):
        tr, _ = self._tracker(latency_target_s=0.1)
        for _ in range(9):
            tr.record(True, latency_s=0.01)
        tr.record(True, latency_s=5.0)  # ok but over the latency SLO
        assert tr.burn_rates()["short"] == pytest.approx(100.0)

    def test_snapshot_shape(self):
        tr, _ = self._tracker()
        tr.record(True)
        tr.record(False)
        snap = tr.snapshot()
        assert snap["availability_target"] == 0.999
        assert snap["budget"] == pytest.approx(0.001)
        assert snap["samples"] == {"short": 2, "long": 2}
        assert snap["error_rates"]["short"] == pytest.approx(0.5)
        assert snap["burn_rates"]["short"] == pytest.approx(500.0)
        assert snap["alarm"] is True
        assert snap["total_ok"] == 1 and snap["total_err"] == 1

    def test_empty_tracker_is_quiet(self):
        tr, _ = self._tracker()
        assert tr.burn_rates() == {"short": 0.0, "long": 0.0}
        assert not tr.alarm()

    def test_validation(self):
        with pytest.raises(ValueError):
            BurnRateTracker(availability_target=1.5)
        with pytest.raises(ValueError):
            BurnRateTracker(short_window_s=100.0, long_window_s=10.0)


# ----------------------------------------------------------------- obslint


class TestObslint:
    def _lint(self, tmp_path, source, baseline=None):
        from replication_faster_rcnn_tpu.analysis import obslint

        p = tmp_path / "mod.py"
        p.write_text(source)
        return obslint.lint_paths([str(p)], baseline=baseline,
                                  pkg_root=str(tmp_path))

    def test_mutation_outside_init_is_flagged(self, tmp_path):
        res = self._lint(tmp_path, (
            "class Engine:\n"
            "    def __init__(self):\n"
            "        self.stats = {'shed': 0}\n"       # construction: fine
            "    def on_shed(self):\n"
            "        self.stats['shed'] += 1\n"        # OB001
            "    def merge(self, other):\n"
            "        self.stats.update(other)\n"       # OB001
            "    def read(self):\n"
            "        return self.stats['shed']\n"      # read: fine
        ))
        assert len(res.findings) == 2
        assert {f.rule for f in res.findings} == {"OB001"}
        assert {f.line for f in res.findings} == {5, 7}
        assert all("self.stats" in f.message for f in res.findings)

    def test_counters_and_suffixed_names_covered(self, tmp_path):
        res = self._lint(tmp_path, (
            "def f(router):\n"
            "    router._counters['x'] = 1\n"
            "    router.flush_stats.setdefault('y', 0)\n"
            "    router.status = 1\n"          # not a stats name: fine
            "    del router._counters['x']\n"
        ))
        assert len(res.findings) == 3

    def test_registry_module_is_exempt(self, tmp_path):
        from replication_faster_rcnn_tpu.analysis import obslint

        d = tmp_path / "telemetry"
        d.mkdir()
        p = d / "metrics.py"
        p.write_text("def f(self):\n    self.stats['x'] = 1\n")
        res = obslint.lint_paths([str(p)], pkg_root=str(tmp_path))
        assert res.findings == []

    def test_package_is_clean(self):
        """The tentpole's contract: no stats-dict mutation anywhere in
        the shipped package outside the registry itself."""
        from replication_faster_rcnn_tpu.analysis import obslint

        res = obslint.lint_package()
        assert res.findings == [], [f.to_dict() for f in res.findings]
        assert res.stale_waivers == []

    def test_frcnn_check_knows_ob001(self, capsys):
        from replication_faster_rcnn_tpu import cli

        assert cli.main(["check", "--rules", "OB001"]) == 0
        assert "finding" in capsys.readouterr().out


# --------------------------------------------------- trace timeline report


class TestTraceTimeline:
    def _events(self, tid="a" * 32):
        root, att1, att2 = "f" * 16, "1" * 16, "2" * 16
        return [
            {"name": "fleet/request", "ph": "X", "ts": 0.0, "dur": 9000.0,
             "pid": 1, "tid": 1,
             "args": {"trace_id": tid, "span_id": root}},
            {"name": "fleet/attempt", "ph": "X", "ts": 100.0, "dur": 3000.0,
             "pid": 1, "tid": 2,
             "args": {"trace_id": tid, "span_id": att1,
                      "parent_span_id": root, "replica": "r0",
                      "hedge": False, "ok": False}},
            {"name": "fleet/attempt", "ph": "X", "ts": 3500.0, "dur": 5000.0,
             "pid": 1, "tid": 2,
             "args": {"trace_id": tid, "span_id": att2,
                      "parent_span_id": root, "replica": "r1",
                      "hedge": False, "ok": True}},
            {"name": "serve/request", "ph": "X", "ts": 3700.0, "dur": 4000.0,
             "pid": 2, "tid": 1,
             "args": {"trace_id": tid, "span_id": "3" * 16,
                      "parent_span_id": att2}},
            {"name": "serve/request", "ph": "X", "ts": 0.0, "dur": 1.0,
             "pid": 3, "tid": 1,
             "args": {"trace_id": "b" * 32, "span_id": "4" * 16}},
        ]

    def test_filters_one_trace_and_derives_network_time(self):
        from replication_faster_rcnn_tpu.telemetry.report import (
            trace_timeline,
        )

        tl = trace_timeline(self._events(), "a" * 32)
        assert tl["trace_id"] == "a" * 32
        assert len(tl["spans"]) == 4  # the other trace's span excluded
        assert tl["replicas"] == ["r0", "r1"]
        winning = next(r for r in tl["spans"]
                       if r["name"] == "fleet/attempt" and r["ok"])
        # attempt 5 ms, replica-side 4 ms: 1 ms on the wire
        assert winning["network_ms"] == pytest.approx(1.0)
        assert tl["total_ms"] == pytest.approx(9.0)

    def test_unknown_trace_returns_none(self):
        from replication_faster_rcnn_tpu.telemetry.report import (
            trace_timeline,
        )

        assert trace_timeline(self._events(), "c" * 32) is None

    def test_format_names_hops_and_failures(self):
        from replication_faster_rcnn_tpu.telemetry.report import (
            format_trace_timeline,
            trace_timeline,
        )

        text = format_trace_timeline(trace_timeline(self._events(), "a" * 32))
        assert "a" * 32 in text
        assert "fleet/attempt" in text and "serve/request" in text
        assert "replica=r0" in text and "FAILED" in text
        assert "network=" in text

    def test_cli_trace_id_filter(self, tmp_path, capsys):
        from replication_faster_rcnn_tpu import cli

        d = tmp_path / "run"
        d.mkdir()
        with open(d / "trace.json", "w") as f:
            json.dump({"traceEvents": self._events(),
                       "displayTimeUnit": "ms"}, f)
        assert cli.main(
            ["telemetry", str(d), "--trace-id", "a" * 32]
        ) == 0
        out = capsys.readouterr().out
        assert "fleet/attempt" in out
        # an unknown id is a clean nonzero exit, not a stack trace
        assert cli.main(
            ["telemetry", str(d), "--trace-id", "c" * 32]
        ) == 1
