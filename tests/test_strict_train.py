"""Runtime strictness harness (ISSUE 5 tentpole, runtime half).

Unit tests prove the two detectors in isolation — the transfer guard
rejects implicit host-to-device transfers inside a strict session, and
the per-program dispatch monitor raises on any post-warmup recompile.
The e2e tests then run real training under ``debug.strict=True`` on
both acceptance feeds (per-batch loader and fused steps_per_dispatch=2)
and assert the final report shows zero implicit transfers (no
StrictViolation / no guard raise) and zero recompiles after warmup over
>= 4 trainer steps each.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from replication_faster_rcnn_tpu.analysis.strict import (
    StrictHarness,
    StrictViolation,
)
from replication_faster_rcnn_tpu.config import (
    DataConfig,
    DebugConfig,
    FasterRCNNConfig,
    MeshConfig,
    ModelConfig,
    ProposalConfig,
    ROITargetConfig,
    TrainConfig,
)


class TestStrictHarnessUnits:
    def test_session_blocks_implicit_h2d(self):
        h = StrictHarness()
        with h.session():
            with pytest.raises(Exception, match="[Dd]isallow"):
                _ = jnp.asarray(np.arange(4)) + 1

    def test_session_allows_explicit_device_put(self):
        h = StrictHarness()
        with h.session():
            x = jax.device_put(np.arange(4))
            assert int(jax.device_get(x).sum()) == 6

    def test_guard_restored_after_session(self):
        h = StrictHarness()
        with h.session():
            pass
        # implicit transfers legal again outside the session
        assert float((jnp.asarray(np.ones(2)) + 1).sum()) == 4.0

    def test_recompile_after_warmup_raises(self):
        f = jax.jit(lambda x: x * 2)
        x4, x8 = jnp.zeros(4), jnp.zeros(8)  # built before the guard
        h = StrictHarness(warmup_dispatches=1)
        with h.session():
            with h.dispatch("p", f):
                f(x4)  # warmup: compile allowed
            with h.dispatch("p", f):
                f(x4)  # warm, same shape: fine
            with pytest.raises(StrictViolation, match="recompiled"):
                with h.dispatch("p", f):
                    f(x8)  # new shape => cache grows => violation
        assert h.report()["programs"]["p"]["recompiles_after_warmup"] == 1
        assert len(h.violations) == 1

    def test_warm_dispatches_counted_per_program(self):
        f = jax.jit(lambda x: x + 1)
        g = jax.jit(lambda x: x - 1)
        x = jnp.zeros(3)
        h = StrictHarness(warmup_dispatches=1)
        with h.session():
            for fn, name in ((f, "f"), (g, "g")):
                for _ in range(3):
                    with h.dispatch(name, fn):
                        fn(x)
        rep = h.report()["programs"]
        for name in ("f", "g"):
            assert rep[name]["dispatches"] == 3
            assert rep[name]["warm_dispatches"] == 2
            assert rep[name]["recompiles_after_warmup"] == 0
        h.check()  # raises StrictViolation if anything was recorded

    def test_extended_warmup_tolerates_retrace(self):
        f = jax.jit(lambda x: x * 3)
        x4, x8, x2 = jnp.zeros(4), jnp.zeros(8), jnp.zeros(2)
        h = StrictHarness(warmup_dispatches=2)
        with h.session():
            with h.dispatch("p", f):
                f(x4)
            with h.dispatch("p", f):
                f(x8)  # second warmup dispatch: recompile allowed
            with pytest.raises(StrictViolation):
                with h.dispatch("p", f):
                    f(x2)

    def test_compile_events_scoped_per_session(self):
        """Back-to-back harnesses must not claim each other's compiles:
        the report counts start/end deltas of the process-wide listener,
        not its lifetime total (satellite: per-session accounting)."""
        from replication_faster_rcnn_tpu.analysis import strict as strict_mod

        x = jnp.zeros(5)
        h1 = StrictHarness()
        with h1.session():
            with h1.dispatch("warmup_prog", jax.jit(lambda v: v * 7)):
                pass  # arm the listener without depending on a compile
        baseline_total = strict_mod.compile_event_count()

        # compile a fresh program OUTSIDE any session: the process-wide
        # counter grows, but no harness may attribute it
        jax.jit(lambda v: v * 11 + 1)(x).block_until_ready()
        grew = strict_mod.compile_event_count() - baseline_total

        h2 = StrictHarness()
        with h2.session():
            pass
        assert h2.report()["compile_events_total"] == 0
        assert h1.session_compile_events() <= baseline_total
        if grew:
            # the stray compile is visible globally yet owned by nobody
            assert strict_mod.compile_event_count() >= baseline_total + 1

    def test_debug_config_validation(self):
        assert DebugConfig().strict is False
        assert DebugConfig(strict=True, strict_warmup=3).strict_warmup == 3
        with pytest.raises(ValueError, match="strict_warmup"):
            DebugConfig(strict_warmup=0)


def _cfg(**train_kw):
    return FasterRCNNConfig(
        model=ModelConfig(
            backbone="resnet18", roi_op="align", compute_dtype="float32"
        ),
        data=DataConfig(dataset="synthetic", image_size=(64, 64), max_boxes=8),
        train=TrainConfig(batch_size=2, n_epoch=1, **train_kw),
        mesh=MeshConfig(num_data=-1),
        proposals=ProposalConfig(pre_nms_train=64, post_nms_train=16),
        roi_targets=ROITargetConfig(n_sample=8),
        debug=DebugConfig(strict=True),
    )


def _assert_strict_clean(trainer, program, min_warm):
    assert trainer.strict is not None
    rep = trainer.strict.report()
    assert rep["violations"] == []
    prog = rep["programs"][program]
    assert prog["warm_dispatches"] >= min_warm
    assert prog["recompiles_after_warmup"] == 0
    trainer.strict.check()  # raises StrictViolation if anything slipped


class TestStrictTrainingE2E:
    """Real trainer.train() under --strict semantics: every post-warmup
    step dispatches with zero implicit transfers (the disallow guard
    would raise) and zero recompiles (the harness would raise)."""

    def test_loader_feed_strict_clean(self, tmp_path):
        from replication_faster_rcnn_tpu.data import SyntheticDataset
        from replication_faster_rcnn_tpu.train import Trainer

        cfg = _cfg()
        ds = SyntheticDataset(cfg.data, length=10)  # 5 steps, 4 post-warmup
        tr = Trainer(cfg, workdir=str(tmp_path / "w"), dataset=ds)
        tr.train(log_every=3)  # crosses a log boundary while guarded
        _assert_strict_clean(tr, "train_step", min_warm=4)

    @pytest.mark.slow  # fused-program compile alone is ~30s on CPU
    def test_fused_feed_strict_clean(self, tmp_path, monkeypatch):
        from replication_faster_rcnn_tpu.data import SyntheticDataset
        from replication_faster_rcnn_tpu.train import Trainer
        from replication_faster_rcnn_tpu.train import train_step as ts

        # loop-form scan compiles ~2x faster on CPU; the dispatch/guard
        # behavior under test is identical to the unrolled TPU default
        monkeypatch.setattr(ts, "fused_scan_unroll", lambda k: 1)
        cfg = _cfg(steps_per_dispatch=2)
        ds = SyntheticDataset(cfg.data, length=12)  # 3 chunks = 6 steps
        tr = Trainer(cfg, workdir=str(tmp_path / "w"), dataset=ds)
        tr.train(log_every=2)
        _assert_strict_clean(tr, "multi_step_k2", min_warm=2)
        rep = tr.strict.report()["programs"]["multi_step_k2"]
        # >= 4 trainer steps executed beyond the warmup chunk
        assert rep["warm_dispatches"] * 2 >= 4

    def test_cli_strict_flag_plumbs_to_config(self):
        from replication_faster_rcnn_tpu import cli

        cfg = cli._build_config(_parse(["--strict"]))
        assert cfg.debug.strict is True
        assert cli._build_config(_parse([])).debug.strict is False


def _parse(argv):
    import argparse

    from replication_faster_rcnn_tpu import cli

    parser = argparse.ArgumentParser()
    cli._add_common(parser)
    return parser.parse_args(argv)
