"""Failpoint registry + chaos harness (fast tier).

Pins the faultlib contracts: spec/schedule parsing, decision determinism
independent of thread interleaving, disarmed no-op cost, fault-kind
behaviors (delay, max_fires, after, file faults, batch poisoning), the
loader's skip-and-substitute containment of injected fetch errors, the
trainer's scheduled-save containment of an injected checkpoint.write
failure (incident + next-interval retry), and the `frcnn chaos --smoke`
acceptance harness end-to-end (twice: CLI and library, same seed =>
identical injected-event log).
"""

import json
import os
import threading
import time

import numpy as np
import pytest

from replication_faster_rcnn_tpu.faultlib import failpoints
from replication_faster_rcnn_tpu.faultlib.failpoints import (
    ChaosError,
    Fault,
    Rule,
)


@pytest.fixture(autouse=True)
def _disarm():
    """Every test starts and ends disarmed — chaos must never leak."""
    failpoints.disarm()
    yield
    failpoints.disarm()


# ---------------------------------------------------------------- parsing


class TestSpecParsing:
    def test_inline_spec_round_trip(self):
        rules = failpoints.parse_spec(
            "loader.fetch:ioerror:0.25:7,batcher.flush:delay:1.0:3:25:2"
        )
        assert rules == [
            Rule("loader.fetch", "ioerror", 0.25, 7),
            Rule("batcher.flush", "delay", 1.0, 3, arg=25.0, max_fires=2),
        ]

    def test_unknown_site_rejected(self):
        with pytest.raises(ValueError, match="unknown failpoint site"):
            failpoints.parse_spec("no.such.site:ioerror:1.0:0")

    def test_unknown_kind_rejected(self):
        with pytest.raises(ValueError, match="unknown fault kind"):
            failpoints.parse_spec("loader.fetch:explode:1.0:0")

    def test_probability_range_enforced(self):
        with pytest.raises(ValueError, match="prob"):
            failpoints.parse_spec("loader.fetch:ioerror:1.5:0")

    def test_malformed_field_count_rejected(self):
        with pytest.raises(ValueError, match="bad failpoint spec"):
            failpoints.parse_spec("loader.fetch:ioerror")

    def test_json_schedule_file(self, tmp_path):
        sched = {
            "rules": [
                {
                    "site": "checkpoint.write",
                    "kind": "torn_write",
                    "prob": 1.0,
                    "seed": 11,
                    "arg": 4,
                    "max_fires": 1,
                    "after": 1,
                },
            ]
        }
        p = tmp_path / "sched.json"
        p.write_text(json.dumps(sched))
        for spec in (str(p), f"@{p}"):
            rules = failpoints.parse_spec(spec)
            assert rules == [
                Rule(
                    "checkpoint.write", "torn_write", 1.0, 11,
                    arg=4.0, max_fires=1, after=1,
                )
            ]

    def test_configure_empty_spec_disarms(self):
        failpoints.configure("loader.fetch:ioerror:1.0:0")
        assert failpoints.armed()
        failpoints.configure("")
        assert not failpoints.armed()


# ----------------------------------------------------------- determinism


def _hammer(site, n_threads=8, hits_per_thread=50):
    """Fire one site from many threads at once; return the event log."""
    start = threading.Barrier(n_threads)

    def worker():
        start.wait()
        for _ in range(hits_per_thread):
            try:
                failpoints.fire(site)
            except ChaosError:
                pass

    threads = [threading.Thread(target=worker) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return failpoints.event_log()


class TestDeterminism:
    def test_same_seed_same_sequence_across_thread_interleavings(self):
        logs = []
        for _ in range(2):
            failpoints.configure("loader.fetch:ioerror:0.3:123")
            logs.append(_hammer("loader.fetch"))
            failpoints.disarm()
        assert logs[0], "schedule injected nothing"
        # the k-th hit's decision is a pure function of (seed, site, kind,
        # k): the fired hit-index set is identical run to run, no matter
        # how the 8 threads interleaved
        assert logs[0] == logs[1]
        assert [e["seq"] for e in logs[0]] == sorted(
            {e["seq"] for e in logs[0]}
        )

    def test_different_seeds_differ(self):
        fired = []
        for seed in (1, 2):
            failpoints.configure(f"loader.fetch:ioerror:0.5:{seed}")
            _hammer("loader.fetch", n_threads=2, hits_per_thread=100)
            fired.append([e["seq"] for e in failpoints.event_log()])
            failpoints.disarm()
        assert fired[0] != fired[1]

    def test_sites_have_independent_streams(self):
        failpoints.configure(
            "loader.fetch:ioerror:0.5:9,batcher.flush:ioerror:0.5:9"
        )
        for _ in range(50):
            for site in ("loader.fetch", "batcher.flush"):
                try:
                    failpoints.fire(site)
                except ChaosError:
                    pass
        hits = failpoints.site_hits()
        assert hits["loader.fetch"] == hits["batcher.flush"] == 50


# -------------------------------------------------------- disarmed no-op


class TestDisarmedNoOp:
    def test_fire_returns_none_and_logs_nothing(self):
        assert failpoints.fire("loader.fetch", index=3) is None
        assert failpoints.event_log() == []
        assert failpoints.site_hits() == {}

    def test_disarmed_fire_is_cheap(self):
        # the disarmed path is one module-global boolean test; 200k calls
        # in well under a second even on a loaded CI box. This is the
        # regression tripwire for someone adding work before the guard.
        t0 = time.perf_counter()
        for _ in range(200_000):
            failpoints.fire("batcher.flush")
        assert time.perf_counter() - t0 < 1.0


# ------------------------------------------------------- kind behaviors


class TestKinds:
    def test_max_fires_exhausts(self):
        failpoints.configure("loader.fetch:ioerror:1.0:0:0:2")
        errs = 0
        for _ in range(5):
            try:
                failpoints.fire("loader.fetch")
            except ChaosError:
                errs += 1
        assert errs == 2

    def test_after_skips_early_hits(self):
        failpoints.configure(
            [Rule("loader.fetch", "ioerror", 1.0, 0, max_fires=1, after=3)]
        )
        outcomes = []
        for _ in range(5):
            try:
                failpoints.fire("loader.fetch")
                outcomes.append("ok")
            except ChaosError:
                outcomes.append("err")
        assert outcomes == ["ok", "ok", "ok", "err", "ok"]

    def test_delay_sleeps_at_site(self):
        failpoints.configure("http.handler:delay:1.0:0:30:1")
        t0 = time.perf_counter()
        inj = failpoints.fire("http.handler")
        assert time.perf_counter() - t0 >= 0.025
        assert inj.kind == "delay"

    def test_torn_write_truncates(self, tmp_path):
        p = tmp_path / "f.bin"
        p.write_bytes(b"0123456789")
        fault = Fault("checkpoint.write", "torn_write", seq=0, arg=4.0)
        touched = failpoints.apply_file_fault(fault, str(p))
        assert touched == [str(p)]
        assert p.read_bytes() == b"0123"

    def test_crc_corrupt_flips_byte_same_length(self, tmp_path):
        d = tmp_path / "step"
        d.mkdir()
        (d / "data.bin").write_bytes(b"abcdef")
        fault = Fault("checkpoint.write", "crc_corrupt", seq=0, arg=0.0)
        failpoints.apply_file_fault(fault, str(d))
        got = (d / "data.bin").read_bytes()
        assert len(got) == 6 and got != b"abcdef"

    def test_poison_batch_nans_images_only(self):
        batch = {
            "image": np.ones((2, 4, 4, 3), np.float32),
            "label": np.arange(2),
        }
        bad = failpoints.poison_batch(batch)
        assert np.isnan(bad["image"]).all()
        np.testing.assert_array_equal(bad["label"], batch["label"])
        assert np.isfinite(batch["image"]).all()  # original untouched

    def test_sink_sees_every_event(self):
        seen = []
        failpoints.configure(
            "loader.fetch:ioerror:1.0:0:0:2", sink=seen.append
        )
        for _ in range(3):
            try:
                failpoints.fire("loader.fetch", index=7)
            except ChaosError:
                pass
        assert len(seen) == 2
        assert all(e["site"] == "loader.fetch" for e in seen)
        assert all(e["index"] == 7 for e in seen)


# --------------------------------------------- containment: data loader


class TestLoaderContainment:
    def test_fetch_substitutes_neighbors_under_injected_ioerror(self):
        from replication_faster_rcnn_tpu.config import DataConfig
        from replication_faster_rcnn_tpu.data import SyntheticDataset
        from replication_faster_rcnn_tpu.data.loader import fetch_sample

        ds = SyntheticDataset(
            DataConfig(dataset="synthetic", image_size=(16, 16), max_boxes=4),
            length=8,
        )
        failpoints.configure("loader.fetch:ioerror:0.4:5")
        skipped = []
        for i in range(len(ds)):
            sample = fetch_sample(
                ds, i, on_skip=lambda idx, exc: skipped.append(idx)
            )
            assert np.isfinite(sample["image"]).all()
        assert skipped, "0.4-probability rule never fired over 8 fetches"

    def test_nan_kind_poisons_fetched_sample(self):
        from replication_faster_rcnn_tpu.config import DataConfig
        from replication_faster_rcnn_tpu.data import SyntheticDataset
        from replication_faster_rcnn_tpu.data.loader import fetch_sample

        ds = SyntheticDataset(
            DataConfig(dataset="synthetic", image_size=(16, 16), max_boxes=4),
            length=4,
        )
        failpoints.configure("loader.fetch:nan:1.0:0:0:1")
        sample = fetch_sample(ds, 0)
        assert np.isnan(sample["image"]).all()


# -------------------------------------- containment: checkpoint.write


def _shim_trainer(tmp_path):
    """A Trainer stripped to its save path: real orbax manager + manifest
    machinery, no model/optimizer construction (that is what keeps this
    in the fast tier). ``Trainer.save`` touches exactly these attrs."""
    import orbax.checkpoint as ocp

    from replication_faster_rcnn_tpu.telemetry import spans as tspans
    from replication_faster_rcnn_tpu.train.trainer import Trainer

    tr = Trainer.__new__(Trainer)
    tr.workdir = str(tmp_path)
    tr.config = None
    tr._topology = {"process_count": 1, "device_count": 1}
    tr._async_writer = None
    tr.tracer = tspans.NULL_TRACER
    tr.watchdog = None
    incidents = []
    tr._fault_incident = lambda kind, **f: incidents.append((kind, f))
    state = {
        "params": {"w": np.ones((4, 4), np.float32)},
        "step": np.zeros((), np.int64),
    }
    tr._replicated_state = lambda: state
    tr._ckpt_mgr = ocp.CheckpointManager(  # backs the lazy property
        str(tmp_path),
        options=ocp.CheckpointManagerOptions(max_to_keep=4, create=True),
    )
    return tr, incidents


class TestCheckpointWriteContainment:
    def test_injected_scheduled_save_failure_contained_and_retried(
        self, tmp_path, capsys
    ):
        tr, incidents = _shim_trainer(tmp_path)
        try:
            failpoints.configure("checkpoint.write:ioerror:1.0:0:0:1")
            # first save: injected IOError rides the scheduled containment
            assert tr.save(step=1, kind="scheduled") is False
            assert tr.checkpoint_manager.latest_step() is None
            kinds = [k for k, _ in incidents]
            assert "checkpoint_save_failed" in kinds
            assert "injected IOError" in capsys.readouterr().err
            # rule exhausted (max_fires=1): the retry lands
            assert tr.save(step=1, kind="scheduled") is True
            assert tr.checkpoint_manager.latest_step() == 1
        finally:
            tr.checkpoint_manager.close()

    def test_injected_required_save_failure_raises(self, tmp_path):
        tr, _ = _shim_trainer(tmp_path)
        try:
            failpoints.configure("checkpoint.write:ioerror:1.0:0")
            with pytest.raises(ChaosError):
                tr.save(step=1, kind="emergency")
        finally:
            tr.checkpoint_manager.close()

    def test_torn_manifest_discards_step_on_restore(self, tmp_path):
        """checkpoint.manifest torn_write garbles the sidecar; the
        verified restore must refuse that step."""
        from replication_faster_rcnn_tpu.train import fault

        tr, _ = _shim_trainer(tmp_path)
        try:
            assert tr.save(step=1, kind="scheduled") is True
            failpoints.configure(
                "checkpoint.manifest:torn_write:1.0:0:3:1"
            )
            assert tr.save(step=2, kind="scheduled") is True
            assert fault.load_manifest(str(tmp_path), 2) is None
            assert fault.load_manifest(str(tmp_path), 1) is not None
        finally:
            tr.checkpoint_manager.close()


# ----------------------------------------------------- acceptance smoke


class TestChaosSmoke:
    def test_run_smoke_invariants_and_reproducibility(self, tmp_path):
        from replication_faster_rcnn_tpu.faultlib import chaos

        result = chaos.run_smoke(str(tmp_path), seed=4)
        assert result["ok"] is True
        assert result["injected_events"] > 0
        assert result["legs"]["loader"]["skipped"] >= 0
        assert (
            result["legs"]["checkpoint"]["restored_step"]
            < result["legs"]["checkpoint"]["torn_step"]
        )
        assert result["legs"]["batcher"]["recovered"] is True
        # the fleet leg: seeded rank-1 loss, 1-rank re-formed plan, and
        # the bring-up (collective.init) replay of the same loss
        assert result["legs"]["fleet"] == {
            "dropped_rank": 1,
            "reformed_world": 1,
            "init_dropped_rank": 1,
        }
        assert not failpoints.armed()  # run_smoke must clean up

    def test_cli_chaos_smoke_subcommand(self, tmp_path, capsys):
        from replication_faster_rcnn_tpu import cli

        rc = cli.main(
            ["chaos", "--smoke", "--seed", "2",
             "--workdir", str(tmp_path), "--json"]
        )
        assert rc == 0
        out = json.loads(capsys.readouterr().out)
        assert out["ok"] is True and out["seed"] == 2
        # both passes left their stores behind under --workdir
        assert os.path.isdir(tmp_path / "pass1")
        assert os.path.isdir(tmp_path / "pass2")

    def test_cli_chaos_without_smoke_flag_errors(self, capsys):
        from replication_faster_rcnn_tpu import cli

        assert cli.main(["chaos"]) == 2
        assert "--smoke" in capsys.readouterr().err
