import numpy as np

from replication_faster_rcnn_tpu.config import AnchorConfig, get_config
from replication_faster_rcnn_tpu.ops import anchors as A


def test_anchor_base_sizes():
    """Reference utils/anchors.py:5-31: h = base*scale*sqrt(ratio),
    w = base*scale/sqrt(ratio); ratio-major ordering."""
    base = A.anchor_base()
    assert base.shape == (9, 4)
    h = base[:, 2] - base[:, 0]
    w = base[:, 3] - base[:, 1]
    # areas: (base*scale)^2 regardless of ratio
    areas = h * w
    np.testing.assert_allclose(
        areas, np.array([128, 256, 512, 128, 256, 512, 128, 256, 512]) ** 2.0, rtol=1e-5
    )
    # ratio = h/w
    np.testing.assert_allclose(h / w, [0.5] * 3 + [1.0] * 3 + [2.0] * 3, rtol=1e-5)
    # centered at origin
    np.testing.assert_allclose(base[:, :2] + base[:, 2:], 0, atol=1e-4)


def test_grid_ordering_and_centers():
    base = A.anchor_base()
    g = A.grid_anchors(base, 16, 3, 5)
    assert g.shape == (3 * 5 * 9, 4)
    # anchor k at cell (r, c) lives at flat (r*W + c)*K + k
    r, c, k = 1, 3, 6
    a = g[(r * 5 + c) * 9 + k]
    np.testing.assert_allclose(
        a, base[k] + np.array([r * 16, c * 16, r * 16, c * 16]), rtol=1e-6
    )
    # correct row/col pairing: row coord moves with r, col coord with c
    a_next_row = g[((r + 1) * 5 + c) * 9 + k]
    np.testing.assert_allclose(a_next_row - a, [16, 0, 16, 0], atol=1e-5)
    a_next_col = g[(r * 5 + (c + 1)) * 9 + k]
    np.testing.assert_allclose(a_next_col - a, [0, 16, 0, 16], atol=1e-5)


def test_full_config_anchor_count():
    cfg = get_config("voc_resnet18")
    assert cfg.feature_size() == (38, 38)  # 600 -> 38 at stride 16
    anchors = A.make_anchors(cfg.anchors, cfg.feature_size())
    assert anchors.shape == (38 * 38 * 9, 4)
    assert cfg.num_anchors() == 38 * 38 * 9


def test_feature_size_other_shapes():
    cfg = get_config("voc_resnet18")
    assert cfg.feature_size((128, 128)) == (8, 8)
    assert cfg.feature_size((601, 333)) == (38, 21)


def test_single_scale_config():
    base = A.anchor_base(scales=(8.0,))
    assert base.shape == (3, 4)
    cfg = AnchorConfig(scales=(8.0,))
    assert cfg.num_base_anchors == 3
