"""Pallas ROIAlign (`ops/pallas/roi_kernel.py`, ISSUE 13): three-way
parity einsum / gather / pallas-interpret, edge cases included.

Unlike the NMS kernel (bit-identical by construction), the fused forward
reassociates the separable bilinear contraction relative to both XLA
formulations, so parity is tolerance-gated: ATOL = 1e-5 absolute against
the gather oracle (observed interpret-mode max |diff| ~2.4e-7 on
detection-scale features; the documented contract lives in PARITY.md).
The backward is the einsum formulation's VJP verbatim (custom_vjp), so
gradients are compared exactly against `method="einsum"` grads."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from replication_faster_rcnn_tpu.ops import roi_ops
from replication_faster_rcnn_tpu.ops.pallas import roi_align_pallas

pytestmark = pytest.mark.pallas_interpret

ATOL = 1e-5


def _feat(h=12, w=10, c=5, seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.standard_normal((h, w, c)).astype(np.float32))


def _three_way(feat, rois, out_size=7, sampling_ratio=2, spatial_scale=1.0):
    ein = roi_ops.roi_align(
        feat, rois, out_size, sampling_ratio, spatial_scale, method="einsum"
    )
    gat = roi_ops.roi_align(
        feat, rois, out_size, sampling_ratio, spatial_scale, method="gather"
    )
    pal = roi_align_pallas(
        feat, rois, out_size, sampling_ratio, spatial_scale, interpret=True
    )
    np.testing.assert_allclose(
        np.asarray(pal), np.asarray(gat), atol=ATOL, rtol=0
    )
    np.testing.assert_allclose(
        np.asarray(pal), np.asarray(ein), atol=ATOL, rtol=0
    )
    return pal


def test_random_rois_all_methods_agree():
    rng = np.random.default_rng(1)
    feat = _feat()
    tl = rng.uniform(0, 8, (6, 2)).astype(np.float32)
    wh = rng.uniform(0.5, 4, (6, 2)).astype(np.float32)
    rois = jnp.asarray(np.concatenate([tl, tl + wh], axis=1))
    _three_way(feat, rois)


def test_border_rois_minus_one_to_extent():
    # sample points fall in the [-1, H] tent-weight border region: rois
    # flush against (and slightly past) the feature-map edges
    feat = _feat()
    rois = jnp.asarray(
        np.array(
            [
                [-0.6, -0.6, 2.0, 2.0],  # past the top-left corner
                [9.5, 7.5, 12.0, 10.0],  # past the bottom-right corner
                [0.0, 0.0, 11.0, 9.0],  # exactly the full map
            ],
            np.float32,
        )
    )
    _three_way(feat, rois)


def test_zero_area_rois():
    # degenerate rois (x1==x2, y1==y2): the extent clamps to 1px minimum
    # in every method — outputs must still agree, and be finite
    feat = _feat()
    rois = jnp.asarray(
        np.array([[3.0, 4.0, 3.0, 4.0], [0.0, 0.0, 0.0, 0.0]], np.float32)
    )
    out = _three_way(feat, rois)
    assert np.isfinite(np.asarray(out)).all()


def test_sampling_ratio_one_and_two():
    rng = np.random.default_rng(2)
    feat = _feat()
    tl = rng.uniform(0, 7, (4, 2)).astype(np.float32)
    wh = rng.uniform(1, 3, (4, 2)).astype(np.float32)
    rois = jnp.asarray(np.concatenate([tl, tl + wh], axis=1))
    for s in (1, 2):
        _three_way(feat, rois, sampling_ratio=s)


def test_spatial_scale_applied_inside_kernel():
    # the pallas wrapper applies spatial_scale itself (roi_ops.roi_align
    # delegates BEFORE its own pre-scaling) — 1/16 image-coord rois must
    # land on the same bins as pre-scaled feature-coord rois
    feat = _feat()
    rois_img = jnp.asarray(
        np.array([[16.0, 32.0, 80.0, 96.0]], np.float32)
    )
    a = roi_align_pallas(feat, rois_img, spatial_scale=1.0 / 16, interpret=True)
    b = roi_align_pallas(feat, rois_img / 16.0, interpret=True)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_gradients_match_einsum_vjp_exactly():
    rng = np.random.default_rng(3)
    feat = _feat(8, 8, 3)
    tl = rng.uniform(0, 5, (3, 2)).astype(np.float32)
    wh = rng.uniform(1, 2, (3, 2)).astype(np.float32)
    rois = jnp.asarray(np.concatenate([tl, tl + wh], axis=1))
    cot = jnp.asarray(
        rng.standard_normal((3, 7, 7, 3)).astype(np.float32)
    )

    def loss_pallas(f):
        return jnp.vdot(roi_align_pallas(f, rois, interpret=True), cot)

    def loss_einsum(f):
        return jnp.vdot(roi_ops.roi_align(f, rois, method="einsum"), cot)

    g_pal = jax.grad(loss_pallas)(feat)
    g_ein = jax.grad(loss_einsum)(feat)
    # custom_vjp replays the einsum formulation for the backward: exact
    np.testing.assert_array_equal(np.asarray(g_pal), np.asarray(g_ein))


def test_vmap_over_batch():
    rng = np.random.default_rng(4)
    batch = 2
    feats = jnp.asarray(
        rng.standard_normal((batch, 9, 9, 4)).astype(np.float32)
    )
    tl = rng.uniform(0, 6, (batch, 5, 2)).astype(np.float32)
    wh = rng.uniform(1, 2, (batch, 5, 2)).astype(np.float32)
    rois = jnp.asarray(np.concatenate([tl, tl + wh], axis=2))
    out = jax.vmap(
        lambda f, r: roi_align_pallas(f, r, interpret=True)
    )(feats, rois)
    for i in range(batch):
        ref = roi_ops.roi_align(feats[i], rois[i], method="gather")
        np.testing.assert_allclose(
            np.asarray(out[i]), np.asarray(ref), atol=ATOL, rtol=0
        )
