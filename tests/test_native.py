"""Native C++ library tests: builds via make, binds via ctypes, and matches
the numpy behavioral specs exactly (the fallbacks ARE the spec)."""

import numpy as np
import pytest

from replication_faster_rcnn_tpu.data import native_ops
from tests import oracles


@pytest.fixture(scope="module")
def lib_available():
    if not native_ops.native_available():
        pytest.skip("native library unavailable (g++/make missing?)")
    return True


class TestResizeNormalize:
    mean = np.asarray([0.485, 0.456, 0.406], np.float32)
    std = np.asarray([0.229, 0.224, 0.225], np.float32)

    def test_native_matches_numpy_spec(self, lib_available):
        rng = np.random.RandomState(0)
        img = rng.randint(0, 256, (50, 100, 3), np.uint8)
        a = native_ops.resize_normalize(img, (64, 64), self.mean, self.std)
        b = native_ops._resize_normalize_numpy(img, (64, 64), self.mean, self.std)
        np.testing.assert_allclose(a, b, atol=2e-5)

    def test_upscale_and_downscale(self, lib_available):
        rng = np.random.RandomState(1)
        for shape, out in [((20, 30, 3), (64, 48)), ((200, 300, 3), (32, 32))]:
            img = rng.randint(0, 256, shape, np.uint8)
            a = native_ops.resize_normalize(img, out, self.mean, self.std)
            b = native_ops._resize_normalize_numpy(img, out, self.mean, self.std)
            assert a.shape == (*out, 3)
            np.testing.assert_allclose(a, b, atol=2e-5)

    def test_identity_size_is_pure_normalize(self, lib_available):
        rng = np.random.RandomState(2)
        img = rng.randint(0, 256, (16, 16, 3), np.uint8)
        a = native_ops.resize_normalize(img, (16, 16), self.mean, self.std)
        expect = (img.astype(np.float32) / 255.0 - self.mean) / self.std
        np.testing.assert_allclose(a, expect, atol=2e-5)


class TestNativeNMS:
    def _case(self, n=200, seed=0):
        rng = np.random.RandomState(seed)
        r1 = rng.uniform(0, 80, (n, 1))
        c1 = rng.uniform(0, 80, (n, 1))
        boxes = np.concatenate(
            [r1, c1, r1 + rng.uniform(5, 40, (n, 1)), c1 + rng.uniform(5, 40, (n, 1))],
            axis=1,
        ).astype(np.float32)
        scores = rng.uniform(size=n).astype(np.float32)
        return boxes, scores

    def test_matches_oracle(self, lib_available):
        boxes, scores = self._case()
        keep = native_ops.nms(boxes, scores, 0.5)
        expect = oracles.nms_np(boxes, scores, 0.5)
        np.testing.assert_array_equal(keep, expect)

    def test_matches_numpy_fallback(self, lib_available):
        boxes, scores = self._case(seed=3)
        a = native_ops.nms(boxes, scores, 0.7, max_keep=20)
        b = native_ops._nms_numpy(boxes, scores, 0.7, 20)
        np.testing.assert_array_equal(a, b)

    def test_max_keep_truncates(self, lib_available):
        boxes, scores = self._case(seed=4)
        keep = native_ops.nms(boxes, scores, 0.99, max_keep=5)
        assert len(keep) == 5

    def test_empty(self, lib_available):
        keep = native_ops.nms(
            np.zeros((0, 4), np.float32), np.zeros((0,), np.float32), 0.5
        )
        assert len(keep) == 0


def test_loader_uses_native_path(tmp_path, lib_available):
    """VOC loader output must equal the native resize+normalize of the raw
    decoded image."""
    from PIL import Image

    from replication_faster_rcnn_tpu.config import DataConfig
    from replication_faster_rcnn_tpu.data import VOCDataset
    from tests.test_data import _write_voc

    root = str(tmp_path / "VOC2007")
    _write_voc(root, ["img0"])
    cfg = DataConfig(dataset="voc", root_dir=root, image_size=(64, 64), max_boxes=8)
    ds = VOCDataset(cfg, "train")
    s = ds[0]
    with Image.open(f"{root}/JPEGImages/img0.jpg") as im:
        raw = np.asarray(im.convert("RGB"), np.uint8)
    expect = native_ops.resize_normalize(
        raw, (64, 64), cfg.pixel_mean, cfg.pixel_std
    )
    np.testing.assert_allclose(s["image"], expect, atol=1e-6)


class TestScaleBoxes:
    def test_matches_numpy_semantics(self, lib_available):
        boxes = np.asarray(
            [[5, 10, 45, 60], [-1, -1, -1, -1], [7.4, 3.3, 20.6, 30.9]], np.float32
        )
        labels = np.asarray([1, -1, 5], np.int32)
        out = native_ops.scale_boxes(boxes, labels, 1.28, 0.64)
        scale = np.asarray([1.28, 0.64, 1.28, 0.64], np.float32)
        expect = np.where((labels >= 0)[:, None], np.round(boxes * scale), boxes)
        np.testing.assert_allclose(out, expect)
        # input untouched (copy semantics)
        assert boxes[0, 0] == 5.0

    def test_half_tie_rounds_to_even_like_numpy(self, lib_available):
        # scale 1.5 x coord 3 = 4.5: np.round gives 4 (half-to-even); the
        # native kernel must agree (nearbyint, not round)
        boxes = np.asarray([[3, 1, 5, 3]], np.float32)
        labels = np.asarray([1], np.int32)
        out = native_ops.scale_boxes(boxes, labels, 1.5, 1.5)
        np.testing.assert_array_equal(out[0], np.round(boxes[0] * 1.5))
