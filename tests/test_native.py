"""Native C++ library tests: builds via make, binds via ctypes, and matches
the numpy behavioral specs exactly (the fallbacks ARE the spec)."""

import numpy as np
import pytest

from replication_faster_rcnn_tpu.data import native_ops
from tests import oracles


@pytest.fixture(scope="module")
def lib_available():
    if not native_ops.native_available():
        pytest.skip("native library unavailable (g++/make missing?)")
    return True


class TestResizeNormalize:
    mean = np.asarray([0.485, 0.456, 0.406], np.float32)
    std = np.asarray([0.229, 0.224, 0.225], np.float32)

    def test_native_matches_numpy_spec(self, lib_available):
        rng = np.random.RandomState(0)
        img = rng.randint(0, 256, (50, 100, 3), np.uint8)
        a = native_ops.resize_normalize(img, (64, 64), self.mean, self.std)
        b = native_ops._resize_normalize_numpy(img, (64, 64), self.mean, self.std)
        np.testing.assert_allclose(a, b, atol=2e-5)

    def test_upscale_and_downscale(self, lib_available):
        rng = np.random.RandomState(1)
        for shape, out in [((20, 30, 3), (64, 48)), ((200, 300, 3), (32, 32))]:
            img = rng.randint(0, 256, shape, np.uint8)
            a = native_ops.resize_normalize(img, out, self.mean, self.std)
            b = native_ops._resize_normalize_numpy(img, out, self.mean, self.std)
            assert a.shape == (*out, 3)
            np.testing.assert_allclose(a, b, atol=2e-5)

    def test_identity_size_is_pure_normalize(self, lib_available):
        rng = np.random.RandomState(2)
        img = rng.randint(0, 256, (16, 16, 3), np.uint8)
        a = native_ops.resize_normalize(img, (16, 16), self.mean, self.std)
        expect = (img.astype(np.float32) / 255.0 - self.mean) / self.std
        np.testing.assert_allclose(a, expect, atol=2e-5)


class TestNativeNMS:
    def _case(self, n=200, seed=0):
        rng = np.random.RandomState(seed)
        r1 = rng.uniform(0, 80, (n, 1))
        c1 = rng.uniform(0, 80, (n, 1))
        boxes = np.concatenate(
            [r1, c1, r1 + rng.uniform(5, 40, (n, 1)), c1 + rng.uniform(5, 40, (n, 1))],
            axis=1,
        ).astype(np.float32)
        scores = rng.uniform(size=n).astype(np.float32)
        return boxes, scores

    def test_matches_oracle(self, lib_available):
        boxes, scores = self._case()
        keep = native_ops.nms(boxes, scores, 0.5)
        expect = oracles.nms_np(boxes, scores, 0.5)
        np.testing.assert_array_equal(keep, expect)

    def test_matches_numpy_fallback(self, lib_available):
        boxes, scores = self._case(seed=3)
        a = native_ops.nms(boxes, scores, 0.7, max_keep=20)
        b = native_ops._nms_numpy(boxes, scores, 0.7, 20)
        np.testing.assert_array_equal(a, b)

    def test_max_keep_truncates(self, lib_available):
        boxes, scores = self._case(seed=4)
        keep = native_ops.nms(boxes, scores, 0.99, max_keep=5)
        assert len(keep) == 5

    def test_empty(self, lib_available):
        keep = native_ops.nms(
            np.zeros((0, 4), np.float32), np.zeros((0,), np.float32), 0.5
        )
        assert len(keep) == 0


def test_loader_uses_native_path(tmp_path, lib_available):
    """VOC loader output must equal the native resize+normalize of the raw
    decoded image."""
    from PIL import Image

    from replication_faster_rcnn_tpu.config import DataConfig
    from replication_faster_rcnn_tpu.data import VOCDataset
    from tests.test_data import _write_voc

    root = str(tmp_path / "VOC2007")
    _write_voc(root, ["img0"])
    cfg = DataConfig(dataset="voc", root_dir=root, image_size=(64, 64), max_boxes=8)
    ds = VOCDataset(cfg, "train")
    s = ds[0]
    with Image.open(f"{root}/JPEGImages/img0.jpg") as im:
        raw = np.asarray(im.convert("RGB"), np.uint8)
    expect = native_ops.resize_normalize(
        raw, (64, 64), cfg.pixel_mean, cfg.pixel_std
    )
    # the loader may decode via the native libjpeg kernel while `expect`
    # decodes via PIL; decoder version skew can move pixels by ~1/255,
    # which is ~0.02 in normalized units
    np.testing.assert_allclose(s["image"], expect, atol=0.03)


class TestJpegDecode:
    mean = np.asarray([0.485, 0.456, 0.406], np.float32)
    std = np.asarray([0.229, 0.224, 0.225], np.float32)

    def _jpeg_bytes(self, arr, mode="RGB", quality=90):
        import io

        from PIL import Image

        buf = io.BytesIO()
        Image.fromarray(arr, mode).save(buf, "JPEG", quality=quality)
        return buf.getvalue()

    def test_matches_pil_decode(self, lib_available):
        """Native decode (no prescale: source < 2x target) must match the
        PIL-decode + resize_normalize pipeline to decoder-skew tolerance."""
        import io

        from PIL import Image

        rng = np.random.RandomState(3)
        # smooth image: JPEG is lossy, parity is decoder-vs-decoder only
        base = rng.randint(0, 256, (6, 8, 3), np.uint8)
        img = np.kron(base, np.ones((16, 16, 1), np.uint8))
        data = self._jpeg_bytes(img)
        got = native_ops.decode_jpeg_resize_normalize(
            data, (80, 96), self.mean, self.std
        )
        assert got is not None
        out, oh, ow = got
        assert (oh, ow) == (96, 128)
        with Image.open(io.BytesIO(data)) as im:
            raw = np.asarray(im.convert("RGB"), np.uint8)
        expect = native_ops.resize_normalize(raw, (80, 96), self.mean, self.std)
        assert np.abs(out - expect).max() < 0.05

    def test_fast_scale_close_to_full_decode(self, lib_available):
        """DCT-domain 1/8 prescale followed by bilinear must stay close to
        the full-size-decode pipeline on a smooth image."""
        rng = np.random.RandomState(4)
        base = rng.randint(60, 200, (8, 8, 3), np.uint8)
        img = np.kron(base, np.ones((64, 64, 1), np.uint8))  # 512x512
        data = self._jpeg_bytes(img, quality=95)
        fast = native_ops.decode_jpeg_resize_normalize(
            data, (64, 64), self.mean, self.std, fast_scale=True
        )
        full = native_ops.decode_jpeg_resize_normalize(
            data, (64, 64), self.mean, self.std, fast_scale=False
        )
        assert fast is not None and full is not None
        assert fast[1:] == full[1:]
        assert np.abs(fast[0] - full[0]).mean() < 0.05

    def test_grayscale_converts_to_rgb(self, lib_available):
        rng = np.random.RandomState(5)
        img = np.kron(
            rng.randint(0, 256, (4, 4), np.uint8), np.ones((16, 16), np.uint8)
        )
        data = self._jpeg_bytes(img, mode="L")
        got = native_ops.decode_jpeg_resize_normalize(
            data, (32, 32), self.mean, self.std
        )
        assert got is not None
        out, oh, ow = got
        assert (oh, ow) == (64, 64) and out.shape == (32, 32, 3)
        # denormalize channel-wise: a gray source has R == G == B
        px = out * self.std + self.mean
        assert np.abs(px[..., 0] - px[..., 1]).max() < 0.02
        assert np.abs(px[..., 1] - px[..., 2]).max() < 0.02

    def test_garbage_returns_none(self, lib_available):
        assert (
            native_ops.decode_jpeg_resize_normalize(
                b"not a jpeg at all", (32, 32), self.mean, self.std
            )
            is None
        )

    def test_stale_so_rebuilds_and_reloads(self, lib_available):
        """A pre-JPEG .so on disk must be rebuilt AND the fresh build must
        actually be used (dlopen caches by pathname, so a naive reload
        returns the stale handle — the rebuilt lib must come in under a
        unique path). Runs in a subprocess: the dlopen cache is per-process
        state this test must own from scratch."""
        import subprocess
        import sys

        code = """
import subprocess, numpy as np
import replication_faster_rcnn_tpu.data.native_ops as native_ops
# simulate the stale library: a build without the JPEG entry points
subprocess.run(["make", "-B", "-C", native_ops._REPO + "/native", "JPEG=0"],
               check=True, capture_output=True)
import io
from PIL import Image
rng = np.random.RandomState(0)
img = rng.randint(0, 256, (64, 64, 3), np.uint8)
buf = io.BytesIO(); Image.fromarray(img).save(buf, "JPEG")
mean = np.zeros(3, np.float32); std = np.ones(3, np.float32)
got = native_ops.decode_jpeg_resize_normalize(buf.getvalue(), (32, 32), mean, std)
assert got is not None, "stale .so was not rebuilt/reloaded"
assert got[1:] == (64, 64)
# the stale-handle core bindings must still work after the swap
out = native_ops.resize_normalize(img, (32, 32), mean, std)
assert out.shape == (32, 32, 3)
print("STALE-RELOAD-OK")
"""
        r = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True,
            text=True,
            timeout=300,
            cwd=native_ops._REPO,
        )
        try:
            assert r.returncode == 0 and "STALE-RELOAD-OK" in r.stdout, (
                r.stdout + r.stderr
            )
        finally:  # restore the full build for later tests/processes
            subprocess.run(
                ["make", "-B", "-C", native_ops._REPO + "/native"],
                capture_output=True,
                timeout=300,
            )

    def test_png_in_jpg_falls_back_to_pil(self, tmp_path, lib_available):
        """_load_image must survive a non-JPEG file with a .jpg name (the
        reference's datasets contain a few) via the PIL fallback."""
        from PIL import Image

        from replication_faster_rcnn_tpu.data.voc import _load_image

        rng = np.random.RandomState(6)
        img = rng.randint(0, 256, (40, 30, 3), np.uint8)
        path = str(tmp_path / "sneaky.jpg")
        Image.fromarray(img).save(path, "PNG")
        out, oh, ow = _load_image(path, (20, 20), self.mean, self.std)
        assert (oh, ow) == (40, 30)
        expect = native_ops.resize_normalize(img, (20, 20), self.mean, self.std)
        np.testing.assert_allclose(out, expect, atol=2e-5)


class TestScaleBoxes:
    def test_matches_numpy_semantics(self, lib_available):
        boxes = np.asarray(
            [[5, 10, 45, 60], [-1, -1, -1, -1], [7.4, 3.3, 20.6, 30.9]], np.float32
        )
        labels = np.asarray([1, -1, 5], np.int32)
        out = native_ops.scale_boxes(boxes, labels, 1.28, 0.64)
        scale = np.asarray([1.28, 0.64, 1.28, 0.64], np.float32)
        expect = np.where((labels >= 0)[:, None], np.round(boxes * scale), boxes)
        np.testing.assert_allclose(out, expect)
        # input untouched (copy semantics)
        assert boxes[0, 0] == 5.0

    def test_half_tie_rounds_to_even_like_numpy(self, lib_available):
        # scale 1.5 x coord 3 = 4.5: np.round gives 4 (half-to-even); the
        # native kernel must agree (nearbyint, not round)
        boxes = np.asarray([[3, 1, 5, 3]], np.float32)
        labels = np.asarray([1], np.int32)
        out = native_ops.scale_boxes(boxes, labels, 1.5, 1.5)
        np.testing.assert_array_equal(out[0], np.round(boxes[0] * 1.5))
