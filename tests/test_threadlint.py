"""threadlint concurrency analyzer: per-rule fixtures, root discovery,
attribution, waiver scoping, and the package-wide gate (ISSUE 8
tentpole).

Mirrors the jaxlint suite's structure: every rule TL001-TL006 is proven
by a positive fixture that must produce exactly that rule and a negative
fixture exercising the same shape that must stay clean. The package
gate asserts the committed baseline keeps the whole host layer at zero
unwaived findings and zero stale waivers.
"""

import os
import pathlib

import pytest

from replication_faster_rcnn_tpu.analysis.jaxlint import (
    load_baseline,
    package_root,
)
from replication_faster_rcnn_tpu.analysis.threadlint import (
    RULES,
    build_thread_index,
    lint_package,
    lint_paths,
)

FIXTURES = pathlib.Path(__file__).parent / "fixtures" / "threadlint"
ALL_RULES = sorted(RULES)


def _lint(name, baseline=None):
    return lint_paths(
        [str(FIXTURES / name)],
        baseline=baseline,
        pkg_root=str(FIXTURES),
    )


class TestRuleFixtures:
    def test_every_rule_has_fixture_pair(self):
        for rule in ALL_RULES:
            stem = rule.lower()
            assert (FIXTURES / f"{stem}_pos.py").exists(), rule
            assert (FIXTURES / f"{stem}_neg.py").exists(), rule

    @pytest.mark.parametrize("rule", ALL_RULES)
    def test_positive_fixture_flags_only_its_rule(self, rule):
        result = _lint(f"{rule.lower()}_pos.py")
        rules = sorted({f.rule for f in result.findings})
        assert rules == [rule], (
            f"{rule} positive fixture: {[str(f) for f in result.findings]}"
        )

    @pytest.mark.parametrize("rule", ALL_RULES)
    def test_negative_fixture_is_clean(self, rule):
        result = _lint(f"{rule.lower()}_neg.py")
        assert result.findings == [], (
            f"{rule} negative fixture: {[str(f) for f in result.findings]}"
        )


class TestRootDiscovery:
    def test_thread_ctor_target_becomes_root_not_edge(self):
        idx, roots, attribution = build_thread_index(
            [str(FIXTURES / "tl001_pos.py")], str(FIXTURES)
        )
        labels = {r.label for r in roots}
        assert any("_work" in lb for lb in labels), labels
        # spawn target is a root; __init__ must NOT gain a call edge to it
        fns = {
            f.qualname: f
            for mi in idx.modules.values()
            for f in mi.functions.values()
        }
        work = fns["Counter._work"]
        init = fns["Counter.__init__"]
        assert work not in idx.edges.get(init, set())

    def test_attribution_separates_worker_from_main(self):
        idx, roots, attribution = build_thread_index(
            [str(FIXTURES / "tl001_pos.py")], str(FIXTURES)
        )
        fns = {
            f.qualname: f
            for mi in idx.modules.values()
            for f in mi.functions.values()
        }
        work_labels = attribution[fns["Counter._work"]]
        bump_labels = attribution[fns["Counter.bump"]]
        assert "main" not in work_labels
        assert bump_labels == {"main"}

    def test_daemon_flag_captured(self):
        _, roots, _ = build_thread_index(
            [str(FIXTURES / "tl006_pos.py")], str(FIXTURES)
        )
        assert any(r.daemon for r in roots)
        _, roots_neg, _ = build_thread_index(
            [str(FIXTURES / "tl006_neg.py")], str(FIXTURES)
        )
        assert not any(r.daemon for r in roots_neg)


class TestWaivers:
    def _waiver_toml(self, tmp_path, finding, reason=None):
        reason = reason or "sentinel contract held by construction in tests"
        toml = tmp_path / "baseline.toml"
        toml.write_text(
            "[[waiver]]\n"
            f'rule = "{finding.rule}"\n'
            f'path = "{finding.path}"\n'
            f'func = "{finding.func}"\n'
            f'reason = "{reason}"\n'
        )
        return str(toml)

    def test_waive_then_unwaive_round_trip(self, tmp_path):
        raw = _lint("tl001_pos.py")
        assert raw.findings, "fixture must fire"
        f = raw.findings[0]
        waived = _lint(
            "tl001_pos.py", baseline=self._waiver_toml(tmp_path, f)
        )
        assert all(x.key() != f.key() for x in waived.findings)
        assert any(x.key() == f.key() for x, _ in waived.suppressed)
        assert waived.stale_waivers == []
        back = _lint("tl001_pos.py")
        assert any(x.key() == f.key() for x in back.findings)

    def test_stale_tl_waiver_reported(self, tmp_path):
        toml = tmp_path / "baseline.toml"
        toml.write_text(
            "[[waiver]]\n"
            'rule = "TL001"\n'
            'path = "tl001_neg.py"\n'
            'func = "*"\n'
            'reason = "was real before the lock landed"\n'
        )
        result = _lint("tl001_neg.py", baseline=str(toml))
        assert result.findings == []
        assert [w.rule for w in result.stale_waivers] == ["TL001"]
        assert not result.to_dict()["ok"]

    def test_jx_waivers_invisible_to_threadlint(self, tmp_path):
        """Baseline.restricted: jaxlint entries in the shared baseline
        never show up as stale here (and vice versa)."""
        toml = tmp_path / "baseline.toml"
        toml.write_text(
            "[[waiver]]\n"
            'rule = "JX001"\n'
            'path = "does_not_matter.py"\n'
            'func = "*"\n'
            'reason = "belongs to the other analyzer entirely"\n'
        )
        result = _lint("tl001_neg.py", baseline=str(toml))
        assert result.stale_waivers == []


class TestPackageGate:
    """Any new cross-thread unlocked write, unbounded queue, sentinel-less
    consumer loop, lock-order cycle, sleep-under-lock, or daemon durable
    write anywhere in the package fails tier-1 here until fixed or
    waived-with-reason."""

    def test_package_lints_clean_against_committed_baseline(self):
        result = lint_package()
        msgs = [str(f) for f in result.findings] + [
            f"stale: {w.rule} {w.path} [{w.func}]"
            for w in result.stale_waivers
        ]
        assert result.findings == [] and result.stale_waivers == [], (
            "\n".join(msgs)
        )

    def test_tl_waivers_carry_substantive_reasons(self):
        base = load_baseline(
            os.path.join(package_root(), "analysis", "baseline.toml")
        ).restricted(RULES)
        for w in base.waivers:
            assert len(w.reason) > 20, f"thin waiver reason: {w}"

    def test_raw_package_lint_findings_are_all_justified(self):
        """Every raw finding must be covered by the committed baseline —
        the waiver set documents exactly the residual risk."""
        raw = lint_package(baseline=None)
        base = load_baseline(
            os.path.join(package_root(), "analysis", "baseline.toml")
        ).restricted(RULES)
        for f in raw.findings:
            assert base.excluded(f) or base.waive(f) is not None, str(f)
