"""Serving engine (ISSUE 7 tentpole): bucketed AOT programs + continuous
micro-batching.

Pure tests pin the MicroBatcher contract (size/deadline flush triggers,
bounded-queue backpressure, drain-on-close, error relay), bucket routing
(snug-bucket selection, oversize downscale/reject), ServingConfig
validation, and the serving_profile regression-gate arithmetic — no JAX
compiles. The live module then compiles ONE 32x32 bucket (batches 1 and
2) and proves the acceptance claims end-to-end: engine detections are
bitwise-identical to `Evaluator.predict_batch`, concurrent submits
coalesce into shared flushes, partial batches pad-to-bucket and un-pad,
boxes de-normalize to original coordinates, and a strict session over
warm dispatches sees 0 implicit transfers and 0 recompiles.
"""

import dataclasses
import json
import os
import queue
import sys
import threading
import time

import numpy as np
import pytest

from replication_faster_rcnn_tpu.config import (
    DataConfig,
    EvalConfig,
    FasterRCNNConfig,
    MeshConfig,
    ModelConfig,
    ProposalConfig,
    ROITargetConfig,
    ServingConfig,
    TrainConfig,
    config_from_dict,
)
from replication_faster_rcnn_tpu.serving import (
    InferenceEngine,
    MicroBatcher,
    OversizedImageError,
    select_bucket,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ------------------------------------------------------------ micro-batcher


class TestMicroBatcher:
    def test_size_trigger_flushes_full_batch(self):
        with MicroBatcher(lambda k, items: [x * 10 for x in items],
                          max_batch=3, max_delay_s=30.0) as mb:
            futs = [mb.submit("k", i) for i in range(3)]
            # size trigger: resolves promptly despite the huge deadline
            assert [f.result(timeout=5) for f in futs] == [0, 10, 20]
            assert mb.flush_log == [("k", 3)]

    def test_deadline_flushes_partial_group(self):
        with MicroBatcher(lambda k, items: list(items),
                          max_batch=8, max_delay_s=0.05) as mb:
            fut = mb.submit("k", "lone")
            assert fut.result(timeout=5) == "lone"
            assert mb.flush_log == [("k", 1)]

    def test_groups_key_separately(self):
        with MicroBatcher(lambda k, items: [(k, x) for x in items],
                          max_batch=2, max_delay_s=30.0) as mb:
            fa = [mb.submit("a", i) for i in range(2)]
            fb = [mb.submit("b", i) for i in range(2)]
            assert [f.result(timeout=5) for f in fa] == [("a", 0), ("a", 1)]
            assert [f.result(timeout=5) for f in fb] == [("b", 0), ("b", 1)]
            assert ("a", 2) in mb.flush_log and ("b", 2) in mb.flush_log

    def test_bounded_queue_backpressure(self):
        release = threading.Event()

        def slow(k, items):
            release.wait(10)
            return list(items)

        mb = MicroBatcher(slow, max_batch=1, max_delay_s=0.0, depth=2)
        try:
            futs = [mb.submit("k", 0)]  # worker takes this and blocks
            deadline = time.monotonic() + 5
            # fill the queue to depth (the worker may drain one entry
            # into its pending group before blocking, so keep topping up)
            while time.monotonic() < deadline:
                try:
                    futs.append(mb.submit("k", 1, timeout=0.05))
                except queue.Full:
                    break
            else:
                pytest.fail("queue never filled")
            with pytest.raises(queue.Full):
                mb.submit("k", 2, timeout=0.05)
        finally:
            release.set()
            mb.close()
        assert all(f.result(timeout=5) in (0, 1) for f in futs)

    def test_close_drains_accepted_requests(self):
        with MicroBatcher(lambda k, items: list(items),
                          max_batch=100, max_delay_s=30.0) as mb:
            futs = [mb.submit("k", i) for i in range(5)]
        # close flushed the partial group (5 < max_batch, before deadline)
        assert [f.result(timeout=1) for f in futs] == list(range(5))

    def test_submit_after_close_raises(self):
        mb = MicroBatcher(lambda k, items: list(items), max_batch=1)
        mb.close()
        with pytest.raises(RuntimeError, match="closed"):
            mb.submit("k", 1)
        mb.close()  # idempotent

    def test_error_relays_to_flush_futures_and_worker_survives(self):
        def process(k, items):
            if "boom" in items:
                raise ValueError("exploded")
            return list(items)

        with MicroBatcher(process, max_batch=2, max_delay_s=30.0) as mb:
            bad = [mb.submit("k", "boom"), mb.submit("k", "x")]
            with pytest.raises(ValueError, match="exploded"):
                bad[0].result(timeout=5)
            with pytest.raises(ValueError):
                bad[1].result(timeout=5)
            # the worker keeps serving after a failed flush
            good = [mb.submit("k", 1), mb.submit("k", 2)]
            assert [f.result(timeout=5) for f in good] == [1, 2]

    def test_result_count_mismatch_fails_flush(self):
        with MicroBatcher(lambda k, items: [1], max_batch=2,
                          max_delay_s=30.0) as mb:
            futs = [mb.submit("k", i) for i in range(2)]
            with pytest.raises(RuntimeError, match="2 items"):
                futs[0].result(timeout=5)

    def test_constructor_validation(self):
        with pytest.raises(ValueError, match="max_batch"):
            MicroBatcher(lambda k, i: i, max_batch=0)
        with pytest.raises(ValueError, match="max_delay_s"):
            MicroBatcher(lambda k, i: i, max_batch=1, max_delay_s=-1)
        with pytest.raises(ValueError, match="depth"):
            MicroBatcher(lambda k, i: i, max_batch=1, depth=0)


class TestMicroBatcherDeterministic:
    """Worker-loop ordering audited with injected time (``clock``) and a
    thread-free drive (``start=False`` + ``_service_once``) — no sleeps,
    no scheduler races (ISSUE 8 satellite: deadline-flush and
    ``_CLOSE``-drain audit)."""

    def _mb(self, clock, **kw):
        kw.setdefault("max_batch", 100)
        kw.setdefault("max_delay_s", 1.0)
        return MicroBatcher(
            lambda k, items: list(items), clock=clock, start=False, **kw
        )

    def test_hot_key_backlog_cannot_starve_other_deadlines(self):
        """The deadline scan runs on EVERY iteration. Before the fix it
        ran only when the queue read timed out, so a sustained backlog on
        one key deferred every other key's deadline flush indefinitely."""
        now = [0.0]
        mb = self._mb(lambda: now[0])
        cold = mb.submit("cold", "victim")
        for i in range(8):
            mb.submit("hot", i)  # backlog: the get never goes Empty
        now[0] = 2.0  # cold's deadline long past
        # one iteration consumes ONE hot entry — and must still flush cold
        assert mb._service_once(block=False)
        assert ("cold", 1) in mb.flush_log
        assert cold.result(timeout=0) == "victim"
        assert mb.queue_depth() > 0  # hot backlog still queued; no starving
        mb.close()

    def test_deadline_is_measured_from_oldest_entry_of_group(self):
        now = [0.0]
        mb = self._mb(lambda: now[0])
        mb.submit("k", "old")
        assert mb._service_once(block=False)  # into pending at t=0
        now[0] = 0.9
        mb.submit("k", "young")  # same group, later arrival
        assert mb._service_once(block=False)
        assert mb.flush_log == []  # 0.9 < 1.0: not due yet
        now[0] = 1.05  # oldest entry (t=0) is now past max_delay_s
        assert mb._service_once(block=False)
        assert mb.flush_log == [("k", 2)]
        mb.close()

    def test_close_sentinel_flushes_all_pending_groups(self):
        now = [0.0]
        mb = self._mb(lambda: now[0])
        fa, fb = mb.submit("a", 1), mb.submit("b", 2)
        mb.close()  # threadless: drains inline through _service_once
        assert fa.result(timeout=0) == 1 and fb.result(timeout=0) == 2
        assert sorted(mb.flush_log) == [("a", 1), ("b", 1)]

    def test_close_on_full_queue_makes_room_inline(self):
        """The sentinel must get a slot even when the queue is at depth
        and no worker thread exists to drain it."""
        now = [0.0]
        mb = self._mb(lambda: now[0], depth=2)
        futs = [mb.submit("k", i) for i in range(2)]  # queue full
        mb.close()  # put(_CLOSE) hits queue.Full -> inline service
        assert [f.result(timeout=0) for f in futs] == [0, 1]

    def test_poll_hook_runs_every_iteration(self):
        beats = []
        now = [0.0]
        mb = MicroBatcher(
            lambda k, items: list(items),
            max_batch=100,
            max_delay_s=1.0,
            clock=lambda: now[0],
            start=False,
            poll_hook=lambda: beats.append(now[0]),
        )
        mb.submit("k", 1)
        mb._service_once(block=False)
        now[0] = 5.0
        mb._service_once(block=False)
        assert beats == [0.0, 5.0]
        mb.close()

    def test_size_trigger_beats_deadline_under_injected_clock(self):
        now = [0.0]
        mb = self._mb(lambda: now[0], max_batch=2)
        mb.submit("k", 1)
        mb.submit("k", 2)
        mb._service_once(block=False)
        assert mb.flush_log == []  # one entry in pending: below size
        mb._service_once(block=False)
        assert mb.flush_log == [("k", 2)]  # size trigger, clock untouched
        mb.close()

    def test_on_expired_reports_dropped_count_deterministically(self):
        """ISSUE 14 satellite: the shed-accounting hooks audited under
        injected time — no live engine, no scheduler in the loop."""
        now = [0.0]
        expired_counts = []
        mb = MicroBatcher(
            lambda k, items: list(items), max_batch=100, max_delay_s=10.0,
            clock=lambda: now[0], start=False,
            on_expired=expired_counts.append,
        )
        doomed = [mb.submit("k", i, deadline_s=1.0) for i in range(2)]
        live = mb.submit("k", "survivor", deadline_s=50.0)
        for _ in range(3):  # stage all three into the pending group
            assert mb._service_once(block=False)
        now[0] = 11.0  # group deadline AND the 1s TTLs are past
        assert mb._service_once(block=False)
        assert expired_counts == [2]  # one flush, both expired entries
        assert mb.expired_total == 2
        for f in doomed:
            with pytest.raises(Exception, match="deadline"):
                f.result(timeout=0)
        assert live.result(timeout=0) == "survivor"
        assert mb.flush_log == [("k", 1)]  # only the live entry dispatched
        mb.close()

    def test_on_flush_result_reports_ok_and_failure_in_order(self):
        now = [0.0]
        outcomes = []

        def process(k, items):
            if "boom" in items:
                raise ValueError("exploded")
            return list(items)

        mb = MicroBatcher(
            process, max_batch=1, max_delay_s=1.0,
            clock=lambda: now[0], start=False,
            on_flush_result=outcomes.append,
        )
        mb.submit("k", "fine")
        mb.submit("k", "boom")
        mb.submit("k", "fine2")
        for _ in range(3):
            mb._service_once(block=False)
        assert outcomes == [True, False, True]
        mb.close()

    def test_all_expired_flush_skips_process_and_flush_result(self):
        """A flush whose every entry expired dispatches nothing — so
        ``on_flush_result`` must not fire (no process outcome to score),
        while ``on_expired`` still reports the drop."""
        now = [0.0]
        outcomes, expired_counts = [], []
        mb = MicroBatcher(
            lambda k, items: list(items), max_batch=100, max_delay_s=1.0,
            clock=lambda: now[0], start=False,
            on_expired=expired_counts.append,
            on_flush_result=outcomes.append,
        )
        mb.submit("k", "late", deadline_s=0.5)
        now[0] = 2.0
        assert mb._service_once(block=False)
        assert expired_counts == [1] and outcomes == []
        assert mb.flush_log == []  # nothing reached process
        mb.close()

    def test_on_flush_stats_reports_per_entry_queue_waits(self):
        now = [0.0]
        stats = []
        mb = MicroBatcher(
            lambda k, items: list(items), max_batch=2, max_delay_s=10.0,
            clock=lambda: now[0], start=False,
            on_flush_stats=lambda k, waits: stats.append((k, waits)),
        )
        mb.submit("k", 1)
        now[0] = 0.3
        mb.submit("k", 2)
        now[0] = 0.5
        mb._service_once(block=False)
        mb._service_once(block=False)
        assert stats == [("k", [0.5, 0.2])]  # waits from each submit time
        mb.close()

    def test_key_depths_gauge_tracks_submit_to_flush(self):
        now = [0.0]
        mb = self._mb(lambda: now[0], max_batch=2)
        mb.submit("a", 1)
        mb.submit("b", 2)
        assert mb.key_depths() == {"a": 1, "b": 1}
        mb.submit("a", 3)
        assert mb.key_depths()["a"] == 2
        mb._service_once(block=False)  # a:1 -> pending
        mb._service_once(block=False)  # b:1 -> pending
        mb._service_once(block=False)  # a:2 -> size-trigger flush
        assert mb.key_depths() == {"b": 1}  # a's entries flushed out
        mb.close()  # drain flushes b
        assert mb.key_depths() == {}

    def test_per_key_max_delay_callable_sets_independent_deadlines(self):
        now = [0.0]
        delays = {"slow": 5.0, "fast": 0.5}
        mb = MicroBatcher(
            lambda k, items: list(items), max_batch=100,
            max_delay_s=lambda k: delays[k],
            clock=lambda: now[0], start=False,
        )
        mb.submit("slow", 1)
        mb.submit("fast", 2)
        mb._service_once(block=False)
        mb._service_once(block=False)
        assert mb.delay_s("slow") == 5.0 and mb.delay_s("fast") == 0.5
        now[0] = 0.6  # fast's deadline only
        mb._service_once(block=False)
        assert mb.flush_log == [("fast", 1)]
        now[0] = 5.1
        mb._service_once(block=False)
        assert mb.flush_log == [("fast", 1), ("slow", 1)]
        mb.close()


# ------------------------------------------------- SLO deadline controller


class TestDeadlineController:
    """ISSUE 14 satellite: per-bucket max_delay adaptation from observed
    queue waits — bounded multiplicative steps inside [floor, ceiling]."""

    def _dc(self, **kw):
        from replication_faster_rcnn_tpu.serving.slo import DeadlineController

        kw.setdefault("slo_ms", 100.0)
        kw.setdefault("floor_ms", 1.0)
        kw.setdefault("ceiling_ms", 50.0)
        kw.setdefault("step", 2.0)
        kw.setdefault("initial_ms", 10.0)
        kw.setdefault("window", 4)
        return DeadlineController(**kw)

    def test_shrinks_when_wait_p99_nears_the_slo(self):
        dc = self._dc()
        dc.on_flush("b", [0.090] * 4)  # 90ms > 0.8 x 100ms
        assert dc.delay_s("b") == pytest.approx(0.005)  # 10 / step
        assert dc.adaptations == 1

    def test_grows_only_with_slo_headroom_and_partial_flushes(self):
        dc = self._dc(max_batch=lambda k: 8)
        dc.on_flush("b", [0.010] * 4)  # partial (4 < 8), p99 well under
        assert dc.delay_s("b") == pytest.approx(0.020)  # 10 x step
        # full flushes: a longer deadline buys nothing -> no growth
        dc2 = self._dc(max_batch=lambda k: 4)
        dc2.on_flush("b", [0.010] * 4)  # full batch
        assert dc2.delay_s("b") == pytest.approx(0.010)
        assert dc2.adaptations == 0

    def test_dead_zone_keeps_deadline_stable(self):
        dc = self._dc()
        dc.on_flush("b", [0.060] * 4)  # 0.4 < 0.6 < 0.8 of the SLO
        assert dc.delay_s("b") == pytest.approx(0.010)
        assert dc.adaptations == 0

    def test_clamped_to_floor_and_ceiling(self):
        dc = self._dc(initial_ms=2.0)
        for _ in range(8):
            dc.on_flush("b", [0.095] * 4)  # shrink every window
        assert dc.delay_s("b") == pytest.approx(0.001)  # floor, not 2/2^8
        dc = self._dc(initial_ms=40.0)
        for _ in range(8):
            dc.on_flush("b", [0.001] * 4)
        assert dc.delay_s("b") == pytest.approx(0.050)  # ceiling

    def test_adapts_once_per_window_not_per_flush(self):
        dc = self._dc(window=8)
        dc.on_flush("b", [0.090] * 4)  # 4 of 8 samples
        assert dc.adaptations == 0
        dc.on_flush("b", [0.090] * 4)  # window reached
        assert dc.adaptations == 1

    def test_keys_adapt_independently(self):
        dc = self._dc()
        dc.on_flush("hot", [0.090] * 4)
        dc.on_flush("idle", [0.002] * 4)
        assert dc.delay_s("hot") == pytest.approx(0.005)
        assert dc.delay_s("idle") == pytest.approx(0.020)
        assert set(dc.delays_ms()) == {"hot", "idle"}

    def test_from_config_maps_serving_knobs(self):
        from replication_faster_rcnn_tpu.serving.slo import DeadlineController

        serving = ServingConfig(
            max_delay_ms=8.0, adaptive_slo_ms=200.0, delay_floor_ms=2.0,
            delay_ceiling_ms=32.0, adaptive_delay_step=2.0,
        )
        dc = DeadlineController.from_config(serving, window=4)
        assert dc.delay_s("any") == pytest.approx(0.008)
        dc.on_flush("b", [0.190] * 4)  # p99 over 0.8 x 200ms
        assert dc.delay_s("b") == pytest.approx(0.004)

    def test_validation(self):
        with pytest.raises(ValueError, match="floor_ms"):
            self._dc(floor_ms=0.0)
        with pytest.raises(ValueError, match="step"):
            self._dc(step=1.0)
        with pytest.raises(ValueError, match="slo_ms"):
            self._dc(slo_ms=0.0)
        with pytest.raises(ValueError, match="window"):
            self._dc(window=0)

    def test_drives_microbatcher_deadlines_through_the_callable_seam(self):
        """Controller + batcher closed loop under injected time: a
        shrink decided at flush N binds the deadline of flush N+1."""
        now = [0.0]
        dc = self._dc(window=2)
        mb = MicroBatcher(
            lambda k, items: list(items), max_batch=100,
            max_delay_s=dc.delay_s, clock=lambda: now[0], start=False,
            on_flush_stats=dc.on_flush,
        )
        f1, f2 = mb.submit("b", 1), mb.submit("b", 2)
        mb._service_once(block=False)
        mb._service_once(block=False)
        now[0] = 0.090  # the pair waits 90ms -> deadline flush + shrink
        mb._service_once(block=False)
        assert f1.result(timeout=0) == 1 and f2.result(timeout=0) == 2
        assert mb.delay_s("b") == pytest.approx(0.005)  # adapted live
        mb.submit("b", 3)
        mb._service_once(block=False)
        now[0] = 0.096  # 6ms later: past the NEW 5ms deadline, not 10ms
        mb._service_once(block=False)
        assert mb.flush_log == [("b", 2), ("b", 1)]
        mb.close()


# ---------------------------------------------------------- bucket routing


class TestSelectBucket:
    BUCKETS = ((32, 32), (64, 64))

    def test_snug_bucket_wins(self):
        assert select_bucket(self.BUCKETS, 20, 30) == (32, 32)
        assert select_bucket(self.BUCKETS, 33, 10) == (64, 64)
        assert select_bucket(self.BUCKETS, 64, 64) == (64, 64)

    def test_oversize_downscale_routes_to_largest(self):
        assert select_bucket(self.BUCKETS, 100, 100, "downscale") == (64, 64)

    def test_oversize_reject_raises(self):
        with pytest.raises(OversizedImageError, match="100x100"):
            select_bucket(self.BUCKETS, 100, 100, "reject")

    def test_no_resolutions_raises(self):
        with pytest.raises(ValueError, match="no serving resolutions"):
            select_bucket((), 10, 10)


# ---------------------------------------------------------- serving config


class TestServingConfig:
    def test_defaults_derive_full_and_half_buckets(self):
        sc = ServingConfig()
        assert sc.bucket_resolutions((600, 600)) == ((300, 300), (600, 600))

    def test_explicit_resolutions_sorted_by_area(self):
        sc = ServingConfig(resolutions=((64, 64), (32, 32)))
        assert sc.bucket_resolutions((600, 600)) == ((32, 32), (64, 64))

    def test_validation(self):
        with pytest.raises(ValueError, match="batch_sizes"):
            ServingConfig(batch_sizes=())
        with pytest.raises(ValueError, match="batch_sizes"):
            ServingConfig(batch_sizes=(0,))
        with pytest.raises(ValueError, match="max_delay_ms"):
            ServingConfig(max_delay_ms=-1)
        with pytest.raises(ValueError, match="queue_depth"):
            ServingConfig(queue_depth=0)
        with pytest.raises(ValueError, match="params_dtype"):
            ServingConfig(params_dtype="float99")
        with pytest.raises(ValueError, match="oversize"):
            ServingConfig(oversize="explode")

    def test_config_from_dict_round_trip(self):
        cfg = FasterRCNNConfig(
            serving=ServingConfig(
                resolutions=((32, 32),), batch_sizes=(1, 4),
                max_delay_ms=5.0, params_dtype="float32",
            )
        )
        rebuilt = config_from_dict(
            json.loads(json.dumps(dataclasses.asdict(cfg)))
        )
        assert rebuilt == cfg

    def test_config_from_dict_without_serving_key_uses_default(self):
        d = dataclasses.asdict(FasterRCNNConfig())
        d.pop("serving")
        assert config_from_dict(d).serving == ServingConfig()


# ------------------------------------------------------- program registry


class TestServingSpecs:
    def test_names_and_specs_cover_the_bucket_matrix(self):
        from replication_faster_rcnn_tpu.train.warmup import (
            build_serving_specs,
            serve_program_name,
            serving_program_names,
        )

        cfg = FasterRCNNConfig(
            data=DataConfig(dataset="synthetic", image_size=(64, 64)),
            serving=ServingConfig(
                resolutions=((32, 32), (64, 64)), batch_sizes=(1, 2)
            ),
        )
        assert serve_program_name(32, 32, 1) == "serve_32x32_b1"
        names = serving_program_names(cfg)
        assert sorted(names) == sorted(
            f"serve_{s}x{s}_b{b}" for s in (32, 64) for b in (1, 2)
        )
        specs = build_serving_specs(cfg)
        assert sorted(specs) == sorted(names)
        for name, spec in specs.items():
            assert spec.feed == "serve"
            assert spec.arg_roles == ("variables", "images")
            h, w = spec.meta["bucket"]
            assert name == f"serve_{h}x{w}_b{spec.meta['batch']}"

    def test_audit_expected_names_include_serving(self):
        from replication_faster_rcnn_tpu.analysis import hlolint

        base = set(hlolint.expected_program_names())
        full = set(
            hlolint.expected_program_names(config=hlolint.audit_config())
        )
        extra = full - base
        serving = {n for n in extra if n.startswith("serve_")}
        # 4 bucket-matrix programs + the serve pallas twin (ISSUE 13)
        # + their 4 __int8 quantized twins and the int8 pallas twin
        # (ISSUE 17)
        assert len(serving) == 10 and "serve_64x64_b1__pallas" in serving
        int8 = {n for n in serving if "__int8" in n}
        assert int8 == {
            "serve_32x32_b1__int8",
            "serve_32x32_b2__int8",
            "serve_64x64_b1__int8",
            "serve_64x64_b2__int8",
            "serve_64x64_b1__int8__pallas",
        }
        # the only other config-dependent names are the remaining pallas
        # twins and the per-bucket training programs (ISSUE 15: the audit
        # config sets data.train_resolutions; ISSUE 19: EVERY train feed
        # buckets, so the matrix is feeds x Ks x resolutions)
        from replication_faster_rcnn_tpu.train.warmup import (
            bucket_train_program_names,
        )

        buckets = set(
            bucket_train_program_names(
                hlolint.audit_config(),
                feeds=hlolint.AUDIT_FEEDS,
                ks=hlolint.AUDIT_KS,
            )
        )
        expected_buckets = (
            len(hlolint.AUDIT_FEEDS) * len(hlolint.AUDIT_KS) * 2
        )
        assert buckets <= extra and len(buckets) == expected_buckets
        assert extra - serving - buckets == {
            "train_loader_k1__pallas",
            "eval_infer__pallas",
        }


# ------------------------------------------------- serving_profile harness


class TestServingProfileGate:
    @pytest.fixture()
    def sp(self):
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "serving_profile",
            os.path.join(REPO, "benchmarks", "serving_profile.py"),
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    def _record(self, sp, ips=100.0, speedup=2.5, p99=50.0):
        return {
            "schema": sp.SCHEMA,
            sp.GATE_KEY: ips,
            "speedup": speedup,
            "sequential_images_per_sec": round(ips / speedup, 3),
            "engine": {"p99_ms": p99},
        }

    def test_regression_beyond_tol_fails(self, sp):
        cur, banked = self._record(sp, ips=80.0), self._record(sp, ips=100.0)
        failures, _ = sp.check_regression(cur, banked, tol=0.15)
        assert len(failures) == 1 and "regressed" in failures[0]

    def test_slip_within_tol_warns(self, sp):
        cur, banked = self._record(sp, ips=90.0), self._record(sp, ips=100.0)
        failures, warnings = sp.check_regression(cur, banked, tol=0.15)
        assert not failures
        assert any("slipping" in w for w in warnings)

    def test_speedup_floor_enforced_without_banked_record(self, sp):
        cur = self._record(sp, speedup=1.4)
        failures, _ = sp.check_regression(cur, None, min_speedup=2.0)
        assert len(failures) == 1 and "floor" in failures[0]

    def test_clean_run_passes(self, sp):
        cur = self._record(sp, ips=101.0)
        failures, warnings = sp.check_regression(cur, self._record(sp))
        assert not failures and not warnings

    def test_schema_mismatch_skips_comparison(self, sp):
        banked = self._record(sp)
        banked["schema"] = "other/v0"
        failures, warnings = sp.check_regression(self._record(sp), banked)
        assert not failures
        assert any("schema" in w for w in warnings)

    def test_banked_cpu_record_meets_acceptance(self, sp):
        """The committed record must hold the >= 2x acceptance claim."""
        path = sp.record_path(sp.record_key("tiny16b32", "cpu"))
        with open(path) as f:
            banked = json.load(f)
        assert banked["schema"] == sp.SCHEMA
        assert banked["speedup"] >= 2.0
        assert banked[sp.GATE_KEY] > banked["sequential_images_per_sec"]
        for leg in ("sequential", "engine", "engine_open_loop"):
            assert banked[leg]["p50_ms"] > 0
            assert banked[leg]["p99_ms"] >= banked[leg]["p50_ms"]


def test_mfu_default_order_puts_wedge_risks_last():
    """VERDICT round 5 item 5: safe validations first, FPN/trace/
    transfer-stress legs last — pinned so appends can't silently
    reshuffle ahead of the wedge classes."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "mfu_experiments", os.path.join(REPO, "benchmarks", "mfu_experiments.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    order = mod.DEFAULT_ORDER
    assert sorted(order) == list(range(len(mod.EXPERIMENTS)))
    names = [mod.EXPERIMENTS[i]["name"] for i in order]
    # the four known wedge classes close the queue, in blast order
    assert names[-5:] == [
        "fpn_b8_reverify",
        "fpn_b16",
        "profile_trace_b16",
        "loader_trainer_600",
        "loader_trainer_600_u8",
    ]
    assert names.index("loader_trainer_600_devcache") < names.index("fpn_b16")


# ------------------------------------------------------------- live engine


def live_config():
    return FasterRCNNConfig(
        model=ModelConfig(
            backbone="resnet18", roi_op="align", compute_dtype="float32"
        ),
        data=DataConfig(
            dataset="synthetic", image_size=(32, 32), max_boxes=8
        ),
        train=TrainConfig(batch_size=1, n_epoch=1),
        mesh=MeshConfig(num_data=1),
        proposals=ProposalConfig(
            pre_nms_train=128, post_nms_train=32,
            pre_nms_test=16, post_nms_test=4,
        ),
        roi_targets=ROITargetConfig(n_sample=8),
        eval=EvalConfig(max_detections=4),
        serving=ServingConfig(
            resolutions=((32, 32),),
            batch_sizes=(1, 2),
            max_delay_ms=20.0,
            queue_depth=8,
            params_dtype="float32",  # bitwise parity with the Evaluator
        ),
    )


@pytest.fixture(scope="module")
def live():
    import jax

    from replication_faster_rcnn_tpu.eval.evaluator import Evaluator
    from replication_faster_rcnn_tpu.models.faster_rcnn import init_variables

    cfg = live_config()
    model, variables = init_variables(cfg, jax.random.PRNGKey(0))
    engine = InferenceEngine(cfg, model, variables, warmup=True)
    ev = Evaluator(cfg, model)
    rng = np.random.RandomState(0)
    images = [
        (rng.rand(32, 32, 3) * 2.0 - 1.0).astype(np.float32)
        for _ in range(3)
    ]
    yield {
        "cfg": cfg, "model": model, "variables": variables,
        "engine": engine, "ev": ev, "images": images,
    }
    engine.close()


class TestLiveEngine:
    def test_warmup_compiled_every_bucket_program(self, live):
        assert sorted(live["engine"].compile_seconds) == [
            "serve_32x32_b1", "serve_32x32_b2"
        ]

    def test_single_submit_bitwise_matches_evaluator(self, live):
        engine, ev = live["engine"], live["ev"]
        img = live["images"][0]
        out = engine.submit(img).result(timeout=60)
        ref = ev.predict_batch(live["variables"], img[None])
        for k in ("boxes", "scores", "classes", "valid"):
            np.testing.assert_array_equal(
                out[k], np.asarray(ref[k][0]),
                err_msg=f"engine vs Evaluator mismatch on {k}",
            )

    def test_concurrent_submits_coalesce_and_match_singles(self, live):
        engine = live["engine"]
        flushes_before = len(engine._batcher.flush_log)
        futs = [engine.submit(img) for img in live["images"][:2]]
        outs = [f.result(timeout=60) for f in futs]
        new = engine._batcher.flush_log[flushes_before:]
        # flush keys are (model_version, bucket) since the hot-swap work
        assert (("0", (32, 32)), 2) in new, f"no coalesced flush in {new}"
        for img, out in zip(live["images"][:2], outs):
            ref = live["ev"].predict_batch(live["variables"], img[None])
            np.testing.assert_allclose(
                out["boxes"], np.asarray(ref["boxes"][0]), atol=1e-5
            )
            np.testing.assert_array_equal(
                out["classes"], np.asarray(ref["classes"][0])
            )

    def test_partial_flush_pads_to_bucket_and_unpads(self, live):
        engine = live["engine"]
        img = live["images"][0]
        padded_before = engine.stats["padded_slots"]
        # force the pad path: drop the b1 program from the size ladder so
        # a single request must ride the compiled b2 program
        orig_sizes = engine.batch_sizes
        engine.batch_sizes = (2,)
        try:
            out = engine._process_bucket(
                (engine.model_version, (32, 32)), [(img, 32, 32)]
            )
        finally:
            engine.batch_sizes = orig_sizes
        assert len(out) == 1  # un-padded: one result for one request
        assert engine.stats["padded_slots"] == padded_before + 1
        ref = live["ev"].predict_batch(live["variables"], img[None])
        np.testing.assert_allclose(
            out[0]["boxes"], np.asarray(ref["boxes"][0]), atol=1e-5
        )

    def test_uint8_routing_and_box_denormalization(self, live):
        engine = live["engine"]
        rng = np.random.RandomState(1)
        # 16x24 uint8 routes to the 32x32 bucket; boxes come back scaled
        # to the ORIGINAL 16x24 frame
        small = (rng.rand(16, 24, 3) * 255).astype(np.uint8)
        out = engine.submit(small).result(timeout=60)
        h_scale, w_scale = 16 / 32, 24 / 32
        assert out["boxes"].shape[-1] == 4
        valid = out["boxes"][np.asarray(out["valid"], bool)]
        if valid.size:
            assert valid[:, 0].max() <= 16 + 1e-3
            assert valid[:, 1].max() <= 24 + 1e-3
        # the same content at bucket size must reproduce the normalized
        # boxes modulo that scaling
        from replication_faster_rcnn_tpu.data import native_ops

        resized = native_ops.resize_normalize(
            small, (32, 32),
            live["cfg"].data.pixel_mean, live["cfg"].data.pixel_std,
        )
        ref = engine.submit(resized.astype(np.float32)).result(timeout=60)
        np.testing.assert_allclose(
            out["boxes"],
            ref["boxes"] * np.asarray(
                [h_scale, w_scale, h_scale, w_scale], np.float32
            ),
            atol=1e-4,
        )

    def test_oversized_image_downscales_by_default(self, live):
        engine = live["engine"]
        big = (np.random.RandomState(2).rand(50, 40, 3) * 255).astype(
            np.uint8
        )
        out = engine.submit(big).result(timeout=60)
        valid = out["boxes"][np.asarray(out["valid"], bool)]
        if valid.size:  # de-normalized to the 50x40 original frame
            assert valid[:, 2].max() <= 50 + 1e-3

    def test_oversized_image_rejected_under_reject_policy(self, live):
        cfg = dataclasses.replace(
            live["cfg"],
            serving=dataclasses.replace(
                live["cfg"].serving, oversize="reject"
            ),
        )
        engine = InferenceEngine(cfg, live["model"], live["variables"])
        try:
            big = np.zeros((40, 40, 3), np.uint8)
            with pytest.raises(OversizedImageError):
                engine.submit(big)
            assert engine.stats["requests"] == 0
        finally:
            engine.close()

    def test_float_image_off_bucket_rejected(self, live):
        with pytest.raises(ValueError, match="matches no serving bucket"):
            live["engine"].submit(np.zeros((16, 16, 3), np.float32))

    def test_predict_images_multi_path_one_wave(self, live, tmp_path):
        from PIL import Image

        from replication_faster_rcnn_tpu.eval.predict import predict_images

        rng = np.random.RandomState(3)
        paths = []
        for i in range(2):
            p = str(tmp_path / f"img{i}.png")
            Image.fromarray(
                (rng.rand(20, 28, 3) * 255).astype(np.uint8)
            ).save(p)
            paths.append(p)
        engine = live["engine"]
        flushes_before = len(engine._batcher.flush_log)
        dets = predict_images(
            live["cfg"], live["model"], live["variables"], paths,
            score_thresh=0.0, engine=engine,
        )
        assert len(dets) == 2
        for d in dets:
            for det in d:
                assert set(det) == {"box", "score", "class_id", "class_name"}
        # both paths coalesced into one shared flush
        assert (("0", (32, 32)), 2) in engine._batcher.flush_log[flushes_before:]

    def test_strict_session_zero_transfers_zero_recompiles(self, live):
        from replication_faster_rcnn_tpu.analysis.strict import StrictHarness

        engine = live["engine"]
        h = StrictHarness()  # dispatch 2+ of each program is checked warm
        engine.strict = h
        try:
            with h.session():
                for _ in range(2):  # two b2 flushes, two b1 flushes
                    futs = [engine.submit(img) for img in live["images"][:2]]
                    _ = [f.result(timeout=60) for f in futs]
                    _ = engine.submit(live["images"][2]).result(timeout=60)
        finally:
            engine.strict = None
        report = h.report()
        assert report["violations"] == []
        assert report["compile_events_total"] == 0
        assert sum(
            p["warm_dispatches"] for p in report["programs"].values()
        ) >= 2

    def test_http_server_end_to_end(self, live, tmp_path):
        import urllib.error
        import urllib.request

        from PIL import Image

        from replication_faster_rcnn_tpu.serving.server import make_server

        p = str(tmp_path / "req.png")
        Image.fromarray(
            (np.random.RandomState(4).rand(20, 20, 3) * 255).astype(np.uint8)
        ).save(p)
        server = make_server(live["engine"], port=0, score_thresh=0.0)
        host, port = server.server_address
        t = threading.Thread(target=server.serve_forever, daemon=True)
        t.start()
        try:
            def call(method, path, payload=None):
                req = urllib.request.Request(
                    f"http://{host}:{port}{path}",
                    data=json.dumps(payload).encode() if payload else None,
                    method=method,
                    headers={"Content-Type": "application/json"},
                )
                with urllib.request.urlopen(req, timeout=60) as resp:
                    return resp.status, json.loads(resp.read())

            status, health = call("GET", "/healthz")
            assert status == 200 and health["buckets"] == [[32, 32]]
            status, out = call("POST", "/predict", {"paths": [p]})
            assert status == 200
            for det in out["detections"][p]:
                assert set(det) == {"box", "score", "class_id", "class_name"}
            status, stats = call("GET", "/stats")
            assert status == 200 and stats["stats"]["requests"] >= 1
            with pytest.raises(urllib.error.HTTPError) as e:
                call("POST", "/predict", {})
            assert e.value.code == 400
            with pytest.raises(urllib.error.HTTPError) as e:
                call("POST", "/predict", {"path": str(tmp_path / "no.png")})
            assert e.value.code == 400
        finally:
            server.shutdown()
            server.server_close()

    def test_get_engine_cache_reuses_and_displaces(self, live):
        from replication_faster_rcnn_tpu.serving.engine import get_engine

        e1 = get_engine(live["cfg"], live["model"], live["variables"])
        e2 = get_engine(live["cfg"], live["model"], live["variables"])
        assert e1 is e2
        variables2 = jax_tree_copy(live["variables"])
        e3 = get_engine(live["cfg"], live["model"], variables2)
        assert e3 is not e1
        # the displaced engine's worker was shut down
        with pytest.raises(RuntimeError, match="closed"):
            e1._batcher.submit((32, 32), None)
        e3.close()


def jax_tree_copy(tree):
    import jax

    return jax.tree_util.tree_map(lambda x: x, tree)


# ------------------------------------------- model-parallel serving layout


def mp_config(num_model=2):
    cfg = live_config()
    return dataclasses.replace(
        cfg,
        mesh=dataclasses.replace(
            cfg.mesh, num_data=1, num_model=num_model, param_sharding=True
        ),
    )


class TestMpServingSpecs:
    """`--mesh-shape DP,MP` serving seam: build_serving_specs attaches the
    `zero.param_shardings` layout to abstract params (shardlint SL001's
    fix for the replicated-params serve plan) and the engine's resident
    upload honors it. Spec construction is lazy — no compiles here."""

    def test_mp_config_attaches_sharded_layout_and_meta(self):
        import jax
        from replication_faster_rcnn_tpu.train.warmup import (
            build_serving_specs,
        )

        specs = build_serving_specs(mp_config())
        spec = specs["serve_32x32_b1"]
        assert spec.meta["param_sharding"] is True
        assert spec.meta["mesh_shape"] == {"data": 1, "model": 2}
        _, (vars_abs, _img) = spec.build()
        param_specs = [
            tuple(leaf.sharding.spec)
            for leaf in jax.tree_util.tree_leaves(vars_abs["params"])
        ]
        assert all(s is not None for s in param_specs)
        # the layout actually splits weights: some leaf rides the model axis
        assert any("model" in str(s) for s in param_specs)
        # non-param collections stay replicated on the same mesh
        for leaf in jax.tree_util.tree_leaves(vars_abs["batch_stats"]):
            assert tuple(leaf.sharding.spec) == ()
            assert dict(leaf.sharding.mesh.shape) == {"data": 1, "model": 2}

    def test_mp_layout_matches_zero_param_shardings(self):
        import jax
        from replication_faster_rcnn_tpu.parallel import zero
        from replication_faster_rcnn_tpu.train.warmup import (
            build_serving_specs,
        )

        cfg = mp_config()
        spec = build_serving_specs(cfg)["serve_32x32_b1"]
        _, (vars_abs, _img) = spec.build()
        leaves = jax.tree_util.tree_leaves(vars_abs["params"])
        mesh = leaves[0].sharding.mesh
        expected = zero.param_shardings(
            vars_abs["params"], mesh, cfg.mesh
        )
        for got, want in zip(
            leaves, jax.tree_util.tree_leaves(expected)
        ):
            assert got.sharding == want

    def test_default_config_attaches_no_shardings(self):
        import jax
        from replication_faster_rcnn_tpu.train.warmup import (
            build_serving_specs,
        )

        spec = build_serving_specs(live_config())["serve_32x32_b1"]
        assert "param_sharding" not in spec.meta
        assert "mesh_shape" not in spec.meta
        _, (vars_abs, _img) = spec.build()
        for leaf in jax.tree_util.tree_leaves(vars_abs):
            assert getattr(leaf, "sharding", None) is None

    def test_batch_target_follows_resident_mesh(self):
        import jax
        from jax.sharding import Mesh, NamedSharding, PartitionSpec

        from replication_faster_rcnn_tpu.serving.engine import _batch_target

        # host / single-device trees: default placement
        assert _batch_target({"w": np.zeros((4, 4))}) is None
        one = jax.device_put(np.zeros((4, 4), np.float32))
        assert _batch_target({"w": one}) is None
        # mp-sharded tree: batch must be replicated over the SAME mesh
        mesh = Mesh(
            np.asarray(jax.devices()[:2]).reshape(1, 2), ("data", "model")
        )
        sharded = jax.device_put(
            np.zeros((4, 4), np.float32),
            NamedSharding(mesh, PartitionSpec("model", None)),
        )
        target = _batch_target({"w": sharded, "b": one})
        assert target == NamedSharding(mesh, PartitionSpec())


@pytest.mark.slow
class TestMpServingParity:
    def test_mp_engine_matches_replicated_engine(self):
        """End-to-end acceptance for satellite 1: the same weights served
        through the (1, 2) model-parallel layout produce the same
        detections as the single-device replicated path."""
        import jax

        from replication_faster_rcnn_tpu.models.faster_rcnn import (
            init_variables,
        )

        cfg_rep = live_config()
        cfg_mp = mp_config()
        model, variables = init_variables(cfg_rep, jax.random.PRNGKey(0))
        img = (
            np.random.RandomState(0).rand(32, 32, 3) * 255
        ).astype(np.uint8)
        eng_rep = InferenceEngine(cfg_rep, model, variables)
        try:
            ref = eng_rep.submit(img).result(timeout=300)
        finally:
            eng_rep.close()
        eng_mp = InferenceEngine(cfg_mp, model, variables)
        try:
            # resident params really live on the 2-device serving mesh
            resident = eng_mp._resident[eng_mp.model_version]
            leaves = jax.tree_util.tree_leaves(resident["params"])
            assert any(
                leaf.sharding.num_devices == 2 for leaf in leaves
            )
            out = eng_mp.submit(img).result(timeout=300)
        finally:
            eng_mp.close()
        np.testing.assert_array_equal(out["classes"], ref["classes"])
        np.testing.assert_array_equal(out["valid"], ref["valid"])
        for k in ("boxes", "scores"):
            np.testing.assert_allclose(
                out[k], ref[k], atol=2e-2, rtol=2e-2,
                err_msg=f"mp vs replicated mismatch on {k}",
            )
